"""Virtual-CPU-mesh environment scrub (single source of truth, no jax).

The session environment routes every Python process to the real TPU via a
sitecustomize hook (``PALLAS_AXON_POOL_IPS`` → axon backend registration at
interpreter start), which wins over in-process ``JAX_PLATFORMS`` settings.
Any code that needs an N-device virtual CPU mesh — the TPU analog of the
reference's ``SparkContext("local[*]")`` (``Graphframes.py:12``) — must
therefore fix the environment *before* Python starts, i.e. in a re-exec or
child process. This module builds that environment; it is deliberately
standalone (stdlib-only) so callers that must not trigger the package
``__init__`` (which imports jax) can load it by file path::

    from importlib import util
    spec = util.spec_from_file_location("_envscrub", path_to_this_file)
    mod = util.module_from_spec(spec)
    spec.loader.exec_module(mod)

Used by ``__graft_entry__.dryrun_multichip`` and ``tests/conftest.py``.
"""

import os


def virtual_cpu_env(n_devices, base=None, override_count=True):
    """Return an environment dict for an ``n_devices`` virtual CPU mesh.

    - Disables the axon TPU registration hook (empty string keeps the
      variable defined but falsy, which the hook treats as off).
    - Forces ``JAX_PLATFORMS=cpu``.
    - Ensures ``--xla_force_host_platform_device_count=n_devices`` is in
      ``XLA_FLAGS``. With ``override_count=False`` an existing count flag
      (e.g. a caller's explicit device-count choice) is preserved.
    """
    env = dict(os.environ if base is None else base)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "").split()
    has_count = any("xla_force_host_platform_device_count" in f for f in flags)
    if override_count:
        flags = [
            f for f in flags
            if "xla_force_host_platform_device_count" not in f
        ]
        has_count = False
    if not has_count:
        flags.append(f"--xla_force_host_platform_device_count={int(n_devices)}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env
