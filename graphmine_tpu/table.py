"""Columnar ``Table`` — the relational layer (Spark DataFrame contract).

The reference drives its whole preprocessing phase through Spark SQL
DataFrame ops (SURVEY §2.2 "DataFrame ops" row): ``read.parquet``
(``Graphframes.py:16``), ``withColumnRenamed`` ×4 (``:26-29``), a SQL-string
``filter("ParentDomain is not null and ChildDomain is not null")`` (``:30``),
``select``/``withColumn`` (``:70-73``), ``distinct``/``count``
(``:18,:54,:85``), ``show(10)`` (``:32,:68,:74,:82``), ``persist`` (``:82``)
and ``collect`` (``:100-110``). The dead data-slicer (``:34-47``) also used
``monotonically_increasing_id`` + ``sort``/``limit``/``subtract``.

This module reproduces that contract TPU-natively: a **host-side columnar
table** (NumPy arrays per column — the Arrow/Catalyst equivalent) whose ops
are all vectorized, with a small SQL predicate parser so the reference's
literal filter strings run unchanged. There is no lazy DAG and no shuffle:
every op materializes eagerly (``persist`` is therefore the identity, kept
for call-site parity), and ``collect`` is a plain host read rather than a
JVM→driver boundary. Device code never sees strings — the bridge to the
engine is :meth:`Table.to_edge_table`, which factorizes to dense int32.

Both snake_case and Spark's camelCase method names are provided.
"""

from __future__ import annotations

import re
from collections import namedtuple
from typing import Any, Callable, Iterable, Mapping, Sequence

import numpy as np

__all__ = ["Table", "read_parquet"]


# ---------------------------------------------------------------------------
# SQL predicate parser (the `filter("...")` surface, Graphframes.py:30)
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<num>-?\d+\.\d+|-?\d+)
      | '(?P<str>(?:[^'\\]|\\.)*)'
      | "(?P<dstr>(?:[^"\\]|\\.)*)"
      | (?P<op><=|>=|!=|<>|==|=|<|>)
      | (?P<lp>\()
      | (?P<rp>\))
      | (?P<comma>,)
      | (?P<word>[A-Za-z_][A-Za-z0-9_.]*)
    )""",
    re.VERBOSE,
)

_KEYWORDS = {"and", "or", "not", "is", "null", "in", "like", "true", "false"}


def _tokenize(expr: str) -> list[tuple[str, Any]]:
    tokens, pos = [], 0
    while pos < len(expr):
        m = _TOKEN_RE.match(expr, pos)
        if not m or m.end() == pos:
            if expr[pos:].strip() == "":
                break
            raise ValueError(f"cannot parse filter expression at: {expr[pos:]!r}")
        pos = m.end()
        if m.group("num") is not None:
            text = m.group("num")
            tokens.append(("lit", float(text) if "." in text else int(text)))
        elif m.group("str") is not None:
            tokens.append(("lit", m.group("str").replace("\\'", "'")))
        elif m.group("dstr") is not None:
            tokens.append(("lit", m.group("dstr").replace('\\"', '"')))
        elif m.group("op") is not None:
            tokens.append(("op", m.group("op")))
        elif m.group("lp"):
            tokens.append(("lp", "("))
        elif m.group("rp"):
            tokens.append(("rp", ")"))
        elif m.group("comma"):
            tokens.append(("comma", ","))
        else:
            word = m.group("word")
            low = word.lower()
            if low in _KEYWORDS:
                tokens.append(("kw", low))
            else:
                tokens.append(("ident", word))
    return tokens


class _Tri:
    """SQL three-valued logic: a boolean vector plus an ``unknown`` (null)
    vector. ``true``/``false``/``unknown`` are disjoint; a row passes a
    filter only when the predicate is *true* (unknown rows drop, and
    ``NOT unknown`` stays unknown — Spark semantics)."""

    __slots__ = ("v", "u")

    def __init__(self, v: np.ndarray, u: np.ndarray | None = None):
        self.v = v
        self.u = np.zeros(len(v), bool) if u is None else u

    def __and__(self, o: "_Tri") -> "_Tri":
        false = (~self.v & ~self.u) | (~o.v & ~o.u)
        v = self.v & o.v
        return _Tri(v, ~v & ~false)

    def __or__(self, o: "_Tri") -> "_Tri":
        v = self.v | o.v
        return _Tri(v, ~v & (self.u | o.u))

    def __invert__(self) -> "_Tri":
        return _Tri(~self.v & ~self.u, self.u)


class _PredicateParser:
    """Recursive-descent parser for the SQL predicate subset Spark-style
    ``filter`` strings use: comparisons, ``is [not] null``, ``like``,
    ``in (...)``, ``and``/``or``/``not``, parentheses. Evaluates under SQL
    three-valued logic (comparisons against null are *unknown*, not false)."""

    def __init__(self, tokens: list[tuple[str, Any]], columns: Mapping[str, np.ndarray], n: int):
        self.toks = tokens
        self.i = 0
        self.cols = columns
        self.n = n

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def take(self, kind=None, value=None):
        tok = self.peek()
        if kind is not None and tok[0] != kind:
            raise ValueError(f"expected {kind}, got {tok}")
        if value is not None and tok[1] != value:
            raise ValueError(f"expected {value!r}, got {tok}")
        self.i += 1
        return tok

    def parse(self) -> np.ndarray:
        tri = self.or_expr()
        if self.peek()[0] is not None:
            raise ValueError(f"trailing tokens: {self.toks[self.i:]}")
        return tri.v  # rows where the predicate is TRUE (unknown drops)

    def or_expr(self) -> _Tri:
        left = self.and_expr()
        while self.peek() == ("kw", "or"):
            self.take()
            left = left | self.and_expr()
        return left

    def and_expr(self) -> _Tri:
        left = self.not_expr()
        while self.peek() == ("kw", "and"):
            self.take()
            left = left & self.not_expr()
        return left

    def not_expr(self) -> _Tri:
        if self.peek() == ("kw", "not"):
            self.take()
            return ~self.not_expr()
        return self.comparison()

    def _operand(self):
        kind, val = self.peek()
        if kind == "lp":
            self.take()
            out = self.or_expr()
            self.take("rp")
            return ("mask", out)
        if kind == "ident":
            self.take()
            if val not in self.cols:
                raise KeyError(f"unknown column {val!r} in filter expression")
            return ("col", val)
        if kind == "lit":
            self.take()
            return ("lit", val)
        if kind == "kw" and val in ("true", "false"):
            self.take()
            return ("lit", val == "true")
        raise ValueError(f"unexpected token {self.peek()} in filter expression")

    def comparison(self) -> _Tri:
        left_kind, left = self._operand()
        if left_kind == "mask":
            return left
        kind, val = self.peek()
        if kind == "kw" and val == "is":
            self.take()
            negate = False
            if self.peek() == ("kw", "not"):
                self.take()
                negate = True
            self.take("kw", "null")
            mask = _isnull(self._resolve(left_kind, left))
            return _Tri(~mask if negate else mask)  # IS NULL is never unknown
        if kind == "kw" and val == "like":
            self.take()
            _, pat = self.take("lit")
            arr = self._resolve(left_kind, left)
            null = _isnull(arr)
            return _Tri(_like(arr, str(pat)) & ~null, null)
        if kind == "kw" and val == "in":
            self.take()
            self.take("lp")
            lits = []
            while True:
                _, lit = self.take("lit")
                lits.append(lit)
                if self.peek()[0] == "comma":
                    self.take()
                    continue
                self.take("rp")
                break
            arr = self._resolve(left_kind, left)
            hit = np.isin(
                arr, np.array(lits, dtype=arr.dtype if arr.dtype != object else object)
            )
            null = _isnull(arr)
            return _Tri(hit & ~null, null)
        if kind == "op":
            self.take()
            right_kind, right = self._operand()
            a = self._resolve(left_kind, left)
            b = self._resolve(right_kind, right)
            return _Tri(_compare(a, val, b), _isnull(a) | _isnull(b))
        if left_kind == "col":
            col = self.cols[left]
            if col.dtype == np.bool_:
                return _Tri(col.copy())
        raise ValueError(f"column {left!r} used as a predicate but is not boolean")

    def _resolve(self, kind, val):
        if kind == "col":
            return self.cols[val]
        return np.full(self.n, val, dtype=object if isinstance(val, str) else None)


def _isnull(col: np.ndarray) -> np.ndarray:
    if col.dtype == object:
        return np.frompyfunc(lambda v: v is None, 1, 1)(col).astype(bool)
    if np.issubdtype(col.dtype, np.floating):
        return np.isnan(col)
    return np.zeros(len(col), dtype=bool)


def _object_as_float(col: np.ndarray, null: np.ndarray) -> np.ndarray | None:
    """float64 view of an object column whose non-null values are all
    numeric — the nullable-int representation joins produce — with NaN at
    nulls; ``None`` if any non-null value is non-numeric."""
    is_num = np.frompyfunc(
        lambda v: isinstance(v, (int, float, np.integer, np.floating))
        and not isinstance(v, bool), 1, 1,
    )(col).astype(bool)
    if not (is_num | null).all():
        return None
    return np.where(null, np.nan, np.where(is_num, col, 0.0)).astype(np.float64)


def _like(col: np.ndarray, pattern: str) -> np.ndarray:
    rx = re.compile(
        "^"
        + "".join(".*" if c == "%" else "." if c == "_" else re.escape(c) for c in pattern)
        + "$"
    )
    f = np.frompyfunc(lambda v: v is not None and rx.match(str(v)) is not None, 1, 1)
    return f(col).astype(bool)


def _compare(a: np.ndarray, op: str, b: np.ndarray) -> np.ndarray:
    null = _isnull(a) | _isnull(b)
    if a.dtype == object or b.dtype == object:
        a = np.where(null, "", a).astype(object)
        b = np.where(null, "", b).astype(object)
    if op in ("=", "=="):
        out = a == b
    elif op in ("!=", "<>"):
        out = a != b
    elif op == "<":
        out = a < b
    elif op == ">":
        out = a > b
    elif op == "<=":
        out = a <= b
    else:
        out = a >= b
    return np.asarray(out, dtype=bool) & ~null  # SQL: comparisons with null are false


# ---------------------------------------------------------------------------
# Table
# ---------------------------------------------------------------------------


class Table:
    """Immutable host-side columnar table with the Spark DataFrame op set.

    Columns are NumPy arrays of equal length; string columns use
    ``dtype=object`` with ``None`` as SQL null (matching the Arrow read
    path). All ops return new ``Table`` objects; none mutate.
    """

    def __init__(self, columns: Mapping[str, np.ndarray] | None = None, **kw):
        cols = dict(columns or {}, **kw)
        self._cols: dict[str, np.ndarray] = {}
        n = None
        for name, values in cols.items():
            arr = _as_column(values)
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(
                    f"column {name!r} has length {len(arr)}, expected {n}"
                )
            self._cols[name] = arr
        self._n = n or 0

    # -- structure ----------------------------------------------------------

    @property
    def columns(self) -> list[str]:
        return list(self._cols)

    @property
    def schema(self) -> dict[str, np.dtype]:
        return {k: v.dtype for k, v in self._cols.items()}

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, name: str) -> np.ndarray:
        return self._cols[name]

    def __repr__(self) -> str:
        return f"Table[{self._n} x {len(self._cols)}: {', '.join(self._cols)}]"

    def _replace(self, cols: dict[str, np.ndarray]) -> "Table":
        t = Table.__new__(Table)
        t._cols = cols
        t._n = len(next(iter(cols.values()))) if cols else 0
        return t

    # -- the reference's op surface -----------------------------------------

    def count(self) -> int:
        """Row count (``Graphframes.py:18,:54,:85``)."""
        return self._n

    def with_column_renamed(self, existing: str, new: str) -> "Table":
        """``withColumnRenamed`` (``Graphframes.py:26-29``)."""
        if existing not in self._cols:
            return self  # Spark semantics: silently no-op on missing column
        if new in self._cols and new != existing:
            # Spark would produce duplicate column names; a dict cannot, and
            # silently dropping a column loses data — fail loudly instead.
            raise ValueError(f"cannot rename {existing!r}: column {new!r} already exists")
        return self._replace(
            {(new if k == existing else k): v for k, v in self._cols.items()}
        )

    def filter(self, cond: "str | np.ndarray | Callable[[Table], np.ndarray]") -> "Table":
        """Row filter: SQL predicate string (``Graphframes.py:30``), boolean
        mask, or callable over the table."""
        if isinstance(cond, str):
            mask = _PredicateParser(_tokenize(cond), self._cols, self._n).parse()
        elif callable(cond) and not isinstance(cond, np.ndarray):
            mask = np.asarray(cond(self), dtype=bool)
        else:
            mask = np.asarray(cond, dtype=bool)
        return self._replace({k: v[mask] for k, v in self._cols.items()})

    where = None  # assigned below (alias)

    def select(self, *names: str) -> "Table":
        """Column projection (``Graphframes.py:53,:70,:92``)."""
        flat: list[str] = []
        for n in names:
            flat.extend(n if isinstance(n, (list, tuple)) else [n])
        missing = [n for n in flat if n not in self._cols]
        if missing:
            raise KeyError(f"unknown columns {missing}; have {self.columns}")
        return self._replace({n: self._cols[n] for n in flat})

    def with_column(self, name: str, values) -> "Table":
        """``withColumn`` (``Graphframes.py:71-73``): add/replace a column.
        ``values`` may be an array or a vectorized fn of the table."""
        arr = values(self) if callable(values) else values
        arr = _as_column(arr)
        if len(arr) != self._n:
            raise ValueError(f"column {name!r} length {len(arr)} != {self._n}")
        cols = dict(self._cols)
        cols[name] = arr
        return self._replace(cols)

    def distinct(self) -> "Table":
        """Distinct rows (``Graphframes.py:53,:85,:92``). Order of first
        appearance is preserved (deterministic, unlike Spark)."""
        if not self._cols:
            return self
        keys = _row_keys(list(self._cols.values()))
        _, idx = np.unique(keys, return_index=True)
        idx.sort()
        return self._replace({k: v[idx] for k, v in self._cols.items()})

    def drop_duplicates(self, subset: Sequence[str] | None = None) -> "Table":
        if subset is None:
            return self.distinct()
        keys = _row_keys([self._cols[c] for c in subset])
        _, idx = np.unique(keys, return_index=True)
        idx.sort()
        return self._replace({k: v[idx] for k, v in self._cols.items()})

    def show(self, n: int = 20, truncate: int = 20) -> str:
        """Pretty-print the first ``n`` rows (``Graphframes.py:32`` etc.);
        returns the rendered string (also printed)."""
        names = self.columns
        rows = [
            [_render(self._cols[c][i], truncate) for c in names]
            for i in range(min(n, self._n))
        ]
        widths = [
            max(len(c), *(len(r[j]) for r in rows)) if rows else len(c)
            for j, c in enumerate(names)
        ]
        sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        out = [sep, "|" + "|".join(f" {c:<{w}} " for c, w in zip(names, widths)) + "|", sep]
        for r in rows:
            out.append("|" + "|".join(f" {v:<{w}} " for v, w in zip(r, widths)) + "|")
        out.append(sep)
        if self._n > n:
            out.append(f"only showing top {n} rows")
        text = "\n".join(out)
        print(text)
        return text

    def persist(self) -> "Table":
        """Parity no-op: ops here are eager, so the materialize-once caching
        the reference needed (``Graphframes.py:82-83``) is automatic."""
        return self

    cache = persist

    def collect(self) -> list:
        """All rows as named tuples — the driver-gather boundary
        (``Graphframes.py:100-110``), here a plain host read."""
        Row = namedtuple("Row", [re.sub(r"\W", "_", c) for c in self.columns])
        cols = [self._cols[c] for c in self.columns]
        return [Row(*(c[i] for c in cols)) for i in range(self._n)]

    def to_dict(self) -> dict[str, np.ndarray]:
        return dict(self._cols)

    # -- the dead data-slicer's surface (Graphframes.py:34-47) ---------------

    def with_row_ids(self, name: str = "_row_id") -> "Table":
        """``monotonically_increasing_id`` analog: contiguous int64 row ids."""
        return self.with_column(name, np.arange(self._n, dtype=np.int64))

    monotonically_increasing_id = with_row_ids

    def sort(self, *by: str, ascending: "bool | Sequence[bool]" = True) -> "Table":
        """Stable multi-column sort; ``ascending`` may be one bool or one
        per key (Spark's list form). Nulls order first ascending / last
        descending (asc_nulls_first / desc_nulls_last defaults).

        Descending keys are implemented by inverting a dense rank rather
        than reversing the sorted order, so every key direction is stable
        (reversal would flip tie order)."""
        if isinstance(ascending, (list, tuple)):
            flags = [bool(a) for a in ascending]
            if len(flags) != len(by):
                raise ValueError(
                    f"ascending has {len(flags)} entries for {len(by)} sort keys"
                )
        else:
            flags = [bool(ascending)] * len(by)
        keys = []
        for c, asc_ in zip(reversed(by), reversed(flags)):
            col = self._cols[c]
            null = _isnull(col)
            if col.dtype == object:
                vals = np.where(null, "", col).astype(str)
            elif np.issubdtype(col.dtype, np.floating):
                vals = np.where(null, 0.0, col)
            else:
                vals = col
            if asc_:
                keys.append(vals)
                keys.append(~null)  # more significant than the value: nulls first
            else:
                _, inv = np.unique(vals, return_inverse=True)
                keys.append(-inv)  # inverted dense rank = descending, any dtype
                keys.append(null)  # nulls last
        order = np.lexsort(tuple(keys))
        return self._replace({k: v[order] for k, v in self._cols.items()})

    orderBy = None  # assigned below

    def limit(self, n: int) -> "Table":
        return self._replace({k: v[:n] for k, v in self._cols.items()})

    def subtract(self, other: "Table") -> "Table":
        """Rows of self not present in ``other`` (set difference)."""
        if self.columns != other.columns:
            raise ValueError("subtract requires identical schemas")
        mine = _row_keys(list(self._cols.values()))
        theirs = _row_keys([other._cols[c] for c in self.columns])
        mask = ~np.isin(mine, theirs)
        return self._replace({k: v[mask] for k, v in self._cols.items()})

    def union(self, other: "Table") -> "Table":
        if self.columns != other.columns:
            raise ValueError("union requires identical schemas")
        return self._replace(
            {k: np.concatenate([v, other._cols[k]]) for k, v in self._cols.items()}
        )

    # -- relational ops (Spark DataFrame surface beyond the reference) -------

    def drop(self, *names: str) -> "Table":
        """Drop columns; missing names are ignored (Spark semantics)."""
        t = self._replace({k: v for k, v in self._cols.items() if k not in names})
        t._n = self._n  # dropping every column must not collapse the row count
        return t

    def dropna(self, subset: Sequence[str] | None = None) -> "Table":
        """Drop rows with a null in any of ``subset`` (default: all columns)."""
        mask = np.ones(self._n, dtype=bool)
        for c in subset or self.columns:
            mask &= ~_isnull(self._cols[c])
        return self._replace({k: v[mask] for k, v in self._cols.items()})

    def fillna(self, value, subset: Sequence[str] | None = None) -> "Table":
        """Replace nulls with ``value`` in type-matching columns (Spark
        semantics: a string value fills only string columns, a number only
        numeric columns; other columns pass through unchanged)."""
        out = dict(self._cols)
        for name in subset or self.columns:
            col = out[name]
            null = _isnull(col)
            if not null.any():
                continue
            numeric_value = isinstance(value, (int, float)) and not isinstance(value, bool)
            if col.dtype == object and isinstance(value, str):
                if _object_as_float(col, null) is None:  # genuinely a string col
                    out[name] = np.where(null, value, col)
            elif np.issubdtype(col.dtype, np.floating) and numeric_value:
                out[name] = np.where(null, col.dtype.type(value), col)
            elif (col.dtype == object and numeric_value
                  and _object_as_float(col, null) is not None):
                # nullable-int columns (object-promoted by joins)
                out[name] = np.where(null, value, col)
        return self._replace(out)

    def join(self, other: "Table", on, how: str = "inner",
             suffix: str = "_r") -> "Table":
        """Equi-join with SQL null semantics (null keys never match).

        ``on`` is a key column name or list present on both sides; output
        has one copy of each key column (coalesced, Spark USING-clause
        semantics), then the remaining left columns, then the remaining
        right columns (renamed with ``suffix`` on collision). Row order:
        left rows in order (each repeated per match), then — for
        right/full joins — unmatched right rows. ``how``: inner, left,
        right, full/outer, left_semi, left_anti (Spark names; ``_outer``
        suffixes accepted)."""
        how = _JOIN_ALIASES.get(how.lower())
        if how is None:
            raise ValueError(
                f"unknown join type; supported: {sorted(set(_JOIN_ALIASES))}"
            )
        on = [on] if isinstance(on, str) else list(on)
        for c in on:
            if c not in self._cols or c not in other._cols:
                raise KeyError(f"join key {c!r} must exist on both sides")

        lnull = np.zeros(self._n, dtype=bool)
        rnull = np.zeros(other._n, dtype=bool)
        for c in on:
            lnull |= _isnull(self._cols[c])
            rnull |= _isnull(other._cols[c])
        lk_cols, rk_cols = [], []
        for c in on:  # coerce mixed int/float key pairs so 1 matches 1.0
            a, b = self._cols[c], other._cols[c]
            if a.dtype != b.dtype and all(
                np.issubdtype(x.dtype, np.number) for x in (a, b)
            ):
                a, b = a.astype(np.float64), b.astype(np.float64)
            lk_cols.append(a)
            rk_cols.append(b)
        lkeys = _row_keys(lk_cols) if on else None
        rkeys = _row_keys(rk_cols) if on else None

        if how == "cross":
            if on:
                raise ValueError("cross join takes no key columns")
            li = np.repeat(np.arange(self._n), other._n)
            ri = np.tile(np.arange(other._n), self._n)
            return self._join_emit(other, on, li, ri, suffix)
        if not on:
            raise ValueError("equi-join requires key columns; use how='cross'")

        r_order = np.argsort(rkeys, kind="stable")
        r_valid = r_order[~rnull[r_order]]  # null keys never match
        rk = rkeys[r_valid]
        lo = np.searchsorted(rk, lkeys, "left")
        hi = np.searchsorted(rk, lkeys, "right")
        cnt = np.where(lnull, 0, hi - lo)

        if how == "left_semi":
            return self.filter(cnt > 0)
        if how == "left_anti":
            return self.filter(cnt == 0)

        # One output row per match; left/full keep unmatched left rows as a
        # single null-padded row (ri = -1 sentinel), in left-row position.
        keep_unmatched_left = how in ("left", "full")
        cnt2 = np.maximum(cnt, 1) if keep_unmatched_left else cnt
        total = int(cnt2.sum())
        starts = (np.cumsum(cnt2) - cnt2).astype(np.int64)  # exclusive cumsum
        li = np.repeat(np.arange(self._n), cnt2)
        ri = np.full(total, -1, dtype=np.int64)
        has = np.repeat(cnt > 0, cnt2)
        pos = np.arange(total) - np.repeat(starts, cnt2)
        ri[has] = r_valid[np.repeat(lo, cnt2)[has] + pos[has]]

        if how in ("right", "full"):
            rmatched = np.zeros(other._n, dtype=bool)
            rmatched[ri[ri >= 0]] = True
            extra = np.flatnonzero(~rmatched)
            li = np.concatenate([li, np.full(len(extra), -1, dtype=np.int64)])
            ri = np.concatenate([ri, extra])
        return self._join_emit(other, on, li, ri, suffix)

    def _join_emit(self, other: "Table", on: list, li: np.ndarray,
                   ri: np.ndarray, suffix: str) -> "Table":
        cols: dict[str, np.ndarray] = {}
        for c in on:  # coalesced key columns (USING semantics)
            kl = _take_nullable(self._cols[c], li)
            if (li < 0).any():  # rows from the right side only (right/full)
                kr = _take_nullable(other._cols[c], ri)
                kl = np.where(li < 0, kr, kl)
            cols[c] = kl
        for c in self.columns:
            if c not in on:
                cols[c] = _take_nullable(self._cols[c], li)
        for c in other.columns:
            if c not in on:
                name = c + suffix if c in cols else c
                if name in cols:
                    raise ValueError(f"column collision after suffixing: {name!r}")
                cols[name] = _take_nullable(other._cols[c], ri)
        return self._replace(cols)

    def group_by(self, *names: str) -> "GroupedTable":
        """Group rows by key columns (null keys group together, as in SQL
        GROUP BY); with no keys, one global group (``df.agg`` semantics)."""
        flat: list[str] = []
        for n in names:
            flat.extend(n if isinstance(n, (list, tuple)) else [n])
        return GroupedTable(self, flat)

    def agg(self, *specs, **named) -> "Table":
        """Global aggregation over the whole table (one output row)."""
        return self.group_by().agg(*specs, **named)

    # -- bridges -------------------------------------------------------------

    def flat_map_distinct(self, *names: str) -> np.ndarray:
        """The reference's vertex-set idiom ``.rdd.flatMap(...).distinct()``
        (``Graphframes.py:53``), vectorized: union of the given columns'
        values with nulls dropped, sorted."""
        cols = [self._cols[n] for n in (names or self.columns)]
        stacked = np.concatenate([c[~_isnull(c)] for c in cols])
        return np.unique(stacked)

    def to_edge_table(self, src_col: str, dst_col: str, num_rows_raw: int | None = None):
        """Factorize two string/int columns into a dense-int32
        :class:`~graphmine_tpu.io.edges.EdgeTable` — the device boundary.
        Replaces the sha1 UDF scheme (``Graphframes.py:57-74``); duplicate
        rows are kept, matching the reference.

        ``num_rows_raw``: the pre-null-filter row count for the EdgeTable's
        provenance field (this table cannot know how many rows an earlier
        ``filter`` removed); defaults to this table's current row count."""
        from graphmine_tpu.io.edges import _from_string_columns

        return _from_string_columns(
            self._cols[src_col],
            self._cols[dst_col],
            num_rows_raw=self._n if num_rows_raw is None else num_rows_raw,
        )

    # -- io ------------------------------------------------------------------

    @classmethod
    def read_parquet(cls, path: str, columns: Sequence[str] | None = None) -> "Table":
        """Glob/dir/file parquet read (``Graphframes.py:16``)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from graphmine_tpu.io.edges import _resolve_paths

        paths = _resolve_paths(path)
        table = pa.concat_tables(
            [pq.read_table(p, columns=list(columns) if columns else None) for p in paths]
        )
        cols = {
            name: table.column(name).to_numpy(zero_copy_only=False)
            for name in table.column_names
        }
        return cls(cols)

    @classmethod
    def from_records(cls, rows: Iterable[Sequence], names: Sequence[str]) -> "Table":
        data = list(zip(*rows)) or [[] for _ in names]
        return cls({n: np.asarray(list(v)) for n, v in zip(names, data)})

    def write_parquet(self, path: str, compression: str = "snappy") -> None:
        """Write one parquet file; ``None`` stays a parquet null (so the
        reference's null-domain rows round-trip, ``Graphframes.py:30``)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        pq.write_table(pa.table(self._to_arrow_cols()), path,
                       compression=compression)

    def write_csv(self, path: str, header: bool = True) -> None:
        import pyarrow as pa
        import pyarrow.csv as pacsv

        pacsv.write_csv(
            pa.table(self._to_arrow_cols()), path,
            write_options=pacsv.WriteOptions(include_header=header),
        )

    def _to_arrow_cols(self) -> dict:
        import pyarrow as pa

        out = {}
        for name, col in self._cols.items():
            if col.dtype == object:
                out[name] = pa.array(col.tolist())  # None -> null
            else:
                out[name] = pa.array(col)
        return out

    @classmethod
    def read_csv(cls, path: str, header: bool = True, sep: str = ",",
                 infer_schema: bool = True) -> "Table":
        """CSV read (``spark.read.csv``); without a header row, columns are
        named ``_c0..`` as Spark does. ``infer_schema=False`` keeps every
        column as strings (Spark's CSV default)."""
        import pyarrow as pa
        import pyarrow.csv as pacsv

        opts = pacsv.ReadOptions(autogenerate_column_names=not header)
        table = pacsv.read_csv(
            path, read_options=opts,
            parse_options=pacsv.ParseOptions(delimiter=sep),
        )
        if not header:
            table = table.rename_columns(
                [f"_c{i}" for i in range(table.num_columns)]
            )
        if not infer_schema:
            table = pa.table({
                name: table.column(name).cast(pa.string())
                for name in table.column_names
            })
        return cls({
            name: table.column(name).to_numpy(zero_copy_only=False)
            for name in table.column_names
        })


# Spark join-type names (and their no-underscore forms) → canonical type.
_JOIN_ALIASES = {
    "inner": "inner", "cross": "cross",
    "left": "left", "leftouter": "left", "left_outer": "left",
    "right": "right", "rightouter": "right", "right_outer": "right",
    "full": "full", "outer": "full", "fullouter": "full", "full_outer": "full",
    "semi": "left_semi", "leftsemi": "left_semi", "left_semi": "left_semi",
    "anti": "left_anti", "leftanti": "left_anti", "left_anti": "left_anti",
}


class GroupedTable:
    """Result of :meth:`Table.group_by` — Spark ``GroupedData`` surface.

    Group order in every output is first appearance in the source table
    (deterministic, unlike Spark). Aggregates ignore nulls except
    ``count("*")``; an all-null group yields a null result cell."""

    _FNS = ("count", "sum", "min", "max", "mean", "avg", "first",
            "count_distinct", "collect_list", "collect_set")

    def __init__(self, table: Table, keys: list):
        self._t = table
        self._keys = keys
        n = len(table)
        if keys:
            rk = _row_keys([table[c] for c in keys])
            _, first_idx, inv = np.unique(rk, return_index=True, return_inverse=True)
            order = np.argsort(first_idx, kind="stable")
            rank = np.empty(len(order), dtype=np.int64)
            rank[order] = np.arange(len(order))
            self._gid = rank[inv]
            self._first = first_idx[order]
            self._ngroups = len(order)
        else:  # global aggregation: one group, even over an empty table
            self._gid = np.zeros(n, dtype=np.int64)
            self._first = np.zeros(0, dtype=np.int64)
            self._ngroups = 1

    def count(self) -> Table:
        """Rows per group, Spark ``groupBy(...).count()`` (counts nulls)."""
        if "count" in self._keys:
            raise ValueError("grouping key is named 'count'; use agg() to name the output")
        cols = {c: self._t[c][self._first] for c in self._keys}
        cols["count"] = np.bincount(self._gid, minlength=self._ngroups).astype(np.int64)
        return self._t._replace(cols)

    def agg(self, *specs, **named) -> Table:
        """Aggregate. Specs: Spark dict style ``{"col": "fn"}`` (output
        named ``fn(col)``), tuples ``("col", "fn")``, or kwargs
        ``out=("col", "fn")``. Fns: count, sum, min, max, mean/avg,
        first, count_distinct, collect_list, collect_set."""
        items: list[tuple[str, str, str]] = []  # (out_name, col, fn)
        for spec in specs:
            if isinstance(spec, Mapping):
                for col, fn in spec.items():
                    items.append((f"{fn}({col})", col, fn))
            elif isinstance(spec, (tuple, list)) and len(spec) == 2:
                col, fn = spec
                items.append((f"{fn}({col})", col, fn))
            else:
                raise TypeError(f"bad agg spec {spec!r}")
        for out, (col, fn) in named.items():
            items.append((out, col, fn))
        if not items:
            return self.count()
        cols = {c: self._t[c][self._first] for c in self._keys}
        for out, col, fn in items:
            if out in cols:
                raise ValueError(f"duplicate output column {out!r}")
            cols[out] = self._agg_one(col, fn.lower())
        return self._t._replace(cols)

    def _numeric_value_cols(self, names) -> list:
        if names:
            return list(names)
        return [c for c in self._t.columns
                if c not in self._keys and self._t[c].dtype != object]

    def sum(self, *cols) -> Table:
        return self.agg({c: "sum" for c in self._numeric_value_cols(cols)})

    def min(self, *cols) -> Table:
        return self.agg({c: "min" for c in self._numeric_value_cols(cols)})

    def max(self, *cols) -> Table:
        return self.agg({c: "max" for c in self._numeric_value_cols(cols)})

    def mean(self, *cols) -> Table:
        return self.agg({c: "mean" for c in self._numeric_value_cols(cols)})

    avg = mean

    def _agg_one(self, col_name: str, fn: str) -> np.ndarray:
        g, n = self._gid, self._ngroups
        if fn == "count" and col_name == "*":
            return np.bincount(g, minlength=n).astype(np.int64)
        col = self._t[col_name]
        null = _isnull(col)
        nonnull_per_group = np.bincount(g[~null], minlength=n).astype(np.int64)
        if fn == "count":
            return nonnull_per_group
        if fn in ("count_distinct", "countdistinct", "nunique"):
            m = ~null
            if not m.any():
                return np.zeros(n, dtype=np.int64)
            pk = _row_keys([g[m], col[m]])
            _, idx = np.unique(pk, return_index=True)
            return np.bincount(g[m][idx], minlength=n).astype(np.int64)
        if fn in ("sum", "mean", "avg"):
            if col.dtype == object:
                # nullable-int columns (object-promoted by joins) still sum
                num = _object_as_float(col, null)
                if num is None:
                    raise TypeError(f"{fn} on non-numeric column {col_name!r}")
                col = num
            empty = nonnull_per_group == 0
            if fn == "sum" and np.issubdtype(col.dtype, np.integer) and not empty.any():
                s_int = np.zeros(n, dtype=np.int64)  # exact above 2**53
                np.add.at(s_int, g, col.astype(np.int64))
                return s_int
            vals = np.where(null, 0, col).astype(np.float64)
            s = np.bincount(g, weights=vals, minlength=n)
            if fn == "sum":
                return np.where(empty, np.nan, s)  # null for all-null groups
            return np.where(empty, np.nan, s / np.maximum(nonnull_per_group, 1))
        if fn in ("min", "max"):
            return _segment_extreme(col, null, g, n, largest=fn == "max")
        if fn == "first":
            # First row of each group (Spark first(), ignoreNulls=False).
            if len(col) == 0:
                return np.full(n, None, dtype=object)
            first = self._first if len(self._first) else np.zeros(n, dtype=np.int64)
            return col[first]
        if fn in ("collect_list", "collect_set"):
            order = np.argsort(g[~null], kind="stable")
            vals, gs = col[~null][order], g[~null][order]
            bounds = np.concatenate([[0], np.cumsum(np.bincount(gs, minlength=n))])
            out = np.empty(n, dtype=object)
            for i in range(n):
                chunk = vals[bounds[i]:bounds[i + 1]].tolist()
                out[i] = list(dict.fromkeys(chunk)) if fn == "collect_set" else chunk
            return out
        raise ValueError(f"unknown aggregate {fn!r}; supported: {self._FNS}")


def _take_nullable(col: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Gather ``col[idx]`` where ``idx == -1`` yields SQL null (int/bool
    columns are promoted to object to hold ``None``)."""
    miss = idx < 0
    if len(col) == 0:  # gather from an empty side: every row is null
        if np.issubdtype(col.dtype, np.floating):
            return np.full(len(idx), np.nan, dtype=col.dtype)
        return np.full(len(idx), None, dtype=object)
    out = col[np.where(miss, 0, idx)]
    if not miss.any():
        return out
    if col.dtype == object:
        out = out.copy()
        out[miss] = None
    elif np.issubdtype(col.dtype, np.floating):
        out = out.copy()
        out[miss] = np.nan
    else:
        out = out.astype(object)
        out[miss] = None
    return out


def _segment_extreme(col: np.ndarray, null: np.ndarray, gid: np.ndarray,
                     n: int, largest: bool) -> np.ndarray:
    """Per-group min/max ignoring nulls, any dtype, via one lexsort.

    Ascending sort within each group with nulls pushed to the far end from
    the answer: min = group's first element, max = group's last."""
    if len(col) == 0:
        if col.dtype == object:
            return np.full(n, None, dtype=object)
        return np.full(n, np.nan, dtype=np.float64)
    if col.dtype == object:
        # nullable-int columns (object-promoted by joins) compare
        # numerically; genuine string columns compare lexicographically
        num = _object_as_float(col, null)
        if num is not None:
            vals = np.where(null, 0.0, num)
        else:
            vals = np.where(null, "", col).astype(str)
    else:
        vals = np.where(null, col[~null][0] if (~null).any() else 0, col)
    null_key = ~null if largest else null  # nulls first for max, last for min
    order = np.lexsort((vals, null_key, gid))
    gs = gid[order]
    starts = np.flatnonzero(np.r_[True, gs[1:] != gs[:-1]])
    if largest:
        pick = np.r_[starts[1:] - 1, len(gs) - 1]
    else:
        pick = starts
    present = np.unique(gs)
    result = col[order[pick]]  # exact values in the column's own dtype
    res_null = null[order[pick]]
    if col.dtype == object:
        out = np.full(n, None, dtype=object)
        out[present] = np.where(res_null, None, result)
        return out
    if len(present) == n and not res_null.any():
        out = np.empty(n, dtype=col.dtype)  # no nulls: keep exact int dtype
        out[present] = result
        return out
    # all-null groups (or the keyless-empty case) become NaN
    out = np.full(n, np.nan, dtype=np.float64)
    out[present] = np.where(res_null, np.nan, result.astype(np.float64))
    return out


def _as_column(values) -> np.ndarray:
    arr = np.asarray(values)
    if arr.dtype.kind in ("U", "S"):
        arr = arr.astype(object)
    if arr.ndim != 1:
        raise ValueError(f"columns must be 1-D, got shape {arr.shape}")
    return arr


def _row_keys(cols: list[np.ndarray]) -> np.ndarray:
    """Hashable per-row keys for distinct/subtract, vectorized.

    Values are escaped before joining so the delimiter (and the null
    sentinel) can never collide with data content."""
    if not cols or len(cols[0]) == 0:
        return np.empty(0, dtype="U1")
    parts = []
    for c in cols:
        s = np.char.replace(c.astype(str).astype("U"), "\\", "\\\\")
        s = np.char.replace(s, "\x1f", "\\u")
        s = np.where(_isnull(c), "\\0", s).astype(object)
        parts.append(s)
    out = parts[0]
    for p in parts[1:]:
        out = out + "\x1f" + p
    return out.astype(str)


def _render(v, truncate: int) -> str:
    s = "null" if v is None else str(v)
    return s if truncate <= 0 or len(s) <= truncate else s[: truncate - 3] + "..."


# Spark camelCase aliases (call-site parity for migrating code).
Table.withColumnRenamed = Table.with_column_renamed
Table.withColumn = Table.with_column
Table.where = Table.filter
Table.orderBy = Table.sort
Table.dropDuplicates = Table.drop_duplicates
Table.toDict = Table.to_dict
Table.groupBy = Table.group_by
Table.groupby = Table.group_by


def read_parquet(path: str, columns: Sequence[str] | None = None) -> Table:
    """Module-level alias of :meth:`Table.read_parquet`."""
    return Table.read_parquet(path, columns)
