"""Columnar ``Table`` — the relational layer (Spark DataFrame contract).

The reference drives its whole preprocessing phase through Spark SQL
DataFrame ops (SURVEY §2.2 "DataFrame ops" row): ``read.parquet``
(``Graphframes.py:16``), ``withColumnRenamed`` ×4 (``:26-29``), a SQL-string
``filter("ParentDomain is not null and ChildDomain is not null")`` (``:30``),
``select``/``withColumn`` (``:70-73``), ``distinct``/``count``
(``:18,:54,:85``), ``show(10)`` (``:32,:68,:74,:82``), ``persist`` (``:82``)
and ``collect`` (``:100-110``). The dead data-slicer (``:34-47``) also used
``monotonically_increasing_id`` + ``sort``/``limit``/``subtract``.

This module reproduces that contract TPU-natively: a **host-side columnar
table** (NumPy arrays per column — the Arrow/Catalyst equivalent) whose ops
are all vectorized, with a small SQL predicate parser so the reference's
literal filter strings run unchanged. There is no lazy DAG and no shuffle:
every op materializes eagerly (``persist`` is therefore the identity, kept
for call-site parity), and ``collect`` is a plain host read rather than a
JVM→driver boundary. Device code never sees strings — the bridge to the
engine is :meth:`Table.to_edge_table`, which factorizes to dense int32.

Both snake_case and Spark's camelCase method names are provided.
"""

from __future__ import annotations

import re
from collections import namedtuple
from typing import Any, Callable, Iterable, Mapping, Sequence

import numpy as np

__all__ = ["Table", "read_parquet"]


# ---------------------------------------------------------------------------
# SQL predicate parser (the `filter("...")` surface, Graphframes.py:30)
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<num>-?\d+\.\d+|-?\d+)
      | '(?P<str>(?:[^'\\]|\\.)*)'
      | "(?P<dstr>(?:[^"\\]|\\.)*)"
      | (?P<op><=|>=|!=|<>|==|=|<|>)
      | (?P<lp>\()
      | (?P<rp>\))
      | (?P<comma>,)
      | (?P<word>[A-Za-z_][A-Za-z0-9_.]*)
    )""",
    re.VERBOSE,
)

_KEYWORDS = {"and", "or", "not", "is", "null", "in", "like", "true", "false"}


def _tokenize(expr: str) -> list[tuple[str, Any]]:
    tokens, pos = [], 0
    while pos < len(expr):
        m = _TOKEN_RE.match(expr, pos)
        if not m or m.end() == pos:
            if expr[pos:].strip() == "":
                break
            raise ValueError(f"cannot parse filter expression at: {expr[pos:]!r}")
        pos = m.end()
        if m.group("num") is not None:
            text = m.group("num")
            tokens.append(("lit", float(text) if "." in text else int(text)))
        elif m.group("str") is not None:
            tokens.append(("lit", m.group("str").replace("\\'", "'")))
        elif m.group("dstr") is not None:
            tokens.append(("lit", m.group("dstr").replace('\\"', '"')))
        elif m.group("op") is not None:
            tokens.append(("op", m.group("op")))
        elif m.group("lp"):
            tokens.append(("lp", "("))
        elif m.group("rp"):
            tokens.append(("rp", ")"))
        elif m.group("comma"):
            tokens.append(("comma", ","))
        else:
            word = m.group("word")
            low = word.lower()
            if low in _KEYWORDS:
                tokens.append(("kw", low))
            else:
                tokens.append(("ident", word))
    return tokens


class _Tri:
    """SQL three-valued logic: a boolean vector plus an ``unknown`` (null)
    vector. ``true``/``false``/``unknown`` are disjoint; a row passes a
    filter only when the predicate is *true* (unknown rows drop, and
    ``NOT unknown`` stays unknown — Spark semantics)."""

    __slots__ = ("v", "u")

    def __init__(self, v: np.ndarray, u: np.ndarray | None = None):
        self.v = v
        self.u = np.zeros(len(v), bool) if u is None else u

    def __and__(self, o: "_Tri") -> "_Tri":
        false = (~self.v & ~self.u) | (~o.v & ~o.u)
        v = self.v & o.v
        return _Tri(v, ~v & ~false)

    def __or__(self, o: "_Tri") -> "_Tri":
        v = self.v | o.v
        return _Tri(v, ~v & (self.u | o.u))

    def __invert__(self) -> "_Tri":
        return _Tri(~self.v & ~self.u, self.u)


class _PredicateParser:
    """Recursive-descent parser for the SQL predicate subset Spark-style
    ``filter`` strings use: comparisons, ``is [not] null``, ``like``,
    ``in (...)``, ``and``/``or``/``not``, parentheses. Evaluates under SQL
    three-valued logic (comparisons against null are *unknown*, not false)."""

    def __init__(self, tokens: list[tuple[str, Any]], columns: Mapping[str, np.ndarray], n: int):
        self.toks = tokens
        self.i = 0
        self.cols = columns
        self.n = n

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def take(self, kind=None, value=None):
        tok = self.peek()
        if kind is not None and tok[0] != kind:
            raise ValueError(f"expected {kind}, got {tok}")
        if value is not None and tok[1] != value:
            raise ValueError(f"expected {value!r}, got {tok}")
        self.i += 1
        return tok

    def parse(self) -> np.ndarray:
        tri = self.or_expr()
        if self.peek()[0] is not None:
            raise ValueError(f"trailing tokens: {self.toks[self.i:]}")
        return tri.v  # rows where the predicate is TRUE (unknown drops)

    def or_expr(self) -> _Tri:
        left = self.and_expr()
        while self.peek() == ("kw", "or"):
            self.take()
            left = left | self.and_expr()
        return left

    def and_expr(self) -> _Tri:
        left = self.not_expr()
        while self.peek() == ("kw", "and"):
            self.take()
            left = left & self.not_expr()
        return left

    def not_expr(self) -> _Tri:
        if self.peek() == ("kw", "not"):
            self.take()
            return ~self.not_expr()
        return self.comparison()

    def _operand(self):
        kind, val = self.peek()
        if kind == "lp":
            self.take()
            out = self.or_expr()
            self.take("rp")
            return ("mask", out)
        if kind == "ident":
            self.take()
            if val not in self.cols:
                raise KeyError(f"unknown column {val!r} in filter expression")
            return ("col", val)
        if kind == "lit":
            self.take()
            return ("lit", val)
        if kind == "kw" and val in ("true", "false"):
            self.take()
            return ("lit", val == "true")
        raise ValueError(f"unexpected token {self.peek()} in filter expression")

    def comparison(self) -> _Tri:
        left_kind, left = self._operand()
        if left_kind == "mask":
            return left
        kind, val = self.peek()
        if kind == "kw" and val == "is":
            self.take()
            negate = False
            if self.peek() == ("kw", "not"):
                self.take()
                negate = True
            self.take("kw", "null")
            mask = _isnull(self._resolve(left_kind, left))
            return _Tri(~mask if negate else mask)  # IS NULL is never unknown
        if kind == "kw" and val == "like":
            self.take()
            _, pat = self.take("lit")
            arr = self._resolve(left_kind, left)
            null = _isnull(arr)
            return _Tri(_like(arr, str(pat)) & ~null, null)
        if kind == "kw" and val == "in":
            self.take()
            self.take("lp")
            lits = []
            while True:
                _, lit = self.take("lit")
                lits.append(lit)
                if self.peek()[0] == "comma":
                    self.take()
                    continue
                self.take("rp")
                break
            arr = self._resolve(left_kind, left)
            hit = np.isin(
                arr, np.array(lits, dtype=arr.dtype if arr.dtype != object else object)
            )
            null = _isnull(arr)
            return _Tri(hit & ~null, null)
        if kind == "op":
            self.take()
            right_kind, right = self._operand()
            a = self._resolve(left_kind, left)
            b = self._resolve(right_kind, right)
            return _Tri(_compare(a, val, b), _isnull(a) | _isnull(b))
        if left_kind == "col":
            col = self.cols[left]
            if col.dtype == np.bool_:
                return _Tri(col.copy())
        raise ValueError(f"column {left!r} used as a predicate but is not boolean")

    def _resolve(self, kind, val):
        if kind == "col":
            return self.cols[val]
        return np.full(self.n, val, dtype=object if isinstance(val, str) else None)


def _isnull(col: np.ndarray) -> np.ndarray:
    if col.dtype == object:
        return np.frompyfunc(lambda v: v is None, 1, 1)(col).astype(bool)
    if np.issubdtype(col.dtype, np.floating):
        return np.isnan(col)
    return np.zeros(len(col), dtype=bool)


def _like(col: np.ndarray, pattern: str) -> np.ndarray:
    rx = re.compile(
        "^"
        + "".join(".*" if c == "%" else "." if c == "_" else re.escape(c) for c in pattern)
        + "$"
    )
    f = np.frompyfunc(lambda v: v is not None and rx.match(str(v)) is not None, 1, 1)
    return f(col).astype(bool)


def _compare(a: np.ndarray, op: str, b: np.ndarray) -> np.ndarray:
    null = _isnull(a) | _isnull(b)
    if a.dtype == object or b.dtype == object:
        a = np.where(null, "", a).astype(object)
        b = np.where(null, "", b).astype(object)
    if op in ("=", "=="):
        out = a == b
    elif op in ("!=", "<>"):
        out = a != b
    elif op == "<":
        out = a < b
    elif op == ">":
        out = a > b
    elif op == "<=":
        out = a <= b
    else:
        out = a >= b
    return np.asarray(out, dtype=bool) & ~null  # SQL: comparisons with null are false


# ---------------------------------------------------------------------------
# Table
# ---------------------------------------------------------------------------


class Table:
    """Immutable host-side columnar table with the Spark DataFrame op set.

    Columns are NumPy arrays of equal length; string columns use
    ``dtype=object`` with ``None`` as SQL null (matching the Arrow read
    path). All ops return new ``Table`` objects; none mutate.
    """

    def __init__(self, columns: Mapping[str, np.ndarray] | None = None, **kw):
        cols = dict(columns or {}, **kw)
        self._cols: dict[str, np.ndarray] = {}
        n = None
        for name, values in cols.items():
            arr = _as_column(values)
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(
                    f"column {name!r} has length {len(arr)}, expected {n}"
                )
            self._cols[name] = arr
        self._n = n or 0

    # -- structure ----------------------------------------------------------

    @property
    def columns(self) -> list[str]:
        return list(self._cols)

    @property
    def schema(self) -> dict[str, np.dtype]:
        return {k: v.dtype for k, v in self._cols.items()}

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, name: str) -> np.ndarray:
        return self._cols[name]

    def __repr__(self) -> str:
        return f"Table[{self._n} x {len(self._cols)}: {', '.join(self._cols)}]"

    def _replace(self, cols: dict[str, np.ndarray]) -> "Table":
        t = Table.__new__(Table)
        t._cols = cols
        t._n = len(next(iter(cols.values()))) if cols else 0
        return t

    # -- the reference's op surface -----------------------------------------

    def count(self) -> int:
        """Row count (``Graphframes.py:18,:54,:85``)."""
        return self._n

    def with_column_renamed(self, existing: str, new: str) -> "Table":
        """``withColumnRenamed`` (``Graphframes.py:26-29``)."""
        if existing not in self._cols:
            return self  # Spark semantics: silently no-op on missing column
        if new in self._cols and new != existing:
            # Spark would produce duplicate column names; a dict cannot, and
            # silently dropping a column loses data — fail loudly instead.
            raise ValueError(f"cannot rename {existing!r}: column {new!r} already exists")
        return self._replace(
            {(new if k == existing else k): v for k, v in self._cols.items()}
        )

    def filter(self, cond: "str | np.ndarray | Callable[[Table], np.ndarray]") -> "Table":
        """Row filter: SQL predicate string (``Graphframes.py:30``), boolean
        mask, or callable over the table."""
        if isinstance(cond, str):
            mask = _PredicateParser(_tokenize(cond), self._cols, self._n).parse()
        elif callable(cond) and not isinstance(cond, np.ndarray):
            mask = np.asarray(cond(self), dtype=bool)
        else:
            mask = np.asarray(cond, dtype=bool)
        return self._replace({k: v[mask] for k, v in self._cols.items()})

    where = None  # assigned below (alias)

    def select(self, *names: str) -> "Table":
        """Column projection (``Graphframes.py:53,:70,:92``)."""
        flat: list[str] = []
        for n in names:
            flat.extend(n if isinstance(n, (list, tuple)) else [n])
        missing = [n for n in flat if n not in self._cols]
        if missing:
            raise KeyError(f"unknown columns {missing}; have {self.columns}")
        return self._replace({n: self._cols[n] for n in flat})

    def with_column(self, name: str, values) -> "Table":
        """``withColumn`` (``Graphframes.py:71-73``): add/replace a column.
        ``values`` may be an array or a vectorized fn of the table."""
        arr = values(self) if callable(values) else values
        arr = _as_column(arr)
        if len(arr) != self._n:
            raise ValueError(f"column {name!r} length {len(arr)} != {self._n}")
        cols = dict(self._cols)
        cols[name] = arr
        return self._replace(cols)

    def distinct(self) -> "Table":
        """Distinct rows (``Graphframes.py:53,:85,:92``). Order of first
        appearance is preserved (deterministic, unlike Spark)."""
        if not self._cols:
            return self
        keys = _row_keys(list(self._cols.values()))
        _, idx = np.unique(keys, return_index=True)
        idx.sort()
        return self._replace({k: v[idx] for k, v in self._cols.items()})

    def drop_duplicates(self, subset: Sequence[str] | None = None) -> "Table":
        if subset is None:
            return self.distinct()
        keys = _row_keys([self._cols[c] for c in subset])
        _, idx = np.unique(keys, return_index=True)
        idx.sort()
        return self._replace({k: v[idx] for k, v in self._cols.items()})

    def show(self, n: int = 20, truncate: int = 20) -> str:
        """Pretty-print the first ``n`` rows (``Graphframes.py:32`` etc.);
        returns the rendered string (also printed)."""
        names = self.columns
        rows = [
            [_render(self._cols[c][i], truncate) for c in names]
            for i in range(min(n, self._n))
        ]
        widths = [
            max(len(c), *(len(r[j]) for r in rows)) if rows else len(c)
            for j, c in enumerate(names)
        ]
        sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        out = [sep, "|" + "|".join(f" {c:<{w}} " for c, w in zip(names, widths)) + "|", sep]
        for r in rows:
            out.append("|" + "|".join(f" {v:<{w}} " for v, w in zip(r, widths)) + "|")
        out.append(sep)
        if self._n > n:
            out.append(f"only showing top {n} rows")
        text = "\n".join(out)
        print(text)
        return text

    def persist(self) -> "Table":
        """Parity no-op: ops here are eager, so the materialize-once caching
        the reference needed (``Graphframes.py:82-83``) is automatic."""
        return self

    cache = persist

    def collect(self) -> list:
        """All rows as named tuples — the driver-gather boundary
        (``Graphframes.py:100-110``), here a plain host read."""
        Row = namedtuple("Row", [re.sub(r"\W", "_", c) for c in self.columns])
        cols = [self._cols[c] for c in self.columns]
        return [Row(*(c[i] for c in cols)) for i in range(self._n)]

    def to_dict(self) -> dict[str, np.ndarray]:
        return dict(self._cols)

    # -- the dead data-slicer's surface (Graphframes.py:34-47) ---------------

    def with_row_ids(self, name: str = "_row_id") -> "Table":
        """``monotonically_increasing_id`` analog: contiguous int64 row ids."""
        return self.with_column(name, np.arange(self._n, dtype=np.int64))

    monotonically_increasing_id = with_row_ids

    def sort(self, *by: str, ascending: bool = True) -> "Table":
        """Stable multi-column sort. Nulls order first ascending / last
        descending (Spark's asc_nulls_first / desc_nulls_last defaults)."""
        keys = []
        for c in reversed(by):
            col = self._cols[c]
            null = _isnull(col)
            if col.dtype == object:
                vals = np.where(null, "", col).astype(str)
            elif np.issubdtype(col.dtype, np.floating):
                vals = np.where(null, 0.0, col)
            else:
                vals = col
            keys.append(vals)
            keys.append(~null)  # more significant than the value: nulls first
        order = np.lexsort(tuple(keys))
        if not ascending:
            order = order[::-1]
        return self._replace({k: v[order] for k, v in self._cols.items()})

    orderBy = None  # assigned below

    def limit(self, n: int) -> "Table":
        return self._replace({k: v[:n] for k, v in self._cols.items()})

    def subtract(self, other: "Table") -> "Table":
        """Rows of self not present in ``other`` (set difference)."""
        if self.columns != other.columns:
            raise ValueError("subtract requires identical schemas")
        mine = _row_keys(list(self._cols.values()))
        theirs = _row_keys([other._cols[c] for c in self.columns])
        mask = ~np.isin(mine, theirs)
        return self._replace({k: v[mask] for k, v in self._cols.items()})

    def union(self, other: "Table") -> "Table":
        if self.columns != other.columns:
            raise ValueError("union requires identical schemas")
        return self._replace(
            {k: np.concatenate([v, other._cols[k]]) for k, v in self._cols.items()}
        )

    # -- bridges -------------------------------------------------------------

    def flat_map_distinct(self, *names: str) -> np.ndarray:
        """The reference's vertex-set idiom ``.rdd.flatMap(...).distinct()``
        (``Graphframes.py:53``), vectorized: union of the given columns'
        values with nulls dropped, sorted."""
        cols = [self._cols[n] for n in (names or self.columns)]
        stacked = np.concatenate([c[~_isnull(c)] for c in cols])
        return np.unique(stacked)

    def to_edge_table(self, src_col: str, dst_col: str, num_rows_raw: int | None = None):
        """Factorize two string/int columns into a dense-int32
        :class:`~graphmine_tpu.io.edges.EdgeTable` — the device boundary.
        Replaces the sha1 UDF scheme (``Graphframes.py:57-74``); duplicate
        rows are kept, matching the reference.

        ``num_rows_raw``: the pre-null-filter row count for the EdgeTable's
        provenance field (this table cannot know how many rows an earlier
        ``filter`` removed); defaults to this table's current row count."""
        from graphmine_tpu.io.edges import _from_string_columns

        return _from_string_columns(
            self._cols[src_col],
            self._cols[dst_col],
            num_rows_raw=self._n if num_rows_raw is None else num_rows_raw,
        )

    # -- io ------------------------------------------------------------------

    @classmethod
    def read_parquet(cls, path: str, columns: Sequence[str] | None = None) -> "Table":
        """Glob/dir/file parquet read (``Graphframes.py:16``)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from graphmine_tpu.io.edges import _resolve_paths

        paths = _resolve_paths(path)
        table = pa.concat_tables(
            [pq.read_table(p, columns=list(columns) if columns else None) for p in paths]
        )
        cols = {
            name: table.column(name).to_numpy(zero_copy_only=False)
            for name in table.column_names
        }
        return cls(cols)

    @classmethod
    def from_records(cls, rows: Iterable[Sequence], names: Sequence[str]) -> "Table":
        data = list(zip(*rows)) or [[] for _ in names]
        return cls({n: np.asarray(list(v)) for n, v in zip(names, data)})


def _as_column(values) -> np.ndarray:
    arr = np.asarray(values)
    if arr.dtype.kind in ("U", "S"):
        arr = arr.astype(object)
    if arr.ndim != 1:
        raise ValueError(f"columns must be 1-D, got shape {arr.shape}")
    return arr


def _row_keys(cols: list[np.ndarray]) -> np.ndarray:
    """Hashable per-row keys for distinct/subtract, vectorized.

    Values are escaped before joining so the delimiter (and the null
    sentinel) can never collide with data content."""
    parts = []
    for c in cols:
        s = np.char.replace(c.astype(str).astype("U"), "\\", "\\\\")
        s = np.char.replace(s, "\x1f", "\\u")
        s = np.where(_isnull(c), "\\0", s).astype(object)
        parts.append(s)
    out = parts[0]
    for p in parts[1:]:
        out = out + "\x1f" + p
    return out.astype(str)


def _render(v, truncate: int) -> str:
    s = "null" if v is None else str(v)
    return s if truncate <= 0 or len(s) <= truncate else s[: truncate - 3] + "..."


# Spark camelCase aliases (call-site parity for migrating code).
Table.withColumnRenamed = Table.with_column_renamed
Table.withColumn = Table.with_column
Table.where = Table.filter
Table.orderBy = Table.sort
Table.dropDuplicates = Table.drop_duplicates
Table.toDict = Table.to_dict


def read_parquet(path: str, columns: Sequence[str] | None = None) -> Table:
    """Module-level alias of :meth:`Table.read_parquet`."""
    return Table.read_parquet(path, columns)
