"""Device-mesh runtime — the framework's "cluster manager".

Replaces the reference's Spark runtime layer (``SparkContext("local[*]")``,
``Graphframes.py:12``, plus the implicit shuffle/scheduler): parallelism is
SPMD over a ``jax.sharding.Mesh``, and all cross-device traffic is XLA
collectives riding ICI (within a slice) / DCN (across slices). There is no
dynamic task scheduler to build — BSP supersteps map 1:1 onto jit programs.

Axis convention: a 1-D mesh over axis ``"v"`` (vertex-range sharding).
Multi-slice (DCN-spanning) meshes are a planned extension: the vertex axis
would factor into (slice, chip) so boundary exchange rides ICI within a
slice and only the reduced label vector crosses DCN.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

VERTEX_AXIS = "v"


def make_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the vertex axis.

    ``local[*]`` parity: with no arguments, uses every visible device.
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} visible"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (VERTEX_AXIS,))
