"""Device-mesh runtime — the framework's "cluster manager".

Replaces the reference's Spark runtime layer (``SparkContext("local[*]")``,
``Graphframes.py:12``, plus the implicit shuffle/scheduler): parallelism is
SPMD over a ``jax.sharding.Mesh``, and all cross-device traffic is XLA
collectives riding ICI (within a slice) / DCN (across slices). There is no
dynamic task scheduler to build — BSP supersteps map 1:1 onto jit programs.

Axis convention: a 1-D mesh over axis ``"v"`` (vertex-range sharding).
Multi-slice (DCN-spanning) meshes are a planned extension: the vertex axis
would factor into (slice, chip) so boundary exchange rides ICI within a
slice and only the reduced label vector crosses DCN.
"""

from __future__ import annotations

from collections import OrderedDict

import jax
import numpy as np
from jax.sharding import Mesh

VERTEX_AXIS = "v"
DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def make_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the vertex axis.

    ``local[*]`` parity: with no arguments, uses every visible device.
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} visible"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (VERTEX_AXIS,))


def surviving_mesh(num_devices: int, exclude=(), devices=None) -> Mesh:
    """A 1-D vertex-axis mesh over the devices that survived a loss.

    ``exclude``: indices (into ``jax.devices()`` order) of dead/suspect
    devices to route around — the elastic-degradation path
    (docs/RESILIENCE.md "Elastic mesh degradation") rebuilds its rung
    meshes through this so a chip that the runtime still *lists* but that
    just failed a collective is never re-enrolled. Takes the first
    ``num_devices`` survivors; raises when fewer remain.
    """
    if devices is None:
        devices = jax.devices()
    exclude = set(exclude)
    alive = [d for i, d in enumerate(devices) if i not in exclude]
    if num_devices > len(alive):
        raise ValueError(
            f"requested {num_devices} devices, only {len(alive)} survive "
            f"({len(exclude)} excluded of {len(devices)} visible)"
        )
    return Mesh(np.asarray(alive[:num_devices]), (VERTEX_AXIS,))


def make_multislice_mesh(
    num_slices: int, chips_per_slice: int | None = None, devices=None
) -> Mesh:
    """A 2-D ``("dcn", "ici")`` mesh for multi-slice / multi-host runs.

    The vertex axis of the sharded graph ops spans *both* axes (devices in
    row-major order: slice-major, chip-minor), so XLA decomposes each
    superstep's all-gather hierarchically — chips within a slice exchange
    over ICI, and only one copy of each slice-level chunk crosses DCN.
    This is the framework's answer to the reference's (never-exercised)
    multi-node story (``SparkContext("local[*]")``, ``Graphframes.py:12``).

    On a multi-host deployment call ``jax.distributed.initialize()`` first;
    ``jax.devices()`` then spans all hosts and this mesh covers the fleet.
    """
    if devices is None:
        devices = jax.devices()
    if chips_per_slice is None:
        if len(devices) % num_slices:
            raise ValueError(
                f"{len(devices)} devices do not divide into {num_slices} slices"
            )
        chips_per_slice = len(devices) // num_slices
    need = num_slices * chips_per_slice
    if need > len(devices):
        raise ValueError(f"requested {need} devices, only {len(devices)} visible")
    grid = np.asarray(devices[:need]).reshape(num_slices, chips_per_slice)
    return Mesh(grid, (DCN_AXIS, ICI_AXIS))


def initialize_distributed(**kw) -> bool:
    """Multi-host bootstrap: ``jax.distributed.initialize`` with idempotence.

    Call once per host before building meshes on a multi-host fleet (the
    coordinator address etc. come from the environment on TPU pods / SLURM
    via jax's own cluster auto-detection, or pass ``coordinator_address=``/
    ``num_processes=``/``process_id=`` explicitly). Returns True when the
    distributed runtime is (now) initialized, False when running
    single-process (no coordinator detectable) — callers can use the same
    code path either way, as jax.devices() reflects the fleet exactly when
    initialization happened. Explicit kwargs that fail to initialize raise.
    """
    import jax

    # jax.distributed.is_initialized is missing on some pinned releases
    # (0.4.x): probe it, falling back to the runtime's global client
    # state, so this entry point works on every jax this repo supports.
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        if probe():
            return True
    else:
        state = getattr(jax.distributed, "global_state", None)
        if state is not None and getattr(state, "client", None) is not None:
            return True
    try:
        jax.distributed.initialize(**kw)
    except ValueError as e:
        # Swallow only the benign no-cluster case: nothing auto-detectable
        # and nothing requested — jax then complains about the missing
        # coordinator_address. Matching on the variable name (not the full
        # sentence) tolerates jax rewording the message. Every other
        # failure — explicit kwargs, a partially-configured cluster
        # ("Number of processes must be defined."), RuntimeError from a
        # detected-but-unreachable coordinator — propagates, so a degraded
        # pod run can never silently continue as N independent
        # single-process runs.
        if kw or "coordinator_address" not in str(e):
            raise
        return False
    return True


# Shared compiled-program cache for jit(shard_map(...)) wrappers: a fresh
# wrapper per call would re-trace the program every invocation. Callers key
# on everything that shapes the program (mesh, static sizes) plus a tag.
# Bounded LRU: sweep-style workloads (tools/consistency_sweep.py) visit many
# distinct shapes, and each entry pins a compiled executable — unbounded
# growth would retain one per shape for the life of the process.
_SHARD_MAP_CACHE_MAX = 64
_SHARD_MAP_CACHE: OrderedDict = OrderedDict()


def cached_jit_shard_map(key, make):
    """Return (building once) ``jax.jit(make())`` memoized under ``key``.

    ``make`` is a zero-arg callable producing the shard_map-wrapped body;
    ``key`` must be hashable and include a per-call-site tag so different
    ops never collide. Used by ``parallel/knn.py`` and ``parallel/ppr.py``.
    Evicts least-recently-used entries past ``_SHARD_MAP_CACHE_MAX``.
    """
    fn = _SHARD_MAP_CACHE.get(key)
    if fn is None:
        fn = jax.jit(make())
        _SHARD_MAP_CACHE[key] = fn
        while len(_SHARD_MAP_CACHE) > _SHARD_MAP_CACHE_MAX:
            _SHARD_MAP_CACHE.popitem(last=False)
    else:
        _SHARD_MAP_CACHE.move_to_end(key)
    return fn
