"""Ring-sharded supersteps: fully distributed labels via ``ppermute``.

:mod:`graphmine_tpu.parallel.sharded` replicates the V-length label vector
on every device — the right trade until V reaches hundreds of millions.
This module is the memory-scalable design from SURVEY §5 (the domain's
"ring attention"): **labels stay vertex-range-sharded**, and each superstep
rotates the label chunks around the mesh ring with ``lax.ppermute`` (D
hops over ICI), gathering sender labels as each chunk passes. Per-device
memory is O(M/D + V/D) with no replicated O(V) term, so the graph size
ceiling scales linearly with the mesh.

The communication pattern per superstep is D ppermute steps of a [V/D]
int32 chunk = one full rotation ≈ the same bytes as one all-gather, but
peak memory never exceeds two chunks. This replaces the Pregel shuffle of
``Graphframes.py:81`` for the regime where the reference's Spark would
spill to disk.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from graphmine_tpu._jax_compat import pcast, shard_map
from jax import lax
from jax.sharding import PartitionSpec as P

from graphmine_tpu.ops.segment import segment_mode
from graphmine_tpu.parallel.mesh import VERTEX_AXIS
from graphmine_tpu.parallel.sharded import (
    ShardedGraph,
    _check_mesh,
    _check_pagerank_weighted,
    _pagerank_terms,
    _fixpoint_supersteps,
    _padded_init_labels,
    _pad_labels,
    _scan_supersteps,
)


def _check_ring_mesh(sg: ShardedGraph, mesh) -> None:
    """Ring schedules ppermute over the single ``VERTEX_AXIS`` — reject
    multi-axis meshes with a real error instead of a cryptic trace-time
    axis failure (the replicated ``sharded.*`` schedules handle 2-D
    ``("dcn", "ici")`` meshes; use those there)."""
    _check_mesh(sg, mesh)
    if tuple(mesh.axis_names) != (VERTEX_AXIS,):
        raise ValueError(
            f"ring schedules need a 1-D ('{VERTEX_AXIS}',) mesh (got axes "
            f"{tuple(mesh.axis_names)}); use the sharded_* replicated "
            "schedules on multi-slice meshes"
        )


def _ring_gather(chunk: jax.Array, global_idx: jax.Array, *, num_shards: int, chunk_size: int) -> jax.Array:
    """Gather ``values[global_idx]`` from a vertex-range-sharded vector.

    ``chunk`` is this device's [chunk_size] slice of the global vector.
    Rotates chunks one hop per step for ``num_shards`` steps; each device
    fills the positions of ``global_idx`` owned by the chunk currently in
    hand. After the full rotation every chunk is back home.

    This is the framework's ring collective — the all-to-all-free neighbor
    exchange primitive (SURVEY §2.3's "comms backend" component).
    """
    my = lax.axis_index(VERTEX_AXIS).astype(jnp.int32)
    perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]
    # Mark the accumulator device-varying up front so the loop carry type
    # is stable (ppermute output is varying; zeros start out unvarying).
    out = pcast(jnp.zeros(global_idx.shape, chunk.dtype), (VERTEX_AXIS,), to="varying")

    def fill(chunk, out, r):
        owner = jnp.mod(my - r, num_shards)
        sel = (global_idx // chunk_size) == owner
        local = jnp.clip(global_idx - owner * chunk_size, 0, chunk_size - 1)
        return jnp.where(sel, chunk[local], out)

    def step(r, state):
        chunk, out = state
        out = fill(chunk, out, r)
        chunk = lax.ppermute(chunk, VERTEX_AXIS, perm)
        return chunk, out

    # num_shards - 1 rotations; the last owner's chunk is filled in hand —
    # a trailing ppermute would only ship chunks home to be discarded.
    chunk, out = lax.fori_loop(0, num_shards - 1, step, (chunk, out))
    return fill(chunk, out, num_shards - 1)


def _lpa_ring_body(own, recv_local, send, deg, *, chunk_size, num_shards):
    """Per-device ring LPA superstep: ring-gather sender labels →
    shard-local segment-mode → select. Output stays sharded."""
    recv_local, send, deg = recv_local[0], send[0], deg[0]
    msg = _ring_gather(own, send, num_shards=num_shards, chunk_size=chunk_size)
    mode, _ = segment_mode(recv_local, msg, num_segments=chunk_size)
    return jnp.where(deg > 0, mode, own).astype(jnp.int32)


def _lpa_ring_body_weighted(own, recv_local, send, deg, w, *, chunk_size,
                            num_shards):
    """Weighted variant: the per-message weights are shard-local (they
    ride the same padded rows as the message CSR; padding weight 0), so
    only the labels travel the ring — the mode becomes an argmax of
    weight sums via ``segment_mode(weights=...)``."""
    recv_local, send, deg, w = recv_local[0], send[0], deg[0], w[0]
    msg = _ring_gather(own, send, num_shards=num_shards, chunk_size=chunk_size)
    mode, _ = segment_mode(recv_local, msg, num_segments=chunk_size, weights=w)
    return jnp.where(deg > 0, mode, own).astype(jnp.int32)


def _cc_ring_body(own, recv_local, send, deg, *, chunk_size, num_shards):
    """Min-label propagation + ring-based pointer jumping, labels sharded."""
    recv_local, send, deg = recv_local[0], send[0], deg[0]
    gather = partial(_ring_gather, num_shards=num_shards, chunk_size=chunk_size)
    msg = gather(own, send)
    neigh_min = jax.ops.segment_min(msg, recv_local, num_segments=chunk_size)
    new = jnp.where(deg > 0, jnp.minimum(own, neigh_min), own).astype(jnp.int32)
    # Pointer jumping (labels = min(labels, labels[labels])) — the gather at
    # arbitrary global ids is just another ring pass over the updated chunks.
    rep = gather(new, new)
    return jnp.minimum(new, rep).astype(jnp.int32)


def _ring_step_fn(sg: ShardedGraph, mesh, body, n_graph_args: int = 3):
    return shard_map(
        partial(body, chunk_size=sg.chunk_size, num_shards=sg.num_shards),
        mesh=mesh,
        in_specs=(P(VERTEX_AXIS),) + (P(VERTEX_AXIS, None),) * n_graph_args,
        out_specs=P(VERTEX_AXIS),
    )


@partial(jax.jit, static_argnames=("max_iter", "mesh"))
def ring_label_propagation(
    sg: ShardedGraph, mesh, max_iter: int = 5, init_labels: jax.Array | None = None
) -> jax.Array:
    """Distributed synchronous LPA with sharded labels.

    Semantics identical to :func:`graphmine_tpu.ops.lpa.label_propagation`
    and :func:`graphmine_tpu.parallel.sharded.sharded_label_propagation`
    (asserted by the virtual-device parity tests); differs only in the
    memory/communication schedule. Returns int32 labels ``[V]``.
    """
    _check_ring_mesh(sg, mesh)
    labels = _padded_init_labels(sg) if init_labels is None else _pad_labels(init_labels, sg)
    if sg.msg_weight is not None:
        step_fn = _ring_step_fn(sg, mesh, _lpa_ring_body_weighted, n_graph_args=4)
        labels = _scan_supersteps(
            lambda l: step_fn(l, sg.msg_recv_local, sg.msg_send, sg.degrees,
                              sg.msg_weight),
            labels, max_iter,
        )
    else:
        step_fn = _ring_step_fn(sg, mesh, _lpa_ring_body)
        labels = _scan_supersteps(
            lambda l: step_fn(l, sg.msg_recv_local, sg.msg_send, sg.degrees),
            labels, max_iter,
        )
    return labels[: sg.num_vertices]


@partial(jax.jit, static_argnames=("max_iter", "mesh", "weighted"))
def ring_pagerank(
    sg: ShardedGraph,
    mesh,
    out_degrees: jax.Array,
    alpha: float = 0.85,
    max_iter: int = 100,
    tol: float = 1e-6,
    weighted: bool | None = None,
) -> jax.Array:
    """Distributed PageRank with the rank vector fully sharded.

    Parity with :func:`graphmine_tpu.ops.pagerank.pagerank` and
    :func:`graphmine_tpu.parallel.sharded.sharded_pagerank` (virtual-mesh
    tested); differs only in the schedule: per power iteration the
    rank/out-degree contribution chunks rotate the ring (one
    ``_ring_gather``), the dangling mass and the convergence delta are
    two scalar ``psum``s, and no device ever holds the full [V] rank
    vector. ``sg`` must come from a **directed** graph; for a weighted
    one pass float out-edge weight sums as ``out_degrees`` (see
    :func:`~graphmine_tpu.parallel.sharded.sharded_pagerank`). Returns
    float32 ranks ``[V]`` summing to 1.
    """
    _check_ring_mesh(sg, mesh)
    weighted = _check_pagerank_weighted(sg, out_degrees, weighted)
    v = sg.num_vertices
    chunk, d = sg.chunk_size, sg.num_shards
    inv_out, reset, dangling = _pagerank_terms(
        out_degrees, v, sg.padded_vertices
    )

    def body(inv_o, res, dang, recv_local, send, *weight):
        recv_local, send = recv_local[0], send[0]
        w = weight[0][0] if weighted else None
        gather = partial(_ring_gather, num_shards=d, chunk_size=chunk)

        def cond(state):
            _, delta, it = state
            return (delta > tol) & (it < max_iter)

        def step(state):
            pr, _, it = state
            msg = gather(pr * inv_o, send) * (recv_local < chunk)
            if w is not None:
                msg = msg * w
            inflow = jax.ops.segment_sum(msg, recv_local, num_segments=chunk)
            dm = lax.psum(jnp.sum(jnp.where(dang, pr, 0.0)), VERTEX_AXIS)
            new = alpha * (inflow + dm * res) + (1.0 - alpha) * res
            delta = lax.psum(jnp.abs(new - pr).sum(), VERTEX_AXIS)
            return new, delta, it + 1

        pr, _, _ = lax.while_loop(
            cond, step, (res, jnp.float32(1.0), jnp.int32(0))
        )
        return pr

    sharded = P(VERTEX_AXIS)
    data = P(VERTEX_AXIS, None)
    pr = shard_map(
        body,
        mesh=mesh,
        in_specs=(sharded, sharded, sharded, data, data)
        + ((data,) if weighted else ()),
        out_specs=sharded,
    )(inv_out, reset, dangling, sg.msg_recv_local, sg.msg_send,
      *((sg.msg_weight,) if weighted else ()))
    return pr[:v]


@partial(jax.jit, static_argnames=("max_iter", "mesh"))
def ring_connected_components(sg: ShardedGraph, mesh, max_iter: int = 0) -> jax.Array:
    """Distributed weakly-connected components with sharded labels; parity
    with :func:`graphmine_tpu.ops.cc.connected_components`."""
    _check_ring_mesh(sg, mesh)
    step_fn = _ring_step_fn(sg, mesh, _cc_ring_body)
    return _fixpoint_supersteps(
        lambda l: step_fn(l, sg.msg_recv_local, sg.msg_send, sg.degrees), sg, max_iter
    )
