"""Source-sharded personalized PageRank — query-axis data parallelism.

:func:`graphmine_tpu.ops.pagerank.parallel_personalized_pagerank` runs one
batched ``[V, S]`` power iteration; every source column shares the per-edge
gather/segment-sum. The natural multi-chip axis for that program is the
SOURCE dimension (every source needs every edge, so the graph replicates —
for vertex-axis memory scaling use ``sharded_pagerank``/``ring_pagerank``):
each device owns ``ceil(S/D)`` teleport columns and runs the identical
power iteration on its slice, with zero cross-device traffic until the
final column concatenation. This is the framework's query-DP pattern — the
Spark-"partitioned DataFrame ops" analog for analysis queries rather than
graph state (SURVEY §2.3).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from graphmine_tpu._jax_compat import shard_map
import numpy as np
from jax.sharding import PartitionSpec as P

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.parallel.mesh import VERTEX_AXIS, cached_jit_shard_map


def _ppr_chunk(src, dst, sources, alpha, tol, *, v, max_iter):
    from graphmine_tpu.ops.pagerank import _batched_ppr

    return _batched_ppr(
        src, dst, v, sources, alpha, max_iter, tol,
        varying_axes=(VERTEX_AXIS,),
    )


def _compiled_body(mesh, v: int, chunk: int, max_iter: int):
    """One compiled program per (mesh, V, source-chunk, max_iter);
    alpha/tol ride as traced scalars so parameter sweeps reuse it."""
    return cached_jit_shard_map(
        ("ppr", mesh, v, chunk, max_iter),
        lambda: shard_map(
            partial(_ppr_chunk, v=v, max_iter=max_iter),
            mesh=mesh,
            # the mesh's one axis shards the SOURCE dimension here
            in_specs=(P(), P(), P(VERTEX_AXIS), P(), P()),
            out_specs=P(None, VERTEX_AXIS),
        ),
    )


def sharded_personalized_pagerank(
    graph: Graph,
    sources,
    mesh,
    alpha: float = 0.85,
    max_iter: int = 100,
    tol: float = 1e-6,
) -> jax.Array:
    """``parallel_personalized_pagerank`` with sources sharded over the
    mesh. Returns ``[V, S]`` (columns sharded); parity with the
    single-device op is asserted by the virtual-mesh tests.

    Convergence matches the single-device batch exactly: the per-chunk
    ``while_loop`` delta is ``pmax``-coupled across the mesh, so every
    column iterates until the globally slowest column meets ``tol`` —
    the same max-over-all-columns stopping rule as the batch, making the
    two paths comparable at float-noise tolerance.
    """
    from graphmine_tpu.ops.pagerank import _validate_sources

    v, d = graph.num_vertices, mesh.size
    sources = _validate_sources(sources, v)
    if sources.size == 0:
        return jnp.zeros((v, 0), jnp.float32)
    s = len(sources)
    chunk = -(-s // d)
    # Padding columns recompute a valid source; sliced away below.
    padded = np.full(d * chunk, sources[0], np.int32)
    padded[:s] = sources
    out = _compiled_body(mesh, v, chunk, max_iter)(
        graph.src, graph.dst, jnp.asarray(padded),
        jnp.float32(alpha), jnp.float32(tol),
    )
    return out[:, :s]
