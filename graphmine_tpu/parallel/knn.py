"""Ring-sharded kNN + LOF over the device mesh.

The north-star outlier path (BASELINE.json: "kNN-graph + LOF ... batched
all-pairs-distance + top-k") runs single-device in :mod:`ops/knn` — every
row's distances need every point, so a naive GSPMD partition of the
all-pairs matmul replicates the full ``[N, F]`` point set per device.
This module is the memory-scalable design, the same schedule as
:mod:`parallel/ring`'s LPA: points stay row-sharded, chunks rotate around
the mesh ring via ``ppermute``, and each device folds the visiting chunk
into a running top-k for its own rows. Per-device memory is
O(N/D x (F + k)) plus one visiting chunk — no replicated [N, F] term,
and each rotation step's distance tile is still one MXU matmul.

Semantics match :func:`graphmine_tpu.ops.knn.knn` (self excluded by
global id, duplicates kept, squared Euclidean, ascending) — pinned by
the virtual-mesh parity tests — with one scoped difference: among
*exactly tied* distances (duplicate points), neighbor order follows the
ring visit order rather than ascending global index, so tied neighbor
id lists can differ while the distance lists agree.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from graphmine_tpu._jax_compat import shard_map
import numpy as np
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from graphmine_tpu.ops.knn import _tiled_knn
# the one jitted wrapper of the shared LOF formula (ops/lof.py owns it)
from graphmine_tpu.ops.lof import _lof_from_knn_jit as _lof_from_knn
from graphmine_tpu.parallel.mesh import VERTEX_AXIS, cached_jit_shard_map


def _knn_ring_body(pts, *, n: int, k: int, chunk: int, num_shards: int,
                   row_tile: int):
    """Per-device ring kNN (runs under shard_map; ``pts`` is this device's
    ``[chunk, F]`` row slice). Each hop folds the visiting chunk into the
    running top-k via the shared :func:`ops.knn._tiled_knn` core
    (id-equality self-exclusion, padding slots masked) and one ``top_k``
    over ``[chunk, 2k]``; D-1 ppermute hops total."""
    my = lax.axis_index(VERTEX_AXIS).astype(jnp.int32)
    local_gid = my * chunk + jnp.arange(chunk, dtype=jnp.int32)
    best_d = jnp.full((chunk, k), jnp.inf, jnp.float32)
    best_g = jnp.zeros((chunk, k), jnp.int32)
    perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]
    visit = pts
    for r in range(num_shards):
        owner = jnp.mod(my - r, num_shards)
        visit_gid = owner * chunk + jnp.arange(chunk, dtype=jnp.int32)
        d2, idx = _tiled_knn(
            pts, visit, k, row_tile,
            ref_mask=visit_gid < n,
            query_ids=local_gid, ref_ids=visit_gid,
        )
        cat_d = jnp.concatenate([best_d, d2], axis=1)
        cat_g = jnp.concatenate([best_g, visit_gid[idx]], axis=1)
        neg, pos = lax.top_k(-cat_d, k)
        best_d = -neg
        best_g = jnp.take_along_axis(cat_g, pos, axis=1)
        if r != num_shards - 1:
            visit = lax.ppermute(visit, VERTEX_AXIS, perm)
    return best_d, best_g


def _compiled_body(mesh, n: int, k: int, chunk: int, row_tile: int):
    """One compiled ring program per (mesh, n, k, chunk, row_tile) — a
    fresh wrapper per call would re-trace the D-unrolled ring every
    invocation."""
    return cached_jit_shard_map(
        ("knn_ring", mesh, n, k, chunk, row_tile),
        lambda: shard_map(
            partial(_knn_ring_body, n=n, k=k, chunk=chunk,
                    num_shards=mesh.size, row_tile=row_tile),
            mesh=mesh,
            in_specs=P(VERTEX_AXIS, None),
            out_specs=(P(VERTEX_AXIS, None), P(VERTEX_AXIS, None)),
        ),
    )


def can_shard(n: int, num_devices: int, k: int) -> bool:
    """Whether an ``[n, F]`` point set can ride the ring with this ``k``:
    every per-device chunk (``ceil(n/D)``) must hold at least ``k``
    candidates for the per-hop top-k. The single owner of the constraint
    :func:`sharded_knn` enforces — dispatchers use this instead of
    re-deriving it."""
    return 0 < k < n and k <= -(-n // num_devices)


def sharded_knn(points, mesh, k: int, row_tile: int = 1024):
    """k nearest neighbors with the point set sharded over a 1-D mesh.

    ``points``: host ``[N, F]`` array. Returns ``(d2, idx)`` jax arrays
    of shape ``[N, k]``, vertex-range sharded over the mesh — same
    contract as :func:`graphmine_tpu.ops.knn.knn` (ascending squared
    distances, self excluded, duplicates kept).
    """
    points = np.asarray(points, np.float32)
    n, f = points.shape
    d = mesh.size
    chunk = -(-n // d)
    if not can_shard(n, d, k):
        if not 0 < k < n:
            raise ValueError(f"k={k} must be < number of points {n}")
        raise ValueError(
            f"k={k} exceeds the per-device chunk {chunk} (= ceil(N/D)); "
            "use fewer devices or the single-device ops.knn path"
        )
    padded = np.zeros((d * chunk, f), np.float32)
    padded[:n] = points
    pts = jax.device_put(padded, NamedSharding(mesh, P(VERTEX_AXIS, None)))
    d2, gid = _compiled_body(mesh, n, k, chunk, row_tile)(pts)
    return d2[:n], gid[:n]


def _ivf_search_body(q_gid, row_sub, pts, m_gid, m_valid, *, k: int):
    """Per-device slice of the IVF cluster-batched search (runs under
    shard_map): this device's chunk rows, one ``lax.map`` of the shared
    :func:`ops.ann._search_clusters` block over them. Points and the
    member tables are replicated — they are O(N x F) / O(n_sub x Lmax)
    small next to the O(candidate-pairs) distance work being split."""
    from graphmine_tpu.ops.ann import _search_clusters

    def one_chunk(args):
        qg, s = args
        mg = m_gid[s]
        return _search_clusters(pts[qg], qg, pts[mg], mg, m_valid[s], k)

    return lax.map(one_chunk, (q_gid, row_sub))


def mesh_ivf_search_exec(mesh):
    """A ``search_exec`` for :func:`graphmine_tpu.ops.ann.ivf_knn` that
    splits the cluster-batched search — the dominant distance work — over
    ``mesh``. Chunk rows are padded to a device-count multiple (appended
    at the end: ``ivf_knn`` slices real rows back off) and row-sharded;
    each device searches its share. One compiled program per (mesh, table
    shapes, k) — the same compile-per-dataset trade the single-device IVF
    path already makes."""

    def exec_fn(pts, m_gid, m_valid, q_gid, row_sub, k):
        d = mesh.size
        r, b = q_gid.shape
        r_pad = -(-r // d) * d
        qg = np.zeros((r_pad, b), np.int32)
        qg[:r] = q_gid
        # padded rows point at sublist 0 with query id 0: searched like
        # any chunk, sliced off by the caller, never read back
        rs = np.zeros((r_pad,), np.int32)
        rs[:r] = row_sub
        body = cached_jit_shard_map(
            ("ivf_search", mesh, pts.shape, m_gid.shape, r_pad, b, k),
            lambda: shard_map(
                partial(_ivf_search_body, k=k),
                mesh=mesh,
                in_specs=(
                    P(VERTEX_AXIS, None), P(VERTEX_AXIS),
                    P(None, None), P(None, None), P(None, None),
                ),
                out_specs=(
                    P(VERTEX_AXIS, None, None), P(VERTEX_AXIS, None, None)
                ),
            ),
        )
        return body(
            jnp.asarray(qg), jnp.asarray(rs), jnp.asarray(pts),
            jnp.asarray(m_gid), jnp.asarray(m_valid),
        )

    return exec_fn


def sharded_lof(points, mesh, k: int = 128, row_tile: int = 1024,
                impl: str = "auto", sink=None):
    """Distributed LOF scores over the device mesh.

    ``impl`` (r6, same policy surface as :func:`ops.lof.lof_scores`):

    - ``"exact"`` — ring-sharded all-pairs kNN (the r2 path): points stay
      row-sharded, chunks rotate via ``ppermute``.
    - ``"ivf"`` — the IVF-flat candidate reduction with its search stage
      sharded over the mesh (:func:`mesh_ivf_search_exec`), so the mesh
      path does LESS work per output slot instead of ring all-pairs. The
      index build (k-means, inverted lists) and final merge stay
      host/default-device — they are a small fraction of the exact
      path's distance work. A pathology-guard fallback inside ``ivf_knn``
      lands on the single-device exact path, LOUDLY (warning +
      ``ivf_fallback`` record through ``sink``).
    - ``"auto"`` — :func:`ops.lof.select_lof_impl`'s measured crossover
      decides (IVF from ~131K points); the choice is emitted as an
      ``impl_selected`` record when ``sink`` is given.

    The post-kNN gathers (``kdist[idx]``, ``lrd[idx]``) touch only ``[N]``
    vectors, so GSPMD's inserted collectives are small. Returns float32
    ``[N]``.
    """
    from graphmine_tpu.ops.lof import select_lof_impl

    if impl not in ("auto", "ivf", "exact"):
        raise ValueError(
            f"unknown sharded LOF impl {impl!r}; use 'auto', 'ivf' or "
            "'exact'"
        )
    n = int(np.asarray(points).shape[0])
    family, reason = select_lof_impl(n, k, impl=impl)
    if sink is not None:
        from graphmine_tpu.obs.costmodel import lof_cost
        from graphmine_tpu.ops.lof import resolved_ivf_min_points

        sink.emit(
            "impl_selected", op="lof_knn", impl=family, requested=impl,
            n=n, k=k, devices=int(mesh.size), reason=reason,
            thresholds={"lof_ivf_min_points": resolved_ivf_min_points()},
            cost=lof_cost(
                family, n, k, features=int(np.asarray(points).shape[-1]),
                devices=int(mesh.size),
            ).record(),
        )
    if family == "ivf":
        from graphmine_tpu.ops.ann import ivf_knn

        d2, gid = ivf_knn(
            points, k=k, sink=sink, search_exec=mesh_ivf_search_exec(mesh)
        )
        return _lof_from_knn(d2, gid, k)
    d2, gid = sharded_knn(points, mesh, k, row_tile)
    return _lof_from_knn(d2, gid, k)
