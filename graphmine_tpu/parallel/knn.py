"""Ring-sharded kNN + LOF over the device mesh.

The north-star outlier path (BASELINE.json: "kNN-graph + LOF ... batched
all-pairs-distance + top-k") runs single-device in :mod:`ops/knn` — every
row's distances need every point, so a naive GSPMD partition of the
all-pairs matmul replicates the full ``[N, F]`` point set per device.
This module is the memory-scalable design, the same schedule as
:mod:`parallel/ring`'s LPA: points stay row-sharded, chunks rotate around
the mesh ring via ``ppermute``, and each device folds the visiting chunk
into a running top-k for its own rows. Per-device memory is
O(N/D x (F + k)) plus one visiting chunk — no replicated [N, F] term,
and each rotation step's distance tile is still one MXU matmul.

Semantics match :func:`graphmine_tpu.ops.knn.knn` (self excluded by
global id, duplicates kept, squared Euclidean, ascending) — pinned by
the virtual-mesh parity tests — with one scoped difference: among
*exactly tied* distances (duplicate points), neighbor order follows the
ring visit order rather than ascending global index, so tied neighbor
id lists can differ while the distance lists agree.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from graphmine_tpu._jax_compat import shard_map
import numpy as np
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from graphmine_tpu.ops.knn import _tiled_knn
# the one jitted wrapper of the shared LOF formula (ops/lof.py owns it)
from graphmine_tpu.ops.lof import _lof_from_knn_jit as _lof_from_knn
from graphmine_tpu.parallel.mesh import VERTEX_AXIS, cached_jit_shard_map


def _knn_ring_body(pts, *, n: int, k: int, chunk: int, num_shards: int,
                   row_tile: int):
    """Per-device ring kNN (runs under shard_map; ``pts`` is this device's
    ``[chunk, F]`` row slice). Each hop folds the visiting chunk into the
    running top-k via the shared :func:`ops.knn._tiled_knn` core
    (id-equality self-exclusion, padding slots masked) and one ``top_k``
    over ``[chunk, 2k]``; D-1 ppermute hops total."""
    my = lax.axis_index(VERTEX_AXIS).astype(jnp.int32)
    local_gid = my * chunk + jnp.arange(chunk, dtype=jnp.int32)
    best_d = jnp.full((chunk, k), jnp.inf, jnp.float32)
    best_g = jnp.zeros((chunk, k), jnp.int32)
    perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]
    visit = pts
    for r in range(num_shards):
        owner = jnp.mod(my - r, num_shards)
        visit_gid = owner * chunk + jnp.arange(chunk, dtype=jnp.int32)
        d2, idx = _tiled_knn(
            pts, visit, k, row_tile,
            ref_mask=visit_gid < n,
            query_ids=local_gid, ref_ids=visit_gid,
        )
        cat_d = jnp.concatenate([best_d, d2], axis=1)
        cat_g = jnp.concatenate([best_g, visit_gid[idx]], axis=1)
        neg, pos = lax.top_k(-cat_d, k)
        best_d = -neg
        best_g = jnp.take_along_axis(cat_g, pos, axis=1)
        if r != num_shards - 1:
            visit = lax.ppermute(visit, VERTEX_AXIS, perm)
    return best_d, best_g


def _compiled_body(mesh, n: int, k: int, chunk: int, row_tile: int):
    """One compiled ring program per (mesh, n, k, chunk, row_tile) — a
    fresh wrapper per call would re-trace the D-unrolled ring every
    invocation."""
    return cached_jit_shard_map(
        ("knn_ring", mesh, n, k, chunk, row_tile),
        lambda: shard_map(
            partial(_knn_ring_body, n=n, k=k, chunk=chunk,
                    num_shards=mesh.size, row_tile=row_tile),
            mesh=mesh,
            in_specs=P(VERTEX_AXIS, None),
            out_specs=(P(VERTEX_AXIS, None), P(VERTEX_AXIS, None)),
        ),
    )


def can_shard(n: int, num_devices: int, k: int) -> bool:
    """Whether an ``[n, F]`` point set can ride the ring with this ``k``:
    every per-device chunk (``ceil(n/D)``) must hold at least ``k``
    candidates for the per-hop top-k. The single owner of the constraint
    :func:`sharded_knn` enforces — dispatchers use this instead of
    re-deriving it."""
    return 0 < k < n and k <= -(-n // num_devices)


def sharded_knn(points, mesh, k: int, row_tile: int = 1024):
    """k nearest neighbors with the point set sharded over a 1-D mesh.

    ``points``: host ``[N, F]`` array. Returns ``(d2, idx)`` jax arrays
    of shape ``[N, k]``, vertex-range sharded over the mesh — same
    contract as :func:`graphmine_tpu.ops.knn.knn` (ascending squared
    distances, self excluded, duplicates kept).
    """
    points = np.asarray(points, np.float32)
    n, f = points.shape
    d = mesh.size
    chunk = -(-n // d)
    if not can_shard(n, d, k):
        if not 0 < k < n:
            raise ValueError(f"k={k} must be < number of points {n}")
        raise ValueError(
            f"k={k} exceeds the per-device chunk {chunk} (= ceil(N/D)); "
            "use fewer devices or the single-device ops.knn path"
        )
    padded = np.zeros((d * chunk, f), np.float32)
    padded[:n] = points
    pts = jax.device_put(padded, NamedSharding(mesh, P(VERTEX_AXIS, None)))
    d2, gid = _compiled_body(mesh, n, k, chunk, row_tile)(pts)
    return d2[:n], gid[:n]


def sharded_lof(points, mesh, k: int = 128, row_tile: int = 1024):
    """Distributed LOF scores: ring-sharded kNN + the shared LOF formula.

    The post-kNN gathers (``kdist[idx]``, ``lrd[idx]``) touch only ``[N]``
    vectors, so GSPMD's inserted collectives are small; the O(N^2) work
    stays ring-scheduled. Returns float32 ``[N]`` (sharded).
    """
    d2, gid = sharded_knn(points, mesh, k, row_tile)
    return _lof_from_knn(d2, gid, k)
