"""Sharded graph container + distributed LPA / CC supersteps.

The distributed design (SURVEY §2.3, §5): **1-D vertex-range sharding**.
Device ``d`` owns the contiguous vertex chunk ``[d*Vc, (d+1)*Vc)`` and every
message *received* by those vertices. Because the message CSR is sorted by
receiving vertex, each device's messages are a contiguous slice, padded to
the max shard size so shapes are static. One superstep is then:

    gather from the replicated label vector (local HBM, no comms)
      → shard-local segment-mode / segment-min over owned vertices
      → ``all_gather`` of the updated chunks over the mesh axis (ICI)

This is the TPU equivalent of a Pregel superstep's shuffle
(``Graphframes.py:81``): per-iteration cross-device traffic is exactly one
tiled all-gather of the V-length label vector — dense, contiguous and
ICI-friendly — instead of a JVM hash shuffle. Power-law skew (SURVEY §7
hard part 3) only affects padding, not correctness: chunks are padded to
the largest shard's message count.

Scale note: labels are replicated (int32 V-vector per device — ~400 MB at
100M vertices), which is the right trade on TPU where HBM is 16-32 GB and
the edge arrays dominate. The edge/message arrays — the actual O(E) term —
are fully sharded.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from graphmine_tpu._jax_compat import shard_map
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from graphmine_tpu.graph.container import Graph, build_graph
from graphmine_tpu.ops.segment import segment_mode
from graphmine_tpu.pipeline.resilience import DivergenceError


# ---- in-loop divergence tripwires -----------------------------------------
# Cheap on-device guards inside the superstep loops (ISSUE 2): NaN/Inf
# ranks, labels outside the padded vertex-id range, period-2 oscillation,
# CC monotonicity violations. The guards are pure device reductions over
# the replicated/sharded iterate; every K supersteps a host callback
# records the FIRST firing (kind, offending shard, superstep), and the
# non-jitted public wrappers raise a classified
# :class:`~graphmine_tpu.pipeline.resilience.DivergenceError` (retryable —
# the canonical cause is transient device corruption) instead of returning
# silently-garbage labels. Armed only when ``tripwire_every > 0``: the
# unarmed programs are byte-identical to the pre-tripwire ones.

_TRIP_KINDS = (
    "none", "label_out_of_range", "oscillation", "nonfinite_ranks",
    "cc_nonmonotone",
)
_TRIP: list = []
# One owner at a time for the trip buffer: the recorder callback's
# identity is baked into the compiled program at trace time (a per-call
# closure would defeat the jit cache and retrace every invocation), so
# the buffer is process-global — and concurrent ARMED calls from
# different threads could steal or erase each other's trips. Armed calls
# serialize on this lock; unarmed calls never touch it.
import threading as _threading

_TRIP_LOCK = _threading.Lock()


def _run_armed(thunk):
    """Run an armed (tripwire_every > 0) computation with exclusive
    ownership of the trip buffer, clearing stale state first and raising
    the recorded DivergenceError after the flush."""
    with _TRIP_LOCK:
        _TRIP.clear()
        return _raise_if_tripped(thunk())


def _record_trip(kind_code, shard, iteration):
    """Host side of the tripwire callback; keeps only the first event
    (later supersteps of an already-poisoned iterate add no forensics)."""
    if not _TRIP:
        _TRIP.append((int(kind_code), int(shard), int(iteration)))


def _fire_trip(fire, kind, shard, iteration):
    """Invoke the host recorder only when a guard actually fired — the
    clean path pays the reduction, never the callback."""
    lax.cond(
        fire,
        lambda args: jax.debug.callback(_record_trip, *args),
        lambda args: None,
        (kind, shard, iteration),
    )


def _raise_if_tripped(outputs):
    """Block on ``outputs``, flush pending callback effects, then surface
    the recorded trip as a DivergenceError. block_until_ready alone only
    waits for the OUTPUT buffers — under async dispatch a debug callback
    can still be queued on the callback thread when they land, and an
    unflushed exit-check firing would let corrupted labels escape."""
    jax.block_until_ready(outputs)
    barrier = getattr(jax, "effects_barrier", None)
    if barrier is not None:
        barrier()
    if _TRIP:
        code, shard, it = _TRIP[0]
        _TRIP.clear()
        raise DivergenceError(_TRIP_KINDS[code], shard, it)
    return outputs


def _label_tripwire(new, cur, prev, it, chunk_size, every):
    """LPA guards: label-out-of-range (a wrapped gather index / corrupted
    collective puts ids outside [0, v_pad)) and period-2 oscillation
    (state t+1 == state t-1 while != state t — synchronous LPA's known
    livelock; bounded max_iter hides it as a silently-wrong answer)."""
    v_pad = new.shape[0]
    bad = (new < 0) | (new >= v_pad)
    oob = jnp.any(bad)
    osc = jnp.all(new == prev) & jnp.any(new != cur)
    kind = jnp.where(oob, 1, jnp.where(osc, 2, 0))
    shard = (jnp.argmax(bad).astype(jnp.int32) // chunk_size)
    fire = (kind > 0) & (((it + 1) % every) == 0)
    _fire_trip(fire, kind, shard, it + 1)


def _cc_tripwire(new, cur, it, chunk_size, every):
    """CC guards: label range plus monotonicity — min-propagation labels
    can only decrease; any increase means corrupted state."""
    v_pad = new.shape[0]
    bad = (new < 0) | (new >= v_pad)
    mono = new > cur
    kind = jnp.where(jnp.any(bad), 1, jnp.where(jnp.any(mono), 4, 0))
    # Attribute the shard by the REPORTED kind: with simultaneous range
    # and monotonicity violations in different shards, blaming a
    # monotonicity-only shard for an out-of-range label would send
    # device forensics to the wrong chip.
    mask = jnp.where(jnp.any(bad), bad, mono)
    shard = (jnp.argmax(mask).astype(jnp.int32) // chunk_size)
    fire = (kind > 0) & (((it + 1) % every) == 0)
    _fire_trip(fire, kind, shard, it + 1)


def _lpa_range_tripwire(new, cur, it, chunk_size, every):
    """Range-only LPA guard for the fixpoint runner (r7 serving repair).
    The oscillation guard needs the previous iterate, which the fixpoint
    carry doesn't hold — a period-2 livelock simply never reaches
    frontier 0 and exhausts the repair budget, which the serving layer's
    full-recompute fallback already handles."""
    v_pad = new.shape[0]
    bad = (new < 0) | (new >= v_pad)
    kind = jnp.where(jnp.any(bad), 1, 0)
    shard = (jnp.argmax(bad).astype(jnp.int32) // chunk_size)
    fire = (kind > 0) & (((it + 1) % every) == 0)
    _fire_trip(fire, kind, shard, it + 1)


def _rank_tripwire(new, it, chunk_size, every):
    """PageRank guard: NaN/Inf anywhere in the rank vector. NaN is
    absorbing through the power iteration AND satisfies no convergence
    test (delta > tol is False for NaN), so an unguarded loop exits
    'converged' with garbage."""
    bad = ~jnp.isfinite(new)
    kind = jnp.where(jnp.any(bad), 3, 0)
    shard = (jnp.argmax(bad).astype(jnp.int32) // chunk_size)
    fire = (kind > 0) & (((it + 1) % every) == 0)
    _fire_trip(fire, kind, shard, it + 1)


# ---- on-device superstep telemetry ----------------------------------------
# Cheap counters ACCUMULATED IN THE LOOP CARRY (ISSUE 3): labels-changed /
# frontier size per superstep, per-shard active counts (the load-imbalance
# ratio GraphBLAST-style frontier telemetry makes sparse iteration
# debuggable with), and rank-residual norms for the power iteration. They
# ride the scan/while carry and come back WITH the final labels in the one
# existing device->host transfer — zero extra host syncs, zero extra
# collectives (the reductions run on the replicated/gathered iterate every
# device already holds). Off by default: the telemetry=False programs are
# byte-identical to the pre-telemetry ones.


@dataclass(frozen=True)
class SuperstepTelemetry:
    """Per-superstep counters from a sharded LPA/CC run.

    ``labels_changed[t]``: vertices whose label changed at superstep t
    (synchronous label propagation's frontier — exactly the vertices
    whose neighbors must re-reduce next step). ``shard_changed[t, d]``:
    the same count split by owning shard — the max/mean ratio is the
    load-imbalance signal (a power-law hub shard staying hot while the
    rest converge). ``iterations``: supersteps actually run (== rows for
    LPA's fixed count; the converged prefix for CC)."""

    labels_changed: np.ndarray      # [T] int32
    shard_changed: np.ndarray       # [T, D] int32
    iterations: int

    @property
    def frontier(self) -> np.ndarray:
        return self.labels_changed

    def imbalance_ratio(self) -> np.ndarray:
        """Per-superstep max-shard / mean-shard activity (1.0 = perfectly
        balanced; quiescent supersteps report 1.0, not NaN)."""
        mean = self.shard_changed.mean(axis=1)
        peak = self.shard_changed.max(axis=1, initial=0)
        return np.where(mean > 0, peak / np.maximum(mean, 1e-9), 1.0)


@dataclass(frozen=True)
class PowerIterTelemetry:
    """Per-iteration residuals from a sharded PageRank run:
    ``residuals[t]`` is the global L1 delta, ``shard_residuals[t, d]``
    its per-shard split (imbalance + where mass is still moving), over
    the ``iterations`` actually run before convergence/max_iter."""

    residuals: np.ndarray           # [T] float32
    shard_residuals: np.ndarray     # [T, D] float32
    iterations: int


def _telemetry_row(new, cur, chunk_size):
    """One superstep's counters, on device: (changed total, per-shard
    changed). Operates on the padded [D*Vc] iterate, so the reshape is
    exact; padding vertices never change (their label is their id)."""
    diff = new != cur
    d = new.shape[0] // chunk_size
    per_shard = jnp.sum(
        diff.reshape(d, chunk_size), axis=1, dtype=jnp.int32
    )
    return jnp.sum(per_shard), per_shard


def _residual_row(new, pr, chunk_size):
    """One power iteration's residuals: (L1 delta, per-shard L1)."""
    diff = jnp.abs(new - pr)
    d = new.shape[0] // chunk_size
    per_shard = diff.reshape(d, chunk_size).sum(axis=1)
    return jnp.sum(per_shard), per_shard


def _vertex_axes(mesh):
    """The mesh axes the vertex dimension is sharded over.

    A 1-D mesh uses the plain vertex axis; a multi-slice 2-D
    ``("dcn", "ici")`` mesh shards vertices over both axes (slice-major),
    so collectives decompose hierarchically — ICI inside a slice, DCN
    across slices."""
    names = tuple(mesh.axis_names)
    return names[0] if len(names) == 1 else names


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class ShardedGraph:
    """Vertex-range-sharded message CSR with static shapes.

    Fields (D = mesh size, Vc = padded vertices per shard, Mp = padded
    messages per shard):

    msg_recv_local : int32 [D, Mp]  receiver minus chunk start; padding = Vc
                     (out-of-range ⇒ dropped by segment reductions)
    msg_send       : int32 [D, Mp]  global sender vertex id; padding = 0
    degrees        : int32 [D, Vc]  per-owned-vertex message count (0 ⇒ keep)
    num_vertices   : int            true V (static)
    chunk_size     : int            Vc (static)
    num_shards     : int            D (static)
    """

    msg_recv_local: jax.Array
    msg_send: jax.Array
    degrees: jax.Array
    num_vertices: int = dataclasses.field(metadata=dict(static=True))
    chunk_size: int = dataclasses.field(metadata=dict(static=True))
    num_shards: int = dataclasses.field(metadata=dict(static=True))
    # Stacked degree-bucket plan for the fast LPA shard body (see
    # ops/bucketed_mode.py for the single-device analysis): per width
    # class c, bucket_send[c] is int32 [D, n_c, w_c] of global sender ids
    # (padding rows/slots = padded_vertices, the label sentinel slot) and
    # bucket_target[c] is int32 [D, n_c] of LOCAL owned-vertex indices
    # (padding rows = chunk_size, dropped by the scatter). Shapes are
    # uniform across shards — SPMD requires one program. Empty tuples =
    # no plan; the sort-based segment_mode body is used instead.
    bucket_send: tuple = ()
    bucket_target: tuple = ()
    # Optional float32 [D, Mp] per-message weights (weighted LPA via the
    # sort shard body; padding slots carry weight 0 and are dropped by the
    # recv sentinel anyway).
    msg_weight: jax.Array | None = None
    # Weighted bucket plan (r2): per class, float32 [D, n_c, w_c] weights
    # aligned slot-for-slot with bucket_send (padding slots 0). Empty on
    # unweighted graphs.
    bucket_weight: tuple = ()
    # Stacked propagation-blocking plan (r7, ops/blocking.py): each
    # shard's vertex chunk is a BIN GROUP — destination-range bins over
    # the shard's local CSR, shard-local tiles, the same one-all_gather
    # ring exchange. blk_src[d]: int32 [Mp] sender ids in sender-major
    # order (padding = padded_vertices, the label sentinel slot);
    # blk_pos[d]: each streamed message's slot in the shard's binned tile
    # (padding messages land in a scratch region past the bins). Per
    # width class c: blk_row_idx[c] int32 [D, n_c, w_c] TILE slots
    # (padding = the reserved sentinel slot), blk_row_target[c] int32
    # [D, n_c] LOCAL owned-vertex indices (padding rows = chunk_size + j
    # scratch, the bucketed plan's trick), blk_row_weight[c] optional
    # float32 [D, n_c, w_c]. None/empty = no blocked plan.
    blk_src: jax.Array | None = None
    blk_pos: jax.Array | None = None
    blk_row_idx: tuple = ()
    blk_row_target: tuple = ()
    blk_row_weight: tuple = ()
    blk_tile_alloc: int = dataclasses.field(
        metadata=dict(static=True), default=0
    )
    # 2D edge partition with neighbor-only frontier exchange (r16,
    # ISSUE 15): the blocked bin groups above, with the in-edges of each
    # shard additionally grouped by the OWNER shard of their sources.
    # Labels stay vertex-range SHARDED (no replicated V-vector, no full
    # all_gather); per superstep each shard ships to each peer exactly
    # the label slots that peer's bins read, as one padded
    # ``lax.ppermute`` shift per peer offset.
    #
    # x2d_send_tab : int32 [D, D-1, B] — LOCAL indices of this shard's
    #                own chunk to ship at peer offset r (axis-1 index
    #                r-1); padding slots = 0 (shipped but never read).
    # x2d_src_local: int32 [D, Mp] — the blocked sender-major stream
    #                remapped onto the COMPACT label table
    #                ``[own (Vc) | peer bufs (D-1)*B | sentinel]``;
    #                padding messages point at the sentinel slot.
    # x2d_boundary : B, the padded per-peer boundary width (static —
    #                one shared SPMD width across all (shard, peer)
    #                pairs). x2d_boundary_total: the exact UNPADDED
    #                boundary slot count summed over every (shard, peer)
    #                pair — the cost model's exchanged-bytes numerator.
    # A 2D partition drops ``blk_src`` (the replicated-gather stream ids
    # it replaces); the remaining blk_* arrays are shared verbatim, so
    # the bin tiles — and therefore the labels — are bit-identical to
    # the blocked family's.
    x2d_send_tab: jax.Array | None = None
    x2d_src_local: jax.Array | None = None
    x2d_boundary: int = dataclasses.field(
        metadata=dict(static=True), default=0
    )
    x2d_boundary_total: int = dataclasses.field(
        metadata=dict(static=True), default=0
    )

    @property
    def padded_vertices(self) -> int:
        return self.chunk_size * self.num_shards


def partition_graph(
    graph_or_src,
    dst=None,
    num_vertices: int | None = None,
    num_shards: int | None = None,
    mesh=None,
    pad_multiple: int = 8,
    build_bucket_plan: bool = False,
    build_blocked_plan: bool = False,
    blocked_tile_slots: int | None = None,
    build_plan2d: bool = False,
) -> ShardedGraph:
    """Partition a graph's message CSR into vertex-range shards (host-side).

    Accepts either a :class:`Graph` or raw ``(src, dst)`` arrays. The shard
    count comes from ``num_shards`` or ``mesh``. ``build_bucket_plan``
    precomputes the stacked degree-bucket plan the fast LPA shard body
    uses (host work + its own HBM, amortized once per graph like the CSR
    itself) — opt in when the partition feeds LPA; CC/PageRank/ring
    consumers never read it. ``build_blocked_plan`` (r7, mutually
    exclusive with ``build_bucket_plan``) precomputes the stacked
    propagation-blocking plan instead: each shard's chunk becomes a bin
    group of shard-local destination tiles (``ops/blocking.py``), used by
    the blocked LPA **and** CC shard bodies; ``blocked_tile_slots``
    overrides the per-bin tile budget (tests force multi-bin layouts).
    ``build_plan2d`` (r16) extends the blocked bin groups with the
    source axis: each shard's in-edges are additionally grouped by the
    owner shard of their sources, yielding the per-peer boundary gather
    tables of the ``sharded_2d`` family (labels sharded, neighbor-only
    ``ppermute`` exchange instead of the full all_gather); the blocked
    stream ids are remapped onto the compact per-shard label table and
    ``blk_src`` is dropped.
    """
    if build_bucket_plan and (build_blocked_plan or build_plan2d):
        raise ValueError(
            "build_bucket_plan and build_blocked_plan/build_plan2d are "
            "mutually exclusive — one plan family per partition"
        )
    if mesh is not None and num_shards is None:
        num_shards = mesh.size
    if num_shards is None:
        raise ValueError("pass num_shards or mesh")
    if not isinstance(graph_or_src, Graph):
        # One source of truth for message-CSR construction semantics.
        # Host-side (r3): this graph exists only to be sliced into shards
        # below — materializing it on one device first would OOM exactly
        # the configs the multi-device schedules are for.
        graph_or_src = build_graph(
            graph_or_src, dst, num_vertices=num_vertices, to_device=False
        )
    g = graph_or_src
    recv = np.asarray(g.msg_recv)
    send = np.asarray(g.msg_send)
    w_msg = None if g.msg_weight is None else np.asarray(g.msg_weight, np.float32)
    num_vertices = g.num_vertices

    d = num_shards
    vc = -(-num_vertices // d)  # ceil
    vc = -(-vc // pad_multiple) * pad_multiple
    # recv is CSR-sorted ascending: shard boundaries come from d binary
    # searches instead of an O(M) divide + bincount pass.
    offsets = np.zeros(d + 1, dtype=np.int64)
    offsets[1:-1] = np.searchsorted(recv, np.arange(1, d) * vc)
    offsets[-1] = len(recv)
    counts = np.diff(offsets)
    mp = max(int(counts.max(initial=0)), 1)
    mp = -(-mp // pad_multiple) * pad_multiple
    # Hard int32 guard on the EXACT padded per-shard message count (the
    # planner's plan-time model uses an estimate; receiver-range sharding
    # is data-skew-dependent, so the real bound is checked here): the
    # shard bodies gather with int32 indices into the [mp]-row message
    # arrays, and a count past 2^31-1 would wrap silently (VERDICT r4
    # weak 2). Loud failure with the remedy instead.
    int32_max = (1 << 31) - 1
    if mp > int32_max:
        worst = int(np.argmax(counts))
        raise ValueError(
            f"per-shard message count {mp:,} (shard {worst} holds "
            f"{int(counts[worst]):,} of {len(recv):,} messages) exceeds the "
            f"int32 gather-index bound {int32_max:,}; add devices so every "
            f"receiver-range shard's messages fit int32"
        )

    # Per-shard slice copies write straight into the padded rows (no temp
    # per shard, no full-array pre-fill — only the padded tails are filled).
    recv_local = np.empty((d, mp), dtype=np.int32)
    send_pad = np.empty((d, mp), dtype=np.int32)
    w_pad = None if w_msg is None else np.zeros((d, mp), dtype=np.float32)
    for s in range(d):
        lo, hi = offsets[s], offsets[s + 1]
        n = hi - lo
        np.subtract(recv[lo:hi], s * vc, out=recv_local[s, :n], casting="unsafe")
        recv_local[s, n:] = vc  # Vc = drop sentinel
        send_pad[s, :n] = send[lo:hi]
        send_pad[s, n:] = 0
        if w_pad is not None:
            w_pad[s, :n] = w_msg[lo:hi]

    # Degrees come free from the CSR pointer (O(V) diff, not an O(M)
    # bincount over the messages); padded vertices get degree 0.
    ptr = np.asarray(g.msg_ptr, dtype=np.int64)
    deg = np.zeros(d * vc, dtype=np.int32)
    deg[:num_vertices] = np.diff(ptr).astype(np.int32)
    deg = deg.reshape(d, vc)

    bucket_send, bucket_target, bucket_weight = (), (), ()
    if build_bucket_plan:
        bucket_send, bucket_target, bucket_weight = _build_shard_bucket_plan(
            deg, send_pad, counts, vc, d, w_pad
        )
    blk = {}
    if build_blocked_plan or build_plan2d:
        blk = _build_shard_blocked_plan(
            deg, send_pad, counts, vc, d, w_pad, blocked_tile_slots
        )
    if build_plan2d:
        blk.update(_build_shard_plan2d(blk.pop("blk_src"), vc, d, pad_multiple))

    # Fields stay host-side (NumPy): shard_graph_arrays does the one
    # device placement, directly to the mesh sharding — no staging copy
    # on the default device.
    return ShardedGraph(
        msg_recv_local=recv_local,
        msg_send=send_pad,
        degrees=deg,
        num_vertices=num_vertices,
        chunk_size=vc,
        num_shards=d,
        bucket_send=bucket_send,
        bucket_target=bucket_target,
        msg_weight=w_pad,
        bucket_weight=bucket_weight,
        **blk,
    )


def _build_shard_bucket_plan(deg, send_pad, counts, chunk_size, d, w_pad=None):
    """Stacked per-shard degree-bucket plan with uniform shapes.

    Every shard's owned vertices are bucketed on the shared 1.10x width
    ladder (``ops/bucketed_mode._extend_widths``); per class the row count
    is padded to the max across shards so one SPMD program serves all
    devices. No histogram path here — a per-shard [n, V] count matrix
    would replicate per device; mega-hubs ride wide sort rows instead.

    Vectorized across shards (one grouped argsort + per-class batched
    gathers instead of classes x shards ``_class_rows`` calls — the
    round-1 host-side scaling wall, VERDICT item 6). Semantics are pinned
    against the direct ``_class_rows`` reference by
    ``tests/test_sharded.py::test_bucket_plan_matches_class_rows_reference``.
    """
    from graphmine_tpu.ops.bucketed_mode import _extend_widths

    sentinel_send = chunk_size * d          # the label sentinel slot
    widths = _extend_widths(int(deg.max(initial=1)))
    classes = np.searchsorted(widths, np.maximum(deg, 1))  # [d, vc]
    # local CSR start of each owned vertex inside its shard's message run
    ptr = np.zeros((d, chunk_size), dtype=np.int64)
    np.cumsum(deg[:, :-1], axis=1, out=ptr[:, 1:])

    eligible = deg > 0
    n_classes = len(widths)
    # Group owned vertices by class in one stable argsort per shard;
    # ineligible (deg == 0) vertices sort to a trailing pseudo-class.
    # Stability keeps rows in ascending vertex order within each class,
    # matching _class_rows' nonzero() order.
    sort_key = np.where(eligible, classes, n_classes).astype(np.int64)
    order = np.argsort(sort_key, axis=1, kind="stable")       # [d, vc]
    flat = (np.arange(d, dtype=np.int64)[:, None] * (n_classes + 1) + sort_key)
    cnt = np.bincount(flat.ravel(), minlength=d * (n_classes + 1))
    cnt = cnt.reshape(d, n_classes + 1)                       # [d, classes+1]
    start = np.zeros_like(cnt)
    np.cumsum(cnt[:, :-1], axis=1, out=start[:, 1:])
    # _class_rows clamps gather indices to the shard's true message count.
    max_idx = np.maximum(counts.astype(np.int64) - 1, 0)[:, None, None]

    bucket_send, bucket_target, bucket_weight = [], [], []
    for c in np.unique(classes[eligible]):
        w = int(widths[c])
        n_s = cnt[:, c]                                       # rows per shard
        n_c = int(n_s.max())
        j = np.arange(n_c, dtype=np.int64)[None, :]           # [1, n_c]
        row_valid = j < n_s[:, None]                          # [d, n_c]
        pos = np.minimum(start[:, c, None] + j, deg.shape[1] - 1)
        rows = np.take_along_axis(order, pos, 1)              # [d, n_c]
        ptr_r = np.take_along_axis(ptr, rows, 1)
        deg_r = np.where(row_valid, np.take_along_axis(deg, rows, 1), 0)
        offs = np.arange(w, dtype=np.int64)[None, None, :]
        idx = ptr_r[..., None] + offs                         # [d, n_c, w]
        valid = offs < deg_r[..., None]
        flat_idx = np.minimum(idx, max_idx).reshape(d, -1)
        gathered = np.take_along_axis(send_pad, flat_idx, 1).reshape(d, n_c, w)
        send_c = np.where(valid, gathered, sentinel_send).astype(np.int32)
        # Padding rows get DISTINCT targets chunk_size + j: the shard body
        # scatters them into in-range scratch slots past the real chunk
        # (sliced away), keeping unique_indices honest with no OOB index.
        tgt_c = np.where(row_valid, rows, chunk_size + j).astype(np.int32)
        bucket_send.append(send_c)
        bucket_target.append(tgt_c)
        if w_pad is not None:
            wg = np.take_along_axis(w_pad, flat_idx, 1).reshape(d, n_c, w)
            bucket_weight.append(np.where(valid, wg, 0.0).astype(np.float32))
    return tuple(bucket_send), tuple(bucket_target), tuple(bucket_weight)


def _build_shard_blocked_plan(
    deg, send_pad, counts, chunk_size, d, w_pad=None, tile_slots=None
):
    """Stacked per-shard propagation-blocking plan with uniform shapes.

    Each shard's vertex chunk is a bin group: the shard's LOCAL message
    CSR is split into destination-range bins (``ops/blocking._blocked_layout``
    — the single layout owner, so the sharded tiles are semantically
    identical to the fused plan's), on ONE shared width ladder and ONE
    tile width (the max across shards) so a single SPMD program serves
    all devices. Padding messages (the CSR rows past ``counts[s]``)
    stream the label-sentinel sender and scatter into a per-shard scratch
    region past the bins; padding rows target ``chunk_size + j`` scratch
    slots exactly like the bucketed plan. Built with a per-shard host
    loop (D is small; the per-shard work is vectorized NumPy).
    """
    import os as _os

    from graphmine_tpu.ops.blocking import (
        DEFAULT_TILE_SLOTS,
        _bin_bounds,
        _blocked_layout,
    )
    from graphmine_tpu.ops.bucketed_mode import _extend_widths

    if tile_slots is None:
        tile_slots = int(
            _os.environ.get("GRAPHMINE_BLOCKED_TILE_SLOTS", DEFAULT_TILE_SLOTS)
        )
    sentinel_send = chunk_size * d              # the label sentinel slot
    mp = send_pad.shape[1]
    widths = _extend_widths(int(deg.max(initial=1)))

    # Local CSR pointers + a first pass for the shared tile width.
    ptrs, tb = [], 8
    for s in range(d):
        ptr_s = np.zeros(chunk_size + 1, dtype=np.int64)
        np.cumsum(deg[s], out=ptr_s[1:])
        ptrs.append(ptr_s)
        bounds = _bin_bounds(ptr_s, tile_slots)
        sizes = ptr_s[bounds[1:]] - ptr_s[bounds[:-1]]
        tb = max(tb, -(-int(sizes.max(initial=1)) // 8) * 8)

    shard_layouts, n_bins_max = [], 1
    for s in range(d):
        layout = _blocked_layout(
            ptrs[s], send_pad[s], tile_slots, widths=widths, tile_width=tb,
            weights=None if w_pad is None else w_pad[s],
        )
        shard_layouts.append(layout)
        n_bins_max = max(n_bins_max, len(layout[2]) - 1)

    tile_total = n_bins_max * tb
    tile_alloc = tile_total + mp + 1
    sentinel_slot = tile_alloc - 1

    blk_src = np.full((d, mp), sentinel_send, dtype=np.int32)
    blk_pos = np.empty((d, mp), dtype=np.int32)
    class_rows: dict = {}
    for s, (src_sorted, scatter_pos, _bounds, _tb, rows) in enumerate(
        shard_layouts
    ):
        n = len(src_sorted)
        blk_src[s, :n] = src_sorted
        blk_pos[s, :n] = scatter_pos
        # padding messages: distinct scratch slots past the bins (their
        # streamed value is the label sentinel; unique indices hold)
        blk_pos[s, n:] = tile_total + np.arange(n, mp, dtype=np.int64)
        for c, payload in rows.items():
            class_rows.setdefault(c, [None] * d)[s] = payload

    blk_row_idx, blk_row_target, blk_row_weight = [], [], []
    for c in sorted(class_rows):
        w = int(widths[c])
        per_shard = class_rows[c]
        n_c = max(
            (p[0].shape[0] for p in per_shard if p is not None), default=0
        )
        idx_c = np.full((d, n_c, w), sentinel_slot, dtype=np.int32)
        tgt_c = np.empty((d, n_c), dtype=np.int32)
        tgt_c[:] = chunk_size + np.arange(n_c, dtype=np.int64)[None, :]
        wgt_c = (
            None if w_pad is None else np.zeros((d, n_c, w), dtype=np.float32)
        )
        for s, payload in enumerate(per_shard):
            if payload is None:
                continue
            vr, idx, wmat = payload
            n = len(vr)
            idx_c[s, :n] = np.where(idx < 0, sentinel_slot, idx)
            tgt_c[s, :n] = vr
            if wgt_c is not None:
                wgt_c[s, :n] = wmat
        blk_row_idx.append(idx_c)
        blk_row_target.append(tgt_c)
        if wgt_c is not None:
            blk_row_weight.append(wgt_c)
    return dict(
        blk_src=blk_src,
        blk_pos=blk_pos,
        blk_row_idx=tuple(blk_row_idx),
        blk_row_target=tuple(blk_row_target),
        blk_row_weight=tuple(blk_row_weight),
        blk_tile_alloc=tile_alloc,
    )


def _build_shard_plan2d(blk_src, chunk_size, d, pad_multiple=8):
    """Source-axis extension of the blocked bin groups (r16): per-peer
    boundary gather tables + the compact-table stream remap.

    For each shard ``s`` and peer offset ``r`` (1..D-1), the boundary
    set ``need(s, r)`` is the sorted unique LOCAL indices (within the
    owner's chunk) of the senders shard ``s``'s bins read from owner
    ``(s - r) % D`` — exactly the label slots that must cross the ICI
    for that (shard, peer) pair, however small the live frontier keeps
    them. All sets pad to one shared width ``B`` (SPMD needs one
    program), and ``send_tab[s, r-1]`` holds what shard ``s`` SHIPS at
    shift ``r``: ``need((s + r) % D, r)`` — the ppermute at shift ``r``
    delivers it to precisely the peer that reads it. The blocked
    sender-major stream (global ids in ``blk_src``) is remapped onto the
    compact per-shard table ``[own (Vc) | bufs (D-1)*B | sentinel]`` so
    the bin phase never touches a replicated label vector; padding
    messages point at the sentinel slot (the blocked plan's padding
    contract, relocated)."""
    mp = blk_src.shape[1]
    # One sorted-unique pass per shard, not one masked unique per
    # (shard, peer) pair: uniq is ascending, so owner ranges are
    # contiguous slices found by searchsorted on the chunk boundaries —
    # O(M log M) total host work (the same order as the blocked plan
    # build this rides on), independent of D.
    need: list[list] = [[] for _ in range(d)]
    uniqs, bounds = [], []
    for s in range(d):
        uniq = np.unique(blk_src[s].astype(np.int64))     # incl. sentinel
        uniqs.append(uniq)
        bound = np.searchsorted(uniq, np.arange(d + 1) * chunk_size)
        bounds.append(bound)
        for r in range(1, d):
            peer = (s - r) % d
            ids = uniq[bound[peer]: bound[peer + 1]]
            need[s].append(ids - peer * chunk_size)
    b = max(
        (len(ids) for row in need for ids in row), default=1
    )
    b = max(-(-max(b, 1) // pad_multiple) * pad_multiple, pad_multiple)
    send_tab = np.zeros((d, max(d - 1, 0), b), dtype=np.int32)
    for s in range(d):
        for r in range(1, d):
            ids = need[(s + r) % d][r - 1]
            send_tab[s, r - 1, : len(ids)] = ids
    sentinel_slot = chunk_size + (d - 1) * b
    src_local = np.full((d, mp), sentinel_slot, dtype=np.int32)
    for s in range(d):
        g = blk_src[s].astype(np.int64)
        owner = g // chunk_size                           # pad -> d
        # one global position pass: index within need[s][r-1] is the
        # position in uniq minus the owner range's start
        pos = np.searchsorted(uniqs[s], g)
        in_need = pos - bounds[s][np.minimum(owner, d - 1)]
        r_of = (s - owner) % d
        out = chunk_size + (r_of - 1) * b + in_need
        out = np.where(owner == s, g - s * chunk_size, out)
        src_local[s] = np.where(owner >= d, sentinel_slot, out)
    total = sum(len(ids) for row in need for ids in row)
    return dict(
        x2d_send_tab=send_tab,
        x2d_src_local=src_local,
        x2d_boundary=int(b),
        x2d_boundary_total=int(total),
    )


def shard_graph_arrays(sg: ShardedGraph, mesh, lpa_only: bool = False) -> ShardedGraph:
    """Place the per-shard arrays on the mesh (leading dim over the vertex axis).

    ``lpa_only`` (valid only with a bucket plan): drop the sort-body CSR
    arrays — the bucketed LPA shard body never reads them, and at
    100M-edge scale they are ~GBs of idle HBM (they cannot merely stay on
    host: the jitted entry points stage every pytree leaf to device).
    Pass such a graph only to ``sharded_label_propagation``; CC/PageRank/
    ring consumers fail loudly on the ``None`` fields.
    """
    axes = _vertex_axes(mesh)
    spec = NamedSharding(mesh, P(axes, None))
    spec3 = NamedSharding(mesh, P(axes, None, None))
    if (
        lpa_only and not sg.bucket_send and sg.blk_src is None
        and sg.x2d_src_local is None
    ):
        raise ValueError(
            "lpa_only requires partition_graph(build_bucket_plan=True), "
            "partition_graph(build_blocked_plan=True) or "
            "partition_graph(build_plan2d=True)"
        )
    place = (lambda a, s: None) if lpa_only else jax.device_put
    return ShardedGraph(
        msg_recv_local=place(sg.msg_recv_local, spec),
        msg_send=place(sg.msg_send, spec),
        degrees=place(sg.degrees, spec),
        num_vertices=sg.num_vertices,
        chunk_size=sg.chunk_size,
        num_shards=sg.num_shards,
        bucket_send=tuple(jax.device_put(b, spec3) for b in sg.bucket_send),
        bucket_target=tuple(jax.device_put(t, spec) for t in sg.bucket_target),
        # msg_weight is a sort-body array too (the bucketed body reads
        # bucket_weight) — drop it under lpa_only like the rest.
        msg_weight=None if sg.msg_weight is None else place(sg.msg_weight, spec),
        bucket_weight=tuple(jax.device_put(b, spec3) for b in sg.bucket_weight),
        blk_src=None if sg.blk_src is None else jax.device_put(sg.blk_src, spec),
        blk_pos=None if sg.blk_pos is None else jax.device_put(sg.blk_pos, spec),
        blk_row_idx=tuple(jax.device_put(b, spec3) for b in sg.blk_row_idx),
        blk_row_target=tuple(jax.device_put(t, spec) for t in sg.blk_row_target),
        blk_row_weight=tuple(jax.device_put(b, spec3) for b in sg.blk_row_weight),
        blk_tile_alloc=sg.blk_tile_alloc,
        x2d_send_tab=(
            None if sg.x2d_send_tab is None
            else jax.device_put(sg.x2d_send_tab, spec3)
        ),
        x2d_src_local=(
            None if sg.x2d_src_local is None
            else jax.device_put(sg.x2d_src_local, spec)
        ),
        x2d_boundary=sg.x2d_boundary,
        x2d_boundary_total=sg.x2d_boundary_total,
    )


def _shard_specs(mesh):
    data_spec = P(_vertex_axes(mesh), None)
    rep = P()
    in_specs = (rep, data_spec, data_spec, data_spec)
    return in_specs, rep


def _check_mesh(sg: ShardedGraph, mesh) -> None:
    mesh_size = mesh.size
    if mesh_size != sg.num_shards:
        raise ValueError(
            f"graph was partitioned into {sg.num_shards} shards but the mesh "
            f"has {mesh_size} devices; re-run partition_graph(mesh=mesh)"
        )


def _lpa_shard_body(labels_full, recv_local, send, deg, weight, *, chunk_size, axes):
    """Per-device LPA superstep body (runs under shard_map). ``weight``:
    optional [1, Mp] per-message weights (weighted mode), else None."""
    recv_local = recv_local[0]
    send = send[0]
    deg = deg[0]
    msg = labels_full[send]
    mode, _ = segment_mode(
        recv_local, msg, num_segments=chunk_size,
        weights=None if weight is None else weight[0],
    )
    start = lax.axis_index(axes).astype(jnp.int32) * chunk_size
    own = lax.dynamic_slice(labels_full, (start,), (chunk_size,))
    new_own = jnp.where(deg > 0, mode, own).astype(jnp.int32)
    return lax.all_gather(new_own, axes, tiled=True)


def _lpa_shard_body_bucketed(
    labels_full, bucket_send, bucket_target, bucket_weight=None, *,
    chunk_size, axes
):
    """Fast LPA shard body: degree-bucketed dense mode per shard.

    Same comms as :func:`_lpa_shard_body` (one tiled all_gather); the
    shard-local reduction swaps the global segment-mode sort for the
    bucketed plan (see ops/bucketed_mode.py — gather-bound analysis).
    Padding rows gather the sentinel label and scatter to DISTINCT
    in-range targets ``chunk_size + j`` of an extended scratch region
    that is sliced away at the end; vertices with no messages are in no
    bucket and keep their label. ``bucket_weight`` (r2): slot-aligned
    weights switch the row modes to weighted argmax.
    """
    from graphmine_tpu.ops.bucketed_mode import (
        _SENTINEL,
        _bucket_mode,
        _bucket_wmode,
    )

    lbl_pad = jnp.concatenate(
        [labels_full, jnp.full((1,), _SENTINEL, jnp.int32)]
    )
    start = lax.axis_index(axes).astype(jnp.int32) * chunk_size
    own = lax.dynamic_slice(labels_full, (start,), (chunk_size,))
    # Padding rows carry DISTINCT targets chunk_size + j (j < n_c): one
    # scratch extension by the max class width keeps every scatter index
    # in range and unique. Do NOT "optimize" this back to out-of-bounds
    # indices with mode="drop" — under shard_map the XLA:CPU lowering of
    # a unique_indices OOB scatter was observed corrupting the last
    # in-range slot with a shifted read (caught by
    # tools/consistency_sweep.py; see docs/DESIGN.md).
    n_max = max((t.shape[-1] for t in bucket_target), default=0)
    own = jnp.concatenate([own, jnp.zeros((n_max,), own.dtype)])
    wmats = bucket_weight or (None,) * len(bucket_send)
    for sidx, tgt, wmat in zip(bucket_send, bucket_target, wmats):
        mat = lbl_pad[sidx[0]]
        vals = (
            _bucket_mode(mat) if wmat is None else _bucket_wmode(mat, wmat[0])
        )
        own = own.at[tgt[0]].set(vals, unique_indices=True)
    return lax.all_gather(
        own[:chunk_size].astype(jnp.int32), axes, tiled=True
    )


def _blocked_shard_tile(labels_full, blk_src, blk_pos, tile_alloc, fill):
    """Per-device bin phase (ops/blocking.py §2, shard-local): stream the
    padded label vector in sender-major order (monotone gather) and
    scatter each message into its slot of this shard's destination-binned
    tile. Padding messages carry the sentinel value into scratch slots
    past the bins; unwritten slots keep ``fill``."""
    lbl_pad = jnp.concatenate([labels_full, jnp.full((1,), fill, jnp.int32)])
    vals = lbl_pad[blk_src[0]]
    tile = jnp.full((tile_alloc,), fill, jnp.int32)
    return tile.at[blk_pos[0]].set(vals, unique_indices=True)


def _lpa_shard_body_blocked(
    labels_full, blk_src, blk_pos, row_idx, row_target, row_weight=None, *,
    chunk_size, tile_alloc, axes
):
    """Blocked LPA shard body: bin phase into the shard-local tile, then
    the bucketed-mode row reduce with TILE-local indices (bounded by the
    tile, not V). Same comms as the other LPA bodies — one tiled
    all_gather. Padding rows scatter to the ``chunk_size + j`` scratch
    extension (sliced away), exactly like the bucketed body; see the OOB
    warning there for why the scratch exists."""
    from graphmine_tpu.ops.bucketed_mode import (
        _SENTINEL,
        _bucket_mode,
        _bucket_wmode,
    )

    tile = _blocked_shard_tile(labels_full, blk_src, blk_pos, tile_alloc, _SENTINEL)
    start = lax.axis_index(axes).astype(jnp.int32) * chunk_size
    own = lax.dynamic_slice(labels_full, (start,), (chunk_size,))
    n_max = max((t.shape[-1] for t in row_target), default=0)
    own = jnp.concatenate([own, jnp.zeros((n_max,), own.dtype)])
    wmats = row_weight or (None,) * len(row_idx)
    for ridx, tgt, wmat in zip(row_idx, row_target, wmats):
        mat = tile[ridx[0]]
        vals = _bucket_mode(mat) if wmat is None else _bucket_wmode(mat, wmat[0])
        own = own.at[tgt[0]].set(vals, unique_indices=True)
    return lax.all_gather(
        own[:chunk_size].astype(jnp.int32), axes, tiled=True
    )


def _cc_shard_body_blocked(
    labels_full, blk_src, blk_pos, row_idx, row_target, *,
    chunk_size, tile_alloc, axes
):
    """Blocked CC shard body: the min-reduce twin of
    :func:`_lpa_shard_body_blocked` — shard-local bin tile, per-row min
    (the int32-max sentinel never wins), pointer jump on the gathered
    full vector (no extra comms), matching :func:`_cc_shard_body`
    step-for-step."""
    from graphmine_tpu.ops.bucketed_mode import _SENTINEL

    tile = _blocked_shard_tile(labels_full, blk_src, blk_pos, tile_alloc, _SENTINEL)
    start = lax.axis_index(axes).astype(jnp.int32) * chunk_size
    own = lax.dynamic_slice(labels_full, (start,), (chunk_size,))
    n_max = max((t.shape[-1] for t in row_target), default=0)
    own = jnp.concatenate([own, jnp.zeros((n_max,), own.dtype)])
    for ridx, tgt in zip(row_idx, row_target):
        row_min = jnp.min(tile[ridx[0]], axis=1)
        own = own.at[tgt[0]].min(row_min, unique_indices=True)
    full = lax.all_gather(
        own[:chunk_size].astype(jnp.int32), axes, tiled=True
    )
    return jnp.minimum(full, full[full])


def _check_2d_mesh(mesh) -> None:
    """The 2D family's neighbor exchange is a ring of ``ppermute`` shifts
    over ONE mesh axis (the parallel/ring.py schedule's topology) —
    reject multi-axis meshes with a real error instead of a cryptic
    trace-time axis failure; the replicated schedules handle 2-D
    ``("dcn", "ici")`` meshes."""
    if len(tuple(mesh.axis_names)) != 1:
        raise ValueError(
            f"the sharded_2d family needs a 1-D mesh for its ppermute "
            f"neighbor exchange (got axes {tuple(mesh.axis_names)}); use "
            "the one-all_gather families on multi-slice meshes"
        )


def _exchange_2d(own, send_tab, *, axes, num_shards):
    """Neighbor-only frontier exchange (r16): one ``lax.ppermute`` shift
    per peer offset r, each carrying ONE padded boundary buffer — the
    label slots the receiving peer's bins actually read
    (``send_tab[r-1]``, host-computed by :func:`_build_shard_plan2d`) —
    instead of one tiled all_gather of the full label chunk. Exchanged
    bytes per chip drop from ``4·Vc·(D-1)`` to ``4·Σ_peer |boundary|``
    (padded to B). Returns the D-1 received buffers in peer-offset
    order, matching the compact-table layout the stream remap indexes."""
    bufs = []
    for r in range(1, num_shards):
        perm = [(i, (i + r) % num_shards) for i in range(num_shards)]
        bufs.append(lax.ppermute(own[send_tab[r - 1]], axes, perm))
    return bufs


def _table_2d(own, bufs, fill):
    """The compact per-shard label table ``[own | peer bufs | sentinel]``
    the 2D stream remap (``x2d_src_local``) gathers from — the
    neighbor-exchange replacement for the replicated padded label
    vector."""
    return jnp.concatenate(
        [own, *bufs, jnp.full((1,), fill, own.dtype)]
    )


def _lpa_shard_body_2d(
    own, src_local, blk_pos, send_tab, row_idx, row_target, row_weight=None,
    *, chunk_size, tile_alloc, axes, num_shards
):
    """2D LPA shard body: neighbor-only exchange into the compact label
    table, then the blocked bin phase + bucketed row reduce with
    tile-local indices. The tile contents are value-for-value identical
    to :func:`_lpa_shard_body_blocked`'s (the stream remap points each
    message at the same sender's label; padding at the same sentinel),
    so the labels are bit-identical to the blocked family — and hence to
    the sort oracle (the r8 order-independence contract). Labels stay
    SHARDED: input and output are the shard's own ``[Vc]`` chunk; no
    replicated V-vector exists anywhere in the superstep."""
    from graphmine_tpu.ops.bucketed_mode import (
        _SENTINEL,
        _bucket_mode,
        _bucket_wmode,
    )

    bufs = _exchange_2d(own, send_tab[0], axes=axes, num_shards=num_shards)
    table = _table_2d(own, bufs, _SENTINEL)
    vals = table[src_local[0]]
    tile = jnp.full((tile_alloc,), _SENTINEL, jnp.int32).at[blk_pos[0]].set(
        vals, unique_indices=True
    )
    n_max = max((t.shape[-1] for t in row_target), default=0)
    out = jnp.concatenate([own, jnp.zeros((n_max,), own.dtype)])
    wmats = row_weight or (None,) * len(row_idx)
    for ridx, tgt, wmat in zip(row_idx, row_target, wmats):
        mat = tile[ridx[0]]
        vals_r = _bucket_mode(mat) if wmat is None else _bucket_wmode(mat, wmat[0])
        out = out.at[tgt[0]].set(vals_r, unique_indices=True)
    return out[:chunk_size].astype(jnp.int32)


def _cc_shard_body_2d(
    own, src_local, blk_pos, send_tab, row_idx, row_target, *,
    chunk_size, tile_alloc, axes, num_shards
):
    """2D CC shard body: the min-reduce twin of
    :func:`_lpa_shard_body_2d`, plus a CHUNK-LOCAL pointer jump. The
    full-vector jump (``full[full]``) of the one-all_gather bodies needs
    random access to arbitrary global label slots — exactly the O(V)
    exchange this family removes — so compression only follows labels
    that land in the shard's own range (sound: any label is a same-
    component vertex id, so ``min(own, labels[label])`` over local
    labels is monotone and component-preserving). Convergence trades
    O(log V) supersteps for O(D + log Vc)-ish on range-clustered
    components — up to O(diameter) when a chain's labels alternate
    shards and the local jump never fires (the serve repair path grants
    its 2D CC runs a D-scaled budget for exactly this) — and the
    FIXPOINT — labels = component-min — is unchanged, so final labels
    stay bit-identical to the oracle and a fixpoint stays a fixpoint
    under one more superstep (the serve-path sampled-exact-check
    predicate)."""
    from graphmine_tpu.ops.bucketed_mode import _SENTINEL

    bufs = _exchange_2d(own, send_tab[0], axes=axes, num_shards=num_shards)
    table = _table_2d(own, bufs, _SENTINEL)
    vals = table[src_local[0]]
    tile = jnp.full((tile_alloc,), _SENTINEL, jnp.int32).at[blk_pos[0]].set(
        vals, unique_indices=True
    )
    n_max = max((t.shape[-1] for t in row_target), default=0)
    out = jnp.concatenate([own, jnp.zeros((n_max,), own.dtype)])
    for ridx, tgt in zip(row_idx, row_target):
        row_min = jnp.min(tile[ridx[0]], axis=1)
        out = out.at[tgt[0]].min(row_min, unique_indices=True)
    new = out[:chunk_size].astype(jnp.int32)
    start = lax.axis_index(axes).astype(jnp.int32) * chunk_size
    loc = new - start
    in_chunk = (loc >= 0) & (loc < chunk_size)
    jumped = new[jnp.clip(loc, 0, chunk_size - 1)]
    return jnp.minimum(new, jnp.where(in_chunk, jumped, new))


def _cc_shard_body(labels_full, recv_local, send, deg, *, chunk_size, axes):
    recv_local = recv_local[0]
    send = send[0]
    deg = deg[0]
    msg = labels_full[send]
    neigh_min = jax.ops.segment_min(msg, recv_local, num_segments=chunk_size)
    start = lax.axis_index(axes).astype(jnp.int32) * chunk_size
    own = lax.dynamic_slice(labels_full, (start,), (chunk_size,))
    new_own = jnp.where(deg > 0, jnp.minimum(own, neigh_min), own).astype(jnp.int32)
    full = lax.all_gather(new_own, axes, tiled=True)
    # Pointer jumping on the (replicated) full vector — no extra comms.
    return jnp.minimum(full, full[full])


def _padded_init_labels(sg: ShardedGraph) -> jax.Array:
    v_pad = sg.padded_vertices
    return jnp.arange(v_pad, dtype=jnp.int32)


def _scan_supersteps(
    step_fn, labels: jax.Array, max_iter: int,
    tripwire_every: int = 0, chunk_size: int = 0, collect: bool = False,
):
    """Fixed-count superstep driver (LPA semantics: exactly max_iter).
    ``tripwire_every > 0`` arms the label tripwires every K supersteps
    (the carry then also holds the previous iterate for the oscillation
    guard); ``collect`` stacks :func:`_telemetry_row` as scan outputs and
    returns ``(labels, (changed[T], shard_changed[T, D]))`` — the
    counters travel with the result, no extra syncs. With both off the
    program is the original lean one."""
    if not tripwire_every and not collect:

        def step(labels, _):
            return step_fn(labels), None

        labels, _ = lax.scan(step, labels, None, length=max_iter)
        return labels

    if not tripwire_every:
        # collect-only: no oscillation guard, so don't thread a second
        # [D*Vc] prev-labels buffer through the carry just to ignore it
        # — telemetry targets exactly the large-graph runs where that
        # extra HBM would hurt.
        def step_c(cur, _):
            new = step_fn(cur)
            return new, _telemetry_row(new, cur, chunk_size)

        labels, ys = lax.scan(step_c, labels, None, length=max_iter)
        return labels, ys

    def step(carry, it):
        cur, prev = carry
        new = step_fn(cur)
        if tripwire_every:
            _label_tripwire(new, cur, prev, it, chunk_size, tripwire_every)
        ys = _telemetry_row(new, cur, chunk_size) if collect else None
        return (new, cur), ys

    (labels, prev), ys = lax.scan(
        step, (labels, labels), jnp.arange(max_iter, dtype=jnp.int32)
    )
    if tripwire_every:
        # Unconditional exit check (every=1): when max_iter is not a
        # multiple of K the last supersteps run unchecked, and garbage
        # must never leave the loop silently.
        _label_tripwire(
            labels, prev, prev, jnp.int32(max_iter - 1), chunk_size, 1
        )
    return (labels, ys) if collect else labels


# Telemetry ring-buffer bound for unbounded (max_iter=0) fixpoint runs:
# pointer jumping converges in O(log V) supersteps, so 4096 rows is far
# past any real trajectory; a pathological overrun overwrites the last
# row rather than growing an O(V)-row buffer alongside the labels.
_FIXPOINT_TELEMETRY_CAP = 4096


def _fixpoint_supersteps(
    step_fn, sg: ShardedGraph, max_iter: int, tripwire_every: int = 0,
    init_labels=None, collect: bool = False, guard=_cc_tripwire,
):
    """Run supersteps until no label changes (CC semantics), bounded by
    ``max_iter`` when nonzero. Shared by the replicated-label and ring
    schedules so the convergence logic has one home. ``tripwire_every``
    arms the ``guard`` tripwire every K supersteps — the CC guards
    (range + monotonicity) by default; the LPA fixpoint runner passes
    its range-only guard (min-monotonicity doesn't hold for mode
    propagation). ``init_labels`` resumes a checkpointed run
    mid-fixpoint or seeds a warm-start repair. ``collect`` accumulates
    :func:`_telemetry_row` into a fixed-size buffer carried through the
    while_loop and returns
    ``(labels, (changed[cap], shard_changed[cap, D], it_end))``."""
    limit = max_iter if max_iter > 0 else sg.num_vertices + 2
    cap = min(limit, _FIXPOINT_TELEMETRY_CAP)

    def cond(state):
        changed, it = state[1], state[2]
        return (changed > 0) & (it < limit)

    def loop_body(state):
        labels = state[0]
        it = state[2]
        new = step_fn(labels)
        if tripwire_every:
            guard(new, labels, it, sg.chunk_size, tripwire_every)
        if collect:
            total, per_shard = _telemetry_row(new, labels, sg.chunk_size)
            row = jnp.minimum(it, cap - 1)
            buf_c = state[3].at[row].set(total)
            buf_s = state[4].at[row].set(per_shard)
            return new, total, it + 1, buf_c, buf_s
        changed = jnp.sum(new != labels, dtype=jnp.int32)
        return new, changed, it + 1

    labels0 = (
        _padded_init_labels(sg) if init_labels is None
        else _pad_labels(init_labels, sg)
    )
    state0 = (labels0, jnp.int32(1), jnp.int32(0))
    if collect:
        state0 = state0 + (
            jnp.zeros((cap,), jnp.int32),
            jnp.zeros((cap, sg.num_shards), jnp.int32),
        )
    out = lax.while_loop(cond, loop_body, state0)
    labels, it_end = out[0], out[2]
    if tripwire_every:
        # Exit check (every=1): a poisoned-but-stable state ends the
        # fixpoint loop between two K-aligned checks; garbage must never
        # leave the loop silently. Monotonicity needs history, so only
        # the range guard applies here (cur=new disables it).
        guard(labels, labels, it_end - 1, sg.chunk_size, 1)
    if collect:
        return labels[: sg.num_vertices], (out[3], out[4], it_end)
    return labels[: sg.num_vertices]


def sharded_label_propagation(
    sg: ShardedGraph, mesh, max_iter: int = 5,
    init_labels: jax.Array | None = None, tripwire_every: int = 0,
    telemetry: bool = False,
):
    """Distributed synchronous LPA; semantics identical to
    :func:`graphmine_tpu.ops.lpa.label_propagation` (asserted by the
    virtual-device parity tests). Returns int32 labels ``[V]``.

    ``tripwire_every``: arm the in-loop divergence tripwires
    (label-out-of-range, period-2 oscillation) every K supersteps — a
    firing raises :class:`~graphmine_tpu.pipeline.resilience.DivergenceError`
    (retryable, with the offending shard index) instead of returning
    garbage labels. 0 (default) = off, the exact pre-tripwire program.

    ``telemetry``: also return a :class:`SuperstepTelemetry` —
    ``(labels, telemetry)`` — whose per-superstep counters accumulate in
    the scan carry and come back with the labels in the same transfer
    (no per-iteration host syncs; labels are bit-identical either way).
    """
    if not tripwire_every:
        out = _sharded_lpa_jit(sg, mesh, max_iter, init_labels, 0, telemetry)
    else:
        out = _run_armed(
            lambda: _sharded_lpa_jit(
                sg, mesh, max_iter, init_labels, tripwire_every, telemetry
            )
        )
    if not telemetry:
        return out
    labels, (changed, per_shard) = out
    return labels, SuperstepTelemetry(
        np.asarray(changed), np.asarray(per_shard), int(max_iter)
    )


def _build_lpa_step(sg: ShardedGraph, mesh):
    """The per-superstep LPA callable for one (graph, mesh) — shared by
    the fixed-count driver (:func:`_sharded_lpa_jit`) and the fixpoint
    repair entry (:func:`_sharded_lpa_fixpoint_jit`). Traced under jit."""
    axes = _vertex_axes(mesh)
    rep = P()
    if sg.x2d_src_local is not None:
        # 2D edge partition (r16): labels sharded, neighbor-only
        # ppermute exchange (partition_graph(build_plan2d=True)). The
        # step's carry is the SHARDED [D*Vc] label vector — the loop
        # drivers and tripwires operate on the logical array unchanged.
        _check_2d_mesh(mesh)
        n = len(sg.blk_row_idx)
        nw = len(sg.blk_row_weight)
        body = shard_map(
            partial(
                _lpa_shard_body_2d, chunk_size=sg.chunk_size,
                tile_alloc=sg.blk_tile_alloc, axes=axes,
                num_shards=sg.num_shards,
            ),
            mesh=mesh,
            in_specs=(
                P(axes),
                P(axes, None),
                P(axes, None),
                P(axes, None, None),
                (P(axes, None, None),) * n,
                (P(axes, None),) * n,
                (P(axes, None, None),) * nw,
            ),
            out_specs=P(axes),
        )
        return lambda l: body(
            l, sg.x2d_src_local, sg.blk_pos, sg.x2d_send_tab,
            sg.blk_row_idx, sg.blk_row_target, sg.blk_row_weight,
        )
    if sg.blk_src is not None:
        # Propagation-blocking path (r7): shard-local bin tiles, same
        # one-all_gather exchange (partition_graph(build_blocked_plan=True)).
        n = len(sg.blk_row_idx)
        nw = len(sg.blk_row_weight)
        body = shard_map(
            partial(
                _lpa_shard_body_blocked, chunk_size=sg.chunk_size,
                tile_alloc=sg.blk_tile_alloc, axes=axes,
            ),
            mesh=mesh,
            in_specs=(
                rep,
                P(axes, None),
                P(axes, None),
                (P(axes, None, None),) * n,
                (P(axes, None),) * n,
                (P(axes, None, None),) * nw,
            ),
            out_specs=rep,
            check_vma=False,
        )
        return lambda l: body(
            l, sg.blk_src, sg.blk_pos, sg.blk_row_idx, sg.blk_row_target,
            sg.blk_row_weight,
        )
    if sg.bucket_send:
        # Fast path: stacked degree-bucket plan (built by partition_graph);
        # weighted graphs carry slot-aligned bucket_weight matrices (r2).
        n = len(sg.bucket_send)
        nw = len(sg.bucket_weight)
        body = shard_map(
            partial(_lpa_shard_body_bucketed, chunk_size=sg.chunk_size, axes=axes),
            mesh=mesh,
            in_specs=(
                rep,
                (P(axes, None, None),) * n,
                (P(axes, None),) * n,
                (P(axes, None, None),) * nw,
            ),
            out_specs=rep,
            # The output is a tiled all_gather — replicated by construction,
            # which the vma checker cannot infer statically.
            check_vma=False,
        )
        return lambda l: body(l, sg.bucket_send, sg.bucket_target, sg.bucket_weight)
    in_specs, _ = _shard_specs(mesh)
    data_spec = P(axes, None)
    body = shard_map(
        partial(_lpa_shard_body, chunk_size=sg.chunk_size, axes=axes),
        mesh=mesh,
        in_specs=in_specs + (data_spec,),  # None weights: empty subtree
        out_specs=rep,
        check_vma=False,
    )
    return lambda l: body(
        l, sg.msg_recv_local, sg.msg_send, sg.degrees, sg.msg_weight
    )


@partial(jax.jit, static_argnames=("max_iter", "mesh", "tripwire_every", "telemetry"))
def _sharded_lpa_jit(
    sg: ShardedGraph, mesh, max_iter: int, init_labels, tripwire_every: int,
    telemetry: bool = False,
):
    _check_mesh(sg, mesh)
    step = _build_lpa_step(sg, mesh)
    labels = _padded_init_labels(sg) if init_labels is None else _pad_labels(init_labels, sg)
    out = _scan_supersteps(
        step, labels, max_iter,
        tripwire_every=tripwire_every, chunk_size=sg.chunk_size,
        collect=telemetry,
    )
    if telemetry:
        labels, ys = out
        return labels[: sg.num_vertices], ys
    return out[: sg.num_vertices]


def sharded_lpa_fixpoint(
    sg: ShardedGraph, mesh, max_iter: int = 0,
    init_labels: jax.Array | None = None, tripwire_every: int = 0,
):
    """Warm-start LPA run to FIXPOINT — the serving delta-repair entry
    (r7, docs/SERVING.md): ``init_labels`` seeds the previous snapshot's
    labels and supersteps run until no label changes, bounded by
    ``max_iter`` (0 = unbounded). Returns
    ``(labels[:V], iterations, converged)`` — ``converged=False`` means
    the budget exhausted first (the serving layer then falls back to a
    cold full recompute rather than publish a non-fixpoint).

    Same shard bodies, comms and mesh semantics as
    :func:`sharded_label_propagation`; only the loop driver differs
    (while-until-quiescent instead of a fixed scan).
    ``tripwire_every`` arms the range-only LPA guard every K supersteps.
    """
    if not tripwire_every:
        out = _sharded_lpa_fixpoint_jit(sg, mesh, max_iter, init_labels, 0)
    else:
        out = _run_armed(
            lambda: _sharded_lpa_fixpoint_jit(
                sg, mesh, max_iter, init_labels, tripwire_every
            )
        )
    labels, (changed, _per_shard, it_end) = out
    it = int(it_end)
    row = min(it, changed.shape[0]) - 1
    converged = it == 0 or int(changed[row]) == 0
    return labels, it, converged


@partial(jax.jit, static_argnames=("max_iter", "mesh", "tripwire_every"))
def _sharded_lpa_fixpoint_jit(
    sg: ShardedGraph, mesh, max_iter: int, init_labels, tripwire_every: int,
):
    _check_mesh(sg, mesh)
    step = _build_lpa_step(sg, mesh)
    return _fixpoint_supersteps(
        step, sg, max_iter, tripwire_every=tripwire_every,
        init_labels=init_labels, collect=True, guard=_lpa_range_tripwire,
    )


def sharded_connected_components(
    sg: ShardedGraph, mesh, max_iter: int = 0, tripwire_every: int = 0,
    init_labels: jax.Array | None = None, telemetry: bool = False,
):
    """Distributed weakly-connected components (min-propagation + pointer
    jumping); parity with :func:`graphmine_tpu.ops.cc.connected_components`.
    ``tripwire_every``: arm the CC divergence tripwires (label range +
    min-monotonicity) every K supersteps; see
    :func:`sharded_label_propagation`. ``init_labels``: resume a
    checkpointed fixpoint mid-run (min-propagation is monotone, so a
    resumed trajectory converges to the identical fixpoint).
    ``telemetry``: return ``(labels, SuperstepTelemetry)`` — counters
    ride the while-loop carry (rows past the converged prefix are
    trimmed host-side; no extra device syncs)."""
    if not tripwire_every:
        out = _sharded_cc_jit(sg, mesh, max_iter, 0, init_labels, telemetry)
    else:
        out = _run_armed(
            lambda: _sharded_cc_jit(
                sg, mesh, max_iter, tripwire_every, init_labels, telemetry
            )
        )
    if not telemetry:
        return out
    labels, (changed, per_shard, it_end) = out
    n = min(int(it_end), changed.shape[0])
    return labels, SuperstepTelemetry(
        np.asarray(changed)[:n], np.asarray(per_shard)[:n], int(it_end)
    )


@partial(jax.jit, static_argnames=("max_iter", "mesh", "tripwire_every", "telemetry"))
def _sharded_cc_jit(
    sg: ShardedGraph, mesh, max_iter: int, tripwire_every: int,
    init_labels=None, telemetry: bool = False,
):
    _check_mesh(sg, mesh)
    in_specs, rep = _shard_specs(mesh)
    axes = _vertex_axes(mesh)
    if sg.x2d_src_local is not None:
        # 2D neighbor-exchange CC (r16): sharded labels, chunk-local
        # pointer jumping — see _cc_shard_body_2d for the convergence
        # trade; the fixpoint (and thus every published label) is
        # bit-identical to the one-all_gather families'.
        _check_2d_mesh(mesh)
        n = len(sg.blk_row_idx)
        body = shard_map(
            partial(
                _cc_shard_body_2d, chunk_size=sg.chunk_size,
                tile_alloc=sg.blk_tile_alloc, axes=axes,
                num_shards=sg.num_shards,
            ),
            mesh=mesh,
            in_specs=(
                P(axes), P(axes, None), P(axes, None),
                P(axes, None, None),
                (P(axes, None, None),) * n, (P(axes, None),) * n,
            ),
            out_specs=P(axes),
        )
        step = lambda l: body(
            l, sg.x2d_src_local, sg.blk_pos, sg.x2d_send_tab,
            sg.blk_row_idx, sg.blk_row_target,
        )
    elif sg.blk_src is not None:
        # Blocked CC shard body (r7): shard-local bin tiles, same
        # fixpoint driver, bit-identical labels (virtual-mesh parity).
        n = len(sg.blk_row_idx)
        body = shard_map(
            partial(
                _cc_shard_body_blocked, chunk_size=sg.chunk_size,
                tile_alloc=sg.blk_tile_alloc, axes=axes,
            ),
            mesh=mesh,
            in_specs=(
                rep, P(axes, None), P(axes, None),
                (P(axes, None, None),) * n, (P(axes, None),) * n,
            ),
            out_specs=rep,
            check_vma=False,
        )
        step = lambda l: body(
            l, sg.blk_src, sg.blk_pos, sg.blk_row_idx, sg.blk_row_target
        )
    else:
        body = shard_map(
            partial(_cc_shard_body, chunk_size=sg.chunk_size, axes=axes),
            mesh=mesh,
            in_specs=in_specs,
            out_specs=rep,
            check_vma=False,
        )
        step = lambda l: body(l, sg.msg_recv_local, sg.msg_send, sg.degrees)
    return _fixpoint_supersteps(
        step, sg,
        max_iter, tripwire_every=tripwire_every, init_labels=init_labels,
        collect=telemetry,
    )


def _pad_labels(labels: jax.Array, sg: ShardedGraph) -> jax.Array:
    v_pad = sg.padded_vertices
    pad = jnp.arange(sg.num_vertices, v_pad, dtype=jnp.int32)
    return jnp.concatenate([labels.astype(jnp.int32), pad])


def _check_pagerank_weighted(sg, out_degrees, weighted):
    """Resolve/validate the weighted flag for the distributed PageRank
    schedules (one owner; used by the replicated and ring paths).

    ``None`` -> weighted iff the graph carries ``msg_weight``. A weighted
    run requires FLOAT out-edge weight sums (``ops.degrees.out_weights``):
    integer out-degrees would mix w-weighted messages with 1/deg outflow
    and silently stop conserving rank mass.
    """
    if weighted is None:
        weighted = sg.msg_weight is not None
    if weighted:
        if sg.msg_weight is None:
            raise ValueError("weighted=True but the graph has no msg_weight")
        if not jnp.issubdtype(jnp.result_type(out_degrees), jnp.floating):
            raise ValueError(
                "weighted PageRank needs float out-edge weight sums "
                "(ops.degrees.out_weights), not integer out-degrees; pass "
                "weighted=False for unweighted ranks on this graph"
            )
    return weighted


def _pagerank_terms(out_degrees, v: int, v_pad: int):
    """Padded degree-derived PageRank terms shared by the replicated and
    ring schedules (one owner for the dangling/teleport semantics).
    ``out_degrees`` may be int out-degrees (unweighted) or float out-edge
    weight sums (weighted; each vertex splits rank in proportion to edge
    weight — NetworkX semantics, matching ``ops.pagerank(weights=...)``).
    Returns ``(inv_out, reset, dangling)``, each ``[v_pad]``."""
    out = jnp.zeros((v_pad,), jnp.float32).at[:v].set(
        jnp.asarray(out_degrees).astype(jnp.float32)
    )
    live = jnp.arange(v_pad) < v
    inv_out = jnp.where(out > 0, 1.0 / jnp.maximum(out, 1e-30), 0.0)
    dangling = (out <= 0) & live
    reset = jnp.where(live, 1.0 / v, 0.0).astype(jnp.float32)
    return inv_out, reset, dangling


def _pagerank_shard_body(state, recv_local, send, deg, weight=None, *,
                         chunk_size, axes, alpha):
    """Per-device PageRank power-iteration step.

    ``state``: (pr_full, inv_out_full, dangling_mass_reset_full) — the
    replicated rank vector and precomputed degree terms. Messages ride the
    same vertex-range-sharded CSR as LPA; per-iteration comms is one tiled
    all_gather of the rank chunk. ``weight``: optional [1, Mp] per-message
    weights — with float out-strengths in ``inv_out`` this is weighted
    PageRank (contribution = rank x w/out_w).
    """
    pr_full, inv_out_full, reset_full, dangling_full = state
    recv_local = recv_local[0]
    send = send[0]
    contrib_full = pr_full * inv_out_full
    msg = contrib_full[send] * (recv_local < chunk_size)
    if weight is not None:
        msg = msg * weight[0]
    inflow = jax.ops.segment_sum(msg, recv_local, num_segments=chunk_size)
    dangling_mass = jnp.sum(jnp.where(dangling_full, pr_full, 0.0))
    start = lax.axis_index(axes).astype(jnp.int32) * chunk_size
    reset_own = lax.dynamic_slice(reset_full, (start,), (chunk_size,))
    new_own = alpha * (inflow + dangling_mass * reset_own) + (1.0 - alpha) * reset_own
    return lax.all_gather(new_own, axes, tiled=True)


def sharded_pagerank(
    sg: ShardedGraph,
    mesh,
    out_degrees: jax.Array,
    alpha: float = 0.85,
    max_iter: int = 100,
    tol: float = 1e-6,
    weighted: bool | None = None,
    tripwire_every: int = 0,
    init_ranks: jax.Array | None = None,
    telemetry: bool = False,
):
    """Distributed PageRank over the vertex-range-sharded message CSR.

    ``sg`` must be partitioned from a **directed** graph
    (``build_graph(..., symmetric=False)``); ``out_degrees`` is the
    directed out-degree vector ``[V]`` (see
    :func:`graphmine_tpu.ops.degrees.out_degrees`) — or, for a weighted
    run, the float out-edge weight sums
    (:func:`graphmine_tpu.ops.degrees.out_weights`): rank then splits
    across out-edges in proportion to weight, matching
    ``ops.pagerank(weights=...)``. ``weighted=None`` follows
    ``sg.msg_weight`` presence; int out_degrees on a weighted run are
    rejected (the w/out mixture would silently conserve no rank mass) —
    pass ``weighted=False`` for unweighted ranks on a weighted graph.
    Parity with :func:`graphmine_tpu.ops.pagerank.pagerank` is asserted
    by the virtual-device tests. Returns float32 ranks ``[V]`` summing
    to 1. ``tripwire_every``: arm the NaN/Inf rank tripwire every K
    power iterations (a NaN rank satisfies no convergence test —
    ``delta > tol`` is False for NaN — so an unguarded loop exits
    'converged' with garbage); see :func:`sharded_label_propagation`.
    ``init_ranks``: resume a checkpointed power iteration mid-run (the
    iteration is a fixed-point map, so a resumed trajectory matches the
    uninterrupted one). ``telemetry``: return
    ``(ranks, PowerIterTelemetry)`` — per-iteration L1 residuals (global
    + per-shard) accumulated in the loop carry, fetched with the ranks
    (no extra syncs; a NaN-poisoned run's residual trail shows WHERE the
    iteration went wrong, not just that it did).
    """
    if not tripwire_every:
        out = _sharded_pagerank_jit(
            sg, mesh, out_degrees, alpha, max_iter, tol, weighted, 0,
            init_ranks, telemetry,
        )
    else:
        out = _run_armed(lambda: _sharded_pagerank_jit(
            sg, mesh, out_degrees, alpha, max_iter, tol, weighted,
            tripwire_every, init_ranks, telemetry,
        ))
    if not telemetry:
        return out
    ranks, (res, shard_res, it_end) = out
    n = min(int(it_end), res.shape[0])
    return ranks, PowerIterTelemetry(
        np.asarray(res)[:n], np.asarray(shard_res)[:n], int(it_end)
    )


@partial(jax.jit, static_argnames=("max_iter", "mesh", "weighted", "tripwire_every", "telemetry"))
def _sharded_pagerank_jit(
    sg: ShardedGraph, mesh, out_degrees, alpha, max_iter: int, tol,
    weighted: bool | None, tripwire_every: int, init_ranks=None,
    telemetry: bool = False,
):
    _check_mesh(sg, mesh)
    weighted = _check_pagerank_weighted(sg, out_degrees, weighted)
    inv_out, reset, dangling = _pagerank_terms(
        out_degrees, sg.num_vertices, sg.padded_vertices
    )

    in_specs, rep = _shard_specs(mesh)
    data_spec = P(_vertex_axes(mesh), None)
    body = shard_map(
        partial(
            _pagerank_shard_body,
            chunk_size=sg.chunk_size,
            axes=_vertex_axes(mesh),
            alpha=alpha,
        ),
        mesh=mesh,
        in_specs=((rep, rep, rep, rep),) + in_specs[1:]
        + ((data_spec,) if weighted else ()),
        out_specs=rep,
        check_vma=False,
    )

    cap = max(max_iter, 1)

    def cond(state):
        delta, it = state[1], state[2]
        return (delta > tol) & (it < max_iter)

    def step(state):
        pr, it = state[0], state[2]
        args = (sg.msg_weight,) if weighted else ()
        new = body(
            (pr, inv_out, reset, dangling), sg.msg_recv_local, sg.msg_send,
            sg.degrees, *args,
        )
        if tripwire_every:
            _rank_tripwire(new, it, sg.chunk_size, tripwire_every)
        if telemetry:
            delta, per_shard = _residual_row(new, pr, sg.chunk_size)
            row = jnp.minimum(it, cap - 1)
            return (new, delta, it + 1,
                    state[3].at[row].set(delta),
                    state[4].at[row].set(per_shard))
        delta = jnp.abs(new - pr).sum()
        return new, delta, it + 1

    if init_ranks is None:
        pr0 = reset
    else:
        # zero-pad: padded vertices carry exactly 0 rank in every
        # uninterrupted iteration (reset/inv_out/dangling are all 0
        # there), so a zero-padded resume matches it bit-for-bit
        pr0 = jnp.zeros((sg.padded_vertices,), jnp.float32).at[
            : sg.num_vertices
        ].set(init_ranks.astype(jnp.float32))
    state0 = (pr0, jnp.float32(1.0), jnp.int32(0))
    if telemetry:
        state0 = state0 + (
            jnp.zeros((cap,), jnp.float32),
            jnp.zeros((cap, sg.num_shards), jnp.float32),
        )
    out = lax.while_loop(cond, step, state0)
    pr, it_end = out[0], out[2]
    if tripwire_every:
        # Exit check (every=1): a NaN delta FAILS `delta > tol` and ends
        # the loop immediately — often before the K-th iteration check —
        # so the final ranks are always re-guarded before they escape.
        _rank_tripwire(pr, it_end - 1, sg.chunk_size, 1)
    if telemetry:
        return pr[: sg.num_vertices], (out[3], out[4], it_end)
    return pr[: sg.num_vertices]
