from graphmine_tpu.parallel.knn import sharded_knn, sharded_lof
from graphmine_tpu.parallel.ppr import sharded_personalized_pagerank
from graphmine_tpu.parallel.mesh import initialize_distributed, make_mesh, make_multislice_mesh
from graphmine_tpu.parallel.ring import (
    ring_connected_components,
    ring_label_propagation,
    ring_pagerank,
)
from graphmine_tpu.parallel.sharded import (
    ShardedGraph,
    partition_graph,
    shard_graph_arrays,
    sharded_label_propagation,
    sharded_lpa_fixpoint,
    sharded_connected_components,
    sharded_pagerank,
)

__all__ = [
    "initialize_distributed",
    "make_mesh",
    "make_multislice_mesh",
    "ShardedGraph",
    "partition_graph",
    "shard_graph_arrays",
    "sharded_label_propagation",
    "sharded_lpa_fixpoint",
    "sharded_connected_components",
    "sharded_pagerank",
    "ring_label_propagation",
    "ring_connected_components",
    "ring_pagerank",
    "sharded_knn",
    "sharded_lof",
    "sharded_personalized_pagerank",
]
