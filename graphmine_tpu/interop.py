"""NetworkX interop: validation-scale bridges to the reference ecosystem.

The reference's ``Overview:8`` names NetworkX as a project technology
(nothing in its code uses it); these converters serve the role it would
have played — cross-checking results on graphs small enough for a
single-threaded host library. The TPU engine remains the scale path.
"""

from __future__ import annotations

import numpy as np

from graphmine_tpu.graph.container import Graph, build_graph
from graphmine_tpu.io.edges import EdgeTable, from_arrays


def to_networkx(obj, labels=None, directed: bool = True, multigraph: bool = False):
    """Convert an :class:`EdgeTable` or :class:`Graph` to a NetworkX graph.

    ``multigraph=True`` preserves duplicate edges (Multi(Di)Graph) — use it
    for oracle comparisons against this engine, which deliberately keeps
    edge multiplicity (LPA parity with ``Graphframes.py:70-81``); the
    default (Di)Graph collapses duplicates. ``labels``: optional
    per-vertex community labels stored as a ``"community"`` node
    attribute. EdgeTable names become ``"name"`` attributes.
    """
    import networkx as nx

    cls = {
        (True, True): nx.MultiDiGraph,
        (True, False): nx.MultiGraph,
        (False, True): nx.DiGraph,
        (False, False): nx.Graph,
    }[(multigraph, directed)]
    g = cls()
    if isinstance(obj, EdgeTable):
        src, dst = np.asarray(obj.src), np.asarray(obj.dst)
        n = obj.num_vertices
        names = obj.names
    elif isinstance(obj, Graph):
        src, dst = np.asarray(obj.src), np.asarray(obj.dst)
        n = obj.num_vertices
        names = None
    else:
        raise TypeError(f"expected EdgeTable or Graph, got {type(obj).__name__}")
    g.add_nodes_from(range(n))
    g.add_edges_from(zip(src.tolist(), dst.tolist()))
    if names is not None:
        nx.set_node_attributes(g, {i: str(names[i]) for i in range(n)}, "name")
    if labels is not None:
        lab = np.asarray(labels)
        nx.set_node_attributes(g, {i: int(lab[i]) for i in range(n)}, "community")
    return g


def from_networkx(nxg) -> EdgeTable:
    """Convert a NetworkX graph to an :class:`EdgeTable` (dense int32 ids).

    Node objects are densified in insertion order; a ``"name"`` node
    attribute (what :func:`to_networkx` writes) becomes the vertex name,
    falling back to ``str(node)`` — so an EdgeTable -> nx -> EdgeTable
    round trip preserves names. Undirected graphs contribute each edge
    once (the engine's symmetric message CSR propagates both directions
    anyway — LPA parity with ``Graphframes.py:81``).
    """
    nodes = list(nxg.nodes())
    index = {u: i for i, u in enumerate(nodes)}
    edges = np.asarray(
        [(index[u], index[v]) for u, v in nxg.edges()], dtype=np.int32
    ).reshape(-1, 2)
    names = np.asarray([str(nxg.nodes[u].get("name", u)) for u in nodes])
    return from_arrays(
        np.ascontiguousarray(edges[:, 0]),
        np.ascontiguousarray(edges[:, 1]),
        names=names,
    )


def graph_from_networkx(nxg) -> Graph:
    """Shortcut: NetworkX graph -> device-resident message-CSR Graph."""
    et = from_networkx(nxg)
    return build_graph(et.src, et.dst, num_vertices=et.num_vertices)
