"""Newman modularity of a community partition.

The reference evaluates community quality only by eyeballing counts
(``Graphframes.py:85,120``); SURVEY §7.7 names Louvain-modularity
comparison as the scale-up capability. This metric is the shared yardstick
for LPA vs Louvain partitions.

Conventions (matching networkx / python-louvain on weighted multigraphs):
the graph is a symmetric weighted message list (both directions of every
edge present) plus per-vertex self-loop weights; a self-loop of weight w
contributes 2w to its vertex's degree and 2w to its community's internal
weight.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from graphmine_tpu.graph.container import Graph


@partial(jax.jit, static_argnames=("num_vertices",))
def modularity_weighted(
    labels: jax.Array,
    recv: jax.Array,
    send: jax.Array,
    weight: jax.Array,
    self_weight: jax.Array,
    num_vertices: int,
    gamma: float = 1.0,
) -> jax.Array:
    """Q = sum_c [ Sigma_in_c / 2m  -  gamma * (Sigma_tot_c / 2m)^2 ].

    ``recv``/``send``/``weight`` are the symmetric message list (self-loops
    excluded, carried in ``self_weight``). Out-of-range ids (padding
    sentinels) are dropped by the segment ops.
    """
    w = weight.astype(jnp.float32)
    k = jax.ops.segment_sum(w, recv, num_segments=num_vertices) + 2.0 * self_weight
    two_m = jnp.maximum(k.sum(), 1e-12)
    valid = recv < num_vertices
    intra_msgs = jnp.where(
        valid & (labels[jnp.minimum(recv, num_vertices - 1)] == labels[send]), w, 0.0
    ).sum()
    sigma_in = intra_msgs + 2.0 * self_weight.sum()
    sigma_tot = jax.ops.segment_sum(k, labels, num_segments=num_vertices)
    return sigma_in / two_m - gamma * jnp.sum((sigma_tot / two_m) ** 2)


def modularity(labels: jax.Array, graph: Graph, gamma: float = 1.0) -> jax.Array:
    """Modularity of ``labels`` on a :class:`Graph` (unit edge weights,
    duplicate edges counted with multiplicity, self-loops handled)."""
    if not graph.symmetric:
        raise ValueError(
            "modularity needs the symmetric message list (both edge "
            "directions); rebuild the graph with symmetric=True"
        )
    v = graph.num_vertices
    is_self = graph.msg_recv == graph.msg_send
    w = jnp.where(is_self, 0.0, 1.0)
    # Every self-loop edge appears twice in the symmetric message list;
    # weight-1 edge => self_weight 1 means counting each appearance as 1/2.
    self_w = jax.ops.segment_sum(
        jnp.where(is_self, 0.5, 0.0), graph.msg_recv, num_segments=v,
        indices_are_sorted=True,
    )
    return modularity_weighted(labels, graph.msg_recv, graph.msg_send, w, self_w, v, gamma)
