"""Newman modularity of a community partition.

The reference evaluates community quality only by eyeballing counts
(``Graphframes.py:85,120``); SURVEY §7.7 names Louvain-modularity
comparison as the scale-up capability. This metric is the shared yardstick
for LPA vs Louvain partitions.

Conventions (matching networkx / python-louvain on weighted multigraphs):
the graph is a symmetric weighted message list (both directions of every
edge present) plus per-vertex self-loop weights; a self-loop of weight w
contributes 2w to its vertex's degree and 2w to its community's internal
weight.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from graphmine_tpu.graph.container import Graph


@partial(jax.jit, static_argnames=("num_vertices",))
def modularity_weighted(
    labels: jax.Array,
    recv: jax.Array,
    send: jax.Array,
    weight: jax.Array,
    self_weight: jax.Array,
    num_vertices: int,
    gamma: float = 1.0,
) -> jax.Array:
    """Q = sum_c [ Sigma_in_c / 2m  -  gamma * (Sigma_tot_c / 2m)^2 ].

    ``recv``/``send``/``weight`` are the symmetric message list (self-loops
    excluded, carried in ``self_weight``). Out-of-range ids (padding
    sentinels) are dropped by the segment ops.
    """
    w = weight.astype(jnp.float32)
    k = jax.ops.segment_sum(w, recv, num_segments=num_vertices) + 2.0 * self_weight
    two_m = jnp.maximum(k.sum(), 1e-12)
    valid = recv < num_vertices
    intra_msgs = jnp.where(
        valid & (labels[jnp.minimum(recv, num_vertices - 1)] == labels[send]), w, 0.0
    ).sum()
    sigma_in = intra_msgs + 2.0 * self_weight.sum()
    sigma_tot = jax.ops.segment_sum(k, labels, num_segments=num_vertices)
    return sigma_in / two_m - gamma * jnp.sum((sigma_tot / two_m) ** 2)


def message_weights(graph: Graph) -> tuple[jax.Array, jax.Array]:
    """Split a symmetric graph's messages into ``(w [M], self_w [V])``.

    The single home of the self-loop convention shared by modularity and
    Louvain's level construction: self-loop messages carry weight 0 in
    ``w`` and accumulate half their weight per appearance into ``self_w``
    (each self-loop edge appears twice in the symmetric list, so a
    self-loop of weight x adds 2x to its vertex's degree). Per-edge
    weights come from ``graph.msg_weight`` when present, else 1.
    """
    _require_symmetric(graph)
    v = graph.num_vertices
    is_self = graph.msg_recv == graph.msg_send
    base = 1.0 if graph.msg_weight is None else graph.msg_weight.astype(jnp.float32)
    w = jnp.where(is_self, 0.0, base)
    self_w = jax.ops.segment_sum(
        jnp.where(is_self, 0.5 * base, 0.0), graph.msg_recv, num_segments=v,
        indices_are_sorted=True,
    )
    return w, self_w


def modularity(labels: jax.Array, graph: Graph, gamma: float = 1.0) -> jax.Array:
    """Modularity of ``labels`` on a :class:`Graph` — per-edge weights when
    the graph carries them (``build_graph(edge_weights=...)``), else unit
    weights; duplicate edges counted with multiplicity, self-loops handled.

    Host graphs (``build_graph(to_device=False)``, r3) dispatch to a NumPy
    twin with identical conventions — no O(E) device transfer for graphs
    the memory planner kept off-device."""
    import numpy as np

    if isinstance(graph.msg_recv, np.ndarray):
        return _modularity_host(labels, graph, gamma)
    w, self_w = message_weights(graph)
    return modularity_weighted(
        labels, graph.msg_recv, graph.msg_send, w, self_w,
        graph.num_vertices, gamma,
    )


def _require_symmetric(graph: Graph) -> None:
    """Shared guard: both modularity paths read the symmetric message
    list."""
    if not graph.symmetric:
        raise ValueError(
            "the message-weight decomposition needs the symmetric message "
            "list (both edge directions); rebuild with symmetric=True"
        )


def _modularity_host(labels, graph: Graph, gamma: float):
    """NumPy twin of ``modularity_weighted`` + ``message_weights`` (same
    self-loop and weight conventions; float64 accumulation)."""
    import numpy as np

    _require_symmetric(graph)
    v = graph.num_vertices
    recv = graph.msg_recv
    send = graph.msg_send
    labels = np.asarray(labels)
    base = (
        np.ones(len(recv), np.float64) if graph.msg_weight is None
        else np.asarray(graph.msg_weight, np.float64)
    )
    is_self = recv == send
    w = np.where(is_self, 0.0, base)
    self_w = np.bincount(
        recv, weights=np.where(is_self, 0.5 * base, 0.0), minlength=v
    )
    k = np.bincount(recv, weights=w, minlength=v) + 2.0 * self_w
    two_m = max(float(k.sum()), 1e-12)
    intra = float(w[labels[recv] == labels[send]].sum())
    sigma_in = intra + 2.0 * float(self_w.sum())
    sigma_tot = np.bincount(labels, weights=k, minlength=v)
    return sigma_in / two_m - gamma * float(np.sum((sigma_tot / two_m) ** 2))
