"""k-truss: the maximal subgraph whose every edge closes >= k-2 triangles.

Cohesive-subgraph family companion to k-core (``ops/kcore.py``), with
NetworkX ``nx.k_truss`` parity on the simple undirected graph.

TPU design: the oriented wedge list of ``ops/triangles.py`` is built once
on the host — each discovered triangle knows the *edge indices* of its
three sides (the generating edge, the (u,w) row entry, and the binary-
search hit for (v,w)) — then peeling is a device fixpoint: a triangle
stays valid while all three edges are active, per-edge support is three
``segment_sum`` scatters over edge ids, and edges below ``k - 2`` support
deactivate, all inside one ``lax.while_loop`` with static shapes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.ops.triangles import _oriented_csr


@partial(jax.jit, static_argnames=("num_edges", "search_iters"))
def _truss_peel(ptr, col, wv, ww, e1, e2, k, num_edges: int, search_iters: int):
    # locate the (v, w) closing edge once — the graph is static, only
    # membership changes during peeling
    lo = ptr[wv]
    hi = ptr[wv + 1]

    def bsearch(_, state):
        lo, hi = state
        mid = (lo + hi) // 2
        val = col[jnp.clip(mid, 0, col.shape[0] - 1)]
        go_right = (val < ww) & (mid < hi)
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, jnp.maximum(mid, lo))
        return lo, hi

    lo_f, _ = lax.fori_loop(0, search_iters, bsearch, (lo, hi))
    found = (lo_f < ptr[wv + 1]) & (
        col[jnp.clip(lo_f, 0, col.shape[0] - 1)] == ww
    ) & (wv != ww)
    e3 = jnp.where(found, lo_f, 0).astype(jnp.int32)

    def body(state):
        active, _ = state
        valid = found & active[e1] & active[e2] & active[e3]
        valid_i = valid.astype(jnp.int32)
        sup = (
            jax.ops.segment_sum(valid_i, e1, num_segments=num_edges)
            + jax.ops.segment_sum(valid_i, e2, num_segments=num_edges)
            + jax.ops.segment_sum(valid_i, e3, num_segments=num_edges)
        )
        new_active = active & (sup >= k - 2)
        changed = jnp.sum(new_active != active, dtype=jnp.int32)
        return new_active, changed

    def cond(state):
        _, changed = state
        return changed > 0

    active, _ = lax.while_loop(
        cond, body, (jnp.ones(num_edges, bool), jnp.int32(1))
    )
    return active


def k_truss(graph: Graph, k: int):
    """Edges of the ``k``-truss: ``(a, b)`` int32 arrays with ``a < b``,
    one row per surviving undirected edge (``nx.k_truss`` parity on the
    simplified graph; isolated vertices simply don't appear)."""
    if k < 2:
        raise ValueError("k must be >= 2 (the 2-truss is the whole graph)")
    ptr, col, wu, wv, ww, _, e1, e2 = _oriented_csr(graph)
    num_edges = len(col)
    if num_edges == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.int32)
    # lo endpoint per edge index (col order == edge order)
    lo_of_edge = np.repeat(np.arange(graph.num_vertices, dtype=np.int32),
                           np.diff(ptr).astype(np.int64))
    max_row = int(np.max(np.diff(ptr), initial=1))
    iters = max(int(np.ceil(np.log2(max(max_row, 2)))) + 1, 1)
    active = np.asarray(_truss_peel(
        jnp.asarray(ptr, jnp.int32), jnp.asarray(col),
        jnp.asarray(wv), jnp.asarray(ww),
        jnp.asarray(e1, jnp.int32), jnp.asarray(e2, jnp.int32),
        jnp.int32(k), num_edges=num_edges, search_iters=iters,
    ))
    x, y = lo_of_edge[active], np.asarray(col)[active]
    return np.minimum(x, y), np.maximum(x, y)  # rank orientation -> a < b
