"""SVD++ latent-factor model (GraphFrames ``svdPlusPlus`` parity).

GraphFrames 0.6.0 exposes GraphX's SVD++ (Koren, KDD'08) on the same
``GraphFrame`` object the reference constructs at ``Graphframes.py:78`` —
part of the dependency capability surface (SURVEY §2.2), though the
reference script never calls it. Rating prediction over a bipartite
(user → item) edge set:

    r̂(u, i) = μ + b_u + b_i + q_iᵀ (p_u + |N(u)|^-½ Σ_{j∈N(u)} y_j)

GraphX trains it with per-edge SGD inside Pregel supersteps (a sequential
host-order scan). The TPU-native redesign is **full-batch gradient descent**
— each epoch is two gathers + four ``segment_sum`` reductions + dense
[V, rank] updates, all inside one ``lax.scan``-compiled loop — trading
SGD's sample efficiency for complete vectorization; the factor updates are
dense [V, rank] ops that XLA fuses and tiles onto the MXU for realistic
ranks.

Gradients through a segment mean (not raw sum) keep the step size
degree-independent on power-law graphs — the full-batch analog of GraphX's
per-edge step.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["SVDPlusPlusModel", "svd_plus_plus", "svdpp_predict"]


@dataclass
class SVDPlusPlusModel:
    """Learned parameters; all arrays indexed by vertex id.

    ``p``/``q``/``y``: user factors, item factors, implicit-feedback item
    factors, each ``[V, rank]``; ``bu``/``bi``: biases ``[V]``; ``mu``:
    global mean rating (GraphX returns the same tuple shape: per-vertex
    (factors, bias) arrays plus μ).
    """

    p: jax.Array
    q: jax.Array
    y: jax.Array
    bu: jax.Array
    bi: jax.Array
    mu: jax.Array

    def tree_flatten(self):  # pragma: no cover - trivial
        return (self.p, self.q, self.y, self.bu, self.bi, self.mu), None

    @classmethod
    def tree_unflatten(cls, _, leaves):  # pragma: no cover - trivial
        return cls(*leaves)


jax.tree_util.register_pytree_node(
    SVDPlusPlusModel,
    SVDPlusPlusModel.tree_flatten,
    SVDPlusPlusModel.tree_unflatten,
)


def _implicit(p, y, src, dst, norm, v):
    """z_u = p_u + |N(u)|^-½ Σ_{j∈N(u)} y_j  (one gather + one segment_sum)."""
    acc = jax.ops.segment_sum(y[dst], src, num_segments=v)
    return p + acc * norm[:, None]


@partial(jax.jit, static_argnames=("num_vertices", "rank", "max_iter"))
def _train(
    src,
    dst,
    ratings,
    num_vertices,
    rank,
    max_iter,
    lr_bias,
    lr_factor,
    reg_bias,
    reg_factor,
    min_val,
    max_val,
    seed,
):
    v, e = num_vertices, src.shape[0]
    mu = jnp.mean(ratings)
    deg_u = jax.ops.segment_sum(jnp.ones((e,), jnp.float32), src, num_segments=v)
    deg_i = jax.ops.segment_sum(jnp.ones((e,), jnp.float32), dst, num_segments=v)
    inv_u = jnp.where(deg_u > 0, 1.0 / jnp.maximum(deg_u, 1.0), 0.0)
    inv_i = jnp.where(deg_i > 0, 1.0 / jnp.maximum(deg_i, 1.0), 0.0)
    norm = jnp.where(deg_u > 0, lax.rsqrt(jnp.maximum(deg_u, 1.0)), 0.0)

    k0, k1, k2 = jax.random.split(jax.random.PRNGKey(seed), 3)
    scale = 0.1 / jnp.sqrt(jnp.float32(rank))
    params = SVDPlusPlusModel(
        p=jax.random.normal(k0, (v, rank), jnp.float32) * scale,
        q=jax.random.normal(k1, (v, rank), jnp.float32) * scale,
        y=jax.random.normal(k2, (v, rank), jnp.float32) * scale,
        bu=jnp.zeros((v,), jnp.float32),
        bi=jnp.zeros((v,), jnp.float32),
        mu=mu,
    )

    def seg_mean_u(vals):
        s = jax.ops.segment_sum(vals, src, num_segments=v)
        return s * (inv_u[:, None] if vals.ndim == 2 else inv_u)

    def seg_mean_i(vals):
        s = jax.ops.segment_sum(vals, dst, num_segments=v)
        return s * (inv_i[:, None] if vals.ndim == 2 else inv_i)

    def epoch(m, _):
        z = _implicit(m.p, m.y, src, dst, norm, v)
        pred = m.mu + m.bu[src] + m.bi[dst] + jnp.sum(m.q[dst] * z[src], axis=1)
        pred = jnp.clip(pred, min_val, max_val)
        err = ratings - pred  # [E]
        rmse = jnp.sqrt(jnp.mean(err * err))

        bu = m.bu + lr_bias * (seg_mean_u(err) - reg_bias * m.bu)
        bi = m.bi + lr_bias * (seg_mean_i(err) - reg_bias * m.bi)
        # dL/dq_i = mean_u err * z_u ; dL/dp_u = mean_i err * q_i
        q = m.q + lr_factor * (seg_mean_i(err[:, None] * z[src]) - reg_factor * m.q)
        p = m.p + lr_factor * (seg_mean_u(err[:, None] * m.q[dst]) - reg_factor * m.p)
        # y_j gradient: each rating (u, i) pushes err*norm_u*q_i onto every
        # j ∈ N(u). t_u = Σ_i err q_i (per-user), then scatter t back to
        # items through the same edges — two segment_sums, no E² blowup.
        t = jax.ops.segment_sum(err[:, None] * m.q[dst], src, num_segments=v)
        y_grad = seg_mean_i((norm * inv_u)[src, None] * t[src])
        y = m.y + lr_factor * (y_grad - reg_factor * m.y)
        return SVDPlusPlusModel(p, q, y, bu, bi, m.mu), rmse

    params, rmse_hist = lax.scan(epoch, params, None, length=max_iter)
    return params, rmse_hist


def svd_plus_plus(
    src,
    dst,
    ratings,
    num_vertices: int,
    rank: int = 10,
    max_iter: int = 20,
    lr_bias: float = 0.5,
    lr_factor: float = 0.5,
    reg_bias: float = 0.05,
    reg_factor: float = 0.05,
    min_val: float = 0.0,
    max_val: float = 5.0,
    seed: int = 0,
):
    """Train SVD++ on rating edges ``(src=user, dst=item, rating)``.

    Returns ``(model, rmse_history)`` — ``rmse_history[t]`` is the training
    RMSE at the start of epoch ``t`` (the structured observability signal;
    GraphX exposes nothing). Hyperparameter names mirror GraphX's ``Conf``:
    rank/maxIters/minVal/maxVal/gamma1/gamma2/lambda1/lambda2 map to
    rank/max_iter/min_val/max_val/lr_bias/lr_factor/reg_bias/reg_factor.
    """
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    ratings = jnp.asarray(ratings, jnp.float32)
    return _train(
        src, dst, ratings, num_vertices, rank, max_iter,
        lr_bias, lr_factor, reg_bias, reg_factor, min_val, max_val, seed,
    )


@jax.jit
def _predict(model: SVDPlusPlusModel, src, dst, train_src, train_dst):
    v = model.p.shape[0]
    e = train_src.shape[0]
    deg_u = jax.ops.segment_sum(
        jnp.ones((e,), jnp.float32), train_src, num_segments=v
    )
    norm = jnp.where(deg_u > 0, lax.rsqrt(jnp.maximum(deg_u, 1.0)), 0.0)
    z = _implicit(model.p, model.y, train_src, train_dst, norm, v)
    return model.mu + model.bu[src] + model.bi[dst] + jnp.sum(
        model.q[dst] * z[src], axis=1
    )


def svdpp_predict(
    model: SVDPlusPlusModel,
    src,
    dst,
    train_src,
    train_dst,
    min_val: float | None = 0.0,
    max_val: float | None = 5.0,
):
    """Predict ratings for query pairs; ``train_*`` define N(u).

    Predictions are clipped to ``[min_val, max_val]`` — the same range the
    training loss used (pass ``None`` to disable either bound)."""
    out = _predict(
        model,
        jnp.asarray(src, jnp.int32),
        jnp.asarray(dst, jnp.int32),
        jnp.asarray(train_src, jnp.int32),
        jnp.asarray(train_dst, jnp.int32),
    )
    if min_val is not None or max_val is not None:
        out = jnp.clip(out, min_val, max_val)
    return out
