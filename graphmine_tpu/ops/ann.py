"""Approximate kNN: TPU-native IVF-flat (k-means + cluster-probe search).

Why this exists (r5, measured): the exact all-pairs scorer is AT the
hardware's top_k/sort roofline — `lax.top_k` on a [1024, 262144] f32
tile runs at 1.6G elem/s against the roofline tier's 1.85G row-sort
rate, so no exact implementation gets meaningfully faster
(docs/DESIGN.md "Exact kNN is at the sort roofline"). The remaining
lever is FEWER CANDIDATE PAIRS. IVF-flat measured 0.95–0.98 recall@32
touching 6–13% of N on Gaussian data — the WORST case for it (real LOF
feature clouds are clustered, which is exactly what inverted lists
exploit).

TPU-first shape discipline — everything the device sees is static:

- **k-means** (:func:`kmeans`): Lloyd iterations where the assignment
  step is the row-tiled `cross_knn` matmul (MXU) and the update is one
  `segment_sum`; empty clusters keep their previous center.
- **Inverted lists**: points are permuted host-side into cluster order,
  every cluster's member row padded to one static ``Lmax``.
- **Cluster-batched search**: each query probes its ``n_probe`` nearest
  centers; (query, cluster) pairs are grouped BY CLUSTER host-side and
  padded to one static ``Qmax``, so the device runs a single
  ``lax.map`` over clusters of ``[Qmax, F] x [F, Lmax]`` distance
  blocks + ``top_k`` — no irregular [N, n_probe * Lmax] gather (which
  would put the candidate fetch right back on the gather roofline the
  exact path already saturates). A member belongs to exactly one
  cluster, so per-query candidates are duplicate-free by construction
  and the final merge is one ``top_k`` over ``n_probe * k``.

The result contract matches :func:`graphmine_tpu.ops.knn.knn`:
``(d2, idx)`` ascending, self excluded — so
:func:`graphmine_tpu.ops.lof.lof_from_knn` consumes it unchanged
(``lof_scores(impl="ivf")``). Shapes (C, Qmax, Lmax) are data-dependent,
so one XLA compile per dataset shape — the same trade the bucketed LPA
plan makes, amortized over every LOF call on that cloud.

The reference has no kNN at all; this extends the north-star scorer
(BASELINE.json "kNN-graph + LOF") past the all-pairs wall.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.ops.knn import cross_knn


_ASSIGN_TILE = 1 << 15  # [32768, C] distance tiles: 64 MB at C=512


def default_n_clusters(n: int) -> int:
    """The IVF index's default cluster count for an ``n``-point set:
    ``~sqrt(N)``, rounded to a multiple of 8, min 8. Single owner —
    :func:`ivf_knn`'s default, the streaming re-fit's full-window sizing
    (and its exact-warmup gate ``n < 4 * C``), and the stream bench's
    reuse micro-bench must all size the SAME index, or a retune here
    would silently desync what they build/gate/measure."""
    return max(8, int(round(np.sqrt(n) / 8)) * 8)


@jax.jit
def _assign_tiled(points: jax.Array, centers: jax.Array) -> jax.Array:
    """Nearest-center id per point via row-tiled full [T, C] distances
    (one matmul + argmin per tile — no top_k machinery; C is small)."""
    n = points.shape[0]
    n_pad = -(-n // _ASSIGN_TILE) * _ASSIGN_TILE
    tiles = jnp.pad(points, ((0, n_pad - n), (0, 0))).reshape(
        n_pad // _ASSIGN_TILE, _ASSIGN_TILE, -1
    )
    c_sq = jnp.sum(centers * centers, axis=1)

    def tile(p):
        cross = lax.dot_general(
            p, centers, dimension_numbers=(((1,), (1,)), ((), ())),
            precision=lax.Precision.HIGHEST,
        )
        # |p|^2 is constant per row — argmin doesn't need it
        return jnp.argmin(c_sq[None, :] - 2.0 * cross, axis=1)

    return lax.map(tile, tiles).reshape(n_pad)[:n].astype(jnp.int32)


@jax.jit
def _lloyd_step(points: jax.Array, centers: jax.Array) -> jax.Array:
    a = _assign_tiled(points, centers)
    c = centers.shape[0]
    sums = jax.ops.segment_sum(points, a, num_segments=c)
    counts = jax.ops.segment_sum(
        jnp.ones((points.shape[0],), jnp.float32), a, num_segments=c
    )
    return jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None],
        centers,
    )


def kmeans(points, n_clusters: int, iters: int = 5, seed: int = 0):
    """Lloyd k-means, MXU-assigned. Returns float32 centers
    ``[n_clusters, F]``. Deterministic in ``seed`` (init = a seeded
    sample of the points). Iterations are host-unrolled calls of one
    jitted step — a ``lax.scan`` around the tiled assignment hit a
    multi-minute XLA:TPU compile (the r4 scan-nesting pathology class);
    the unrolled form compiles the step once and reuses it."""
    pts = np.asarray(points, np.float32)
    n = pts.shape[0]
    if n_clusters > n:
        raise ValueError(f"n_clusters={n_clusters} > num points {n}")
    rng = np.random.default_rng(seed)
    init = pts[rng.choice(n, n_clusters, replace=False)]
    centers = jnp.asarray(init)
    pts_dev = jnp.asarray(pts)
    for _ in range(iters):
        centers = _lloyd_step(pts_dev, centers)
    return centers


@partial(jax.jit, static_argnames=("k",))
def _search_clusters(q_vec, q_gid, m_vec, m_gid, m_valid, k: int):
    """One cluster's block: exact distances from its padded query batch
    to its padded member list, masked top-k. Shapes: q_vec [Qmax, F],
    m_vec [Lmax, F]; returns ([Qmax, k] d2 asc, [Qmax, k] global ids)."""
    cross = lax.dot_general(
        q_vec, m_vec, dimension_numbers=(((1,), (1,)), ((), ())),
        precision=lax.Precision.HIGHEST,  # the r4 MXU bf16 lesson
    )
    d2 = (
        jnp.sum(q_vec * q_vec, axis=1)[:, None]
        - 2.0 * cross
        + jnp.sum(m_vec * m_vec, axis=1)[None, :]
    )
    d2 = jnp.maximum(d2, 0.0)
    d2 = jnp.where(~m_valid[None, :], jnp.inf, d2)
    d2 = jnp.where(q_gid[:, None] == m_gid[None, :], jnp.inf, d2)  # self
    neg, j = lax.top_k(-d2, k)
    return -neg, m_gid[j]


def _search_chunks(pts, m_gid, m_valid, q_gid, row_sub, k: int):
    """Default (single-device) executor for the cluster-batched search:
    one ``lax.map`` over the fixed-size query chunks. Inputs are the host
    tables :func:`ivf_knn` built (float32 points, int32 member/query ids,
    bool member validity); returns ``([R, B, k] d2, [R, B, k] gid)``.
    Padded duplicate query slots produce junk rows; they are never read
    back (``slot_of_pair`` only maps REAL pairs)."""
    pts_dev = jnp.asarray(pts)
    m_gid_dev = jnp.asarray(m_gid)
    m_valid_dev = jnp.asarray(m_valid)

    def one_chunk(args):
        qg, s = args
        mg = m_gid_dev[s]
        return _search_clusters(
            pts_dev[qg], qg, pts_dev[mg], mg, m_valid_dev[s], k
        )

    return lax.map(one_chunk, (jnp.asarray(q_gid), jnp.asarray(row_sub)))


def _exact_fallback(pts, k, guard: str, detail: str, sink):
    """The honest exit when an IVF pathology guard trips: run the exact
    path — but LOUDLY (ADVICE r5). The silent version cost a round of
    bench triage: 'ivf' timings that were secretly exact-path timings.
    ``guard`` names which guard fired; the warning + ``ivf_fallback``
    metrics record carry it."""
    import warnings

    from graphmine_tpu.ops.knn import knn as exact_knn

    warnings.warn(
        f"ivf_knn guard {guard!r} tripped ({detail}); falling back to the "
        "exact kNN path",
        stacklevel=3,
    )
    if sink is not None:
        sink.emit("ivf_fallback", guard=guard, detail=detail)
    return exact_knn(pts, k, impl="auto")


def ivf_knn(
    points,
    k: int,
    n_clusters: int | None = None,
    n_probe: int = 16,
    seed: int = 0,
    kmeans_iters: int = 5,
    sink=None,
    centers=None,
    search_exec=None,
):
    """Approximate k nearest neighbors (IVF-flat). ``(d2, idx)`` like
    :func:`~graphmine_tpu.ops.knn.knn`: ``[N, k]`` ascending squared
    distances, self excluded, float32/int32.

    ``n_clusters`` defaults to ``~sqrt(N)`` (rounded to a multiple of 8,
    min 8); ``n_probe`` nearest clusters are searched per query —
    recall rises with ``n_probe / n_clusters`` (measured 0.95–0.98 at
    6–13% candidate fraction on Gaussian clouds; the bench lof tier
    records recall on its real feature cloud). Falls back to the exact
    path when the cloud is too small for the machinery to pay
    (``N < 4 * n_clusters`` or ``k >= Lmax`` after clustering); pathology
    guards (capacity / probe skew / chunk-index bound) also fall back,
    each with a ``warnings.warn`` and — when ``sink`` (a
    :class:`~graphmine_tpu.pipeline.metrics.MetricsSink`) is given — an
    ``ivf_fallback`` record naming the guard (ADVICE r5).

    ``centers`` (r6): pre-trained float32 ``[C, F]`` k-means centers —
    skips the Lloyd iterations entirely (the expensive part of index
    construction) and only re-assigns points against them. The streaming
    LOF scorer reuses one trained index across sliding windows this way
    (centroids are stable between chunks; see
    :class:`~graphmine_tpu.ops.streaming_lof.StreamingLOF`).

    ``search_exec`` (r6): overrides the device executor for the
    cluster-batched search stage — ``(pts, m_gid, m_valid, q_gid,
    row_sub, k) -> (d2_all, gid_all)`` of shape ``[R', B, k]`` with
    ``R' >= R`` chunk rows (extra padded rows appended at the END are
    sliced off; their results are never read). The mesh-sharded LOF path
    distributes exactly this stage — the dominant distance work — over
    devices (:func:`graphmine_tpu.parallel.knn.sharded_lof`).
    """
    pts = np.asarray(points, np.float32)
    n, f = pts.shape
    if not 0 < k < n:
        raise ValueError(f"k={k} must be in (0, {n})")
    if centers is not None:
        centers = jnp.asarray(np.asarray(centers, np.float32))
        if centers.ndim != 2 or centers.shape[1] != f:
            raise ValueError(
                f"centers must be [C, {f}], got {tuple(centers.shape)}"
            )
        n_clusters = int(centers.shape[0])
    elif n_clusters is None:
        n_clusters = default_n_clusters(n)
    n_probe = min(n_probe, n_clusters)
    from graphmine_tpu.ops.knn import knn as exact_knn

    if n < 4 * n_clusters:
        # documented sizing fallback, not a pathology guard: tiny clouds
        # route to the exact path by design, no warning
        return exact_knn(pts, k, impl="auto")

    if centers is None:
        centers = kmeans(pts, n_clusters, iters=kmeans_iters, seed=seed)
    # probe assignment: each query's n_probe nearest centers; column 0
    # is the owning cluster (a point is always a member of its own
    # nearest cluster's list).
    _, probe = cross_knn(jnp.asarray(pts), centers, n_probe)
    probe = np.asarray(probe)
    assign = probe[:, 0]

    # ---- host: SIZE-CAPPED inverted sublists ---------------------------
    # k-means on clustered data skews hard (one blob -> one giant
    # cluster); an uncapped member matrix sets Lmax = that cluster's
    # size, and every chunk probing it pays [B, Lmax] distance + top_k
    # work — measured WORSE than exact at 262K on 64-blob data. Big
    # clusters are split into sublists of at most l_cap members; a query
    # probing the cluster searches all of its sublists (pairs expand
    # accordingly; the per-query merge pads to the max pair count).
    order = np.argsort(assign, kind="stable")     # members in cluster order
    sizes = np.bincount(assign, minlength=n_clusters)
    starts = np.zeros(n_clusters, np.int64)
    np.cumsum(sizes[:-1], out=starts[1:])
    l_cap = max(2 * (-(-n // n_clusters)), k + 1)
    n_subs_per_c = np.maximum(-(-sizes // l_cap), 1)
    n_sub = int(n_subs_per_c.sum())
    sub_cluster = np.repeat(np.arange(n_clusters), n_subs_per_c)
    sub_first = np.zeros(n_clusters, np.int64)
    np.cumsum(n_subs_per_c[:-1], out=sub_first[1:])
    sub_rank = np.arange(n_sub) - sub_first[sub_cluster]
    sub_start = starts[sub_cluster] + sub_rank * l_cap
    sub_len = np.minimum(sizes[sub_cluster] - sub_rank * l_cap, l_cap)
    sub_len = np.maximum(sub_len, 0)
    l_max = int(sub_len.max())
    if k >= sizes.max():
        # no cluster can fill its own top-k; recall craters — the honest
        # move is the exact path.
        return _exact_fallback(
            pts, k, "k_unfillable",
            f"k={k} >= largest cluster size {int(sizes.max())}", sink,
        )
    # member id matrix [n_sub, Lmax] (clamps keep empty sublists
    # in-bounds; their rows are fully masked)
    j = np.arange(l_max)
    m_rows = sub_start[:, None] + np.minimum(
        j[None, :], np.maximum(sub_len[:, None] - 1, 0)
    )
    m_gid = order[np.minimum(m_rows, n - 1)].astype(np.int32)
    m_valid = j[None, :] < sub_len[:, None]

    # (query, sublist) pairs grouped by sublist, then chopped into
    # FIXED-size chunks of B query slots: one hot sublist probed by half
    # the queries would otherwise set a padded [Qmax] batch shape and an
    # O(n_sub x Qmax x k) result — the first 262K run OOMed exactly
    # there. Chunk rows bound the device working set independent of
    # probe skew.
    chunk_b = 4096
    probe_subs = n_subs_per_c[probe]              # [N, p] sublists/probe
    pairs_per_q = probe_subs.sum(axis=1)          # [N]
    p_max = int(pairs_per_q.max())

    # Two pathology guards (code-review r5), both -> honest exact path:
    #
    # 1. CAPACITY: a query whose probed clusters hold < k+1 members
    #    total cannot fill its top-k; the inf-padded slots would reach
    #    lof_from_knn, whose duplicate-floor eps reads dists.sum() —
    #    one inf row silently zeroes EVERY LOF score.
    # 2. SKEW: one dominant cluster (k-means found no real structure)
    #    expands into ~size/l_cap sublists per probe; the pair tables
    #    and [n_pairs, k] result buffers then scale with that skew —
    #    the same blowup class the sublist cap fixed on the member
    #    side. IVF has nothing to exploit on such a cloud anyway.
    probed_sizes = sizes[probe].sum(axis=1)       # members across probes
    if int(probed_sizes.min()) < k + 1:
        return _exact_fallback(
            pts, k, "capacity",
            f"a query's probed clusters hold {int(probed_sizes.min())} "
            f"members < k+1={k + 1} (its top-k cannot fill)", sink,
        )
    if p_max > 4 * n_probe:
        return _exact_fallback(
            pts, k, "skew",
            f"probe expansion {p_max} sublists/query > 4*n_probe="
            f"{4 * n_probe} (one dominant cluster; IVF has no structure "
            "to exploit)", sink,
        )
    pair_q = np.repeat(
        np.arange(n, dtype=np.int64), pairs_per_q
    )
    # expand each probed cluster c into sub_first[c] .. +n_subs_per_c[c]
    flat_c = probe.reshape(-1).astype(np.int64)
    flat_q_subs = probe_subs.reshape(-1)
    pair_c = (
        np.repeat(sub_first[flat_c], flat_q_subs)
        + (
            np.arange(int(flat_q_subs.sum()))
            - np.repeat(
                np.cumsum(flat_q_subs) - flat_q_subs, flat_q_subs
            )
        )
    )
    n_pairs = len(pair_q)
    pair_order = np.argsort(pair_c, kind="stable")
    q_counts = np.bincount(pair_c, minlength=n_sub)
    q_starts = np.zeros(n_sub, np.int64)
    np.cumsum(q_counts[:-1], out=q_starts[1:])
    chunks_per_s = -(-q_counts // chunk_b)       # ceil; 0 for unprobed
    r_rows = int(chunks_per_s.sum())
    # Loud int32 bound (ADVICE r5): the merge-gather take table indexes
    # the flat [r_rows * chunk_b + 1] result rows, and jnp.asarray would
    # SILENTLY downcast an int64 host table to int32 on device — a row id
    # past 2^31-1 would wrap to a junk gather instead of failing. The
    # junk-row sentinel id r_rows * chunk_b is the largest value stored.
    if r_rows * chunk_b >= (1 << 31):
        return _exact_fallback(
            pts, k, "index_bound",
            f"merge-gather row ids reach {r_rows * chunk_b:,} >= 2^31 "
            "(int32 device gather would wrap)", sink,
        )
    row_sub = np.repeat(np.arange(n_sub), chunks_per_s)
    chunk_rank = (
        np.arange(r_rows) - np.repeat(
            np.cumsum(chunks_per_s) - chunks_per_s, chunks_per_s
        )
    )
    row_start = q_starts[row_sub] + chunk_rank * chunk_b
    row_len = np.minimum(
        q_counts[row_sub] - chunk_rank * chunk_b, chunk_b
    )
    jb = np.arange(chunk_b)
    q_rows = row_start[:, None] + np.minimum(
        jb[None, :], np.maximum(row_len[:, None] - 1, 0)
    )
    q_valid = jb[None, :] < row_len[:, None]
    q_gid = pair_q[pair_order[q_rows]].astype(np.int32)  # [R, B]

    # inverse mapping: valid (row, slot) cells in row-major order visit
    # sorted pair positions 0..P-1 in order (chunks ascend within each
    # ascending sublist), so each REAL pair's flat [R * B] result row is
    # its valid-cell flat index.
    slot_of_pair = np.empty(n_pairs, np.int64)
    slot_of_pair[pair_order] = np.arange(
        r_rows * chunk_b
    ).reshape(r_rows, chunk_b)[q_valid]

    exec_fn = search_exec if search_exec is not None else _search_chunks
    d2_all, gid_all = exec_fn(
        pts, m_gid, m_valid, q_gid, row_sub.astype(np.int32), k
    )
    if d2_all.shape[0] < r_rows or d2_all.shape != (
        d2_all.shape[0], chunk_b, k
    ) or gid_all.shape != d2_all.shape:
        # a short/misshapen executor result would otherwise clamp real
        # pair indices onto the junk row in the merge gather — degraded
        # results with no error. Fail loudly instead.
        raise ValueError(
            f"search_exec returned shapes {tuple(d2_all.shape)}/"
            f"{tuple(gid_all.shape)}; expected [R'>= {r_rows}, "
            f"{chunk_b}, {k}] with extra rows appended at the end"
        )
    # [R', B, k] -> per-pair rows -> tiled [T, p_max * k] merges (one
    # monolithic [N, p_max * k] gather + top_k would hold ~4 GB of merge
    # operands at 262K x 16 x 128). Queries with fewer than p_max pairs
    # pad with the appended all-inf junk row: never selected. The slice
    # to r_rows * chunk_b drops any executor-padded chunk rows (a mesh
    # executor pads R to a device-count multiple) AND pins the junk-row
    # sentinel id below at the same flat index either way.
    d2_flat = jnp.concatenate(
        [d2_all.reshape(-1, k)[: r_rows * chunk_b],
         jnp.full((1, k), jnp.inf, d2_all.dtype)]
    )
    gid_flat = jnp.concatenate(
        [gid_all.reshape(-1, k)[: r_rows * chunk_b],
         jnp.full((1, k), -1, jnp.int32)]
    )
    junk = r_rows * chunk_b
    merge_t = 16384
    n_pad = -(-n // merge_t) * merge_t
    take = np.full((n_pad, p_max), junk, np.int64)
    pair_col = (
        np.arange(n_pairs)
        - np.repeat(np.cumsum(pairs_per_q) - pairs_per_q, pairs_per_q)
    )
    take[pair_q, pair_col] = slot_of_pair
    # Explicit int32, not an implicit jnp downcast: the bound above
    # guarantees every row id (junk sentinel included) fits, and the cast
    # states the invariant instead of relying on x64-mode defaults.
    take_dev = jnp.asarray(
        take.astype(np.int32).reshape(n_pad // merge_t, merge_t, p_max)
    )

    # NB: the flat result arrays are jit ARGUMENTS, not closure captures
    # — a closed-over concrete array is baked into the HLO as a constant,
    # and serializing the ~GB-scale [R * B, k] buffers hung XLA:TPU
    # compilation for minutes (found the hard way, r5).
    d2_out, gid_out = _merge_tiles(d2_flat, gid_flat, take_dev, k)
    return (
        d2_out.reshape(n_pad, k)[:n],
        gid_out.reshape(n_pad, k)[:n],
    )


@partial(jax.jit, static_argnames=("k",))
def _merge_tiles(d2_flat, gid_flat, take_tiles, k: int):
    """Per-query merge: gather each tile's pair rows, one top-k over the
    ``p_max * k`` candidates (duplicate-free: every member belongs to
    exactly one sublist)."""
    merge_t, p_max = take_tiles.shape[1], take_tiles.shape[2]

    def tile(tk):
        d2_t = d2_flat[tk].reshape(merge_t, p_max * k)
        gid_t = gid_flat[tk].reshape(merge_t, p_max * k)
        neg, sel = lax.top_k(-d2_t, k)
        return -neg, jnp.take_along_axis(gid_t, sel, axis=1)

    return lax.map(tile, take_tiles)
