"""Outlier detection, parity path: recursive LPA + bottom-decile threshold.

This is the capability the reference *intended* but left as dead code
(``Graphframes.py:121-137``): for every community, re-run label propagation
on its induced subgraph, then flag sub-communities in the bottom decile by
size as outliers.

TPU-native design: instead of a host loop building a GraphFrame per
community (the dead spec), one **masked global LPA** computes every
community's recursive LPA simultaneously — cross-community messages are
retargeted to a drop sentinel, so propagation happens strictly inside each
community's induced subgraph. O(E) per superstep, zero host loops, no
dynamic shapes.

The decile rule follows the dead spec (``Graphframes.py:135-136``):
sub-communities sorted by size descending, threshold element at index
``-len//10``; communities with fewer than 10 sub-communities produce no
outliers (the reference's ``-int(len/10)`` would index element 0 there —
a bug we do not copy).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.ops.segment import segment_mode


@partial(jax.jit, static_argnames=("max_iter",))
def masked_label_propagation(
    graph: Graph, communities: jax.Array, max_iter: int = 5
) -> jax.Array:
    """LPA restricted to intra-community edges, for all communities at once.

    Equivalent to running ``labelPropagation(maxIter)`` independently on
    every community's induced subgraph (the dead spec at
    ``Graphframes.py:122-126``), because labels can only flow along
    messages whose endpoints share a community.
    """
    v = graph.num_vertices
    keep = communities[graph.msg_send] == communities[graph.msg_recv]
    recv = jnp.where(keep, graph.msg_recv, v)  # v = drop sentinel
    deg = jax.ops.segment_sum(keep.astype(jnp.int32), graph.msg_recv, num_segments=v)
    labels0 = jnp.arange(v, dtype=jnp.int32)

    def step(labels, _):
        msg = labels[graph.msg_send]
        mode, _ = segment_mode(recv, msg, num_segments=v)
        return jnp.where(deg > 0, mode, labels).astype(jnp.int32), None

    labels, _ = lax.scan(step, labels0, None, length=max_iter)
    return labels


@dataclass(frozen=True)
class OutlierReport:
    """Result of the recursive-LPA outlier pass (host-side arrays)."""

    sub_labels: np.ndarray        # int32 [V] sub-community of each vertex
    outlier_vertices: np.ndarray  # bool [V] vertex is in an outlier sub-community
    sub_sizes: np.ndarray         # int32 [S] size of each distinct sub-community
    sub_parents: np.ndarray       # int32 [S] parent community of each sub-community
    thresholds: dict              # parent community -> bottom-decile size threshold


def recursive_lpa_outliers(
    graph: Graph, communities: jax.Array, max_iter: int = 5, decile: float = 0.1
) -> OutlierReport:
    """Parity outlier detector (dead spec, ``Graphframes.py:121-137``).

    Device side: one masked LPA over the whole graph. Host side: the
    per-parent decile thresholds over the (tiny) sub-community size table.
    """
    sub = np.asarray(masked_label_propagation(graph, communities, max_iter=max_iter))
    comm = np.asarray(communities)
    sub_ids, inverse, sizes = np.unique(sub, return_inverse=True, return_counts=True)
    parents = comm[sub_ids]  # sub-community label = a member vertex id

    outlier_sub = np.zeros(len(sub_ids), dtype=bool)
    thresholds: dict[int, int] = {}
    for parent in np.unique(parents):
        in_parent = parents == parent
        n = int(in_parent.sum())
        cut = int(n * decile)
        if cut == 0:
            continue  # fewer than 1/decile sub-communities: no decile defined
        order = np.sort(sizes[in_parent])[::-1]  # most_common() order (:135)
        threshold = int(order[-cut])
        thresholds[int(parent)] = threshold
        outlier_sub |= in_parent & (sizes <= threshold)

    return OutlierReport(
        sub_labels=sub.astype(np.int32),
        outlier_vertices=outlier_sub[inverse],
        sub_sizes=sizes.astype(np.int32),
        sub_parents=parents.astype(np.int32),
        thresholds=thresholds,
    )
