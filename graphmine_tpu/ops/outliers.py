"""Outlier detection, parity path: recursive LPA + bottom-decile threshold.

This is the capability the reference *intended* but left as dead code
(``Graphframes.py:121-137``): for every community, re-run label propagation
on its induced subgraph, then flag sub-communities in the bottom decile by
size as outliers.

TPU-native design: instead of a host loop building a GraphFrame per
community (the dead spec), one **masked global LPA** computes every
community's recursive LPA simultaneously — cross-community messages are
retargeted to a drop sentinel, so propagation happens strictly inside each
community's induced subgraph. O(E) per superstep, zero host loops, no
dynamic shapes.

The decile rule follows the dead spec (``Graphframes.py:135-136``):
sub-communities sorted by size descending, threshold element at index
``-len//10``; communities with fewer than 10 sub-communities produce no
outliers (the reference's ``-int(len/10)`` would index element 0 there —
a bug we do not copy).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.ops.segment import segment_mode


@partial(jax.jit, static_argnames=("max_iter",))
def masked_label_propagation(
    graph: Graph, communities: jax.Array, max_iter: int = 5
) -> jax.Array:
    """LPA restricted to intra-community edges, for all communities at once.

    Equivalent to running ``labelPropagation(maxIter)`` independently on
    every community's induced subgraph (the dead spec at
    ``Graphframes.py:122-126``), because labels can only flow along
    messages whose endpoints share a community.
    """
    v = graph.num_vertices
    keep = communities[graph.msg_send] == communities[graph.msg_recv]
    recv = jnp.where(keep, graph.msg_recv, v)  # v = drop sentinel
    deg = jax.ops.segment_sum(keep.astype(jnp.int32), graph.msg_recv, num_segments=v)
    labels0 = jnp.arange(v, dtype=jnp.int32)

    def step(labels, _):
        msg = labels[graph.msg_send]
        mode, _ = segment_mode(recv, msg, num_segments=v)
        return jnp.where(deg > 0, mode, labels).astype(jnp.int32), None

    labels, _ = lax.scan(step, labels0, None, length=max_iter)
    return labels


@dataclass(frozen=True)
class OutlierReport:
    """Result of the recursive-LPA outlier pass (host-side arrays)."""

    sub_labels: np.ndarray        # int32 [V] sub-community of each vertex
    outlier_vertices: np.ndarray  # bool [V] vertex is in an outlier sub-community
    sub_sizes: np.ndarray         # int32 [S] size of each distinct sub-community
    sub_parents: np.ndarray       # int32 [S] parent community of each sub-community
    thresholds: dict              # parent community -> bottom-decile size threshold


def recursive_lpa_outliers(
    graph: Graph, communities: jax.Array, max_iter: int = 5, decile: float = 0.1
) -> OutlierReport:
    """Parity outlier detector (dead spec, ``Graphframes.py:121-137``).

    Device side: one masked LPA over the whole graph. Host side: the
    per-parent decile thresholds over the (tiny) sub-community size table.
    """
    sub = np.asarray(masked_label_propagation(graph, communities, max_iter=max_iter))
    return _decile_report(sub, np.asarray(communities), decile)


def recursive_lpa_outliers_sharded(
    graph: Graph,
    communities,
    mesh,
    max_iter: int = 5,
    decile: float = 0.1,
    schedule: str = "replicated",
) -> OutlierReport:
    """Scale-out recursive-LPA outlier pass (dead spec,
    ``Graphframes.py:121-137``) for graphs that do not fit one device.

    Equivalence: masked LPA retargets every cross-community message to a
    drop sentinel, so it equals PLAIN LPA over the graph whose edge set is
    filtered to intra-community edges — ``segment_mode`` is value-sorted
    with a smallest-value tie-break (order-independent), and both keep a
    vertex's own label when it has no surviving messages. That filtered
    graph is built HOST-side (NumPy, O(E)) from the host-resident arrays
    of a scale-out :class:`Graph`, then partitioned over the mesh and run
    through the distributed LPA schedules — so the reference's specified
    outlier capability survives at exactly the scale where the
    device-resident masked pass cannot (VERDICT r3 item 2).

    ``schedule``: ``"replicated"`` (full label vector per device, one
    all_gather per superstep) or ``"ring"`` (labels stay sharded, chunks
    rotate over ICI) — pass the planner-resolved schedule of the main run.
    The filtered graph is a subgraph of the one the planner already
    budgeted, partitioned with the plain sort-body CSR (no bucket plan):
    strictly less device memory than the main LPA under the same schedule.

    The recursive pass is unweighted regardless of ``graph.msg_weight``
    (parity with :func:`masked_label_propagation`, whose mode is a count).
    """
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_label_propagation,
    )

    if schedule not in ("replicated", "ring"):
        raise ValueError(
            f"unknown schedule {schedule!r}; expected 'replicated' or "
            "'ring' (the planner's distributed schedules — a 'single' "
            "plan should use recursive_lpa_outliers)"
        )
    comm = np.asarray(communities)
    src = np.asarray(graph.src)
    dst = np.asarray(graph.dst)
    keep = comm[src] == comm[dst]
    intra = build_graph(
        src[keep], dst[keep], num_vertices=graph.num_vertices,
        symmetric=graph.symmetric, to_device=False,
    )
    sg = shard_graph_arrays(partition_graph(intra, mesh=mesh), mesh)
    if schedule == "ring":
        from graphmine_tpu.parallel.ring import ring_label_propagation

        sub = ring_label_propagation(sg, mesh, max_iter=max_iter)
    else:
        sub = sharded_label_propagation(sg, mesh, max_iter=max_iter)
    return _decile_report(np.asarray(sub), comm, decile)


def _decile_report(sub: np.ndarray, comm: np.ndarray, decile: float) -> OutlierReport:
    """Host-side bottom-decile thresholding over the sub-community size
    table (``Graphframes.py:135-136`` semantics); shared by the
    single-device masked pass and the scale-out sharded pass.

    Vectorized grouped decile (r5): the original per-parent Python loop
    was O(parents x sub-communities) — the sharded bench tier measured it
    at 220-300 s on the chip-tier graph (~10^5 parent communities), while
    the device LPA it post-processes takes ~3 s. One (parent, size)
    lexsort + per-group threshold gather does the same decile in
    O(S log S); semantics are unchanged (the threshold is the cut-th
    smallest size within the parent, ties all flagged — pinned by the
    outlier tests).
    """
    sub_ids, inverse, sizes = np.unique(sub, return_inverse=True, return_counts=True)
    parents = comm[sub_ids]  # sub-community label = a member vertex id

    outlier_sub = np.zeros(len(sub_ids), dtype=bool)
    thresholds: dict[int, int] = {}
    if len(sub_ids):
        order = np.lexsort((sizes, parents))  # group by parent, sizes asc
        p_sorted = parents[order]
        s_sorted = sizes[order]
        uniq_p, starts, counts = np.unique(
            p_sorted, return_index=True, return_counts=True
        )
        cuts = (counts * decile).astype(np.int64)
        has_decile = cuts > 0  # fewer than 1/decile sub-communities: skip
        thr = s_sorted[starts[has_decile] + cuts[has_decile] - 1]
        thresholds = dict(zip(
            uniq_p[has_decile].astype(int).tolist(),
            thr.astype(int).tolist(),
        ))
        # per-sorted-row parent group id -> its threshold (or -1: nothing
        # can be <= -1, so no-decile groups flag nothing)
        thr_full = np.full(len(uniq_p), -1, dtype=np.int64)
        thr_full[has_decile] = thr
        group_of_row = np.repeat(np.arange(len(uniq_p)), counts)
        out_sorted = s_sorted <= thr_full[group_of_row]
        outlier_sub[order] = out_sorted

    return OutlierReport(
        sub_labels=sub.astype(np.int32),
        outlier_vertices=outlier_sub[inverse],
        sub_sizes=sizes.astype(np.int32),
        sub_parents=parents.astype(np.int32),
        thresholds=thresholds,
    )
