"""Degree views of a graph.

The GraphFrames surface exposes ``degrees`` / ``inDegrees`` /
``outDegrees`` DataFrames on the object built at ``Graphframes.py:78``;
here they are dense int32 vectors (duplicate edges counted with
multiplicity, matching the reference's kept duplicates,
``Graphframes.py:70-74``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from graphmine_tpu.graph.container import Graph


def out_degrees(graph: Graph) -> jax.Array:
    return jax.ops.segment_sum(
        jnp.ones_like(graph.src), graph.src, num_segments=graph.num_vertices
    )


def out_weights(graph: Graph) -> jax.Array:
    """Out-edge weight sums (weighted out-degree), float32.

    The ``out_degrees`` analog for weighted graphs — what the distributed
    PageRank schedules take for weighted rank splitting. On an unweighted
    graph this is just ``out_degrees`` as float. Note: on a symmetric
    graph messages flow both directions, so the sum is the *undirected*
    strength; pass a directed graph for true out-strengths.
    """
    if graph.msg_weight is None:
        return out_degrees(graph).astype(jnp.float32)
    return jax.ops.segment_sum(
        graph.msg_weight, graph.msg_send, num_segments=graph.num_vertices
    )


def in_degrees(graph: Graph) -> jax.Array:
    return jax.ops.segment_sum(
        jnp.ones_like(graph.dst), graph.dst, num_segments=graph.num_vertices
    )


def degrees(graph: Graph) -> jax.Array:
    """Undirected degree (in + out; self-loops therefore count twice)."""
    return out_degrees(graph) + in_degrees(graph)
