"""Degree views of a graph.

The GraphFrames surface exposes ``degrees`` / ``inDegrees`` /
``outDegrees`` DataFrames on the object built at ``Graphframes.py:78``;
here they are dense int32 vectors (duplicate edges counted with
multiplicity, matching the reference's kept duplicates,
``Graphframes.py:70-74``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from graphmine_tpu.graph.container import Graph


def out_degrees(graph: Graph) -> jax.Array:
    return jax.ops.segment_sum(
        jnp.ones_like(graph.src), graph.src, num_segments=graph.num_vertices
    )


def in_degrees(graph: Graph) -> jax.Array:
    return jax.ops.segment_sum(
        jnp.ones_like(graph.dst), graph.dst, num_segments=graph.num_vertices
    )


def degrees(graph: Graph) -> jax.Array:
    """Undirected degree (in + out; self-loops therefore count twice)."""
    return out_degrees(graph) + in_degrees(graph)
