"""HITS and closeness centrality — engine-surface extensions.

The reference never computes centrality beyond degree, but its GraphFrame
object is the one-stop analysis surface (``Graphframes.py:78``); these round
out that surface for NetworkX migrants (the reference's ``Overview:8`` names
NetworkX as a tool considered). TPU design: both are dense-vector
power/frontier iterations on the same gather + ``segment_sum`` machinery as
PageRank/BFS — no new memory shapes, jit-compiled, static shapes.

Semantics match NetworkX (``nx.hits``, ``nx.closeness_centrality``) and are
oracle-tested against it.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.ops.paths import shortest_paths


@partial(jax.jit, static_argnames=("max_iter",))
def hits(
    graph: Graph, max_iter: int = 100, tol: float = 1e-8
) -> tuple[jax.Array, jax.Array]:
    """HITS hub and authority scores ``([V], [V])``, NetworkX semantics.

    One iteration: ``a = Aᵀh`` (authorities gather hub mass along in-edges),
    ``h = Aa`` (hubs gather authority mass along out-edges), each normalized
    by its max; converges when the L1 hub delta drops below ``tol`` (checked
    in the ``while_loop`` — no host sync), bounded by ``max_iter``. Final
    vectors are sum-normalized (``nx.hits(normalized=True)``).

    Use a ``symmetric=False`` graph (directed edges); on a symmetric graph
    hubs equal authorities (eigenvector centrality up to normalization).
    """
    v = graph.num_vertices
    src, dst = graph.src, graph.dst
    h0 = jnp.full(v, 1.0 / v, dtype=jnp.float32)

    def step(state):
        h, _, err, i = state
        a = jax.ops.segment_sum(h[src], dst, num_segments=v)
        h_new = jax.ops.segment_sum(a[dst], src, num_segments=v)
        h_new = h_new / jnp.maximum(h_new.max(), 1e-30)
        a = a / jnp.maximum(a.max(), 1e-30)
        err = jnp.abs(h_new - h).sum()
        return h_new, a, err, i + 1

    def cond(state):
        _, _, err, i = state
        return (err >= tol) & (i < max_iter)

    h, a, _, _ = lax.while_loop(
        cond, step, (h0, jnp.zeros(v, jnp.float32), jnp.inf, jnp.array(0))
    )
    h = h / jnp.maximum(h.sum(), 1e-30)
    a = a / jnp.maximum(a.sum(), 1e-30)
    return h, a


@partial(jax.jit, static_argnames=("max_iter",))
def eigenvector_centrality(
    graph: Graph, max_iter: int = 100, tol: float = 1e-6
) -> jax.Array:
    """Eigenvector centrality ``[V]`` — power iteration on ``Aᵀx`` (each
    vertex accumulates its in-neighbors' scores), L2-normalized with an
    L1 convergence test scaled by V, matching ``nx.eigenvector_centrality``.
    Use a symmetric graph for the undirected notion."""
    v = graph.num_vertices
    src, dst = (
        (graph.msg_send, graph.msg_recv) if graph.symmetric
        else (graph.src, graph.dst)
    )
    x0 = jnp.full(v, 1.0 / v, jnp.float32)

    def step(state):
        x, _, it = state
        nxt = x + jax.ops.segment_sum(x[src], dst, num_segments=v)
        norm = jnp.sqrt(jnp.sum(nxt * nxt))
        nxt = nxt / jnp.maximum(norm, 1e-30)
        err = jnp.abs(nxt - x).sum()
        return nxt, err, it + 1

    def cond(state):
        _, err, it = state
        return (err >= v * tol) & (it < max_iter)

    x, _, _ = lax.while_loop(cond, step, (x0, jnp.inf, jnp.array(0)))
    return x


@partial(jax.jit, static_argnames=("max_iter", "normalized"))
def katz_centrality(
    graph: Graph,
    alpha: float = 0.1,
    beta: float = 1.0,
    max_iter: int = 1000,
    tol: float = 1e-6,
    normalized: bool = True,
) -> jax.Array:
    """Katz centrality ``[V]``: fixpoint of ``x = alpha·Aᵀx + beta``
    (NetworkX semantics, including the final L2 normalization). ``alpha``
    must be below ``1/λ_max`` to converge."""
    v = graph.num_vertices
    src, dst = (
        (graph.msg_send, graph.msg_recv) if graph.symmetric
        else (graph.src, graph.dst)
    )
    x0 = jnp.zeros(v, jnp.float32)

    def step(state):
        x, _, it = state
        nxt = alpha * jax.ops.segment_sum(x[src], dst, num_segments=v) + beta
        err = jnp.abs(nxt - x).sum()
        return nxt, err, it + 1

    def cond(state):
        _, err, it = state
        return (err >= v * tol) & (it < max_iter)

    x, _, _ = lax.while_loop(cond, step, (x0, jnp.inf, jnp.array(0)))
    if normalized:
        x = x / jnp.maximum(jnp.sqrt(jnp.sum(x * x)), 1e-30)
    return x


def betweenness_centrality(
    graph: Graph,
    sources=None,
    normalized: bool = True,
    directed: bool | None = None,
    source_batch: int = 8,
    mesh=None,
) -> jax.Array:
    """Betweenness centrality ``[V]`` (float32) via Brandes' algorithm as
    data-parallel level sweeps — no priority queues or per-node stacks:
    one BFS forward pass accumulates shortest-path counts per level, one
    backward pass accumulates pair dependencies per level, both as
    gather + ``segment_sum`` supersteps batched ``source_batch`` sources
    at a time (the same lane-block recipe as ``shortest_paths``).

    ``sources=None`` runs every vertex (exact, NetworkX-oracle tested);
    an id array runs the standard sampled estimator scaled by ``V/k``.
    Parallel edges count as distinct shortest paths (multigraph
    semantics, the engine's multiplicity convention — dedupe the edge
    list first for simple-graph parity).

    ``mesh``: optional ``jax.sharding.Mesh`` — sources are sharded across
    the mesh (graph replicated per device) and partial accumulators meet
    in one ``psum``; equivalent to the single-device result up to float32
    summation order (per-device partials reduce in a different order).
    ``directed`` defaults to ``not graph.symmetric``; undirected scores
    are halved (each unordered pair is counted from both endpoints) and
    ``normalized`` applies NetworkX's ``1/((V-1)(V-2))`` (×2 undirected).
    """
    v = graph.num_vertices
    if directed is None:
        directed = not graph.symmetric
    if directed:
        send, recv = graph.src, graph.dst
    else:
        send = jnp.concatenate([graph.src, graph.dst])
        recv = jnp.concatenate([graph.dst, graph.src])
    if sources is None:
        src_ids = jnp.arange(v, dtype=jnp.int32)
    else:
        src_ids = jnp.atleast_1d(jnp.asarray(sources, jnp.int32))
    k = int(src_ids.shape[0])
    b = max(1, min(source_batch, k))
    n_dev = 1 if mesh is None else int(np.prod(mesh.devices.shape))
    pad = (-k) % (b * n_dev)  # every device gets whole tiles
    tiles = jnp.concatenate([src_ids, jnp.zeros(pad, jnp.int32)]).reshape(-1, b)
    # padded lanes recompute source 0; mask their contribution out
    lane_valid = (jnp.arange(k + pad) < k).reshape(-1, b)

    def tile_scan(tiles_, valid_):
        def tile(acc, args):
            srcs, valid = args
            # scan with a running [V] sum — a stacked [tiles, V] result
            # would be O(V^2 / b) for exact betweenness
            return acc + _brandes_tile(srcs, valid, send=send, recv=recv, v=v), None

        acc, _ = lax.scan(tile, jnp.zeros(v, jnp.float32), (tiles_, valid_))
        return acc

    if mesh is None:
        bc = tile_scan(tiles, lane_valid)
    else:
        # Source-parallel: the graph is replicated, the source tiles are
        # sharded across every mesh axis, partial accumulators meet in one
        # psum over ICI — embarrassingly parallel Brandes.
        from jax.sharding import PartitionSpec as P

        from graphmine_tpu._jax_compat import shard_map

        axes = tuple(mesh.axis_names)

        def per_device(tiles_, valid_):
            return jax.lax.psum(tile_scan(tiles_, valid_), axis_name=axes)

        bc = shard_map(
            per_device, mesh=mesh,
            in_specs=(P(axes), P(axes)), out_specs=P(),
            # while_loop carries mix sharded-derived and replicated values;
            # varying-axis checking can't track that through the fixpoint
            check_vma=False,
        )(tiles, lane_valid)
    if not directed:
        bc = bc / 2.0
    if sources is not None and k and k < v:
        bc = bc * (v / k)  # sampled-source estimator rescale
    if normalized and v > 2:
        scale = 1.0 / ((v - 1) * (v - 2))
        if not directed:
            scale *= 2.0
        bc = bc * scale
    return bc


def _brandes_tile(srcs, valid, *, send, recv, v: int) -> jax.Array:
    """Dependency accumulation for one lane block of sources: ``[V]``.

    Both segment sums flatten the lane axis into the segment ids
    (``vertex * b + lane``) instead of segment-summing a ``[M, b]``
    operand over its leading axis — the 2-D form chained across
    supersteps miscompiles to zeros on the TPU backend this was built
    against (single steps are fine; verified minimal repro), and the
    flat form is equivalent.
    """
    b = srcs.shape[0]
    lanes = jnp.arange(b, dtype=jnp.int32)
    unreach = jnp.int32(v + 1)
    seg_recv = (recv[:, None] * b + lanes[None, :]).ravel()
    seg_send = (send[:, None] * b + lanes[None, :]).ravel()
    dist = jnp.full((v, b), unreach, jnp.int32)
    dist = dist.at[srcs, lanes].min(0)
    sigma = jnp.zeros((v, b), jnp.float32).at[srcs, lanes].add(1.0)

    def fwd(state):
        dist, sigma, it, _ = state
        on_level = dist[send] == it
        msg = jnp.where(on_level, sigma[send], 0.0)
        contrib = jax.ops.segment_sum(
            msg.ravel(), seg_recv, num_segments=v * b
        ).reshape(v, b)
        newly = (dist == unreach) & (contrib > 0)
        dist = jnp.where(newly, it + 1, dist)
        sigma = jnp.where(newly, contrib, sigma)
        return dist, sigma, it + 1, jnp.sum(newly, dtype=jnp.int32)

    def fwd_cond(state):
        _, _, it, progressed = state
        return (progressed > 0) & (it < v)

    dist, sigma, depth, _ = lax.while_loop(
        fwd_cond, fwd, (dist, sigma, jnp.int32(0), jnp.int32(1))
    )

    def bwd(state):
        delta, it = state
        # edges u->w on shortest paths with dist[w] == it+1 push
        # sigma[u]/sigma[w] * (1 + delta[w]) back to u at level it
        on_sp = (dist[send] == it) & (dist[recv] == it + 1)
        ratio = sigma[send] / jnp.maximum(sigma[recv], 1.0)
        msg = jnp.where(on_sp, ratio * (1.0 + delta[recv]), 0.0)
        back = jax.ops.segment_sum(
            msg.ravel(), seg_send, num_segments=v * b
        ).reshape(v, b)
        delta = jnp.where(dist == it, back, delta)
        return delta, it - 1

    def bwd_cond(state):
        _, it = state
        return it >= 0

    delta, _ = lax.while_loop(
        bwd_cond, bwd, (jnp.zeros((v, b), jnp.float32), depth - 1)
    )
    # sources don't count their own dependency; padded lanes contribute 0
    delta = delta.at[srcs, lanes].set(0.0)
    return jnp.where(valid[None, :], delta, 0.0).sum(axis=1)


def closeness_centrality(
    graph: Graph, vertices=None, wf_improved: bool = True
) -> jax.Array:
    """Closeness centrality for ``vertices`` (default: all), ``[L]`` float32.

    NetworkX semantics: for vertex ``u`` with ``r`` vertices able to reach
    it and total incoming distance ``s``: ``(r-1)/s``, scaled by
    ``(r-1)/(V-1)`` when ``wf_improved`` (the Wasserman–Faust correction
    NetworkX applies by default). Isolated vertices score 0. A symmetric
    graph gives the undirected notion; a ``symmetric=False`` graph gives
    directed closeness over incoming paths — exactly
    ``nx.closeness_centrality(DiGraph)``.

    Cost: landmarks run through batched multi-source BFS tiles
    (``shortest_paths``), ``[V, L]`` result memory. Exact closeness for
    every vertex means ``L = V``; on large graphs pass a landmark sample
    instead (the standard approximation) and keep ``L`` bounded.
    """
    v = graph.num_vertices
    idx = (
        jnp.arange(v, dtype=jnp.int32)
        if vertices is None
        else jnp.atleast_1d(jnp.asarray(vertices, jnp.int32))
    )
    # [V, L]: symmetric graphs walk the undirected message CSR; directed
    # graphs follow edge direction toward the target (incoming distance)
    direction = "both" if graph.symmetric else "out"
    dist = shortest_paths(graph, idx, direction=direction)
    unreach = jnp.iinfo(jnp.int32).max
    reach = dist < unreach
    total = jnp.where(reach, dist, 0).astype(jnp.float32).sum(axis=0)
    r = reach.sum(axis=0).astype(jnp.float32)  # includes the vertex itself
    c = jnp.where(total > 0, (r - 1.0) / jnp.maximum(total, 1.0), 0.0)
    if wf_improved:
        c = c * (r - 1.0) / max(v - 1, 1)
    return c
