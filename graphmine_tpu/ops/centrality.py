"""HITS and closeness centrality — engine-surface extensions.

The reference never computes centrality beyond degree, but its GraphFrame
object is the one-stop analysis surface (``Graphframes.py:78``); these round
out that surface for NetworkX migrants (the reference's ``Overview:8`` names
NetworkX as a tool considered). TPU design: both are dense-vector
power/frontier iterations on the same gather + ``segment_sum`` machinery as
PageRank/BFS — no new memory shapes, jit-compiled, static shapes.

Semantics match NetworkX (``nx.hits``, ``nx.closeness_centrality``) and are
oracle-tested against it.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.ops.paths import shortest_paths


@partial(jax.jit, static_argnames=("max_iter",))
def hits(
    graph: Graph, max_iter: int = 100, tol: float = 1e-8
) -> tuple[jax.Array, jax.Array]:
    """HITS hub and authority scores ``([V], [V])``, NetworkX semantics.

    One iteration: ``a = Aᵀh`` (authorities gather hub mass along in-edges),
    ``h = Aa`` (hubs gather authority mass along out-edges), each normalized
    by its max; converges when the L1 hub delta drops below ``tol`` (checked
    in the ``while_loop`` — no host sync), bounded by ``max_iter``. Final
    vectors are sum-normalized (``nx.hits(normalized=True)``).

    Use a ``symmetric=False`` graph (directed edges); on a symmetric graph
    hubs equal authorities (eigenvector centrality up to normalization).
    """
    v = graph.num_vertices
    src, dst = graph.src, graph.dst
    h0 = jnp.full(v, 1.0 / v, dtype=jnp.float32)

    def step(state):
        h, _, err, i = state
        a = jax.ops.segment_sum(h[src], dst, num_segments=v)
        h_new = jax.ops.segment_sum(a[dst], src, num_segments=v)
        h_new = h_new / jnp.maximum(h_new.max(), 1e-30)
        a = a / jnp.maximum(a.max(), 1e-30)
        err = jnp.abs(h_new - h).sum()
        return h_new, a, err, i + 1

    def cond(state):
        _, _, err, i = state
        return (err >= tol) & (i < max_iter)

    h, a, _, _ = lax.while_loop(
        cond, step, (h0, jnp.zeros(v, jnp.float32), jnp.inf, jnp.array(0))
    )
    h = h / jnp.maximum(h.sum(), 1e-30)
    a = a / jnp.maximum(a.sum(), 1e-30)
    return h, a


def closeness_centrality(
    graph: Graph, vertices=None, wf_improved: bool = True
) -> jax.Array:
    """Closeness centrality for ``vertices`` (default: all), ``[L]`` float32.

    NetworkX semantics: for vertex ``u`` with ``r`` vertices able to reach
    it and total incoming distance ``s``: ``(r-1)/s``, scaled by
    ``(r-1)/(V-1)`` when ``wf_improved`` (the Wasserman–Faust correction
    NetworkX applies by default). Isolated vertices score 0. A symmetric
    graph gives the undirected notion; a ``symmetric=False`` graph gives
    directed closeness over incoming paths — exactly
    ``nx.closeness_centrality(DiGraph)``.

    Cost: landmarks run through batched multi-source BFS tiles
    (``shortest_paths``), ``[V, L]`` result memory. Exact closeness for
    every vertex means ``L = V``; on large graphs pass a landmark sample
    instead (the standard approximation) and keep ``L`` bounded.
    """
    v = graph.num_vertices
    idx = (
        jnp.arange(v, dtype=jnp.int32)
        if vertices is None
        else jnp.atleast_1d(jnp.asarray(vertices, jnp.int32))
    )
    # [V, L]: symmetric graphs walk the undirected message CSR; directed
    # graphs follow edge direction toward the target (incoming distance)
    direction = "both" if graph.symmetric else "out"
    dist = shortest_paths(graph, idx, direction=direction)
    unreach = jnp.iinfo(jnp.int32).max
    reach = dist < unreach
    total = jnp.where(reach, dist, 0).astype(jnp.float32).sum(axis=0)
    r = reach.sum(axis=0).astype(jnp.float32)  # includes the vertex itself
    c = jnp.where(total > 0, (r - 1.0) / jnp.maximum(total, 1.0), 0.0)
    if wf_improved:
        c = c * (r - 1.0) / max(v - 1, 1)
    return c
