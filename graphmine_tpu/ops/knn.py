"""Brute-force kNN: tiled all-pairs distances + top-k.

The reference has no kNN; BASELINE.json specifies it as the basis of the
LOF scorer ("batched all-pairs distance + top-k Pallas kernel"). This
module is the XLA implementation — row-tiled so the [N, N] distance
matrix never materializes, MXU-friendly (the inner op is a [T, F] x
[F, N] matmul). The fused Pallas kernel lives in
:mod:`graphmine_tpu.pallas_kernels.knn_pallas`; real-v5e timing (the
:func:`knn` auto-policy table) showed XLA's dot+top_k *faster* for
k > 8, so this path is the production one at the deployed k (LOF runs
k=100-128) and the oracle the Pallas kernel is tested against; Pallas
serves the small-k regime.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def knn(points: jax.Array, k: int, row_tile: int = 1024, impl: str = "auto"):
    """k nearest neighbors under squared Euclidean distance, self excluded.

    Returns ``(dists, idx)`` with shapes ``[N, k]``, ascending by distance.

    ``impl``: ``"auto"`` picks by measurement (below); ``"xla"`` /
    ``"pallas"`` force a path.

    Auto-policy provenance (VERDICT r4 item 5 — the selection must cite a
    measurement, not an assumption): timed on a real TPU v5e, 65536x8
    f32 points, best-of-3 steady-state (round 5, 2026-07-31; the same
    sweep rides the lof bench tier's ``knn_impl_timing`` detail):

        k=8    pallas 0.260 s   xla 0.300 s   pallas 1.15x faster
        k=16   pallas 0.439 s   xla 0.416 s   pallas 0.95x (xla wins)
        k=32   pallas 0.727 s   xla 0.614 s   pallas 0.85x
        k=64   pallas 1.318 s   xla 1.075 s   pallas 0.82x
        k=128  pallas 2.484 s   xla 2.047 s   pallas 0.82x

    The fused kernel's running top-k fold is k rounds of min-extraction
    (VPU) per distance block — linear in k — while XLA's ``lax.top_k``
    amortizes better, so the Pallas win holds only at small k. Hence:
    Pallas on TPU for k <= 8, XLA otherwise (flipped from the r1-r4
    ``k <= 128`` assumption the r4 verdict called out as unmeasured).
    """
    if impl == "auto":
        impl = "pallas" if _on_tpu() and k <= 8 else "xla"
    if impl == "pallas":
        from graphmine_tpu.pallas_kernels.knn_pallas import knn_pallas

        return knn_pallas(points, k)
    return _knn_xla(points, k, row_tile)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


def _tiled_knn(queries, refs, k, row_tile, *, exclude_self=False, ref_mask=None,
               query_ids=None, ref_ids=None):
    """Shared row-tiled distance + top-k core.

    ``d2[i, j] = |q_i|^2 - 2 q_i . r_j + |r_j|^2`` — the matmul is the MXU
    op; tiles keep the [N, M] distance matrix from materializing.
    ``exclude_self`` masks the diagonal (queries are the refs);
    ``ref_mask`` (bool [M]) hides invalid reference slots;
    ``query_ids``/``ref_ids`` (int32 [N]/[M], given together) exclude
    pairs whose ids match — the ring-sharded path's self-exclusion, where
    query and reference chunks carry global row ids.
    """
    n, _ = queries.shape
    m = refs.shape[0]
    if n == 0:
        dt = jnp.promote_types(queries.dtype, refs.dtype)
        return jnp.zeros((0, k), dt), jnp.zeros((0, k), jnp.int32)
    ref_sq = jnp.sum(refs * refs, axis=1)
    q_sq = jnp.sum(queries * queries, axis=1)
    n_pad = -(-n // row_tile) * row_tile
    pad = n_pad - n
    rows = jnp.pad(queries, ((0, pad), (0, 0))).reshape(n_pad // row_tile, row_tile, -1)
    row_sq = jnp.pad(q_sq, (0, pad)).reshape(n_pad // row_tile, row_tile)
    row_idx = jnp.arange(n_pad, dtype=jnp.int32).reshape(n_pad // row_tile, row_tile)
    if query_ids is not None:
        row_idx = jnp.pad(
            query_ids.astype(jnp.int32), (0, pad), constant_values=-1
        ).reshape(n_pad // row_tile, row_tile)
    invalid = None if ref_mask is None else ~ref_mask

    def tile_knn(args):
        tile, tile_sq, tile_ids = args
        # precision=HIGHEST: the TPU MXU's default one-pass bf16 rounding
        # of f32 operands puts ~1e-2-relative error on d2 — the r4
        # cross-backend audit measured 0.084 abs TPU-vs-CPU divergence on
        # these distances before this was forced to true f32 (the
        # multi-pass cost is invisible at F ~ 8-64 feature dims).
        cross = lax.dot_general(
            tile, refs,
            dimension_numbers=(((1,), (1,)), ((), ())),
            precision=lax.Precision.HIGHEST,
        )
        d2 = tile_sq[:, None] - 2.0 * cross + ref_sq[None, :]
        d2 = jnp.maximum(d2, 0.0)
        if exclude_self:
            self_mask = tile_ids[:, None] == jnp.arange(m, dtype=jnp.int32)[None, :]
            d2 = jnp.where(self_mask, jnp.inf, d2)
        if query_ids is not None:
            d2 = jnp.where(tile_ids[:, None] == ref_ids[None, :], jnp.inf, d2)
        if invalid is not None:
            d2 = jnp.where(invalid[None, :], jnp.inf, d2)
        neg_top, idx = lax.top_k(-d2, k)
        return -neg_top, idx

    dists, idx = lax.map(tile_knn, (rows, row_sq, row_idx))
    return dists.reshape(n_pad, k)[:n], idx.reshape(n_pad, k)[:n]


@partial(jax.jit, static_argnames=("k", "row_tile"))
def _knn_xla(points: jax.Array, k: int, row_tile: int = 1024):
    n, _ = points.shape
    if k >= n:
        raise ValueError(f"k={k} must be < number of points {n}")
    return _tiled_knn(points, points, k, row_tile, exclude_self=True)


@partial(jax.jit, static_argnames=("k", "row_tile"))
def cross_knn(
    queries: jax.Array,
    refs: jax.Array,
    k: int,
    ref_mask: jax.Array | None = None,
    row_tile: int = 1024,
):
    """k nearest *reference* points for each query (no self-exclusion).

    The cross-set primitive of the streaming LOF scorer: queries arrive in
    chunks, references are a fixed-capacity window. ``ref_mask`` (bool
    ``[M]``) marks valid window slots — invalid slots never match, so a
    partially filled window keeps a static shape (no recompiles as the
    stream warms up). Returns ``(d2, idx)``, shapes ``[N, k]``, ascending.
    """
    m = refs.shape[0]
    if k > m:
        raise ValueError(f"k={k} must be <= number of references {m}")
    return _tiled_knn(queries, refs, k, row_tile, ref_mask=ref_mask)
