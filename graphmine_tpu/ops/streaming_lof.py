"""Streaming LOF — outlier scores for point streams too large for all-pairs.

The BASELINE.json config ladder ends at "Twitter-2010 (41M/1.4B, streaming
LOF on v5p-64)": at that scale the O(N^2) all-pairs pass of
:mod:`graphmine_tpu.ops.lof` is off the table. The streaming design scores
each arriving chunk against a fixed-capacity reference *window*:

- **fit**: kNN of window against itself → per-reference k-distance and
  local reachability density (lrd), exactly batch LOF's model state;
- **score**: chunk-vs-window cross kNN (one MXU matmul per row tile),
  reachability against the window's k-distances, LOF(q) = mean lrd of
  q's reference neighbors / lrd(q) — the classic reference-model LOF
  (sklearn's ``novelty=True`` scoring), validated against that oracle;
- **slide**: scored chunks enter the window ring-buffer style, evicting
  the oldest points; re-fit happens on the padded window.

TPU-first details: the window lives in a fixed ``[capacity, F]`` buffer
with a validity mask, so every fit/score step compiles once and reruns for
the whole stream — no shape churn while the window fills (SURVEY §7 hard
part 4: static shapes over dynamic ones).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from graphmine_tpu.ops.knn import cross_knn


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class LOFModel:
    """Fitted reference-window state: points + mask + k-distance + lrd."""

    refs: jax.Array       # [M, F] padded reference points
    mask: jax.Array       # bool [M] — valid slots
    kdist: jax.Array      # [M] distance to k-th neighbor (within window)
    lrd: jax.Array        # [M] local reachability density
    k: int = dataclasses.field(metadata=dict(static=True), default=20)


@partial(jax.jit, static_argnames=("k", "row_tile"))
def fit_lof(refs: jax.Array, mask: jax.Array | None = None, k: int = 20,
            row_tile: int = 1024) -> LOFModel:
    """Fit the LOF reference model on a (possibly padded) point set.

    ``mask`` marks valid rows; invalid rows get zeroed model state and never
    act as neighbors. Needs at least ``k + 1`` valid points.
    """
    m = refs.shape[0]
    if mask is None:
        mask = jnp.ones((m,), bool)
    # self-exclusion: ask for k+1 within the window and drop column 0
    # (the point itself at distance 0; under duplicates any zero-distance
    # column is an equally valid self representative).
    d2, idx = cross_knn(refs, refs, k=k + 1, ref_mask=mask, row_tile=row_tile)
    d2, idx = d2[:, 1:], idx[:, 1:]
    dists = jnp.sqrt(jnp.maximum(d2, 0.0))
    # duplicate guard, same rule as batch lof_scores: floor reach distances
    # at a fraction of the mean positive kNN distance
    pos = (dists > 0) & mask[:, None]
    eps = 1e-3 * jnp.where(pos, dists, 0.0).sum() / jnp.maximum(pos.sum(), 1)
    kdist = dists[:, -1]
    reach = jnp.maximum(jnp.maximum(kdist[idx], dists), eps)
    lrd = k / jnp.maximum(reach.sum(axis=1), 1e-12)
    zero = jnp.zeros_like(kdist)
    return LOFModel(
        refs=refs, mask=mask,
        kdist=jnp.where(mask, kdist, zero),
        lrd=jnp.where(mask, lrd, zero),
        k=k,
    )


@partial(jax.jit, static_argnames=("k",))
def _model_state_from_knn(d2: jax.Array, idx: jax.Array, k: int):
    """k-distance + lrd from a self-excluding kNN result (``[M, k]``) —
    the :func:`fit_lof` formula factored out so the IVF re-fit path
    (``StreamingLOF(impl="ivf")``) shares the duplicate-floor eps and
    reach semantics with the exact fit bit-for-bit."""
    dists = jnp.sqrt(jnp.maximum(d2, 0.0))
    pos = (dists > 0) & jnp.isfinite(dists)
    eps = 1e-3 * jnp.where(pos, dists, 0.0).sum() / jnp.maximum(pos.sum(), 1)
    kdist = dists[:, -1]
    reach = jnp.maximum(jnp.maximum(kdist[idx], dists), eps)
    lrd = k / jnp.maximum(reach.sum(axis=1), 1e-12)
    return kdist, lrd


@partial(jax.jit, static_argnames=("row_tile",))
def score_lof(model: LOFModel, queries: jax.Array, row_tile: int = 1024) -> jax.Array:
    """LOF score per query against the fitted window (higher = outlier)."""
    d2, idx = cross_knn(
        queries, model.refs, k=model.k, ref_mask=model.mask, row_tile=row_tile
    )
    dists = jnp.sqrt(jnp.maximum(d2, 0.0))
    pos = dists > 0
    eps = 1e-3 * jnp.where(pos, dists, 0.0).sum() / jnp.maximum(pos.sum(), 1)
    reach = jnp.maximum(jnp.maximum(model.kdist[idx], dists), eps)
    lrd_q = model.k / jnp.maximum(reach.sum(axis=1), 1e-12)
    return jnp.mean(model.lrd[idx], axis=1) / jnp.maximum(lrd_q, 1e-12)


class StreamingLOF:
    """Sliding-window streaming LOF scorer.

    >>> s = StreamingLOF(k=20, capacity=4096)
    >>> for chunk in stream:            # chunks of [n_i, F] points
    ...     scores = s.update(chunk)    # scores, then admits the chunk

    Each chunk is scored against the current window, then written into the
    fixed-capacity ring buffer (evicting the oldest points) and the model is
    re-fit. All device steps have static shapes once the feature dim and
    chunk size are seen, so the stream runs from a handful of compilations.

    ``impl="ivf"`` (r6): the window re-fit — the dominant cost term, a
    ``[capacity, capacity]`` self-kNN every admitted chunk — routes
    through the IVF-flat index (:func:`graphmine_tpu.ops.ann.ivf_knn`)
    with **one reused set of k-means centers**: the window slides by one
    chunk per re-fit, so its cluster structure is stable between fits,
    and re-fits skip the Lloyd iterations entirely (points are only
    re-assigned against the trained centers — one small matmul).
    Chunk-vs-window *scoring* stays exact cross-kNN (it is
    ``[chunk, capacity]``, far off the all-pairs wall). Centers train on
    the first FULL window (earlier re-fits stay exact — centers fit to
    a small early sample would index every later window badly);
    ``ivf_retrain_every=N`` re-trains every N IVF re-fits to track
    drift (0 = train once, the default — the ring buffer's content
    drifts one chunk at a time, and the bench stream tier records the
    reuse win/regression each capture).
    """

    def __init__(self, k: int = 20, capacity: int = 4096,
                 admit_threshold: float | None = None, impl: str = "exact",
                 ivf_retrain_every: int = 0, sink=None, centers=None):
        """``admit_threshold``: if set, points scoring above it are flagged
        but NOT admitted to the window. Without it, persistent outlier
        clusters eventually enter the window and start looking normal —
        sometimes wanted (regime change), sometimes not (contamination).

        ``centers`` (r7): pre-trained float32 ``[C, F]`` k-means centers
        to seed the IVF re-fit path with — a serving-layer scorer
        resuming from a snapshot skips Lloyd entirely (the same
        ``ivf_knn(centers=...)`` reuse the first full window would
        otherwise train; ``ivf_retrain_every`` still refreshes them on
        its cadence). Ignored under ``impl="exact"``."""
        if capacity <= k + 1:
            raise ValueError(f"capacity {capacity} must exceed k+1 = {k + 1}")
        if impl not in ("exact", "ivf"):
            raise ValueError(f"unknown impl {impl!r}; use 'exact' or 'ivf'")
        if ivf_retrain_every < 0:
            raise ValueError("ivf_retrain_every must be >= 0 (0 = once)")
        self.k = k
        self.capacity = capacity
        self.admit_threshold = admit_threshold
        self.impl = impl
        self.ivf_retrain_every = ivf_retrain_every
        self.ivf_retrains = 0  # kmeans trainings performed (reuse metric)
        self._sink = sink
        self._ivf_fits = 0     # re-fits that actually rode the index
        # trained [C, F] centers (impl="ivf"); seeded from `centers` when
        # given so a resumed scorer never re-trains what a prior
        # process/snapshot already paid for
        self._centers = (
            None if centers is None else np.asarray(centers, np.float32)
        )
        self._refs: np.ndarray | None = None  # [capacity, F]
        self._valid = 0        # number of valid slots (grows to capacity)
        self._write = 0        # ring-buffer write head
        self._model: LOFModel | None = None

    @property
    def fitted(self) -> bool:
        return self._model is not None

    def sync(self) -> None:
        """Block until the most recent re-fit has completed on device.

        ``update`` blocks on the chunk's *scores* (host fetch) but
        dispatches the window re-fit asynchronously — its cost is normally
        absorbed by the next chunk's scoring. Call this after the last
        chunk when measuring throughput, so the final fit's device time is
        inside the timed window."""
        if self._model is not None:
            jax.block_until_ready(self._model)

    def update(self, chunk) -> np.ndarray:
        """Score ``chunk`` against the window, then admit it and re-fit.

        Returns ``[n]`` LOF scores. The first chunk bootstraps the window
        (needs at least ``k + 1`` points) and is scored *in-window* with the
        self-excluding batch formula; every later chunk is scored against
        the window as fitted *before* the chunk entered it.
        """
        chunk = np.asarray(chunk, dtype=np.float32)
        if chunk.ndim != 2:
            raise ValueError("chunk must be [n, features]")
        bootstrap = self._model is None
        if bootstrap:
            if len(chunk) < self.k + 1:
                raise ValueError(
                    f"first chunk needs >= k+1 = {self.k + 1} points, got {len(chunk)}"
                )
            from graphmine_tpu.ops.lof import lof_scores

            scores = np.asarray(lof_scores(jnp.asarray(chunk), k=self.k))
        else:
            scores = np.asarray(score_lof(self._model, jnp.asarray(chunk)))
        admit = chunk
        if self.admit_threshold is not None:
            admit = chunk[scores <= self.admit_threshold]
        if bootstrap and len(admit) < self.k + 1:
            # raise before touching window state, so the caller can retry
            # with a bigger/cleaner chunk and bootstrap again
            raise ValueError(
                f"admit_threshold leaves {len(admit)} bootstrap points; "
                f"need >= k+1 = {self.k + 1}"
            )
        if len(admit):
            if self._refs is None:
                self._refs = np.zeros((self.capacity, chunk.shape[1]), np.float32)
            self._admit(admit)
            self._fit()
        return scores

    def _fit(self) -> None:
        if self.impl == "ivf":
            self._fit_ivf()
        else:
            self._model = fit_lof(
                jnp.asarray(self._refs), jnp.asarray(self._mask()), k=self.k
            )

    def _fit_ivf(self) -> None:
        """Window re-fit through the IVF index with reused centers.

        The index is sized for the FULL window (``~sqrt(capacity)``
        clusters) and its centers train on the first FULL window — not
        merely the first one past the index's minimum viable size:
        centers fit to a small early sample (one regime of the stream)
        would index every later full-capacity window badly, degraded
        recall with no announcement. Until the fill, re-fits take the
        exact path — the stream warms up exact, then switches to the
        index once, permanently. The self-kNN result feeds the same
        k-distance/lrd model state as :func:`fit_lof` (ivf_knn excludes
        self by id, exactly like the batch scorer's kNN contract).
        """
        from graphmine_tpu.ops.ann import default_n_clusters, ivf_knn, kmeans

        n_clusters = default_n_clusters(self.capacity)
        valid = self._valid
        pts = self._refs[:valid]
        if valid < self.capacity:
            self._model = fit_lof(
                jnp.asarray(self._refs), jnp.asarray(self._mask()), k=self.k
            )
            return
        retrain = self._centers is None or (
            self.ivf_retrain_every
            and self._ivf_fits % self.ivf_retrain_every == 0
        )
        if retrain:
            self._centers = kmeans(pts, n_clusters, seed=0)
            self.ivf_retrains += 1
        self._ivf_fits += 1
        d2, idx = ivf_knn(
            pts, k=self.k, centers=self._centers, sink=self._sink
        )
        kdist, lrd = _model_state_from_knn(d2, idx, self.k)
        pad = self.capacity - valid
        self._model = LOFModel(
            refs=jnp.asarray(self._refs),
            mask=jnp.asarray(self._mask()),
            kdist=jnp.pad(kdist, (0, pad)),
            lrd=jnp.pad(lrd, (0, pad)),
            k=self.k,
        )

    def _mask(self) -> np.ndarray:
        mask = np.zeros(self.capacity, bool)
        mask[: self._valid] = True
        return mask

    def _admit(self, chunk: np.ndarray) -> None:
        take = chunk[-self.capacity:]  # only the newest fit in the window
        n = len(take)
        end = min(self._write + n, self.capacity)
        first = end - self._write
        self._refs[self._write:end] = take[:first]
        if first < n:
            self._refs[: n - first] = take[first:]
        self._write = (self._write + n) % self.capacity
        self._valid = min(self._valid + n, self.capacity)
