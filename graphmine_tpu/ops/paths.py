"""BFS distances and landmark shortest paths.

Engine-surface parity with GraphFrames' ``bfs`` / ``shortestPaths`` (the
object built at ``Graphframes.py:78`` exposes both; the reference script
never calls them). TPU design: distances are dense int32 vectors; one
superstep relaxes every edge with a gather + ``segment_min`` — Bellman-Ford
over unit weights, which for BFS converges in diameter supersteps inside a
single ``lax.while_loop``.

Direction conventions:
- ``direction="out"``: follow edge direction (src -> dst), GraphFrames'
  default for bfs.
- ``direction="both"``: treat edges as undirected (uses the symmetric
  message CSR).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph

UNREACHABLE = jnp.iinfo(jnp.int32).max


def _edges(graph: Graph, direction: str):
    if direction == "out":
        return graph.src, graph.dst
    if direction == "both":
        if not graph.symmetric:
            raise ValueError(
                "direction='both' needs a graph built with symmetric=True "
                "(the message CSR of an asymmetric graph only carries the "
                "forward direction)"
            )
        return graph.msg_send, graph.msg_recv
    raise ValueError(f"direction must be 'out' or 'both', got {direction!r}")


@partial(jax.jit, static_argnames=("direction", "max_depth"))
def bfs_distances(
    graph: Graph, sources: jax.Array, direction: str = "out", max_depth: int = 0
) -> jax.Array:
    """Hop distance from the nearest of ``sources`` to every vertex.

    Returns int32 ``[V]``; unreachable vertices get ``UNREACHABLE``
    (int32 max). ``sources`` is an int array of vertex ids.
    """
    v = graph.num_vertices
    send, recv = _edges(graph, direction)
    limit = max_depth if max_depth > 0 else v + 1
    dist0 = jnp.full((v,), UNREACHABLE, jnp.int32).at[sources].set(0)

    def step(state):
        dist, _, it = state
        # saturating +1 so UNREACHABLE does not wrap
        msg = jnp.where(dist[send] == UNREACHABLE, UNREACHABLE, dist[send] + 1)
        relaxed = jax.ops.segment_min(msg, recv, num_segments=v)
        new = jnp.minimum(dist, relaxed)
        changed = jnp.sum(new != dist, dtype=jnp.int32)
        return new, changed, it + 1

    def cond(state):
        _, changed, it = state
        return (changed > 0) & (it < limit)

    dist, _, _ = lax.while_loop(cond, step, (dist0, jnp.int32(1), jnp.int32(0)))
    return dist


def shortest_paths(graph: Graph, landmarks, direction: str = "out",
                   landmark_batch: int = 16) -> jax.Array:
    """Distance to each landmark, shape ``[V, L]`` (GraphFrames
    ``shortestPaths`` semantics: distance FROM each vertex TO the landmark
    following edge direction).

    Landmarks run ``landmark_batch`` at a time in one vectorized
    Bellman-Ford (every relaxation handles the whole lane block —
    per-superstep message buffer is ``[M, B]`` int32, so lower ``B`` on
    huge graphs); tiles are processed sequentially via ``lax.map``.
    """
    landmarks = jnp.atleast_1d(jnp.asarray(landmarks, jnp.int32))
    num = int(landmarks.shape[0])
    b = max(1, min(landmark_batch, num))
    # distance v -> landmark along src->dst == distance landmark -> v along
    # reversed edges; for "both" the graph is symmetric already.
    if direction == "out":
        send, recv = graph.dst, graph.src
    else:
        send, recv = _edges(graph, direction)
    pad = (-num) % b
    tiles = jnp.concatenate(
        [landmarks, jnp.zeros(pad, jnp.int32)]
    ).reshape(-1, b)
    per = partial(_bfs_tile, send=send, recv=recv, v=graph.num_vertices)
    out = lax.map(per, tiles)  # [T, V, B]
    return jnp.moveaxis(out, 0, 1).reshape(graph.num_vertices, -1)[:, :num]


def _bfs_tile(sources: jax.Array, *, send, recv, v: int) -> jax.Array:
    """Per-source BFS distances for one lane block: ``[V, B]``."""
    b = sources.shape[0]
    dist0 = jnp.full((v, b), UNREACHABLE, jnp.int32)
    dist0 = dist0.at[sources, jnp.arange(b)].min(0)

    def step(state):
        dist, _, it = state
        msg = jnp.where(dist[send] == UNREACHABLE, UNREACHABLE, dist[send] + 1)
        relaxed = jax.ops.segment_min(msg, recv, num_segments=v)
        new = jnp.minimum(dist, relaxed)
        changed = jnp.sum(new != dist, dtype=jnp.int32)
        return new, changed, it + 1

    def cond(state):
        _, changed, it = state
        return (changed > 0) & (it < v + 1)

    dist, _, _ = lax.while_loop(cond, step, (dist0, jnp.int32(1), jnp.int32(0)))
    return dist


def weighted_shortest_paths(
    graph: Graph,
    sources: jax.Array,
    weights: jax.Array,
    direction: str = "out",
    max_iter: int = 0,
) -> jax.Array:
    """Weighted distance from the nearest of ``sources`` to every vertex —
    Bellman-Ford over the same gather + ``segment_min`` superstep as BFS
    (no priority queue: data-parallel relaxation converges in
    longest-shortest-path-hops iterations, the TPU-friendly trade).

    ``weights``: non-negative float ``[E]`` aligned with ``graph.src`` /
    ``graph.dst`` (for ``direction="both"`` each edge's weight applies in
    both directions). Returns float32 ``[V]`` with ``inf`` for unreachable
    vertices. Negative weights converge too (bounded by ``max_iter``,
    default V), but negative *cycles* are not detected.
    """
    # NaN weights would poison distances AND defeat the convergence check
    # (NaN != NaN keeps `changed` nonzero for the full V iterations) — same
    # host-side guard build_graph(edge_weights=...) applies; skipped only
    # when tracing (weights produced inside a caller's jit).
    if not isinstance(weights, jax.core.Tracer):
        w_host = np.asarray(weights)
        if np.isnan(w_host).any():
            raise ValueError("weights must not contain NaN")
    return _weighted_shortest_paths_jit(graph, sources, weights, direction,
                                        max_iter)


@partial(jax.jit, static_argnames=("direction", "max_iter"))
def _weighted_shortest_paths_jit(
    graph: Graph,
    sources: jax.Array,
    weights: jax.Array,
    direction: str = "out",
    max_iter: int = 0,
) -> jax.Array:
    v = graph.num_vertices
    w = jnp.asarray(weights, jnp.float32)
    if direction == "out":
        send, recv = graph.src, graph.dst
    elif direction == "both":
        # weights align with the edge list, not the sorted message CSR, so
        # build the two directions straight from src/dst
        send = jnp.concatenate([graph.src, graph.dst])
        recv = jnp.concatenate([graph.dst, graph.src])
        w = jnp.concatenate([w, w])
    else:
        raise ValueError(f"direction must be 'out' or 'both', got {direction!r}")
    dist0 = jnp.full((v,), jnp.inf, jnp.float32).at[sources].set(0.0)
    limit = max_iter if max_iter > 0 else v

    def step(state):
        dist, _, it = state
        relaxed = jax.ops.segment_min(dist[send] + w, recv, num_segments=v)
        new = jnp.minimum(dist, relaxed)
        changed = jnp.sum(new != dist, dtype=jnp.int32)
        return new, changed, it + 1

    def cond(state):
        _, changed, it = state
        return (changed > 0) & (it < limit)

    dist, _, _ = lax.while_loop(cond, step, (dist0, jnp.int32(1), jnp.int32(0)))
    return dist


@partial(jax.jit, static_argnames=("direction", "max_depth"))
def bfs_parents(
    graph: Graph, sources: jax.Array, direction: str = "out", max_depth: int = 0
) -> tuple[jax.Array, jax.Array]:
    """BFS distances plus parent pointers for path reconstruction.

    Returns ``(dist, parent)``, both int32 ``[V]``. ``parent[v]`` is the
    smallest-id predecessor of ``v`` on some shortest path from ``sources``
    (-1 for sources and unreachable vertices). Parents are recovered in one
    extra relaxation pass after the distance fixpoint — keeps the hot loop
    identical to :func:`bfs_distances`.
    """
    v = graph.num_vertices
    send, recv = _edges(graph, direction)
    dist = bfs_distances(graph, sources, direction=direction, max_depth=max_depth)
    on_sp = (dist[send] != UNREACHABLE) & (dist[recv] == dist[send] + 1)
    cand = jnp.where(on_sp, send, UNREACHABLE)
    parent = jax.ops.segment_min(cand, recv, num_segments=v)
    parent = jnp.where((parent == UNREACHABLE) | (dist == 0), -1, parent)
    return dist, parent.astype(jnp.int32)


def bfs(
    graph: Graph,
    from_vertices,
    to_vertices,
    direction: str = "out",
    max_path_length: int = 10,
):
    """Shortest paths from a source set to a target set.

    Semantics of ``GraphFrame.bfs(fromExpr, toExpr, maxPathLength)`` (the
    object at ``Graphframes.py:78`` exposes it): breadth-first search stops
    at the first depth where any target is reached; one shortest path per
    target at that depth is returned. Instead of SQL expressions the
    endpoint sets are vertex-id arrays — build them with any host-side
    predicate over vertex properties.

    Returns a list of int32 NumPy paths ``[source, ..., target]``, empty if
    no target is within ``max_path_length`` hops. The distance/parent sweep
    is one compiled kernel; only the final pointer walk (path-length steps)
    runs on host.
    """
    import numpy as np

    from_vertices = jnp.atleast_1d(jnp.asarray(from_vertices, jnp.int32))
    to_np = np.atleast_1d(np.asarray(to_vertices, np.int64))
    dist, parent = bfs_parents(
        graph, from_vertices, direction=direction, max_depth=max_path_length
    )
    dist, parent = np.asarray(dist), np.asarray(parent)
    if to_np.size == 0:
        return []
    tdist = dist[to_np]
    reach = tdist != int(UNREACHABLE)
    if not reach.any():
        return []
    best = int(tdist[reach].min())
    paths = []
    for t in to_np[reach & (tdist == best)]:
        path = [int(t)]
        while parent[path[-1]] >= 0:
            path.append(int(parent[path[-1]]))
        paths.append(np.asarray(path[::-1], dtype=np.int32))
    return paths
