"""Clustering-accuracy metrics: adjusted Rand index and NMI.

The reference names "accuracy" as an evaluation metric (``Overview:9``)
but never computes one — its only quality signal is the community *count*
print (``Graphframes.py:85``). These are the standard external measures
for comparing a detected partition against ground truth (e.g. SBM planted
blocks from :func:`graphmine_tpu.datasets.sbm`) or between two algorithms
(LPA vs Louvain), label-permutation invariant by construction.

Host-side vectorized NumPy (partitions are small [V] int arrays; nothing
here is a device hot path), oracle-tested against scikit-learn.
"""

from __future__ import annotations

import numpy as np


def _contingency(a: np.ndarray, b: np.ndarray):
    """Sparse contingency: ``(cell_counts, cell_rows, cell_cols, row_sums,
    col_sums)`` over compacted label ids — O(nnz) memory, so comparing two
    fine-grained partitions (each with ~V communities) never materializes
    a ka×kb table."""
    a = np.asarray(a).ravel()
    b = np.asarray(b).ravel()
    if a.shape != b.shape:
        raise ValueError(f"label arrays differ in length: {a.shape} vs {b.shape}")
    _, ai = np.unique(a, return_inverse=True)
    _, bi = np.unique(b, return_inverse=True)
    ka, kb = int(ai.max(initial=-1)) + 1, int(bi.max(initial=-1)) + 1
    codes = ai.astype(np.int64) * kb + bi
    uniq, counts = np.unique(codes, return_counts=True)
    row_sums = np.bincount(ai, minlength=ka)
    col_sums = np.bincount(bi, minlength=kb)
    return counts, uniq // kb, uniq % kb, row_sums, col_sums


def adjusted_rand_index(labels_a, labels_b) -> float:
    """Adjusted Rand index in [-0.5, 1]; 1 = identical partitions, ~0 =
    chance agreement. Permutation-invariant (matches
    ``sklearn.metrics.adjusted_rand_score``)."""
    counts, _, _, row_sums, col_sums = _contingency(labels_a, labels_b)
    n = row_sums.sum()
    if n == 0:
        return 1.0

    def comb2(x):
        return x * (x - 1) / 2.0

    sum_ij = comb2(counts.astype(np.float64)).sum()
    sum_a = comb2(row_sums.astype(np.float64)).sum()
    sum_b = comb2(col_sums.astype(np.float64)).sum()
    total = comb2(float(n))
    expected = sum_a * sum_b / total if total else 0.0
    max_index = 0.5 * (sum_a + sum_b)
    if max_index == expected:  # both partitions trivial (all-one-cluster etc.)
        return 1.0
    return float((sum_ij - expected) / (max_index - expected))


def normalized_mutual_info(labels_a, labels_b,
                           average: str = "arithmetic") -> float:
    """NMI in [0, 1]; 1 = identical partitions. ``average``:
    arithmetic (sklearn default) | geometric | min | max."""
    counts, rows, cols, row_sums, col_sums = _contingency(labels_a, labels_b)
    n = float(row_sums.sum())
    if n == 0:
        return 1.0
    pa = row_sums / n
    pb = col_sums / n
    pab = counts / n  # nonzero cells only
    mi = float(np.sum(pab * np.log(pab / (pa[rows] * pb[cols]))))
    ha = -float(np.sum(pa[pa > 0] * np.log(pa[pa > 0])))
    hb = -float(np.sum(pb[pb > 0] * np.log(pb[pb > 0])))
    if ha == 0.0 and hb == 0.0:  # both single-cluster: identical
        return 1.0
    if average == "arithmetic":
        denom = (ha + hb) / 2.0
    elif average == "geometric":
        denom = np.sqrt(ha * hb)
    elif average == "min":
        denom = min(ha, hb)
    elif average == "max":
        denom = max(ha, hb)
    else:
        raise ValueError(f"unknown average {average!r}")
    if denom == 0.0:
        return 0.0
    return float(np.clip(mi / denom, 0.0, 1.0))
