"""Connected components via iterated min-label propagation.

The reference never calls ``connectedComponents`` but BASELINE.json names it
as a required capability (GraphFrames exposes it on the object built at
``Graphframes.py:78``). Semantics: *weakly* connected components of the
directed edge list — messages flow both directions, every vertex ends with
the smallest vertex id reachable from it.

Two device-side accelerations over naive propagation:
- each step takes ``min(own, neighbor mins)`` (monotone, so safe);
- **pointer jumping** (``labels = labels[labels]``) after each propagation
  halves the remaining depth, giving O(log V) convergence on long chains —
  the classic PRAM trick, a good fit for XLA's static-shape while_loop.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from graphmine_tpu.graph.container import Graph


def cc_superstep(labels: jax.Array, graph: Graph) -> jax.Array:
    msg = labels[graph.msg_send]
    neigh_min = jax.ops.segment_min(
        msg, graph.msg_recv, num_segments=graph.num_vertices, indices_are_sorted=True
    )
    new = jnp.minimum(labels, neigh_min)
    # Pointer jumping: follow the current representative one hop.
    return jnp.minimum(new, new[new]).astype(jnp.int32)


@partial(jax.jit, static_argnames=("max_iter", "return_iterations"))
def connected_components(
    graph: Graph, max_iter: int = 0, return_iterations: bool = False
):
    """Weakly-connected component labels ``[V]`` (smallest member vertex id).

    Runs to fixpoint inside a ``lax.while_loop`` (bounded by ``max_iter``
    when nonzero). Returns int32 labels; distinct count on the bundled data
    must equal the measured golden of 34 WCCs (BASELINE.md).

    ``return_iterations`` additionally returns the supersteps-to-fixpoint
    count (int32 scalar, includes the final no-change confirming pass) —
    the ``cc`` bench tier reports it alongside edges/s (VERDICT r4 item 2).
    """
    limit = max_iter if max_iter > 0 else graph.num_vertices + 2

    def cond(state):
        labels, prev_changed, it = state
        return (prev_changed > 0) & (it < limit)

    def body(state):
        labels, _, it = state
        new = cc_superstep(labels, graph)
        changed = jnp.sum(new != labels, dtype=jnp.int32)
        return new, changed, it + 1

    labels0 = jnp.arange(graph.num_vertices, dtype=jnp.int32)
    labels, _, iters = lax.while_loop(
        cond, body, (labels0, jnp.int32(1), jnp.int32(0))
    )
    if return_iterations:
        return labels, iters
    return labels
