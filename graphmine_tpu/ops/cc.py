"""Connected components via iterated min-label propagation.

The reference never calls ``connectedComponents`` but BASELINE.json names it
as a required capability (GraphFrames exposes it on the object built at
``Graphframes.py:78``). Semantics: *weakly* connected components of the
directed edge list — messages flow both directions, every vertex ends with
the smallest vertex id reachable from it.

Two device-side accelerations over naive propagation:
- each step takes ``min(own, neighbor mins)`` (monotone, so safe);
- **pointer jumping** (``labels = labels[labels]``) after each propagation
  halves the remaining depth, giving O(log V) convergence on long chains —
  the classic PRAM trick, a good fit for XLA's static-shape while_loop.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from graphmine_tpu.graph.container import Graph


def cc_superstep(labels: jax.Array, graph: Graph) -> jax.Array:
    msg = labels[graph.msg_send]
    neigh_min = jax.ops.segment_min(
        msg, graph.msg_recv, num_segments=graph.num_vertices, indices_are_sorted=True
    )
    new = jnp.minimum(labels, neigh_min)
    # Pointer jumping: follow the current representative one hop.
    return jnp.minimum(new, new[new]).astype(jnp.int32)


def cc_superstep_bucketed(labels: jax.Array, plan) -> jax.Array:
    """One CC superstep on the fused degree-bucket plan — the min-reduce
    twin of :func:`~graphmine_tpu.ops.bucketed_mode.lpa_superstep_bucketed`
    (r5). Per-step function identical to :func:`cc_superstep` (min over
    own + incoming labels, then pointer jump), so the two paths agree
    bit-for-bit every superstep (tested).

    Why: the r5 cc bench tier measured the segment_min superstep at
    21.9M edges/s/chip — 2.5x off the gather roofline — because the
    sorted-segment reduction over the [M] message array dominates. The
    plan's dense [n_b, w_b] rows turn that into row-wise ``min`` (pure
    VPU) after the same gather the LPA kernel already amortized; padding
    slots gather the int32-max sentinel, which never wins a min. Mega-hub
    rows ride an exact segment_min over their (row-grouped) message
    spans instead of dense rows, mirroring the histogram path's shape
    policy. Requires a FUSED plan (``send_idx`` present, e.g. from
    :func:`~graphmine_tpu.ops.bucketed_mode.build_graph_and_plan`).
    """
    if plan.send_idx is None:
        raise ValueError(
            "cc_superstep_bucketed needs a fused plan (send_idx); build "
            "it with build_graph_and_plan or BucketedModePlan.from_edges"
        )
    sentinel = jnp.iinfo(jnp.int32).max
    lbl_pad = jnp.concatenate(
        [labels.astype(jnp.int32), jnp.full((1,), sentinel, jnp.int32)]
    )
    new = labels.astype(jnp.int32)
    for ids, sidx in zip(plan.vertex_ids, plan.send_idx):
        row_min = jnp.min(lbl_pad[sidx], axis=1)
        new = new.at[ids].min(row_min, unique_indices=True, mode="drop")
    if plan.hist_vertex_ids is not None:
        n_hist = plan.hist_vertex_ids.shape[0]
        rows = plan.hist_row_offset // jnp.int32(plan.num_vertices)
        hub_min = jax.ops.segment_min(
            labels[plan.hist_send].astype(jnp.int32), rows,
            num_segments=n_hist, indices_are_sorted=True,
        )
        new = new.at[plan.hist_vertex_ids].min(
            hub_min, unique_indices=True, mode="drop"
        )
    return jnp.minimum(new, new[new]).astype(jnp.int32)


def connected_components(
    graph: Graph, max_iter: int = 0, return_iterations: bool = False,
    plan="auto", sink=None,
):
    """Weakly-connected component labels ``[V]`` (smallest member vertex id).

    Runs to fixpoint inside a ``lax.while_loop`` (bounded by ``max_iter``
    when nonzero). Returns int32 labels; distinct count on the bundled data
    must equal the measured golden of 34 WCCs (BASELINE.md).

    ``return_iterations`` additionally returns the supersteps-to-fixpoint
    count (int32 scalar, includes the final no-change confirming pass) —
    the ``cc`` bench tier reports it alongside edges/s (VERDICT r4 item 2).

    ``plan``: a fused :class:`BucketedModePlan` (r5) — supersteps run
    :func:`cc_superstep_bucketed` instead of the segment_min path
    (identical labels every step, tested; measured 2.57x on the
    100M-edge cc bench tier, `bench_r5_final_tpu.log`) — or a
    :class:`~graphmine_tpu.ops.blocking.BlockedPlan` (r7): supersteps run
    :func:`~graphmine_tpu.ops.blocking.cc_superstep_blocked`, the
    destination-binned bin-then-reduce layout past the gather roofline.
    The default ``"auto"`` resolves the family through
    :func:`~graphmine_tpu.ops.blocking.select_superstep_family` (the
    single crossover-policy owner; same per-graph plan cache as
    :func:`~graphmine_tpu.ops.lpa.label_propagation`); ``None`` forces
    the segment_min path. Callers that built the graph with
    ``build_graph_and_plan`` / ``build_graph_and_blocked_plan`` can pass
    their plan directly. ``sink``: optional MetricsSink — auto
    resolutions emit ``impl_selected`` + ``plan_build`` provenance
    records (see ``label_propagation``).
    """
    from graphmine_tpu.ops.blocking import BlockedPlan

    if isinstance(plan, str) and plan == "auto":
        from graphmine_tpu.ops.blocking import (
            emit_plan_records,
            select_superstep_family,
        )
        from graphmine_tpu.ops.lpa import _cached_auto_plan

        plan = None
        if not isinstance(graph.msg_ptr, jax.core.Tracer):
            family, reason = select_superstep_family(
                graph.num_vertices, graph.num_messages,
                weighted=graph.msg_weight is not None,
            )
            seconds, cached = 0.0, False
            if family != "sort":
                plan, seconds, cached = _cached_auto_plan(graph, family)
            emit_plan_records(
                sink, "cc_superstep", plan, reason, seconds, cached,
                graph.num_edges, graph.num_messages,
                num_vertices=graph.num_vertices,
            )
    if isinstance(plan, BlockedPlan):
        # Full plan/graph identity check HERE, where the graph is in
        # hand — cc_superstep_blocked alone can only check V, and a
        # same-V plan from a different graph would silently mis-reduce.
        if (
            plan.num_vertices != graph.num_vertices
            or plan.num_messages != graph.num_messages
        ):
            raise ValueError(
                f"plan built for V={plan.num_vertices}, "
                f"M={plan.num_messages} but graph has "
                f"V={graph.num_vertices}, M={graph.num_messages} — "
                "plan/graph mismatch"
            )
    elif plan is not None and plan.send_idx is None:
        plan = None  # non-fused plan: no label-gather indices to min over
    if sink is not None and not isinstance(graph.msg_ptr, jax.core.Tracer):
        # Achieved-vs-model attribution (ISSUE 12): run the fixpoint with
        # the iteration counter on (so the window size is the REAL
        # supersteps-to-fixpoint, not the bound), wall-time it, and judge
        # it against the analytical cost model.
        from graphmine_tpu.obs.costmodel import (
            emit_superstep_timing,
            superstep_cost,
            timed_fixpoint,
        )

        (labels, iters), secs, cold = timed_fixpoint(
            lambda: _connected_components(graph, max_iter, True, plan),
            jit_fn=_connected_components,
        )
        iters = int(iters)
        # weighted=False explicitly: CC's min ignores the weight payload
        # even when the shared auto plan carries one.
        cost = superstep_cost(
            "cc_superstep", "sort" if plan is None else "auto",
            graph.num_vertices, graph.num_messages, graph.num_edges,
            plan=plan, weighted=False,
        )
        emit_superstep_timing(
            sink, "cc_superstep", cost, iters, iters, secs,
            graph.num_edges, variant="fused", cold_compile=cold,
        )
        if return_iterations:
            return labels, iters
        return labels
    return _connected_components(graph, max_iter, return_iterations, plan)


@partial(jax.jit, static_argnames=("max_iter", "return_iterations"))
def _connected_components(
    graph: Graph, max_iter: int = 0, return_iterations: bool = False,
    plan=None,
):
    limit = max_iter if max_iter > 0 else graph.num_vertices + 2

    def cond(state):
        labels, prev_changed, it = state
        return (prev_changed > 0) & (it < limit)

    from graphmine_tpu.ops.blocking import BlockedPlan, cc_superstep_blocked

    def body(state):
        labels, _, it = state
        if plan is None:
            new = cc_superstep(labels, graph)
        elif isinstance(plan, BlockedPlan):
            new = cc_superstep_blocked(labels, plan)
        else:
            new = cc_superstep_bucketed(labels, plan)
        changed = jnp.sum(new != labels, dtype=jnp.int32)
        return new, changed, it + 1

    labels0 = jnp.arange(graph.num_vertices, dtype=jnp.int32)
    labels, _, iters = lax.while_loop(
        cond, body, (labels0, jnp.int32(1), jnp.int32(0))
    )
    if return_iterations:
        return labels, iters
    return labels
