"""Triangle counting and local clustering coefficients.

Engine-surface parity with GraphFrames' ``triangleCount`` (exposed on the
object built at ``Graphframes.py:78``; semantics there: direction and
duplicate edges ignored — triangles of the underlying simple undirected
graph). Also feeds the clustering-coefficient feature of the LOF outlier
scorer (SURVEY §7.5).

TPU design — degree-ordered wedge checking:

1. host: simplify edges (dedup, drop self-loops), orient each edge from
   lower to higher (degree, id) rank; build the oriented CSR and expand
   the exact wedge list (u, v, w): for every oriented edge (u, v), every
   oriented neighbor w of u. |wedges| = sum_u d+(u)^2, kept near-linear
   by the degree ordering (d+ = O(sqrt(m))).
2. device: one vectorized binary search per wedge — is (v, w) an oriented
   edge? — as a fori_loop of gathers over the oriented CSR (static
   iteration count = ceil(log2(max row length))), then three
   ``segment_sum`` scatters credit each triangle to its corners.

No [V, V] densification, no per-vertex host loops; everything after the
host build is O(|wedges|) gathers.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph, simple_undirected_edges


def _oriented_csr(graph: Graph):
    """Host-side: simple undirected edges oriented by (degree, id) rank.

    Returns ``(ptr, col, wedge_u, wedge_v, wedge_w, simple_degree,
    wedge_e1, wedge_e2)`` — the last two are per-wedge *edge indices*
    (into the ``col`` order, which IS the edge order): the generating
    edge ``(u, v)`` and the ``(u, w)`` row entry. Consumers that close a
    wedge (k-truss) get the third side's index from their binary-search
    hit, so every triangle knows all three edges from one shared build.
    """
    v = graph.num_vertices
    a, b = simple_undirected_edges(graph)

    deg = np.bincount(a, minlength=v) + np.bincount(b, minlength=v)
    # orient small rank -> large rank; rank = (degree, id)
    rank = deg.astype(np.int64) * v + np.arange(v)
    lo = np.where(rank[a] <= rank[b], a, b)
    hi = np.where(rank[a] <= rank[b], b, a)

    order = np.lexsort((hi, lo))
    lo, hi = lo[order], hi[order]
    counts = np.bincount(lo, minlength=v)
    ptr = np.zeros(v + 1, np.int64)
    np.cumsum(counts, out=ptr[1:])

    # wedge expansion: edge (u, v) x each w in N+(u)
    d_u = counts[lo]
    wedge_u = np.repeat(lo, d_u)
    wedge_v = np.repeat(hi, d_u)
    # w indices: for each edge e with endpoint u, the whole row of u;
    # within-run offsets computed vectorized (no per-edge host loop)
    total = int(d_u.sum())
    starts = np.cumsum(d_u) - d_u
    offsets = np.arange(total, dtype=np.int64) - np.repeat(starts, d_u)
    wedge_e2 = np.repeat(ptr[lo], d_u) + offsets
    wedge_w = hi[wedge_e2]
    wedge_e1 = np.repeat(np.arange(len(lo), dtype=np.int64), d_u)
    return (
        ptr.astype(np.int64), hi.astype(np.int32),
        wedge_u.astype(np.int32), wedge_v.astype(np.int32), wedge_w.astype(np.int32),
        deg.astype(np.int32),
        wedge_e1.astype(np.int32), wedge_e2.astype(np.int32),
    )


@partial(jax.jit, static_argnames=("num_vertices", "search_iters"))
def _count_device(ptr, col, wedge_v, wedge_w, wedge_u, num_vertices: int, search_iters: int):
    """Vectorized membership test: is (v, w) an oriented edge? Then credit
    triangles to u, v, w via segment sums."""
    lo = ptr[wedge_v]
    hi = ptr[wedge_v + 1]

    def bsearch(_, state):
        lo, hi = state
        mid = (lo + hi) // 2
        val = col[jnp.clip(mid, 0, col.shape[0] - 1)]
        go_right = (val < wedge_w) & (mid < hi)
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, jnp.maximum(mid, lo))
        return lo, hi

    lo_f, _ = lax.fori_loop(0, search_iters, bsearch, (lo, hi))
    found = (lo_f < ptr[wedge_v + 1]) & (col[jnp.clip(lo_f, 0, col.shape[0] - 1)] == wedge_w)
    # skip degenerate wedges where v == w (the edge itself)
    found &= wedge_v != wedge_w
    hit = found.astype(jnp.int32)
    tri = (
        jax.ops.segment_sum(hit, wedge_u, num_segments=num_vertices)
        + jax.ops.segment_sum(hit, wedge_v, num_segments=num_vertices)
        + jax.ops.segment_sum(hit, wedge_w, num_segments=num_vertices)
    )
    return tri, hit.sum()


def _triangles(graph: Graph):
    """Shared pipeline: host build + device count once.

    Returns ``(tri [V], total, simple_degree [V])``.
    """
    ptr, col, wu, wv, ww, deg, _, _ = _oriented_csr(graph)
    if len(wu) == 0:
        z = jnp.zeros((graph.num_vertices,), jnp.int32)
        return z, jnp.int32(0), jnp.asarray(deg, jnp.int32)
    max_row = int(np.max(np.diff(ptr), initial=1))
    iters = max(int(np.ceil(np.log2(max(max_row, 2)))) + 1, 1)
    tri, total = _count_device(
        jnp.asarray(ptr, jnp.int32), jnp.asarray(col),
        jnp.asarray(wv), jnp.asarray(ww), jnp.asarray(wu),
        num_vertices=graph.num_vertices, search_iters=iters,
    )
    return tri, total, jnp.asarray(deg, jnp.int32)


def triangle_count(graph: Graph):
    """Per-vertex triangle counts ``[V]`` and the global triangle total.

    GraphFrames ``triangleCount`` semantics (simple undirected graph).
    """
    tri, total, _ = _triangles(graph)
    return tri, total


def clustering_coefficient(graph: Graph, _cached=None) -> jax.Array:
    """Local clustering coefficient ``[V]`` (float32): triangles through a
    vertex over its wedge count on the simplified graph.

    ``_cached`` optionally takes a prior :func:`_triangles` result so a
    caller needing both counts and coefficients pays the pipeline once.
    """
    tri, _, deg = _triangles(graph) if _cached is None else _cached
    deg = deg.astype(jnp.float32)
    wedges = deg * (deg - 1.0) / 2.0
    return jnp.where(wedges > 0, tri / jnp.maximum(wedges, 1.0), 0.0).astype(jnp.float32)
