"""Triangle counting and local clustering coefficients.

Engine-surface parity with GraphFrames' ``triangleCount`` (exposed on the
object built at ``Graphframes.py:78``; semantics there: direction and
duplicate edges ignored — triangles of the underlying simple undirected
graph). Also feeds the clustering-coefficient feature of the LOF outlier
scorer (SURVEY §7.5).

TPU design — degree-ordered wedge checking:

1. host: simplify edges (dedup, drop self-loops), orient each edge from
   lower to higher (degree, id) rank; build the oriented CSR and expand
   the exact wedge list (u, v, w): for every oriented edge (u, v), every
   oriented neighbor w of u. |wedges| = sum_u d+(u)^2, kept near-linear
   by the degree ordering (d+ = O(sqrt(m))).
2. device: one vectorized binary search per wedge — is (v, w) an oriented
   edge? — as a fori_loop of gathers over the oriented CSR (static
   iteration count = ceil(log2(max row length))), then three
   ``segment_sum`` scatters credit each triangle to its corners.

No [V, V] densification, no per-vertex host loops; everything after the
host build is O(|wedges|) gathers.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph, simple_undirected_edges


def _oriented_csr(graph: Graph, simple_edges=None):
    """Host-side: simple undirected edges oriented by (degree, id) rank.

    Returns ``(ptr, col, wedge_u, wedge_v, wedge_w, simple_degree,
    wedge_e1, wedge_e2)`` — the last two are per-wedge *edge indices*
    (into the ``col`` order, which IS the edge order): the generating
    edge ``(u, v)`` and the ``(u, w)`` row entry. Consumers that close a
    wedge (k-truss) get the third side's index from their binary-search
    hit, so every triangle knows all three edges from one shared build.

    ``simple_edges``: optional precomputed
    :func:`simple_undirected_edges` result — callers that already paid
    the O(E log E) dedup (the driver's wedge-budget probe) pass it so
    the pipeline runs it once per graph, not once per consumer.
    """
    v = graph.num_vertices
    a, b = simple_edges or simple_undirected_edges(graph)

    deg = np.bincount(a, minlength=v) + np.bincount(b, minlength=v)
    # orient small rank -> large rank; rank = (degree, id)
    rank = deg.astype(np.int64) * v + np.arange(v)
    lo = np.where(rank[a] <= rank[b], a, b)
    hi = np.where(rank[a] <= rank[b], b, a)

    order = np.lexsort((hi, lo))
    lo, hi = lo[order], hi[order]
    counts = np.bincount(lo, minlength=v)
    ptr = np.zeros(v + 1, np.int64)
    np.cumsum(counts, out=ptr[1:])

    # wedge expansion: edge (u, v) x each w in N+(u)
    d_u = counts[lo]
    wedge_u = np.repeat(lo, d_u)
    wedge_v = np.repeat(hi, d_u)
    # w indices: for each edge e with endpoint u, the whole row of u;
    # within-run offsets computed vectorized (no per-edge host loop)
    total = int(d_u.sum())
    starts = np.cumsum(d_u) - d_u
    offsets = np.arange(total, dtype=np.int64) - np.repeat(starts, d_u)
    wedge_e2 = np.repeat(ptr[lo], d_u) + offsets
    wedge_w = hi[wedge_e2]
    wedge_e1 = np.repeat(np.arange(len(lo), dtype=np.int64), d_u)
    return (
        ptr.astype(np.int64), hi.astype(np.int32),
        wedge_u.astype(np.int32), wedge_v.astype(np.int32), wedge_w.astype(np.int32),
        deg.astype(np.int32),
        wedge_e1.astype(np.int32), wedge_e2.astype(np.int32),
    )


@partial(jax.jit, static_argnames=("num_vertices", "search_iters"))
def _count_device(ptr, col, wedge_v, wedge_w, wedge_u, num_vertices: int, search_iters: int):
    """Vectorized membership test: is (v, w) an oriented edge? Then credit
    triangles to u, v, w via segment sums."""
    lo = ptr[wedge_v]
    hi = ptr[wedge_v + 1]

    def bsearch(_, state):
        lo, hi = state
        mid = (lo + hi) // 2
        val = col[jnp.clip(mid, 0, col.shape[0] - 1)]
        go_right = (val < wedge_w) & (mid < hi)
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, jnp.maximum(mid, lo))
        return lo, hi

    lo_f, _ = lax.fori_loop(0, search_iters, bsearch, (lo, hi))
    found = (lo_f < ptr[wedge_v + 1]) & (col[jnp.clip(lo_f, 0, col.shape[0] - 1)] == wedge_w)
    # skip degenerate wedges where v == w (the edge itself)
    found &= wedge_v != wedge_w
    hit = found.astype(jnp.int32)
    tri = (
        jax.ops.segment_sum(hit, wedge_u, num_segments=num_vertices)
        + jax.ops.segment_sum(hit, wedge_v, num_segments=num_vertices)
        + jax.ops.segment_sum(hit, wedge_w, num_segments=num_vertices)
    )
    return tri, hit.sum()


def _triangles(graph: Graph, simple_edges=None):
    """Shared pipeline: host build + device count once.

    Returns ``(tri [V], total, simple_degree [V])``.
    """
    ptr, col, wu, wv, ww, deg, _, _ = _oriented_csr(graph, simple_edges)
    if len(wu) == 0:
        z = jnp.zeros((graph.num_vertices,), jnp.int32)
        return z, jnp.int32(0), jnp.asarray(deg, jnp.int32)
    max_row = int(np.max(np.diff(ptr), initial=1))
    iters = max(int(np.ceil(np.log2(max(max_row, 2)))) + 1, 1)
    tri, total = _count_device(
        jnp.asarray(ptr, jnp.int32), jnp.asarray(col),
        jnp.asarray(wv), jnp.asarray(ww), jnp.asarray(wu),
        num_vertices=graph.num_vertices, search_iters=iters,
    )
    return tri, total, jnp.asarray(deg, jnp.int32)


def triangle_count(graph: Graph):
    """Per-vertex triangle counts ``[V]`` and the global triangle total.

    GraphFrames ``triangleCount`` semantics (simple undirected graph).
    """
    tri, total, _ = _triangles(graph)
    return tri, total


def oriented_wedge_count(graph: Graph, simple_edges=None) -> int:
    """Exact count of oriented wedges the exact triangle pipeline would
    materialize — WITHOUT materializing them (O(E log E) host work, O(E)
    memory).

    This is the feasibility probe for :func:`_oriented_csr`, whose wedge
    expansion allocates ~28 bytes per wedge on the host: a mega-hub
    power-law graph at 25M edges reaches ~10^10 oriented wedges (~300 GB)
    — the round-5 e2e bench run was OOM-killed at 130 GB RSS exactly
    here. Callers (the pipeline driver's LOF feature phase) compare this
    against a budget and fall back to
    :func:`sampled_clustering_coefficient`, whose cost is independent of
    the wedge count. ``simple_edges``: optional precomputed
    :func:`simple_undirected_edges` pair (see :func:`_oriented_csr`).
    """
    v = graph.num_vertices
    a, b = simple_edges or simple_undirected_edges(graph)
    if len(a) == 0:
        return 0
    deg = np.bincount(a, minlength=v) + np.bincount(b, minlength=v)
    rank = deg.astype(np.int64) * v + np.arange(v)
    lo = np.where(rank[a] <= rank[b], a, b)
    counts = np.bincount(lo, minlength=v).astype(np.int64)
    # each oriented edge (u, v) expands against u's whole oriented row
    return int(counts[lo].sum())


def clustering_coefficient(
    graph: Graph, _cached=None, simple_edges=None
) -> jax.Array:
    """Local clustering coefficient ``[V]`` (float32): triangles through a
    vertex over its wedge count on the simplified graph.

    ``_cached`` optionally takes a prior :func:`_triangles` result so a
    caller needing both counts and coefficients pays the pipeline once;
    ``simple_edges`` forwards a precomputed dedup (see
    :func:`_oriented_csr`).
    """
    tri, _, deg = (
        _triangles(graph, simple_edges) if _cached is None else _cached
    )
    deg = deg.astype(jnp.float32)
    wedges = deg * (deg - 1.0) / 2.0
    return jnp.where(wedges > 0, tri / jnp.maximum(wedges, 1.0), 0.0).astype(jnp.float32)


def _splitmix64(x):
    """Vectorized splitmix64 finalizer (uint64 in, uint64 out) — the
    stateless per-(vertex, sample) RNG of the wedge sampler. uint64
    wraparound is the intended modular arithmetic."""
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def _hash_u01(key, seed_mix):
    """Hash uint64 keys + a pre-mixed seed to float64 uniforms in [0, 1)."""
    with np.errstate(over="ignore"):
        z = _splitmix64(key ^ seed_mix)
    return (z >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))


def sampled_clustering_coefficient(
    graph: Graph, samples: int = 64, seed: int = 0,
    chunk_vertices: int = 1 << 20, simple_edges=None,
) -> np.ndarray:
    """Wedge-sampled approximate local clustering coefficient ``[V]``
    (float32, HOST NumPy) — the at-scale replacement for the exact wedge
    pipeline (VERDICT r3 item 5).

    For every vertex with simple-undirected degree >= 2, draws ``samples``
    uniform unordered neighbor pairs (distinct within each pair, drawn
    with replacement across pairs) and reports the closed fraction — an
    unbiased estimator of the exact coefficient with binomial standard
    error ``<= 1 / (2 * sqrt(samples))`` per vertex (~0.0625 at the
    default 64; the error-bound test pins a 4-sigma envelope against the
    exact pipeline). Work is O(V * samples * log E) membership binary
    searches + one O(E log E) host CSR build — independent of the wedge
    count, which is what makes the clustering feature (and therefore the
    full 8-feature LOF set) survive at the scale where the exact
    O(sum d+^2) wedge expansion is infeasible.

    Processes vertices in ``chunk_vertices`` blocks so peak scratch memory
    stays ~``chunk_vertices * samples`` words regardless of V. Draws are a
    stateless splitmix64 hash of ``(seed, vertex, sample)``, so the result
    is a pure function of the seed — changing ``chunk_vertices`` to fit
    host RAM cannot change the estimates (pinned in tests).
    ``simple_edges`` forwards a precomputed dedup (see
    :func:`_oriented_csr`).
    """
    v = graph.num_vertices
    a, b = simple_edges or simple_undirected_edges(graph)
    # full undirected adjacency CSR of the simple graph (both directions)
    nodes = np.concatenate([a, b])
    nbrs = np.concatenate([b, a])
    order = np.argsort(nodes, kind="stable")
    nbrs = nbrs[order]
    deg = np.bincount(a, minlength=v) + np.bincount(b, minlength=v)
    ptr = np.zeros(v + 1, np.int64)
    np.cumsum(deg, out=ptr[1:])
    # membership oracle: composite keys of the (a < b) edge list — already
    # sorted by construction (simple_undirected_edges unpacks a sorted
    # np.unique key array, and a*v+b reconstructs it exactly)
    edge_keys = a.astype(np.int64) * v + b.astype(np.int64)

    out = np.zeros(v, np.float32)
    seed_mix = _splitmix64(np.uint64(seed & 0xFFFFFFFFFFFFFFFF))
    active = np.flatnonzero(deg >= 2)
    for lo in range(0, len(active), chunk_vertices):
        vs = active[lo:lo + chunk_vertices]
        d = deg[vs].astype(np.int64)[:, None]           # [c, 1]
        # uniform unordered distinct pair (i, j) per sample: i uniform in
        # [0, d), j = (i + 1 + uniform[0, d-1)) mod d
        s_idx = np.arange(samples, dtype=np.uint64)[None, :]
        key = vs.astype(np.uint64)[:, None] * np.uint64(2 * samples)
        r1 = _hash_u01(key + 2 * s_idx, seed_mix)
        r2 = _hash_u01(key + 2 * s_idx + np.uint64(1), seed_mix)
        i = (r1 * d).astype(np.int64)
        j = (i + 1 + (r2 * (d - 1)).astype(np.int64)) % d
        base = ptr[vs][:, None]
        n1 = nbrs[base + i].astype(np.int64)
        n2 = nbrs[base + j].astype(np.int64)
        key = np.minimum(n1, n2) * v + np.maximum(n1, n2)
        pos = np.searchsorted(edge_keys, key)
        closed = (pos < len(edge_keys)) & (
            edge_keys[np.minimum(pos, len(edge_keys) - 1)] == key
        )
        out[vs] = closed.mean(axis=1, dtype=np.float64).astype(np.float32)
    return out
