from graphmine_tpu.ops.segment import segment_mode
from graphmine_tpu.ops.lpa import label_propagation, lpa_superstep
from graphmine_tpu.ops.cc import connected_components
from graphmine_tpu.ops.louvain import louvain
from graphmine_tpu.ops.modularity import modularity

__all__ = ["segment_mode", "label_propagation", "lpa_superstep", "connected_components", "louvain", "modularity"]
