from graphmine_tpu.ops.segment import segment_mode
from graphmine_tpu.ops.aggregate import aggregate_messages, pregel
from graphmine_tpu.ops.lpa import label_propagation, lpa_superstep
from graphmine_tpu.ops.cc import connected_components
from graphmine_tpu.ops.scc import strongly_connected_components
from graphmine_tpu.ops.paths import bfs, bfs_parents
from graphmine_tpu.ops.motifs import find, parse_pattern
from graphmine_tpu.ops.streaming_lof import StreamingLOF, fit_lof, score_lof
from graphmine_tpu.ops.louvain import leiden, louvain
from graphmine_tpu.ops.modularity import modularity
from graphmine_tpu.ops.bucketed_mode import BucketedModePlan, bucketed_mode, lpa_superstep_bucketed
from graphmine_tpu.ops.blocking import (
    BlockedPlan,
    blocked_inflow,
    build_graph_and_blocked_plan,
    cc_superstep_blocked,
    lpa_superstep_blocked,
    select_superstep_family,
)
from graphmine_tpu.ops.pagerank import pagerank, parallel_personalized_pagerank
from graphmine_tpu.ops.svdpp import SVDPlusPlusModel, svd_plus_plus, svdpp_predict
from graphmine_tpu.ops.degrees import degrees, in_degrees, out_degrees
from graphmine_tpu.ops.paths import bfs_distances, shortest_paths, weighted_shortest_paths
from graphmine_tpu.ops.cluster_metrics import adjusted_rand_index, normalized_mutual_info
from graphmine_tpu.ops.triangles import triangle_count, clustering_coefficient
from graphmine_tpu.ops.kcore import core_numbers
from graphmine_tpu.ops.mis import greedy_color, maximal_independent_set
from graphmine_tpu.ops.linkpred import link_prediction
from graphmine_tpu.ops.ktruss import k_truss
from graphmine_tpu.ops.embedding import spectral_embedding
from graphmine_tpu.ops.stats import degree_assortativity, density, diameter, reciprocity
from graphmine_tpu.ops.centrality import (
    betweenness_centrality,
    closeness_centrality,
    eigenvector_centrality,
    hits,
    katz_centrality,
)

__all__ = ["degree_assortativity", "density", "diameter", "reciprocity", "spectral_embedding", "k_truss", "link_prediction", "maximal_independent_set", "greedy_color", "hits", "closeness_centrality", "betweenness_centrality",
           "eigenvector_centrality", "katz_centrality",
           "weighted_shortest_paths",
           "adjusted_rand_index", "normalized_mutual_info","segment_mode", "BucketedModePlan", "bucketed_mode", "lpa_superstep_bucketed",
           "BlockedPlan", "blocked_inflow", "build_graph_and_blocked_plan", "cc_superstep_blocked", "lpa_superstep_blocked", "select_superstep_family", "aggregate_messages", "pregel", "find", "parse_pattern", "StreamingLOF", "fit_lof", "score_lof", "label_propagation", "lpa_superstep", "connected_components", "strongly_connected_components", "louvain", "leiden", "modularity", "pagerank", "parallel_personalized_pagerank", "svd_plus_plus", "svdpp_predict", "SVDPlusPlusModel", "degrees", "in_degrees", "out_degrees", "bfs", "bfs_parents", "bfs_distances", "shortest_paths", "triangle_count", "clustering_coefficient", "core_numbers"]
