"""Link-prediction scores for candidate vertex pairs.

NetworkX-parity neighborhood-overlap measures (``jaccard_coefficient``,
``adamic_adar_index``, ``common_neighbors``, ``preferential_attachment``,
``resource_allocation_index``), defined on the simple undirected graph
(duplicates and self-loops dropped, as NetworkX does).

Host-side vectorized NumPy — this is candidate-pair preprocessing (the
same class of op as the kNN feature stage), not a superstep kernel. The
membership test is one ``searchsorted`` over row-offset-encoded adjacency
(``row * V + col``, globally sorted), so cost is
``O(Σ deg(u) · log E)`` over the pairs with no per-pair Python.
"""

from __future__ import annotations

import numpy as np

from graphmine_tpu.graph.container import Graph, simple_undirected_edges

_METHODS = ("common_neighbors", "jaccard", "adamic_adar",
            "resource_allocation", "preferential_attachment")


def _adjacency(graph: Graph):
    """Sorted CSR of the simple undirected graph + encoded entry list."""
    a, b = simple_undirected_edges(graph)
    v = graph.num_vertices
    src = np.concatenate([a, b]).astype(np.int64)
    dst = np.concatenate([b, a]).astype(np.int64)
    order = np.lexsort((dst, src))
    src, dst = src[order], dst[order]
    deg = np.bincount(src, minlength=v)
    indptr = np.concatenate([[0], np.cumsum(deg)])
    encoded = src * v + dst  # globally ascending
    return indptr, dst, encoded, deg


def link_prediction(
    graph: Graph, pairs, method: str = "jaccard"
) -> np.ndarray:
    """Scores ``[P]`` (float64) for candidate ``pairs`` (``[P, 2]`` int
    array or iterable of 2-tuples). ``method`` is one of
    ``common_neighbors | jaccard | adamic_adar | resource_allocation |
    preferential_attachment`` (NetworkX-oracle tested)."""
    if method not in _METHODS:
        raise ValueError(f"unknown method {method!r}; choose from {_METHODS}")
    pairs = np.asarray(pairs, dtype=np.int64)
    if pairs.size == 0:
        return np.zeros(0, dtype=np.float64)
    pairs = np.atleast_2d(pairs)
    if pairs.shape[-1] != 2:
        raise ValueError("pairs must have shape [P, 2]")
    u, w = pairs[:, 0], pairs[:, 1]
    v = graph.num_vertices
    if (u < 0).any() or (u >= v).any() or (w < 0).any() or (w >= v).any():
        raise ValueError("pair endpoints out of range")
    if (u == w).any():
        raise ValueError("self-pairs are not valid link-prediction candidates")
    indptr, nbrs, encoded, deg = _adjacency(graph)

    if method == "preferential_attachment":
        return (deg[u] * deg[w]).astype(np.float64)

    # all overlap measures are symmetric: expand the lower-degree endpoint
    # so a (hub, leaf) pair costs deg(leaf), not deg(hub)
    swap = deg[w] < deg[u]
    u, w = np.where(swap, w, u), np.where(swap, u, w)

    # expand every pair over N(u); membership of each neighbor k in N(w)
    # via binary search on the encoded entries
    cnt = deg[u]
    total = int(cnt.sum())
    starts_out = np.cumsum(cnt) - cnt
    pid = np.repeat(np.arange(len(u)), cnt)
    pos = (np.repeat(indptr[u], cnt)
           + (np.arange(total) - np.repeat(starts_out, cnt)))
    ks = nbrs[pos]
    probe = w[pid] * v + ks
    loc = np.searchsorted(encoded, probe)
    member = (loc < len(encoded)) & (encoded[np.minimum(loc, len(encoded) - 1)]
                                     == probe)

    if method == "common_neighbors":
        vals = member.astype(np.float64)
    elif method == "adamic_adar":
        # common neighbors always have deg >= 2, so log(deg) > 0
        vals = np.where(member, 1.0 / np.log(np.maximum(deg[ks], 2)), 0.0)
    elif method == "resource_allocation":
        vals = np.where(member, 1.0 / np.maximum(deg[ks], 1), 0.0)
    else:  # jaccard
        vals = member.astype(np.float64)
    score = np.bincount(pid, weights=vals, minlength=len(u))
    if method == "jaccard":
        union = deg[u] + deg[w] - score
        return np.where(union > 0, score / np.maximum(union, 1), 0.0)
    return score
