"""Strongly connected components — coloring algorithm on device.

Engine-surface parity with ``GraphFrame.stronglyConnectedComponents`` (the
object built at ``Graphframes.py:78`` exposes it; the reference script never
calls it). GraphX implements SCC as iterated Pregel passes; the TPU-native
design is the *coloring* algorithm (Orzan), which is the same BSP shape as
our LPA/CC kernels — no recursion, no dynamic subgraphs:

  repeat until every vertex is assigned:
    1. forward min-propagation of vertex ids among unassigned vertices to a
       fixpoint ("coloring") — each vertex's color = smallest unassigned id
       that reaches it along edge direction;
    2. roots are vertices whose color is their own id; the root's SCC is the
       set of vertices that reach it *backward* without leaving its color
       class (forward-reach ∩ backward-reach);
    3. assign those vertices their color as final SCC id and mask them out.

Every pass peels at least each root's SCC, so the outer loop terminates;
inner loops are edge relaxations (gather + ``segment_min``/``segment_max``)
under ``lax.while_loop`` with static shapes. Labels are canonical
representatives (a member vertex id), not necessarily the minimum id in the
SCC — compare partitions, not raw labels (SURVEY §6).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from graphmine_tpu.graph.container import Graph

_SENT = jnp.iinfo(jnp.int32).max


@jax.jit
def strongly_connected_components(graph: Graph) -> jax.Array:
    """SCC id per vertex, int32 ``[V]`` (id = a member vertex of the SCC)."""
    v = graph.num_vertices
    src, dst = graph.src, graph.dst
    ids = jnp.arange(v, dtype=jnp.int32)

    def color_fixpoint(unassigned):
        """Forward min-propagation of ids within the unassigned set."""

        def body(state):
            color, _ = state
            msg = jnp.where(unassigned[src], color[src], _SENT)
            relax = jax.ops.segment_min(msg, dst, num_segments=v)
            new = jnp.where(unassigned, jnp.minimum(color, relax), color)
            changed = jnp.sum(new != color, dtype=jnp.int32)
            return new, changed

        init = jnp.where(unassigned, ids, _SENT)
        color, _ = lax.while_loop(lambda s: s[1] > 0, body, (init, jnp.int32(1)))
        return color

    def backward_fixpoint(roots, color, unassigned):
        """Backward reachability of roots within each color class."""

        def body(state):
            in_scc, _ = state
            hit = in_scc[dst] & (color[src] == color[dst])
            relax = jax.ops.segment_max(
                hit.astype(jnp.int32), src, num_segments=v
            ) > 0
            new = in_scc | (relax & unassigned)
            changed = jnp.sum(new != in_scc, dtype=jnp.int32)
            return new, changed

        in_scc, _ = lax.while_loop(lambda s: s[1] > 0, body, (roots, jnp.int32(1)))
        return in_scc

    def outer(scc):
        unassigned = scc < 0
        color = color_fixpoint(unassigned)
        roots = unassigned & (color == ids)
        in_scc = backward_fixpoint(roots, color, unassigned)
        return jnp.where(in_scc, color, scc)

    scc0 = jnp.full((v,), -1, jnp.int32)
    scc = lax.while_loop(lambda s: jnp.any(s < 0), outer, scc0)
    return scc
