"""Maximal independent set (Luby) and greedy coloring (repeated MIS).

Classic BSP parallel-graph algorithms, absent from GraphFrames but
standard in any graph toolkit. TPU design: per-round random priorities
(threaded ``jax.random`` keys — deterministic given ``seed``), one
``segment_max`` over the symmetric message CSR to find local maxima, and
state transitions as ``where`` updates inside a single ``lax.while_loop``
— no frontier queues, static shapes throughout.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from graphmine_tpu.graph.container import Graph

_UNDECIDED, _IN, _OUT = 0, 1, 2


def _round_priorities(key, it, v):
    """Fresh uint32 priority per vertex per round, bit 0 reserved so 0 can
    be the masked-out sentinel."""
    k = jax.random.fold_in(key, it)
    return jax.random.bits(k, (v,), jnp.uint32) | jnp.uint32(1)


def _mis_rounds(state, send, recv, v, key, limit):
    """Run Luby rounds until no vertex is undecided (or ``limit``)."""
    # self-loops must not let a vertex block itself (its own priority in
    # its neighbor max would make it undecidable forever)
    not_self = send != recv

    def round_(carry):
        state, it = carry
        pri = jnp.where(state == _UNDECIDED, _round_priorities(key, it, v), 0)
        nbr_max = jax.ops.segment_max(
            jnp.where(not_self, pri[send], 0), recv, num_segments=v,
            indices_are_sorted=True,
        )
        # strictly above every undecided neighbor (ties collide with
        # probability ~deg/2^32 per round; a collision only defers both
        # vertices to the next round's fresh randomness)
        join = (state == _UNDECIDED) & (pri > nbr_max)
        state = jnp.where(join, _IN, state)
        nbr_in = jax.ops.segment_max(
            jnp.where(not_self, (state[send] == _IN).astype(jnp.int32), 0),
            recv, num_segments=v, indices_are_sorted=True,
        )
        state = jnp.where((state == _UNDECIDED) & (nbr_in > 0), _OUT, state)
        return state, it + 1

    def cond(carry):
        state, it = carry
        return jnp.any(state == _UNDECIDED) & (it < limit)

    state, _ = lax.while_loop(cond, round_, (state, jnp.int32(0)))
    return state


@partial(jax.jit, static_argnames=("max_iter",))
def maximal_independent_set(
    graph: Graph, seed: int = 0, max_iter: int = 0
) -> jax.Array:
    """Boolean ``[V]`` MIS membership mask (independent and maximal;
    property-tested). Requires a symmetric graph; deterministic for a
    given ``seed``; self-loops are ignored (a vertex is never its own
    neighbor). Luby's algorithm terminates in O(log V) rounds with high
    probability; ``max_iter`` (default V) is the hard cap."""
    if not graph.symmetric:
        raise ValueError("maximal_independent_set needs symmetric=True "
                         "(independence is an undirected property)")
    v = graph.num_vertices
    limit = max_iter if max_iter > 0 else v
    key = jax.random.PRNGKey(seed)
    state = jnp.full(v, _UNDECIDED, jnp.int32)
    state = _mis_rounds(state, graph.msg_send, graph.msg_recv, v, key, limit)
    return state == _IN


@partial(jax.jit, static_argnames=("max_colors",))
def greedy_color(graph: Graph, seed: int = 0, max_colors: int = 0) -> jax.Array:
    """Proper vertex coloring ``[V]`` (int32 color ids from 0) by repeated
    MIS: round ``c``'s maximal independent set of the still-uncolored
    subgraph gets color ``c``. Color count is within O(Δ) of optimal on
    bounded-degree graphs (property-tested: no edge joins equal colors).
    Requires a symmetric graph; deterministic for a given ``seed``;
    self-loops are ignored (otherwise no proper coloring exists). With
    the default cap every vertex is colored; an explicit ``max_colors``
    that runs out leaves the remainder at the ``-1`` sentinel."""
    if not graph.symmetric:
        raise ValueError("greedy_color needs symmetric=True")
    v = graph.num_vertices
    send, recv = graph.msg_send, graph.msg_recv
    limit = max_colors if max_colors > 0 else v
    key = jax.random.PRNGKey(seed)

    def color_round(carry):
        colors, c = carry
        # MIS over the uncolored subgraph: colored vertices start _OUT so
        # they neither join nor block their uncolored neighbors
        state = jnp.where(colors < 0, _UNDECIDED, _OUT)
        state = _mis_rounds(state, send, recv, v,
                            jax.random.fold_in(key, c), v)
        colors = jnp.where(state == _IN, c, colors)
        return colors, c + 1

    def cond(carry):
        colors, c = carry
        return jnp.any(colors < 0) & (c < limit)

    colors, _ = lax.while_loop(
        cond, color_round, (jnp.full(v, -1, jnp.int32), jnp.int32(0))
    )
    return colors
