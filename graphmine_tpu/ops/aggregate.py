"""Generic message passing: ``aggregate_messages`` + ``pregel``.

Engine-surface parity with the Pregel substrate the reference leans on:
``GraphFrame.labelPropagation`` (``Graphframes.py:81``) is GraphX Pregel
underneath (SURVEY CS-3), and GraphFrames additionally exposes the substrate
directly as ``aggregateMessages`` and (0.8+) a ``pregel`` builder. This
module is the TPU-native version of that substrate: a superstep is

    gather endpoint values → per-edge message fn → segment-reduce at the
    receiving vertex → vertex update fn

compiled to one XLA program per iteration (``lax.scan`` over supersteps).
No shuffle, no driver round-trips; on a sharded graph the same functions run
under ``shard_map`` (see :mod:`graphmine_tpu.parallel.sharded`).

Unlike GraphFrames' SQL-expression API, message/update functions here are
plain JAX callables over arrays — idiomatic for XLA and strictly more
expressive than Catalyst expressions.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.ops.segment import segment_mode

# message fn: (src_values, dst_values, edge_values) -> [E] message array.
MessageFn = Callable[[Any, Any, Any], jax.Array]


def _tree_take(tree: Any, idx: jax.Array) -> Any:
    return jax.tree_util.tree_map(lambda a: a[idx], tree)


def _reduce(reduce: str, msgs: jax.Array, recv: jax.Array, num_segments: int):
    if reduce == "sum":
        return jax.ops.segment_sum(msgs, recv, num_segments=num_segments)
    if reduce == "max":
        return jax.ops.segment_max(msgs, recv, num_segments=num_segments)
    if reduce == "min":
        return jax.ops.segment_min(msgs, recv, num_segments=num_segments)
    if reduce == "mean":
        total = jax.ops.segment_sum(msgs, recv, num_segments=num_segments)
        ones = jnp.ones_like(recv, dtype=msgs.dtype)
        count = jax.ops.segment_sum(ones, recv, num_segments=num_segments)
        return total / jnp.maximum(count, 1)
    if reduce == "mode":
        if not jnp.issubdtype(msgs.dtype, jnp.integer):
            raise TypeError(
                f"reduce='mode' needs integer messages, got {msgs.dtype} "
                "(segment_mode is pure int32 arithmetic)"
            )
        mode, _ = segment_mode(recv, msgs, num_segments=num_segments)
        return mode
    raise ValueError(f"unknown reduce {reduce!r}; want sum|max|min|mean|mode")


def aggregate_messages(
    graph: Graph,
    vertex_values: Any,
    edge_values: Any = None,
    *,
    to_dst: MessageFn | None = None,
    to_src: MessageFn | None = None,
    reduce: str = "sum",
) -> jax.Array:
    """One gather → message → segment-reduce round (GraphFrames
    ``aggregateMessages`` semantics).

    Parameters
    ----------
    vertex_values : pytree of ``[V]`` arrays, gathered at both endpoints and
        handed to the message functions.
    edge_values : optional pytree of ``[E]`` arrays (edge attributes).
    to_dst / to_src : ``fn(src_vals, dst_vals, edge_vals) -> [E] msgs`` sent
        to the edge's dst / src respectively; at least one must be given.
    reduce : ``sum|max|min|mean|mode`` applied per receiving vertex.

    Returns the ``[V]`` reduced aggregate. Vertices receiving no message get
    the reducer's identity (0 for sum/mean, dtype max/min for min/max,
    int32 max for mode) — mask with degree if that matters.
    """
    if to_dst is None and to_src is None:
        raise ValueError("provide at least one of to_dst/to_src")
    sv = _tree_take(vertex_values, graph.src)
    dv = _tree_take(vertex_values, graph.dst)
    msgs, recv = [], []
    if to_dst is not None:
        msgs.append(jnp.asarray(to_dst(sv, dv, edge_values)))
        recv.append(graph.dst)
    if to_src is not None:
        msgs.append(jnp.asarray(to_src(sv, dv, edge_values)))
        recv.append(graph.src)
    m = msgs[0] if len(msgs) == 1 else jnp.concatenate(msgs)
    r = recv[0] if len(recv) == 1 else jnp.concatenate(recv)
    return _reduce(reduce, m, r, graph.num_vertices)


def pregel(
    graph: Graph,
    init_state: Any,
    *,
    to_dst: MessageFn | None = None,
    to_src: MessageFn | None = None,
    reduce: str = "sum",
    update: Callable[[Any, jax.Array], Any],
    max_iter: int = 10,
    edge_values: Any = None,
) -> Any:
    """Run ``max_iter`` synchronous supersteps of a vertex program.

    ``init_state`` is a pytree of ``[V]`` arrays; each superstep computes the
    per-vertex aggregate via :func:`aggregate_messages` and applies
    ``update(state, aggregate) -> new_state``. The whole loop is one
    ``lax.scan`` — exactly the BSP shape of GraphX Pregel (SURVEY CS-3)
    without per-superstep shuffles.

    Fixed iteration count mirrors the reference's ``maxIter`` contract
    (``Graphframes.py:81`` runs exactly 5 supersteps, no convergence test);
    for convergence-tested loops use ``lax.while_loop`` directly, as
    :func:`graphmine_tpu.ops.cc.connected_components` does.

    Not jitted here on purpose: the callables would have to be static jit
    arguments, and inline lambdas (the idiomatic call style) would then
    recompile the whole scan on every invocation. ``lax.scan`` already
    executes the loop as compiled XLA; for repeated driver-loop use, wrap
    *your* call site — ``jax.jit(lambda g, s: pregel(g, s, to_dst=f, ...))``
    — so the cache is keyed by your stable closure.
    """

    def step(state, _):
        agg = aggregate_messages(
            graph, state, edge_values, to_dst=to_dst, to_src=to_src, reduce=reduce
        )
        return update(state, agg), None

    state, _ = lax.scan(step, init_state, None, length=max_iter)
    return state
