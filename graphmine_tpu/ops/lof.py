"""Local Outlier Factor (LOF) scoring on device.

North-star outlier capability (BASELINE.json: "kNN-graph + LOF outlier
scorer ... LOF AUROC on held-out outliers"). Standard LOF (Breunig et al.):

    k-distance(p)   = distance to p's k-th neighbor
    reach_k(p, o)   = max(k-distance(o), d(p, o))
    lrd(p)          = k / sum_o reach_k(p, o)
    LOF(p)          = mean_o lrd(o) / lrd(p)

Scores ≈ 1 for inliers, >> 1 for outliers. Validated against the
scikit-learn oracle in tests (SURVEY §4).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from graphmine_tpu.ops.knn import knn


@partial(jax.jit, static_argnames=("k", "row_tile", "impl"))
def lof_scores(
    points: jax.Array, k: int = 20, row_tile: int = 1024, impl: str = "auto"
) -> jax.Array:
    """LOF score per point, shape ``[N]`` (higher = more outlying).

    Discrete graph features produce many *identical* rows; classic LOF
    degenerates there (k-distance 0 ⇒ lrd → ∞ ⇒ unbounded scores for
    duplicate-adjacent points — the known LOF duplicates problem). Reach
    distances are floored at 1e-3 x the mean positive kNN distance, which
    bounds scores at a meaningful scale and is a no-op on duplicate-free
    data (the sklearn parity test).

    Choosing ``k``: it must exceed the size of any *clustered* anomaly
    group — a batch of anomalies with near-identical features forms its
    own dense region, and with ``k`` below the group size each one's kNN
    neighborhood is just the other anomalies, so they score as inliers
    (measured: 64 injected hubs at 65K vertices swing AUROC 0.49 → 0.91
    going from k=20 to k=100; see ``bench.py --tier lof``).
    """
    d2, idx = knn(points, k=k, row_tile=row_tile, impl=impl)
    return lof_from_knn(d2, idx, k)


def lof_from_knn(d2: jax.Array, idx: jax.Array, k: int) -> jax.Array:
    """LOF scores from a kNN result (``[N, k]`` squared distances +
    neighbor indices). Shared by the all-pairs path above and the
    ring-sharded path (:func:`graphmine_tpu.parallel.knn.sharded_lof`) —
    the gathers ``kdist[idx]`` / ``lrd[idx]`` are over ``[N]`` vectors, so
    under GSPMD they cost one small all-gather each."""
    dists = jnp.sqrt(d2)
    pos = dists > 0
    eps = 1e-3 * dists.sum() / jnp.maximum(pos.sum(), 1)
    kdist = dists[:, -1]
    reach = jnp.maximum(jnp.maximum(kdist[idx], dists), eps)  # [N, k]
    lrd = k / jnp.maximum(reach.sum(axis=1), 1e-12)
    return jnp.mean(lrd[idx], axis=1) / jnp.maximum(lrd, 1e-12)


def auroc(scores, is_outlier) -> float:
    """Area under the ROC curve via the rank statistic (host-side)."""
    import numpy as np
    from scipy.stats import rankdata

    scores = np.asarray(scores, dtype=np.float64)
    y = np.asarray(is_outlier, dtype=bool)
    n_pos, n_neg = int(y.sum()), int((~y).sum())
    if n_pos == 0 or n_neg == 0:
        raise ValueError("need both outliers and inliers for AUROC")
    ranks = rankdata(scores)  # average ranks handle ties correctly
    return float((ranks[y].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))
