"""Local Outlier Factor (LOF) scoring on device.

North-star outlier capability (BASELINE.json: "kNN-graph + LOF outlier
scorer ... LOF AUROC on held-out outliers"). Standard LOF (Breunig et al.):

    k-distance(p)   = distance to p's k-th neighbor
    reach_k(p, o)   = max(k-distance(o), d(p, o))
    lrd(p)          = k / sum_o reach_k(p, o)
    LOF(p)          = mean_o lrd(o) / lrd(p)

Scores ≈ 1 for inliers, >> 1 for outliers. Validated against the
scikit-learn oracle in tests (SURVEY §4).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from graphmine_tpu.ops.knn import knn


def lof_scores(
    points: jax.Array, k: int = 20, row_tile: int = 1024, impl: str = "auto",
    sink=None,
) -> jax.Array:
    """LOF score per point, shape ``[N]`` (higher = more outlying).

    Discrete graph features produce many *identical* rows; classic LOF
    degenerates there (k-distance 0 ⇒ lrd → ∞ ⇒ unbounded scores for
    duplicate-adjacent points — the known LOF duplicates problem). Reach
    distances are floored at 1e-3 x the mean positive kNN distance, which
    bounds scores at a meaningful scale and is a no-op on duplicate-free
    data (the sklearn parity test).

    Choosing ``k``: it must exceed the size of any *clustered* anomaly
    group — a batch of anomalies with near-identical features forms its
    own dense region, and with ``k`` below the group size each one's kNN
    neighborhood is just the other anomalies, so they score as inliers
    (measured: 64 injected hubs at 65K vertices swing AUROC 0.49 → 0.91
    going from k=20 to k=100; see ``bench.py --tier lof``).

    ``impl="ivf"`` (r5) routes the kNN through the approximate IVF-flat
    index (:func:`graphmine_tpu.ops.ann.ivf_knn`) — the exact all-pairs
    scorer is AT the top_k roofline (docs/DESIGN.md), so large clouds
    trade a measured sliver of recall for the candidate reduction; the
    lof bench tier records recall and the AUROC delta on real silicon.
    (This wrapper is NOT jitted: the IVF path is host-orchestrated —
    inverted-list construction needs concrete points; the exact paths
    and :func:`lof_from_knn` are jitted internally as before.)

    ``sink``: optional MetricsSink forwarded to :func:`ivf_knn` so its
    pathology-guard fallbacks to the exact path surface as
    ``ivf_fallback`` records (ADVICE r5) — ignored by the exact impls.
    """
    if impl == "ivf":
        from graphmine_tpu.ops.ann import ivf_knn

        d2, idx = ivf_knn(points, k=k, sink=sink)
    else:
        d2, idx = knn(points, k=k, row_tile=row_tile, impl=impl)
    return _lof_from_knn_jit(d2, idx, k)


def lof_from_knn(d2: jax.Array, idx: jax.Array, k: int) -> jax.Array:
    """LOF scores from a kNN result (``[N, k]`` squared distances +
    neighbor indices). Shared by the all-pairs path above and the
    ring-sharded path (:func:`graphmine_tpu.parallel.knn.sharded_lof`) —
    the gathers ``kdist[idx]`` / ``lrd[idx]`` are over ``[N]`` vectors, so
    under GSPMD they cost one small all-gather each."""
    dists = jnp.sqrt(d2)
    finite_pos = (dists > 0) & jnp.isfinite(dists)
    # finite-masked mean (r5): an approximate-kNN source could in
    # principle hand an inf slot; summing it here would turn eps — and
    # through reach/lrd EVERY score — into garbage. ivf_knn guards its
    # own capacity, but the formula must not be poisonable by one slot.
    eps = 1e-3 * jnp.where(finite_pos, dists, 0.0).sum() / jnp.maximum(
        finite_pos.sum(), 1
    )
    kdist = dists[:, -1]
    reach = jnp.maximum(jnp.maximum(kdist[idx], dists), eps)  # [N, k]
    lrd = k / jnp.maximum(reach.sum(axis=1), 1e-12)
    return jnp.mean(lrd[idx], axis=1) / jnp.maximum(lrd, 1e-12)


# lof_scores (a host-dispatching wrapper since the r5 IVF path) jits the
# formula once here; external lof_from_knn callers keep the raw function.
_lof_from_knn_jit = partial(jax.jit, static_argnames=("k",))(lof_from_knn)


def auroc(scores, is_outlier) -> float:
    """Area under the ROC curve via the rank statistic (host-side)."""
    import numpy as np
    from scipy.stats import rankdata

    scores = np.asarray(scores, dtype=np.float64)
    y = np.asarray(is_outlier, dtype=bool)
    n_pos, n_neg = int(y.sum()), int((~y).sum())
    if n_pos == 0 or n_neg == 0:
        raise ValueError("need both outliers and inliers for AUROC")
    ranks = rankdata(scores)  # average ranks handle ties correctly
    return float((ranks[y].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))
