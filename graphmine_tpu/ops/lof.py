"""Local Outlier Factor (LOF) scoring on device.

North-star outlier capability (BASELINE.json: "kNN-graph + LOF outlier
scorer ... LOF AUROC on held-out outliers"). Standard LOF (Breunig et al.):

    k-distance(p)   = distance to p's k-th neighbor
    reach_k(p, o)   = max(k-distance(o), d(p, o))
    lrd(p)          = k / sum_o reach_k(p, o)
    LOF(p)          = mean_o lrd(o) / lrd(p)

Scores ≈ 1 for inliers, >> 1 for outliers. Validated against the
scikit-learn oracle in tests (SURVEY §4).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp

from graphmine_tpu.ops.knn import knn

# Auto-policy crossover (VERDICT r5 weak-item 3 — the selection must cite
# a measurement, not an assumption; same discipline as the r5 kNN flip in
# ops/knn.py). Timed on a real TPU v5e, 8-dim f32 LOF feature clouds,
# k=128, warm caches (round 5, 2026-07-31; docs/DESIGN.md "IVF-flat
# approximate kNN"; the lof bench tier's ``knn_impl_timing``/``ivf_lof``
# details re-measure both ends each capture):
#
#     N=65,536    exact 2.3 s     ivf 4.2 s    exact 1.8x faster
#     N=262,144   exact 27.8 s    ivf 9.0 s    ivf   3.1x faster
#                 recall@128 0.9999, AUROC 0.9895 vs 0.9905 (delta -0.001)
#
# The exact path is AT the top_k/sort roofline (docs/DESIGN.md), so its
# cost grows ~N^2 while IVF's candidate fraction shrinks with N — the
# crossover sits between the two measured points; 2^17 = 131,072 is their
# geometric midpoint, conservative in that every measured IVF win is well
# above it. Override per-process with GRAPHMINE_LOF_IVF_MIN_N (tests pin
# the dispatch by lowering it; an operator who measured a different
# crossover on another part can move it without a code change).
LOF_IVF_MIN_POINTS = 1 << 17


def select_lof_impl(
    n: int, k: int, impl: str = "auto", ivf_min_points: int | None = None,
) -> tuple[str, str]:
    """Resolve the LOF kNN implementation family for an ``[n, F]`` cloud.

    Returns ``(family, reason)`` with ``family`` one of ``"ivf"`` /
    ``"exact"``. ``impl="auto"`` applies the measured crossover above
    (:data:`LOF_IVF_MIN_POINTS`, overridable via ``ivf_min_points`` or
    ``$GRAPHMINE_LOF_IVF_MIN_N``); any explicit ``impl`` is honored
    verbatim. Pure host-side policy — the single owner consulted by
    :func:`lof_scores`, the pipeline planner (``plan_lof``) and the
    sharded scorer (:func:`graphmine_tpu.parallel.knn.sharded_lof`), so
    the dispatch they apply can never diverge.
    """
    if impl not in ("auto", "ivf", "xla", "pallas", "exact"):
        raise ValueError(
            f"unknown LOF impl {impl!r}; use 'auto', 'ivf', 'exact', "
            "'xla' or 'pallas'"
        )
    if impl != "auto":
        family = "ivf" if impl == "ivf" else "exact"
        return family, f"impl={impl!r} requested explicitly"
    ivf_min_points = resolved_ivf_min_points(ivf_min_points)
    if n >= ivf_min_points:
        if 0 < k < n:
            return "ivf", (
                f"n={n} >= crossover {ivf_min_points}: IVF-flat measured "
                "3.1x over exact at 262K points (recall 0.9999, AUROC "
                "delta -0.001)"
            )
        # the reason must state what actually decided — a record claiming
        # "below the crossover" at n=200K would mislead the triage flow
        return "exact", (
            f"k={k} not in (0, n={n}): IVF needs a fillable top-k; the "
            "exact path owns the contract error"
        )
    return "exact", (
        f"n={n} < crossover {ivf_min_points}: exact all-pairs wins below "
        "~131K points (IVF index overheads dominate; measured at 65K)"
    )


def resolved_ivf_min_points(ivf_min_points: int | None = None) -> int:
    """The ACTIVE exact→IVF crossover (env override applied) — the
    threshold provenance every ``impl_selected`` record carries so an
    auto flip is explainable from the JSONL alone (ISSUE 12)."""
    if ivf_min_points is not None:
        return int(ivf_min_points)
    return int(os.environ.get("GRAPHMINE_LOF_IVF_MIN_N", LOF_IVF_MIN_POINTS))


def lof_scores(
    points: jax.Array, k: int = 20, row_tile: int = 1024, impl: str = "auto",
    sink=None, ivf_min_points: int | None = None,
) -> jax.Array:
    """LOF score per point, shape ``[N]`` (higher = more outlying).

    Discrete graph features produce many *identical* rows; classic LOF
    degenerates there (k-distance 0 ⇒ lrd → ∞ ⇒ unbounded scores for
    duplicate-adjacent points — the known LOF duplicates problem). Reach
    distances are floored at 1e-3 x the mean positive kNN distance, which
    bounds scores at a meaningful scale and is a no-op on duplicate-free
    data (the sklearn parity test).

    Choosing ``k``: it must exceed the size of any *clustered* anomaly
    group — a batch of anomalies with near-identical features forms its
    own dense region, and with ``k`` below the group size each one's kNN
    neighborhood is just the other anomalies, so they score as inliers
    (measured: 64 injected hubs at 65K vertices swing AUROC 0.49 → 0.91
    going from k=20 to k=100; see ``bench.py --tier lof``).

    ``impl="auto"`` (r6) is SCALE-AWARE: clouds at or above the measured
    crossover (:data:`LOF_IVF_MIN_POINTS`; provenance table above) route
    through the approximate IVF-flat index
    (:func:`graphmine_tpu.ops.ann.ivf_knn`) — the exact all-pairs scorer
    is AT the top_k roofline (docs/DESIGN.md), so large clouds trade a
    measured sliver of recall (0.9999) for the candidate reduction —
    while smaller clouds keep the exact path, whose own Pallas/XLA choice
    stays :func:`graphmine_tpu.ops.knn.knn`'s measured policy.
    ``impl="ivf"`` forces the index; ``"xla"``/``"pallas"`` force an
    exact path. (This wrapper is NOT jitted: the IVF path is
    host-orchestrated — inverted-list construction needs concrete
    points; the exact paths and :func:`lof_from_knn` are jitted
    internally as before.)

    ``sink``: optional MetricsSink. The resolved choice is emitted as an
    ``impl_selected`` record (op/impl/n/k/reason — joins the span
    timeline, surfaced by ``tools/obs_report.py``), and the IVF path's
    pathology-guard fallbacks to the exact path stay loud as
    ``ivf_fallback`` records (ADVICE r5).
    """
    n = int(points.shape[0])
    family, reason = select_lof_impl(
        n, k, impl=impl, ivf_min_points=ivf_min_points
    )
    if sink is not None:
        from graphmine_tpu.obs.costmodel import lof_cost

        sink.emit(
            "impl_selected", op="lof_knn", impl=family, requested=impl,
            n=n, k=k, reason=reason,
            # the deciding crossover + the model's numbers (ISSUE 12):
            # a policy flip is explainable from the JSONL alone
            thresholds={"lof_ivf_min_points": resolved_ivf_min_points(
                ivf_min_points
            )},
            cost=lof_cost(
                family, n, k, features=int(points.shape[-1])
            ).record(),
        )
    if family == "ivf":
        from graphmine_tpu.ops.ann import ivf_knn

        d2, idx = ivf_knn(points, k=k, sink=sink)
    else:
        # "auto"/"exact" leave the XLA-vs-Pallas choice to knn's own
        # measured policy; explicit "xla"/"pallas" force a kernel
        exact_impl = "auto" if impl in ("auto", "exact") else impl
        d2, idx = knn(points, k=k, row_tile=row_tile, impl=exact_impl)
    return _lof_from_knn_jit(d2, idx, k)


def lof_from_knn(d2: jax.Array, idx: jax.Array, k: int) -> jax.Array:
    """LOF scores from a kNN result (``[N, k]`` squared distances +
    neighbor indices). Shared by the all-pairs path above and the
    ring-sharded path (:func:`graphmine_tpu.parallel.knn.sharded_lof`) —
    the gathers ``kdist[idx]`` / ``lrd[idx]`` are over ``[N]`` vectors, so
    under GSPMD they cost one small all-gather each."""
    dists = jnp.sqrt(d2)
    finite_pos = (dists > 0) & jnp.isfinite(dists)
    # finite-masked mean (r5): an approximate-kNN source could in
    # principle hand an inf slot; summing it here would turn eps — and
    # through reach/lrd EVERY score — into garbage. ivf_knn guards its
    # own capacity, but the formula must not be poisonable by one slot.
    eps = 1e-3 * jnp.where(finite_pos, dists, 0.0).sum() / jnp.maximum(
        finite_pos.sum(), 1
    )
    kdist = dists[:, -1]
    reach = jnp.maximum(jnp.maximum(kdist[idx], dists), eps)  # [N, k]
    lrd = k / jnp.maximum(reach.sum(axis=1), 1e-12)
    return jnp.mean(lrd[idx], axis=1) / jnp.maximum(lrd, 1e-12)


# lof_scores (a host-dispatching wrapper since the r5 IVF path) jits the
# formula once here; external lof_from_knn callers keep the raw function.
_lof_from_knn_jit = partial(jax.jit, static_argnames=("k",))(lof_from_knn)


def auroc(scores, is_outlier) -> float:
    """Area under the ROC curve via the rank statistic (host-side)."""
    import numpy as np
    from scipy.stats import rankdata

    scores = np.asarray(scores, dtype=np.float64)
    y = np.asarray(is_outlier, dtype=bool)
    n_pos, n_neg = int(y.sum()), int((~y).sum())
    if n_pos == 0 or n_neg == 0:
        raise ValueError("need both outliers and inliers for AUROC")
    ranks = rankdata(scores)  # average ranks handle ties correctly
    return float((ranks[y].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))
