"""Spectral vertex embeddings via subspace iteration.

The "feature extraction and model development" capability the reference's
``Overview:4`` names but never builds: each vertex gets a ``d``-dimensional
coordinate from the top nontrivial eigenvectors of the symmetrically
normalized adjacency ``D^{-1/2} A D^{-1/2}`` — the classic spectral
embedding whose coordinates cluster communities geometrically (input to
kNN/LOF, k-means, or any downstream model).

TPU design: orthogonal (subspace) iteration — the block power method.
Each round is one sparse matvec block (gather + ``segment_sum`` over the
message CSR, lane axis flattened into the segment ids: the 2-D form is
the known chained-``segment_sum`` miscompile, docs/DESIGN.md) followed by
a thin QR of the tall-skinny ``[V, d+1]`` block on the MXU. The trivial
``D^{1/2}·1`` eigenvector is computed in closed form and deflated every
round, so all ``d`` returned columns are informative.

Oracle: scipy ``eigsh`` subspace agreement (principal angles) and SBM
planted-block recovery (tests).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph


@partial(jax.jit, static_argnames=("dim", "num_iters"))
def spectral_embedding(
    graph: Graph, dim: int = 8, num_iters: int = 60, seed: int = 0
) -> jax.Array:
    """``[V, dim]`` float32 spectral coordinates (top nontrivial
    eigenvectors of the normalized adjacency, orthonormal columns,
    eigenvalue-ordered). Requires a symmetric graph; isolated vertices
    embed at the origin. Deterministic for a given ``seed``."""
    if not graph.symmetric:
        raise ValueError("spectral_embedding needs symmetric=True "
                         "(the normalized adjacency must be symmetric)")
    v = graph.num_vertices
    if dim + 1 > v:
        raise ValueError(
            f"dim={dim} needs at least dim+1={dim + 1} vertices (have {v}); "
            "lower dim for toy graphs"
        )
    send, recv = graph.msg_send, graph.msg_recv
    b = dim + 1  # extra lane absorbs leakage toward the deflated direction
    deg = jax.ops.segment_sum(
        jnp.ones_like(send, jnp.float32), recv, num_segments=v,
        indices_are_sorted=True,
    )
    inv_sqrt = jnp.where(deg > 0, 1.0 / jnp.sqrt(jnp.maximum(deg, 1.0)), 0.0)
    # closed-form trivial eigenvector of D^{-1/2} A D^{-1/2}: D^{1/2} 1
    triv = jnp.sqrt(jnp.maximum(deg, 0.0))
    triv = triv / jnp.maximum(jnp.sqrt(jnp.sum(triv * triv)), 1e-30)

    lanes = jnp.arange(b, dtype=jnp.int32)
    # int32 segment ids: fine while V * (dim+1) < 2^31 (V ~ 100M at dim 16)
    seg_flat = (recv[:, None] * b + lanes[None, :]).ravel()

    def matvec(x):  # [V, b] -> M @ x with M = D^{-1/2} A D^{-1/2}
        msgs = (x * inv_sqrt[:, None])[send]
        y = jax.ops.segment_sum(
            msgs.ravel(), seg_flat, num_segments=v * b
        ).reshape(v, b)
        return y * inv_sqrt[:, None]

    def matvec_shifted(x):
        # iterate on (M + I)/2, spectrum in [0, 1]: subspace iteration
        # converges to the largest-|λ| directions, and without the shift a
        # bipartite-ish graph's λ ≈ -1 mirror branch would win over the
        # algebraically-largest ones the embedding wants
        return 0.5 * (matvec(x) + x)

    # restrict to the non-isolated subgraph: without this, the shift gives
    # isolated vertices λ_shifted = 1/2, tying them into the top subspace
    active = (deg > 0).astype(jnp.float32)[:, None]

    def deflate(x):
        # true-f32 product: the MXU's default bf16 rounding is enough to
        # perturb the deflation direction across backends (r4 audit class)
        proj = jnp.matmul(triv, x, precision=lax.Precision.HIGHEST)
        return (x - triv[:, None] * proj[None, :]) * active

    x0 = jax.random.normal(jax.random.PRNGKey(seed), (v, b), jnp.float32)

    def body(_, x):
        y = deflate(matvec_shifted(x))
        q, _ = jnp.linalg.qr(y)
        return q

    q = lax.fori_loop(0, num_iters, body, jnp.linalg.qr(deflate(x0))[0])
    # order columns by Rayleigh quotient of the unshifted operator
    lam = jnp.sum(q * matvec(q), axis=0)
    order = jnp.argsort(-lam)
    return q[:, order[:dim]]
