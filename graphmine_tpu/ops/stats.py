"""Whole-graph summary statistics: assortativity, reciprocity, density,
diameter — the one-number descriptors an analyst reaches for first
(NetworkX parity, oracle-tested).

Host/NumPy for the closed-form statistics (they are O(E) reductions over
the edge list, not supersteps); the diameter estimate rides the compiled
BFS machinery.
"""

from __future__ import annotations

import numpy as np

from graphmine_tpu.graph.container import Graph, simple_undirected_edges


def degree_assortativity(graph: Graph) -> float:
    """Pearson correlation of degrees across undirected edges
    (``nx.degree_assortativity_coefficient`` on the simple graph).
    Returns NaN when every vertex has the same degree (zero variance)."""
    a, b = simple_undirected_edges(graph)
    if len(a) == 0:
        return float("nan")
    v = graph.num_vertices
    deg = np.bincount(a, minlength=v) + np.bincount(b, minlength=v)
    x = np.concatenate([deg[a], deg[b]]).astype(np.float64)
    y = np.concatenate([deg[b], deg[a]]).astype(np.float64)
    sx = x.std()
    if sx == 0:
        return float("nan")
    return float(((x - x.mean()) * (y - y.mean())).mean() / (sx * y.std()))


def _directed_codes(graph: Graph, drop_self_loops: bool) -> np.ndarray:
    """Distinct directed edges encoded ``src * V + dst`` (int64)."""
    src = np.asarray(graph.src).astype(np.int64)
    dst = np.asarray(graph.dst).astype(np.int64)
    if drop_self_loops:
        keep = src != dst
        src, dst = src[keep], dst[keep]
    return np.unique(src * graph.num_vertices + dst)


def reciprocity(graph: Graph) -> float:
    """Fraction of directed edges whose reverse also exists
    (``nx.reciprocity``; duplicates collapse, self-loops drop). Raises on
    symmetric graphs — messages already flow both ways there, so the
    question is meaningless (NetworkX raises for undirected too)."""
    if graph.symmetric:
        raise ValueError(
            "reciprocity needs a directed graph (build_graph(symmetric=False))"
        )
    v = graph.num_vertices
    codes = _directed_codes(graph, drop_self_loops=True)
    if len(codes) == 0:
        return float("nan")
    rev = (codes % v) * v + codes // v
    return float(np.isin(codes, rev).mean())


def density(graph: Graph, directed: bool | None = None) -> float:
    """Edge density (``nx.density``: distinct edges — self-loops count —
    over ``V(V-1)`` ordered or unordered pairs)."""
    v = graph.num_vertices
    if v <= 1:
        return 0.0
    if directed is None:
        directed = not graph.symmetric
    if directed:
        e = len(_directed_codes(graph, drop_self_loops=False))
        return e / (v * (v - 1))
    src = np.asarray(graph.src).astype(np.int64)
    dst = np.asarray(graph.dst).astype(np.int64)
    e = len(np.unique(np.minimum(src, dst) * v + np.maximum(src, dst)))
    return 2.0 * e / (v * (v - 1))


def diameter(graph: Graph, exact: bool = False, seed: int = 0) -> int:
    """Longest shortest path in hops over the symmetric graph, ignoring
    unreachable pairs (largest finite eccentricity).

    Default: the double-sweep lower bound — BFS from a random vertex of
    the largest component, then BFS from the farthest vertex found; exact
    on trees and typically tight on real graphs. ``exact=True`` runs BFS
    from every vertex through the batched ``shortest_paths`` tiles —
    ``[V, V]`` distances, so only for validation-scale graphs."""
    from graphmine_tpu.ops.paths import UNREACHABLE, bfs_distances, shortest_paths

    v = graph.num_vertices
    if v == 0:
        return 0
    if exact:
        dist = np.asarray(shortest_paths(
            graph, np.arange(v, dtype=np.int32), direction="both"))
        finite = dist[dist < int(UNREACHABLE)]
        return int(finite.max(initial=0))
    # start inside the largest component, else a sweep from a small or
    # singleton component reports its tiny eccentricity
    from graphmine_tpu.ops.cc import connected_components

    comp = np.asarray(connected_components(graph))
    vals, counts = np.unique(comp, return_counts=True)
    members = np.flatnonzero(comp == vals[counts.argmax()])
    rng = np.random.default_rng(seed)
    start = np.int32(members[rng.integers(0, len(members))])
    d1 = np.asarray(bfs_distances(graph, np.array([start]), direction="both"))
    d1 = np.where(d1 < int(UNREACHABLE), d1, -1)
    far = np.int32(d1.argmax())
    d2 = np.asarray(bfs_distances(graph, np.array([far]), direction="both"))
    d2 = np.where(d2 < int(UNREACHABLE), d2, -1)
    return int(max(d1.max(initial=0), d2.max(initial=0)))
