"""Community census + induced subgraphs — vectorized.

Replaces the reference's driver-side outlier-prep loops
(``Graphframes.py:92-120``): collecting every vertex per community
(O(C·V)) and scanning the full edge table per vertex (O(C·V·E)) become a
handful of segment-sums and boolean masks, all on device, no host loop
over communities (SURVEY §7 hard part 4: masks, never per-community host
loops).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from graphmine_tpu.graph.container import Graph


def community_sizes(labels: jax.Array) -> jax.Array:
    """Vertex count per label value, shape ``[V]`` (0 for unused labels).

    ``sizes[labels]`` gives each vertex its community's size. This is the
    per-community census the reference printed at ``Graphframes.py:120``.
    """
    v = labels.shape[0]
    ones = jnp.ones_like(labels)
    return jax.ops.segment_sum(ones, labels, num_segments=v)


def intra_community_edge_mask(labels: jax.Array, graph: Graph) -> jax.Array:
    """Boolean ``[E]``: edge endpoints share a community.

    The vectorized form of the reference's per-vertex edge scan
    (``Graphframes.py:109-113``): the induced subgraph of every community,
    all at once.
    """
    return labels[graph.src] == labels[graph.dst]


def community_edge_counts(labels: jax.Array, graph: Graph) -> jax.Array:
    """Intra-community edge count per label value, shape ``[V]``."""
    v = labels.shape[0]
    mask = intra_community_edge_mask(labels, graph)
    return jax.ops.segment_sum(
        mask.astype(jnp.int32), labels[graph.src], num_segments=v
    )


def census_table(labels: jax.Array, graph: Graph):
    """Host-friendly summary: (label values, vertex counts, intra-edge counts),
    dense arrays over present labels only — the structured replacement for the
    reference's print-per-community loop (``Graphframes.py:100-120``).

    Host graphs (``build_graph(to_device=False)``, r3) compute with NumPy
    bincounts — no O(E) device transfer for graphs the memory planner kept
    off-device; identical results (tested)."""
    import numpy as np

    labels_np = np.asarray(labels)
    if isinstance(graph.src, np.ndarray):
        v = labels_np.shape[0]
        sizes = np.bincount(labels_np, minlength=v)
        src = graph.src
        mask = labels_np[src] == labels_np[graph.dst]
        edges = np.bincount(labels_np[src[mask]], minlength=v)
    else:
        sizes = np.asarray(community_sizes(labels))
        edges = np.asarray(community_edge_counts(labels, graph))
    present = np.flatnonzero(sizes > 0)
    return present, sizes[present], edges[present]
