"""Motif finding — the GraphFrames ``find()`` pattern DSL.

Engine-surface parity with ``GraphFrame.find`` (exposed on the object built
at ``Graphframes.py:78``; the reference script never calls it). Patterns are
the GraphFrames structural-motif language:

    "(a)-[e]->(b); (b)-[e2]->(c); !(c)-[]->(a)"

- ``(a)-[e]->(b)``: an edge bound to name ``e`` from vertex ``a`` to ``b``;
- names may be omitted: ``(a)-[]->(b)`` (anonymous edge), ``(a)-[e]->()``
  (anonymous vertex) — anonymous elements constrain the match but produce
  no output column;
- ``!(...)``: negated term — no such edge may exist. Negated terms must use
  an anonymous edge, and their vertices must be bound by positive terms
  (GraphFrames' own restrictions).

Like GraphFrames, matching is relational, not isomorphic: distinct names may
bind to the same vertex, duplicate edge rows yield duplicate matches, and
each term is a join against the edge table.

Design: motif search is a *driver-side relational* operation, not a
superstep kernel — the TPU-native split keeps it on host as vectorized
NumPy sort/searchsorted joins (no per-row Python, the anti-pattern of the
reference's O(C·V·E) driver loops at ``Graphframes.py:100-118``), while
supersteps stay on device. Joins expand left-to-right through the pattern;
negated terms are vectorized anti-joins on int64 edge keys.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

import numpy as np

from graphmine_tpu.graph.container import Graph

_TERM = re.compile(r"^(!?)\s*\(\s*(\w*)\s*\)\s*-\s*\[\s*(\w*)\s*\]\s*->\s*\(\s*(\w*)\s*\)$")


@dataclass(frozen=True)
class _Term:
    negated: bool
    a: str  # source vertex name ('' = anonymous)
    e: str  # edge name ('' = anonymous)
    b: str  # destination vertex name ('' = anonymous)


def parse_pattern(pattern: str) -> list[_Term]:
    """Parse the motif DSL into terms; raises ``ValueError`` on bad syntax."""
    terms = []
    for raw in pattern.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        m = _TERM.match(raw)
        if m is None:
            raise ValueError(f"cannot parse motif term {raw!r}")
        neg, a, e, b = m.groups()
        if neg and e:
            raise ValueError(f"negated term {raw!r} cannot name its edge")
        terms.append(_Term(negated=bool(neg), a=a, e=e, b=b))
    if not terms:
        raise ValueError("empty motif pattern")
    vertex_names = {n for t in terms for n in (t.a, t.b) if n}
    edge_names = [t.e for t in terms if t.e]
    if vertex_names & set(edge_names):
        raise ValueError(
            f"names used for both a vertex and an edge: {vertex_names & set(edge_names)}"
        )
    if len(edge_names) != len(set(edge_names)):
        raise ValueError("each edge name may appear in only one term")
    pos_names = {n for t in terms if not t.negated for n in (t.a, t.b) if n}
    for t in terms:
        if t.negated:
            for n in (t.a, t.b):
                if n and n not in pos_names:
                    raise ValueError(
                        f"vertex {n!r} appears only in a negated term; bind it "
                        "in a positive term first"
                    )
    return terms


@dataclass
class MotifResult:
    """Match table: one row per motif occurrence.

    ``vertices[name]`` — int32 vertex ids ``[N]`` per named vertex;
    ``edges[name]`` — int64 edge-row indices ``[N]`` into ``graph.src/dst``
    per named edge.
    """

    vertices: dict
    edges: dict
    num_matches: int

    def __len__(self) -> int:
        return self.num_matches

    def column(self, name: str) -> np.ndarray:
        if name in self.vertices:
            return self.vertices[name]
        if name in self.edges:
            return self.edges[name]
        raise KeyError(name)


class _Joiner:
    """Edge table indexed for vectorized expand-joins."""

    def __init__(self, graph: Graph):
        self.src = np.asarray(graph.src, dtype=np.int64)
        self.dst = np.asarray(graph.dst, dtype=np.int64)
        self.v = graph.num_vertices
        self.e = len(self.src)
        # Sort indexes and the unique-edge-key table cost O(E log E) each;
        # built on first use — src-chained patterns never pay for the dst
        # index, and only negated terms need edge_keys.
        self._by_src = self._src_sorted = None
        self._by_dst = self._dst_sorted = None
        self._edge_keys = None

    @property
    def by_src(self):
        if self._by_src is None:
            self._by_src = np.argsort(self.src, kind="stable")
            self._src_sorted = self.src[self._by_src]
        return self._by_src

    @property
    def src_sorted(self):
        self.by_src
        return self._src_sorted

    @property
    def by_dst(self):
        if self._by_dst is None:
            self._by_dst = np.argsort(self.dst, kind="stable")
            self._dst_sorted = self.dst[self._by_dst]
        return self._by_dst

    @property
    def dst_sorted(self):
        self.by_dst
        return self._dst_sorted

    @property
    def edge_keys(self):
        if self._edge_keys is None:
            self._edge_keys = np.unique(self.src * self.v + self.dst)
        return self._edge_keys

    def expand(self, bound: np.ndarray, by: str):
        """For each bound endpoint value, enumerate matching edge rows.

        Returns ``(row_idx, edge_idx)``: ``row_idx`` repeats each input row
        once per matching edge; ``edge_idx`` is the matched edge row.
        """
        sorted_vals = self.src_sorted if by == "src" else self.dst_sorted
        order = self.by_src if by == "src" else self.by_dst
        start = np.searchsorted(sorted_vals, bound, side="left")
        stop = np.searchsorted(sorted_vals, bound, side="right")
        counts = stop - start
        row_idx = np.repeat(np.arange(len(bound)), counts)
        total = int(counts.sum())
        offsets = np.arange(total, dtype=np.int64) - np.repeat(
            np.cumsum(counts) - counts, counts
        )
        edge_idx = order[np.repeat(start, counts) + offsets]
        return row_idx, edge_idx

    def has_edge(self, a_vals: np.ndarray, b_vals: np.ndarray) -> np.ndarray:
        return np.isin(a_vals * self.v + b_vals, self.edge_keys, assume_unique=False)


def find(graph: Graph, pattern: str) -> MotifResult:
    """Find all occurrences of a structural motif (GraphFrames ``find``)."""
    terms = parse_pattern(pattern)
    jn = _Joiner(graph)

    # Binding table: columns -> int64 arrays of equal length. Vertex columns
    # hold vertex ids, edge columns edge-row indices. Anonymous elements get
    # fresh internal names (constrain the join, dropped from the output).
    cols: dict[str, np.ndarray] = {}
    n_rows = 1  # empty-pattern convention: one all-unbound row
    fresh = 0

    def take(idx):
        nonlocal cols
        cols = {k: v[idx] for k, v in cols.items()}

    for t in terms:
        if t.negated:
            continue  # applied after all positive terms
        a, b = t.a, t.b
        if not a:
            a, fresh = f"__anon{fresh}", fresh + 1
        if not b:
            b, fresh = f"__anon{fresh}", fresh + 1
        a_bound, b_bound = a in cols, b in cols
        if not cols:
            # first term: bind directly to the edge table
            edge_idx = np.arange(jn.e, dtype=np.int64)
            cols[a] = jn.src.copy()
            if b == a:
                keep = jn.dst == jn.src
                take(np.nonzero(keep)[0])
                edge_idx = edge_idx[keep]
            else:
                cols[b] = jn.dst.copy()
            if t.e:
                cols[t.e] = edge_idx
            n_rows = len(cols[a])
            continue
        if a_bound:
            row_idx, edge_idx = jn.expand(cols[a], by="src")
            take(row_idx)
            if b_bound or b == a:
                keep = jn.dst[edge_idx] == cols[b if b_bound else a]
                take(np.nonzero(keep)[0])
                edge_idx = edge_idx[keep]
            else:
                cols[b] = jn.dst[edge_idx]
        elif b_bound:
            row_idx, edge_idx = jn.expand(cols[b], by="dst")
            take(row_idx)
            cols[a] = jn.src[edge_idx]
        else:
            # cross join: every current row x every edge
            row_idx = np.repeat(np.arange(n_rows), jn.e)
            edge_idx = np.tile(np.arange(jn.e, dtype=np.int64), n_rows)
            take(row_idx)
            cols[a] = jn.src[edge_idx]
            if b == a:
                keep = jn.dst[edge_idx] == cols[a]
                take(np.nonzero(keep)[0])
                edge_idx = edge_idx[keep]
            else:
                cols[b] = jn.dst[edge_idx]
        if t.e:
            cols[t.e] = edge_idx
        n_rows = len(next(iter(cols.values())))

    for t in terms:
        if not t.negated:
            continue
        if n_rows == 0:
            break
        a_vals = cols[t.a] if t.a else None
        b_vals = cols[t.b] if t.b else None
        if a_vals is None and b_vals is None:
            # "no edge at all exists" — degenerate but well-defined
            exists = jn.e > 0
            keep = np.zeros(n_rows, bool) if exists else np.ones(n_rows, bool)
        elif a_vals is None:
            # no edge into b from anywhere
            keep = ~np.isin(b_vals, jn.dst)
        elif b_vals is None:
            keep = ~np.isin(a_vals, jn.src)
        else:
            keep = ~jn.has_edge(a_vals, b_vals)
        take(np.nonzero(keep)[0])
        # all-negated patterns have no binding columns; the row count is
        # carried by the mask itself
        n_rows = len(next(iter(cols.values()))) if cols else int(keep.sum())

    edge_names = {t.e for t in terms if t.e}
    vertices = {
        k: v.astype(np.int32)
        for k, v in cols.items()
        if not k.startswith("__anon") and k not in edge_names
    }
    edges = {k: cols[k] for k in edge_names if k in cols}
    return MotifResult(vertices=vertices, edges=edges, num_matches=n_rows)
