"""Segment reductions beyond sum/max — notably *segment mode*.

The one true compute kernel of the reference pipeline is GraphX's Pregel LPA
superstep (``Graphframes.py:81``): each vertex adopts the most frequent label
among its incoming messages. "Most frequent per segment" has no native XLA
segment op; this module implements it with static shapes and pure int32
arithmetic (TPU-friendly, no x64):

  sort (segment, value) pairs  →  run-length rank via a max-scan  →
  segment_max of ranks (max multiplicity)  →  segment_min over the
  max-multiplicity candidates (deterministic smallest-value tie-break).

O(M log M) compute, O(M) memory, fully jit-able, no data-dependent shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_INT32_MAX = jnp.iinfo(jnp.int32).max


def segment_mode(
    segment_ids: jax.Array,
    values: jax.Array,
    num_segments: int,
    indices_are_sorted: bool = False,
    weights: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Most frequent ``value`` per segment; ties break toward the smallest.

    Out-of-range segment ids (e.g. ``num_segments`` used as a padding
    sentinel) are dropped. Empty segments yield ``(INT32_MAX, 0)``.

    ``weights``: optional non-negative per-element weights — the winner
    becomes the value with the largest weight *sum* per segment
    (unweighted = all-ones weights; the weighted LPA semantics).

    Returns ``(mode, count)`` with shapes ``[num_segments]``: the winning
    value and its multiplicity (weight sum, float32, when weighted).

    Note on parity: GraphX's tie-break is implementation-defined (hash-map
    iteration order), so golden comparisons against GraphFrames must compare
    community *partitions*, not raw label values (see SURVEY §6).
    """
    del indices_are_sorted  # the lexicographic sort below handles both cases
    segment_ids = segment_ids.astype(jnp.int32)
    values = values.astype(jnp.int32)
    if weights is not None:
        return _segment_mode_weighted(
            segment_ids, values, weights.astype(jnp.float32), num_segments
        )
    seg_s, val_s = lax.sort((segment_ids, values), num_keys=2)
    m = seg_s.shape[0]
    pos = jnp.arange(m, dtype=jnp.int32)
    new_run = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), (seg_s[1:] != seg_s[:-1]) | (val_s[1:] != val_s[:-1])]
    )
    # Index of each element's run start, via max-scan of start positions.
    # lax.cummax, not associative_scan: the generic scan unrolls into log(M)
    # irregular slice/concat stages that take minutes of TPU compile time at
    # M ~ 10^7; cummax lowers to XLA's native cumulative op (~9x faster
    # compile, same result).
    run_start = lax.cummax(jnp.where(new_run, pos, -1))
    rank = pos - run_start  # 0-based multiplicity-1 within the run
    best_rank = jax.ops.segment_max(
        rank, seg_s, num_segments=num_segments, indices_are_sorted=True
    )
    # Candidates: elements sitting at the maximal rank of their segment
    # (the last element of every maximal-multiplicity run).
    is_cand = rank == best_rank[jnp.clip(seg_s, 0, num_segments - 1)]
    is_cand &= seg_s < num_segments
    cand_val = jnp.where(is_cand, val_s, _INT32_MAX)
    mode = jax.ops.segment_min(
        cand_val, seg_s, num_segments=num_segments, indices_are_sorted=True
    )
    count = jnp.maximum(best_rank + 1, 0)
    return mode, count


def _segment_mode_weighted(segment_ids, values, weights, num_segments):
    """Weighted variant: argmax of per-(segment, value) weight sums, ties
    toward the smallest value. Same sort machinery; the run multiplicity
    becomes the run's weight sum, accumulated *per run* with segment_sum —
    never as differences of a global cumsum, whose float32 quantization at
    M >~ 2^24 elements would corrupt small sums (measured)."""
    seg_s, val_s, w_s = lax.sort((segment_ids, values, weights), num_keys=2)
    m = seg_s.shape[0]
    new_run = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), (seg_s[1:] != seg_s[:-1]) | (val_s[1:] != val_s[:-1])]
    )
    run_id = jnp.cumsum(new_run.astype(jnp.int32)) - 1
    run_total = jax.ops.segment_sum(
        w_s, run_id, num_segments=m, indices_are_sorted=True
    )[run_id]
    best_w = jax.ops.segment_max(
        jnp.where(seg_s < num_segments, run_total, -jnp.inf),
        seg_s, num_segments=num_segments, indices_are_sorted=True,
    )
    # every element of a winning run is a candidate (same value per run)
    is_cand = run_total == best_w[jnp.clip(seg_s, 0, num_segments - 1)]
    is_cand &= seg_s < num_segments
    cand_val = jnp.where(is_cand, val_s, _INT32_MAX)
    mode = jax.ops.segment_min(
        cand_val, seg_s, num_segments=num_segments, indices_are_sorted=True
    )
    return mode, jnp.maximum(best_w, 0.0)
