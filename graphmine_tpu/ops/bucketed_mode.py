"""Degree-bucketed dense segment-mode — the fast path of the LPA superstep.

The sort-based :func:`graphmine_tpu.ops.segment.segment_mode` pays one
global O(M log M) two-key sort per superstep — at 10^7+ messages the sort
dominates LPA wall-clock. This module exploits two static facts about the
message CSR (``graph.msg_ptr`` — built once on host, ``container.py``):

1. each vertex's messages are a *contiguous* slice, and
2. the slice lengths (degrees) are known at trace time.

So vertices are **bucketed by degree class** (power-of-two widths), and
each bucket's messages are gathered into a dense ``[n_b, w_b]`` matrix and
sorted **row-wise** — many independent tiny sorts along the minor axis
(XLA lowers these to vectorized bitonic networks) instead of one giant
global sort. Power-law skew (SURVEY §7 hard part 3) is exactly what the
bucketing absorbs: the million degree≤8 vertices ride in width-8 rows
while the one degree-100K hub gets its own wide row; padding never exceeds
2× and the global sort's log(M) factor drops to log(w) per element.

The plan (bucket membership + padded gather indices) is host-precomputed
from the static CSR once per graph and reused across all supersteps and
runs — the same amortization the message CSR itself gets.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph

_SENTINEL = jnp.iinfo(jnp.int32).max
_MIN_WIDTH = 8


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class BucketedModePlan:
    """Static gather plan: per degree-class vertex ids + message indices.

    ``vertex_ids[b]``: int32 ``[n_b]`` — vertices in bucket ``b``.
    ``msg_idx[b]``: int32 ``[n_b, w_b]`` — indices into the message array,
    padded with ``num_messages`` (gathers a sentinel label slot).
    """

    vertex_ids: tuple
    msg_idx: tuple
    num_vertices: int = dataclasses.field(metadata=dict(static=True))
    num_messages: int = dataclasses.field(metadata=dict(static=True))

    @classmethod
    def from_graph(cls, graph: Graph) -> "BucketedModePlan":
        """Build from a device-resident graph. Note: fetches ``msg_ptr`` to
        host; when the original edge arrays are still on host, prefer
        :meth:`from_edges` (no device round-trip)."""
        return cls.from_ptr(np.asarray(graph.msg_ptr), graph.num_vertices)

    @classmethod
    def from_edges(
        cls, src, dst, num_vertices: int, symmetric: bool = True
    ) -> "BucketedModePlan":
        """Host-pure construction from endpoint arrays — same CSR layout as
        :func:`graphmine_tpu.graph.container.build_graph` (messages grouped
        by receiver, stable order)."""
        from graphmine_tpu.graph.container import message_ptr

        return cls.from_ptr(message_ptr(src, dst, num_vertices, symmetric), num_vertices)

    @classmethod
    def from_ptr(cls, ptr: np.ndarray, num_vertices: int) -> "BucketedModePlan":
        ptr = np.asarray(ptr).astype(np.int64)
        deg = ptr[1:] - ptr[:-1]
        m = int(ptr[-1])
        if m >= np.iinfo(np.int32).max:
            raise ValueError("message count exceeds int32; shard the build")
        classes = np.maximum(
            np.ceil(np.log2(np.maximum(deg, 1))).astype(np.int64),
            int(np.log2(_MIN_WIDTH)),
        )
        vertex_ids, msg_idx = [], []
        for c in np.unique(classes[deg > 0]):
            ids = np.nonzero((classes == c) & (deg > 0))[0].astype(np.int32)
            w = 1 << int(c)
            offs = np.arange(w, dtype=np.int64)[None, :]
            idx = ptr[ids][:, None] + offs
            valid = offs < deg[ids][:, None]
            idx = np.where(valid, idx, m).astype(np.int32)
            vertex_ids.append(jnp.asarray(ids))
            msg_idx.append(jnp.asarray(idx))
        return cls(
            vertex_ids=tuple(vertex_ids),
            msg_idx=tuple(msg_idx),
            num_vertices=num_vertices,
            num_messages=m,
        )


def _rowwise_mode(lbl: jax.Array) -> jax.Array:
    """Mode of each row of a ``[n, w]`` int32 matrix; sentinel entries
    ignored; ties break toward the smallest value. Rows must contain at
    least one non-sentinel entry."""
    s = jnp.sort(lbl, axis=1)
    w = s.shape[1]
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    new_run = jnp.concatenate(
        [jnp.ones((s.shape[0], 1), jnp.bool_), s[:, 1:] != s[:, :-1]], axis=1
    )
    run_start = lax.cummax(jnp.where(new_run, pos, -1), axis=1)
    rank = pos - run_start
    rank = jnp.where(s == _SENTINEL, -1, rank)
    best = rank.max(axis=1)
    cand = jnp.where(rank == best[:, None], s, _SENTINEL)
    return cand.min(axis=1)


def bucketed_mode(plan: BucketedModePlan, messages: jax.Array, fallback: jax.Array):
    """Per-vertex mode of ``messages`` under the plan's CSR layout.

    ``messages``: int32 ``[M]`` in message-CSR order (``labels[msg_send]``).
    ``fallback``: int32 ``[V]`` — value for vertices with no messages
    (LPA: keep the old label). Returns int32 ``[V]``.
    """
    if messages.shape[0] != plan.num_messages or fallback.shape[0] != plan.num_vertices:
        raise ValueError(
            f"plan built for M={plan.num_messages}, V={plan.num_vertices} but got "
            f"M={messages.shape[0]}, V={fallback.shape[0]} — plan/graph mismatch"
        )
    msgs_pad = jnp.concatenate(
        [messages.astype(jnp.int32), jnp.full((1,), _SENTINEL, jnp.int32)]
    )
    out = fallback.astype(jnp.int32)
    for ids, idx in zip(plan.vertex_ids, plan.msg_idx):
        out = out.at[ids].set(_rowwise_mode(msgs_pad[idx]))
    return out


def lpa_superstep_bucketed(
    labels: jax.Array, graph: Graph, plan: BucketedModePlan
) -> jax.Array:
    """One LPA superstep via the bucketed plan — semantics identical to
    :func:`graphmine_tpu.ops.lpa.lpa_superstep` (asserted by tests)."""
    msg = labels[graph.msg_send]
    return bucketed_mode(plan, msg, labels)
