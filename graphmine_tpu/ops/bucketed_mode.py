"""Degree-bucketed dense segment-mode — the fast path of the LPA superstep.

The sort-based :func:`graphmine_tpu.ops.segment.segment_mode` pays one
global O(M log M) two-key sort per superstep — at 10^7+ messages the sort
dominates LPA wall-clock. This module exploits two static facts about the
message CSR (``graph.msg_ptr`` — built once on host, ``container.py``):

1. each vertex's messages are a *contiguous* slice, and
2. the slice lengths (degrees) are known at trace time.

So vertices are **bucketed by degree class** and each bucket's messages
are gathered into a dense ``[n_b, w_b]`` matrix whose row-wise mode is
computed with the cheapest method for its width. Measured on TPU v5e, the
superstep is **gather-latency-bound** (~125M gathered elements/s; the mode
arithmetic is ~10x cheaper), so the design minimizes *gathered slots*:

- width classes step by 1.10x (r4; exact widths through degree 20),
  capping row padding at 10% — the r1-r3 1.5x ladder allowed 33%, and
  tightening it moved the gather-bound chip rate +15% on real v5e;
- degree 1 and 2 get exact sentinel-free widths (copy / elementwise-min —
  a two-message mode is ``min``: equal -> that label, tie -> smallest);
- widths <= 32 use an O(w^2) pairwise-equality count (pure VPU compare+add,
  no sort compile), wider buckets the bitonic row sort + run-length scan;
- mega-hubs (degree > 2048) skip dense rows entirely: their neighbor
  labels scatter-add into a per-hub histogram over the label space and
  ``argmax`` picks the mode (first-max = smallest label, matching the
  tie rule). This caps both padding and the widest sort compiles.

Power-law skew (SURVEY §7 hard part 3) is exactly what this absorbs: the
million degree<=8 vertices ride in narrow rows while a degree-100K hub
becomes one histogram pass. The plan (bucket membership + padded gather
indices) is host-precomputed from the static CSR once per graph and reused
across all supersteps and runs — the same amortization the message CSR
itself gets.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph

_SENTINEL = jnp.iinfo(jnp.int32).max

# 1.10x-step width ladder (r4): padding <= 10% per row. The r1-r3 1.5x
# ladder capped padding at 33% and measured 2.374 gathered slots/edge on
# the bench graph; at 1.10x that drops to ~2.08, and since the superstep
# is gather-bound the chip rate moved 54.2 -> 62.6M edges/s/chip on real
# v5e (+15%, ladder experiment r4; 1.08x gained only ~1% more while the
# host plan build kept growing — the kernel is AT the ~130M slots/s
# measured gather roofline from here). Degrees 1-20 get exact widths
# (zero padding where most power-law vertices live). Degrees beyond the
# ladder (fused plans only) go to the histogram path; non-fused plans
# extend the ladder as the max degree needs.
_WIDTHS = (1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18,
           19, 20, 22, 24, 26, 28, 30, 33, 36, 39, 42, 46, 50, 55, 60, 66,
           72, 79, 86, 94, 103, 113, 124, 136, 149, 163, 179, 196, 215,
           236, 259, 284, 312, 343, 377, 414, 455, 500, 550, 605, 665,
           731, 804, 884, 972, 1069, 1175, 1292, 1421, 1563, 1719, 1890,
           2048)
_PAIRWISE_MAX_W = 32      # <=32: O(w^2) pairwise mode; >32: row sort
_HIST_MIN_DEG = 2048      # fused plans: degree above this -> histogram mode
_HIST_BUDGET = 1 << 26    # max total int32 entries across all histograms


def _extend_widths(max_deg: int) -> np.ndarray:
    """The width ladder, extended by 1.5x steps beyond its 2048 cap to
    cover ``max_deg`` (coarser out there on purpose: degrees past the
    histogram threshold are few, so padding on their rows is cheap while
    every extra wide class is another sort network to compile)."""
    ws = list(_WIDTHS)
    while ws[-1] < max_deg:
        ws.append(ws[-1] + ws[-1] // 2)
    return np.asarray(ws, dtype=np.int64)


@partial(jax.jit, static_argnames=("w", "fill"))
def _gather_rows_device(send, starts, degs, w: int, fill: int):
    """Device-side [n, w] bucket-matrix construction — same output as
    :func:`_class_rows` with ``values=send``, but the big gather runs on
    the accelerator against the already-resident ``[M]`` sender array, so
    the host never materializes (or transfers) the padded matrices."""
    offs = jnp.arange(w, dtype=jnp.int32)[None, :]
    idx = starts[:, None] + offs
    valid = offs < degs[:, None]
    safe = jnp.minimum(idx, send.shape[0] - 1)
    return jnp.where(valid, send[safe].astype(jnp.int32), fill)


def _class_rows(ptr, deg, eligible, classes, c, w, values, fill, num_values,
                out_dtype=np.int32, weight_values=None):
    """Rows and padded [n, w] gather matrix for one width class (host).

    The single source of truth for bucket-row construction, shared by
    :meth:`BucketedModePlan.from_ptr` and the sharded plan builder
    (``parallel/sharded.py``) so the two stay semantically identical.
    ``values=None`` emits message *indices* (non-fused plans); otherwise
    ``values`` is gathered (fused plans: sender ids). Padding slots get
    ``fill``. ``weight_values``: optional per-message weights gathered
    through the SAME idx/valid in the same pass (padding 0) — returns a
    third float32 matrix, avoiding a second full construction.
    """
    rows = np.nonzero((classes == c) & eligible)[0]
    offs = np.arange(w, dtype=np.int64)[None, :]
    idx = ptr[rows][:, None] + offs
    valid = offs < deg[rows][:, None]
    safe = np.minimum(idx, max(num_values - 1, 0))
    if values is None:
        mat = np.where(valid, idx, fill)
    else:
        mat = np.where(valid, values[safe], fill)
    if weight_values is None:
        return rows, mat.astype(out_dtype)
    wmat = np.where(valid, weight_values[safe], 0.0).astype(np.float32)
    return rows, mat.astype(out_dtype), wmat


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class BucketedModePlan:
    """Static gather plan: per degree-class vertex ids + message indices.

    ``vertex_ids[b]``: int32 ``[n_b]`` — vertices in bucket ``b``.
    ``msg_idx[b]``: int32 ``[n_b, w_b]`` — indices into the message array,
    padded with ``num_messages`` (gathers a sentinel label slot). ``None``
    on fused plans (``send_idx`` replaces it; halves plan HBM).
    ``send_idx[b]``: optional int32 ``[n_b, w_b]`` — the *sender vertex id*
    behind each slot (padding = ``num_vertices``). When present, the LPA
    superstep gathers straight from the label vector — one fused gather
    instead of materializing the [M] message array and re-gathering it.
    """

    vertex_ids: tuple
    msg_idx: tuple | None
    num_vertices: int = dataclasses.field(metadata=dict(static=True))
    num_messages: int = dataclasses.field(metadata=dict(static=True))
    send_idx: tuple | None = None
    # Histogram path (fused plans, degree > _HIST_MIN_DEG): exact (unpadded)
    # sender ids of all hub messages, the owning hub's row offset (row * V)
    # per message, and the hub vertex ids. None when no hub qualifies.
    hist_vertex_ids: jax.Array | None = None
    hist_send: jax.Array | None = None
    hist_row_offset: jax.Array | None = None
    # Weighted-mode payload (built when the graph carries msg_weight):
    # per-class float32 [n_b, w_b] weights aligned slot-for-slot with
    # send_idx/msg_idx (padding = 0), plus the hub messages' weights.
    weight_mat: tuple | None = None
    hist_weight: jax.Array | None = None

    @classmethod
    def from_graph(cls, graph: Graph, with_send: bool = False) -> "BucketedModePlan":
        """Build from a device-resident graph. Note: fetches ``msg_ptr``
        (and ``msg_send`` when ``with_send``) to host; when the original
        edge arrays are still on host, prefer :meth:`from_edges` (no device
        round-trip, fused-gather plan included)."""
        send = np.asarray(graph.msg_send) if with_send else None
        w = None if graph.msg_weight is None else np.asarray(graph.msg_weight)
        return cls.from_ptr(
            np.asarray(graph.msg_ptr), graph.num_vertices, send,
            weights_sorted=w,
        )

    @classmethod
    def from_edges(
        cls, src, dst, num_vertices: int, symmetric: bool = True
    ) -> "BucketedModePlan":
        """Host-pure construction from endpoint arrays — same CSR layout as
        :func:`graphmine_tpu.graph.container.build_graph` (messages grouped
        by receiver, stable order). Includes the fused-gather ``send_idx``
        plan."""
        from graphmine_tpu.graph.container import _message_csr

        src = np.asarray(src, dtype=np.int32)
        dst = np.asarray(dst, dtype=np.int32)
        if src.shape != dst.shape or src.ndim != 1:
            raise ValueError("src/dst must be equal-length 1-D arrays")
        ptr, _, send_sorted, _ = _message_csr(src, dst, num_vertices, symmetric)
        return cls.from_ptr(ptr, num_vertices, send_sorted)

    @classmethod
    def from_ptr(
        cls, ptr: np.ndarray, num_vertices: int,
        send_sorted: np.ndarray | None = None,
        send_device: "jax.Array | None" = None,
        weights_sorted: np.ndarray | None = None,
    ) -> "BucketedModePlan":
        """``send_device``: the device-resident ``[M]`` sender array (e.g.
        ``graph.msg_send``). When given, bucket matrices and hub histogram
        inputs are built on the accelerator — only ``[n_b]`` row starts and
        degrees cross the host boundary instead of the ~2.5E padded plan
        entries. Bit-identical to the host path.

        ``weights_sorted``: optional float [M] per-message weights in the
        same CSR order; builds the weighted-mode payload (host path only).
        """
        if weights_sorted is not None and send_device is not None:
            raise ValueError(
                "weighted plans are host-built; pass send_sorted, not "
                "send_device"
            )
        ptr = np.asarray(ptr).astype(np.int64)
        deg = ptr[1:] - ptr[:-1]
        m = int(ptr[-1])
        if m >= np.iinfo(np.int32).max:
            raise ValueError("message count exceeds int32; shard the build")

        # Mega-hubs -> histogram path (fused plans only: it needs messages
        # to be labels in [0, V)). Budget-capped so the [n_hist, V] count
        # matrix stays bounded; overflow hubs fall back to sort rows.
        hist_mask = np.zeros(len(deg), dtype=bool)
        if send_sorted is not None and num_vertices > 0:
            allowed = max(_HIST_BUDGET // max(num_vertices, 1), 0)
            cand = np.nonzero(deg > _HIST_MIN_DEG)[0]
            if len(cand) > allowed:
                cand = cand[np.argsort(deg[cand], kind="stable")[::-1][:allowed]]
            hist_mask[cand] = True

        widths = _extend_widths(int(deg[~hist_mask].max(initial=1)))
        classes = np.searchsorted(widths, np.maximum(deg, 1))
        vertex_ids, msg_idx, send_idx, weight_mat = [], [], [], []
        bucketed = (deg > 0) & ~hist_mask
        for c in np.unique(classes[bucketed]):
            # Fused plans carry only sender-id matrices — msg_idx would
            # double plan HBM and never be read.
            if send_device is not None and send_sorted is not None:
                rows = np.nonzero((classes == c) & bucketed)[0]
                mat = _gather_rows_device(
                    send_device,
                    jnp.asarray(ptr[rows].astype(np.int32)),
                    jnp.asarray(deg[rows].astype(np.int32)),
                    int(widths[c]), num_vertices,
                )
                ids = rows
            elif weights_sorted is not None:
                ids, mat, wmat = _class_rows(
                    ptr, deg, bucketed, classes, c, int(widths[c]),
                    send_sorted, num_vertices if send_sorted is not None else m, m,
                    weight_values=np.asarray(weights_sorted, np.float32),
                )
                mat = jnp.asarray(mat)
                weight_mat.append(jnp.asarray(wmat))
            else:
                ids, mat = _class_rows(
                    ptr, deg, bucketed, classes, c, int(widths[c]),
                    send_sorted, num_vertices if send_sorted is not None else m, m,
                )
                mat = jnp.asarray(mat)
            vertex_ids.append(jnp.asarray(ids.astype(np.int32)))
            (msg_idx if send_sorted is None else send_idx).append(mat)

        hist_vertex_ids = hist_send = hist_row_offset = hist_weight = None
        if hist_mask.any():
            hubs = np.nonzero(hist_mask)[0]
            rows = np.repeat(np.arange(len(hubs), dtype=np.int64), deg[hubs])
            assert len(hubs) * num_vertices < np.iinfo(np.int32).max
            hist_vertex_ids = jnp.asarray(hubs.astype(np.int32))
            if send_device is not None:
                # Hub messages are contiguous CSR spans — device slices, no
                # host gather or transfer of the hub message payload.
                hist_send = jnp.concatenate(
                    [send_device[int(ptr[h]):int(ptr[h + 1])] for h in hubs]
                ).astype(jnp.int32)
            else:
                pos = np.concatenate(
                    [np.arange(ptr[h], ptr[h + 1], dtype=np.int64) for h in hubs]
                )
                hist_send = jnp.asarray(send_sorted[pos].astype(np.int32))
                if weights_sorted is not None:
                    hist_weight = jnp.asarray(
                        np.asarray(weights_sorted, np.float32)[pos]
                    )
            hist_row_offset = jnp.asarray((rows * num_vertices).astype(np.int32))

        return cls(
            vertex_ids=tuple(vertex_ids),
            msg_idx=tuple(msg_idx) if send_sorted is None else None,
            num_vertices=num_vertices,
            num_messages=m,
            send_idx=tuple(send_idx) if send_sorted is not None else None,
            hist_vertex_ids=hist_vertex_ids,
            hist_send=hist_send,
            hist_row_offset=hist_row_offset,
            weight_mat=tuple(weight_mat) if weights_sorted is not None else None,
            hist_weight=hist_weight,
        )


def build_graph_and_plan(
    src, dst, num_vertices: int | None = None, symmetric: bool = True,
    use_native: bool = True, edge_weights=None,
):
    """Build the :class:`Graph` and its fused plan from ONE message-CSR
    pass — the pipeline's single-device fast path. Calling
    :func:`~graphmine_tpu.graph.container.build_graph` and
    :meth:`BucketedModePlan.from_edges` separately runs the counting sort
    twice over the same edges; this shares it. ``edge_weights`` builds a
    weighted graph plus the plan's weight payload in the same pass."""
    from graphmine_tpu.graph.container import (
        _graph_from_csr,
        _message_csr,
        _prepare_edges,
        _prepare_weights,
    )

    src, dst, num_vertices = _prepare_edges(src, dst, num_vertices)
    w = _prepare_weights(edge_weights, src)
    ptr, recv, send, w_sorted = _message_csr(
        src, dst, num_vertices, symmetric, use_native, weights=w
    )
    graph = _graph_from_csr(
        src, dst, ptr, recv, send, num_vertices, symmetric, msg_weight=w_sorted
    )
    # Host plan build by default. A device-side variant exists
    # (from_ptr(send_device=graph.msg_send)) that avoids shipping the
    # ~2.5E padded plan entries over the host boundary, but it costs one
    # XLA compile per width class whose shapes change with every graph —
    # measured a wash warm and far slower cold on the current setup; see
    # docs/DESIGN.md ("Plan construction placement").
    return graph, BucketedModePlan.from_ptr(
        ptr, num_vertices, send, weights_sorted=w_sorted
    )


def _rowwise_mode(lbl: jax.Array) -> jax.Array:
    """Mode of each row of a ``[n, w]`` int32 matrix; sentinel entries
    ignored; ties break toward the smallest value. Rows must contain at
    least one non-sentinel entry."""
    s = jnp.sort(lbl, axis=1)
    w = s.shape[1]
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    new_run = jnp.concatenate(
        [jnp.ones((s.shape[0], 1), jnp.bool_), s[:, 1:] != s[:, :-1]], axis=1
    )
    run_start = lax.cummax(jnp.where(new_run, pos, -1), axis=1)
    rank = pos - run_start
    rank = jnp.where(s == _SENTINEL, -1, rank)
    best = rank.max(axis=1)
    cand = jnp.where(rank == best[:, None], s, _SENTINEL)
    return cand.min(axis=1)


def _rowwise_mode_pairwise(lbl: jax.Array) -> jax.Array:
    """Same contract as :func:`_rowwise_mode` via O(w^2) pairwise-equality
    counting — pure compare+add on the VPU, no sort network to compile.
    Faster to compile and comparable to run for narrow rows."""
    valid = lbl != _SENTINEL
    eq = (lbl[:, :, None] == lbl[:, None, :]) & valid[:, None, :]
    counts = jnp.where(valid, jnp.sum(eq, axis=2, dtype=jnp.int32), 0)
    best = counts.max(axis=1)
    cand = jnp.where(counts == best[:, None], lbl, _SENTINEL)
    return cand.min(axis=1)


def _bucket_mode(mat: jax.Array) -> jax.Array:
    """Row-wise mode with the cheapest method for the bucket width.

    Width 1 is the value itself; width 2 is ``min`` (rows are exact by
    construction: the w=2 class holds only degree-2 vertices — equal
    labels -> that label, distinct -> tie -> smallest); narrow rows use
    pairwise counting, wide rows the bitonic sort + run-length scan."""
    w = mat.shape[1]
    if w == 1:
        return mat[:, 0]
    if w == 2:
        return jnp.min(mat, axis=1)
    if w <= _PAIRWISE_MAX_W:
        return _rowwise_mode_pairwise(mat)
    return _rowwise_mode(mat)


def _segmented_row_cumsum(new_run: jax.Array, vals: jax.Array) -> jax.Array:
    """Inclusive per-run cumulative sum along axis 1, reset where
    ``new_run`` is set — an UNROLLED Hillis-Steele segmented scan
    (log2(w) steps of static pad/slice + add/select).

    Replaces ``lax.associative_scan`` with the same segmented-⊕ operator:
    the generic scan's recursive odd/even splitting took the r4 weighted
    chip tier past its 900 s child timeout on real TPU — minutes of
    Mosaic compile PER width class (the same pathology
    ``segment.py:segment_mode`` documents for 1-D scans, where the fix is
    ``lax.cummax``; no native segmented-sum cumulative op exists, hence
    the manual unroll here). Numerics match the scan: every within-run
    prefix is a sum of that run's elements only — never differences of a
    row-wide cumsum, whose float32 ulp at wide rows would misrank labels.
    """
    flag = new_run
    val = vals
    d = 1
    w = vals.shape[1]
    while d < w:
        # combine x[p-d] into x[p]; identity (False, 0) pads the left edge
        a_f = jnp.pad(flag[:, :-d], ((0, 0), (d, 0)), constant_values=False)
        a_v = jnp.pad(val[:, :-d], ((0, 0), (d, 0)))
        val = jnp.where(flag, val, a_v + val)
        flag = flag | a_f
        d *= 2
    return val


def _rowwise_wmode(lbl: jax.Array, wgt: jax.Array) -> jax.Array:
    """Weighted mode of each ``[n, w]`` row: argmax of per-label weight
    sums, ties toward the smallest label. Sentinel slots carry weight 0
    and are excluded. Weights must be non-negative (LPA weights are): a
    run's within-run cumulative sums then never exceed its total, so the
    global max of the scan is always attained at a run end.

    Per-run sums come from a SEGMENTED scan (reset at run boundaries),
    not differences of a row-wide cumsum: at wide rows the row prefix
    reaches magnitudes where float32 ulp exceeds small weight gaps, and
    total-as-difference misranks labels (the same corruption
    ``segment.py:_segment_mode_weighted`` documents and avoids)."""
    # One multi-operand sort carries the weights through the sort network
    # itself — no argsort + per-slot gathers (gathers are the measured
    # bottleneck on TPU, docs/DESIGN.md).
    s, ws = lax.sort(
        (lbl, jnp.where(lbl == _SENTINEL, 0.0, wgt)), dimension=1, num_keys=1
    )
    new_run = jnp.concatenate(
        [jnp.ones((s.shape[0], 1), jnp.bool_), s[:, 1:] != s[:, :-1]], axis=1
    )
    score = _segmented_row_cumsum(new_run, ws)
    score = jnp.where(s == _SENTINEL, -1.0, score)
    best = score.max(axis=1)
    cand = jnp.where(score == best[:, None], s, _SENTINEL)
    return cand.min(axis=1)


def _rowwise_wmode_pairwise(lbl: jax.Array, wgt: jax.Array) -> jax.Array:
    """Same contract as :func:`_rowwise_wmode` via O(w^2) pairwise-equality
    weight sums — no sort network for narrow rows."""
    valid = lbl != _SENTINEL
    wz = jnp.where(valid, wgt, 0.0)
    eq = (lbl[:, :, None] == lbl[:, None, :]) & valid[:, None, :]
    scores = jnp.where(valid, jnp.sum(eq * wz[:, None, :], axis=2), -1.0)
    best = scores.max(axis=1)
    cand = jnp.where(scores == best[:, None], lbl, _SENTINEL)
    return cand.min(axis=1)


def _bucket_wmode(mat: jax.Array, wmat: jax.Array) -> jax.Array:
    """Weighted :func:`_bucket_mode`: cheapest method per bucket width."""
    w = mat.shape[1]
    if w == 1:
        return mat[:, 0]
    if w == 2:
        # degree-2 rows are exact: equal labels -> that label; else the
        # heavier label wins, equal weights tie toward the smaller label.
        l0, l1 = mat[:, 0], mat[:, 1]
        w0, w1 = wmat[:, 0], wmat[:, 1]
        pick0 = (w0 > w1) | ((w0 == w1) & (l0 <= l1))
        return jnp.where(l0 == l1, l0, jnp.where(pick0, l0, l1))
    if w <= _PAIRWISE_MAX_W:
        return _rowwise_wmode_pairwise(mat, wmat)
    return _rowwise_wmode(mat, wmat)


def bucketed_mode(plan: BucketedModePlan, messages: jax.Array, fallback: jax.Array,
                  weights: str | None = "plan"):
    """Per-vertex mode of ``messages`` under the plan's CSR layout.

    ``messages``: int32 ``[M]`` in message-CSR order (``labels[msg_send]``).
    ``fallback``: int32 ``[V]`` — value for vertices with no messages
    (LPA: keep the old label). Returns int32 ``[V]``.

    ``weights="plan"`` (default): when the plan carries a weight payload
    (built from a weighted graph), the mode is the argmax of per-value
    weight sums — weighted-LPA semantics. Pass ``weights=None`` to force
    the plain unweighted mode for generic reductions over a weighted
    graph's plan.
    """
    if weights not in ("plan", None):
        raise ValueError("weights must be 'plan' or None")
    if plan.msg_idx is None:
        raise ValueError(
            "this plan is fused (send_idx only) — use lpa_superstep_bucketed, "
            "or build with from_graph/from_ptr for generic message reduction"
        )
    if messages.shape[0] != plan.num_messages or fallback.shape[0] != plan.num_vertices:
        raise ValueError(
            f"plan built for M={plan.num_messages}, V={plan.num_vertices} but got "
            f"M={messages.shape[0]}, V={fallback.shape[0]} — plan/graph mismatch"
        )
    msgs_pad = jnp.concatenate(
        [messages.astype(jnp.int32), jnp.full((1,), _SENTINEL, jnp.int32)]
    )
    out = fallback.astype(jnp.int32)
    wmats = (
        plan.weight_mat
        if weights == "plan" and plan.weight_mat is not None
        else (None,) * len(plan.vertex_ids)
    )
    for ids, idx, wmat in zip(plan.vertex_ids, plan.msg_idx, wmats):
        mat = msgs_pad[idx]
        mode = _bucket_mode(mat) if wmat is None else _bucket_wmode(mat, wmat)
        out = out.at[ids].set(mode, unique_indices=True, mode="drop")
    return out


def lpa_superstep_bucketed(
    labels: jax.Array, graph: Graph, plan: BucketedModePlan
) -> jax.Array:
    """One LPA superstep via the bucketed plan — semantics identical to
    :func:`graphmine_tpu.ops.lpa.lpa_superstep` (asserted by tests).

    With a fused plan (``send_idx`` present, e.g. from
    :meth:`BucketedModePlan.from_edges`) the [M] message array is never
    materialized: each bucket gathers sender labels directly — one gather
    instead of two, saving an [M]-sized HBM round trip per superstep.

    Weighted graphs are first-class (r2; was sort-path-only): the plan
    carries slot-aligned weight matrices and the row modes become argmax
    of per-label weight sums (ties toward the smallest label, matching
    ``segment_mode(weights=...)``)."""
    if graph.msg_weight is not None and plan.weight_mat is None:
        raise ValueError(
            "graph carries msg_weight but the plan has no weight payload; "
            "build it with build_graph_and_plan(edge_weights=...), "
            "BucketedModePlan.from_graph, or from_ptr(weights_sorted=...)"
        )
    if plan.send_idx is not None:
        if (
            labels.shape[0] != plan.num_vertices
            or graph.num_messages != plan.num_messages
        ):
            raise ValueError(
                f"plan built for V={plan.num_vertices}, M={plan.num_messages} "
                f"but got V={labels.shape[0]}, M={graph.num_messages} — "
                "plan/graph mismatch"
            )
        lbl_pad = jnp.concatenate(
            [labels.astype(jnp.int32), jnp.full((1,), _SENTINEL, jnp.int32)]
        )
        out = labels.astype(jnp.int32)
        wmats = plan.weight_mat or (None,) * len(plan.vertex_ids)
        for ids, sidx, wmat in zip(plan.vertex_ids, plan.send_idx, wmats):
            mat = lbl_pad[sidx]
            mode = (
                _bucket_mode(mat) if wmat is None else _bucket_wmode(mat, wmat)
            )
            out = out.at[ids].set(mode, unique_indices=True, mode="drop")
        if plan.hist_vertex_ids is not None:
            # Mega-hub mode: per-hub label histogram + argmax. Exact slot
            # count (no padding), no wide sort; argmax's first-max rule is
            # the smallest-label tie-break. Weighted: the histogram
            # accumulates weights instead of counts.
            n_hist = plan.hist_vertex_ids.shape[0]
            neigh = labels[plan.hist_send].astype(jnp.int32)
            flat = plan.hist_row_offset + neigh
            if plan.hist_weight is None:
                hist = jnp.zeros((n_hist * plan.num_vertices,), jnp.int32)
                hist = hist.at[flat].add(1, mode="drop")
            else:
                # Weights may legally all be 0 for a hub (validation only
                # requires >= 0); an all-zero histogram row would argmax to
                # label 0 — possibly never received. Start every slot at
                # -inf, raise *received* slots to 0.0 with a scatter-max,
                # then accumulate: unreceived labels stay -inf and ties
                # resolve to the smallest received label, matching
                # segment_mode and the row-wise weighted paths
                # (cross-path one-answer invariant), with no second buffer.
                hist = jnp.full((n_hist * plan.num_vertices,), -jnp.inf,
                                jnp.float32)
                hist = hist.at[flat].max(0.0, mode="drop")
                hist = hist.at[flat].add(plan.hist_weight, mode="drop")
            counts = hist.reshape(n_hist, plan.num_vertices)
            modes = jnp.argmax(counts, axis=1).astype(jnp.int32)
            out = out.at[plan.hist_vertex_ids].set(
                modes, unique_indices=True, mode="drop"
            )
        return out
    msg = labels[graph.msg_send]
    return bucketed_mode(plan, msg, labels)
