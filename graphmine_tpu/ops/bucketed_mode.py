"""Degree-bucketed dense segment-mode — the fast path of the LPA superstep.

The sort-based :func:`graphmine_tpu.ops.segment.segment_mode` pays one
global O(M log M) two-key sort per superstep — at 10^7+ messages the sort
dominates LPA wall-clock. This module exploits two static facts about the
message CSR (``graph.msg_ptr`` — built once on host, ``container.py``):

1. each vertex's messages are a *contiguous* slice, and
2. the slice lengths (degrees) are known at trace time.

So vertices are **bucketed by degree class** (power-of-two widths), and
each bucket's messages are gathered into a dense ``[n_b, w_b]`` matrix and
sorted **row-wise** — many independent tiny sorts along the minor axis
(XLA lowers these to vectorized bitonic networks) instead of one giant
global sort. Power-law skew (SURVEY §7 hard part 3) is exactly what the
bucketing absorbs: the million degree≤8 vertices ride in width-8 rows
while the one degree-100K hub gets its own wide row; padding never exceeds
2× and the global sort's log(M) factor drops to log(w) per element.

The plan (bucket membership + padded gather indices) is host-precomputed
from the static CSR once per graph and reused across all supersteps and
runs — the same amortization the message CSR itself gets.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph

_SENTINEL = jnp.iinfo(jnp.int32).max
_MIN_WIDTH = 8


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class BucketedModePlan:
    """Static gather plan: per degree-class vertex ids + message indices.

    ``vertex_ids[b]``: int32 ``[n_b]`` — vertices in bucket ``b``.
    ``msg_idx[b]``: int32 ``[n_b, w_b]`` — indices into the message array,
    padded with ``num_messages`` (gathers a sentinel label slot). ``None``
    on fused plans (``send_idx`` replaces it; halves plan HBM).
    ``send_idx[b]``: optional int32 ``[n_b, w_b]`` — the *sender vertex id*
    behind each slot (padding = ``num_vertices``). When present, the LPA
    superstep gathers straight from the label vector — one fused gather
    instead of materializing the [M] message array and re-gathering it.
    """

    vertex_ids: tuple
    msg_idx: tuple | None
    num_vertices: int = dataclasses.field(metadata=dict(static=True))
    num_messages: int = dataclasses.field(metadata=dict(static=True))
    send_idx: tuple | None = None

    @classmethod
    def from_graph(cls, graph: Graph, with_send: bool = False) -> "BucketedModePlan":
        """Build from a device-resident graph. Note: fetches ``msg_ptr``
        (and ``msg_send`` when ``with_send``) to host; when the original
        edge arrays are still on host, prefer :meth:`from_edges` (no device
        round-trip, fused-gather plan included)."""
        send = np.asarray(graph.msg_send) if with_send else None
        return cls.from_ptr(np.asarray(graph.msg_ptr), graph.num_vertices, send)

    @classmethod
    def from_edges(
        cls, src, dst, num_vertices: int, symmetric: bool = True
    ) -> "BucketedModePlan":
        """Host-pure construction from endpoint arrays — same CSR layout as
        :func:`graphmine_tpu.graph.container.build_graph` (messages grouped
        by receiver, stable order). Includes the fused-gather ``send_idx``
        plan."""
        from graphmine_tpu.graph.container import _message_csr

        src = np.asarray(src, dtype=np.int32)
        dst = np.asarray(dst, dtype=np.int32)
        if src.shape != dst.shape or src.ndim != 1:
            raise ValueError("src/dst must be equal-length 1-D arrays")
        ptr, _, send_sorted = _message_csr(src, dst, num_vertices, symmetric)
        return cls.from_ptr(ptr, num_vertices, send_sorted)

    @classmethod
    def from_ptr(
        cls, ptr: np.ndarray, num_vertices: int, send_sorted: np.ndarray | None = None
    ) -> "BucketedModePlan":
        ptr = np.asarray(ptr).astype(np.int64)
        deg = ptr[1:] - ptr[:-1]
        m = int(ptr[-1])
        if m >= np.iinfo(np.int32).max:
            raise ValueError("message count exceeds int32; shard the build")
        classes = np.maximum(
            np.ceil(np.log2(np.maximum(deg, 1))).astype(np.int64),
            int(np.log2(_MIN_WIDTH)),
        )
        vertex_ids, msg_idx, send_idx = [], [], []
        for c in np.unique(classes[deg > 0]):
            ids = np.nonzero((classes == c) & (deg > 0))[0].astype(np.int32)
            w = 1 << int(c)
            offs = np.arange(w, dtype=np.int64)[None, :]
            idx = ptr[ids][:, None] + offs
            valid = offs < deg[ids][:, None]
            vertex_ids.append(jnp.asarray(ids))
            if send_sorted is not None:
                # Fused plan: only sender-id matrices go to device — the
                # msg_idx matrices would double plan HBM and never be read.
                s = send_sorted[np.minimum(idx, m - 1)]
                send_idx.append(jnp.asarray(np.where(valid, s, num_vertices).astype(np.int32)))
            else:
                msg_idx.append(jnp.asarray(np.where(valid, idx, m).astype(np.int32)))
        return cls(
            vertex_ids=tuple(vertex_ids),
            msg_idx=tuple(msg_idx) if send_sorted is None else None,
            num_vertices=num_vertices,
            num_messages=m,
            send_idx=tuple(send_idx) if send_sorted is not None else None,
        )


def build_graph_and_plan(
    src, dst, num_vertices: int | None = None, symmetric: bool = True,
    use_native: bool = True,
):
    """Build the :class:`Graph` and its fused plan from ONE message-CSR
    pass — the pipeline's single-device fast path. Calling
    :func:`~graphmine_tpu.graph.container.build_graph` and
    :meth:`BucketedModePlan.from_edges` separately runs the counting sort
    twice over the same edges; this shares it."""
    from graphmine_tpu.graph.container import (
        _graph_from_csr,
        _message_csr,
        _prepare_edges,
    )

    src, dst, num_vertices = _prepare_edges(src, dst, num_vertices)
    ptr, recv, send = _message_csr(src, dst, num_vertices, symmetric, use_native)
    graph = _graph_from_csr(src, dst, ptr, recv, send, num_vertices, symmetric)
    return graph, BucketedModePlan.from_ptr(ptr, num_vertices, send)


def _rowwise_mode(lbl: jax.Array) -> jax.Array:
    """Mode of each row of a ``[n, w]`` int32 matrix; sentinel entries
    ignored; ties break toward the smallest value. Rows must contain at
    least one non-sentinel entry."""
    s = jnp.sort(lbl, axis=1)
    w = s.shape[1]
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    new_run = jnp.concatenate(
        [jnp.ones((s.shape[0], 1), jnp.bool_), s[:, 1:] != s[:, :-1]], axis=1
    )
    run_start = lax.cummax(jnp.where(new_run, pos, -1), axis=1)
    rank = pos - run_start
    rank = jnp.where(s == _SENTINEL, -1, rank)
    best = rank.max(axis=1)
    cand = jnp.where(rank == best[:, None], s, _SENTINEL)
    return cand.min(axis=1)


def bucketed_mode(plan: BucketedModePlan, messages: jax.Array, fallback: jax.Array):
    """Per-vertex mode of ``messages`` under the plan's CSR layout.

    ``messages``: int32 ``[M]`` in message-CSR order (``labels[msg_send]``).
    ``fallback``: int32 ``[V]`` — value for vertices with no messages
    (LPA: keep the old label). Returns int32 ``[V]``.
    """
    if plan.msg_idx is None:
        raise ValueError(
            "this plan is fused (send_idx only) — use lpa_superstep_bucketed, "
            "or build with from_graph/from_ptr for generic message reduction"
        )
    if messages.shape[0] != plan.num_messages or fallback.shape[0] != plan.num_vertices:
        raise ValueError(
            f"plan built for M={plan.num_messages}, V={plan.num_vertices} but got "
            f"M={messages.shape[0]}, V={fallback.shape[0]} — plan/graph mismatch"
        )
    msgs_pad = jnp.concatenate(
        [messages.astype(jnp.int32), jnp.full((1,), _SENTINEL, jnp.int32)]
    )
    out = fallback.astype(jnp.int32)
    for ids, idx in zip(plan.vertex_ids, plan.msg_idx):
        out = out.at[ids].set(_rowwise_mode(msgs_pad[idx]))
    return out


def lpa_superstep_bucketed(
    labels: jax.Array, graph: Graph, plan: BucketedModePlan
) -> jax.Array:
    """One LPA superstep via the bucketed plan — semantics identical to
    :func:`graphmine_tpu.ops.lpa.lpa_superstep` (asserted by tests).

    With a fused plan (``send_idx`` present, e.g. from
    :meth:`BucketedModePlan.from_edges`) the [M] message array is never
    materialized: each bucket gathers sender labels directly — one gather
    instead of two, saving an [M]-sized HBM round trip per superstep."""
    if plan.send_idx is not None:
        if (
            labels.shape[0] != plan.num_vertices
            or graph.num_messages != plan.num_messages
        ):
            raise ValueError(
                f"plan built for V={plan.num_vertices}, M={plan.num_messages} "
                f"but got V={labels.shape[0]}, M={graph.num_messages} — "
                "plan/graph mismatch"
            )
        lbl_pad = jnp.concatenate(
            [labels.astype(jnp.int32), jnp.full((1,), _SENTINEL, jnp.int32)]
        )
        out = labels.astype(jnp.int32)
        for ids, sidx in zip(plan.vertex_ids, plan.send_idx):
            out = out.at[ids].set(_rowwise_mode(lbl_pad[sidx]))
        return out
    msg = labels[graph.msg_send]
    return bucketed_mode(plan, msg, labels)
