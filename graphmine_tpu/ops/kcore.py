"""k-core decomposition via iterated neighborhood H-indices.

Core numbers generalize the reference pipeline's degree-based outlier
features (SURVEY §7.5: per-vertex structural features for the LOF scorer);
peripheral low-core vertices are classic anomaly candidates. No GraphFrames
equivalent exists — this extends the engine surface.

Algorithm (Lü et al., "The H-index of a network node"): initialize
``h[v] = degree(v)``; repeatedly set ``h[v]`` to the H-index of its
neighbors' current values (the largest ``x`` such that at least ``x``
neighbors have ``h >= x``). The fixpoint is exactly the core number.
TPU formulation: per-superstep sort of (vertex, -h) message pairs, rank
within each vertex's run (cummax of run starts — same machinery as
:func:`graphmine_tpu.ops.segment.segment_mode`), then
``segment_max(min(h_sorted, rank+1))``. Monotone decreasing, so it
converges; runs inside one ``lax.while_loop``.

Operates on the simple undirected graph (duplicates/self-loops dropped),
the standard k-core convention.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph, simple_undirected_edges


def _simple_messages(graph: Graph):
    """Host-side: symmetric message list of the simplified graph."""
    a, b = simple_undirected_edges(graph)
    recv = np.concatenate([a, b])
    send = np.concatenate([b, a])
    order = np.argsort(recv, kind="stable")
    return recv[order], send[order]


@partial(jax.jit, static_argnames=("num_vertices", "max_iter"))
def _core_device(recv, send, num_vertices: int, max_iter: int):
    v = num_vertices
    deg = jax.ops.segment_sum(jnp.ones_like(recv), recv, num_segments=v)
    m = recv.shape[0]
    pos = jnp.arange(m, dtype=jnp.int32)

    def hindex_sweep(h):
        neg_h = -h[send]
        seg_s, negh_s = lax.sort((recv, neg_h), num_keys=2)
        new_seg = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), seg_s[1:] != seg_s[:-1]]
        )
        run_start = lax.cummax(jnp.where(new_seg, pos, -1))
        rank = pos - run_start  # 0-based position within the vertex's run
        cand = jnp.minimum(-negh_s, rank + 1)
        # empty segments (isolated vertices) come back as int32 min; their
        # core number is 0
        return jnp.maximum(jax.ops.segment_max(cand, seg_s, num_segments=v), 0)

    def cond(state):
        _, changed, it = state
        return (changed > 0) & (it < max_iter)

    def body(state):
        h, _, it = state
        new = jnp.minimum(h, hindex_sweep(h))
        changed = jnp.sum(new != h, dtype=jnp.int32)
        return new, changed, it + 1

    h, _, _ = lax.while_loop(cond, body, (deg, jnp.int32(1), jnp.int32(0)))
    return h


def core_numbers(graph: Graph, max_iter: int = 0) -> jax.Array:
    """Core number per vertex, int32 ``[V]`` (0 for isolated vertices)."""
    recv, send = _simple_messages(graph)
    if len(recv) == 0:
        return jnp.zeros((graph.num_vertices,), jnp.int32)
    limit = max_iter if max_iter > 0 else graph.num_vertices + 1
    return _core_device(
        jnp.asarray(recv), jnp.asarray(send),
        num_vertices=graph.num_vertices, max_iter=limit,
    )
