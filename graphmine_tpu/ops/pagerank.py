"""PageRank as a jit-compiled power iteration.

The reference never calls PageRank, but it is part of the engine surface
its GraphFrame object exposes (the same object built at
``Graphframes.py:78`` also provides ``pageRank``); SURVEY §2.2 scopes the
framework to that engine surface. TPU design: rank is a dense float32
vector; one iteration is a gather along edge sources + ``segment_sum`` at
destinations — the same message machinery as LPA with sum instead of mode.

Semantics match the classic formulation (and GraphFrames/GraphX up to
their scaling convention): damping ``alpha``, uniform teleport (or a
personalized reset distribution), dangling-vertex mass redistributed via
the teleport vector, ranks summing to 1.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from graphmine_tpu._jax_compat import pcast
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph


def pagerank(
    graph: Graph,
    alpha: float = 0.85,
    max_iter: int = 100,
    tol: float = 1e-6,
    reset: jax.Array | None = None,
    weights: jax.Array | None = None,
    plan="auto",
    sink=None,
) -> jax.Array:
    """PageRank vector ``[V]`` (float32, sums to 1).

    ``reset``: optional personalization distribution (normalized
    internally); ``None`` = uniform teleport. ``weights``: optional
    non-negative per-edge weights ``[E]`` (aligned with ``graph.src``) —
    each vertex splits its rank across out-edges in proportion to weight
    (NetworkX weighted-pagerank semantics; vertices whose out-weight sums
    to 0 are treated as dangling). Converges when the L1 delta drops
    below ``tol`` (checked inside the while_loop — no host sync per
    iteration), bounded by ``max_iter``.

    ``plan``: a :class:`~graphmine_tpu.ops.blocking.BlockedPlan` routes
    the inflow through the destination-binned bin-then-reduce layout
    (``blocked_inflow``; sums reassociate, so parity is to float
    tolerance). Requires a **directed** message CSR
    (``build_graph(..., symmetric=False)`` — a symmetric CSR carries both
    directions and would double the inflow) and ``weights=None`` (the
    per-edge ``weights`` argument is edge-order-aligned, not CSR-aligned;
    a weighted run refuses loudly rather than silently dropping or
    misaligning weights — pass ``plan=None``). The default ``"auto"``
    consults :func:`~graphmine_tpu.ops.blocking.select_superstep_family`
    and flips to blocked only past the measured crossover on an eligible
    graph; everything else keeps the segment_sum path bit-for-bit.
    ``sink``: optional MetricsSink for the ``impl_selected`` /
    ``plan_build`` provenance records.
    """
    from graphmine_tpu.ops.blocking import BlockedPlan

    resolved = None
    if isinstance(plan, str) and plan == "auto":
        if (
            weights is None
            and not graph.symmetric
            and not isinstance(graph.msg_ptr, jax.core.Tracer)
        ):
            from graphmine_tpu.ops.blocking import (
                emit_plan_records,
                select_superstep_family,
            )
            from graphmine_tpu.ops.lpa import _cached_auto_plan

            family, reason = select_superstep_family(
                graph.num_vertices, graph.num_messages
            )
            if family == "blocked":
                resolved, seconds, cached = _cached_auto_plan(graph, "blocked")
                emit_plan_records(
                    sink, "pagerank_inflow", resolved, reason, seconds,
                    cached, graph.num_edges, graph.num_messages,
                    num_vertices=graph.num_vertices,
                )
    elif isinstance(plan, BlockedPlan):
        if (
            plan.num_vertices != graph.num_vertices
            or plan.num_messages != graph.num_messages
        ):
            # blocked_inflow alone can only check V; a same-V plan from a
            # different graph would silently route rank the wrong way
            raise ValueError(
                f"plan built for V={plan.num_vertices}, "
                f"M={plan.num_messages} but graph has "
                f"V={graph.num_vertices}, M={graph.num_messages} — "
                "plan/graph mismatch"
            )
        if graph.symmetric:
            raise ValueError(
                "blocked PageRank needs a directed message CSR "
                "(build_graph(..., symmetric=False)); this graph's CSR "
                "carries both directions and would double the inflow"
            )
        if weights is not None:
            raise ValueError(
                "blocked PageRank does not carry the edge-aligned weights "
                "argument (the plan's layout is CSR-aligned); pass "
                "plan=None for weighted ranks — weights are never "
                "silently dropped"
            )
        resolved = plan
    elif plan is not None:
        raise ValueError(
            f"plan must be 'auto', None, or a BlockedPlan; got {plan!r}"
        )
    if sink is not None and not isinstance(graph.msg_ptr, jax.core.Tracer):
        # Achieved-vs-model attribution (ISSUE 12): _pagerank returns its
        # while_loop iteration count, so the window is the REAL
        # supersteps-to-tolerance; judged against the analytical model
        # (segment_sum inflow ≈ the sort gather; blocked_inflow ≈ the
        # binned two-pass).
        from graphmine_tpu.obs.costmodel import (
            emit_superstep_timing,
            superstep_cost,
            timed_fixpoint,
        )

        (pr, iters), secs, cold = timed_fixpoint(
            lambda: _pagerank(
                graph, alpha, max_iter, tol, reset, weights, resolved
            ),
            jit_fn=_pagerank,
        )
        iters = max(int(iters), 1)
        cost = superstep_cost(
            "pagerank_inflow", "sort" if resolved is None else "auto",
            graph.num_vertices, graph.num_messages, graph.num_edges,
            plan=resolved, weighted=weights is not None,
        )
        emit_superstep_timing(
            sink, "pagerank_inflow", cost, iters, iters, secs,
            graph.num_edges, variant="fused", cold_compile=cold,
        )
        return pr
    pr, _ = _pagerank(graph, alpha, max_iter, tol, reset, weights, resolved)
    return pr


@partial(jax.jit, static_argnames=("max_iter",))
def _pagerank(
    graph: Graph,
    alpha: float = 0.85,
    max_iter: int = 100,
    tol: float = 1e-6,
    reset: jax.Array | None = None,
    weights: jax.Array | None = None,
    plan=None,
) -> jax.Array:
    v = graph.num_vertices
    src, dst = graph.src, graph.dst
    if weights is None:
        out_w = jax.ops.segment_sum(
            jnp.ones_like(src, jnp.float32), src, num_segments=v
        )
        edge_frac = None
    else:
        w = jnp.maximum(weights.astype(jnp.float32), 0.0)
        out_w = jax.ops.segment_sum(w, src, num_segments=v)
        edge_frac = w / jnp.maximum(out_w[src], 1e-30)
    inv_out = jnp.where(out_w > 0, 1.0 / jnp.maximum(out_w, 1e-30), 0.0).astype(
        jnp.float32
    )
    dangling = out_w <= 0
    if reset is None:
        reset_v = jnp.full((v,), 1.0 / v, jnp.float32)
    else:
        r = jnp.maximum(reset.astype(jnp.float32), 0.0)
        reset_v = r / jnp.maximum(r.sum(), 1e-12)

    def step(state):
        pr, _, it = state
        if plan is not None:
            from graphmine_tpu.ops.blocking import blocked_inflow

            inflow = blocked_inflow(plan, pr * inv_out)
        elif edge_frac is None:
            inflow = jax.ops.segment_sum((pr * inv_out)[src], dst, num_segments=v)
        else:
            inflow = jax.ops.segment_sum(pr[src] * edge_frac, dst, num_segments=v)
        dangling_mass = jnp.sum(jnp.where(dangling, pr, 0.0))
        new = alpha * (inflow + dangling_mass * reset_v) + (1.0 - alpha) * reset_v
        delta = jnp.abs(new - pr).sum()
        return new, delta, it + 1

    def cond(state):
        _, delta, it = state
        return (delta > tol) & (it < max_iter)

    pr0 = jnp.full((v,), 1.0 / v, jnp.float32)
    pr, _, it = lax.while_loop(cond, step, (pr0, jnp.float32(1.0), jnp.int32(0)))
    # iterations ride along so the sink path can report the REAL window
    # (the public wrapper discards them for plain callers)
    return pr, it


def _validate_sources(sources, v: int) -> np.ndarray:
    """Shared source-id coercion/validation for the single-device and
    source-sharded (parallel/ppr.py) PPR entry points."""
    sources = np.asarray(sources, dtype=np.int32)
    if sources.size and (sources.min() < 0 or sources.max() >= v):
        bad = sources[(sources < 0) | (sources >= v)]
        raise ValueError(f"source ids {bad.tolist()} out of range [0, {v})")
    return sources


@partial(jax.jit, static_argnames=("v", "max_iter", "varying_axes"))
def _batched_ppr(src, dst, v, sources, alpha, max_iter, tol,
                 varying_axes=None):
    """``varying_axes``: set when called inside ``shard_map`` with sharded
    ``sources`` (parallel/ppr.py) — the loop carry must then be marked
    device-varying up front so its type matches the varying loop output."""
    s = sources.shape[0]
    out_deg = jax.ops.segment_sum(jnp.ones_like(src), src, num_segments=v)
    inv_out = jnp.where(out_deg > 0, 1.0 / jnp.maximum(out_deg, 1), 0.0).astype(
        jnp.float32
    )
    dangling = out_deg == 0
    # One-hot teleport distributions, one column per source: [V, S].
    reset = jnp.zeros((v, s), jnp.float32).at[sources, jnp.arange(s)].set(1.0)

    def step(state):
        pr, _, it = state
        contrib = pr * inv_out[:, None]
        inflow = jax.ops.segment_sum(contrib[src], dst, num_segments=v)
        dangling_mass = jnp.sum(jnp.where(dangling[:, None], pr, 0.0), axis=0)
        new = alpha * (inflow + dangling_mass[None, :] * reset) + (1.0 - alpha) * reset
        delta = jnp.abs(new - pr).sum(axis=0).max()
        if varying_axes:
            # Couple the stopping rule across the mesh: every column chunk
            # iterates until the globally slowest column converges —
            # exactly the single-device batch's max-over-all-columns rule,
            # so the sharded result matches it to float noise.
            delta = lax.pmax(delta, varying_axes)
        return new, delta, it + 1

    def cond(state):
        _, delta, it = state
        return (delta > tol) & (it < max_iter)

    pr0 = jnp.full((v, s), 1.0 / v, jnp.float32)
    if varying_axes:
        # pr varies per device; delta stays replicated (the pmax in step
        # produces the same coupled value everywhere).
        pr0 = pcast(pr0, varying_axes, to="varying")
    pr, _, _ = lax.while_loop(cond, step, (pr0, jnp.float32(1.0), jnp.int32(0)))
    return pr


def parallel_personalized_pagerank(
    graph: Graph,
    sources,
    alpha: float = 0.85,
    max_iter: int = 100,
    tol: float = 1e-6,
) -> jax.Array:
    """Personalized PageRank from many sources at once — GraphFrames'
    ``parallelPersonalizedPageRank`` (part of the GraphFrame capability
    surface, SURVEY §2.2).

    Returns ``[V, S]``: column ``j`` is the PPR vector teleporting to
    ``sources[j]``. One batched power iteration over the whole [V, S] rank
    matrix — the per-edge gather/segment-sum is shared across sources, so S
    sources cost barely more HBM traffic than one (vs GraphX, which runs a
    vector program per source over the same Pregel machinery).
    """
    sources = _validate_sources(sources, graph.num_vertices)
    if sources.size == 0:
        return jnp.zeros((graph.num_vertices, 0), jnp.float32)
    return _batched_ppr(
        graph.src, graph.dst, graph.num_vertices, jnp.asarray(sources), alpha,
        max_iter, tol,
    )
