"""Label propagation — the TPU-native core of the pipeline.

Reproduces the semantics of ``GraphFrame.labelPropagation(maxIter=5)`` as
invoked at ``Graphframes.py:81`` (GraphX Pregel LPA):

- initial label of every vertex = its own id;
- synchronous supersteps: each vertex adopts the **mode of its neighbors'
  labels**, messages flowing along both directions of every directed edge,
  duplicate edges counted with multiplicity (``Graphframes.py:70-74``);
- exactly ``max_iter`` supersteps, no convergence test;
- isolated vertices keep their label;
- tie-break: deterministic smallest-label (GraphX's is implementation-
  defined, so cross-engine validation compares partitions, not ids).

The superstep is one gather + one segment-mode over the precomputed message
CSR — no shuffle, no driver round-trips. Under jit the whole ``max_iter``
loop is a single ``lax.scan`` XLA program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.ops.segment import segment_mode


def lpa_superstep(labels: jax.Array, graph: Graph) -> jax.Array:
    """One synchronous LPA superstep: gather → segment-mode → select."""
    msg = labels[graph.msg_send]
    mode, _ = segment_mode(
        graph.msg_recv, msg, num_segments=graph.num_vertices, indices_are_sorted=True
    )
    deg = graph.degrees()
    return jnp.where(deg > 0, mode, labels).astype(jnp.int32)


@partial(jax.jit, static_argnames=("max_iter", "return_history"))
def label_propagation(
    graph: Graph,
    max_iter: int = 5,
    init_labels: jax.Array | None = None,
    return_history: bool = False,
    plan=None,
):
    """Run ``max_iter`` LPA supersteps; returns int32 labels ``[V]``.

    With ``return_history=True`` also returns the per-iteration count of
    vertices whose label changed (the structured observability signal the
    reference lacked — SURVEY §5 metrics).

    ``plan``: an optional
    :class:`~graphmine_tpu.ops.bucketed_mode.BucketedModePlan` for the
    graph — switches every superstep to the degree-bucketed dense mode
    kernel (~1.4× faster at 10^7 messages; identical results). Worth its
    one-time host build cost when the same graph runs many supersteps.
    """
    labels = (
        jnp.arange(graph.num_vertices, dtype=jnp.int32)
        if init_labels is None
        else init_labels.astype(jnp.int32)
    )

    if plan is None:
        superstep = lambda lbl: lpa_superstep(lbl, graph)
    else:
        from graphmine_tpu.ops.bucketed_mode import lpa_superstep_bucketed

        superstep = lambda lbl: lpa_superstep_bucketed(lbl, graph, plan)

    def step(labels, _):
        new = superstep(labels)
        changed = jnp.sum(new != labels, dtype=jnp.int32)
        return new, changed

    labels, changed = lax.scan(step, labels, None, length=max_iter)
    if return_history:
        return labels, changed
    return labels


def num_communities(labels: jax.Array) -> jax.Array:
    """Distinct-label count (the reference's headline print, ``Graphframes.py:85``)."""
    v = labels.shape[0]
    present = jnp.zeros((v,), jnp.int32).at[labels].set(1, mode="drop")
    return present.sum()


def canonicalize(labels: jax.Array) -> jax.Array:
    """Relabel communities to dense ids ordered by first member vertex.

    Makes partitions comparable across engines/tie-breaks (SURVEY §6:
    validate partitions, not raw label values).
    """
    v = labels.shape[0]
    first_member = jnp.full((v,), v, jnp.int32).at[labels].min(jnp.arange(v, dtype=jnp.int32))
    rep = first_member[labels]  # representative = smallest vertex id in community
    order = jnp.unique(rep, size=v, fill_value=v)
    dense = jnp.searchsorted(order, rep)
    return dense.astype(jnp.int32)
