"""Label propagation — the TPU-native core of the pipeline.

Reproduces the semantics of ``GraphFrame.labelPropagation(maxIter=5)`` as
invoked at ``Graphframes.py:81`` (GraphX Pregel LPA):

- initial label of every vertex = its own id;
- synchronous supersteps: each vertex adopts the **mode of its neighbors'
  labels**, messages flowing along both directions of every directed edge,
  duplicate edges counted with multiplicity (``Graphframes.py:70-74``);
- exactly ``max_iter`` supersteps, no convergence test;
- isolated vertices keep their label;
- tie-break: deterministic smallest-label (GraphX's is implementation-
  defined, so cross-engine validation compares partitions, not ids).

The superstep is one gather + one segment-mode over the precomputed message
CSR — no shuffle, no driver round-trips. Under jit the whole ``max_iter``
loop is a single ``lax.scan`` XLA program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.ops.segment import segment_mode


def lpa_superstep(labels: jax.Array, graph: Graph) -> jax.Array:
    """One synchronous LPA superstep: gather → segment-mode → select.

    On a weighted graph (``build_graph(edge_weights=...)``) the mode is
    the label with the largest incoming *weight sum* (ties toward the
    smallest label) — classic weighted LPA; unweighted is the all-ones
    special case."""
    msg = labels[graph.msg_send]
    mode, _ = segment_mode(
        graph.msg_recv, msg, num_segments=graph.num_vertices,
        indices_are_sorted=True, weights=graph.msg_weight,
    )
    deg = graph.degrees()
    return jnp.where(deg > 0, mode, labels).astype(jnp.int32)


def label_propagation(
    graph: Graph,
    max_iter: int = 5,
    init_labels: jax.Array | None = None,
    return_history: bool = False,
    plan="auto",
    sink=None,
):
    """Run ``max_iter`` LPA supersteps; returns int32 labels ``[V]``.

    With ``return_history=True`` also returns the per-iteration count of
    vertices whose label changed (the structured observability signal the
    reference lacked — SURVEY §5 metrics).

    ``plan``: a
    :class:`~graphmine_tpu.ops.bucketed_mode.BucketedModePlan` (the
    degree-bucketed dense mode kernel, ~3x the sort superstep at 10^7
    messages) or a :class:`~graphmine_tpu.ops.blocking.BlockedPlan` (the
    propagation-blocking bin-then-reduce engine past the gather roofline)
    — identical labels either way, tested. The default ``"auto"``
    resolves the family through
    :func:`~graphmine_tpu.ops.blocking.select_superstep_family` (the
    single crossover-policy owner) and builds the plan from the graph
    (cached per graph, per family). Auto stays on the sort path when
    custom ``init_labels`` are given (the fused plan's
    histogram/sentinel machinery assumes labels in ``[0, V)`` — the
    default ``arange`` initialization guarantees that, arbitrary labels
    don't) or under an enclosing jit trace, where host plan construction
    is impossible. Pass ``None`` to force the sort-based superstep.

    ``sink``: optional MetricsSink — each auto resolution emits an
    ``impl_selected`` record, and each plan materialization a
    ``plan_build`` record (family, build seconds, bins/buckets, padded
    slots/edge), so host plan cost is visible in obs_report instead of
    hiding inside first-call latency.
    """
    from graphmine_tpu.ops.blocking import BlockedPlan, emit_plan_records
    from graphmine_tpu.ops.bucketed_mode import BucketedModePlan

    if isinstance(plan, str) and plan == "auto":
        plan = None
        if init_labels is None and not isinstance(graph.msg_ptr, jax.core.Tracer):
            from graphmine_tpu.ops.blocking import select_superstep_family

            family, reason = select_superstep_family(
                graph.num_vertices, graph.num_messages,
                weighted=graph.msg_weight is not None,
            )
            seconds, cached = 0.0, False
            if family != "sort":
                # Weighted graphs ride the fast paths too (r2): both
                # builders carry the slot-aligned weight payload.
                plan, seconds, cached = _cached_auto_plan(graph, family)
            emit_plan_records(
                sink, "lpa_superstep", plan, reason, seconds, cached,
                graph.num_edges, graph.num_messages,
                num_vertices=graph.num_vertices,
            )
    elif plan is not None and not isinstance(
        plan, (BucketedModePlan, BlockedPlan)
    ):
        raise ValueError(
            "plan must be 'auto', None, a BucketedModePlan or a "
            f"BlockedPlan; got {plan!r}"
        )
    if (
        isinstance(plan, BucketedModePlan)
        and plan.hist_vertex_ids is not None
        and init_labels is not None
        and not isinstance(init_labels, jax.core.Tracer)
    ):
        # The fused histogram path scatter-adds labels as indices in
        # [0, V); out-of-range labels would silently drop and argmax an
        # all-zero histogram to label 0. Check while still concrete.
        import numpy as _np

        il = _np.asarray(init_labels)
        if len(il) and (il.min() < 0 or il.max() >= plan.num_vertices):
            raise ValueError(
                "fused plans with a histogram path need init_labels in "
                f"[0, {plan.num_vertices}); got range "
                f"[{int(il.min())}, {int(il.max())}] — pass plan=None for "
                "arbitrary label values"
            )
    if sink is not None and not isinstance(graph.msg_ptr, jax.core.Tracer):
        # Achieved-vs-model attribution (ISSUE 12): wall-time the whole
        # compiled scan as one window of max_iter supersteps and judge it
        # against the analytical cost model — one superstep_timing record
        # per call, zero extra device syncs beyond the result fetch the
        # caller was about to pay anyway.
        from graphmine_tpu.obs.costmodel import (
            emit_superstep_timing,
            superstep_cost,
            timed_fixpoint,
        )

        out, secs, cold = timed_fixpoint(
            lambda: _label_propagation(
                graph, max_iter, init_labels, return_history, plan
            ),
            jit_fn=_label_propagation,
        )
        cost = superstep_cost(
            "lpa_superstep",
            "sort" if plan is None else "auto",
            graph.num_vertices, graph.num_messages, graph.num_edges,
            plan=plan, weighted=graph.msg_weight is not None,
        )
        emit_superstep_timing(
            sink, "lpa_superstep", cost, max_iter, max_iter, secs,
            graph.num_edges, variant="fused", cold_compile=cold,
        )
        return out
    return _label_propagation(graph, max_iter, init_labels, return_history, plan)


_auto_plan_cache: dict = {}


def _cached_auto_plan(graph: Graph, family: str = "bucketed"):
    """Auto plan per (graph, family), cached so repeated calls pay the
    host build (device->host fetch of msg_ptr/msg_send + NumPy layout)
    once. Keyed by the identity of the graph's msg_ptr array; a weakref
    finalizer evicts the entry when that array is collected. Returns
    ``(plan, build_seconds, cached)`` — the ``plan_build`` record's raw
    material (seconds is 0.0 on a cache hit)."""
    import weakref

    from graphmine_tpu.ops.blocking import BlockedPlan, timed_plan_build
    from graphmine_tpu.ops.bucketed_mode import BucketedModePlan

    key = id(graph.msg_ptr)
    hit = _auto_plan_cache.get(key)
    if hit is None or hit[0]() is not graph.msg_ptr:
        ref = weakref.ref(
            graph.msg_ptr, lambda _, k=key: _auto_plan_cache.pop(k, None)
        )
        hit = (ref, {})
        _auto_plan_cache[key] = hit
    plans = hit[1]
    if family in plans:
        return plans[family], 0.0, True
    if family == "blocked":
        plan, seconds = timed_plan_build(lambda: BlockedPlan.from_graph(graph))
    elif family == "bucketed":
        plan, seconds = timed_plan_build(
            lambda: BucketedModePlan.from_graph(graph, with_send=True)
        )
    else:
        raise ValueError(f"no plan to build for family {family!r}")
    plans[family] = plan
    return plan, seconds, False


@partial(jax.jit, static_argnames=("max_iter", "return_history"))
def _label_propagation(
    graph: Graph,
    max_iter: int = 5,
    init_labels: jax.Array | None = None,
    return_history: bool = False,
    plan=None,
):
    labels = (
        jnp.arange(graph.num_vertices, dtype=jnp.int32)
        if init_labels is None
        else init_labels.astype(jnp.int32)
    )

    if plan is None:
        superstep = lambda lbl: lpa_superstep(lbl, graph)
    else:
        from graphmine_tpu.ops.blocking import (
            BlockedPlan,
            lpa_superstep_blocked,
        )
        from graphmine_tpu.ops.bucketed_mode import lpa_superstep_bucketed

        if isinstance(plan, BlockedPlan):
            superstep = lambda lbl: lpa_superstep_blocked(lbl, graph, plan)
        else:
            superstep = lambda lbl: lpa_superstep_bucketed(lbl, graph, plan)

    def step(labels, _):
        new = superstep(labels)
        changed = jnp.sum(new != labels, dtype=jnp.int32)
        return new, changed

    labels, changed = lax.scan(step, labels, None, length=max_iter)
    if return_history:
        return labels, changed
    return labels


def num_communities(labels: jax.Array) -> jax.Array:
    """Distinct-label count (the reference's headline print, ``Graphframes.py:85``)."""
    v = labels.shape[0]
    present = jnp.zeros((v,), jnp.int32).at[labels].set(1, mode="drop")
    return present.sum()


def canonicalize(labels: jax.Array) -> jax.Array:
    """Relabel communities to dense ids ordered by first member vertex.

    Makes partitions comparable across engines/tie-breaks (SURVEY §6:
    validate partitions, not raw label values).
    """
    v = labels.shape[0]
    first_member = jnp.full((v,), v, jnp.int32).at[labels].min(jnp.arange(v, dtype=jnp.int32))
    rep = first_member[labels]  # representative = smallest vertex id in community
    order = jnp.unique(rep, size=v, fill_value=v)
    dense = jnp.searchsorted(order, rep)
    return dense.astype(jnp.int32)
