"""Label propagation — the TPU-native core of the pipeline.

Reproduces the semantics of ``GraphFrame.labelPropagation(maxIter=5)`` as
invoked at ``Graphframes.py:81`` (GraphX Pregel LPA):

- initial label of every vertex = its own id;
- synchronous supersteps: each vertex adopts the **mode of its neighbors'
  labels**, messages flowing along both directions of every directed edge,
  duplicate edges counted with multiplicity (``Graphframes.py:70-74``);
- exactly ``max_iter`` supersteps, no convergence test;
- isolated vertices keep their label;
- tie-break: deterministic smallest-label (GraphX's is implementation-
  defined, so cross-engine validation compares partitions, not ids).

The superstep is one gather + one segment-mode over the precomputed message
CSR — no shuffle, no driver round-trips. Under jit the whole ``max_iter``
loop is a single ``lax.scan`` XLA program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.ops.segment import segment_mode


def lpa_superstep(labels: jax.Array, graph: Graph) -> jax.Array:
    """One synchronous LPA superstep: gather → segment-mode → select.

    On a weighted graph (``build_graph(edge_weights=...)``) the mode is
    the label with the largest incoming *weight sum* (ties toward the
    smallest label) — classic weighted LPA; unweighted is the all-ones
    special case."""
    msg = labels[graph.msg_send]
    mode, _ = segment_mode(
        graph.msg_recv, msg, num_segments=graph.num_vertices,
        indices_are_sorted=True, weights=graph.msg_weight,
    )
    deg = graph.degrees()
    return jnp.where(deg > 0, mode, labels).astype(jnp.int32)


def label_propagation(
    graph: Graph,
    max_iter: int = 5,
    init_labels: jax.Array | None = None,
    return_history: bool = False,
    plan="auto",
):
    """Run ``max_iter`` LPA supersteps; returns int32 labels ``[V]``.

    With ``return_history=True`` also returns the per-iteration count of
    vertices whose label changed (the structured observability signal the
    reference lacked — SURVEY §5 metrics).

    ``plan``: a
    :class:`~graphmine_tpu.ops.bucketed_mode.BucketedModePlan` for the
    graph — switches every superstep to the degree-bucketed dense mode
    kernel (~3x faster at 10^7 messages; identical results, tested). The
    default ``"auto"`` builds it from the graph (cached per graph) when
    the message count amortizes the one-time host build. Auto stays on
    the sort path when custom ``init_labels`` are given (the fused plan's
    histogram/sentinel machinery assumes labels in ``[0, V)`` — the
    default ``arange`` initialization guarantees that, arbitrary labels
    don't) or under an enclosing jit trace, where host plan construction
    is impossible. Pass ``None`` to force the sort-based superstep.
    """
    from graphmine_tpu.ops.bucketed_mode import BucketedModePlan

    if isinstance(plan, str) and plan == "auto":
        plan = None
        if (
            init_labels is None
            and not isinstance(graph.msg_ptr, jax.core.Tracer)
            and graph.num_messages >= (1 << 16)
        ):
            # Weighted graphs ride the fast path too (r2): from_graph
            # builds the plan's slot-aligned weight payload.
            plan = _cached_auto_plan(graph)
    elif plan is not None and not isinstance(plan, BucketedModePlan):
        raise ValueError(
            f"plan must be 'auto', None, or a BucketedModePlan; got {plan!r}"
        )
    if (
        isinstance(plan, BucketedModePlan)
        and plan.hist_vertex_ids is not None
        and init_labels is not None
        and not isinstance(init_labels, jax.core.Tracer)
    ):
        # The fused histogram path scatter-adds labels as indices in
        # [0, V); out-of-range labels would silently drop and argmax an
        # all-zero histogram to label 0. Check while still concrete.
        import numpy as _np

        il = _np.asarray(init_labels)
        if len(il) and (il.min() < 0 or il.max() >= plan.num_vertices):
            raise ValueError(
                "fused plans with a histogram path need init_labels in "
                f"[0, {plan.num_vertices}); got range "
                f"[{int(il.min())}, {int(il.max())}] — pass plan=None for "
                "arbitrary label values"
            )
    return _label_propagation(graph, max_iter, init_labels, return_history, plan)


_auto_plan_cache: dict = {}


def _cached_auto_plan(graph: Graph):
    """Fused plan per graph, cached so repeated calls pay the host build
    (device->host fetch of msg_ptr/msg_send + NumPy bucketing) once.
    Keyed by the identity of the graph's msg_ptr array; a weakref
    finalizer evicts the entry when that array is collected."""
    import weakref

    from graphmine_tpu.ops.bucketed_mode import BucketedModePlan

    key = id(graph.msg_ptr)
    hit = _auto_plan_cache.get(key)
    if hit is not None and hit[0]() is graph.msg_ptr:
        return hit[1]
    plan = BucketedModePlan.from_graph(graph, with_send=True)
    ref = weakref.ref(graph.msg_ptr, lambda _, k=key: _auto_plan_cache.pop(k, None))
    _auto_plan_cache[key] = (ref, plan)
    return plan


@partial(jax.jit, static_argnames=("max_iter", "return_history"))
def _label_propagation(
    graph: Graph,
    max_iter: int = 5,
    init_labels: jax.Array | None = None,
    return_history: bool = False,
    plan=None,
):
    labels = (
        jnp.arange(graph.num_vertices, dtype=jnp.int32)
        if init_labels is None
        else init_labels.astype(jnp.int32)
    )

    if plan is None:
        superstep = lambda lbl: lpa_superstep(lbl, graph)
    else:
        from graphmine_tpu.ops.bucketed_mode import lpa_superstep_bucketed

        superstep = lambda lbl: lpa_superstep_bucketed(lbl, graph, plan)

    def step(labels, _):
        new = superstep(labels)
        changed = jnp.sum(new != labels, dtype=jnp.int32)
        return new, changed

    labels, changed = lax.scan(step, labels, None, length=max_iter)
    if return_history:
        return labels, changed
    return labels


def num_communities(labels: jax.Array) -> jax.Array:
    """Distinct-label count (the reference's headline print, ``Graphframes.py:85``)."""
    v = labels.shape[0]
    present = jnp.zeros((v,), jnp.int32).at[labels].set(1, mode="drop")
    return present.sum()


def canonicalize(labels: jax.Array) -> jax.Array:
    """Relabel communities to dense ids ordered by first member vertex.

    Makes partitions comparable across engines/tie-breaks (SURVEY §6:
    validate partitions, not raw label values).
    """
    v = labels.shape[0]
    first_member = jnp.full((v,), v, jnp.int32).at[labels].min(jnp.arange(v, dtype=jnp.int32))
    rep = first_member[labels]  # representative = smallest vertex id in community
    order = jnp.unique(rep, size=v, fill_value=v)
    dense = jnp.searchsorted(order, rep)
    return dense.astype(jnp.int32)
