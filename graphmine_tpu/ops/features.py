"""Per-vertex structural features for the kNN/LOF outlier scorer.

The north-star upgrade over the reference's size-threshold heuristic
(BASELINE.json: "kNN-graph + LOF outlier scorer"): each vertex gets a small
dense feature vector derived from graph structure, and outliers are scored
geometrically. All features are O(E) segment ops.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.ops.census import community_sizes


@partial(jax.jit, static_argnames=())
def vertex_features(graph: Graph, communities: jax.Array) -> jax.Array:
    """Feature matrix ``[V, 6]`` (float32):

    log1p(out-degree), log1p(in-degree), log1p(message degree),
    log1p(community size), log1p(mean neighbor degree), and the
    **same-community neighbor fraction** — the share of a vertex's
    messages arriving from its own community.

    The last feature is the direct signature of a community-bridging
    outlier (edges scattered uniformly across the graph land in foreign
    communities), which raw degree cannot separate under a power-law
    degree distribution: legitimate hubs out-degree injected anomalies by
    orders of magnitude. Degree-ish features are log-scaled to tame that
    same power law (max degree 1,223 at 4.6K vertices on the bundled
    data — SURVEY §7 hard part 3); the fraction is already in [0, 1].
    """
    v = graph.num_vertices
    ones_e = jnp.ones_like(graph.src)
    out_deg = jax.ops.segment_sum(ones_e, graph.src, num_segments=v)
    in_deg = jax.ops.segment_sum(ones_e, graph.dst, num_segments=v)
    msg_deg = graph.degrees()
    comm_size = community_sizes(communities)[communities]
    neigh_deg_sum = jax.ops.segment_sum(
        msg_deg[graph.msg_send], graph.msg_recv, num_segments=v,
        indices_are_sorted=True,
    )
    mean_neigh_deg = neigh_deg_sum / jnp.maximum(msg_deg, 1)
    same = (communities[graph.msg_send] == communities[graph.msg_recv]).astype(
        jnp.int32
    )
    same_cnt = jax.ops.segment_sum(
        same, graph.msg_recv, num_segments=v, indices_are_sorted=True
    )
    same_frac = same_cnt / jnp.maximum(msg_deg, 1)
    feats = jnp.log1p(
        jnp.stack(
            [out_deg, in_deg, msg_deg, comm_size, mean_neigh_deg], axis=1
        ).astype(jnp.float32)
    )
    return jnp.concatenate([feats, same_frac[:, None].astype(jnp.float32)], axis=1)


def standardize(feats: jax.Array) -> jax.Array:
    """Zero-mean unit-variance columns (guarding constant features)."""
    mu = feats.mean(axis=0, keepdims=True)
    sd = feats.std(axis=0, keepdims=True)
    return (feats - mu) / jnp.maximum(sd, 1e-6)
