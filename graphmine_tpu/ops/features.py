"""Per-vertex structural features for the kNN/LOF outlier scorer.

The north-star upgrade over the reference's size-threshold heuristic
(BASELINE.json: "kNN-graph + LOF outlier scorer"): each vertex gets a small
dense feature vector derived from graph structure, and outliers are scored
geometrically. Cost: mostly O(E) segment ops, plus two O(M log M) device
argsorts (distinct neighbor communities) and one host-side oriented-CSR
triangle pass (clustering coefficient — forward a warm triangle cache via
``triangles_cache`` to skip it).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.ops.census import community_sizes


def vertex_features(
    graph: Graph, communities: jax.Array, triangles_cache=None,
    include_clustering: bool | str = True, simple_edges=None,
) -> jax.Array:
    """Feature matrix ``[V, 8]`` (float32):

    log1p(out-degree), log1p(in-degree), log1p(message degree),
    log1p(community size), log1p(mean neighbor degree), the
    **same-community neighbor fraction** — the share of a vertex's
    messages arriving from its own community — plus
    log1p(**distinct neighbor communities**) and the local
    **clustering coefficient**.

    Same-frac/distinct-communities are the direct signature of a
    community-bridging outlier (edges scattered uniformly across the
    graph land in many foreign communities), and random bridges close
    almost no triangles, so the clustering coefficient separates them
    from organically embedded hubs — raw degree cannot under a power-law
    degree distribution: legitimate hubs out-degree injected anomalies by
    orders of magnitude. Measured on the AUROC harness (`bench.py --tier
    lof`): r1 CPU-class measurements 0.89–0.91 with the first six
    features, 0.91–0.93 with all eight; r4 real-TPU capture (after the
    true-f32 distance fix, which alone moved the headline from 0.92 to
    0.99) 0.9905 with all eight. Degree-ish features are log-scaled to tame the power
    law (max degree 1,223 at 4.6K vertices on the bundled data — SURVEY
    §7 hard part 3); fractions are already in [0, 1].
    """
    # clustering_coefficient orients the CSR on the host, so it runs
    # outside jit; everything else is one compiled program.
    # ``triangles_cache``: a prior ops.triangles._triangles result (e.g.
    # GraphFrame._triangle_cache()) to skip the host pass.
    # ``include_clustering`` mirrors the host twin: True = exact wedge
    # pipeline; ``"sampled"`` = the wedge-count-independent estimator
    # (r5: the exact expansion allocates ~28 B/wedge on the host, which
    # OOM-killed a 25M-edge mega-hub run at 130 GB — the driver probes
    # ``oriented_wedge_count`` and passes "sampled" past its budget);
    # False zeros the column (the measured-weaker host-7 configuration).
    if isinstance(include_clustering, np.bool_):
        include_clustering = bool(include_clustering)
    if include_clustering == "sampled":
        from graphmine_tpu.ops.triangles import sampled_clustering_coefficient

        clust = jnp.asarray(sampled_clustering_coefficient(
            graph, simple_edges=simple_edges
        ))
    elif include_clustering is True:
        from graphmine_tpu.ops.triangles import clustering_coefficient

        clust = clustering_coefficient(
            graph, _cached=triangles_cache, simple_edges=simple_edges
        )
    elif include_clustering is False:
        clust = jnp.zeros((graph.num_vertices,), jnp.float32)
    else:
        raise ValueError(
            f"include_clustering must be True, False or 'sampled' "
            f"(got {include_clustering!r})"
        )
    return _vertex_features_jit(graph, communities, clust)


@partial(jax.jit, static_argnames=())
def _vertex_features_jit(
    graph: Graph, communities: jax.Array, clust: jax.Array
) -> jax.Array:
    v = graph.num_vertices
    ones_e = jnp.ones_like(graph.src)
    out_deg = jax.ops.segment_sum(ones_e, graph.src, num_segments=v)
    in_deg = jax.ops.segment_sum(ones_e, graph.dst, num_segments=v)
    msg_deg = graph.degrees()
    comm_size = community_sizes(communities)[communities]
    neigh_deg_sum = jax.ops.segment_sum(
        msg_deg[graph.msg_send], graph.msg_recv, num_segments=v,
        indices_are_sorted=True,
    )
    mean_neigh_deg = neigh_deg_sum / jnp.maximum(msg_deg, 1)
    same = (communities[graph.msg_send] == communities[graph.msg_recv]).astype(
        jnp.int32
    )
    same_cnt = jax.ops.segment_sum(
        same, graph.msg_recv, num_segments=v, indices_are_sorted=True
    )
    same_frac = same_cnt / jnp.maximum(msg_deg, 1)
    distinct = _distinct_neighbor_communities(graph, communities, v)
    feats = jnp.log1p(
        jnp.stack(
            [out_deg, in_deg, msg_deg, comm_size, mean_neigh_deg,
             distinct.astype(jnp.float32)], axis=1
        ).astype(jnp.float32)
    )
    return jnp.concatenate(
        [feats, same_frac[:, None].astype(jnp.float32),
         clust[:, None].astype(jnp.float32)], axis=1
    )


def _distinct_neighbor_communities(
    graph: Graph, communities: jax.Array, v: int
) -> jax.Array:
    """Per-vertex count of distinct communities among message senders.

    Messages are ordered by (receiver, sender community) with two stable
    argsorts — no 64-bit composite key, so it stays int32-safe at any V —
    then run boundaries are segment-summed per receiver."""
    c = communities[graph.msg_send]
    o1 = jnp.argsort(c, stable=True)
    o2 = jnp.argsort(graph.msg_recv[o1], stable=True)
    perm = o1[o2]
    rc, cs = graph.msg_recv[perm], c[perm]
    new_run = jnp.concatenate(
        [jnp.ones(1, dtype=bool), (rc[1:] != rc[:-1]) | (cs[1:] != cs[:-1])]
    )
    return jax.ops.segment_sum(new_run.astype(jnp.int32), rc, num_segments=v)


def vertex_features_host(
    graph: Graph, communities, include_clustering: bool | str = True,
    clustering_samples: int = 64, clustering_seed: int = 0,
):
    """NumPy twin of :func:`vertex_features` for HOST graphs
    (``build_graph(to_device=False)``, r3 scale-out mode): the O(E)/O(M)
    feature columns compute with bincounts and one int64 unique — no
    device transfer of the edge arrays.

    ``include_clustering`` selects the 8th column:

    * ``True`` — the exact wedge pipeline; matches
      :func:`vertex_features` within float32 rounding (tested; host
      accumulation is float64).
    * ``"sampled"`` (r4, the scale-out default) — the wedge-sampled
      estimator (:func:`~graphmine_tpu.ops.triangles.
      sampled_clustering_coefficient`, per-vertex stderr
      ``<= 1/(2*sqrt(clustering_samples))``), whose cost is independent
      of the wedge count — so the full 8-feature set survives at the
      scale where the exact O(sum d+^2) expansion is infeasible.
    * ``False`` — zero the column (7 informative features). The lof-tier
      AUROC harness (``bench.py --tier lof`` detail) scores the 7-feature
      and sampled-8 configs next to the exact-8 headline every run, so
      the as-deployed scale-out quality is a recorded number, not a
      proxy band (VERDICT r3 item 5). r4 real-TPU capture (65K vertices,
      64 injected anomalies, k=128, after the true-f32 distance fix):
      exact-8 **0.9905**, host-7 **0.9940**, sampled-8 **0.9887** — all
      three configs within ~0.005 of each other at this scale.
    """
    v = graph.num_vertices
    src = np.asarray(graph.src)
    dst = np.asarray(graph.dst)
    recv = np.asarray(graph.msg_recv)
    send = np.asarray(graph.msg_send)
    comm = np.asarray(communities)

    out_deg = np.bincount(src, minlength=v).astype(np.float64)
    in_deg = np.bincount(dst, minlength=v).astype(np.float64)
    msg_deg = np.diff(np.asarray(graph.msg_ptr).astype(np.int64)).astype(
        np.float64
    )
    comm_size = np.bincount(comm, minlength=v).astype(np.float64)[comm]
    neigh_deg_sum = np.bincount(recv, weights=msg_deg[send], minlength=v)
    mean_neigh_deg = neigh_deg_sum / np.maximum(msg_deg, 1.0)
    same = comm[send] == comm[recv]
    same_cnt = np.bincount(recv[same], minlength=v).astype(np.float64)
    same_frac = same_cnt / np.maximum(msg_deg, 1.0)
    # distinct neighbor communities: unique (receiver, sender-community)
    # pairs via one int64 composite key (V <= 2^31 so recv * V + comm
    # stays within int64)
    key = recv.astype(np.int64) * v + comm[send].astype(np.int64)
    uniq = np.unique(key)
    distinct = np.bincount((uniq // v).astype(np.int64), minlength=v).astype(
        np.float64
    )
    # Normalize bool-likes first (ADVICE r4): callers threading flags out
    # of numpy/config arrays pass np.True_/np.False_, which the identity
    # checks below would bounce to the typo ValueError.
    if isinstance(include_clustering, np.bool_):
        include_clustering = bool(include_clustering)
    if include_clustering == "sampled":
        from graphmine_tpu.ops.triangles import sampled_clustering_coefficient

        clust = sampled_clustering_coefficient(
            graph, samples=clustering_samples, seed=clustering_seed
        ).astype(np.float64)
    elif include_clustering is True:
        from graphmine_tpu.ops.triangles import clustering_coefficient

        clust = np.asarray(clustering_coefficient(graph), np.float64)
    elif include_clustering is False:
        clust = np.zeros(v, np.float64)
    else:
        # a typo like "sample" must not silently run the exact wedge
        # pipeline — the path documented as infeasible at exactly the
        # scale this twin exists for
        raise ValueError(
            f"include_clustering must be True, False or 'sampled' "
            f"(got {include_clustering!r})"
        )
    feats = np.log1p(
        np.stack(
            [out_deg, in_deg, msg_deg, comm_size, mean_neigh_deg, distinct],
            axis=1,
        )
    ).astype(np.float32)
    return np.concatenate(
        [feats, same_frac[:, None].astype(np.float32),
         clust[:, None].astype(np.float32)], axis=1,
    )


def standardize(feats: jax.Array) -> jax.Array:
    """Zero-mean unit-variance columns (guarding constant features)."""
    mu = feats.mean(axis=0, keepdims=True)
    sd = feats.std(axis=0, keepdims=True)
    return (feats - mu) / jnp.maximum(sd, 1e-6)
