"""Louvain community detection, TPU-native.

SURVEY §7.7 names Louvain-modularity comparison as the scale-up capability
beyond the reference's LPA (``Graphframes.py:81``). Classic Louvain is
sequential (one vertex moves at a time); the TPU design replaces the inner
phase with **synchronous parallel local moves** — every vertex evaluates
the modularity gain of joining each neighboring community and the best
movers switch together — the standard parallel-Louvain formulation,
expressed as sort/segment kernels:

  inner sweep (device, jit):  sort (vertex, neighbor-community) message
      pairs → per-run weight totals → per-vertex argmax of the gain score
      → masked synchronous move (alternating vertex parity breaks the
      two-vertex swap oscillation of synchronous moves)
  level contraction (host):   communities become super-vertices; edge
      weights aggregate; self-loops accumulate internal weight

Levels repeat until modularity stops improving. All device arrays are
padded to powers of two so compiled programs are reused across levels.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.ops.modularity import modularity

_NEG_BIG = -3.4e38
_INT32_MAX = np.iinfo(np.int32).max


@dataclass(frozen=True)
class _Level:
    """One Louvain level: symmetric weighted messages + self-loop weights
    (host-side, padded; recv == padded V is the drop sentinel)."""

    recv: np.ndarray         # int32 [M_pad]
    send: np.ndarray         # int32 [M_pad]
    weight: np.ndarray       # float32 [M_pad]
    self_weight: np.ndarray  # float32 [V_pad]
    num_vertices: int        # true vertex count (<= V_pad)


def _pow2(n: int) -> int:
    return 1 << max(int(max(n, 1) - 1).bit_length(), 3)


def _pad_level(recv, send, w, self_w, v) -> _Level:
    m_pad, v_pad = _pow2(len(recv)), _pow2(v)
    pad = m_pad - len(recv)
    recv = np.concatenate([recv.astype(np.int32), np.full(pad, v_pad, np.int32)])
    send = np.concatenate([send.astype(np.int32), np.zeros(pad, np.int32)])
    w = np.concatenate([w.astype(np.float32), np.zeros(pad, np.float32)])
    self_w = np.concatenate([self_w.astype(np.float32), np.zeros(v_pad - v, np.float32)])
    return _Level(recv, send, w, self_w, v)


def _level_from_graph(graph: Graph) -> _Level:
    if not graph.symmetric:
        raise ValueError(
            "louvain needs the symmetric message list (both edge "
            "directions); rebuild the graph with symmetric=True"
        )
    from graphmine_tpu.ops.modularity import message_weights

    recv = np.asarray(graph.msg_recv)
    send = np.asarray(graph.msg_send)
    v = graph.num_vertices
    # Shared self-loop/weight convention (modularity.message_weights) so the
    # gain computation optimizes exactly the score modularity() reports.
    w, self_w = (np.asarray(a, dtype=np.float32) for a in message_weights(graph))
    return _pad_level(recv, send, w, self_w, v)


@partial(jax.jit, static_argnames=("num_vertices", "max_sweeps"))
def _local_moves(
    recv, send, weight, self_weight, num_vertices: int,
    gamma: float, max_sweeps: int, init=None,
):
    """Synchronous gain-based local moves until no vertex moves (bounded by
    ``max_sweeps``). Operates on padded arrays; ``num_vertices`` is the
    padded size (padding vertices are isolated and never move). ``init``:
    optional starting partition (default singletons — classic Louvain;
    Leiden seeds later levels with the previous level's communities).
    Returns int32 community labels [num_vertices]."""
    v = num_vertices
    w = weight.astype(jnp.float32)
    k = jax.ops.segment_sum(w, recv, num_segments=v) + 2.0 * self_weight
    two_m = jnp.maximum(k.sum(), 1e-12)
    vertex_ids = jnp.arange(v, dtype=jnp.int32)
    m = recv.shape[0]

    def sweep(comm, it):
        sigma_tot = jax.ops.segment_sum(k, comm, num_segments=v)
        comm_size = jax.ops.segment_sum(jnp.ones((v,), jnp.int32), comm, num_segments=v)
        # Candidate messages: neighbor communities, plus a zero-weight
        # "stay" candidate per vertex so the current community is always
        # scored (w_{i->c_i} accumulates onto it via the run sum).
        seg = jnp.concatenate([recv, vertex_ids])
        val = jnp.concatenate([comm[send], comm])
        wgt = jnp.concatenate([w, jnp.zeros((v,), jnp.float32)])
        seg_s, val_s, w_s = lax.sort((seg, val, wgt), num_keys=2)
        new_run = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_),
             (seg_s[1:] != seg_s[:-1]) | (val_s[1:] != val_s[:-1])]
        )
        run_id = jnp.cumsum(new_run.astype(jnp.int32)) - 1
        run_w = jax.ops.segment_sum(w_s, run_id, num_segments=m + v)[run_id]
        # Gain score for vertex i joining community d (terms constant in i
        # dropped):  w_{i->d} - gamma * k_i * Sigma_tot'_d / (2m), with
        # Sigma_tot' excluding i itself when d is i's current community.
        seg_c = jnp.clip(seg_s, 0, v - 1)
        k_i = k[seg_c]
        own = val_s == comm[seg_c]
        tot_adj = sigma_tot[jnp.clip(val_s, 0, v - 1)] - jnp.where(own, k_i, 0.0)
        score = jnp.where(
            seg_s < v, run_w - gamma * k_i * tot_adj / two_m, _NEG_BIG
        )
        best = jax.ops.segment_max(score, seg_s, num_segments=v)
        is_best = (score >= best[seg_c]) & (seg_s < v)
        cand = jnp.where(is_best, val_s, _INT32_MAX)
        choice = jax.ops.segment_min(cand, seg_s, num_segments=v)
        choice = jnp.where(choice == _INT32_MAX, comm, choice)
        # Strict improvement over staying, with an epsilon against float
        # noise.
        stay = jnp.where((seg_s < v) & own, score, _NEG_BIG)
        stay_best = jax.ops.segment_max(stay, seg_s, num_segments=v)
        improves = best > stay_best + 1e-4
        # Two synchronous-move safeguards (both needed — parity alone does
        # not serialize same-parity neighbors): (a) alternating vertex
        # parity serializes half of all conflicting moves; (b) the
        # singleton-ordering rule of parallel Louvain — a singleton vertex
        # may join another singleton's community only in the direction of
        # the smaller community id — breaks the remaining two-singleton
        # swap cycle, which would otherwise oscillate forever.
        may_move = (vertex_ids % 2) == (it % 2)
        i_single = comm_size[comm] == 1
        tgt_single = comm_size[jnp.clip(choice, 0, v - 1)] == 1
        swap_risk = i_single & tgt_single & (choice > comm)
        new_comm = jnp.where(improves & may_move & ~swap_risk, choice, comm)
        moved = jnp.sum(new_comm != comm, dtype=jnp.int32)
        return new_comm, moved

    def cond(state):
        _, quiet, it = state
        # Parity alternation means a single quiet sweep only proves half
        # the vertices have no move; stop after a full quiet even+odd pair.
        return (quiet < 2) & (it < max_sweeps)

    def body(state):
        comm, quiet, it = state
        comm, moved = sweep(comm, it)
        quiet = jnp.where(moved > 0, jnp.int32(0), quiet + 1)
        return comm, quiet, it + 1

    comm0 = vertex_ids if init is None else jnp.asarray(init, jnp.int32)
    comm, _, _ = lax.while_loop(cond, body, (comm0, jnp.int32(0), jnp.int32(0)))
    return comm


def _contract(level: _Level, comm: np.ndarray):
    """Host-side level contraction: communities -> super-vertices.

    Returns ``(new_level, dense)`` where ``dense[i]`` is the super-vertex of
    old vertex ``i``. The O(V+M) host work per level mirrors the host-side
    partitioning in :mod:`graphmine_tpu.parallel.sharded` — levels shrink
    geometrically so level 0 dominates.
    """
    v = level.num_vertices
    uniq, dense = np.unique(comm[:v], return_inverse=True)
    c = len(uniq)
    real = level.recv < len(level.self_weight)
    cu = dense[level.recv[real]]
    cv = dense[level.send[real]]
    w = level.weight[real]
    internal = cu == cv
    new_self = np.zeros(c, np.float64)
    np.add.at(new_self, dense, level.self_weight[:v].astype(np.float64))
    np.add.at(new_self, cu[internal], 0.5 * w[internal].astype(np.float64))
    key = cu[~internal].astype(np.int64) * c + cv[~internal]
    pairs, pair_inv = np.unique(key, return_inverse=True)
    new_w = np.zeros(len(pairs), np.float64)
    np.add.at(new_w, pair_inv, w[~internal].astype(np.float64))
    new_recv = (pairs // c).astype(np.int32)
    new_send = (pairs % c).astype(np.int32)
    new_level = _pad_level(new_recv, new_send, new_w, new_self, c)
    return new_level, dense.astype(np.int32)


def leiden(
    graph: Graph,
    gamma: float = 1.0,
    max_levels: int = 12,
    max_sweeps: int = 32,
    tol: float = 1e-6,
):
    """Leiden-style community detection: Louvain local moves plus a
    **refinement phase** before each contraction.

    Refinement re-runs the local moves from singletons with edge weights
    masked to intra-community messages only, so aggregation merges
    connected intra-community groups instead of whole (possibly
    badly-connected) Louvain communities; the next level's moves start
    from the previous communities projected onto the refined
    super-vertices (the Leiden aggregate-with-initial-partition rule,
    here as a deterministic variant of Traag et al.'s randomized
    refinement). Returns ``(labels, q)`` like :func:`louvain`.

    Measured behavior (pinned by tests): modularity within a fraction of
    a percent of Louvain's either way on SBM/R-MAT families — sometimes
    above, e.g. +0.011 on one R-MAT seed — while every community that
    Louvain leaves internally *disconnected* (10 of them on that same
    graph) is split into connected pieces, the property Leiden exists
    to provide.
    """
    level = _level_from_graph(graph)
    mapping = np.arange(graph.num_vertices, dtype=np.int32)
    best_labels = mapping
    best_q = float(modularity(jnp.asarray(mapping), graph, gamma))
    v_pad = len(level.self_weight)
    init = np.arange(v_pad, dtype=np.int32)  # level 0: singletons
    for _ in range(max_levels):
        v_pad = len(level.self_weight)
        comm = np.asarray(_local_moves(
            level.recv, level.send, level.weight, level.self_weight,
            num_vertices=v_pad, gamma=gamma, max_sweeps=max_sweeps,
            init=jnp.asarray(init),
        ))
        # partition of record at this level, flattened to original vertices
        flat = comm[mapping]
        _, flat_dense = np.unique(flat, return_inverse=True)
        q = float(modularity(jnp.asarray(flat_dense.astype(np.int32)), graph, gamma))
        if q > best_q + tol:
            best_labels, best_q = flat_dense.astype(np.int32), q
        # refinement: local moves from singletons over intra-community
        # messages only (cross-community weights masked to zero, so no
        # merge can cross a community boundary)
        recv_c = np.clip(level.recv, 0, v_pad - 1)
        intra = comm[level.send] == comm[recv_c]
        refined = np.asarray(_local_moves(
            level.recv, level.send,
            np.where(intra, level.weight, 0.0).astype(np.float32),
            level.self_weight, num_vertices=v_pad, gamma=gamma,
            max_sweeps=max_sweeps,
            # explicit singleton init keeps one compiled program per shape
            # (init=None would be a second jit variant of the same kernel)
            init=jnp.arange(v_pad, dtype=jnp.int32),
        ))
        new_level, dense = _contract(level, refined)
        # next level's initial partition: each refined super-vertex starts
        # in the community its members came from
        c = new_level.num_vertices
        first_member = np.full(c, np.iinfo(np.int64).max)
        np.minimum.at(first_member, dense,
                      np.arange(level.num_vertices, dtype=np.int64))
        sv_comm = comm[first_member]
        _, sv_comm_dense = np.unique(sv_comm, return_inverse=True)
        next_pad = len(new_level.self_weight)
        init = np.arange(next_pad, dtype=np.int32)
        init[:c] = sv_comm_dense.astype(np.int32)
        mapping = dense[mapping]
        if new_level.num_vertices >= level.num_vertices or q <= best_q - tol:
            break
        level = new_level
    # Final guarantee pass: split any internally disconnected community
    # into its connected components. Always modularity-non-decreasing —
    # for a community whose parts share no internal edge, separating them
    # removes no intra-community weight and shrinks the Σ_tot² penalty.
    labels = _split_disconnected(best_labels, graph)
    q = float(modularity(jnp.asarray(labels), graph, gamma))
    return jnp.asarray(labels, jnp.int32), q


def _split_disconnected(labels: np.ndarray, graph: Graph) -> np.ndarray:
    """Relabel so every community is a connected piece: connected
    components of the intra-community edge subgraph (vertices with no
    intra-community edge become singletons, which also never lowers Q)."""
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.cc import connected_components

    labels = np.asarray(labels)
    src = np.asarray(graph.src)
    dst = np.asarray(graph.dst)
    keep = labels[src] == labels[dst]
    sub = build_graph(src[keep], dst[keep], num_vertices=graph.num_vertices)
    comp = np.asarray(connected_components(sub))
    _, dense = np.unique(comp, return_inverse=True)
    return dense.astype(np.int32)


def louvain(
    graph: Graph,
    gamma: float = 1.0,
    max_levels: int = 12,
    max_sweeps: int = 32,
    tol: float = 1e-6,
):
    """Louvain community labels + modularity for a :class:`Graph`.

    Returns ``(labels, q)``: int32 labels ``[V]`` (values are level-0
    vertex-dense community ids) and the float modularity of that partition
    on the input graph. Deterministic: synchronous sweeps with smallest-id
    tie-breaks, no randomness.
    """
    level = _level_from_graph(graph)
    mapping = np.arange(graph.num_vertices, dtype=np.int32)
    best_labels, best_q = mapping, float(modularity(jnp.asarray(mapping), graph, gamma))
    for _ in range(max_levels):
        comm = np.asarray(
            _local_moves(
                level.recv, level.send, level.weight, level.self_weight,
                num_vertices=len(level.self_weight), gamma=gamma,
                max_sweeps=max_sweeps,
            )
        )
        new_level, dense = _contract(level, comm)
        mapping = dense[mapping]
        q = float(modularity(jnp.asarray(mapping), graph, gamma))
        if q > best_q + tol:
            best_labels, best_q = mapping.copy(), q
        shrunk = new_level.num_vertices < level.num_vertices
        if not shrunk or q <= best_q - tol:
            break
        level = new_level
    return jnp.asarray(best_labels, jnp.int32), best_q
