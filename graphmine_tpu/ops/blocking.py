"""Propagation-blocking superstep engine: destination-binned message tiles.

Every superstep family (LPA / CC / PageRank) is **random-gather bound**:
the r4 width-ladder work drove the fused bucketed kernel to the measured
~130M gathered-slots/s roofline (BENCH_r05 ``roofline`` tier;
``ops/bucketed_mode.py`` header), so further chip-rate gains require
changing the *memory-access pattern*, not the arithmetic. This module
implements propagation blocking (PAPERS.md: arXiv 2011.08451 "Optimizing
Graph Processing and Preprocessing with Hardware Assisted Propagation
Blocking"; arXiv 1608.01362 "Making Caches Work for Graph Analytics") as
a third plan family next to the sort path and the degree-bucketed plan:

1. **Host plan** (:class:`BlockedPlan`, built once per graph like the
   message CSR itself): destination vertices are grouped into contiguous
   **bins** sized so one bin's message tile fits on-chip (VMEM is ~16 MB
   per core — ``/opt/skills/guides/pallas_guide.md``; the default
   ``DEFAULT_TILE_SLOTS`` int32 tile is 1 MiB). Bin boundaries snap to
   vertex boundaries so no vertex's messages straddle two tiles, and the
   CSR (already destination-sorted) makes each bin's messages one
   contiguous slice.

2. **Bin phase** (per superstep, on device): stream the per-vertex values
   once in *sender-major* order — ``values[src_sorted]`` with monotone
   non-decreasing indices, a sequential pass over the value vector
   instead of a random walk over it — and scatter each message into its
   host-precomputed slot of the destination-binned tile. The scatter's
   active window at any point of the stream is one insertion frontier per
   bin (the propagation-blocking locality argument; the ``blocking``
   bench tier measures the resulting binned-pass slots/s against the
   random-gather slots/s on the same message volume).

3. **Reduce phase**: each destination's messages are a contiguous run
   *inside its bin's tile*, so the reduce reuses the bucketed-mode width
   ladder within the bin — dense ``[n, w]`` rows gathered with
   **tile-local** indices (bounded by the tile size, not V) and resolved
   by the existing row-mode / row-min / row-sum machinery
   (:func:`~graphmine_tpu.ops.bucketed_mode._bucket_mode` et al.), so the
   r4 padding wins stack with the layout change rather than compete.

Row reductions are order-independent within a row (the row mode sorts or
pairwise-counts; min and the weighted argmax are commutative with the
same smallest-label tie-break), so blocked LPA/CC supersteps are
**bit-identical** to the sort-based ``segment_mode`` oracle — pinned by
``tests/test_blocking.py`` across power-law / ring / self-loop /
isolated-vertex / duplicate-edge graphs, fused and sharded.

Unlike the fused bucketed plan there is no mega-hub histogram path: a
hub's messages stay contiguous in its (oversized) bin tile and ride a
wide sort row on the 1.5x-extended ladder — the blocked layout is also
the gate to bigger-than-HBM graphs, since bins stream tile-by-tile
instead of materializing one global gather.
"""

from __future__ import annotations

import dataclasses
import os
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from graphmine_tpu.graph.container import Graph
from graphmine_tpu.ops.bucketed_mode import (
    _SENTINEL,
    _bucket_mode,
    _bucket_wmode,
    _extend_widths,
)

# ---- plan-family crossover policy (single owner) ---------------------------
# Measured provenance (same treatment as the r5 bucketed flip and the r6
# IVF flip):
#   * bucketed beats sort from ~2^16 messages (r1 measurement, the
#     threshold label_propagation has shipped since; plan build amortizes
#     past there).
#   * blocked targets the regime where the value table no longer behaves
#     cache-resident: the random gather pays full HBM latency per slot
#     once the [V] int32 table is far beyond on-chip memory (VMEM ~16 MB
#     => ~2^22 int32 entries; BLOCKED_MIN_VERTICES = 2^21 keeps one
#     doubling of headroom below that wall), and the two-pass layout's
#     extra tile traffic amortizes only at ~2^22+ messages. The measured
#     anchor is the `blocking` bench tier (binned-pass vs random-gather
#     slots/s on the same message volume — `python bench.py --tier
#     blocking`, record `blocking_binned_slots_per_sec`); the current
#     container only holds its CPU-fallback record
#     (`blocking_binned_slots_per_sec_cpu_fallback`), so these constants
#     are set from the VMEM capacity model above pending the silicon
#     capture (ROADMAP backlog). Env overrides let a measured part move
#     the wall without a code change.
BUCKETED_MIN_MESSAGES = 1 << 16
BLOCKED_MIN_MESSAGES = 1 << 22
BLOCKED_MIN_VERTICES = 1 << 21

# 2D edge partition with neighbor-only frontier exchange (r16): on a
# >= 2-device mesh the exchange term is the scaling ceiling ROADMAP
# names — the one-all_gather families ship 4·Vc·(D-1) bytes per chip per
# superstep regardless of how small the live frontier is, while the 2D
# family ships 4·Σ_peer |boundary(peer)| (range-partitioned power-law
# CSRs keep boundaries well under Vc; serve-path repair frontiers keep
# them near empty). The message floor mirrors the bucketed crossover's
# rationale: the per-peer boundary tables are one more O(M log M) host
# pass (one sorted-unique per shard + a positional remap), which below
# ~16K messages would dominate the run it plans. Unmeasured
# on silicon yet (the `exchange` bench tier is the capture point — its
# modeled-bytes record is honest on CPU); env overrides move the wall
# without a code change.
SHARDED2D_MIN_MESSAGES = 1 << 14
SHARDED2D_MIN_DEVICES = 2

#: One bin's message-tile budget (int32 slots). 2^18 slots = 1 MiB —
#: small against the ~16 MB/core VMEM so the tile, its row matrices and
#: the reduce transients co-reside on chip (docs/DESIGN.md "Propagation-
#: blocking binned layout").
DEFAULT_TILE_SLOTS = 1 << 18

FAMILIES = ("blocked", "bucketed", "sort", "sharded_2d")


def crossover_thresholds() -> dict:
    """The ACTIVE family-crossover constants, env overrides applied — the
    numbers that decide every ``plan="auto"`` resolution. One owner for
    both the selection itself (:func:`select_superstep_family`) and the
    provenance records (``impl_selected`` carries this dict, so a policy
    flip is explainable from the JSONL alone — ISSUE 12 satellite)."""
    return {
        "bucketed_min_messages": BUCKETED_MIN_MESSAGES,
        "blocked_min_messages": int(
            os.environ.get(
                "GRAPHMINE_BLOCKED_MIN_MESSAGES", BLOCKED_MIN_MESSAGES
            )
        ),
        "blocked_min_vertices": int(
            os.environ.get(
                "GRAPHMINE_BLOCKED_MIN_VERTICES", BLOCKED_MIN_VERTICES
            )
        ),
        "sharded2d_min_messages": int(
            os.environ.get(
                "GRAPHMINE_SHARDED2D_MIN_MESSAGES", SHARDED2D_MIN_MESSAGES
            )
        ),
        "sharded2d_min_devices": int(
            os.environ.get(
                "GRAPHMINE_SHARDED2D_MIN_DEVICES", SHARDED2D_MIN_DEVICES
            )
        ),
    }


def select_superstep_family(
    num_vertices: int, num_messages: int, requested: str = "auto",
    weighted: bool = False, num_devices: int = 1,
) -> tuple[str, str]:
    """Resolve the superstep plan family — THE single policy owner behind
    ``plan="auto"`` in ``ops/lpa.py`` / ``ops/cc.py`` / ``ops/pagerank.py``
    and ``pipeline/planner.plan_superstep``.

    Returns ``(family, reason)`` with ``family`` in :data:`FAMILIES`.
    ``requested`` forces a family (still validated); the
    ``GRAPHMINE_SUPERSTEP_FAMILY`` env var forces it process-wide, and
    ``GRAPHMINE_BLOCKED_MIN_MESSAGES`` / ``GRAPHMINE_BLOCKED_MIN_VERTICES``
    move the blocked crossover (tests, parts with different on-chip
    capacity). ``weighted`` is accepted for signature stability: every
    family carries the slot-aligned weight payload, so weights never
    change the selection (the weighted contract is enforced at superstep
    time — see :func:`lpa_superstep_blocked`).

    ``num_devices`` (r16) gates the ``sharded_2d`` family: on a >= 2
    device mesh past ``SHARDED2D_MIN_MESSAGES`` the 2D edge partition's
    neighbor-only exchange replaces the per-superstep label all_gather
    (``parallel/sharded.py``: labels sharded, per-peer boundary
    ``ppermute``). Single-device resolutions (every fused caller) never
    see it; an explicit ``requested="sharded_2d"`` on fewer than 2
    devices is a loud error, while the process-wide env override simply
    does not apply there (it targets the sharded paths; raising would
    break the fused ops under a global override).
    """
    del weighted
    thr = crossover_thresholds()
    d = int(num_devices)
    if requested != "auto":
        if requested not in FAMILIES:
            raise ValueError(
                f"unknown superstep family {requested!r}; expected one of "
                f"{FAMILIES} or 'auto'"
            )
        if requested == "sharded_2d" and d < 2:
            raise ValueError(
                "superstep family 'sharded_2d' needs a >= 2-device mesh "
                f"(num_devices={d}); its neighbor-only exchange has no "
                "single-device meaning — use 'blocked' there"
            )
        return requested, f"requested {requested!r}"
    env = os.environ.get("GRAPHMINE_SUPERSTEP_FAMILY")
    if env and not (env == "sharded_2d" and d < 2):
        if env not in FAMILIES:
            raise ValueError(
                f"GRAPHMINE_SUPERSTEP_FAMILY={env!r} is not one of {FAMILIES}"
            )
        return env, f"GRAPHMINE_SUPERSTEP_FAMILY={env} (env override)"
    if (
        d >= thr["sharded2d_min_devices"]
        and num_messages >= thr["sharded2d_min_messages"]
    ):
        return "sharded_2d", (
            f"D={d} >= {thr['sharded2d_min_devices']} and "
            f"M={num_messages} >= {thr['sharded2d_min_messages']}: 2D edge "
            "partition — neighbor-only boundary exchange beats the "
            "4·Vc·(D-1)-byte label all_gather (bench tier 'exchange')"
        )
    min_m = thr["blocked_min_messages"]
    min_v = thr["blocked_min_vertices"]
    if num_messages >= min_m and num_vertices >= min_v:
        return "blocked", (
            f"V={num_vertices} >= {min_v} and M={num_messages} >= {min_m}: "
            "value table past on-chip capacity — destination-binned tiles "
            "beat the random-gather roofline (bench tier 'blocking')"
        )
    if num_messages >= BUCKETED_MIN_MESSAGES:
        return "bucketed", (
            f"M={num_messages} >= {BUCKETED_MIN_MESSAGES}: degree-bucketed "
            "dense rows amortize the host plan build (r1 crossover)"
        )
    return "sort", (
        f"M={num_messages} < {BUCKETED_MIN_MESSAGES}: sort-based "
        "segment_mode superstep (plan build would dominate)"
    )


# ---- host plan construction ------------------------------------------------


def _bin_bounds(ptr: np.ndarray, tile_slots: int) -> np.ndarray:
    """Destination-bin vertex boundaries (int64 ``[n_bins + 1]``): greedy
    contiguous vertex ranges of at most ``tile_slots`` messages each,
    snapped to vertex boundaries. A vertex whose own degree exceeds the
    budget gets a dedicated (oversized) bin — its tile is then the max
    over bins, but its messages stay one contiguous run."""
    v = len(ptr) - 1
    bounds = [0]
    while bounds[-1] < v:
        start = bounds[-1]
        end = int(np.searchsorted(ptr, ptr[start] + tile_slots, side="right")) - 1
        bounds.append(min(max(end, start + 1), v))
    return np.asarray(bounds, dtype=np.int64)


def _blocked_layout(
    ptr: np.ndarray,
    send: np.ndarray,
    tile_slots: int,
    widths: np.ndarray | None = None,
    tile_width: int | None = None,
    weights: np.ndarray | None = None,
):
    """Host core of the blocked layout, shared by the single-device
    builder and the per-shard stacked builder (``parallel/sharded.py``).

    ``ptr``/``send``/``weights``: the (local) message CSR. ``widths``: a
    shared width ladder (the sharded builder passes one ladder for all
    shards; ``None`` extends the default ladder to this CSR's max
    degree). ``tile_width``: force the per-bin tile width Tb (the sharded
    builder passes the max across shards so SPMD shapes stay uniform).

    Returns ``(src_sorted, scatter_pos, bounds, tb, rows)`` where
    ``rows`` maps width-class index ``c`` -> ``(vertex_rows, idx_mat,
    weight_mat | None)``: per-destination dense rows whose ``idx_mat``
    entries are *tile slots* (``-1`` marks padding — the caller rewrites
    it to its tile's sentinel slot).
    """
    ptr = np.asarray(ptr, dtype=np.int64)
    deg = ptr[1:] - ptr[:-1]
    m = int(ptr[-1])
    bounds = _bin_bounds(ptr, tile_slots)
    n_bins = len(bounds) - 1
    bin_msg_start = ptr[bounds[:-1]]                     # [n_bins]
    bin_sizes = ptr[bounds[1:]] - bin_msg_start
    tb = int(bin_sizes.max(initial=1))
    tb = -(-tb // 8) * 8
    if tile_width is not None:
        if tile_width < tb:
            raise ValueError(
                f"tile_width {tile_width} below this CSR's max bin size {tb}"
            )
        tb = tile_width

    # Tile slot of every CSR message position: bin-major, CSR order
    # within the bin (so each destination's messages stay contiguous).
    pos = np.arange(m, dtype=np.int64)
    bin_of = np.searchsorted(bin_msg_start, pos, side="right") - 1
    slot_of_csr = bin_of * tb + (pos - bin_msg_start[bin_of])

    # Sender-major stream order (stable: equal senders keep CSR order so
    # the layout is deterministic). The phase-1 gather indices
    # (src_sorted) are monotone non-decreasing by construction.
    order = np.argsort(send[:m], kind="stable")
    src_sorted = send[:m][order].astype(np.int32)
    scatter_pos = slot_of_csr[order].astype(np.int32)

    if widths is None:
        widths = _extend_widths(int(deg.max(initial=1)))
    classes = np.searchsorted(widths, np.maximum(deg, 1))
    eligible = deg > 0
    row_start = np.zeros(len(deg), dtype=np.int64)
    row_start[eligible] = slot_of_csr[ptr[:-1][eligible]]
    w_arr = None if weights is None else np.asarray(weights, np.float32)

    rows = {}
    for c in np.unique(classes[eligible]):
        w = int(widths[c])
        vr = np.nonzero((classes == c) & eligible)[0]
        offs = np.arange(w, dtype=np.int64)[None, :]
        valid = offs < deg[vr][:, None]
        idx = np.where(valid, row_start[vr][:, None] + offs, -1)
        wmat = None
        if w_arr is not None:
            cidx = np.minimum(ptr[vr][:, None] + offs, max(m - 1, 0))
            wmat = np.where(valid, w_arr[cidx], 0.0).astype(np.float32)
        rows[int(c)] = (vr, idx, wmat)
    return src_sorted, scatter_pos, bounds, tb, rows


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class BlockedPlan:
    """Static propagation-blocking plan for one graph's message CSR.

    ``src_sorted``: int32 ``[M]`` — sender vertex ids in sender-major
    order (monotone; the phase-1 sequential value pass).
    ``scatter_pos``: int32 ``[M]`` — each streamed message's slot in the
    destination-binned tile (bin-major; CSR order within a bin).
    ``row_idx[c]``: int32 ``[n_c, w_c]`` — per-destination dense rows of
    *tile slots* on the shared width ladder (padding = the tile's
    reserved sentinel slot). ``row_vertex[c]``: int32 ``[n_c]`` — the
    owning destination vertex ids. ``weight_mat[c]``: optional float32
    ``[n_c, w_c]`` slot-aligned message weights (padding 0) — present iff
    built from a weighted CSR.
    """

    src_sorted: jax.Array
    scatter_pos: jax.Array
    row_idx: tuple
    row_vertex: tuple
    num_vertices: int = dataclasses.field(metadata=dict(static=True))
    num_messages: int = dataclasses.field(metadata=dict(static=True))
    num_bins: int = dataclasses.field(metadata=dict(static=True))
    tile_slots: int = dataclasses.field(metadata=dict(static=True))
    tile_alloc: int = dataclasses.field(metadata=dict(static=True))
    weight_mat: tuple | None = None

    @property
    def num_width_classes(self) -> int:
        return len(self.row_idx)

    @property
    def padded_row_slots(self) -> int:
        """Total reduce-phase gather slots (incl. row padding)."""
        return int(sum(int(r.shape[0]) * int(r.shape[1]) for r in self.row_idx))

    @classmethod
    def from_graph(cls, graph: Graph, tile_slots: int | None = None) -> "BlockedPlan":
        """Build from a (device- or host-resident) graph; fetches
        ``msg_ptr``/``msg_send`` (and ``msg_weight``) to host once —
        the same amortization the message CSR itself gets."""
        w = None if graph.msg_weight is None else np.asarray(graph.msg_weight)
        return cls.from_ptr(
            np.asarray(graph.msg_ptr), graph.num_vertices,
            np.asarray(graph.msg_send), weights_sorted=w,
            tile_slots=tile_slots,
        )

    @classmethod
    def from_ptr(
        cls,
        ptr: np.ndarray,
        num_vertices: int,
        send_sorted: np.ndarray,
        weights_sorted: np.ndarray | None = None,
        tile_slots: int | None = None,
    ) -> "BlockedPlan":
        """Host-pure construction from the message CSR (``ptr`` int
        ``[V+1]``, ``send_sorted`` int32 ``[M]`` in CSR order,
        ``weights_sorted`` optional float ``[M]``)."""
        if tile_slots is None:
            tile_slots = int(
                os.environ.get("GRAPHMINE_BLOCKED_TILE_SLOTS", DEFAULT_TILE_SLOTS)
            )
        if tile_slots < 1:
            raise ValueError("tile_slots must be >= 1")
        ptr = np.asarray(ptr, dtype=np.int64)
        m = int(ptr[-1]) if len(ptr) else 0
        if m >= np.iinfo(np.int32).max:
            raise ValueError("message count exceeds int32; shard the build")
        send_sorted = np.asarray(send_sorted, dtype=np.int32)
        if m == 0:
            return cls(
                src_sorted=jnp.zeros((0,), jnp.int32),
                scatter_pos=jnp.zeros((0,), jnp.int32),
                row_idx=(), row_vertex=(),
                num_vertices=num_vertices, num_messages=0,
                num_bins=0, tile_slots=tile_slots, tile_alloc=1,
                weight_mat=None if weights_sorted is None else (),
            )
        src_sorted, scatter_pos, bounds, tb, rows = _blocked_layout(
            ptr, send_sorted, tile_slots, weights=weights_sorted,
        )
        n_bins = len(bounds) - 1
        # One reserved slot past the bins: never scattered to, stays at
        # the reduce's fill value — the target of every row padding slot
        # (bins padded short of Tb would also work, but a FULL final bin
        # leaves no guaranteed-unwritten slot).
        tile_alloc = n_bins * tb + 1
        sentinel_slot = tile_alloc - 1
        row_idx, row_vertex, weight_mat = [], [], []
        for c in sorted(rows):
            vr, idx, wmat = rows[c]
            row_vertex.append(jnp.asarray(vr.astype(np.int32)))
            row_idx.append(
                jnp.asarray(
                    np.where(idx < 0, sentinel_slot, idx).astype(np.int32)
                )
            )
            if wmat is not None:
                weight_mat.append(jnp.asarray(wmat))
        return cls(
            src_sorted=jnp.asarray(src_sorted),
            scatter_pos=jnp.asarray(scatter_pos),
            row_idx=tuple(row_idx),
            row_vertex=tuple(row_vertex),
            num_vertices=num_vertices,
            num_messages=m,
            num_bins=n_bins,
            tile_slots=tb,
            tile_alloc=tile_alloc,
            weight_mat=tuple(weight_mat) if weights_sorted is not None else None,
        )


def build_graph_and_blocked_plan(
    src, dst, num_vertices: int | None = None, symmetric: bool = True,
    use_native: bool = True, edge_weights=None, tile_slots: int | None = None,
):
    """Build the :class:`Graph` and its :class:`BlockedPlan` from ONE
    message-CSR pass — the blocked twin of
    :func:`~graphmine_tpu.ops.bucketed_mode.build_graph_and_plan` (the
    driver's single-device fast path when the planner resolves the
    ``blocked`` family)."""
    from graphmine_tpu.graph.container import (
        _graph_from_csr,
        _message_csr,
        _prepare_edges,
        _prepare_weights,
    )

    src, dst, num_vertices = _prepare_edges(src, dst, num_vertices)
    w = _prepare_weights(edge_weights, src)
    ptr, recv, send, w_sorted = _message_csr(
        src, dst, num_vertices, symmetric, use_native, weights=w
    )
    graph = _graph_from_csr(
        src, dst, ptr, recv, send, num_vertices, symmetric, msg_weight=w_sorted
    )
    plan = BlockedPlan.from_ptr(
        ptr, num_vertices, send, weights_sorted=w_sorted, tile_slots=tile_slots
    )
    return graph, plan


# ---- device supersteps -----------------------------------------------------


def _blocked_tile(plan: BlockedPlan, values_pad: jax.Array, fill) -> jax.Array:
    """The two blocked passes: phase 1 streams ``values_pad`` in
    sender-major order (monotone gather indices), phase 2 scatters each
    message into its destination bin's tile slot. Unwritten slots (bin
    padding + the reserved sentinel slot) keep ``fill``, which the reduce
    rows rely on (mode/min sentinel, sum identity 0)."""
    vals = values_pad[plan.src_sorted]
    tile = jnp.full((plan.tile_alloc,), fill, values_pad.dtype)
    return tile.at[plan.scatter_pos].set(vals, unique_indices=True)


def _check_plan(plan: BlockedPlan, labels: jax.Array, graph: Graph | None):
    if labels.shape[0] != plan.num_vertices or (
        graph is not None and graph.num_messages != plan.num_messages
    ):
        raise ValueError(
            f"plan built for V={plan.num_vertices}, M={plan.num_messages} "
            f"but got V={labels.shape[0]}"
            + (f", M={graph.num_messages}" if graph is not None else "")
            + " — plan/graph mismatch"
        )


def lpa_superstep_blocked(
    labels: jax.Array, graph: Graph, plan: BlockedPlan
) -> jax.Array:
    """One LPA superstep via the blocked plan — semantics identical to
    :func:`graphmine_tpu.ops.lpa.lpa_superstep` (bit-identical labels,
    pinned by ``tests/test_blocking.py``).

    Weighted graphs are first-class: the plan's slot-aligned
    ``weight_mat`` switches the row modes to the per-label weight-sum
    argmax. A weighted graph with a weight-less plan **refuses loudly**
    (the serving layer's contract for weighted snapshots,
    ``serve/delta.py``) — silently dropping weights would change weighted
    LPA's semantics; rebuild via :meth:`BlockedPlan.from_graph` or route
    to the sort/bucketed path."""
    if graph.msg_weight is not None and plan.weight_mat is None:
        raise ValueError(
            "graph carries msg_weight but the blocked plan has no weight "
            "payload; build it with BlockedPlan.from_graph / "
            "build_graph_and_blocked_plan(edge_weights=...), or pass "
            "plan=None / a weighted bucketed plan — weights are never "
            "silently dropped"
        )
    _check_plan(plan, labels, graph)
    lbl_pad = jnp.concatenate(
        [labels.astype(jnp.int32), jnp.full((1,), _SENTINEL, jnp.int32)]
    )
    tile = _blocked_tile(plan, lbl_pad, _SENTINEL)
    out = labels.astype(jnp.int32)
    wmats = plan.weight_mat or (None,) * len(plan.row_idx)
    for ids, ridx, wmat in zip(plan.row_vertex, plan.row_idx, wmats):
        mat = tile[ridx]
        mode = _bucket_mode(mat) if wmat is None else _bucket_wmode(mat, wmat)
        out = out.at[ids].set(mode, unique_indices=True, mode="drop")
    return out


def cc_superstep_blocked(labels: jax.Array, plan: BlockedPlan) -> jax.Array:
    """One CC superstep on the blocked plan — the min-reduce twin of
    :func:`lpa_superstep_blocked`, step-for-step identical to
    :func:`graphmine_tpu.ops.cc.cc_superstep` (min over own + incoming
    labels, then pointer jump); padding slots carry the int32-max
    sentinel, which never wins a min."""
    _check_plan(plan, labels, None)
    lbl_pad = jnp.concatenate(
        [labels.astype(jnp.int32), jnp.full((1,), _SENTINEL, jnp.int32)]
    )
    tile = _blocked_tile(plan, lbl_pad, _SENTINEL)
    new = labels.astype(jnp.int32)
    for ids, ridx in zip(plan.row_vertex, plan.row_idx):
        row_min = jnp.min(tile[ridx], axis=1)
        new = new.at[ids].min(row_min, unique_indices=True, mode="drop")
    return jnp.minimum(new, new[new]).astype(jnp.int32)


def blocked_inflow(plan: BlockedPlan, contrib: jax.Array) -> jax.Array:
    """Per-destination sum of ``contrib[sender]`` over the blocked layout
    — the PageRank inflow (``segment_sum`` twin; float sums reassociate
    across the row layout, so parity is to float tolerance, not bits).
    ``contrib``: float ``[V]`` per-vertex outgoing contribution."""
    if contrib.shape[0] != plan.num_vertices:
        raise ValueError(
            f"plan built for V={plan.num_vertices} but contrib has "
            f"V={contrib.shape[0]} — plan/graph mismatch"
        )
    c_pad = jnp.concatenate([contrib, jnp.zeros((1,), contrib.dtype)])
    tile = _blocked_tile(plan, c_pad, jnp.zeros((), contrib.dtype))
    inflow = jnp.zeros((plan.num_vertices,), contrib.dtype)
    for ids, ridx in zip(plan.row_vertex, plan.row_idx):
        inflow = inflow.at[ids].set(
            jnp.sum(tile[ridx], axis=1), unique_indices=True, mode="drop"
        )
    return inflow


# ---- plan-build observability ----------------------------------------------


def plan_build_stats(plan, num_edges: int) -> dict:
    """The ``plan_build`` record payload for either plan family (see
    ``obs/schema.py``): bins/width classes and the padded gather slots
    per edge — the number the width-ladder work optimizes and the blocked
    layout re-balances (docs/DESIGN.md)."""
    from graphmine_tpu.ops.bucketed_mode import BucketedModePlan

    e = max(int(num_edges), 1)
    if isinstance(plan, BlockedPlan):
        # tile pass (M slots) + reduce rows
        slots = plan.num_messages + plan.padded_row_slots
        return {
            "family": "blocked",
            "bins": plan.num_bins,
            "width_classes": plan.num_width_classes,
            "tile_slots": plan.tile_slots,
            "padded_slots_per_edge": round(slots / e, 3),
        }
    if isinstance(plan, BucketedModePlan):
        mats = plan.send_idx if plan.send_idx is not None else plan.msg_idx
        slots = sum(int(m.shape[0]) * int(m.shape[1]) for m in mats or ())
        if plan.hist_send is not None:
            slots += int(plan.hist_send.shape[0])
        return {
            "family": "bucketed",
            "bins": 0,
            "width_classes": len(plan.vertex_ids),
            "padded_slots_per_edge": round(slots / e, 3),
        }
    raise TypeError(f"unknown plan type {type(plan).__name__}")


def emit_plan_records(
    sink, op: str, plan, reason: str, seconds: float, cached: bool,
    num_edges: int, num_messages: int, num_vertices: int | None = None,
) -> None:
    """Emit the ``impl_selected`` + ``plan_build`` provenance pair for one
    auto-plan resolution (no-op without a sink). ``plan=None`` (sort
    family) emits only ``impl_selected`` — there is no plan to build.

    Both records carry the decision's full evidence (ISSUE 12): the
    active crossover ``thresholds`` (:func:`crossover_thresholds`) and
    the analytical ``cost`` sub-record
    (:func:`graphmine_tpu.obs.costmodel.superstep_cost` — exact padded
    slots when a plan exists), so every auto-policy flip ships the
    numbers that justified it."""
    if sink is None:
        return
    from graphmine_tpu.obs.costmodel import superstep_cost

    family = "sort" if plan is None else plan_build_stats(plan, num_edges)["family"]
    v = (
        num_vertices if num_vertices is not None
        else getattr(plan, "num_vertices", 0)
    )
    cost = superstep_cost(
        op, family, v, num_messages, num_edges, plan=plan
    )
    sink.emit(
        "impl_selected", op=op, impl=family, n=num_messages, reason=reason,
        thresholds=crossover_thresholds(), cost=cost.record(),
    )
    if plan is None:
        return
    stats = plan_build_stats(plan, num_edges)
    sink.emit(
        "plan_build", op=op, seconds=round(seconds, 6), cached=cached,
        cost=cost.record(), **stats,
    )


def timed_plan_build(build) -> tuple:
    """``(plan, seconds)`` for one host plan build."""
    t0 = time.perf_counter()
    plan = build()
    return plan, time.perf_counter() - t0
