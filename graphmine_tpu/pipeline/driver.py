"""End-to-end pipeline driver: load → build → LPA → census → outliers.

Reproduces the five phases of the reference script
(``CommunityDetection/Graphframes.py``) with the TPU-native engine:

  CS-1 ingestion (:12-32)      → parquet/edge-list load, null filter, counts
  CS-2 graph construction (:53-78) → dense factorize + message CSR
  CS-3 label propagation (:81-85)  → jit/shard_map LPA supersteps
  CS-4 census (:92-120)            → segment-sum community table
  CS-5 outliers (:121-137, dead)   → recursive LPA decile + kNN/LOF scores

plus the subsystems the reference lacked: structured metrics (edges/sec/
chip), profiling, checkpoint/resume, multi-device execution.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

import numpy as np

from graphmine_tpu.graph.container import Graph, graph_from_edge_table
from graphmine_tpu.io.edges import EdgeTable, load_edge_list, load_parquet_edges
from graphmine_tpu.pipeline import checkpoint as ckpt
from graphmine_tpu.pipeline import resilience
from graphmine_tpu.pipeline.config import PipelineConfig
from graphmine_tpu.pipeline.metrics import MetricsSink, maybe_profile


def _visible_devices() -> int:
    import jax

    return len(jax.devices())


def device_hbm_bytes(devices=None) -> int | None:
    """Best-effort real per-device HBM via ``memory_stats()``: the MIN
    of ``bytes_limit`` across all local devices (ISSUE 14 satellite) — a
    heterogeneous or partially-occupied mesh must plan against its
    smallest chip, and trusting ``jax.devices()[0]`` alone budgeted
    against whichever part happened to enumerate first.

    Returns None when no backend reports it (CPU returns None, some
    tunneled runtimes raise) — the planner then falls back to its
    16 GiB default. Queried here, not in the planner, so host-side
    planning paths never import jax (planner.hbm_bytes_per_device).
    ``devices`` overrides the enumeration (tests)."""
    if devices is None:
        import jax

        try:
            devices = jax.local_devices()
        except Exception:
            return None
    limits = []
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:
            continue
        if not stats:
            continue
        limit = stats.get("bytes_limit")
        if limit and limit > 0:
            limits.append(int(limit))
    return min(limits) if limits else None


def _memory_sample() -> dict | None:
    """Measured memory for ``memory_watermark`` records (ISSUE 14):
    per-device ``bytes_in_use``/``peak_bytes_in_use`` when the backend's
    allocator exposes them (also cached for the heartbeat thread, which
    must never probe the runtime itself — obs/heartbeat.py), host RSS
    otherwise (``source: "rss"``). ``memory_stats`` is a host-side
    allocator query — sampling at the telemetry cadence adds zero
    device syncs."""
    per = []
    try:
        import jax

        for dev in jax.local_devices():
            try:
                stats = dev.memory_stats() or {}
            except Exception:
                continue
            if stats.get("bytes_in_use") is None:
                continue
            in_use = int(stats["bytes_in_use"])
            per.append({
                "device": int(getattr(dev, "id", len(per))),
                "bytes_in_use": in_use,
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use") or in_use
                ),
                "bytes_limit": int(stats.get("bytes_limit") or 0) or None,
            })
    except Exception:
        per = []
    if per:
        from graphmine_tpu.obs.heartbeat import note_device_memory

        note_device_memory(per)
        # achieved is the CURRENT fleet-wide max (phase-attributable);
        # the lifetime peak and the smallest limit ride as context — the
        # device holding an old allocator peak may be near-idle NOW,
        # and reporting its current bytes would understate the phase.
        limits = [s["bytes_limit"] for s in per if s["bytes_limit"]]
        return {
            "bytes_in_use": max(s["bytes_in_use"] for s in per),
            "peak_bytes_in_use": max(
                s["peak_bytes_in_use"] for s in per
            ),
            "bytes_limit": min(limits) if limits else None,
            "source": "device",
        }
    from graphmine_tpu.obs.memmodel import rss_sample

    return rss_sample()


@dataclass
class PipelineResult:
    edge_table: EdgeTable
    graph: Graph
    labels: np.ndarray                 # community label per vertex
    num_communities: int
    community_table: tuple             # (labels present, sizes, intra-edge counts)
    outliers: object | None = None     # OutlierReport (recursive_lpa)
    lof: np.ndarray | None = None      # LOF score per vertex
    metrics: MetricsSink = field(default_factory=MetricsSink)


def run_pipeline(config: PipelineConfig) -> PipelineResult:
    config.validate()
    from graphmine_tpu.obs.spans import Tracer

    # Records stream to --metrics-out AS EMITTED (MetricsSink.emit), not
    # only at exit: a preemption or OOM-kill skips every finally block,
    # and those are exactly the runs whose retry/degrade/rollback trail
    # the operator needs for offline triage. Every record carries the
    # tracer's run/trace/span identity (docs/OBSERVABILITY.md), and the
    # stream begins with a run_start header delimiting this run's segment
    # of a (possibly reused, append-mode) metrics file.
    tracer = Tracer(run_id=config.run_id)
    m = MetricsSink(stream_path=config.metrics_out, tracer=tracer)
    m.emit(
        "run_start", pid=os.getpid(), data_path=config.data_path,
        backend=config.backend, schedule=config.schedule,
        community_method=config.community_method, max_iter=config.max_iter,
    )
    hb = None
    if config.heartbeat_every_s:
        from graphmine_tpu.obs.heartbeat import Heartbeat

        hb = Heartbeat(
            m, every_s=config.heartbeat_every_s, prom_path=config.prom_out
        ).start()
    run_err: BaseException | None = None
    try:
        result = _run_pipeline(config, m)
        if config.snapshot_out:
            # Serving hand-off (r7, docs/SERVING.md): the run's final
            # phase publishes labels/CC/LOF/census + edges as a versioned
            # snapshot generation the serve/ subsystem queries and
            # delta-repairs against.
            _publish_snapshot(config, result, m)
        return result
    except BaseException as e:
        run_err = e
        raise
    finally:
        # Finalized on EVERY exit, not just success: stop the heartbeat,
        # close the run with a run_end record (so offline triage can tell
        # a finished run from a killed one), publish the registry, close
        # the live stream or append what it never persisted. A failed
        # flush must not mask the pipeline's own outcome.
        if hb is not None:
            hb.stop()
        if run_err is None:
            m.emit("run_end", ok=True)
        else:
            m.emit(
                "run_end", ok=False, error=resilience.classify_error(run_err),
                error_detail=repr(run_err),
            )
        tracer.close()
        import logging

        if config.prom_out:
            try:
                m.registry.write_textfile(
                    config.prom_out, labels={"run_id": tracer.run_id}
                )
            except OSError as prom_err:
                logging.getLogger("graphmine_tpu").warning(
                    "could not write --prom-out %s: %r",
                    config.prom_out, prom_err,
                )
        if config.metrics_out:
            try:
                m.finalize(config.metrics_out)
            except OSError as flush_err:
                logging.getLogger("graphmine_tpu").warning(
                    "could not write --metrics-out %s: %r",
                    config.metrics_out, flush_err,
                )


def _run_pipeline(config: PipelineConfig, m: MetricsSink) -> PipelineResult:
    # ---- CS-1 ingestion -------------------------------------------------
    def _load():
        resilience.fault_point("load", path=config.data_path)
        if config.data_format == "parquet":
            return load_parquet_edges(
                config.data_path, batch_rows=config.batch_rows
            )
        return load_edge_list(
            config.data_path, weight_col=config.edge_weight_col,
            quarantine=config.quarantine_inputs,
        )

    with m.span("load"), m.timed(
        "load", path=config.data_path, format=config.data_format
    ):
        table = resilience.run_phase("load", _load, config.resilience, m)
    m.emit(
        "counts",  # parity with the prints at Graphframes.py:18 and :54
        rows_raw=table.num_rows_raw,
        edges=table.num_edges,
        vertices=table.num_vertices,
    )
    if table.quarantine and config.quarantine_inputs:
        # rows set aside instead of crashing ingestion (docs/RESILIENCE.md).
        # Gated on the flag: parquet loaders always count their null filter,
        # but --no-quarantine-inputs promises a strict-parsing run whose
        # metrics stream carries no quarantine records.
        m.emit("quarantine", **table.quarantine)

    # ---- CS-2 graph construction ---------------------------------------
    # Schedule resolution happens HERE, before any device allocation: the
    # memory planner (pipeline/planner.py) models per-device HBM for each
    # schedule and either picks one ("auto") or validates the requested
    # one — an impossible config raises PlanError with the numbers now,
    # instead of OOMing deep inside XLA after minutes of graph build.
    n_dev = config.num_devices or _visible_devices()
    run_plan = None
    if config.community_method == "lpa" and config.backend != "graphframes":
        from graphmine_tpu.pipeline.planner import (
            hbm_bytes_per_device,
            plan_run,
        )

        # Budget chain: env override → what THIS device actually reports
        # (a v4/v5p part has 2-6x the v5e default) → 16 GiB. The callable
        # keeps the device query lazy: an env-pinned budget never touches
        # memory_stats.
        run_plan = plan_run(
            table.num_vertices,
            table.num_edges,
            n_dev,
            weighted=table.weights is not None,
            requested=config.schedule,
            hbm=hbm_bytes_per_device(device_hbm_bytes),
        )
        from graphmine_tpu.obs.memmodel import schedule_footprint

        m.emit(
            "plan",
            schedule=run_plan.schedule,
            bytes_per_device=run_plan.bytes_per_device,
            hbm_budget=run_plan.hbm_bytes,
            reason=run_plan.reason,
            # the memory plane's named inventory behind bytes_per_device
            # (ISSUE 14): the same seeds the planner's accept/reject used,
            # decomposed — obs_report's recalibration suggestion compares
            # measured watermarks against exactly these components
            mem=schedule_footprint(
                run_plan.schedule, table.num_vertices, table.num_edges,
                n_dev, weighted=table.weights is not None,
            ).record(),
        )
    # The fused LPA plan is only consumed by the single-device jax LPA
    # path; build it (from the same message-CSR pass as the Graph) only
    # when that path will run — it is pure HBM/host waste for louvain,
    # graphframes, and sharded runs.
    wants_plan = run_plan is not None and run_plan.schedule == "single"
    # Which plan FAMILY that single-device path runs (r7): the planner
    # resolves blocked vs bucketed at plan time through the single
    # crossover-policy owner (ops/blocking.select_superstep_family), with
    # the blocked→bucketed degradation rung — same provenance treatment
    # as the r6 IVF flip. "sort" at tiny scale still builds the bucketed
    # plan here (the shared-CSR-pass build is the historical single-path
    # behavior; the plan is cheap exactly where "sort" wins).
    sstep_plan = None
    if wants_plan:
        import dataclasses as _dc

        from graphmine_tpu.pipeline.planner import plan_superstep

        sstep_plan = plan_superstep(
            table.num_vertices, 2 * table.num_edges,
            weighted=table.weights is not None,
        )
        if sstep_plan.family == "sort" and not os.environ.get(
            "GRAPHMINE_SUPERSTEP_FAMILY"
        ):
            # AUTO resolved "sort" on size alone — but the single-device
            # path has always built the fused plan in the SAME
            # message-CSR pass as the Graph, so the crossover's
            # plan-build-cost rationale doesn't apply here: keep the
            # bucketed kernel and say so, rather than record a family
            # the driver doesn't run. An EXPLICIT env force of "sort"
            # is honored as-is (the sort superstep really runs).
            sstep_plan = _dc.replace(
                sstep_plan, family="bucketed", degrade_to="sort",
                reason=sstep_plan.reason + " — driver single path: plan "
                "build shares the graph's CSR pass, bucketed kernel kept",
            )
        # Plan-time memory pre-degrade (ISSUE 14): a family whose MODELED
        # footprint already exceeds the planning budget cannot survive
        # the build — consume its rung NOW, with the oversized inventory
        # in the degrade record, instead of letting XLA OOM after the
        # plan materializes. Honors degradation="off" (an operator who
        # sized the run wants the OOM, not a silently leaner family).
        if config.resilience.degradation == "auto":
            from graphmine_tpu.obs.memmodel import predegrade_superstep
            from graphmine_tpu.pipeline.planner import _SUPERSTEP_DEGRADE

            fam, _fit, steps = predegrade_superstep(
                sstep_plan.family, table.num_vertices, 2 * table.num_edges,
                table.num_edges, table.weights is not None,
                run_plan.hbm_bytes,
            )
            for depth, (frm, to, oversized) in enumerate(steps, 1):
                m.emit(
                    "degrade", stage="plan_superstep", to=to, depth=depth,
                    kind="mem_plan",
                    error=(
                        f"plan-time memory pre-degrade: modeled {frm!r} "
                        f"footprint {oversized.total_bytes:,} B exceeds "
                        f"the {run_plan.hbm_bytes:,} B budget"
                    ),
                    mem=oversized.record(),
                )
            if steps:
                sstep_plan = _dc.replace(
                    sstep_plan, family=fam,
                    degrade_to=_SUPERSTEP_DEGRADE[fam],
                    reason=sstep_plan.reason
                    + f" — pre-degraded to {fam!r}: modeled footprint of "
                    f"{steps[0][0]!r} exceeds the memory budget",
                )
        from graphmine_tpu.obs.costmodel import superstep_cost
        from graphmine_tpu.ops.blocking import crossover_thresholds

        m.emit(
            "impl_selected", op="lpa_superstep", impl=sstep_plan.family,
            n=2 * table.num_edges, reason=sstep_plan.reason,
            # the deciding crossover constants + the model's pre-build
            # estimate (ISSUE 12; the plan_build record below carries the
            # exact padded counts once the plan exists)
            thresholds=crossover_thresholds(),
            cost=superstep_cost(
                "lpa_superstep", sstep_plan.family, table.num_vertices,
                2 * table.num_edges, table.num_edges,
                weighted=table.weights is not None,
            ).record(),
        )
    # Scale-out mode (r3): when the planner chose a distributed schedule
    # AND the whole graph cannot also fit one device, the full Graph stays
    # HOST-side NumPy — partitioning slices it onto the mesh, and the
    # census/modularity phases dispatch to their NumPy twins. Building it
    # device-resident here would OOM device 0 before LPA ever ran.
    scale_out = (
        run_plan is not None
        and run_plan.schedule != "single"
        and run_plan.estimates.get("single", 0) > run_plan.hbm_bytes
    )
    if scale_out:
        m.emit("scale_out", message="full graph exceeds one device: host-"
               "resident graph; outlier phases run distributed (recursive "
               "LPA over the intra-community subgraph, sharded kNN/LOF)")
    def _build():
        resilience.fault_point("build_graph")
        if wants_plan and sstep_plan.family != "sort":
            from graphmine_tpu.ops.blocking import (
                build_graph_and_blocked_plan,
                plan_build_stats,
            )
            from graphmine_tpu.ops.bucketed_mode import build_graph_and_plan

            builder = (
                build_graph_and_blocked_plan
                if sstep_plan.family == "blocked" else build_graph_and_plan
            )
            t0 = time.perf_counter()
            g, plan = builder(
                table.src, table.dst, num_vertices=table.num_vertices,
                edge_weights=table.weights,
            )
            # plan_build: the host plan cost, visible in obs_report
            # instead of hiding inside first-call latency (the
            # impl_selected record above already carries the rationale).
            from graphmine_tpu.obs.costmodel import superstep_cost

            m.emit(
                "plan_build", op="lpa_superstep",
                seconds=round(time.perf_counter() - t0, 6), cached=False,
                cost=superstep_cost(
                    "lpa_superstep", sstep_plan.family, table.num_vertices,
                    2 * table.num_edges, table.num_edges, plan=plan,
                ).record(),
                **plan_build_stats(plan, table.num_edges),
            )
            # single-element holder, not the bare plan: the LPA loop can
            # release the fused plan's padded device matrices when the
            # degradation ladder leaves the fused kernel, with no caller
            # frame still pinning a reference
            return g, [plan]
        return graph_from_edge_table(table, to_device=not scale_out), [None]

    with m.span("build_graph"), m.timed("build_graph"):
        graph, plan_holder = resilience.run_phase(
            "build_graph", _build, config.resilience, m
        )

    # ---- CS-3 community detection --------------------------------------
    if config.community_method in ("louvain", "leiden"):
        from graphmine_tpu.ops.louvain import leiden, louvain

        if config.checkpoint_dir:
            m.emit("warning", message="checkpoint/resume applies to LPA only; "
                   f"{config.community_method} runs are not checkpointed")
        algo = leiden if config.community_method == "leiden" else louvain
        with m.span(config.community_method), m.timed(
            config.community_method, gamma=config.gamma
        ):
            labels, q = algo(graph, gamma=config.gamma)
    else:
        with m.span("lpa"):
            labels = _run_lpa(
                config, table, graph, m, plan_holder, n_dev, run_plan,
                sstep_plan,
            )
        q = None

    # ---- CS-4 census ----------------------------------------------------
    from graphmine_tpu.ops.census import census_table
    from graphmine_tpu.ops.lpa import num_communities
    from graphmine_tpu.ops.modularity import modularity

    def _census():
        resilience.fault_point("census")
        n = int(num_communities(labels))
        table_ = census_table(labels, graph)
        qq = q if q is not None else float(
            modularity(labels, graph, gamma=config.gamma)
        )
        return n, table_, qq

    with m.span("census"), m.timed("census"):
        n_comm, (present, sizes, edge_counts), q = resilience.run_phase(
            "census", _census, config.resilience, m
        )
    # parity with "There are N Communities in the Dataset." (:85)
    m.emit("communities", count=n_comm, largest=int(sizes.max(initial=0)), modularity=round(q, 6))

    result = PipelineResult(
        edge_table=table,
        graph=graph,
        labels=np.asarray(labels),
        num_communities=n_comm,
        community_table=(present, sizes, edge_counts),
        metrics=m,
    )

    # ---- CS-5 outliers --------------------------------------------------
    if config.outlier_method in ("recursive_lpa", "both"):
        if scale_out:
            # The device-resident masked pass would materialize the full
            # graph on one device, which the planner just ruled out.
            # Run the distributed composition instead: host-side
            # intra-community edge filter → planner-resolved distributed
            # LPA schedule → host decile (VERDICT r3 item 2). scale_out
            # implies a multi-device plan (plan_run maps any request on
            # one device to "single"), so a mesh always exists here.
            from graphmine_tpu.ops.outliers import recursive_lpa_outliers_sharded
            from graphmine_tpu.parallel.mesh import make_mesh

            scorer = lambda: recursive_lpa_outliers_sharded(
                graph, labels, make_mesh(n_dev),
                max_iter=config.sub_max_iter, decile=config.decile,
                schedule=run_plan.schedule,
            )
            timing_kv = dict(schedule=run_plan.schedule, devices=n_dev)
        else:
            from graphmine_tpu.ops.outliers import recursive_lpa_outliers

            scorer = lambda: recursive_lpa_outliers(
                graph, labels, max_iter=config.sub_max_iter,
                decile=config.decile,
            )
            timing_kv = {}

        def _outliers():
            resilience.fault_point("outliers_recursive")
            return scorer()

        with m.span("outliers_recursive_lpa"), m.timed(
            "outliers_recursive_lpa", **timing_kv
        ):
            result.outliers = resilience.run_phase(
                "outliers_recursive", _outliers, config.resilience, m
            )
        m.emit(
            "outlier_summary",
            method="recursive_lpa",
            flagged_vertices=int(result.outliers.outlier_vertices.sum()),
            sub_communities=len(result.outliers.sub_sizes),
        )
    if config.outlier_method in ("lof", "both"):
        from graphmine_tpu.ops.features import (
            standardize,
            vertex_features,
            vertex_features_host,
        )
        from graphmine_tpu.ops.lof import lof_scores

        from graphmine_tpu.parallel.knn import can_shard
        from graphmine_tpu.pipeline.planner import plan_lof

        k = min(config.lof_k, graph.num_vertices - 1)
        use_sharded_lof = n_dev > 1 and can_shard(graph.num_vertices, n_dev, k)
        # Plan-time impl resolution (r6): the measured IVF crossover
        # (ops/lof.py provenance table) decides here, BEFORE any scorer
        # runs, so the degradation ladder below is built in the right
        # direction — exact primary gets the leaner IVF index as its OOM
        # rung; IVF primary gets the roofline-bounded exact tiles as its
        # rescue rung. The scorers re-apply the same policy function and
        # emit the impl_selected record through the sink.
        lof_plan = plan_lof(graph.num_vertices, k, requested=config.lof_impl)
        # Memory plane (ISSUE 14): the planned impl's workspace inventory
        # (exact [rows, n] distance/top-k tiles vs the IVF cluster-batched
        # model) — watermarked after scoring, attached to any OOM degrade.
        from graphmine_tpu.obs.memmodel import (
            emit_memory_watermark,
            lof_footprint,
        )

        lof_mem_holder = [lof_footprint(
            lof_plan.impl, graph.num_vertices, k, features=8,
            devices=n_dev if use_sharded_lof else 1,
        )]

        def _lof_degrade_context() -> dict:
            return {"mem": lof_mem_holder[0].record()}

        def _lof_rung_entered() -> None:
            # The ladder rung runs the OPPOSITE impl: re-point the holder
            # so the post-phase watermark pairs the surviving rung's
            # model with its measured peak (the failed primary's model
            # already rode the degrade record via _lof_degrade_context).
            lof_mem_holder[0] = lof_footprint(
                lof_plan.degrade_to, graph.num_vertices, k, features=8,
                devices=n_dev if use_sharded_lof else 1,
            )
        if use_sharded_lof and config.lof_impl in ("xla", "pallas"):
            m.emit(
                "warning",
                message=f"lof_impl={config.lof_impl!r} forces an exact "
                "single-device kernel; the multi-device path runs the "
                "exact ring-sharded kNN/LOF instead (auto/ivf DO apply "
                "to the sharded scorer)",
            )
        if scale_out and not use_sharded_lof:
            m.emit(
                "warning",
                message="lof skipped in scale-out mode: the all-pairs "
                "single-device scorer cannot hold a graph this size; add "
                "devices so the sharded kNN/LOF path can run",
            )
            return result
        # Wedge-budget guard (r5): the exact clustering pipeline
        # materializes every oriented wedge on the host (~28 B each) —
        # a mega-hub power-law graph at 25M edges has ~10^10 of them,
        # and the first e2e bench run was OOM-killed at 130 GB RSS
        # before this guard existed. The probe is O(E log E) host work;
        # past the budget the clustering column comes from the sampled
        # estimator (stderr <= 1/(2*sqrt(64)) per vertex), same as
        # scale-out mode. Default 2.5e8 wedges ~ 7 GB host scratch.
        feature_mode = "device-8"
        simple_edges = None
        if not scale_out:
            from graphmine_tpu.graph.container import simple_undirected_edges
            from graphmine_tpu.ops.triangles import oriented_wedge_count

            wedge_budget = int(float(os.environ.get(
                "GRAPHMINE_WEDGE_BUDGET", "2.5e8"
            )))
            # One O(E log E) dedup, shared with the clustering column
            # below (exact or sampled) — the probe must not double the
            # host prep it exists to bound (code-review r5).
            simple_edges = simple_undirected_edges(graph)
            wedges = oriented_wedge_count(graph, simple_edges=simple_edges)
            if wedges > wedge_budget:
                feature_mode = "device-8-sampled"
                m.emit(
                    "warning",
                    message=f"exact clustering infeasible: {wedges:,} "
                    f"oriented wedges exceed GRAPHMINE_WEDGE_BUDGET="
                    f"{wedge_budget:,} (~28 B/wedge host scratch); using "
                    "the wedge-sampled estimator",
                )
        with m.span("outliers_lof"), m.timed(
                     "outliers_lof", k=config.lof_k,
                     devices=n_dev if use_sharded_lof else 1,
                     features="host-8-sampled" if scale_out else feature_mode):
            if scale_out:
                # Host feature twin (no O(E) device transfer). The exact
                # wedge pipeline is infeasible exactly when the graph
                # exceeds one device, so the clustering column comes from
                # the wedge-SAMPLED estimator (r4): the full 8-feature
                # set survives at scale with a bounded per-vertex error
                # (ops/triangles.sampled_clustering_coefficient).
                feats = standardize(vertex_features_host(
                    graph, labels, include_clustering="sampled"
                ))
            else:
                feats = standardize(vertex_features(
                    graph, labels,
                    include_clustering=(
                        "sampled" if feature_mode == "device-8-sampled"
                        else True
                    ),
                    simple_edges=simple_edges,
                ))
            if use_sharded_lof:
                # Multi-device (parallel/knn.py): the planner-resolved
                # family — IVF candidate reduction with the search stage
                # sharded over the mesh at crossover scale (r6), else the
                # exact ring-sharded kNN — plus the opposite family as
                # the degradation rung.
                from graphmine_tpu.parallel.knn import sharded_lof
                from graphmine_tpu.parallel.mesh import make_mesh

                impl_sharded = (
                    "ivf" if lof_plan.impl == "ivf" else "exact"
                )

                def _score():
                    resilience.fault_point("outliers_lof")
                    return sharded_lof(
                        feats, make_mesh(n_dev), k=k, impl=impl_sharded,
                        sink=m,
                    )

                def _rung_sharded():
                    _lof_rung_entered()
                    return sharded_lof(
                        feats, make_mesh(n_dev), k=k,
                        impl=lof_plan.degrade_to, sink=m,
                    )

                ladder = ((
                    f"lof_sharded_{lof_plan.degrade_to}", _rung_sharded,
                ),)
            else:
                # Planner-selected family (r6): impl="auto" deploys the
                # IVF index at the measured crossover scale (~3.1x at
                # 262K points for ~0.001 AUROC — ops/lof.py provenance);
                # config.lof_impl passes through so explicit choices
                # stay honored, and lof_scores re-applies the same
                # policy + emits the impl_selected record.
                def _score():
                    resilience.fault_point("outliers_lof")
                    return lof_scores(feats, k=k, impl=config.lof_impl, sink=m)

                # Degradation rung, direction from the plan: the exact
                # scorer's [V, V] distance tiles OOM -> the IVF index's
                # bounded candidate set; the IVF scorer's data-dependent
                # pair tables blow up -> the roofline-bounded exact path.
                rung_impl = (
                    "xla" if lof_plan.degrade_to == "exact" else "ivf"
                )

                def _rung_fused():
                    _lof_rung_entered()
                    return lof_scores(feats, k=k, impl=rung_impl, sink=m)

                ladder = ((
                    f"lof_{lof_plan.degrade_to}", _rung_fused,
                ),)
            scores = resilience.run_phase(
                "outliers_lof", _score, config.resilience, m, ladder=ladder,
                degrade_context=_lof_degrade_context,
            )
            result.lof = np.asarray(scores)
            # Phase-cadence watermark (ISSUE 14): the workspace model of
            # the impl that actually SCORED (the holder re-points on a
            # rung entry) vs the bytes peaked while scoring.
            emit_memory_watermark(
                m, "lof_knn", lof_mem_holder[0], _memory_sample(),
                budget_bytes=run_plan.hbm_bytes if run_plan is not None
                else None,
                impl=lof_mem_holder[0].family,
            )
        m.emit(
            "outlier_summary",
            method="lof",
            max_score=float(result.lof.max()),
            over_1_5=int((result.lof > 1.5).sum()),
        )
    return result


def _publish_snapshot(config: PipelineConfig, result: PipelineResult, m: MetricsSink) -> None:
    """Publish the pipeline's outputs as one snapshot generation.

    CC labels are computed here (the pipeline itself has no CC phase):
    device-resident graphs run the fused single-device fixpoint; host-
    resident graphs (scale-out mode) shard over the mesh — the planner
    just ruled out materializing them on one device. Wrapped in
    ``run_phase`` so transient publish weather retries like any phase.
    """
    from graphmine_tpu.serve.snapshot import SnapshotStore

    table, graph = result.edge_table, result.graph
    n_dev = config.num_devices or _visible_devices()

    def _publish():
        resilience.fault_point("snapshot_publish")
        if isinstance(graph.src, np.ndarray):
            from graphmine_tpu.parallel.mesh import make_mesh
            from graphmine_tpu.parallel.sharded import (
                partition_graph,
                shard_graph_arrays,
                sharded_connected_components,
            )

            from graphmine_tpu.obs.costmodel import (
                emit_superstep_timing,
                sharded_superstep_cost,
                timed_fixpoint,
            )

            mesh = make_mesh(n_dev)
            sg = shard_graph_arrays(partition_graph(graph, mesh=mesh), mesh)
            # telemetry=True returns the real supersteps-to-fixpoint on
            # the existing while-loop carry (no extra device syncs) — the
            # CC phase's achieved-vs-model window (ISSUE 12).
            from graphmine_tpu.parallel.sharded import _sharded_cc_jit

            (cc_labels, tele), secs, cold = timed_fixpoint(
                lambda: sharded_connected_components(sg, mesh, telemetry=True),
                jit_fn=_sharded_cc_jit,
            )
            emit_superstep_timing(
                m, "cc_superstep",
                sharded_superstep_cost(
                    "cc_superstep", sg, graph.num_edges,
                    num_messages=graph.num_messages, weighted=False,
                ),
                tele.iterations, tele.iterations, secs, graph.num_edges,
                variant="sharded", cold_compile=cold,
            )
            cc = np.asarray(cc_labels)
        else:
            from graphmine_tpu.ops.cc import connected_components

            # sink=m: the auto seam emits impl_selected/plan_build AND
            # the CC phase's superstep_timing record (ops/cc.py).
            cc = np.asarray(connected_components(graph, sink=m))
        present, sizes, edge_counts = result.community_table
        arrays = {
            "src": np.asarray(table.src, np.int32),
            "dst": np.asarray(table.dst, np.int32),
            "labels": np.asarray(result.labels, np.int32),
            "cc_labels": cc.astype(np.int32),
            "census_present": np.asarray(present),
            "census_sizes": np.asarray(sizes),
            "census_edges": np.asarray(edge_counts),
        }
        if result.lof is not None:
            arrays["lof"] = np.asarray(result.lof, np.float32)
        if table.weights is not None:
            # Preserved so queries/provenance keep the real graph; the
            # delta-repair path refuses weighted snapshots loudly (its
            # propagations are unweighted — repairing weighted-LPA labels
            # with unweighted supersteps would silently change semantics).
            arrays["weights"] = np.asarray(table.weights, np.float32)
        store = SnapshotStore(config.snapshot_out)
        # Result-quality plane (ISSUE 13, docs/OBSERVABILITY.md "Result
        # quality"): a driver publish is the version chain's first link —
        # seed/readopt the canary probe so the serving writer scores the
        # SAME frozen probe, read the parent's result columns for drift,
        # and emit quality_snapshot/quality_drift/canary_score in the
        # publishing trace. GRAPHMINE_QUALITY=0 disables; failures are
        # telemetry-only and must never fail the publish phase.
        quality_on = os.environ.get("GRAPHMINE_QUALITY", "1") != "0"
        parent_arrays, parent_meta, canary = {}, {}, None
        if quality_on:
            from graphmine_tpu.obs.quality import CanaryProbe

            try:
                peeked = store.peek_arrays(
                    ("labels", "lof", "canary_features", "canary_is_anomaly")
                )
                if peeked is not None:
                    parent_arrays, parent_meta = peeked
                canary = CanaryProbe.from_arrays(parent_arrays, parent_meta)
                if canary is None:
                    canary = CanaryProbe.generate(
                        seed=int(os.environ.get("GRAPHMINE_CANARY_SEED", "0"))
                    )
                arrays.update(canary.arrays())
            except Exception as e:  # noqa: BLE001 — telemetry only
                m.emit("warning", message=f"canary probe unavailable: {e!r}")
                canary = None
        snap = store.publish(
            arrays,
            fingerprint=ckpt.graph_fingerprint(
                table.src, table.dst, table.weights
            ),
            run_id=m.tracer.run_id if m.tracer is not None else "",
            mesh_shape=[n_dev],
            extra_meta={"canary": canary.meta()} if canary is not None
            else None,
            sink=m,
        )
        if quality_on:
            from graphmine_tpu.obs.quality import run_quality_pass

            try:
                run_quality_pass(
                    arrays["labels"], arrays.get("lof"), snap.version,
                    parent_labels=parent_arrays.get("labels"),
                    parent_lof=parent_arrays.get("lof"),
                    parent_version=parent_meta.get("version"),
                    canary=canary, sink=m, registry=m.registry,
                )
            except Exception as e:  # noqa: BLE001 — telemetry only: the
                # publish already COMMITTED; raising here would hand a
                # succeeded publish to run_phase as a failure and a
                # retry would publish a duplicate version
                m.emit("warning", message=f"quality pass failed: {e!r}")
        return snap

    with m.span("snapshot_publish"):
        resilience.run_phase(
            "snapshot_publish", _publish, config.resilience, m
        )


def _emit_superstep_telemetry(
    m: MetricsSink, new, old, chunk: int, ndev: int, variant: str,
    iteration: int,
) -> int:
    """``superstep_telemetry`` record: per-shard active counts and the
    load-imbalance ratio for one superstep. Called only at the existing
    tripwire/checkpoint cadence boundaries, where the driver already
    syncs per superstep — the reduction runs on device and only a
    [D]-int vector crosses to the host. Shards are the REAL partition
    chunks (``chunk`` is partition_graph's padded size); shard count is
    clamped to the chunks that actually cover real vertices, so the
    per-shard counts sum to exactly the labels-changed total — which is
    returned, sparing the caller a second full-vertex diff pass."""
    import jax.numpy as jnp

    d = max(1, min(int(ndev), -(-int(new.shape[0]) // max(chunk, 1))))
    diff = new != old
    pad = d * chunk - int(diff.shape[0])
    if pad > 0:
        diff = jnp.concatenate([diff, jnp.zeros((pad,), diff.dtype)])
    per = np.asarray(
        jnp.sum(jnp.reshape(diff, (d, chunk)), axis=1, dtype=jnp.int32)
    )
    changed = int(per.sum())
    mean = changed / d
    imbalance = float(per.max()) / mean if mean > 0 else 1.0
    m.emit(
        "superstep_telemetry",
        iteration=iteration,
        labels_changed=changed,
        # synchronous LPA's frontier IS the changed set: exactly the
        # vertices whose neighbors must re-reduce next superstep
        frontier=changed,
        shard_changed=per.tolist(),
        shard_max=int(per.max()),  # per is never empty: d >= 1
        shard_min=int(per.min()),
        imbalance=round(imbalance, 3),
        devices=int(ndev),
        variant=variant,
    )
    return changed


def _run_lpa(
    config: PipelineConfig, table: EdgeTable, graph: Graph, m: MetricsSink,
    plan_holder: list, n_dev: int, run_plan, sstep_plan=None,
):
    """Community detection with backend dispatch, checkpointing and
    per-iteration metrics. Runs iterations one jit call at a time so the
    labels-changed counter and edges/sec are observable (the whole loop is
    still device-resident; only the scalar counter syncs)."""
    if config.backend == "graphframes":
        from graphmine_tpu.pipeline.backends import lpa_graphframes

        with m.timed("lpa", backend="graphframes"):
            return lpa_graphframes(table, config.max_iter)

    import jax
    import jax.numpy as jnp

    from graphmine_tpu.obs.costmodel import (
        WindowTimer,
        sharded_superstep_cost,
        superstep_cost,
    )
    from graphmine_tpu.obs.memmodel import (
        emit_memory_watermark,
        sharded_superstep_footprint,
        superstep_footprint,
    )
    from graphmine_tpu.parallel.mesh import make_mesh
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_label_propagation,
    )

    # Achieved-vs-model window timing (ISSUE 12): per-superstep wall
    # durations accumulate here and flush as `superstep_timing` records
    # at the EXISTING telemetry cadence — the driver already syncs every
    # superstep for the labels-changed counter, so this adds zero device
    # syncs. Each operating point (make_superstep) installs its own cost
    # estimate in current["cost"].
    wtimer = WindowTimer()
    chips = max(n_dev, 1)
    start_iter = 0
    labels = jnp.arange(graph.num_vertices, dtype=jnp.int32)

    # One O(E) hash per run; ties every checkpoint to this exact graph,
    # id assignment (bulk vs batch_rows ingestion assign different ids),
    # and edge weights (weighted/unweighted trajectories differ).
    fingerprint = (
        ckpt.graph_fingerprint(table.src, table.dst, table.weights)
        if config.checkpoint_dir else None
    )

    def _reload_checkpoint():
        """Newest recoverable state across BOTH checkpoint formats
        (sharded manifest + npz; the higher iteration wins, one corrupt
        format does not veto the other) — see checkpoint.load_newest."""
        return ckpt.load_newest(
            config.checkpoint_dir, fingerprint=fingerprint, sink=m
        )

    if config.resume and config.checkpoint_dir:
        loaded = _reload_checkpoint()
        if loaded is not None:
            saved_labels, start_iter = loaded
            if start_iter > config.max_iter:
                raise ValueError(
                    f"checkpoint at iteration {start_iter} exceeds "
                    f"max_iter={config.max_iter}; delete the checkpoint or "
                    f"raise max_iter"
                )
            labels = jnp.asarray(saved_labels, dtype=jnp.int32)
            m.emit("resume", iteration=start_iter)

    # Dispatch on the planner-resolved schedule (plan_run maps an explicit
    # "ring"/"replicated" request on one device to "single").
    if config.schedule == "ring" and run_plan.schedule == "single":
        m.emit("warning", message="schedule='ring' needs >1 device; "
               "running the single-device fused kernel instead")

    policy = config.resilience
    # Mutable loop state shared by every ladder rung: a retry re-enters
    # and a degradation steps down FROM THE LAST GOOD SUPERSTEP, never
    # from iteration 0 — supersteps are deterministic, so a resumed
    # trajectory is byte-identical to an uninterrupted one.
    state = {"labels": labels, "it": start_iter}
    # The ACTIVE operating point: the elastic device rungs shrink "ndev"
    # below the starting mesh, and the sharded-checkpoint writer splits
    # by whatever is current (a checkpoint's shard count is metadata, not
    # a restore constraint — load_sharded re-shards).
    current = {"ndev": n_dev, "variant": run_plan.schedule}
    # The last memory_watermark record emitted (ISSUE 14): a reactive
    # OOM's degrade record attaches it (plus the active operating
    # point's modeled inventory) via run_phase's degrade_context, so
    # model-miss vs fragmentation is triageable from the JSONL alone —
    # joinable back to the full watermark by span path.
    last_watermark: dict = {"rec": None}

    def _mem_watermark(op_iteration: int, variant: str, ndev: int) -> None:
        rec = emit_memory_watermark(
            m, "lpa_superstep", current.get("mem"), _memory_sample(),
            budget_bytes=run_plan.hbm_bytes, iteration=int(op_iteration),
            variant=variant, devices=int(ndev),
        )
        if rec is not None:
            last_watermark["rec"] = rec

    def _lpa_degrade_context() -> dict:
        ctx = {}
        est = current.get("mem")
        if est is not None:
            ctx["mem"] = est.record()
        w = last_watermark["rec"]
        if w is not None:
            ctx["last_watermark"] = {
                k: w.get(k)
                for k in (
                    "t", "op", "iteration", "predicted_bytes",
                    "achieved_bytes", "headroom_frac", "source",
                    "span_path",
                )
            }
        return ctx
    # Device indices implicated in a device-loss error (parsed best-effort
    # from its message): the runtime usually still LISTS a chip that just
    # failed a collective, and a rung mesh built from the first N visible
    # devices would re-enroll it — every halved rung would then die the
    # same death, exhausting the elastic ladder without ever routing
    # around the loss.
    dead_devices: set = set()

    def _rung_mesh(ndev: int):
        from graphmine_tpu.parallel.mesh import surviving_mesh

        if dead_devices:
            try:
                return surviving_mesh(ndev, exclude=sorted(dead_devices))
            except ValueError:
                # exclusions leave too few survivors: better to try the
                # first-N mesh (maybe the parse over-matched) than abort
                pass
        return make_mesh(ndev)

    def _note_dead_devices() -> None:
        """Harvest chip indices from the device-loss error that triggered
        this descent (run_phase records it in the degrade event just
        before invoking the rung). Message parsing is best-effort — an
        unattributed loss still degrades, just without the exclusion."""
        import re

        device_degrades = [
            r for r in m.of_phase("degrade") if r.get("kind") == "device"
        ]
        if device_degrades:
            for tok in re.findall(
                r"(?:chip|device)\s+#?(\d+)",
                device_degrades[-1].get("error", ""),
            ):
                dead_devices.add(int(tok))

    def make_superstep(variant: str, ndev: int):
        """Build the per-superstep callable for one operating point
        (schedule x device count: the planner's memory rungs keep the
        mesh and lean the schedule; the elastic device rungs keep the
        schedule and shrink the mesh)."""
        if variant == "ring":
            # Memory-scalable schedule: labels stay sharded, chunks rotate
            # over ICI (parallel/ring.py). Uses the sort-body message CSR.
            from graphmine_tpu.parallel.ring import ring_label_propagation

            mesh = _rung_mesh(ndev)
            with m.timed("partition", shards=ndev, schedule="ring"):
                sg = shard_graph_arrays(partition_graph(graph, mesh=mesh), mesh)
            current["chunk_size"] = sg.chunk_size
            current["cost"] = sharded_superstep_cost(
                "lpa_superstep", sg, graph.num_edges,
                num_messages=graph.num_messages,
            )
            current["mem"] = sharded_superstep_footprint(
                "lpa_superstep", sg, schedule="ring",
            )
            return lambda lbl: ring_label_propagation(
                sg, mesh, max_iter=1, init_labels=lbl
            )
        if variant == "replicated":
            mesh = _rung_mesh(ndev)
            with m.timed("partition", shards=ndev, schedule="replicated"):
                sg = shard_graph_arrays(
                    partition_graph(graph, mesh=mesh, build_bucket_plan=True),
                    mesh,
                    lpa_only=run_plan.lpa_only,
                )
            current["chunk_size"] = sg.chunk_size
            current["cost"] = sharded_superstep_cost(
                "lpa_superstep", sg, graph.num_edges,
                num_messages=graph.num_messages,
            )
            current["mem"] = sharded_superstep_footprint(
                "lpa_superstep", sg, schedule="replicated",
            )
            return lambda lbl: sharded_label_propagation(
                sg, mesh, max_iter=1, init_labels=lbl
            )
        if variant == "single_sort":
            # Degradation rung: the plain sort-based superstep over the
            # bare message CSR — no padded bucket matrices, ~identical
            # labels by construction (tests/test_lpa.py pins parity).
            from graphmine_tpu.ops.lpa import lpa_superstep

            current["chunk_size"] = graph.num_vertices
            current["cost"] = superstep_cost(
                "lpa_superstep", "sort", graph.num_vertices,
                graph.num_messages, graph.num_edges,
                weighted=graph.msg_weight is not None,
            )
            current["mem"] = superstep_footprint(
                "lpa_superstep", "sort", graph.num_vertices,
                graph.num_messages, num_edges=graph.num_edges,
                weighted=graph.msg_weight is not None,
            )
            step = jax.jit(lpa_superstep)
            return lambda lbl: step(lbl, graph)
        if variant == "single_bucketed":
            # Blocked→bucketed degradation rung (r7): the blocked plan's
            # tile + stream arrays were released on entry (plan_holder
            # cleared below); rebuild the degree-bucketed fused plan —
            # identical labels, less HBM than tile + rows — and record
            # its host cost like every other plan build.
            from graphmine_tpu.ops.blocking import plan_build_stats
            from graphmine_tpu.ops.bucketed_mode import lpa_superstep_bucketed
            from graphmine_tpu.ops.lpa import _cached_auto_plan

            plan, secs, cached = _cached_auto_plan(graph, "bucketed")
            current["cost"] = superstep_cost(
                "lpa_superstep", "bucketed", graph.num_vertices,
                graph.num_messages, graph.num_edges, plan=plan,
            )
            current["mem"] = superstep_footprint(
                "lpa_superstep", "bucketed", graph.num_vertices,
                graph.num_messages, num_edges=graph.num_edges, plan=plan,
            )
            m.emit(
                "plan_build", op="lpa_superstep", seconds=round(secs, 6),
                cached=cached, cost=current["cost"].record(),
                **plan_build_stats(plan, graph.num_edges),
            )
            current["chunk_size"] = graph.num_vertices
            step = jax.jit(lpa_superstep_bucketed)
            return lambda lbl: step(lbl, graph, plan)
        # "single": the planner-resolved fused plan family — the
        # degree-bucketed kernel (ops/bucketed_mode.py, ~3x the sort
        # superstep) or the propagation-blocking bin-then-reduce engine
        # (ops/blocking.py, past the gather roofline); identical labels
        # either way. The plan was built alongside the Graph from one
        # shared message-CSR pass (wants_plan in run_pipeline is true
        # exactly for this branch).
        from graphmine_tpu.ops.blocking import (
            BlockedPlan,
            lpa_superstep_blocked,
        )
        from graphmine_tpu.ops.bucketed_mode import lpa_superstep_bucketed

        if plan_holder[0] is None:
            raise ValueError("single-device LPA requires the fused plan "
                             "built by run_pipeline (wants_plan)")
        current["chunk_size"] = graph.num_vertices
        plan = plan_holder[0]
        current["cost"] = superstep_cost(
            "lpa_superstep", "auto", graph.num_vertices,
            graph.num_messages, graph.num_edges, plan=plan,
        )
        current["mem"] = superstep_footprint(
            "lpa_superstep", "auto", graph.num_vertices,
            graph.num_messages, num_edges=graph.num_edges, plan=plan,
        )
        step = jax.jit(
            lpa_superstep_blocked if isinstance(plan, BlockedPlan)
            else lpa_superstep_bucketed
        )
        return lambda lbl: step(lbl, graph, plan)

    def save_ck(iteration: int) -> None:
        if not config.checkpoint_dir:
            return
        if current["ndev"] > 1:
            # Distributed rungs write the shard-aware manifest format:
            # per-shard files + sha256 manifest, re-shardable on restore
            # (the elastic path after a chip loss resumes on D' != D).
            ckpt.save_sharded(
                config.checkpoint_dir, np.asarray(state["labels"]),
                iteration, fingerprint=fingerprint,
                num_shards=current["ndev"], sink=m,
            )
        else:
            ckpt.save_labels(
                config.checkpoint_dir, state["labels"], iteration,
                fingerprint=fingerprint, sink=m,
            )

    # Built supersteps survive retry re-entry: a transient failure at
    # superstep N must not repartition/reshard the whole graph (minutes
    # of host+device work at scale) nor emit a duplicate "partition"
    # record before resuming at N. Keyed (variant, ndev): the elastic
    # rungs rebuild the same schedule on a smaller mesh.
    superstep_cache: dict = {}
    # Operating points that have completed >=1 superstep in THIS build:
    # the first superstep of a freshly built point includes its XLA
    # compile, which can dwarf the steady-state bound the operator sized
    # the watchdog for — arming it there would kill the very rung a
    # degradation just rescued the run with. The watchdog arms from the
    # second superstep.
    warmed: set = set()
    # Operating points whose entry preamble (cache purge, device-loss
    # state salvage, mesh_degrade record) already ran: transient-retry
    # re-entries must not re-salvage or re-emit.
    entered: set = set()
    trip_k = policy.tripwire_every_k

    def check_tripwire(new, it: int, variant: str) -> None:
        """Host-side divergence tripwire at the superstep boundary (the
        driver already syncs each superstep for the labels-changed
        counter, so the guard costs one more reduction every K steps).
        Real vertices can only ever carry real vertex ids — the mode /
        min of incoming real labels, or their own id — so anything
        outside [0, V) means corrupted state. The in-memory iterate is
        untrusted after a trip: roll back to the last checkpoint before
        raising the (retryable) error, so the retry resumes from trusted
        bytes instead of re-propagating the garbage."""
        bad = (new < 0) | (new >= graph.num_vertices)
        n_bad = int(bad.sum())
        if not n_bad:
            return
        # The REAL per-device chunk (partition_graph's padded size,
        # recorded by make_superstep) — a ceil(V/D) approximation would
        # attribute boundary vertices to the wrong shard.
        chunk = current.get("chunk_size") or graph.num_vertices
        shard = int(jnp.argmax(bad)) // chunk
        err = resilience.DivergenceError(
            "label_out_of_range", shard, it + 1
        )
        m.tripwire(
            err.kind, err.shard, err.iteration,
            stage="lpa", bad_vertices=n_bad, variant=variant,
        )
        restored = (
            _reload_checkpoint() if config.checkpoint_dir else None
        )
        if restored is not None:
            state["labels"] = jnp.asarray(restored[0], dtype=jnp.int32)
            state["it"] = restored[1]
            m.emit("resume", iteration=restored[1], reason="tripwire")
        raise err

    def make_runner(variant: str | None, ndev: int | None = None):
        """The remaining-supersteps loop at one operating point. Runs
        iterations one jit call at a time so the labels-changed counter
        and edges/sec stay observable (the loop is device-resident; only
        the scalar counter syncs) and every superstep is a watchdog +
        checkpoint + tripwire boundary. ``ndev=None`` inherits the mesh
        size current at entry (memory rungs lean the schedule wherever
        the elastic ladder already moved the run); an explicit ``ndev``
        is an elastic device rung. ``variant=None`` inherits the variant
        current at entry: a device rung must rebuild the schedule the run
        was ACTUALLY using — re-running the planner's original choice
        would undo a memory degradation whose rung was already consumed
        (replicated OOMs -> ring rescues -> chip dies -> the smaller mesh
        must run ring, not replicated again)."""

        def run():
            nd = current["ndev"] if ndev is None else ndev
            var = current["variant"] if variant is None else variant
            key = (var, nd)
            if key not in entered:
                entered.add(key)
                if nd < current["ndev"]:
                    # Elastic descent: route the rung mesh around the
                    # implicated chip(s), and salvage the loop state —
                    # the failed mesh's device arrays may be GONE with
                    # the lost chip. In-memory labels when the host
                    # transfer still works, else the last sharded
                    # checkpoint (re-shard on restore handles the new
                    # device count).
                    _note_dead_devices()
                    try:
                        host_labels = np.asarray(state["labels"])
                        resumed_from = "memory"
                    except Exception as salvage_err:
                        restored = (
                            _reload_checkpoint()
                            if config.checkpoint_dir else None
                        )
                        if restored is None:
                            raise RuntimeError(
                                "device loss with no recoverable state: "
                                "the in-memory labels died with the mesh "
                                f"({salvage_err!r}) and no checkpoint "
                                "exists — set checkpoint_dir to make "
                                "device loss survivable"
                            ) from salvage_err
                        host_labels, state["it"] = restored
                        resumed_from = "checkpoint"
                    state["labels"] = jnp.asarray(
                        host_labels, dtype=jnp.int32
                    )
                    m.emit(
                        "mesh_degrade", from_devices=current["ndev"],
                        to_devices=nd, schedule=var,
                        iteration=state["it"], resumed_from=resumed_from,
                        dead_devices=sorted(dead_devices),
                    )
            current["ndev"], current["variant"] = nd, var
            # The ladder degrades BECAUSE device memory ran out (or a
            # chip died): before building this rung's superstep, release
            # everything the failed rung held on device — its cached
            # superstep closure (sharded label/bucket arrays) and, once
            # the fused kernel is abandoned, the plan's padded bucket
            # matrices. Retries re-enter the SAME operating point, so its
            # cache entry survives.
            for stale in [k for k in superstep_cache if k != key]:
                del superstep_cache[stale]
                warmed.discard(stale)  # re-entry would recompile
            if var != "single":
                plan_holder[0] = None
            if key not in superstep_cache:
                superstep_cache[key] = make_superstep(var, nd)
            one_iter = superstep_cache[key]
            m.registry.gauge(
                "graphmine_devices_alive",
                "devices in the active LPA mesh",
            ).set(nd)
            # A rung entry (or retry re-entry) starts a fresh timing
            # window: a window must never mix supersteps from two
            # operating points — the cost model it is judged against is
            # per-point.
            wtimer.reset()
            # Rung-entry watermark (ISSUE 14): predicted footprint of the
            # operating point just built vs the bytes actually resident —
            # the baseline an OOM later in this rung is triaged against
            # (memory_stats is a host query; no device sync).
            _mem_watermark(state["it"], var, nd)
            while state["it"] < config.max_iter:
                it = state["it"]

                def step_sync():
                    resilience.fault_point(
                        "lpa_superstep", iteration=it + 1, variant=var,
                        state=state, num_shards=nd,
                    )
                    new = one_iter(state["labels"])
                    new.block_until_ready()
                    return new

                # Superstep span (emit=False: lpa_iter IS the superstep
                # record, already carrying this span's identity — a span
                # record per superstep would double the stream). The
                # TraceAnnotation names the XLA profiler slice after the
                # span path, lining device traces up with the span tree.
                with m.span("superstep", emit=False, iteration=it + 1):
                    was_warm = key in warmed
                    t0 = time.perf_counter()
                    # Watchdog contract: checkpoint-then-abort. On a hung
                    # superstep the LAST GOOD labels (iteration `it`) are
                    # saved before SuperstepTimeout surfaces, so the run
                    # resumes exactly where it hung. Unarmed (None) for an
                    # operating point's compile-bearing first superstep —
                    # see ``warmed`` above.
                    new = resilience.run_with_watchdog(
                        "lpa_superstep", step_sync,
                        policy.superstep_timeout_s if was_warm else None,
                        m,
                        # no hook at all without a checkpoint_dir: the
                        # timeout message/record must not claim a
                        # checkpoint was saved
                        on_timeout=(
                            (lambda it=it: save_ck(it))
                            if config.checkpoint_dir else None
                        ),
                    )
                    dt = time.perf_counter() - t0
                    warmed.add(key)
                    if was_warm:
                        # the compile-bearing first superstep of an
                        # operating point is excluded from the timing
                        # window, exactly like the watchdog above — a
                        # compile-dominated window would read far below
                        # model on healthy hardware, the false positive
                        # the roofline flag exists to avoid
                        wtimer.add(dt)
                    # Cadence (r3): every Nth superstep, plus always the
                    # final one so a completed run's checkpoint is never
                    # stale.
                    will_save = config.checkpoint_dir and (
                        (it + 1) % config.checkpoint_every == 0
                        or it + 1 == config.max_iter
                    )
                    # A superstep that will CHECKPOINT is always guarded
                    # too (when tripwires are armed): persisting
                    # unverified labels would rotate the last
                    # tripwire-validated generation away, and the rollback
                    # the tripwire promises would restore
                    # intact-but-garbage bytes.
                    if trip_k and ((it + 1) % trip_k == 0 or will_save):
                        check_tripwire(new, it, var)
                    # Superstep telemetry piggybacks on the EXISTING
                    # cadence (tripwire / checkpoint boundaries, plus the
                    # final superstep): the driver already syncs each
                    # superstep for the labels-changed counter, so the
                    # per-shard [D] fetch adds no sync point — and
                    # off-cadence supersteps pay nothing. At a telemetry
                    # boundary the changed count comes from the per-shard
                    # sums (one diff pass, not two).
                    if will_save or it + 1 == config.max_iter or (
                        trip_k and (it + 1) % trip_k == 0
                    ):
                        changed = _emit_superstep_telemetry(
                            m, new, state["labels"],
                            current.get("chunk_size") or graph.num_vertices,
                            nd, var, it + 1,
                        )
                        # superstep_timing rides the same cadence: the
                        # window since the last boundary, judged against
                        # this operating point's cost model (ISSUE 12).
                        wtimer.flush(
                            m, "lpa_superstep", current.get("cost"),
                            it + 1, graph.num_edges, variant=var,
                        )
                        # memory_watermark rides the same boundary
                        # (ISSUE 14): predicted vs measured peak for
                        # this operating point, zero extra syncs.
                        _mem_watermark(it + 1, var, nd)
                    else:
                        changed = int((new != state["labels"]).sum())
                    state["labels"] = new
                    state["it"] = it + 1
                    reg = m.registry
                    reg.gauge(
                        "graphmine_superstep", "last completed LPA superstep"
                    ).set(it + 1)
                    reg.gauge(
                        "graphmine_labels_changed",
                        "labels changed in the last superstep",
                    ).set(changed)
                    reg.counter(
                        "graphmine_supersteps_total",
                        "LPA supersteps completed this run",
                    ).inc()
                    m.lpa_iteration(it + 1, changed, graph.num_edges, dt, chips)
                    if will_save:
                        save_ck(it + 1)
            return state["labels"]

        return run

    from graphmine_tpu.pipeline.planner import (
        degradation_ladder,
        elastic_device_ladder,
    )

    rungs = degradation_ladder(
        run_plan.schedule, n_dev,
        family=sstep_plan.family if sstep_plan is not None else "bucketed",
    )
    # Elastic device rungs (DEGRADABLE_DEVICE failures): halved mesh,
    # resumed from salvage/checkpoint, running the variant CURRENT at
    # descent time (variant=None) — a memory degradation that already
    # moved the run off the planner's original schedule must survive the
    # descent (replicated OOMs -> ring rescues -> chip dies -> ring@2dev,
    # never replicated again). The 1-device floor runs the sort-based
    # single kernel — only when the full graph fits one device (in
    # scale-out mode there is no such floor).
    device_rungs = []
    for d2 in elastic_device_ladder(run_plan.schedule, n_dev):
        if d2 > 1:
            device_rungs.append(
                (f"elastic@{d2}dev", make_runner(None, d2))
            )
        elif run_plan.estimates.get("single", 0) <= run_plan.hbm_bytes:
            device_rungs.append(
                ("single_sort@1dev", make_runner("single_sort", 1))
            )
    # An explicitly forced "sort" family (env) runs the sort superstep
    # as its primary — no plan was built, and "single" would demand one.
    primary = (
        "single_sort"
        if (
            run_plan.schedule == "single"
            and sstep_plan is not None and sstep_plan.family == "sort"
        )
        else run_plan.schedule
    )
    with maybe_profile(config.profile_dir, sink=m):
        labels = resilience.run_phase(
            "lpa", make_runner(primary), policy, m,
            ladder=tuple((v, make_runner(v)) for v in rungs),
            device_ladder=tuple(device_rungs),
            # supersteps advanced since the last failure => a NEW incident:
            # the retry budget bounds attempts per incident, not per run
            progress=lambda: state["it"],
            # a reactive OOM's degrade record carries the failed point's
            # modeled inventory + the last watermark (ISSUE 14)
            degrade_context=_lpa_degrade_context,
        )
    return labels


def main(argv=None) -> None:
    import logging

    logging.basicConfig(level=logging.INFO, format="%(message)s")
    from graphmine_tpu.pipeline.config import parse_args

    config = parse_args(argv)  # --help / bad flags exit before jax loads
    from graphmine_tpu.compile_cache import enable_compile_cache

    enable_compile_cache()
    result = run_pipeline(config)
    _show(result, config.show)


def _show(result: PipelineResult, n: int) -> None:
    """Terminal summary (parity with the reference's .show(10) calls)."""
    present, sizes, edges = result.community_table
    order = np.argsort(sizes)[::-1][:n]
    print(f"\nVertices: {result.edge_table.num_vertices}  "
          f"Edges: {result.edge_table.num_edges}")
    print(f"There are {result.num_communities} Communities in the Dataset.")
    print(f"\nTop {len(order)} communities (label, vertices, intra-edges):")
    for i in order:
        name = result.edge_table.names[present[i]]
        print(f"  {present[i]:>8}  {sizes[i]:>8}  {edges[i]:>8}   ({name})")
    if result.outliers is not None:
        print(f"\nRecursive-LPA outliers: {int(result.outliers.outlier_vertices.sum())} "
              f"vertices in bottom-decile sub-communities")
    if result.lof is not None:
        top = np.argsort(result.lof)[::-1][:n]
        print(f"\nTop {len(top)} LOF outliers (vertex, score, name):")
        for v in top:
            print(f"  {v:>8}  {result.lof[v]:>7.3f}   ({result.edge_table.names[v]})")


if __name__ == "__main__":
    main()
