"""End-to-end pipeline driver: load → build → LPA → census → outliers.

Reproduces the five phases of the reference script
(``CommunityDetection/Graphframes.py``) with the TPU-native engine:

  CS-1 ingestion (:12-32)      → parquet/edge-list load, null filter, counts
  CS-2 graph construction (:53-78) → dense factorize + message CSR
  CS-3 label propagation (:81-85)  → jit/shard_map LPA supersteps
  CS-4 census (:92-120)            → segment-sum community table
  CS-5 outliers (:121-137, dead)   → recursive LPA decile + kNN/LOF scores

plus the subsystems the reference lacked: structured metrics (edges/sec/
chip), profiling, checkpoint/resume, multi-device execution.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

import numpy as np

from graphmine_tpu.graph.container import Graph, graph_from_edge_table
from graphmine_tpu.io.edges import EdgeTable, load_edge_list, load_parquet_edges
from graphmine_tpu.pipeline import checkpoint as ckpt
from graphmine_tpu.pipeline.config import PipelineConfig
from graphmine_tpu.pipeline.metrics import MetricsSink, maybe_profile


def _visible_devices() -> int:
    import jax

    return len(jax.devices())


def device_hbm_bytes() -> int | None:
    """Best-effort real per-device HBM via ``memory_stats()``.

    Returns None when the backend doesn't report it (CPU returns None,
    some tunneled runtimes raise) — the planner then falls back to its
    16 GiB default. Queried here, not in the planner, so host-side
    planning paths never import jax (planner.hbm_bytes_per_device)."""
    import jax

    try:
        stats = jax.devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if limit and limit > 0 else None


@dataclass
class PipelineResult:
    edge_table: EdgeTable
    graph: Graph
    labels: np.ndarray                 # community label per vertex
    num_communities: int
    community_table: tuple             # (labels present, sizes, intra-edge counts)
    outliers: object | None = None     # OutlierReport (recursive_lpa)
    lof: np.ndarray | None = None      # LOF score per vertex
    metrics: MetricsSink = field(default_factory=MetricsSink)


def run_pipeline(config: PipelineConfig) -> PipelineResult:
    config.validate()
    m = MetricsSink()

    # ---- CS-1 ingestion -------------------------------------------------
    with m.timed("load", path=config.data_path, format=config.data_format):
        if config.data_format == "parquet":
            table = load_parquet_edges(config.data_path, batch_rows=config.batch_rows)
        else:
            table = load_edge_list(
                config.data_path, weight_col=config.edge_weight_col
            )
    m.emit(
        "counts",  # parity with the prints at Graphframes.py:18 and :54
        rows_raw=table.num_rows_raw,
        edges=table.num_edges,
        vertices=table.num_vertices,
    )

    # ---- CS-2 graph construction ---------------------------------------
    # Schedule resolution happens HERE, before any device allocation: the
    # memory planner (pipeline/planner.py) models per-device HBM for each
    # schedule and either picks one ("auto") or validates the requested
    # one — an impossible config raises PlanError with the numbers now,
    # instead of OOMing deep inside XLA after minutes of graph build.
    n_dev = config.num_devices or _visible_devices()
    run_plan = None
    if config.community_method == "lpa" and config.backend != "graphframes":
        from graphmine_tpu.pipeline.planner import (
            hbm_bytes_per_device,
            plan_run,
        )

        # Budget chain: env override → what THIS device actually reports
        # (a v4/v5p part has 2-6x the v5e default) → 16 GiB. The callable
        # keeps the device query lazy: an env-pinned budget never touches
        # memory_stats.
        run_plan = plan_run(
            table.num_vertices,
            table.num_edges,
            n_dev,
            weighted=table.weights is not None,
            requested=config.schedule,
            hbm=hbm_bytes_per_device(device_hbm_bytes),
        )
        m.emit(
            "plan",
            schedule=run_plan.schedule,
            bytes_per_device=run_plan.bytes_per_device,
            hbm_budget=run_plan.hbm_bytes,
            reason=run_plan.reason,
        )
    # The fused LPA plan is only consumed by the single-device jax LPA
    # path; build it (from the same message-CSR pass as the Graph) only
    # when that path will run — it is pure HBM/host waste for louvain,
    # graphframes, and sharded runs.
    wants_plan = run_plan is not None and run_plan.schedule == "single"
    # Scale-out mode (r3): when the planner chose a distributed schedule
    # AND the whole graph cannot also fit one device, the full Graph stays
    # HOST-side NumPy — partitioning slices it onto the mesh, and the
    # census/modularity phases dispatch to their NumPy twins. Building it
    # device-resident here would OOM device 0 before LPA ever ran.
    scale_out = (
        run_plan is not None
        and run_plan.schedule != "single"
        and run_plan.estimates.get("single", 0) > run_plan.hbm_bytes
    )
    if scale_out:
        m.emit("scale_out", message="full graph exceeds one device: host-"
               "resident graph; outlier phases run distributed (recursive "
               "LPA over the intra-community subgraph, sharded kNN/LOF)")
    with m.timed("build_graph"):
        if wants_plan:
            from graphmine_tpu.ops.bucketed_mode import build_graph_and_plan

            graph, mode_plan = build_graph_and_plan(
                table.src, table.dst, num_vertices=table.num_vertices,
                edge_weights=table.weights,
            )
        else:
            graph = graph_from_edge_table(table, to_device=not scale_out)
            mode_plan = None

    # ---- CS-3 community detection --------------------------------------
    if config.community_method in ("louvain", "leiden"):
        from graphmine_tpu.ops.louvain import leiden, louvain

        if config.checkpoint_dir:
            m.emit("warning", message="checkpoint/resume applies to LPA only; "
                   f"{config.community_method} runs are not checkpointed")
        algo = leiden if config.community_method == "leiden" else louvain
        with m.timed(config.community_method, gamma=config.gamma):
            labels, q = algo(graph, gamma=config.gamma)
    else:
        labels = _run_lpa(config, table, graph, m, mode_plan, n_dev, run_plan)
        q = None

    # ---- CS-4 census ----------------------------------------------------
    from graphmine_tpu.ops.census import census_table
    from graphmine_tpu.ops.lpa import num_communities
    from graphmine_tpu.ops.modularity import modularity

    with m.timed("census"):
        n_comm = int(num_communities(labels))
        present, sizes, edge_counts = census_table(labels, graph)
        if q is None:
            q = float(modularity(labels, graph, gamma=config.gamma))
    # parity with "There are N Communities in the Dataset." (:85)
    m.emit("communities", count=n_comm, largest=int(sizes.max(initial=0)), modularity=round(q, 6))

    result = PipelineResult(
        edge_table=table,
        graph=graph,
        labels=np.asarray(labels),
        num_communities=n_comm,
        community_table=(present, sizes, edge_counts),
        metrics=m,
    )

    # ---- CS-5 outliers --------------------------------------------------
    if config.outlier_method in ("recursive_lpa", "both"):
        if scale_out:
            # The device-resident masked pass would materialize the full
            # graph on one device, which the planner just ruled out.
            # Run the distributed composition instead: host-side
            # intra-community edge filter → planner-resolved distributed
            # LPA schedule → host decile (VERDICT r3 item 2). scale_out
            # implies a multi-device plan (plan_run maps any request on
            # one device to "single"), so a mesh always exists here.
            from graphmine_tpu.ops.outliers import recursive_lpa_outliers_sharded
            from graphmine_tpu.parallel.mesh import make_mesh

            with m.timed("outliers_recursive_lpa", schedule=run_plan.schedule,
                         devices=n_dev):
                result.outliers = recursive_lpa_outliers_sharded(
                    graph, labels, make_mesh(n_dev),
                    max_iter=config.sub_max_iter, decile=config.decile,
                    schedule=run_plan.schedule,
                )
        else:
            from graphmine_tpu.ops.outliers import recursive_lpa_outliers

            with m.timed("outliers_recursive_lpa"):
                result.outliers = recursive_lpa_outliers(
                    graph, labels, max_iter=config.sub_max_iter, decile=config.decile
                )
        m.emit(
            "outlier_summary",
            method="recursive_lpa",
            flagged_vertices=int(result.outliers.outlier_vertices.sum()),
            sub_communities=len(result.outliers.sub_sizes),
        )
    if config.outlier_method in ("lof", "both"):
        from graphmine_tpu.ops.features import (
            standardize,
            vertex_features,
            vertex_features_host,
        )
        from graphmine_tpu.ops.lof import lof_scores

        from graphmine_tpu.parallel.knn import can_shard

        k = min(config.lof_k, graph.num_vertices - 1)
        use_sharded_lof = n_dev > 1 and can_shard(graph.num_vertices, n_dev, k)
        if use_sharded_lof and config.lof_impl != "auto":
            m.emit(
                "warning",
                message=f"lof_impl={config.lof_impl!r} applies to the "
                "single-device scorer only; the multi-device path runs "
                "the exact ring-sharded kNN/LOF",
            )
        if scale_out and not use_sharded_lof:
            m.emit(
                "warning",
                message="lof skipped in scale-out mode: the all-pairs "
                "single-device scorer cannot hold a graph this size; add "
                "devices so the sharded kNN/LOF path can run",
            )
            return result
        # Wedge-budget guard (r5): the exact clustering pipeline
        # materializes every oriented wedge on the host (~28 B each) —
        # a mega-hub power-law graph at 25M edges has ~10^10 of them,
        # and the first e2e bench run was OOM-killed at 130 GB RSS
        # before this guard existed. The probe is O(E log E) host work;
        # past the budget the clustering column comes from the sampled
        # estimator (stderr <= 1/(2*sqrt(64)) per vertex), same as
        # scale-out mode. Default 2.5e8 wedges ~ 7 GB host scratch.
        feature_mode = "device-8"
        simple_edges = None
        if not scale_out:
            from graphmine_tpu.graph.container import simple_undirected_edges
            from graphmine_tpu.ops.triangles import oriented_wedge_count

            wedge_budget = int(float(os.environ.get(
                "GRAPHMINE_WEDGE_BUDGET", "2.5e8"
            )))
            # One O(E log E) dedup, shared with the clustering column
            # below (exact or sampled) — the probe must not double the
            # host prep it exists to bound (code-review r5).
            simple_edges = simple_undirected_edges(graph)
            wedges = oriented_wedge_count(graph, simple_edges=simple_edges)
            if wedges > wedge_budget:
                feature_mode = "device-8-sampled"
                m.emit(
                    "warning",
                    message=f"exact clustering infeasible: {wedges:,} "
                    f"oriented wedges exceed GRAPHMINE_WEDGE_BUDGET="
                    f"{wedge_budget:,} (~28 B/wedge host scratch); using "
                    "the wedge-sampled estimator",
                )
        with m.timed("outliers_lof", k=config.lof_k,
                     devices=n_dev if use_sharded_lof else 1,
                     features="host-8-sampled" if scale_out else feature_mode):
            if scale_out:
                # Host feature twin (no O(E) device transfer). The exact
                # wedge pipeline is infeasible exactly when the graph
                # exceeds one device, so the clustering column comes from
                # the wedge-SAMPLED estimator (r4): the full 8-feature
                # set survives at scale with a bounded per-vertex error
                # (ops/triangles.sampled_clustering_coefficient).
                feats = standardize(vertex_features_host(
                    graph, labels, include_clustering="sampled"
                ))
            else:
                feats = standardize(vertex_features(
                    graph, labels,
                    include_clustering=(
                        "sampled" if feature_mode == "device-8-sampled"
                        else True
                    ),
                    simple_edges=simple_edges,
                ))
            if use_sharded_lof:
                # Multi-device: ring-sharded kNN + distributed LOF — the
                # O(V^2) distance work is scheduled over the mesh with no
                # replicated [V, F] (parallel/knn.py).
                from graphmine_tpu.parallel.knn import sharded_lof
                from graphmine_tpu.parallel.mesh import make_mesh

                scores = sharded_lof(feats, make_mesh(n_dev), k=k)
            else:
                # config.lof_impl="ivf" opts large clouds into the
                # approximate IVF index (r5; measured ~3x at 262K points
                # for ~0.001 AUROC — see config.py)
                scores = lof_scores(feats, k=k, impl=config.lof_impl)
            result.lof = np.asarray(scores)
        m.emit(
            "outlier_summary",
            method="lof",
            max_score=float(result.lof.max()),
            over_1_5=int((result.lof > 1.5).sum()),
        )
    return result


def _run_lpa(
    config: PipelineConfig, table: EdgeTable, graph: Graph, m: MetricsSink,
    mode_plan, n_dev: int, run_plan,
):
    """Community detection with backend dispatch, checkpointing and
    per-iteration metrics. Runs iterations one jit call at a time so the
    labels-changed counter and edges/sec are observable (the whole loop is
    still device-resident; only the scalar counter syncs)."""
    if config.backend == "graphframes":
        from graphmine_tpu.pipeline.backends import lpa_graphframes

        with m.timed("lpa", backend="graphframes"):
            return lpa_graphframes(table, config.max_iter)

    import jax
    import jax.numpy as jnp

    from graphmine_tpu.parallel.mesh import make_mesh
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_label_propagation,
    )

    chips = max(n_dev, 1)
    start_iter = 0
    labels = jnp.arange(graph.num_vertices, dtype=jnp.int32)

    # One O(E) hash per run; ties every checkpoint to this exact graph,
    # id assignment (bulk vs batch_rows ingestion assign different ids),
    # and edge weights (weighted/unweighted trajectories differ).
    fingerprint = (
        ckpt.graph_fingerprint(table.src, table.dst, table.weights)
        if config.checkpoint_dir else None
    )

    if config.resume and config.checkpoint_dir:
        loaded = ckpt.load_labels(config.checkpoint_dir, fingerprint=fingerprint)
        if loaded is not None:
            saved_labels, start_iter = loaded
            if start_iter > config.max_iter:
                raise ValueError(
                    f"checkpoint at iteration {start_iter} exceeds "
                    f"max_iter={config.max_iter}; delete the checkpoint or "
                    f"raise max_iter"
                )
            labels = jnp.asarray(saved_labels, dtype=jnp.int32)
            m.emit("resume", iteration=start_iter)

    # Dispatch on the planner-resolved schedule (plan_run maps an explicit
    # "ring"/"replicated" request on one device to "single").
    if config.schedule == "ring" and run_plan.schedule == "single":
        m.emit("warning", message="schedule='ring' needs >1 device; "
               "running the single-device fused kernel instead")
    if run_plan.schedule == "ring":
        # Memory-scalable schedule: labels stay sharded, chunks rotate
        # over ICI (parallel/ring.py). Uses the sort-body message CSR.
        from graphmine_tpu.parallel.ring import ring_label_propagation

        mesh = make_mesh(n_dev)
        with m.timed("partition", shards=n_dev, schedule="ring"):
            sg = shard_graph_arrays(partition_graph(graph, mesh=mesh), mesh)

        def one_iter(lbl):
            return ring_label_propagation(sg, mesh, max_iter=1, init_labels=lbl)

    elif run_plan.schedule == "replicated":
        mesh = make_mesh(n_dev)
        with m.timed("partition", shards=n_dev, schedule="replicated"):
            sg = shard_graph_arrays(
                partition_graph(graph, mesh=mesh, build_bucket_plan=True),
                mesh,
                lpa_only=run_plan.lpa_only,
            )

        def one_iter(lbl):
            return sharded_label_propagation(sg, mesh, max_iter=1, init_labels=lbl)

    else:
        # Fused degree-bucketed kernel (ops/bucketed_mode.py): ~3x the
        # sort-based superstep, identical labels. The plan was built
        # alongside the Graph from one shared message-CSR pass
        # (wants_plan in run_pipeline is true exactly for this branch).
        from graphmine_tpu.ops.bucketed_mode import lpa_superstep_bucketed

        if mode_plan is None:
            raise ValueError("single-device LPA requires the fused plan "
                             "built by run_pipeline (wants_plan)")
        plan = mode_plan
        step = jax.jit(lpa_superstep_bucketed)

        def one_iter(lbl):
            return step(lbl, graph, plan)

    with maybe_profile(config.profile_dir):
        for it in range(start_iter, config.max_iter):
            t0 = time.perf_counter()
            new = one_iter(labels)
            new.block_until_ready()
            dt = time.perf_counter() - t0
            changed = int((new != labels).sum())
            labels = new
            m.lpa_iteration(it + 1, changed, graph.num_edges, dt, chips)
            # Cadence (r3): every Nth superstep, plus always the final one
            # so a completed run's checkpoint is never stale.
            if config.checkpoint_dir and (
                (it + 1) % config.checkpoint_every == 0
                or it + 1 == config.max_iter
            ):
                ckpt.save_labels(
                    config.checkpoint_dir, labels, it + 1, fingerprint=fingerprint
                )
    return labels


def main(argv=None) -> None:
    import logging

    logging.basicConfig(level=logging.INFO, format="%(message)s")
    from graphmine_tpu.pipeline.config import parse_args

    config = parse_args(argv)  # --help / bad flags exit before jax loads
    from graphmine_tpu.compile_cache import enable_compile_cache

    enable_compile_cache()
    result = run_pipeline(config)
    _show(result, config.show)


def _show(result: PipelineResult, n: int) -> None:
    """Terminal summary (parity with the reference's .show(10) calls)."""
    present, sizes, edges = result.community_table
    order = np.argsort(sizes)[::-1][:n]
    print(f"\nVertices: {result.edge_table.num_vertices}  "
          f"Edges: {result.edge_table.num_edges}")
    print(f"There are {result.num_communities} Communities in the Dataset.")
    print(f"\nTop {len(order)} communities (label, vertices, intra-edges):")
    for i in order:
        name = result.edge_table.names[present[i]]
        print(f"  {present[i]:>8}  {sizes[i]:>8}  {edges[i]:>8}   ({name})")
    if result.outliers is not None:
        print(f"\nRecursive-LPA outliers: {int(result.outliers.outlier_vertices.sum())} "
              f"vertices in bottom-decile sub-communities")
    if result.lof is not None:
        top = np.argsort(result.lof)[::-1][:n]
        print(f"\nTop {len(top)} LOF outliers (vertex, score, name):")
        for v in top:
            print(f"  {v:>8}  {result.lof[v]:>7.3f}   ({result.edge_table.names[v]})")


if __name__ == "__main__":
    main()
