"""Pipeline configuration — one dataclass + CLI.

Replaces the reference's scattered hardcoded constants (SURVEY §5 config):
the GraphFrames package pin env var (``Graphframes.py:3``), ``local[*]``
(``:12``), the data glob (``:16``), ``maxIter=5`` (``:81``, ``:126``),
``show(10)``, and the bottom-decile outlier threshold (``:136``).
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field

from graphmine_tpu.pipeline.resilience import ResilienceConfig


@dataclass
class PipelineConfig:
    # data
    data_path: str = "/root/reference/CommunityDetection/data/outlinks_pq"
    data_format: str = "parquet"  # parquet | edgelist
    batch_rows: int | None = None  # parquet only: stream in bounded batches
    # edgelist only: 0-based column holding a per-edge float weight
    # (weighted LPA: mode = argmax of incoming weight sums).
    edge_weight_col: int | None = None
    # engine (the plugin boundary from BASELINE.json)
    backend: str = "jax"  # jax | graphframes
    num_devices: int | None = None  # None = all visible (local[*] parity, :12)
    # Multi-device LPA schedule: "auto" (default, r3) consults the memory
    # planner (pipeline/planner.py) and picks the fastest schedule that
    # fits per-device HBM — single-device fused kernel, else "replicated"
    # (gathers the full V-length label vector per superstep; fastest to
    # ~100M vertices), else "ring" (labels stay sharded, chunks rotate
    # over ICI via ppermute — O(V/D + M/D) per device). Explicit
    # "replicated"/"ring" are honored but still planner-checked: an
    # impossible config fails loudly at plan time, not inside XLA.
    schedule: str = "auto"  # auto | replicated | ring
    # community detection
    community_method: str = "lpa"  # lpa (Graphframes.py:81 parity) | louvain | leiden
    max_iter: int = 5  # Graphframes.py:81
    gamma: float = 1.0  # louvain resolution
    # outlier detection
    outlier_method: str = "both"  # recursive_lpa | lof | both | none
    sub_max_iter: int = 5  # Graphframes.py:126
    decile: float = 0.1  # Graphframes.py:136
    # LOF neighborhood size. Must exceed the size of any *clustered*
    # anomaly group or the group's members score each other as inliers:
    # measured AUROC 0.49 at k=20 vs 0.91-0.93 at k>=100 on 64 injected
    # hubs (docs/DESIGN.md, bench.py --tier lof). 128 is the measured
    # best; the driver clamps it to num_vertices - 1 on small graphs.
    lof_k: int = 128
    # LOF kNN implementation. "auto" (r6) is SCALE-AWARE: the planner
    # deploys the approximate IVF-flat index at the measured crossover
    # (>= 131K points — 3.1x over exact at 262K for recall 0.9999 /
    # AUROC -0.001; docs/DESIGN.md "LOF impl auto-policy"), the exact
    # path below it (whose own XLA/Pallas choice is ops/knn.py's
    # measured policy). The resolved family is emitted as an
    # impl_selected metrics record, and the degradation ladder runs the
    # opposite family as its rung. Explicit values force a path;
    # GRAPHMINE_LOF_IVF_MIN_N moves the crossover.
    lof_impl: str = "auto"  # auto | xla | pallas | ivf
    # observability (docs/OBSERVABILITY.md)
    show: int = 10  # .show(10) parity
    profile_dir: str | None = None  # jax.profiler trace output
    # write every metrics record (incl. retry/degrade/quarantine/rollback
    # recovery events, docs/RESILIENCE.md) as JSON lines to this path at
    # the end of the run — the on-disk twin of the logging stream. Opened
    # in APPEND mode: a resumed run reusing the path adds a new
    # run_start-delimited segment instead of clobbering the prior trail.
    metrics_out: str | None = None
    # run identity stamped on every record/span (tools/obs_report.py joins
    # on it); None autogenerates a sortable UTC id. Set it explicitly to
    # correlate with an external scheduler's job id.
    run_id: str | None = None
    # emit a `heartbeat` record every N seconds (phase, gauges, RSS) so a
    # hung run is distinguishable from a dead one; None/0 = off.
    heartbeat_every_s: float | None = None
    # publish the counter/gauge registry as a Prometheus textfile at this
    # path (atomically, each heartbeat + once at exit) — the node_exporter
    # textfile-collector hand-off for runs with no scrape endpoint.
    prom_out: str | None = None
    # serving (docs/SERVING.md): publish the run's results — community
    # labels, CC labels, LOF scores, census, edge arrays, provenance —
    # as a versioned snapshot generation at this store directory, as the
    # pipeline's final phase. The serving layer (graphmine_tpu/serve/,
    # tools/serve_cli.py) queries it and ingests edge deltas against it
    # with warm-start repair instead of cold full recomputes.
    snapshot_out: str | None = None
    # checkpoint / resume
    checkpoint_dir: str | None = None
    # Save every N supersteps (plus always the final one). 1 = every
    # superstep — right for maxIter=5 parity runs; long billion-edge runs
    # (the case checkpointing exists for, SURVEY §5) should raise it: at
    # north-star scale each save is a ~64 MB npz. Multi-device rungs
    # write the sharded MANIFEST format (per-shard files + sha256,
    # re-shardable on restore — docs/RESILIENCE.md); single-device rungs
    # write the npz. --resume reads both and takes the newer iteration.
    checkpoint_every: int = 1
    resume: bool = False
    # resilience (docs/RESILIENCE.md): retry/backoff budget, superstep
    # watchdog, memory + elastic-device degradation policy, and the
    # in-loop divergence tripwires for every pipeline phase. CLI flags
    # are flattened (--max-retries, --superstep-timeout-s,
    # --tripwire-every-k, ...).
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    # Count-and-set-aside malformed rows / NaN weights at ingestion
    # (emitted as a "quarantine" metrics record) instead of crashing.
    # --no-quarantine-inputs restores strict parsing.
    quarantine_inputs: bool = True

    def validate(self) -> "PipelineConfig":
        self.resilience.validate()
        if self.data_format not in ("parquet", "edgelist"):
            raise ValueError(f"unknown data_format {self.data_format!r}")
        if self.backend not in ("jax", "graphframes"):
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.schedule not in ("auto", "replicated", "ring"):
            raise ValueError(f"unknown schedule {self.schedule!r}")
        if self.outlier_method not in ("recursive_lpa", "lof", "both", "none"):
            raise ValueError(f"unknown outlier_method {self.outlier_method!r}")
        if self.lof_impl not in ("auto", "xla", "pallas", "ivf"):
            raise ValueError(f"unknown lof_impl {self.lof_impl!r}")
        if self.community_method not in ("lpa", "louvain", "leiden"):
            raise ValueError(f"unknown community_method {self.community_method!r}")
        if self.backend == "graphframes" and self.community_method != "lpa":
            raise ValueError(
                "backend='graphframes' only provides labelPropagation; "
                "use community_method='lpa' or backend='jax'"
            )
        if self.max_iter < 0 or self.sub_max_iter < 0:
            raise ValueError("max_iter must be >= 0")
        if self.batch_rows is not None and self.batch_rows <= 0:
            raise ValueError("batch_rows must be positive")
        if self.batch_rows is not None and self.data_format != "parquet":
            raise ValueError("batch_rows applies to parquet input only")
        if self.edge_weight_col is not None and self.data_format != "edgelist":
            raise ValueError("edge_weight_col applies to edgelist input only")
        if self.edge_weight_col is not None and self.backend == "graphframes":
            raise ValueError(
                "backend='graphframes' runs unweighted labelPropagation; "
                "use backend='jax' for weighted LPA"
            )
        if not 0 < self.decile < 1:
            raise ValueError("decile must be in (0, 1)")
        if self.checkpoint_every < 1:
            raise ValueError("checkpoint_every must be >= 1")
        if self.heartbeat_every_s is not None and self.heartbeat_every_s <= 0:
            raise ValueError("heartbeat_every_s must be positive (or unset)")
        return self


def parse_args(argv=None) -> PipelineConfig:
    parser = argparse.ArgumentParser(
        prog="graphmine_tpu.pipeline",
        description="TPU-native community + outlier detection pipeline",
    )
    def add_field(f):
        name = "--" + f.name.replace("_", "-")
        default = f.default
        if f.type in ("bool", bool):
            # BooleanOptionalAction so default-True flags (e.g.
            # quarantine_inputs) stay switchable: --no-quarantine-inputs
            parser.add_argument(
                name, action=argparse.BooleanOptionalAction, default=default
            )
        else:
            typ = str
            if f.type in ("int", int):
                typ = int
            elif f.type in ("float", float):
                typ = float
            elif f.type in ("int | None",):
                typ = int
            elif f.type in ("float | None",):
                typ = float
            parser.add_argument(name, type=typ, default=default)

    for f in dataclasses.fields(PipelineConfig):
        if f.name == "resilience":
            continue  # nested config: its fields flatten onto the CLI
        add_field(f)
    res_fields = dataclasses.fields(ResilienceConfig)
    for f in res_fields:
        add_field(f)
    ns = vars(parser.parse_args(argv))
    resilience = ResilienceConfig(
        **{f.name: ns.pop(f.name) for f in res_fields}
    )
    return PipelineConfig(**ns, resilience=resilience).validate()
