"""Structured metrics + profiling hooks.

The reference's only observability was ``print``/``show`` calls
(``Graphframes.py:18,32,54,68,74,82,85,120``). Here every pipeline phase
emits a structured JSON record, and LPA reports the driver's headline
metric — **edges/sec/chip** per iteration (BASELINE.json ``"metric"``).
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from dataclasses import dataclass, field

log = logging.getLogger("graphmine_tpu")


@dataclass
class MetricsSink:
    """Collects phase timings and counters; emits JSON lines via logging.

    ``stream_path``: when set, every record is ALSO appended to that file
    as it is emitted (line-buffered JSONL). Exit-time-only persistence
    would lose exactly the records that matter most — a preemption or
    OOM-kill ends the process without running any ``finally`` block, and
    those are the runs whose retry/degrade/rollback trail the operator
    needs. A stream write failure disables streaming with one warning
    (the in-memory records remain for the exit-time fallback)."""

    records: list = field(default_factory=list)
    stream_path: str | None = None
    _stream: object = field(default=None, repr=False)
    _stream_ok: bool = field(default=True, repr=False)

    def emit(self, phase: str, **kv) -> dict:
        rec = {"phase": phase, "t": time.time(), **kv}
        self.records.append(rec)
        line = json.dumps(rec, default=str)
        log.info("%s", line)
        if self.stream_path is not None and self._stream_ok:
            try:
                if self._stream is None:
                    self._stream = open(self.stream_path, "w")
                self._stream.write(line + "\n")
                self._stream.flush()
            except OSError as e:
                self._stream_ok = False
                log.warning(
                    "metrics stream to %s failed: %r; records will be "
                    "written at exit instead", self.stream_path, e,
                )
        return rec

    @contextlib.contextmanager
    def timed(self, phase: str, **kv):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.emit(phase, seconds=round(time.perf_counter() - t0, 4), **kv)

    def of_phase(self, phase: str) -> list:
        """All records for one phase name — recovery events (``retry``,
        ``degrade``, ``quarantine``, ``checkpoint_rollback``, ...) are
        phases like any other, so observability tooling and tests filter
        them the same way."""
        return [r for r in self.records if r.get("phase") == phase]

    def write_jsonl(self, path: str) -> str:
        """Dump every record as JSON lines (the on-disk twin of the
        logging stream; one file per run for offline triage)."""
        with open(path, "w") as f:
            for rec in self.records:
                f.write(json.dumps(rec, default=str) + "\n")
        return path

    def finalize(self, path: str) -> str:
        """End-of-run persistence: when the live stream wrote every
        record, just close it; otherwise (streaming off, or it failed
        mid-run) write the whole file in one pass."""
        if self._stream is not None:
            try:
                self._stream.close()
            except OSError:
                self._stream_ok = False
            self._stream = None
            if self._stream_ok and self.stream_path == path:
                return path
        return self.write_jsonl(path)

    def tripwire(self, kind: str, shard: int, iteration: int, **kv):
        """Structured record for an in-loop divergence-tripwire firing
        (docs/RESILIENCE.md): which guard, the offending shard index, the
        superstep it fired at — one fixed shape so offline triage can
        filter `of_phase("tripwire")` without per-caller key guessing."""
        return self.emit(
            "tripwire", kind=kind, shard=int(shard),
            iteration=int(iteration), **kv,
        )

    def lpa_iteration(self, it: int, changed: int, num_edges: int, seconds: float, chips: int):
        """Per-superstep record with the headline edges/sec/chip metric."""
        eps = num_edges / seconds if seconds > 0 else float("inf")
        return self.emit(
            "lpa_iter",
            iteration=it,
            labels_changed=changed,
            seconds=round(seconds, 5),
            edges_per_sec=round(eps),
            edges_per_sec_per_chip=round(eps / max(chips, 1)),
        )


@contextlib.contextmanager
def maybe_profile(profile_dir: str | None):
    """jax.profiler trace around a pipeline phase (SURVEY §5 tracing)."""
    if not profile_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(profile_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
