"""Structured metrics + profiling hooks.

The reference's only observability was ``print``/``show`` calls
(``Graphframes.py:18,32,54,68,74,82,85,120``). Here every pipeline phase
emits a structured JSON record, and LPA reports the driver's headline
metric — **edges/sec/chip** per iteration (BASELINE.json ``"metric"``).

Run-correlated tracing (docs/OBSERVABILITY.md): a sink constructed with a
:class:`~graphmine_tpu.obs.spans.Tracer` stamps every record with
``run_id`` / ``trace_id`` / ``span_id`` / ``span_path``, so the
resilience machine's retry / degrade / mesh_degrade / tripwire /
checkpoint records are joinable into one causal timeline
(``tools/obs_report.py``). The sink also owns a counter/gauge
:class:`~graphmine_tpu.obs.registry.Registry` (the level surface the
heartbeat and the Prometheus textfile exporter read).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field

from graphmine_tpu.obs.registry import Registry
from graphmine_tpu.obs.spans import xla_annotation

log = logging.getLogger("graphmine_tpu")


@dataclass
class MetricsSink:
    """Collects phase timings and counters; emits JSON lines via logging.

    ``stream_path``: when set, every record is ALSO appended to that file
    as it is emitted (line-buffered JSONL). Exit-time-only persistence
    would lose exactly the records that matter most — a preemption or
    OOM-kill ends the process without running any ``finally`` block, and
    those are the runs whose retry/degrade/rollback trail the operator
    needs. The stream opens in **append** mode: a resumed run reusing the
    same ``--metrics-out`` path must not clobber the prior attempt's
    trail (each run's records begin at its ``run_start`` header and carry
    its ``run_id``). A stream write failure disables streaming with one
    warning (the in-memory records remain for the exit-time fallback).

    ``tracer``: optional :class:`~graphmine_tpu.obs.spans.Tracer`; when
    set, every record carries the current span's identity. ``registry``:
    the run's counter/gauge registry (always present — callers increment
    unconditionally; it only *exports* when asked).

    ``max_records``: optional in-memory cap for **long-lived serving
    processes** (a batch run keeps the default: everything). The serve
    layer emits one ``access_log`` record per HTTP request; retaining
    them all in ``records`` would grow RSS linearly with traffic until
    the server is OOM-killed. With a cap, the oldest records are
    dropped once the list exceeds it — records already persisted by the
    live stream lose nothing on disk, and :meth:`finalize` accounts for
    the drops so it never re-appends or skips survivors. Callers doing
    exit-time-only persistence with a cap are accepting bounded memory
    over a complete exit dump (the serving CLI streams, so it never
    hits that trade).

    Emission is thread-safe (the heartbeat thread and the driver thread
    share one sink); each record is appended and streamed under one lock.
    """

    records: list = field(default_factory=list)
    stream_path: str | None = None
    tracer: object | None = None
    registry: Registry = field(default_factory=Registry, repr=False)
    max_records: int | None = None
    _stream: object = field(default=None, repr=False)
    _stream_ok: bool = field(default=True, repr=False)
    _streamed: int = field(default=0, repr=False)
    _dropped: int = field(default=0, repr=False)
    _lost: int = field(default=0, repr=False)
    _lost_warned: bool = field(default=False, repr=False)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def emit(self, phase: str, _span=None, **kv) -> dict:
        """Append one record (and stream it). ``_span`` pins the record
        to a specific :class:`~graphmine_tpu.obs.spans.Span` instead of
        the thread-current one — used for ``span`` records, which must
        carry their *own* identity, emitted after the span closed."""
        rec = {"phase": phase, "t": time.time()}
        tr = self.tracer
        if tr is not None:
            sp = _span if _span is not None else tr.current()
            rec["run_id"] = tr.run_id
            # The SPAN's trace id, not the tracer's: a span opened with
            # remote=/new_trace= (cross-process propagation, fleet
            # requests) carries an adopted/minted trace, and records
            # emitted inside it must land in THAT trace or the stitched
            # fleet timeline falls apart at every process boundary.
            rec["trace_id"] = sp.trace_id
            rec["span_id"] = sp.span_id
            rec["span_path"] = sp.path
            if _span is not None and sp.parent_id is not None:
                rec["parent_span_id"] = sp.parent_id
        rec.update(kv)
        line = json.dumps(rec, default=str)
        log.info("%s", line)
        with self._lock:
            self.records.append(rec)
            if self.stream_path is not None and self._stream_ok:
                try:
                    if self._stream is None:
                        self._stream = open(self.stream_path, "a")
                    self._stream.write(line + "\n")
                    self._stream.flush()
                    self._streamed += 1
                except OSError as e:
                    self._stream_ok = False
                    log.warning(
                        "metrics stream to %s failed: %r; records will be "
                        "written at exit instead", self.stream_path, e,
                    )
            if (
                self.max_records is not None
                and len(self.records) > self.max_records
            ):
                drop = len(self.records) - self.max_records
                # Dropped records with a global index past the streamed
                # prefix were never persisted anywhere — count them and
                # say so ONCE, or the 'written at exit instead' promise
                # emit makes when the stream dies becomes a silent lie
                # under the cap.
                lost = max(
                    0,
                    (self._dropped + drop)
                    - max(self._streamed, self._dropped),
                )
                del self.records[:drop]
                self._dropped += drop
                if lost:
                    self._lost += lost
                    if not self._lost_warned:
                        self._lost_warned = True
                        log.warning(
                            "max_records=%d dropped record(s) the stream "
                            "never persisted (running total tracked; "
                            "%d so far) — they will NOT appear in any "
                            "exit-time dump", self.max_records, self._lost,
                        )
        return rec

    @contextlib.contextmanager
    def timed(self, phase: str, **kv):
        """Timed phase record. When the body raises, the record keeps its
        failure identity — ``ok=false`` plus ``error`` (the classified
        kind from the resilience taxonomy) and ``error_detail`` — instead
        of being indistinguishable from a success; the exception always
        propagates."""
        t0 = time.perf_counter()
        try:
            yield
        except BaseException as e:
            from graphmine_tpu.pipeline.resilience import classify_error

            self.emit(
                phase, seconds=round(time.perf_counter() - t0, 4),
                ok=False, error=classify_error(e), error_detail=repr(e),
                **kv,
            )
            raise
        self.emit(phase, seconds=round(time.perf_counter() - t0, 4), **kv)

    @contextlib.contextmanager
    def span(self, name: str, emit: bool = True, annotate: bool = True,
             remote=None, new_trace: bool = False, **attrs):
        """Open a tracer span for the block (no-op yielding None without
        a tracer). ``emit``: write a ``span`` record at close (the phase
        waterfall's raw material) — superstep spans pass False so a long
        run is not doubled by per-superstep span records (``lpa_iter``
        already carries the superstep span's identity). ``annotate``:
        also enter a ``jax.profiler.TraceAnnotation`` named by the span
        path, so XLA profiler traces line up with the span tree.
        ``remote``/``new_trace`` pass through to
        :meth:`~graphmine_tpu.obs.spans.Tracer.span` — adopt a
        propagated :class:`~graphmine_tpu.obs.spans.TraceContext`, or
        mint a per-request trace (the fleet router's root span)."""
        if self.tracer is None:
            yield None
            return
        sp = None
        try:
            with self.tracer.span(
                name, remote=remote, new_trace=new_trace, **attrs
            ) as sp:
                if annotate:
                    with xla_annotation(sp.path):
                        yield sp
                else:
                    yield sp
        finally:
            if emit and sp is not None:
                self.emit(
                    "span", _span=sp, name=sp.name,
                    seconds=round(sp.seconds, 4), status=sp.status,
                    **sp.attrs,
                )

    def of_phase(self, phase: str) -> list:
        """All records for one phase name — recovery events (``retry``,
        ``degrade``, ``quarantine``, ``checkpoint_rollback``, ...) are
        phases like any other, so observability tooling and tests filter
        them the same way (span-tagged records filter identically: the
        trace keys ride alongside ``phase``, never replace it)."""
        return [r for r in self.records if r.get("phase") == phase]

    def write_jsonl(self, path: str) -> str:
        """Dump every record as JSON lines (full-file rewrite — the
        explicit export API; run-appending persistence is
        :meth:`finalize`)."""
        with open(path, "w") as f:
            for rec in self.records:
                f.write(json.dumps(rec, default=str) + "\n")
        return path

    def finalize(self, path: str) -> str:
        """End-of-run persistence: when the live stream wrote every
        record, just close it; otherwise (streaming off, or it failed
        mid-run, or a different target path) **append** the records the
        stream never persisted — never truncate, the file may hold prior
        runs' records (a resumed run reusing one ``--metrics-out``)."""
        if self._stream is not None:
            try:
                self._stream.close()
            except OSError:
                self._stream_ok = False
            self._stream = None
            if self._stream_ok and self.stream_path == path:
                return path
        # max_records drops shift list positions: the first
        # never-streamed record sits at streamed-minus-dropped (dropped
        # records were, by the emit-order invariant, streamed first).
        start = (
            max(0, self._streamed - self._dropped)
            if path == self.stream_path else 0
        )
        # A stream that died mid-write (ENOSPC, EIO) can leave a torn
        # final line; appending straight after it would merge the torn
        # prefix with the first record below into one unparseable line.
        needs_nl = False
        try:
            with open(path, "rb") as rf:
                rf.seek(-1, os.SEEK_END)
                needs_nl = rf.read(1) != b"\n"
        except (OSError, ValueError):
            pass  # missing or empty file: nothing to repair
        with open(path, "a") as f:
            if needs_nl:
                f.write("\n")
            for rec in self.records[start:]:
                f.write(json.dumps(rec, default=str) + "\n")
        return path

    def tripwire(self, kind: str, shard: int, iteration: int, **kv):
        """Structured record for an in-loop divergence-tripwire firing
        (docs/RESILIENCE.md): which guard, the offending shard index, the
        superstep it fired at — one fixed shape so offline triage can
        filter `of_phase("tripwire")` without per-caller key guessing."""
        return self.emit(
            "tripwire", kind=kind, shard=int(shard),
            iteration=int(iteration), **kv,
        )

    def lpa_iteration(self, it: int, changed: int, num_edges: int, seconds: float, chips: int):
        """Per-superstep record with the headline edges/sec/chip metric."""
        eps = num_edges / seconds if seconds > 0 else float("inf")
        return self.emit(
            "lpa_iter",
            iteration=it,
            labels_changed=changed,
            seconds=round(seconds, 5),
            edges_per_sec=round(eps),
            edges_per_sec_per_chip=round(eps / max(chips, 1)),
        )


def shard_sink(
    obs_dir: str,
    role: str,
    run_id: str | None = None,
    max_records: int | None = None,
) -> MetricsSink:
    """One process's slice of the federated metrics plane (ISSUE 11,
    docs/OBSERVABILITY.md "Fleet tracing"): a streaming sink whose JSONL
    lands at ``<obs_dir>/<role>-<pid>.jsonl``. Every fleet process
    (router, replicas, writer, standby, chaos driver) pointed at one
    ``--obs-dir`` leaves a shard there; ``tools/trace_stitch.py`` joins
    the directory into per-trace cross-process timelines — no log
    aggregator required, the filesystem is the collector."""
    from graphmine_tpu.obs.spans import Tracer

    os.makedirs(obs_dir, exist_ok=True)
    safe_role = "".join(
        c if c.isalnum() or c in "-_" else "-" for c in role
    ) or "proc"
    return MetricsSink(
        stream_path=os.path.join(
            obs_dir, f"{safe_role}-{os.getpid()}.jsonl"
        ),
        tracer=Tracer(run_id=run_id),
        max_records=max_records,
    )


@contextlib.contextmanager
def maybe_profile(profile_dir: str | None, sink: MetricsSink | None = None):
    """jax.profiler trace around a pipeline phase (SURVEY §5 tracing).

    Hardened (ISSUE 3 satellite): a failing ``start_trace`` runs the body
    unprofiled instead of aborting the run, and ``stop_trace`` failures
    are contained — a raise out of the ``finally`` would *mask the
    body's own error*, which is the one the operator needs. Either
    outcome is recorded as a ``profile_capture`` record carrying the
    trace dir, so offline reports can link the XLA trace (or its
    absence) to the run.
    """
    if not profile_dir:
        yield
        return
    import jax

    try:
        jax.profiler.start_trace(profile_dir)
    except Exception as e:
        log.warning("profiler start_trace(%s) failed: %r; running "
                    "unprofiled", profile_dir, e)
        if sink is not None:
            sink.emit("profile_capture", dir=profile_dir, ok=False,
                      error=repr(e))
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            log.warning("profiler stop_trace failed: %r (trace dir %s may "
                        "be incomplete)", e, profile_dir)
            if sink is not None:
                sink.emit("profile_capture", dir=profile_dir, ok=False,
                          error=repr(e))
        else:
            if sink is not None:
                sink.emit("profile_capture", dir=profile_dir, ok=True)
