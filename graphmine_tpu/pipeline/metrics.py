"""Structured metrics + profiling hooks.

The reference's only observability was ``print``/``show`` calls
(``Graphframes.py:18,32,54,68,74,82,85,120``). Here every pipeline phase
emits a structured JSON record, and LPA reports the driver's headline
metric — **edges/sec/chip** per iteration (BASELINE.json ``"metric"``).
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from dataclasses import dataclass, field

log = logging.getLogger("graphmine_tpu")


@dataclass
class MetricsSink:
    """Collects phase timings and counters; emits JSON lines via logging."""

    records: list = field(default_factory=list)

    def emit(self, phase: str, **kv) -> dict:
        rec = {"phase": phase, "t": time.time(), **kv}
        self.records.append(rec)
        log.info("%s", json.dumps(rec, default=str))
        return rec

    @contextlib.contextmanager
    def timed(self, phase: str, **kv):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.emit(phase, seconds=round(time.perf_counter() - t0, 4), **kv)

    def lpa_iteration(self, it: int, changed: int, num_edges: int, seconds: float, chips: int):
        """Per-superstep record with the headline edges/sec/chip metric."""
        eps = num_edges / seconds if seconds > 0 else float("inf")
        return self.emit(
            "lpa_iter",
            iteration=it,
            labels_changed=changed,
            seconds=round(seconds, 5),
            edges_per_sec=round(eps),
            edges_per_sec_per_chip=round(eps / max(chips, 1)),
        )


@contextlib.contextmanager
def maybe_profile(profile_dir: str | None):
    """jax.profiler trace around a pipeline phase (SURVEY §5 tracing)."""
    if not profile_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(profile_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
