"""Checkpoint / resume for long label-propagation runs.

The reference's closest artifact is ``persist()`` (``Graphframes.py:82``) —
in-memory caching only. Here the label state + iteration counter are saved
so billion-edge LPA runs can resume (SURVEY §5 checkpoint/resume). The
state is one int32 array + a counter; np.savez is the efficient, dependency-
free representation (orbax would add sharded async saves for multi-host —
noted as the upgrade path).
"""

from __future__ import annotations

import os

import numpy as np


def graph_fingerprint(src, dst, weights=None) -> str:
    """Content hash of the edge arrays — the id-assignment identity.

    Labels index vertices by the ids the loader assigned; any change to
    the data OR to id-assignment order (e.g. bulk vs ``batch_rows``
    streaming ingestion, which documents different id orders) changes
    this fingerprint, so a stale checkpoint cannot silently relabel a
    permuted graph. ``weights`` participate too: weighted and unweighted
    dynamics over the same topology follow different label trajectories,
    so their checkpoints must not be interchangeable.
    """
    import hashlib

    h = hashlib.sha1()
    h.update(np.ascontiguousarray(np.asarray(src, np.int32)).tobytes())
    h.update(np.ascontiguousarray(np.asarray(dst, np.int32)).tobytes())
    if weights is not None:
        h.update(b"w")
        h.update(np.ascontiguousarray(np.asarray(weights, np.float32)).tobytes())
    return h.hexdigest()


def save_labels(
    checkpoint_dir: str, labels, iteration: int, tag: str = "lpa",
    fingerprint: str | None = None,
) -> str:
    os.makedirs(checkpoint_dir, exist_ok=True)
    path = os.path.join(checkpoint_dir, f"{tag}_labels.npz")
    tmp = path + ".tmp.npz"  # .npz suffix keeps np.savez from renaming
    np.savez(
        tmp,
        labels=np.asarray(labels),
        iteration=np.int64(iteration),
        fingerprint=np.str_(fingerprint or ""),
    )
    os.replace(tmp, path)
    return path


def load_labels(checkpoint_dir: str, tag: str = "lpa", fingerprint: str | None = None):
    """Returns (labels, iteration) or None when no checkpoint exists.

    ``fingerprint``: when given and the checkpoint recorded one, the two
    must match — a mismatch means the checkpoint indexes a different
    graph or id assignment, and resuming would silently mislabel every
    vertex (raises ValueError instead).
    """
    path = os.path.join(checkpoint_dir, f"{tag}_labels.npz")
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        saved_fp = str(z["fingerprint"]) if "fingerprint" in z else ""
        if fingerprint and not saved_fp:
            import warnings

            warnings.warn(
                f"checkpoint at {path} predates graph fingerprinting; cannot "
                "verify it matches this graph/id assignment — resuming "
                "unchecked (re-save to upgrade)",
                stacklevel=2,
            )
        if fingerprint and saved_fp and fingerprint != saved_fp:
            raise ValueError(
                f"checkpoint at {path} was written for a different graph or "
                f"vertex-id assignment (fingerprint {saved_fp[:12]}... != "
                f"{fingerprint[:12]}...); delete the checkpoint or reload the "
                "data the way the original run did (e.g. same batch_rows)"
            )
        return z["labels"], int(z["iteration"])


def save_sharded(checkpoint_dir: str, labels, iteration: int, tag: str = "lpa") -> str:
    """Orbax save of (labels, iteration) — the multi-host path.

    Unlike :func:`save_labels` (single-host npz), orbax writes each shard
    from its owning host (async-capable, atomic via its own finalization
    protocol), so a DCN-spanning run checkpoints without gathering the
    label vector to one host. Same state contents as the npz path; the two
    are interchangeable for single-host runs.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.path.join(checkpoint_dir, f"{tag}_orbax"))
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(
            path,
            {"labels": labels, "iteration": np.int64(iteration)},
            force=True,
        )
    return path


def load_sharded(checkpoint_dir: str, tag: str = "lpa", sharding=None):
    """Restore an orbax checkpoint; returns (labels, iteration) or None.

    ``sharding``: optional ``jax.sharding.Sharding`` to restore the label
    array directly into (device-resident, correctly placed on the mesh —
    no host bounce). Defaults to host numpy.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.path.join(checkpoint_dir, f"{tag}_orbax"))
    if not os.path.exists(path):
        return None
    import jax

    with ocp.StandardCheckpointer() as ckptr:
        # StandardCheckpointer.metadata returns StepMetadata in newer
        # orbax (tree under .item_metadata) and the raw tree in older.
        meta = ckptr.metadata(path)
        meta = getattr(meta, "item_metadata", meta)
        if sharding is None:
            # Restore into a host-numpy skeleton built from the saved
            # metadata: orbax then validates the topology instead of
            # warning that targetless restores are unsafe.
            target = jax.tree.map(
                lambda m: np.zeros(m.shape, m.dtype), dict(meta)
            )
        else:
            lbl = meta["labels"]
            target = {
                "labels": jax.ShapeDtypeStruct(
                    lbl.shape, lbl.dtype, sharding=sharding
                ),
                "iteration": 0,
            }
        state = ckptr.restore(path, target)
    return state["labels"], int(state["iteration"])
