"""Checkpoint / resume for long label-propagation runs.

The reference's closest artifact is ``persist()`` (``Graphframes.py:82``) —
in-memory caching only. Here the label state + iteration counter are saved
so billion-edge LPA runs can resume (SURVEY §5 checkpoint/resume). Two
formats, both dependency-free:

- ``save_labels`` / ``load_labels``: one atomic npz (single-device runs);
- ``save_sharded`` / ``load_sharded``: a manifest of per-shard files with
  per-shard sha256 (distributed runs) — Pregel-style confined-recovery
  checkpointing (Malewicz et al. SIGMOD'10), able to RE-SHARD ON RESTORE
  so a checkpoint taken on D devices resumes on D' != D after a chip loss.
"""

from __future__ import annotations

import hashlib
import json
import os
import zipfile
import zlib

import numpy as np


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed its integrity check (zip CRC or state checksum)
    and no good fallback existed. The message names every file tried."""


class FingerprintMismatch(ValueError):
    """The checkpoint indexes a different graph / id assignment. Distinct
    from corruption on purpose: rolling back to a previous checkpoint of
    the SAME wrong graph would not help, so this always propagates."""


def _state_checksum(labels: np.ndarray, iteration: int, fingerprint: str) -> str:
    """Content hash of the full checkpoint state — written at save time,
    re-derived at load time. Catches silent bit damage that slips past the
    zip-member CRC (e.g. a rewritten-in-place but internally consistent
    member) and any tearing between the arrays."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(labels).tobytes())
    h.update(str(labels.dtype).encode())
    h.update(str(labels.shape).encode())
    h.update(str(int(iteration)).encode())
    h.update((fingerprint or "").encode())
    return h.hexdigest()


def _prev_path(path: str) -> str:
    return path[: -len(".npz")] + ".prev.npz"


def graph_fingerprint(src, dst, weights=None) -> str:
    """Content hash of the edge arrays — the id-assignment identity.

    Labels index vertices by the ids the loader assigned; any change to
    the data OR to id-assignment order (e.g. bulk vs ``batch_rows``
    streaming ingestion, which documents different id orders) changes
    this fingerprint, so a stale checkpoint cannot silently relabel a
    permuted graph. ``weights`` participate too: weighted and unweighted
    dynamics over the same topology follow different label trajectories,
    so their checkpoints must not be interchangeable.
    """
    import hashlib

    h = hashlib.sha1()
    h.update(np.ascontiguousarray(np.asarray(src, np.int32)).tobytes())
    h.update(np.ascontiguousarray(np.asarray(dst, np.int32)).tobytes())
    if weights is not None:
        h.update(b"w")
        h.update(np.ascontiguousarray(np.asarray(weights, np.float32)).tobytes())
    return h.hexdigest()


def _emit_save(sink, path: str, iteration: int, fmt: str, shards: int) -> None:
    """``checkpoint_save`` record: every durable save joins the run's
    causal timeline (span-stamped by the sink), so offline triage can see
    exactly which generation a later rollback/resume landed on."""
    if sink is not None:
        sink.emit(
            "checkpoint_save", path=path, iteration=int(iteration),
            format=fmt, shards=int(shards), bytes=_tree_bytes(path),
        )


def _tree_bytes(path: str) -> int:
    if os.path.isdir(path):
        return sum(
            os.path.getsize(os.path.join(path, f)) for f in os.listdir(path)
        )
    return os.path.getsize(path)


def save_labels(
    checkpoint_dir: str, labels, iteration: int, tag: str = "lpa",
    fingerprint: str | None = None, sink=None,
) -> str:
    """Durably save (labels, iteration) — torn-write-proof.

    Write protocol: tmp file → fsync → rotate the current checkpoint to
    ``*.prev.npz`` → rename tmp into place → fsync the directory. A kill at
    any point leaves either the old checkpoint or the new one fully intact,
    never a truncated ``.npz``; the rotation keeps the last good state
    available for :func:`load_labels`'s corruption rollback. The embedded
    ``checksum`` covers labels + iteration + fingerprint. ``sink``: emits
    a ``checkpoint_save`` record per save.
    """
    os.makedirs(checkpoint_dir, exist_ok=True)
    path = os.path.join(checkpoint_dir, f"{tag}_labels.npz")
    tmp = path + ".tmp.npz"  # .npz suffix keeps np.savez from renaming
    labels_np = np.asarray(labels)
    np.savez(
        tmp,
        labels=labels_np,
        iteration=np.int64(iteration),
        fingerprint=np.str_(fingerprint or ""),
        checksum=np.str_(
            _state_checksum(labels_np, iteration, fingerprint or "")
        ),
    )
    with open(tmp, "rb+") as f:
        os.fsync(f.fileno())
    if os.path.exists(path):
        os.replace(path, _prev_path(path))
    os.replace(tmp, path)
    dirfd = os.open(checkpoint_dir, os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)
    _emit_save(sink, path, iteration, "npz", 1)
    return path


# Everything np.load / zipfile can throw at damaged bytes: truncation
# (BadZipFile/EOFError), bit flips in a member (BadZipFile "Bad CRC-32",
# zlib.error), header damage (ValueError/KeyError/OSError from the npy
# parser), plus our own checksum verdict.
_CORRUPTION_ERRORS = (
    zipfile.BadZipFile, zlib.error, EOFError, KeyError, OSError,
    ValueError, CheckpointCorruptionError,
)


def _read_verified(path: str, fingerprint: str | None):
    """Load one checkpoint file, verifying integrity then identity.

    Raises a :data:`_CORRUPTION_ERRORS` member on damaged bytes (the
    caller may roll back) or :class:`FingerprintMismatch` on a
    wrong-graph checkpoint (the caller must NOT roll back — every
    generation of this checkpoint indexes the same wrong graph).
    """
    with np.load(path) as z:
        labels = z["labels"]
        iteration = int(z["iteration"])
        saved_fp = str(z["fingerprint"]) if "fingerprint" in z else ""
        if "checksum" in z:
            want = str(z["checksum"])
            got = _state_checksum(labels, iteration, saved_fp)
            if want != got:
                raise CheckpointCorruptionError(
                    f"checkpoint at {path} failed its state checksum "
                    f"({got[:12]}... != recorded {want[:12]}...)"
                )
        if fingerprint and not saved_fp:
            import warnings

            warnings.warn(
                f"checkpoint at {path} predates graph fingerprinting; cannot "
                "verify it matches this graph/id assignment — resuming "
                "unchecked (re-save to upgrade)",
                stacklevel=3,
            )
        if fingerprint and saved_fp and fingerprint != saved_fp:
            raise FingerprintMismatch(
                f"checkpoint at {path} was written for a different graph or "
                f"vertex-id assignment (fingerprint {saved_fp[:12]}... != "
                f"{fingerprint[:12]}...); delete the checkpoint or reload the "
                "data the way the original run did (e.g. same batch_rows)"
            )
        return labels, iteration


def _read_verified_confirmed(path: str, fingerprint: str | None):
    """:func:`_read_verified` with one confirming re-read before a
    corruption verdict. ``OSError`` sits in :data:`_CORRUPTION_ERRORS`
    (damaged headers surface as it), but it is also how transient I/O
    weather (flaky NFS, EIO) presents — and condemning the NEWEST healthy
    checkpoint on one unlucky read would silently resume from older
    state. Real corruption is deterministic across reads; transient
    weather is not, so a second read disambiguates cheaply."""
    try:
        return _read_verified(path, fingerprint)
    except FingerprintMismatch:
        raise
    except _CORRUPTION_ERRORS as first:
        try:
            return _read_verified(path, fingerprint)
        except FingerprintMismatch:
            raise
        except _CORRUPTION_ERRORS:
            raise first


def _load_with_rollback(path, prev, read_confirmed, sink, what, delete_hint):
    """The generation-rollback state machine shared by BOTH formats
    (npz files and sharded manifest directories — ``os.path.exists`` /
    ``os.replace`` cover either): verify current; on corruption roll
    back to ``prev``, promote it to the current slot so the next save's
    rotation cannot demote the corrupt generation into the prev slot,
    and set the condemned generation aside at a ``.corrupt`` name no
    later incident overwrites (even after the confirming re-read, a
    condemned NEWER checkpoint is evidence the operator may still want).
    ``checkpoint_rollback`` is emitted only once a previous generation
    exists to roll back TO — an unrecoverable corruption must not read
    as a rollback in the metrics stream. FingerprintMismatch propagates
    untouched (rolling back cannot fix a wrong-graph checkpoint)."""
    if not os.path.exists(path) and not os.path.exists(prev):
        return None
    try:
        if not os.path.exists(path):
            raise CheckpointCorruptionError(
                f"{what} at {path} is missing (previous generation "
                f"exists at {prev})"
            )
        return read_confirmed(path)
    except FingerprintMismatch:
        raise
    except _CORRUPTION_ERRORS as e:
        primary_error = e
    if not os.path.exists(prev):
        raise CheckpointCorruptionError(
            f"{what} at {path} is corrupt ({primary_error!r}) and no "
            f"previous generation exists; {delete_hint}"
        ) from primary_error
    if sink is not None:
        sink.emit(
            "checkpoint_rollback", path=path, error=repr(primary_error),
        )
    try:
        labels, iteration = read_confirmed(prev)
    except FingerprintMismatch:
        raise
    except _CORRUPTION_ERRORS as e2:
        raise CheckpointCorruptionError(
            f"both {what} generations are corrupt: {path} "
            f"({primary_error!r}) and {prev} ({e2!r}); {delete_hint}"
        ) from e2
    if os.path.exists(path):
        condemned = path + ".corrupt"
        n = 1
        while os.path.exists(condemned):
            condemned = f"{path}.corrupt.{n}"
            n += 1
        os.replace(path, condemned)
    os.replace(prev, path)
    if sink is not None:
        sink.emit(
            "checkpoint_rollback_ok", path=path, iteration=iteration,
        )
    return labels, iteration


def load_labels(
    checkpoint_dir: str, tag: str = "lpa", fingerprint: str | None = None,
    sink=None,
):
    """Returns (labels, iteration) or None when no checkpoint exists.

    Integrity: every load re-verifies the zip CRCs and the embedded state
    checksum. A corrupt current checkpoint automatically **rolls back** to
    the rotated ``*.prev.npz`` (the last good save), promoting it back to
    the current slot; the condemned file is preserved at ``*.npz.corrupt``
    for forensics (the verdict may stem from a transient read error on
    healthy bytes). When both generations are damaged,
    :class:`CheckpointCorruptionError` names every file tried. Rollbacks
    are emitted as ``checkpoint_rollback`` records through ``sink`` (a
    :class:`~graphmine_tpu.pipeline.metrics.MetricsSink`) when given.

    ``fingerprint``: when given and the checkpoint recorded one, the two
    must match — a mismatch means the checkpoint indexes a different
    graph or id assignment, and resuming would silently mislabel every
    vertex (raises :class:`FingerprintMismatch` instead).
    """
    path = os.path.join(checkpoint_dir, f"{tag}_labels.npz")
    return _load_with_rollback(
        path, _prev_path(path),
        lambda p: _read_verified_confirmed(p, fingerprint),
        sink, "checkpoint",
        f"delete {checkpoint_dir!r} to restart from scratch",
    )


# ---- shard-aware manifest checkpoints -------------------------------------
# The distributed twin of save_labels/load_labels (ISSUE 2): per-shard .npy
# files written atomic+fsync, a JSON manifest carrying the graph
# fingerprint, mesh shape, iteration and per-shard sha256, two rotated
# generations with the same rollback/forensic-preserve semantics as the
# npz path — and RE-SHARD ON RESTORE: the loader returns the full label
# vector, so a checkpoint taken on D devices resumes on D' != D (the
# elastic path after losing a chip; the caller re-partitions the graph
# onto the surviving mesh and passes the labels as init_labels). Per-shard
# files are also the multi-host upgrade path: each host can write only the
# shards it owns (orbax-style) without gathering the vector to one host.

MANIFEST_NAME = "manifest.json"
_MANIFEST_VERSION = 1


def sharded_dir(checkpoint_dir: str, tag: str = "lpa") -> str:
    """Current-generation directory of a sharded manifest checkpoint."""
    return os.path.join(checkpoint_dir, f"{tag}_sharded")


def shard_file(gen_dir: str, shard: int) -> str:
    return os.path.join(gen_dir, f"shard_{shard:05d}.npy")


def _sharded_prev_dir(gen_dir: str) -> str:
    return gen_dir + ".prev"


def _fsync_file(path: str) -> None:
    with open(path, "rb+") as f:
        os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    dirfd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _manifest_checksum(body: dict) -> str:
    """Content hash of the manifest payload (everything but the checksum
    field itself) — a bit flip that still parses as JSON must not pass."""
    canon = json.dumps(
        {k: v for k, v in sorted(body.items()) if k != "checksum"},
        sort_keys=True,
    )
    return hashlib.sha256(canon.encode()).hexdigest()


def save_sharded(
    checkpoint_dir: str,
    labels,
    iteration: int,
    tag: str = "lpa",
    fingerprint: str | None = None,
    num_shards: int | None = None,
    sink=None,
) -> str:
    """Durably save (labels, iteration) as a manifest of per-shard files.

    ``num_shards``: how many shard files to split the label vector into —
    pass the mesh size so each file is one device's chunk (defaults to the
    label array's sharding when it is a committed jax array on a mesh,
    else 1). Write protocol: every shard + the manifest land in a tmp
    generation directory (each file fsync'd, manifest last), the previous
    generation rotates to ``*.prev``, and one directory rename publishes
    the new generation — a kill at any point leaves the old or the new
    generation fully intact, never a torn mix. ``sink``: emits a
    ``checkpoint_save`` record per save. Returns the generation dir.
    """
    labels_np = np.asarray(labels)
    if num_shards is None:
        num_shards = max(
            len(getattr(labels, "sharding", None).device_set)
            if getattr(labels, "sharding", None) is not None else 1,
            1,
        )
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    os.makedirs(checkpoint_dir, exist_ok=True)
    gen = sharded_dir(checkpoint_dir, tag)
    tmp = f"{gen}.tmp.{os.getpid()}"
    # Sweep EVERY stale tmp generation, not just this pid's: the crash-
    # resume loop this format exists for leaves <gen>.tmp.<oldpid> behind
    # on each SIGKILL mid-save, and restarted processes never reuse the
    # old pid — without the sweep, preemptions leak one full label-vector
    # copy per kill. One driver per checkpoint_dir is already the
    # concurrency contract (the generation rotation assumes it).
    import glob as _glob
    import shutil

    for stale in _glob.glob(gen + ".tmp.*"):
        shutil.rmtree(stale, ignore_errors=True)
    os.makedirs(tmp)

    # Even chunking (last shard takes the remainder); boundaries are
    # recorded in the manifest, so the loader never re-derives them.
    v = len(labels_np)
    chunk = -(-v // num_shards) if v else 0
    sizes, shas = [], []
    for s in range(num_shards):
        part = labels_np[s * chunk: (s + 1) * chunk]
        path = shard_file(tmp, s)
        np.save(path, part)
        _fsync_file(path)
        sizes.append(int(len(part)))
        shas.append(_file_sha256(path))

    body = {
        "version": _MANIFEST_VERSION,
        "tag": tag,
        "iteration": int(iteration),
        "fingerprint": fingerprint or "",
        "num_shards": int(num_shards),
        "mesh_shape": [int(num_shards)],
        "num_vertices": int(v),
        "dtype": str(labels_np.dtype),
        "shard_sizes": sizes,
        "shard_sha256": shas,
    }
    body["checksum"] = _manifest_checksum(body)
    man_tmp = os.path.join(tmp, MANIFEST_NAME + ".tmp")
    with open(man_tmp, "w") as f:
        json.dump(body, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(man_tmp, os.path.join(tmp, MANIFEST_NAME))
    _fsync_dir(tmp)

    # Publish: rotate current -> .prev, tmp -> current. Directory renames
    # need a clear target, so a stale .prev is removed first — it is two
    # generations old by now, strictly older than what replaces it.
    prev = _sharded_prev_dir(gen)
    if os.path.exists(gen):
        if os.path.exists(prev):
            shutil.rmtree(prev)
        os.replace(gen, prev)
    os.replace(tmp, gen)
    _fsync_dir(checkpoint_dir)
    _emit_save(sink, gen, iteration, "sharded", num_shards)
    return gen


def _read_sharded_verified(gen_dir: str, fingerprint: str | None):
    """Load one sharded generation, verifying manifest checksum, every
    shard's sha256 and the assembled length, then the graph fingerprint.
    Raises a :data:`_CORRUPTION_ERRORS` member on damaged bytes,
    :class:`FingerprintMismatch` on a wrong-graph checkpoint."""
    man_path = os.path.join(gen_dir, MANIFEST_NAME)
    try:
        with open(man_path) as f:
            body = json.load(f)
    except json.JSONDecodeError as e:
        raise CheckpointCorruptionError(
            f"manifest at {man_path} is not valid JSON ({e})"
        ) from e
    want = body.get("checksum", "")
    got = _manifest_checksum(body)
    if want != got:
        raise CheckpointCorruptionError(
            f"manifest at {man_path} failed its checksum "
            f"({got[:12]}... != recorded {want[:12]}...)"
        )
    saved_fp = body.get("fingerprint", "")
    if fingerprint and saved_fp and fingerprint != saved_fp:
        raise FingerprintMismatch(
            f"sharded checkpoint at {gen_dir} was written for a different "
            f"graph or vertex-id assignment (fingerprint {saved_fp[:12]}... "
            f"!= {fingerprint[:12]}...); delete the checkpoint or reload "
            "the data the way the original run did"
        )
    parts = []
    for s in range(body["num_shards"]):
        path = shard_file(gen_dir, s)
        sha = _file_sha256(path)
        if sha != body["shard_sha256"][s]:
            raise CheckpointCorruptionError(
                f"shard {s} at {path} failed its sha256 ({sha[:12]}... != "
                f"manifest {body['shard_sha256'][s][:12]}...)"
            )
        part = np.load(path)
        if len(part) != body["shard_sizes"][s]:
            raise CheckpointCorruptionError(
                f"shard {s} at {path} holds {len(part)} rows, manifest "
                f"says {body['shard_sizes'][s]}"
            )
        parts.append(part)
    labels = (
        np.concatenate(parts) if parts
        else np.empty(0, np.dtype(body["dtype"]))
    )
    if len(labels) != body["num_vertices"]:
        raise CheckpointCorruptionError(
            f"sharded checkpoint at {gen_dir} assembles to {len(labels)} "
            f"vertices, manifest says {body['num_vertices']}"
        )
    return labels.astype(np.dtype(body["dtype"]), copy=False), int(
        body["iteration"]
    )


def _read_sharded_confirmed(gen_dir: str, fingerprint: str | None):
    """One confirming re-read before a corruption verdict — same
    transient-I/O-weather rationale as :func:`_read_verified_confirmed`."""
    try:
        return _read_sharded_verified(gen_dir, fingerprint)
    except FingerprintMismatch:
        raise
    except _CORRUPTION_ERRORS as first:
        try:
            return _read_sharded_verified(gen_dir, fingerprint)
        except FingerprintMismatch:
            raise
        except _CORRUPTION_ERRORS:
            raise first


def load_sharded(
    checkpoint_dir: str, tag: str = "lpa", sharding=None,
    fingerprint: str | None = None, sink=None,
):
    """Restore a sharded manifest checkpoint; returns (labels, iteration)
    or None when no generation exists.

    Every shard's sha256 and the manifest checksum are re-verified. A
    corrupt current generation **rolls back** to the rotated ``*.prev``
    generation (promoted back to the current slot; the condemned
    generation directory is preserved at ``*.corrupt`` for forensics),
    emitting ``checkpoint_rollback`` / ``checkpoint_rollback_ok`` records
    through ``sink``. A wrong ``fingerprint`` raises
    :class:`FingerprintMismatch` WITHOUT rollback — every generation of
    that checkpoint indexes the same wrong graph.

    The returned labels are the full ``[V]`` vector regardless of how many
    shards wrote it — restore is shard-count agnostic, so a checkpoint
    taken on D devices resumes on D' != D (re-shard on restore).
    ``sharding``: optional ``jax.sharding.Sharding`` to place the restored
    labels onto directly.
    """
    gen = sharded_dir(checkpoint_dir, tag)
    if not os.path.exists(gen) and not os.path.exists(_sharded_prev_dir(gen)):
        # A checkpoint from the REMOVED orbax format must fail loudly,
        # not read as "no checkpoint": silently restarting a multi-day
        # run from iteration 0 across the upgrade would discard every
        # superstep. (load_newest holds this error while it tries the
        # npz format, so a dir that also has a valid npz still resumes.)
        legacy = os.path.join(checkpoint_dir, f"{tag}_orbax")
        if os.path.isdir(legacy):
            raise CheckpointCorruptionError(
                f"checkpoint at {legacy} uses the removed orbax format; "
                "this release reads the sharded-manifest and npz formats "
                "only. Finish the run with the previous release, or "
                "convert: restore the orbax state with orbax.checkpoint."
                "StandardCheckpointer().restore(...) and re-save it via "
                "checkpoint.save_sharded(...)"
            )
        return None
    out = _load_with_rollback(
        gen, _sharded_prev_dir(gen),
        lambda p: _read_sharded_confirmed(p, fingerprint),
        sink, "sharded checkpoint",
        f"delete {gen!r} (and its .prev) to restart from scratch",
    )
    if out is None:
        return None
    labels, iteration = out
    return _place(labels, sharding), iteration


def _place(labels: np.ndarray, sharding):
    if sharding is None:
        return labels
    import jax

    return jax.device_put(labels, sharding)


def _peek_sharded_iteration(checkpoint_dir: str, tag: str):
    """Cheap current-generation iteration read (manifest JSON only, no
    shard hashing); None = unreadable/absent (the full loader may still
    recover via rollback)."""
    try:
        with open(os.path.join(sharded_dir(checkpoint_dir, tag), MANIFEST_NAME)) as f:
            return int(json.load(f)["iteration"])
    except Exception:
        return None


def _peek_npz_iteration(checkpoint_dir: str, tag: str):
    """Cheap current-generation iteration read (one npz member, no label
    decompression or checksum); None = unreadable/absent."""
    try:
        with np.load(os.path.join(checkpoint_dir, f"{tag}_labels.npz")) as z:
            return int(z["iteration"])
    except Exception:
        return None


def load_newest(
    checkpoint_dir: str, tag: str = "lpa", fingerprint: str | None = None,
    sink=None,
):
    """Newest recoverable (labels, iteration) across BOTH checkpoint
    formats — the sharded manifest (distributed saves) and the npz
    (single-device saves); a run that walked the elastic ladder down to
    one device leaves both in the directory, and the higher iteration
    wins. The one owner of this rule (the driver's --resume and the
    resume-check tool both call it).

    The loser is not fully loaded: iterations are peeked first (manifest
    JSON / one npz member), and a format provably no newer than what
    already loaded is skipped — at north-star scale each full load
    re-hashes the whole label vector, and paying that twice per resume
    just to compare two counters would double resume I/O. A format whose
    peek is unreadable is still tried (its rollback may recover), and a
    loaded result BELOW its own peek (a rollback happened) re-opens the
    comparison.

    One format being corrupt beyond its own rollback must not veto the
    other: per-format :class:`CheckpointCorruptionError` is held while
    the other format is tried, and only re-raised when NOTHING loads.
    :class:`FingerprintMismatch` always propagates — every format of
    that checkpoint indexes the same wrong graph. Returns None when no
    checkpoint exists in either format.
    """
    entries = [
        (_peek_sharded_iteration(checkpoint_dir, tag), load_sharded),
        (_peek_npz_iteration(checkpoint_dir, tag), load_labels),
    ]
    # Most-promising first; unknown peeks last (tried, not trusted).
    entries.sort(
        key=lambda t: float("-inf") if t[0] is None else t[0], reverse=True
    )
    found, errors = [], []
    for peek, loader in entries:
        if found and peek is not None and peek <= found[-1][1]:
            break  # provably not newer than what already loaded
        try:
            out = loader(
                checkpoint_dir, tag=tag, fingerprint=fingerprint, sink=sink
            )
        except FingerprintMismatch:
            raise
        except CheckpointCorruptionError as e:
            errors.append(e)
            continue
        if out is not None:
            found.append(out)
    if found:
        return max(found, key=lambda t: t[1])
    if errors:
        raise errors[0]
    return None
