"""Checkpoint / resume for long label-propagation runs.

The reference's closest artifact is ``persist()`` (``Graphframes.py:82``) —
in-memory caching only. Here the label state + iteration counter are saved
so billion-edge LPA runs can resume (SURVEY §5 checkpoint/resume). The
state is one int32 array + a counter; np.savez is the efficient, dependency-
free representation (orbax would add sharded async saves for multi-host —
noted as the upgrade path).
"""

from __future__ import annotations

import os

import numpy as np


def save_labels(checkpoint_dir: str, labels, iteration: int, tag: str = "lpa") -> str:
    os.makedirs(checkpoint_dir, exist_ok=True)
    path = os.path.join(checkpoint_dir, f"{tag}_labels.npz")
    tmp = path + ".tmp.npz"  # .npz suffix keeps np.savez from renaming
    np.savez(tmp, labels=np.asarray(labels), iteration=np.int64(iteration))
    os.replace(tmp, path)
    return path


def load_labels(checkpoint_dir: str, tag: str = "lpa"):
    """Returns (labels, iteration) or None when no checkpoint exists."""
    path = os.path.join(checkpoint_dir, f"{tag}_labels.npz")
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        return z["labels"], int(z["iteration"])
