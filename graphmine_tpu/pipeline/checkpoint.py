"""Checkpoint / resume for long label-propagation runs.

The reference's closest artifact is ``persist()`` (``Graphframes.py:82``) —
in-memory caching only. Here the label state + iteration counter are saved
so billion-edge LPA runs can resume (SURVEY §5 checkpoint/resume). The
state is one int32 array + a counter; np.savez is the efficient, dependency-
free representation (orbax would add sharded async saves for multi-host —
noted as the upgrade path).
"""

from __future__ import annotations

import hashlib
import os
import zipfile
import zlib

import numpy as np


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed its integrity check (zip CRC or state checksum)
    and no good fallback existed. The message names every file tried."""


class FingerprintMismatch(ValueError):
    """The checkpoint indexes a different graph / id assignment. Distinct
    from corruption on purpose: rolling back to a previous checkpoint of
    the SAME wrong graph would not help, so this always propagates."""


def _state_checksum(labels: np.ndarray, iteration: int, fingerprint: str) -> str:
    """Content hash of the full checkpoint state — written at save time,
    re-derived at load time. Catches silent bit damage that slips past the
    zip-member CRC (e.g. a rewritten-in-place but internally consistent
    member) and any tearing between the arrays."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(labels).tobytes())
    h.update(str(labels.dtype).encode())
    h.update(str(labels.shape).encode())
    h.update(str(int(iteration)).encode())
    h.update((fingerprint or "").encode())
    return h.hexdigest()


def _prev_path(path: str) -> str:
    return path[: -len(".npz")] + ".prev.npz"


def graph_fingerprint(src, dst, weights=None) -> str:
    """Content hash of the edge arrays — the id-assignment identity.

    Labels index vertices by the ids the loader assigned; any change to
    the data OR to id-assignment order (e.g. bulk vs ``batch_rows``
    streaming ingestion, which documents different id orders) changes
    this fingerprint, so a stale checkpoint cannot silently relabel a
    permuted graph. ``weights`` participate too: weighted and unweighted
    dynamics over the same topology follow different label trajectories,
    so their checkpoints must not be interchangeable.
    """
    import hashlib

    h = hashlib.sha1()
    h.update(np.ascontiguousarray(np.asarray(src, np.int32)).tobytes())
    h.update(np.ascontiguousarray(np.asarray(dst, np.int32)).tobytes())
    if weights is not None:
        h.update(b"w")
        h.update(np.ascontiguousarray(np.asarray(weights, np.float32)).tobytes())
    return h.hexdigest()


def save_labels(
    checkpoint_dir: str, labels, iteration: int, tag: str = "lpa",
    fingerprint: str | None = None,
) -> str:
    """Durably save (labels, iteration) — torn-write-proof.

    Write protocol: tmp file → fsync → rotate the current checkpoint to
    ``*.prev.npz`` → rename tmp into place → fsync the directory. A kill at
    any point leaves either the old checkpoint or the new one fully intact,
    never a truncated ``.npz``; the rotation keeps the last good state
    available for :func:`load_labels`'s corruption rollback. The embedded
    ``checksum`` covers labels + iteration + fingerprint.
    """
    os.makedirs(checkpoint_dir, exist_ok=True)
    path = os.path.join(checkpoint_dir, f"{tag}_labels.npz")
    tmp = path + ".tmp.npz"  # .npz suffix keeps np.savez from renaming
    labels_np = np.asarray(labels)
    np.savez(
        tmp,
        labels=labels_np,
        iteration=np.int64(iteration),
        fingerprint=np.str_(fingerprint or ""),
        checksum=np.str_(
            _state_checksum(labels_np, iteration, fingerprint or "")
        ),
    )
    with open(tmp, "rb+") as f:
        os.fsync(f.fileno())
    if os.path.exists(path):
        os.replace(path, _prev_path(path))
    os.replace(tmp, path)
    dirfd = os.open(checkpoint_dir, os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)
    return path


# Everything np.load / zipfile can throw at damaged bytes: truncation
# (BadZipFile/EOFError), bit flips in a member (BadZipFile "Bad CRC-32",
# zlib.error), header damage (ValueError/KeyError/OSError from the npy
# parser), plus our own checksum verdict.
_CORRUPTION_ERRORS = (
    zipfile.BadZipFile, zlib.error, EOFError, KeyError, OSError,
    ValueError, CheckpointCorruptionError,
)


def _read_verified(path: str, fingerprint: str | None):
    """Load one checkpoint file, verifying integrity then identity.

    Raises a :data:`_CORRUPTION_ERRORS` member on damaged bytes (the
    caller may roll back) or :class:`FingerprintMismatch` on a
    wrong-graph checkpoint (the caller must NOT roll back — every
    generation of this checkpoint indexes the same wrong graph).
    """
    with np.load(path) as z:
        labels = z["labels"]
        iteration = int(z["iteration"])
        saved_fp = str(z["fingerprint"]) if "fingerprint" in z else ""
        if "checksum" in z:
            want = str(z["checksum"])
            got = _state_checksum(labels, iteration, saved_fp)
            if want != got:
                raise CheckpointCorruptionError(
                    f"checkpoint at {path} failed its state checksum "
                    f"({got[:12]}... != recorded {want[:12]}...)"
                )
        if fingerprint and not saved_fp:
            import warnings

            warnings.warn(
                f"checkpoint at {path} predates graph fingerprinting; cannot "
                "verify it matches this graph/id assignment — resuming "
                "unchecked (re-save to upgrade)",
                stacklevel=3,
            )
        if fingerprint and saved_fp and fingerprint != saved_fp:
            raise FingerprintMismatch(
                f"checkpoint at {path} was written for a different graph or "
                f"vertex-id assignment (fingerprint {saved_fp[:12]}... != "
                f"{fingerprint[:12]}...); delete the checkpoint or reload the "
                "data the way the original run did (e.g. same batch_rows)"
            )
        return labels, iteration


def _read_verified_confirmed(path: str, fingerprint: str | None):
    """:func:`_read_verified` with one confirming re-read before a
    corruption verdict. ``OSError`` sits in :data:`_CORRUPTION_ERRORS`
    (damaged headers surface as it), but it is also how transient I/O
    weather (flaky NFS, EIO) presents — and condemning the NEWEST healthy
    checkpoint on one unlucky read would silently resume from older
    state. Real corruption is deterministic across reads; transient
    weather is not, so a second read disambiguates cheaply."""
    try:
        return _read_verified(path, fingerprint)
    except FingerprintMismatch:
        raise
    except _CORRUPTION_ERRORS as first:
        try:
            return _read_verified(path, fingerprint)
        except FingerprintMismatch:
            raise
        except _CORRUPTION_ERRORS:
            raise first


def load_labels(
    checkpoint_dir: str, tag: str = "lpa", fingerprint: str | None = None,
    sink=None,
):
    """Returns (labels, iteration) or None when no checkpoint exists.

    Integrity: every load re-verifies the zip CRCs and the embedded state
    checksum. A corrupt current checkpoint automatically **rolls back** to
    the rotated ``*.prev.npz`` (the last good save), promoting it back to
    the current slot; the condemned file is preserved at ``*.npz.corrupt``
    for forensics (the verdict may stem from a transient read error on
    healthy bytes). When both generations are damaged,
    :class:`CheckpointCorruptionError` names every file tried. Rollbacks
    are emitted as ``checkpoint_rollback`` records through ``sink`` (a
    :class:`~graphmine_tpu.pipeline.metrics.MetricsSink`) when given.

    ``fingerprint``: when given and the checkpoint recorded one, the two
    must match — a mismatch means the checkpoint indexes a different
    graph or id assignment, and resuming would silently mislabel every
    vertex (raises :class:`FingerprintMismatch` instead).
    """
    path = os.path.join(checkpoint_dir, f"{tag}_labels.npz")
    prev = _prev_path(path)
    if not os.path.exists(path) and not os.path.exists(prev):
        return None
    try:
        if not os.path.exists(path):
            raise CheckpointCorruptionError(
                f"checkpoint at {path} is missing (previous generation "
                f"exists at {prev})"
            )
        return _read_verified_confirmed(path, fingerprint)
    except FingerprintMismatch:
        raise
    except _CORRUPTION_ERRORS as e:
        primary_error = e
    if not os.path.exists(prev):
        raise CheckpointCorruptionError(
            f"checkpoint at {path} is corrupt ({primary_error!r}) and no "
            f"previous generation exists; delete {checkpoint_dir!r} to "
            "restart from scratch"
        ) from primary_error
    # Emitted only once a previous generation exists to roll back TO —
    # an unrecoverable corruption must not read as a rollback in the
    # metrics stream (checkpoint_rollback_ok still marks success).
    if sink is not None:
        sink.emit(
            "checkpoint_rollback", path=path, error=repr(primary_error),
        )
    try:
        labels, iteration = _read_verified_confirmed(prev, fingerprint)
    except FingerprintMismatch:
        raise
    except _CORRUPTION_ERRORS as e2:
        raise CheckpointCorruptionError(
            f"both checkpoint generations are corrupt: {path} "
            f"({primary_error!r}) and {prev} ({e2!r}); delete "
            f"{checkpoint_dir!r} to restart from scratch"
        ) from e2
    # Promote the good generation back to the current slot so the next
    # save's rotation cannot demote the corrupt file into the prev slot.
    # The suspect file is set aside, NOT destroyed — and at a name no
    # later incident overwrites: even after the confirming re-read
    # (_read_verified_confirmed), a condemned NEWER checkpoint is
    # evidence the operator may still want.
    if os.path.exists(path):
        condemned = path + ".corrupt"
        n = 1
        while os.path.exists(condemned):
            condemned = f"{path}.corrupt.{n}"
            n += 1
        os.replace(path, condemned)
    os.replace(prev, path)
    if sink is not None:
        sink.emit(
            "checkpoint_rollback_ok", path=path, iteration=iteration,
        )
    return labels, iteration


def save_sharded(checkpoint_dir: str, labels, iteration: int, tag: str = "lpa") -> str:
    """Orbax save of (labels, iteration) — the multi-host path.

    Unlike :func:`save_labels` (single-host npz), orbax writes each shard
    from its owning host (async-capable, atomic via its own finalization
    protocol), so a DCN-spanning run checkpoints without gathering the
    label vector to one host. Same state contents as the npz path; the two
    are interchangeable for single-host runs.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.path.join(checkpoint_dir, f"{tag}_orbax"))
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(
            path,
            # 0-d ndarray, not np.int64: orbax's StandardCheckpointHandler
            # rejects numpy scalar types on some releases
            {"labels": labels, "iteration": np.asarray(iteration, np.int64)},
            force=True,
        )
    return path


def load_sharded(checkpoint_dir: str, tag: str = "lpa", sharding=None):
    """Restore an orbax checkpoint; returns (labels, iteration) or None.

    ``sharding``: optional ``jax.sharding.Sharding`` to restore the label
    array directly into (device-resident, correctly placed on the mesh —
    no host bounce). Defaults to host numpy.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.path.join(checkpoint_dir, f"{tag}_orbax"))
    if not os.path.exists(path):
        return None
    import jax

    with ocp.StandardCheckpointer() as ckptr:
        # StandardCheckpointer.metadata returns StepMetadata in newer
        # orbax (tree under .item_metadata) and the raw tree in older.
        meta = ckptr.metadata(path)
        meta = getattr(meta, "item_metadata", meta)
        if sharding is None:
            # Restore into a host-numpy skeleton built from the saved
            # metadata: orbax then validates the topology instead of
            # warning that targetless restores are unsafe.
            target = jax.tree.map(
                lambda m: np.zeros(m.shape, m.dtype), dict(meta)
            )
        else:
            lbl = meta["labels"]
            target = {
                "labels": jax.ShapeDtypeStruct(
                    lbl.shape, lbl.dtype, sharding=sharding
                ),
                "iteration": 0,
            }
        state = ckptr.restore(path, target)
    return state["labels"], int(state["iteration"])
