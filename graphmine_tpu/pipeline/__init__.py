from graphmine_tpu.pipeline.config import PipelineConfig
from graphmine_tpu.pipeline.driver import run_pipeline

__all__ = ["PipelineConfig", "run_pipeline"]
