"""Backend plugin boundary: jax (native) | graphframes (legacy).

BASELINE.json's north star keeps the original Spark driver as a plugin
boundary — the pipeline dispatches community detection to either the
TPU-native engine or GraphFrames. The graphframes path needs a
pyspark+JVM+graphframes environment (the reference's ``README.md:1-22``
setup); it is gated, not bundled.
"""

from __future__ import annotations

import numpy as np


class GraphFramesUnavailable(RuntimeError):
    pass


# The bridge materializes per-row Python lists on the driver, exactly the
# scaling cliff the reference hits (Graphframes.py:100-118). It exists for
# small-graph cross-validation only; refuse anything bigger.
MAX_BRIDGE_EDGES = 5_000_000


def lpa_graphframes(edge_table, max_iter: int) -> np.ndarray:
    """Run labelPropagation via GraphFrames (reference engine, Graphframes.py:78-81).

    Returns int labels aligned to the edge table's dense vertex ids.
    Raises :class:`GraphFramesUnavailable` when pyspark/graphframes are not
    installed (they are not part of this environment), and ``ValueError``
    beyond :data:`MAX_BRIDGE_EDGES` — the driver-side row lists below
    would OOM like the reference does; the jax backend is the scale path.
    """
    if edge_table.num_edges > MAX_BRIDGE_EDGES:
        raise ValueError(
            f"graphframes bridge is capped at {MAX_BRIDGE_EDGES:,} edges "
            f"(got {edge_table.num_edges:,}): it collects driver-side row "
            "lists; use backend='jax' at scale"
        )
    try:
        import pyspark  # noqa: F401
        from graphframes import GraphFrame  # noqa: F401
    except ImportError as e:
        raise GraphFramesUnavailable(
            "backend='graphframes' needs pyspark + graphframes "
            "(see the reference README: spark-2.4.5 + graphframes 0.6.0); "
            "install them or use backend='jax'"
        ) from e

    from pyspark.sql import SparkSession

    spark = SparkSession.builder.appName("CommunityDetection").getOrCreate()
    try:
        v_rows = [(int(i), str(n)) for i, n in enumerate(edge_table.names)]
        e_rows = [(int(s), int(d)) for s, d in zip(edge_table.src, edge_table.dst)]
        vertices = spark.createDataFrame(v_rows, ["id", "name"])
        edges = spark.createDataFrame(e_rows, ["src", "dst"])
        result = GraphFrame(vertices, edges).labelPropagation(maxIter=max_iter)
        rows = result.select("id", "label").collect()
    finally:
        spark.stop()
    labels = np.zeros(edge_table.num_vertices, dtype=np.int64)
    for r in rows:
        labels[r["id"]] = r["label"]
    return labels
