"""Resilient phase execution: error taxonomy, bounded retry, degradation.

The reference Spark script inherited fault tolerance from the JVM engine
(lineage recomputation, task retry, speculative execution). The TPU-native
driver has no such engine underneath it — one transient XLA runtime error,
preemption, or OOM killed a whole billion-edge run. This module is the
driver's execution armor:

- an **error taxonomy** (:func:`classify_error`): every exception out of a
  pipeline phase is *retryable* (transient runtime/RPC weather — retry the
  same work), *degradable* (resource exhaustion — the same work cannot
  succeed at this operating point; step down the degradation ladder), or
  *fatal* (bugs, bad input, preemption — surface immediately);
- :func:`run_phase`: bounded retry with exponential backoff + deterministic
  jitter for retryables, ladder descent for degradables, immediate
  re-raise for fatals — every decision emitted as a structured record
  through the :class:`~graphmine_tpu.pipeline.metrics.MetricsSink`;
- :func:`run_with_watchdog`: a wall-clock bound on a single phase step
  (hung LPA supersteps), with a checkpoint-then-abort hook;
- :func:`fault_point`: the deterministic fault-injection seam used by
  :mod:`graphmine_tpu.testing.faults` so every recovery path above is
  exercised in CI on CPU, no TPU required.

Everything here is stdlib-only and host-side; nothing imports jax.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from dataclasses import dataclass

RETRYABLE = "retryable"
DEGRADABLE = "degradable"
# A device (or its ICI link) died: the operating point itself is gone, not
# merely too big. Retrying cannot help (the chip stays dead) and the memory
# ladder is the wrong move (the survivors have the same HBM) — the only way
# forward is an ELASTIC rung: re-partition onto the surviving device count
# and resume from the last sharded checkpoint (pipeline/planner.py
# elastic_device_ladder + the driver's device rungs).
DEGRADABLE_DEVICE = "degradable_device"
FATAL = "fatal"

# Transient runtime weather: the work is sound, the attempt was unlucky.
# XLA/PJRT runtime errors carry their absl status code as a message PREFIX
# ("UNAVAILABLE: socket closed ..."), so the status tokens are anchored to
# the start of the message — a fatal error that merely *quotes* a token
# ("failed reading /data/ABORTED_run/...") must not be retried. The phrase
# markers are specific enough to match anywhere. The injected faults in
# testing/faults.py use the same message shapes on purpose: the classifier
# under test is this one, not a test double.
_RETRYABLE_STATUS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED", "UNKNOWN")
_RETRYABLE_PHRASES = ("socket closed", "connection reset", "transport closed")

# Resource exhaustion: retrying the identical program would OOM again;
# the only way forward is a smaller operating point (degradation ladder).
_DEGRADABLE_STATUS = ("RESOURCE_EXHAUSTED",)
_DEGRADABLE_PHRASES = ("Out of memory", "out of memory")

# Device/ICI loss: a chip or its interconnect left the mesh. Checked BEFORE
# the retryable markers — real device-loss reports often ride otherwise
# transient-looking statuses ("UNAVAILABLE: ... device failure"), and
# retrying onto a dead chip just burns the retry budget. The injected
# device-loss fault (testing/faults.py) uses the same message shapes so the
# classifier under test is this one.
_DEVICE_LOSS_STATUS = ("DATA_LOSS",)
_DEVICE_LOSS_PHRASES = (
    "device failure", "ICI link", "interconnect failure",
    "device is lost", "chip halted",
)

# Divergence tripwires (parallel/sharded.py) raise DivergenceError, but a
# trip detected by an on-device guard surfaces through an XLA host-callback
# wrapper that may re-wrap it (XlaRuntimeError quoting the message) — the
# marker token classifies the wrapped form identically to the original.
_DIVERGENCE_MARKER = "GRAPHMINE_DIVERGENCE"


def _status_prefixed(msg: str, codes: tuple) -> bool:
    return any(msg == c or msg.startswith(c + ":") for c in codes)


class ResilienceError(RuntimeError):
    """Base for errors raised by the resilience layer itself."""

    graphmine_error_class = FATAL


class RetriesExhausted(ResilienceError):
    """A retryable error outlasted the retry budget. ``__cause__`` holds
    the final underlying error."""


class SuperstepTimeout(ResilienceError):
    """A watchdogged phase step exceeded its wall-clock bound. When a
    checkpoint hook was given, the last good state was checkpointed
    before this was raised — the message says which case applies."""


class DivergenceError(ResilienceError):
    """An in-loop divergence tripwire fired: the iterate (labels / ranks)
    is numerically or structurally garbage — NaN/Inf ranks, labels outside
    the vertex id range, a period-2 oscillation, a CC monotonicity
    violation. Classified RETRYABLE: the canonical cause is transient
    device corruption (a bit flip, a torn collective), and the driver
    rolls the loop state back to the last checkpoint before the retry so
    the re-attempt starts from trusted bytes, not from the garbage that
    tripped. ``kind`` / ``shard`` / ``iteration`` carry the forensics."""

    graphmine_error_class = RETRYABLE

    def __init__(self, kind: str, shard: int, iteration: int):
        super().__init__(
            f"{_DIVERGENCE_MARKER}: {kind} detected in shard {shard} at "
            f"superstep {iteration}; the iterate is untrusted — resume "
            "from the last good checkpoint"
        )
        self.kind = kind
        self.shard = int(shard)
        self.iteration = int(iteration)


def classify_error(exc: BaseException) -> str:
    """Map an exception to RETRYABLE / DEGRADABLE / DEGRADABLE_DEVICE /
    FATAL.

    Precedence: an explicit ``graphmine_error_class`` attribute (the
    protocol for injected faults and our own error types) wins; then
    device-loss markers (a dead chip can masquerade as transient
    UNAVAILABLE weather — retrying onto it cannot help); then degradable
    resource-exhaustion markers (checked before retryable ones: an OOM
    status string may also mention a retryable-looking transport detail);
    then transient markers and connection errors; else fatal. The
    divergence-tripwire marker is matched anywhere in the message so a
    :class:`DivergenceError` re-wrapped by an XLA callback boundary still
    classifies retryable.
    """
    explicit = getattr(exc, "graphmine_error_class", None)
    if explicit in (RETRYABLE, DEGRADABLE, DEGRADABLE_DEVICE, FATAL):
        return explicit
    if isinstance(exc, MemoryError):
        return DEGRADABLE
    msg = str(exc)
    if _status_prefixed(msg, _DEVICE_LOSS_STATUS) or any(
        m in msg for m in _DEVICE_LOSS_PHRASES
    ):
        return DEGRADABLE_DEVICE
    if _status_prefixed(msg, _DEGRADABLE_STATUS) or any(
        m in msg for m in _DEGRADABLE_PHRASES
    ):
        return DEGRADABLE
    if _DIVERGENCE_MARKER in msg:
        return RETRYABLE
    if isinstance(exc, ConnectionError):
        return RETRYABLE
    if _status_prefixed(msg, _RETRYABLE_STATUS) or any(
        m in msg for m in _RETRYABLE_PHRASES
    ):
        return RETRYABLE
    return FATAL


@dataclass
class ResilienceConfig:
    """Knobs for :func:`run_phase` / :func:`run_with_watchdog`.

    ``max_retries`` bounds *additional* attempts per phase (0 = one attempt,
    no retry). Backoff for attempt ``n`` (1-based) is
    ``min(backoff_base_s * 2**(n-1), backoff_max_s)`` scaled by a
    deterministic jitter in ``[1 - jitter, 1 + jitter]`` (seeded per phase
    and process: reproducible within one process, decorrelated across
    phases and across a fleet of workers).
    ``superstep_timeout_s`` arms the LPA superstep watchdog (None = off,
    the default). Size it to steady-state step time: the driver leaves the
    compile-bearing first superstep of each operating point unarmed, so
    XLA compilation (which can dwarf a steady-state step) never trips it.
    ``degradation`` is ``"auto"`` (walk the ladder on degradable errors) or
    ``"off"`` (surface the error; an operator who sized the run wants the
    OOM, not a silently slower schedule); it governs BOTH ladder families
    — the memory rungs and the elastic device rungs.
    ``tripwire_every_k`` arms the in-loop divergence tripwires (NaN/Inf
    ranks, label-out-of-range, oscillation — docs/RESILIENCE.md) every K
    supersteps; 0 (the default) leaves them off. K trades detection
    latency against one extra reduction + host sync per checked superstep.
    """

    max_retries: int = 2
    backoff_base_s: float = 0.05
    backoff_max_s: float = 5.0
    jitter: float = 0.5
    superstep_timeout_s: float | None = None
    degradation: str = "auto"
    tripwire_every_k: int = 0

    def validate(self) -> "ResilienceConfig":
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.backoff_base_s < 0 or self.backoff_max_s < 0:
            raise ValueError("backoff seconds must be >= 0")
        if not 0 <= self.jitter <= 1:
            raise ValueError("jitter must be in [0, 1]")
        if self.superstep_timeout_s is not None and self.superstep_timeout_s <= 0:
            raise ValueError("superstep_timeout_s must be positive")
        if self.degradation not in ("auto", "off"):
            raise ValueError(f"unknown degradation policy {self.degradation!r}")
        if self.tripwire_every_k < 0:
            raise ValueError("tripwire_every_k must be >= 0 (0 = off)")
        return self


def _count(metrics, name: str) -> None:
    """Bump a counter on the sink's registry when it has one — the
    resilience layer stays duck-typed over ``metrics`` (tests pass bare
    stubs), so the level surface is best-effort by design."""
    reg = getattr(metrics, "registry", None)
    if reg is not None:
        reg.counter(name).inc()


def _rung_span(metrics, label: str):
    """A tracer span around one ladder rung's execution (no-op for sinks
    without span support). ``emit=False``: the rung's identity matters —
    every retry/fault record inside carries ``rung:<label>`` in its span
    path — but a span *record* per rung attempt would double the stream
    for phases that never degrade."""
    span = getattr(metrics, "span", None)
    if span is None:
        return contextlib.nullcontext()
    return span(f"rung:{label}", emit=False)


def backoff_s(policy: ResilienceConfig, attempt: int, rng: random.Random) -> float:
    """Jittered exponential delay before retry ``attempt`` (1-based)."""
    base = min(policy.backoff_base_s * (2 ** (attempt - 1)), policy.backoff_max_s)
    return base * (1 + policy.jitter * (2 * rng.random() - 1))


def _retry_loop(name, thunk, policy, metrics, sleep, rng, progress=None):
    """Retry ``thunk`` on transient errors, ``max_retries`` times per
    *incident*: when ``progress()`` has advanced since the last failure
    (e.g. the LPA loop's iteration counter), the budget resets — a
    multi-hour run that recovers cleanly from independent transient
    events at superstep 10 and superstep 9000 must not die on the
    third, hours later, because a lifetime counter ran out."""
    attempt = 0
    last_mark = progress() if progress is not None else None
    while True:
        try:
            return thunk()
        except Exception as e:
            if classify_error(e) != RETRYABLE:
                raise
            if progress is not None:
                mark = progress()
                if mark != last_mark:
                    attempt = 0
                    last_mark = mark
            attempt += 1
            if attempt > policy.max_retries:
                metrics.emit(
                    "retries_exhausted", stage=name,
                    attempts=attempt, error=repr(e),
                )
                raise RetriesExhausted(
                    f"phase {name!r} still failing transiently after "
                    f"{attempt} attempts with no progress: {e!r}"
                ) from e
            delay = backoff_s(policy, attempt, rng)
            _count(metrics, "graphmine_retries_total")
            metrics.emit(
                "retry", stage=name, attempt=attempt,
                backoff_s=round(delay, 4), error=repr(e),
            )
            sleep(delay)


def run_phase(
    name: str,
    fn,
    policy: ResilienceConfig,
    metrics,
    ladder: tuple = (),
    sleep=time.sleep,
    progress=None,
    device_ladder: tuple = (),
    degrade_context=None,
):
    """Run ``fn()`` with the full retry/degrade/fail taxonomy applied.

    ``ladder``: ordered ``(label, thunk)`` fallbacks for DEGRADABLE
    (memory) failures — each rung is itself retried on transient errors.
    Thunks that share mutable state (e.g. the LPA loop's labels +
    iteration counter) make a rung *resume* rather than restart; see the
    driver.

    ``device_ladder``: ordered ``(label, thunk)`` fallbacks for
    DEGRADABLE_DEVICE (device/ICI loss) failures — the elastic rungs that
    re-partition onto fewer devices. The two families advance
    independently: an OOM steps the memory ladder, a device loss steps
    the device ladder, and a run may walk both (lose a chip, then OOM on
    the smaller mesh).

    ``progress``: optional zero-arg callable sampled at each failure; when
    its value has advanced since the previous failure the retry budget
    resets — ``max_retries`` bounds attempts per *incident*, not per phase
    lifetime (see :func:`_retry_loop`).

    ``degrade_context``: optional zero-arg callable returning extra
    key/values merged into every ``degrade`` record — the memory plane
    (ISSUE 14) uses it to attach the failed operating point's modeled
    ``mem`` inventory and the last ``memory_watermark``, so a reactive
    OOM is triageable (model-miss vs fragmentation) from the JSONL
    alone. Telemetry only: a raising context never masks the failure
    being recorded, and its keys must not collide with the record's own
    (``stage``/``to``/``depth``/``kind``/``error``).

    Emits ``retry`` / ``retries_exhausted`` / ``degrade`` records through
    ``metrics`` (device rungs carry ``kind="device"``). Raises the
    classified-fatal error, the degradable error when its ladder is
    exhausted (or degradation is off), or :class:`RetriesExhausted`.
    """
    # Jitter stream seeded per (phase, process): reproducible within one
    # process, but DIFFERENT across a fleet — N preempted workers retrying
    # a shared dependency must not wake in lockstep (the thundering herd
    # jitter exists to prevent).
    rng = random.Random(f"{name}:{os.getpid()}")
    mem = list(ladder)
    dev = list(device_ladder)
    thunk = fn
    depth = 0
    # The rung label names the span every record inside executes under
    # ("rung:primary", then the ladder labels) — the span-path join key
    # that ties a retry record to the operating point it retried AT.
    rung = "primary"

    def _degrade_extra() -> dict:
        if degrade_context is None:
            return {}
        try:
            extra = dict(degrade_context() or {})
        except Exception:  # noqa: BLE001 — context is telemetry only
            return {}
        # A context key colliding with the record's own kwargs would
        # raise TypeError AT the emit call — outside the guard above,
        # masking the very failure being recorded. Drop reserved keys.
        for reserved in ("phase", "t", "stage", "to", "depth", "kind",
                         "error"):
            extra.pop(reserved, None)
        return extra

    while True:
        try:
            with _rung_span(metrics, rung):
                return _retry_loop(
                    name, thunk, policy, metrics, sleep, rng, progress
                )
        except Exception as e:
            cls = classify_error(e)
            if policy.degradation != "auto":
                raise
            if cls == DEGRADABLE and mem:
                rung, thunk = mem.pop(0)
                depth += 1
                _count(metrics, "graphmine_degrades_total")
                metrics.emit(
                    "degrade", stage=name, to=rung, depth=depth,
                    error=repr(e), **_degrade_extra(),
                )
                continue
            if cls == DEGRADABLE_DEVICE and dev:
                rung, thunk = dev.pop(0)
                depth += 1
                _count(metrics, "graphmine_degrades_total")
                metrics.emit(
                    "degrade", stage=name, to=rung, depth=depth,
                    kind="device", error=repr(e), **_degrade_extra(),
                )
                continue
            raise


def run_with_watchdog(name, fn, timeout_s, metrics, on_timeout=None):
    """Run ``fn()`` bounded by ``timeout_s`` wall-clock seconds.

    The work runs in a daemon worker thread; on timeout, ``on_timeout()``
    fires (the driver checkpoints the last good labels) and
    :class:`SuperstepTimeout` is raised. A truly hung device call cannot be
    interrupted portably from Python, so the contract is
    **checkpoint-then-abort**: the abandoned worker stays parked in the
    runtime while the process surfaces the error, and the run resumes from
    the checkpoint after the hang is resolved. ``timeout_s`` of None/0
    runs ``fn`` inline with no thread.
    """
    if not timeout_s:
        return fn()
    result: list = []
    err: list = []

    def _target():
        try:
            result.append(fn())
        except BaseException as e:  # propagate even SystemExit-ish faults
            err.append(e)

    t = threading.Thread(target=_target, daemon=True, name=f"{name}-watchdog")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        # Run the hook FIRST, tolerating its failure: the record and the
        # message must state what actually happened, and a failing save
        # (disk full) must not suppress the timeout — the hang is the
        # root cause the operator needs to see.
        checkpointed = False
        save_err = None
        if on_timeout is not None:
            try:
                on_timeout()
                checkpointed = True
            except Exception as e:
                save_err = e
        _count(metrics, "graphmine_watchdog_timeouts_total")
        metrics.emit(
            "watchdog_timeout", stage=name, timeout_s=timeout_s,
            checkpointed=checkpointed,
        )
        if checkpointed:
            hint = ("last good state was checkpointed — resume after "
                    "resolving the hang")
        elif on_timeout is not None:
            hint = (f"the checkpoint hook FAILED ({save_err!r}); no "
                    "recovery point was saved")
        else:
            hint = ("NO checkpoint hook was configured; the run restarts "
                    "from scratch (set checkpoint_dir to make hangs "
                    "resumable)")
        raise SuperstepTimeout(
            f"phase {name!r} exceeded its {timeout_s}s watchdog; {hint}"
        ) from save_err
    if err:
        raise err[0]
    return result[0]


# ---- fault-injection seam -------------------------------------------------
# Production code calls fault_point(site, ...) at instrumented points; the
# hook is None (zero-cost beyond one attribute read) unless
# graphmine_tpu.testing.faults installs an injector. Kept here, not in the
# testing package, so production modules never import test code.

_fault_hook = None


def set_fault_hook(hook) -> None:
    """Install (or clear, with None) the process-wide fault hook."""
    global _fault_hook
    _fault_hook = hook


def fault_point(site: str, **ctx) -> None:
    """Named instrumentation point; raises whatever the installed injector
    decides to raise at this site (deterministically, per its plan)."""
    hook = _fault_hook
    if hook is not None:
        hook(site, **ctx)
