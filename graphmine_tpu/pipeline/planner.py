"""Pre-allocation memory planner + automatic schedule selection.

VERDICT r2 item 3: ``docs/DESIGN.md`` carries a *measured* memory model
(≈36 bytes/edge on the fused LPA path; replicated labels ≈400 MB/device at
100M vertices, ``parallel/sharded.py:20-23``; a ≈400M-directed-edge HBM
ceiling on a 16 GB chip) — but nothing consulted it: a 300M-vertex config
OOMed deep inside XLA instead of being routed to the ring schedule at plan
time. This module encodes that model as ``plan_run(...)`` so the driver
picks the cheapest schedule that fits and rejects impossible configs with
a loud, numeric error *before* any device allocation.

The reference has no analog (Spark sizes nothing; the author's abandoned
driver-side data slicer, ``Graphframes.py:34-47``, is the closest trace of
the same fight) — this is the framework's answer to that capability hint.

Model constants, all derived from DESIGN.md "Single-chip capacity" and the
array inventory of the three LPA execution paths (int32 = 4 bytes, message
count M = 2E for a directed edge list propagated both ways):

  single (fused bucketed kernel, one device)
      36 B/edge   edge endpoints 2E + message CSR (4E+V) + bucketed plan
                  ≈2.5E + per-bucket gather transient ≈2.5E
    +  8 B/vertex labels in + out
    + 16 B/edge   when weighted (msg_weight 2E floats + slot-aligned
                  weight matrices ≈2E after the width ladder; the r4 1.10x
                  ladder pads ~10%, so ≈2E stays conservative)

  replicated (parallel/sharded.py, lpa_only=True trimming)
      36 B/edge / D   the same O(E) arrays, vertex-range sharded
    + 16 B/vertex     replicated labels + updated copy + all-gather
                      staging (the ≈400 MB/100M-vertices term, x4)
    + 16 B/edge / D   when weighted

  ring (parallel/ring.py)
      36 B/edge / D   sharded O(E) arrays
    + 24 B/vertex / D labels sharded + two rotating ppermute chunks
                      + staging — no replicated V-term at all
    + 16 B/edge / D   when weighted
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from graphmine_tpu.obs import memmodel

# Byte model constants: DERIVED from the memory plane's single owner
# (obs/memmodel.py, ISSUE 14) — the same seeds decompose into the named
# inventory the `plan` record and every memory_watermark ship, so a
# recalibration moves this planner and the records together. The names
# are kept as local aliases because this module's docstring/derivation
# notes above reference them.
_BYTES_PER_EDGE = memmodel.BYTES_PER_EDGE
_BYTES_PER_EDGE_WEIGHTED = memmodel.BYTES_PER_EDGE_WEIGHTED
_SINGLE_BYTES_PER_VERTEX = memmodel.SINGLE_BYTES_PER_VERTEX
_REPLICATED_BYTES_PER_VERTEX = memmodel.REPLICATED_BYTES_PER_VERTEX
_RING_BYTES_PER_VERTEX = memmodel.RING_BYTES_PER_VERTEX

# Default HBM per device: 16 GiB (TPU v5e, the measured chip of
# DESIGN.md). Overridable per-process for other parts/CPU testing.
_DEFAULT_HBM = 16 * (1 << 30)
# Plan against 90% of physical HBM: XLA's own workspace + fragmentation.
_HBM_HEADROOM = 0.9

# Per-device message-index bound (VERDICT r4 weak 2): every device kernel
# gathers with int32 indices into the [M]-length per-device message
# arrays, so a schedule that puts more than 2^31-1 messages on one device
# would overflow SILENTLY at gather time. The planner rejects such
# schedules here, explicitly — HBM byte budgets usually reject them first
# on a 16 GiB part (2^31 messages model ≈36 GiB), but that is a
# coincidence of byte constants, not the invariant; a future part or env
# override with huge HBM must still hit this wall loudly. The modeled
# per-device count is M = 2E (symmetric message flow) over D, with 12%
# slack for the bucket-ladder/pad_multiple padding; the EXACT skew-aware
# bound is re-checked at partition time (parallel/sharded.py) and at
# device assembly (graph/container._graph_from_csr).
_INT32_MAX = (1 << 31) - 1
_SHARD_PAD_SLACK = 1.12


def messages_per_device(schedule: str, num_edges: int, num_devices: int) -> int:
    """Modeled per-device message-array length for ``schedule``."""
    m = 2.0 * num_edges
    if schedule == "single" or num_devices <= 1:
        return int(m)
    return int(m / num_devices * _SHARD_PAD_SLACK)


class PlanError(ValueError):
    """No schedule fits the config — raised at plan time, pre-allocation."""


@dataclass(frozen=True)
class RunPlan:
    """Resolved execution plan for one LPA run."""

    schedule: str            # "single" | "replicated" | "ring"
    lpa_only: bool           # shard_graph_arrays HBM trimming flag
    bytes_per_device: int    # modeled peak for the chosen schedule
    hbm_bytes: int           # per-device budget the plan was made against
    reason: str              # one-line human-readable selection rationale
    estimates: dict = field(default_factory=dict)  # schedule -> bytes/device


def hbm_bytes_per_device(device_bytes=None) -> int:
    """Per-device HBM the planner budgets against.

    Precedence (VERDICT r3 item 3): ``GRAPHMINE_HBM_BYTES`` (tests,
    explicit budget overrides) → ``device_bytes`` (the caller's measured
    ``memory_stats()["bytes_limit"]`` as an int, or a zero-arg callable
    producing it lazily — the driver passes ``device_hbm_bytes`` itself,
    queried only when the env var did not win) → the 16 GiB v5e default.
    This function never imports jax itself — callers planning host-side
    stay device-free; a v4 (32 GiB) or v5p (95 GiB) part is budgeted
    correctly exactly when the caller passes what the runtime reports."""
    env = os.environ.get("GRAPHMINE_HBM_BYTES")
    if env:
        return int(env)
    # device_bytes may be a callable (the driver passes device_hbm_bytes
    # itself) so the device is only touched when the env override did NOT
    # win — an operator pinning the budget must bypass a flaky runtime's
    # memory query entirely, not run-and-discard it (code-review r4).
    if callable(device_bytes):
        device_bytes = device_bytes()
    if device_bytes:
        return int(device_bytes)
    return _DEFAULT_HBM


def estimate_bytes_per_device(
    schedule: str,
    num_vertices: int,
    num_edges: int,
    num_devices: int,
    weighted: bool = False,
) -> int:
    """Modeled peak HBM per device for ``schedule`` — delegated to the
    memory plane's single owner (:func:`memmodel.schedule_bytes_per_device`,
    ISSUE 14): one inventory, two consumers (this planner's accept/reject
    and the ``plan``/``memory_watermark`` record inventories), bit-identical
    arithmetic to the constants this module used to own."""
    return memmodel.schedule_bytes_per_device(
        schedule, num_vertices, num_edges, num_devices, weighted
    )


def degradation_ladder(
    schedule: str, num_devices: int, family: str = "bucketed"
) -> list[str]:
    """Successive LPA operating points after resource exhaustion under
    ``schedule`` — the planner's answer to "the plan fit on paper but the
    device disagreed" (fragmentation, a co-tenant, an optimistic budget).

    Each rung trades speed for strictly less per-device memory, per the
    model above:

    - ``single`` with the ``blocked`` plan family (r7) →
      ``single_bucketed`` → ``single_sort``: first drop the blocked
      plan's tile + stream arrays and rebuild the degree-bucketed fused
      plan (the r5/r6-measured path, less HBM than tile + rows), then
      drop plans entirely for the sort superstep.
    - ``single`` → ``single_sort``: drop the fused kernel's padded bucket
      matrices and per-bucket gather transients (~5E of the 36 B/edge);
      the plain sort-based superstep runs over the bare message CSR.
    - ``replicated`` → ``ring``: drop the replicated V-length label
      vector (the 16 B/vertex term) — labels stay sharded, chunks rotate
      over ICI.
    - ``ring``: nothing below — ring is already the memory floor; the
      failure surfaces.

    The driver re-runs the remaining supersteps on the next rung from the
    last good label state, recording a ``degrade`` metrics event.
    """
    if schedule == "single" or num_devices <= 1:
        if family == "blocked":
            return ["single_bucketed", "single_sort"]
        if family == "sort":
            return []  # already the memory floor; the failure surfaces
        return ["single_sort"]
    if schedule == "replicated":
        return ["ring"]
    return []


def elastic_device_ladder(schedule: str, num_devices: int) -> list[int]:
    """Surviving-device rungs after a device/ICI loss under ``schedule``
    — the ELASTIC family (DEGRADABLE_DEVICE errors), orthogonal to the
    memory ladder above: a lost chip leaves the survivors with the same
    per-device HBM, so the answer is not a leaner schedule but a smaller
    mesh — re-partition via ``partition_graph`` onto D' devices and
    resume from the last sharded checkpoint.

    Rungs halve (D//2, D//4, ..., 1): after one loss the surviving count
    is D-1, but meshes want the even chunking the partitioner pads for,
    halving bounds the rung count to log D (each re-partition is minutes
    of host work at scale), and a halved mesh tolerates further losses
    before the next descent. Single-device runs have no mesh to shrink.
    """
    if schedule == "single" or num_devices <= 1:
        return []
    rungs = []
    d = num_devices // 2
    while d >= 1:
        rungs.append(d)
        d //= 2
    return rungs


@dataclass(frozen=True)
class SuperstepPlan:
    """Resolved superstep plan family for one graph (r7).

    ``family`` is the selected layout (``"sharded_2d"`` / ``"blocked"``
    / ``"bucketed"`` / ``"sort"``); ``degrade_to`` is the family a
    resource failure steps down to — sharded_2d degrades to blocked
    (drop the per-peer boundary tables, fall back to the one-all_gather
    exchange), blocked to bucketed (drop the tile + stream arrays, keep
    dense rows), bucketed to sort (drop all padded plan matrices), sort
    has nowhere leaner to go."""

    family: str        # "sharded_2d" | "blocked" | "bucketed" | "sort"
    degrade_to: str    # next rung's family
    reason: str        # one-line selection rationale (measured provenance)


_SUPERSTEP_DEGRADE = {
    "sharded_2d": "blocked", "blocked": "bucketed", "bucketed": "sort",
    "sort": "sort",
}


def plan_superstep(
    num_vertices: int, num_messages: int, requested: str = "auto",
    weighted: bool = False, num_devices: int = 1,
) -> SuperstepPlan:
    """Resolve the LPA/CC superstep plan family at plan time.

    Thin planner wrapper over
    :func:`graphmine_tpu.ops.blocking.select_superstep_family` (the
    single crossover-policy owner, with the measured-provenance table)
    so the driver's single-device dispatch AND its blocked→bucketed
    degradation rung come from one plan-time decision — the same
    treatment :func:`plan_lof` gives the IVF flip. ``num_devices`` (r16)
    gates the ``sharded_2d`` family: >= 2-device callers (the serve
    sharded repair path, the exchange bench tier) resolve the
    neighbor-exchange family here, with its degradation rung back to the
    one-all_gather ``blocked`` family. NOTE: imports the ops layer
    (hence jax) lazily, like ``plan_lof``.
    """
    from graphmine_tpu.ops.blocking import select_superstep_family

    family, reason = select_superstep_family(
        num_vertices, num_messages, requested=requested, weighted=weighted,
        num_devices=num_devices,
    )
    return SuperstepPlan(
        family=family, degrade_to=_SUPERSTEP_DEGRADE[family], reason=reason
    )


@dataclass(frozen=True)
class LofPlan:
    """Resolved LOF-scorer plan for one feature cloud (r6).

    ``impl`` is the selected kNN family (``"ivf"`` / ``"exact"``);
    ``degrade_to`` is the family the degradation ladder steps to on a
    resource failure — the two are always opposite, so IVF→exact is a
    rung exactly as exact→IVF long has been: an exact scorer that OOMs
    its [V, V] distance tiles steps DOWN to the bounded-candidate index,
    and an IVF scorer whose data-dependent pair tables blow up steps
    ACROSS to the roofline-bounded exact tiles."""

    impl: str          # "ivf" | "exact"
    degrade_to: str    # the ladder rung's family ("exact" | "ivf")
    reason: str        # one-line selection rationale (measured provenance)


def plan_lof(
    num_points: int, k: int, requested: str = "auto",
    ivf_min_points: int | None = None,
) -> LofPlan:
    """Resolve the LOF kNN implementation for the ``outliers_lof`` phase.

    Thin planner wrapper over :func:`graphmine_tpu.ops.lof.select_lof_impl`
    (the single policy owner, with the measured-crossover provenance
    table) so the driver's dispatch AND its degradation-ladder direction
    come from one plan-time decision — the e2e pipeline deploys IVF at
    scale because the planner said so, not because an operator passed an
    opt-in string. NOTE: unlike the rest of this module this imports the
    ops layer (hence jax) lazily — callers planning a LOF phase are about
    to run one anyway.
    """
    from graphmine_tpu.ops.lof import select_lof_impl

    family, reason = select_lof_impl(
        num_points, k, impl=requested, ivf_min_points=ivf_min_points
    )
    return LofPlan(
        impl=family,
        degrade_to="exact" if family == "ivf" else "ivf",
        reason=reason,
    )


def plan_run(
    num_vertices: int,
    num_edges: int,
    num_devices: int,
    weighted: bool = False,
    requested: str = "auto",
    hbm: int | None = None,
) -> RunPlan:
    """Pick the LPA schedule for this (V, E, D) — or reject loudly.

    ``requested="auto"`` selects the first schedule that fits the
    per-device budget, in *speed* preference order (not lowest memory):
    single-device fused kernel when D == 1, else replicated (faster: one
    all-gather, no rotation pipeline) before ring (scalable: no replicated
    V-term, often smaller but slower). An explicit ``requested`` schedule
    is honored but still checked — if it cannot fit, the error says which
    schedule *would*, instead of letting XLA OOM after minutes of build.
    """
    if num_devices < 1:
        raise ValueError("num_devices must be >= 1")
    budget = int((hbm if hbm is not None else hbm_bytes_per_device())
                 * _HBM_HEADROOM)

    candidates = (
        ["single"] if num_devices == 1 else ["replicated", "ring"]
    )
    # estimates always include "single" (even for D > 1): the driver uses
    # it to decide whether the FULL graph may also live on one device for
    # the census/outlier phases, or must stay host-side (scale-out mode).
    est = {
        s: estimate_bytes_per_device(
            s, num_vertices, num_edges, num_devices, weighted
        )
        for s in dict.fromkeys(candidates + ["single"])
    }

    def _gb(b):
        return f"{b / (1 << 30):.2f} GiB"

    def _idx_ok(s):
        return messages_per_device(s, num_edges, num_devices) <= _INT32_MAX

    def _idx_error(s):
        mpd = messages_per_device(s, num_edges, num_devices)
        need_d = int(2.0 * num_edges * _SHARD_PAD_SLACK / _INT32_MAX) + 1
        return PlanError(
            f"message-index overflow: schedule '{s}' puts ~{mpd:,} messages "
            f"on one device for E={num_edges:,} on {num_devices} device(s), "
            f"above the int32 gather-index bound {_INT32_MAX:,} the device "
            f"kernels index messages with — this would wrap SILENTLY at "
            f"gather time; use >= {need_d} devices so every shard's "
            f"messages fit int32"
        )

    if requested != "auto":
        # "ring" on one device runs the single-device kernel (the driver
        # warned about this pre-r3; the planner owns the mapping now).
        sched = requested if num_devices > 1 else "single"
        if not _idx_ok(sched):
            raise _idx_error(sched)
        need = est.get(sched) or estimate_bytes_per_device(
            sched, num_vertices, num_edges, num_devices, weighted
        )
        if need > budget:
            fits = [s for s, b in est.items() if b <= budget]
            hint = (
                f"schedule '{fits[0]}' would fit ({_gb(est[fits[0]])})"
                if fits else
                "no schedule fits; add devices or shrink the graph"
            )
            raise PlanError(
                f"schedule '{sched}' needs {_gb(need)}/device for "
                f"V={num_vertices:,} E={num_edges:,} on {num_devices} "
                f"device(s) — budget is {_gb(budget)} "
                f"(90% of {_gb(int(budget / _HBM_HEADROOM))} HBM); {hint}"
            )
        return RunPlan(
            schedule=sched,
            lpa_only=sched == "replicated",
            bytes_per_device=need,
            hbm_bytes=budget,
            reason=f"requested '{requested}' ({_gb(need)}/device fits)",
            estimates=est,
        )

    idx_blocked = [s for s in candidates if not _idx_ok(s)]
    for sched in candidates:
        if est[sched] <= budget and _idx_ok(sched):
            why = {
                "single": "one device: fused bucketed kernel",
                "replicated": "fastest multi-device schedule that fits",
                "ring": (
                    "replicated labels would not fit "
                    f"({_gb(est.get('replicated', 0))}/device); ring keeps "
                    "labels sharded"
                ),
            }[sched]
            return RunPlan(
                schedule=sched,
                lpa_only=sched == "replicated",
                bytes_per_device=est[sched],
                hbm_bytes=budget,
                reason=why,
                estimates=est,
            )

    if idx_blocked and all(
        est[s] <= budget for s in idx_blocked
    ):
        # the ONLY blocker is the int32 message-index bound — say so
        # (an enormous-HBM part/env override lands here, not on bytes)
        raise _idx_error(idx_blocked[-1])
    detail = ", ".join(f"{s}={_gb(b)}" for s, b in est.items())
    blocked_note = (
        f" (schedule(s) {', '.join(repr(s) for s in idx_blocked)} also "
        f"exceed the int32 per-device message-index bound)"
        if idx_blocked else ""
    )
    raise PlanError(
        f"no LPA schedule fits V={num_vertices:,} E={num_edges:,} "
        f"{'weighted ' if weighted else ''}on {num_devices} device(s): "
        f"modeled peak per device {detail} vs budget {_gb(budget)} "
        f"(90% of HBM){blocked_note}. Add devices (O(E) terms shard "
        f"linearly), or set GRAPHMINE_HBM_BYTES if this part has more "
        f"memory."
    )
