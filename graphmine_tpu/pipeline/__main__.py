from graphmine_tpu.pipeline.driver import main

main()
