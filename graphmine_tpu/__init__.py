"""graphmine_tpu — a TPU-native massive-graph-mining framework.

A from-scratch JAX/XLA/Pallas re-design of the capability surface of the
reference PySpark project (community detection + outlier detection over
massive graphs, ``/root/reference/CommunityDetection/Graphframes.py``):

- L0  ingestion: parquet / edge-list readers, null filtering, dense int32
      string factorization (replaces the sha1[:8] NodeHash scheme of
      ``Graphframes.py:57-58`` — no birthday collisions, device-friendly).
- L1  mesh runtime: ``jax.sharding.Mesh`` + ``shard_map`` over ICI; XLA
      collectives (psum / all_gather / ppermute) are the comms backend
      (replaces Spark shuffle + py4j). See :mod:`graphmine_tpu.parallel`.
- L2  sharded graph container: vertex-range-sharded message CSR + vertex
      property arrays (replaces Spark DataFrames / GraphFrames).
- L3  graph ops: label propagation (``Graphframes.py:81``), connected
      components, degrees, community census (replaces the O(C*V*E) driver
      loops of ``Graphframes.py:100-118``), induced subgraphs, kNN + LOF
      outlier scoring (the intended capability of ``Graphframes.py:121-137``).
- L4  pipeline driver with a plugin boundary (backend=jax|graphframes).
      See :mod:`graphmine_tpu.pipeline`.

Exports are **lazy** (PEP 562): ``graphmine_tpu.X`` imports X's defining
module on first access. This keeps the package importable on hosts with
no jax at all — the observability plane (``graphmine_tpu.obs``, used by
the stdlib-only fleet tools ``tools/obs_report.py`` /
``tools/trace_stitch.py`` / ``tools/schema_lint.py``) must load on a
bare triage machine, and an eager ``from .graph.container import ...``
here would drag the whole device stack in with it.
"""

__version__ = "0.1.0"

# export name -> (defining module, attribute). None attribute = the
# module itself. find_motifs aliases ops.motifs.find.
_EXPORTS = {
    "Graph": ("graphmine_tpu.graph.container", "Graph"),
    "build_graph": ("graphmine_tpu.graph.container", "build_graph"),
    "GraphFrame": ("graphmine_tpu.frames", "GraphFrame"),
    "load_parquet_edges": ("graphmine_tpu.io.edges", "load_parquet_edges"),
    "load_edge_list": ("graphmine_tpu.io.edges", "load_edge_list"),
    "label_propagation": ("graphmine_tpu.ops.lpa", "label_propagation"),
    "connected_components": ("graphmine_tpu.ops.cc", "connected_components"),
    "leiden": ("graphmine_tpu.ops.louvain", "leiden"),
    "louvain": ("graphmine_tpu.ops.louvain", "louvain"),
    "modularity": ("graphmine_tpu.ops.modularity", "modularity"),
    "pagerank": ("graphmine_tpu.ops.pagerank", "pagerank"),
    "parallel_personalized_pagerank": (
        "graphmine_tpu.ops.pagerank", "parallel_personalized_pagerank"
    ),
    "degrees": ("graphmine_tpu.ops.degrees", "degrees"),
    "in_degrees": ("graphmine_tpu.ops.degrees", "in_degrees"),
    "out_degrees": ("graphmine_tpu.ops.degrees", "out_degrees"),
    "out_weights": ("graphmine_tpu.ops.degrees", "out_weights"),
    "bfs": ("graphmine_tpu.ops.paths", "bfs"),
    "bfs_distances": ("graphmine_tpu.ops.paths", "bfs_distances"),
    "bfs_parents": ("graphmine_tpu.ops.paths", "bfs_parents"),
    "shortest_paths": ("graphmine_tpu.ops.paths", "shortest_paths"),
    "weighted_shortest_paths": (
        "graphmine_tpu.ops.paths", "weighted_shortest_paths"
    ),
    "adjusted_rand_index": (
        "graphmine_tpu.ops.cluster_metrics", "adjusted_rand_index"
    ),
    "normalized_mutual_info": (
        "graphmine_tpu.ops.cluster_metrics", "normalized_mutual_info"
    ),
    "strongly_connected_components": (
        "graphmine_tpu.ops.scc", "strongly_connected_components"
    ),
    "aggregate_messages": (
        "graphmine_tpu.ops.aggregate", "aggregate_messages"
    ),
    "pregel": ("graphmine_tpu.ops.aggregate", "pregel"),
    "find_motifs": ("graphmine_tpu.ops.motifs", "find"),
    "StreamingLOF": ("graphmine_tpu.ops.streaming_lof", "StreamingLOF"),
    "fit_lof": ("graphmine_tpu.ops.streaming_lof", "fit_lof"),
    "score_lof": ("graphmine_tpu.ops.streaming_lof", "score_lof"),
    "standardize": ("graphmine_tpu.ops.features", "standardize"),
    "vertex_features": ("graphmine_tpu.ops.features", "vertex_features"),
    "vertex_features_host": (
        "graphmine_tpu.ops.features", "vertex_features_host"
    ),
    "ivf_knn": ("graphmine_tpu.ops.ann", "ivf_knn"),
    "kmeans": ("graphmine_tpu.ops.ann", "kmeans"),
    "knn": ("graphmine_tpu.ops.knn", "knn"),
    "lof_scores": ("graphmine_tpu.ops.lof", "lof_scores"),
    "select_lof_impl": ("graphmine_tpu.ops.lof", "select_lof_impl"),
    "masked_label_propagation": (
        "graphmine_tpu.ops.outliers", "masked_label_propagation"
    ),
    "recursive_lpa_outliers": (
        "graphmine_tpu.ops.outliers", "recursive_lpa_outliers"
    ),
    "recursive_lpa_outliers_sharded": (
        "graphmine_tpu.ops.outliers", "recursive_lpa_outliers_sharded"
    ),
    "triangle_count": ("graphmine_tpu.ops.triangles", "triangle_count"),
    "clustering_coefficient": (
        "graphmine_tpu.ops.triangles", "clustering_coefficient"
    ),
    "sampled_clustering_coefficient": (
        "graphmine_tpu.ops.triangles", "sampled_clustering_coefficient"
    ),
    "core_numbers": ("graphmine_tpu.ops.kcore", "core_numbers"),
    "greedy_color": ("graphmine_tpu.ops.mis", "greedy_color"),
    "maximal_independent_set": (
        "graphmine_tpu.ops.mis", "maximal_independent_set"
    ),
    "link_prediction": ("graphmine_tpu.ops.linkpred", "link_prediction"),
    "k_truss": ("graphmine_tpu.ops.ktruss", "k_truss"),
    "spectral_embedding": (
        "graphmine_tpu.ops.embedding", "spectral_embedding"
    ),
    "degree_assortativity": (
        "graphmine_tpu.ops.stats", "degree_assortativity"
    ),
    "density": ("graphmine_tpu.ops.stats", "density"),
    "diameter": ("graphmine_tpu.ops.stats", "diameter"),
    "reciprocity": ("graphmine_tpu.ops.stats", "reciprocity"),
    "betweenness_centrality": (
        "graphmine_tpu.ops.centrality", "betweenness_centrality"
    ),
    "closeness_centrality": (
        "graphmine_tpu.ops.centrality", "closeness_centrality"
    ),
    "eigenvector_centrality": (
        "graphmine_tpu.ops.centrality", "eigenvector_centrality"
    ),
    "hits": ("graphmine_tpu.ops.centrality", "hits"),
    "katz_centrality": ("graphmine_tpu.ops.centrality", "katz_centrality"),
    "datasets": ("graphmine_tpu.datasets", None),
    "Table": ("graphmine_tpu.table", "Table"),
    "read_parquet": ("graphmine_tpu.table", "read_parquet"),
    "svd_plus_plus": ("graphmine_tpu.ops.svdpp", "svd_plus_plus"),
    "svdpp_predict": ("graphmine_tpu.ops.svdpp", "svdpp_predict"),
    "from_networkx": ("graphmine_tpu.interop", "from_networkx"),
    "graph_from_networkx": (
        "graphmine_tpu.interop", "graph_from_networkx"
    ),
    "to_networkx": ("graphmine_tpu.interop", "to_networkx"),
    "graphx_label_propagation": (
        "graphmine_tpu.oracle", "graphx_label_propagation"
    ),
    "BlockedPlan": ("graphmine_tpu.ops.blocking", "BlockedPlan"),
    "blocked_inflow": ("graphmine_tpu.ops.blocking", "blocked_inflow"),
    "build_graph_and_blocked_plan": (
        "graphmine_tpu.ops.blocking", "build_graph_and_blocked_plan"
    ),
    "cc_superstep_blocked": (
        "graphmine_tpu.ops.blocking", "cc_superstep_blocked"
    ),
    "lpa_superstep_blocked": (
        "graphmine_tpu.ops.blocking", "lpa_superstep_blocked"
    ),
    "select_superstep_family": (
        "graphmine_tpu.ops.blocking", "select_superstep_family"
    ),
    "obs": ("graphmine_tpu.obs", None),
    "CostEstimate": ("graphmine_tpu.obs.costmodel", "CostEstimate"),
    "superstep_cost": ("graphmine_tpu.obs.costmodel", "superstep_cost"),
    "sharded_superstep_cost": (
        "graphmine_tpu.obs.costmodel", "sharded_superstep_cost"
    ),
    "lof_cost": ("graphmine_tpu.obs.costmodel", "lof_cost"),
    "rooflines": ("graphmine_tpu.obs.costmodel", "rooflines"),
    # memory plane (ISSUE 14) — the HBM footprint twins of the cost rows
    "MemEstimate": ("graphmine_tpu.obs.memmodel", "MemEstimate"),
    "superstep_footprint": (
        "graphmine_tpu.obs.memmodel", "superstep_footprint"
    ),
    "sharded_superstep_footprint": (
        "graphmine_tpu.obs.memmodel", "sharded_superstep_footprint"
    ),
    "lof_footprint": ("graphmine_tpu.obs.memmodel", "lof_footprint"),
    "schedule_footprint": (
        "graphmine_tpu.obs.memmodel", "schedule_footprint"
    ),
    "crossover_thresholds": (
        "graphmine_tpu.ops.blocking", "crossover_thresholds"
    ),
    "LofPlan": ("graphmine_tpu.pipeline.planner", "LofPlan"),
    "PlanError": ("graphmine_tpu.pipeline.planner", "PlanError"),
    "RunPlan": ("graphmine_tpu.pipeline.planner", "RunPlan"),
    "SuperstepPlan": ("graphmine_tpu.pipeline.planner", "SuperstepPlan"),
    "plan_lof": ("graphmine_tpu.pipeline.planner", "plan_lof"),
    "plan_run": ("graphmine_tpu.pipeline.planner", "plan_run"),
    "plan_superstep": ("graphmine_tpu.pipeline.planner", "plan_superstep"),
}

__all__ = ["__version__", *sorted(_EXPORTS)]


def __getattr__(name: str):
    """PEP 562 lazy export: import the defining module on first access
    and cache the attribute on the package, so the second access is a
    plain dict hit."""
    try:
        module_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    module = importlib.import_module(module_name)
    value = module if attr is None else getattr(module, attr)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
