"""graphmine_tpu — a TPU-native massive-graph-mining framework.

A from-scratch JAX/XLA/Pallas re-design of the capability surface of the
reference PySpark project (community detection + outlier detection over
massive graphs, ``/root/reference/CommunityDetection/Graphframes.py``):

- L0  ingestion: parquet / edge-list readers, null filtering, dense int32
      string factorization (replaces the sha1[:8] NodeHash scheme of
      ``Graphframes.py:57-58`` — no birthday collisions, device-friendly).
- L1  mesh runtime: ``jax.sharding.Mesh`` + ``shard_map`` over ICI; XLA
      collectives (psum / all_gather / ppermute) are the comms backend
      (replaces Spark shuffle + py4j). See :mod:`graphmine_tpu.parallel`.
- L2  sharded graph container: vertex-range-sharded message CSR + vertex
      property arrays (replaces Spark DataFrames / GraphFrames).
- L3  graph ops: label propagation (``Graphframes.py:81``), connected
      components, degrees, community census (replaces the O(C*V*E) driver
      loops of ``Graphframes.py:100-118``), induced subgraphs, kNN + LOF
      outlier scoring (the intended capability of ``Graphframes.py:121-137``).
- L4  pipeline driver with a plugin boundary (backend=jax|graphframes).
      See :mod:`graphmine_tpu.pipeline`.
"""

__version__ = "0.1.0"

from graphmine_tpu.graph.container import Graph, build_graph
from graphmine_tpu.frames import GraphFrame
from graphmine_tpu.io.edges import load_parquet_edges, load_edge_list
from graphmine_tpu.ops.lpa import label_propagation
from graphmine_tpu.ops.cc import connected_components
from graphmine_tpu.ops.louvain import leiden, louvain
from graphmine_tpu.ops.modularity import modularity
from graphmine_tpu.ops.pagerank import pagerank, parallel_personalized_pagerank
from graphmine_tpu.ops.degrees import degrees, in_degrees, out_degrees, out_weights
from graphmine_tpu.ops.paths import (
    bfs,
    bfs_distances,
    bfs_parents,
    shortest_paths,
    weighted_shortest_paths,
)
from graphmine_tpu.ops.cluster_metrics import adjusted_rand_index, normalized_mutual_info
from graphmine_tpu.ops.scc import strongly_connected_components
from graphmine_tpu.ops.aggregate import aggregate_messages, pregel
from graphmine_tpu.ops.motifs import find as find_motifs
from graphmine_tpu.ops.streaming_lof import StreamingLOF, fit_lof, score_lof
from graphmine_tpu.ops.features import (
    standardize,
    vertex_features,
    vertex_features_host,
)
from graphmine_tpu.ops.ann import ivf_knn, kmeans
from graphmine_tpu.ops.knn import knn
from graphmine_tpu.ops.lof import lof_scores, select_lof_impl
from graphmine_tpu.ops.outliers import (
    masked_label_propagation,
    recursive_lpa_outliers,
    recursive_lpa_outliers_sharded,
)
from graphmine_tpu.ops.triangles import (
    triangle_count,
    clustering_coefficient,
    sampled_clustering_coefficient,
)
from graphmine_tpu.ops.kcore import core_numbers
from graphmine_tpu.ops.mis import greedy_color, maximal_independent_set
from graphmine_tpu.ops.linkpred import link_prediction
from graphmine_tpu.ops.ktruss import k_truss
from graphmine_tpu.ops.embedding import spectral_embedding
from graphmine_tpu.ops.stats import degree_assortativity, density, diameter, reciprocity
from graphmine_tpu.ops.centrality import (
    betweenness_centrality,
    closeness_centrality,
    eigenvector_centrality,
    hits,
    katz_centrality,
)
from graphmine_tpu import datasets
from graphmine_tpu.table import Table, read_parquet
from graphmine_tpu.ops.svdpp import svd_plus_plus, svdpp_predict
from graphmine_tpu.interop import from_networkx, graph_from_networkx, to_networkx
from graphmine_tpu.oracle import graphx_label_propagation
from graphmine_tpu.ops.blocking import (
    BlockedPlan,
    blocked_inflow,
    build_graph_and_blocked_plan,
    cc_superstep_blocked,
    lpa_superstep_blocked,
    select_superstep_family,
)
from graphmine_tpu.pipeline.planner import (
    LofPlan,
    PlanError,
    RunPlan,
    SuperstepPlan,
    plan_lof,
    plan_run,
    plan_superstep,
)

__all__ = [
    "graphx_label_propagation",
    "plan_run",
    "plan_lof",
    "plan_superstep",
    "RunPlan",
    "LofPlan",
    "SuperstepPlan",
    "PlanError",
    "BlockedPlan",
    "blocked_inflow",
    "build_graph_and_blocked_plan",
    "cc_superstep_blocked",
    "lpa_superstep_blocked",
    "select_superstep_family",
    "select_lof_impl",
    "vertex_features_host",
    "Graph",
    "GraphFrame",
    "build_graph",
    "load_parquet_edges",
    "load_edge_list",
    "label_propagation",
    "connected_components",
    "louvain",
    "leiden",
    "modularity",
    "pagerank",
    "parallel_personalized_pagerank",
    "svd_plus_plus",
    "svdpp_predict",
    "degrees",
    "in_degrees",
    "out_degrees",
    "bfs",
    "bfs_distances",
    "bfs_parents",
    "shortest_paths",
    "weighted_shortest_paths",
    "adjusted_rand_index",
    "normalized_mutual_info",
    "strongly_connected_components",
    "aggregate_messages",
    "pregel",
    "find_motifs",
    "StreamingLOF",
    "fit_lof",
    "standardize",
    "vertex_features",
    "ivf_knn",
    "kmeans",
    "knn",
    "lof_scores",
    "score_lof",
    "triangle_count",
    "clustering_coefficient",
    "sampled_clustering_coefficient",
    "masked_label_propagation",
    "recursive_lpa_outliers",
    "recursive_lpa_outliers_sharded",
    "core_numbers",
    "maximal_independent_set",
    "greedy_color",
    "link_prediction",
    "k_truss",
    "spectral_embedding",
    "degree_assortativity",
    "density",
    "diameter",
    "reciprocity",
    "hits",
    "closeness_centrality",
    "betweenness_centrality",
    "eigenvector_centrality",
    "katz_centrality",
    "datasets",
    "Table",
    "read_parquet",
    "to_networkx",
    "from_networkx",
    "graph_from_networkx",
    "__version__",
]
