"""Test-support utilities shipped with the package (deterministic fault
injection, corruption helpers). Production modules never import from here;
the coupling runs one way, through
:func:`graphmine_tpu.pipeline.resilience.set_fault_hook`."""
