"""Deterministic fault injection — every recovery path exercised on CPU.

A :class:`FaultInjector` installs into the
:func:`graphmine_tpu.pipeline.resilience.fault_point` seam and raises a
planned error the Nth time a named site is hit::

    inj = FaultInjector()
    inj.add("lpa_superstep", transient_error, at=2)      # 2nd superstep
    inj.add("lpa_superstep", oom_error, at=4, repeat=2)  # 4th AND 5th hit
    with inj.installed():
        run_pipeline(cfg)
    assert inj.fired("lpa_superstep") == 1

Sites currently instrumented in the driver: ``load``, ``build_graph``,
``lpa_superstep`` (ctx: ``iteration``), ``census``, ``outliers_recursive``,
``outliers_lof``.

The error factories below produce exceptions whose *messages* mimic real
XLA/PJRT runtime failures (``UNAVAILABLE: ...``, ``RESOURCE_EXHAUSTED:
...``), so the production classifier
(:func:`~graphmine_tpu.pipeline.resilience.classify_error`) is the code
under test — not a test double.

File corruptors (:func:`corrupt_file`, :func:`truncate_file`) damage
checkpoints/parquet bytes in place to exercise checksum rollback and
ingestion failure paths; :func:`corrupt_shard` / :func:`corrupt_manifest`
target one shard file / the manifest of a sharded-manifest checkpoint.

Device-level faults (ISSUE 2): :func:`device_loss` mimics a dead chip /
torn ICI link (classified DEGRADABLE_DEVICE — drives the elastic
mesh-degradation rungs), and :func:`poison_labels` is a ctx-aware
*mutator* that silently corrupts one shard of the driver's label state —
exercising the divergence tripwires, which must catch corruption that
announces nothing.

Fleet-level faults (ISSUE 9): :func:`replica_kill` /
:func:`replica_slow` / :func:`replica_stale` act on ONE in-process
replica of a serving fleet — a dead listener, a slow data plane behind a
live health probe, a version-pinned stale replica — the three failure
shapes the fleet router's state machine, circuit breakers and
committed-version routing exist to absorb (tests/test_fleet.py).

Durable-write-path faults (ISSUE 10): :func:`wal_torn_tail` tears the
write-ahead log's last frame (a kill mid-append — the open must keep
the intact prefix), :func:`writer_kill_mid_apply` is the SIGKILL-shaped
writer loss whose zombie publish the epoch fence must refuse, and
:func:`ship_lag` congests the standby's log shipping so the replication
lag gauges — and the promotion's loss-bound story — are testable
(tests/test_wal.py).

Sharded-write-plane faults (ISSUE 17): :func:`writer_shard_kill` kills
ONE vertex-range writer shard (its range flips read-only while the rest
keep accepting), and :func:`shard_publish_torn` crashes the epoch
coordinator between stage and commit — the torn two-phase publish whose
recovery must leave the previous epoch served
(tests/test_shardplane.py).
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass, field

from graphmine_tpu.pipeline import resilience


class InjectedTransientError(Exception):
    """Looks like transient device/RPC weather; classified retryable."""


class InjectedOOM(Exception):
    """Looks like device memory exhaustion; classified degradable."""


class SimulatedPreemption(Exception):
    """A preempted worker: the process dies mid-run. Fatal by contract —
    recovery is a NEW process resuming from the checkpoint, not a retry."""

    graphmine_error_class = resilience.FATAL


class InjectedHang(Exception):
    """Marker used via :func:`hang` (sleeps, never raises)."""


class InjectedDeviceLoss(Exception):
    """Looks like a dead chip / torn ICI link; classified
    DEGRADABLE_DEVICE — the elastic mesh-degradation rungs respond."""


def transient_error() -> Exception:
    return InjectedTransientError(
        "UNAVAILABLE: socket closed; failed to connect to remote runtime "
        "(injected fault)"
    )


def oom_error() -> Exception:
    return InjectedOOM(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "9437184000 bytes (injected fault)"
    )


def preemption() -> Exception:
    return SimulatedPreemption("worker preempted (injected fault)")


def device_loss(chip: int = 2) -> Exception:
    """A device/ICI failure mid-collective — classified by MESSAGE through
    the real classifier (DATA_LOSS status + device-failure phrase), like
    the other factories: the production taxonomy is the code under test."""
    return InjectedDeviceLoss(
        f"DATA_LOSS: device failure on chip {chip}: ICI link down during "
        "all-gather (injected fault)"
    )


def poison_labels(shard: int, num_shards: int, value: int = -7):
    """A ctx-aware MUTATOR (not an error factory): silently corrupts the
    driver's in-memory label state — shard ``shard`` of a ``num_shards``
    split is overwritten with ``value`` (an out-of-vertex-range id, i.e. a
    wrapped gather index / torn collective) — and lets the superstep run.
    Nothing raises here: the point is exercising the divergence TRIPWIRES,
    which must catch the garbage the fault did NOT announce. Install at a
    site whose ctx carries ``state`` (the driver's ``lpa_superstep``)."""

    def _mutate(**ctx):
        import numpy as np

        state = ctx.get("state")
        if state is None or "labels" not in state:
            raise ValueError(
                "poison_labels needs a fault site whose ctx carries the "
                "driver's mutable state (lpa_superstep)"
            )
        labels = np.asarray(state["labels"]).copy()
        chunk = -(-len(labels) // num_shards)
        labels[shard * chunk: (shard + 1) * chunk] = value
        state["labels"] = labels
        return None  # no error raised — the corruption is silent

    _mutate.wants_ctx = True
    return _mutate


# Parked hang() sleepers, each waiting on its OWN event. A single shared
# event is unfixably racy for this: set()-then-clear() can put a notified
# sleeper back to sleep (Event.wait re-checks the flag), and swapping in a
# fresh event races sleepers that haven't sampled the global yet. With one
# event per sleeper, release simply sets every registered event — an event,
# once set, stays set for its owner.
_sleepers_lock = None  # threading.Lock, created lazily
_sleepers: list = []


def _release_abandoned_sleepers() -> None:
    """Wake every parked :func:`hang` sleeper (see ``_sleepers``)."""
    if _sleepers_lock is None:
        return
    with _sleepers_lock:
        for ev in _sleepers:
            ev.set()
        _sleepers.clear()


def _parked_sleep(seconds: float):
    """An interruptible ``seconds`` sleep registered with the abandoned-
    sleeper release (see ``_sleepers``) — shared by :func:`hang` and
    :func:`slow_repair`."""
    import threading

    global _sleepers_lock
    if _sleepers_lock is None:
        _sleepers_lock = threading.Lock()
    ev = threading.Event()
    with _sleepers_lock:
        _sleepers.append(ev)
    ev.wait(seconds)
    with _sleepers_lock:
        if ev in _sleepers:
            _sleepers.remove(ev)


def hang(seconds: float):
    """Return a 'factory' that sleeps instead of raising — a hung device
    call for watchdog tests. The watchdog abandons the worker thread, so
    the sleep is interruptible: uninstalling the injector releases any
    abandoned sleepers (a process exiting right after the timeout must
    not race runtime teardown against a still-parked thread)."""

    def _sleep():
        _parked_sleep(seconds)
        return None

    _sleep.is_hang = True
    return _sleep


# ---- serve-side injectors (ISSUE 8: write-path overload chaos) -------------


def slow_repair(seconds: float):
    """A slowed delta repair: install at the ``delta_repair`` seam
    (``serve/delta.py::_verify_or_fallback``) with ``repeat=`` covering
    the burst, and every apply stalls ``seconds`` before verifying —
    the deterministic stand-in for a repair that outgrew its working
    set. Unlike :func:`hang` it is the APPLY PATH that slows, so queued
    deltas pile up behind the publish worker and the admission ladder
    (coalesce → defer → shed) is what keeps the backlog bounded. The
    sleep is interruptible on injector uninstall, and the repaired
    state passes through untouched (``wants_ctx`` so the ctx-carrying
    seam doesn't hand a positional payload to a plain factory)."""

    def _stall(**ctx):
        _parked_sleep(seconds)
        return None

    _stall.wants_ctx = True
    _stall.is_slow_repair = True
    return _stall


def delta_burst(
    num_vertices: int,
    batches: int,
    rows_per_batch: int,
    seed: int = 0,
    delete_frac: float = 0.0,
    base_src=None,
    base_dst=None,
):
    """Deterministic write-burst generator: ``batches`` POST /delta
    payload dicts of ``rows_per_batch`` rows each, drawn from a seeded
    RNG so a chaos test's admission verdicts replay identically.
    ``delete_frac`` of each batch's rows are deletes sampled from
    ``base_src``/``base_dst`` (matching deletes) when given, else from
    the id space (mostly-unmatched deletes — the quarantine path)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    n_del = int(rows_per_batch * delete_frac)
    n_ins = rows_per_batch - n_del
    payloads = []
    for _ in range(batches):
        ins = rng.integers(0, num_vertices, size=(n_ins, 2))
        payload = {"insert": ins.tolist()}
        if n_del:
            if base_src is not None and len(base_src):
                idx = rng.integers(0, len(base_src), n_del)
                payload["delete"] = [
                    [int(base_src[i]), int(base_dst[i])] for i in idx
                ]
            else:
                payload["delete"] = rng.integers(
                    0, num_vertices, size=(n_del, 2)
                ).tolist()
        payloads.append(payload)
    return payloads


def noisy_neighbor_burst(
    tenant: str,
    num_vertices: int,
    batches: int,
    rows_per_batch: int,
    seed: int = 0,
    stall_s: float = 0.0,
):
    """The multi-tenant abuse kit (ISSUE 16): one tenant hammering a
    shared server while its co-tenants must stay within SLO. Returns
    ``(payloads, staller)``:

    - ``payloads``: a :func:`delta_burst` aimed at ``tenant`` (POST each
      with ``X-Tenant-Id: <tenant>``);
    - ``staller``: a ``delta_repair``-seam injector that stalls
      ``stall_s`` **only when the apply belongs to** ``tenant`` — the
      ctx's ``tenant`` key, threaded from the ingestor's store — so the
      abusive tenant's applies become expensive while B's and C's stay
      fast. ``None`` when ``stall_s`` is 0 (pure volume abuse).

    Install the staller with ``repeat=`` covering the burst; the
    acceptance test (tests/test_tenancy.py) asserts from live endpoints
    that the victims' reads hold p99, their deltas keep publishing with
    zero sheds charged to the abuser's debt, and only the abuser's
    alert plane fires."""
    payloads = delta_burst(
        num_vertices, batches, rows_per_batch, seed=seed,
    )

    staller = None
    if stall_s > 0:

        def _tenant_stall(**ctx):
            if ctx.get("tenant") == tenant:
                _parked_sleep(stall_s)
            return None

        _tenant_stall.wants_ctx = True
        _tenant_stall.is_slow_repair = True
        staller = _tenant_stall
    return payloads, staller


def slow_client_post(
    host: str,
    port: int,
    path: str,
    payload: dict,
    chunk_bytes: int = 8,
    delay_s: float = 0.01,
    timeout_s: float = 30.0,
):
    """POST ``payload`` dribbling the body ``chunk_bytes`` at a time with
    ``delay_s`` between writes — the slow-loris-shaped client a threaded
    server must tolerate without stalling OTHER requests (each handler
    thread blocks only on its own socket). Returns
    ``(status_code, parsed_json_body)``."""
    import json as _json
    import socket

    body = _json.dumps(payload).encode()
    head = (
        f"POST {path} HTTP/1.1\r\nHost: {host}:{port}\r\n"
        f"Content-Type: application/json\r\n"
        f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
    ).encode()
    with socket.create_connection((host, port), timeout=timeout_s) as sock:
        sock.sendall(head)
        for i in range(0, len(body), chunk_bytes):
            sock.sendall(body[i: i + chunk_bytes])
            if delay_s:
                import time as _time

                _time.sleep(delay_s)
        raw = b""
        while True:
            got = sock.recv(65536)
            if not got:
                break
            raw += got
    status_line, _, rest = raw.partition(b"\r\n")
    status = int(status_line.split()[1])
    _, _, resp_body = rest.partition(b"\r\n\r\n")
    return status, _json.loads(resp_body.decode())


# ---- fleet-level injectors (ISSUE 9: replicated serving chaos) -------------
#
# These act on ONE in-process replica (a serve.server.SnapshotServer),
# not the global fault_point seam — a 3-replica fleet chaos test must be
# able to kill one replica, slow another, and leave the third healthy
# inside one process. The seams they drive (chaos_delay_s /
# chaos_hold_version, per-instance attributes the production middleware
# and reload() consult) are the serve-side analog of the resilience
# fault hook: zero-cost no-ops in production, deterministic handles in
# chaos tests.


def replica_kill(server) -> None:
    """Hard-kill a replica's HTTP listener in place — every subsequent
    connection is refused, exactly what the fleet router sees when a
    replica process dies. Unlike ``SnapshotServer.stop()`` there is no
    graceful queue drain: the 'process' just stops answering. The
    Python object survives, so the test can still inspect its state;
    'restarting the replica' is constructing a fresh SnapshotServer on
    the same port (ThreadingHTTPServer sets SO_REUSEADDR)."""
    httpd = server._httpd
    server._httpd = None
    if httpd is not None:
        httpd.shutdown()
        httpd.server_close()
    t = server._thread
    server._thread = None
    if t is not None:
        t.join(timeout=10)


def replica_slow(server, seconds: float) -> None:
    """Slow ONE replica: every request (including its /healthz) stalls
    ``seconds`` before handling. With the fleet's generous probe timeout
    the replica stays alive-and-healthy while its data-plane latency
    blows the router's per-attempt read timeout — the exact shape that
    must open the per-replica circuit breaker rather than mark the
    replica down. ``replica_slow(server, 0.0)`` heals it."""
    server.chaos_delay_s = float(seconds)


def wal_torn_tail(wal_root: str, cut_bytes: int = 7) -> str:
    """Tear the tail of the newest write-ahead-log segment in place —
    the bytes a kill mid-append leaves behind (a frame whose payload
    never finished). ``cut_bytes`` lands inside the final record's
    payload, so the sha256 (or the length) can no longer verify; the
    next :class:`~graphmine_tpu.serve.wal.WriteAheadLog` open must keep
    every record BEFORE the tear, truncate it, and keep appending —
    never refuse the whole log. Returns the damaged segment path."""
    import glob as _glob

    segs = sorted(_glob.glob(os.path.join(wal_root, "wal-*.seg")))
    if not segs:
        raise ValueError(f"no WAL segments under {wal_root!r} to tear")
    path = segs[-1]
    size = os.path.getsize(path)
    keep = max(8, size - max(1, cut_bytes))  # never cut into the magic
    with open(path, "r+b") as f:
        f.truncate(keep)
    return path


def writer_kill_mid_apply(server) -> None:
    """SIGKILL-shaped writer loss for an in-process chaos test: the
    HTTP listener dies instantly (every later connection refused — what
    the fleet prober sees when the writer process is killed) while the
    apply worker is left RUNNING with whatever it already popped — the
    zombie half of a killed writer. That zombie's eventual publish is
    exactly the deposed-writer comeback the store's epoch fence must
    refuse once the standby is promoted (``publish_fenced``); its
    WAL-durable queue survives on disk for the promotion replay. (A
    real SIGKILL also stops the worker — in-process we cannot kill a
    thread, and leaving it grinding makes the test STRICTER: the fence,
    not process death, is what protects the store.)"""
    replica_kill(server)


def ship_lag(server_or_shipper, seconds: float) -> None:
    """Slow ONE standby's log shipping: every poll of the primary's
    /wal stalls ``seconds`` first — the deterministic stand-in for a
    congested replication link. The standby stays healthy and serving
    reads while its replication lag (the /healthz gauge pair) grows;
    ``ship_lag(x, 0.0)`` heals. Accepts a SnapshotServer (standby) or a
    LogShipper."""
    shipper = getattr(server_or_shipper, "_shipper", server_or_shipper)
    if shipper is None or not hasattr(shipper, "chaos_delay_s"):
        raise ValueError(
            "ship_lag needs a standby server (standby_of=...) or a "
            "LogShipper"
        )
    shipper.chaos_delay_s = float(seconds)


def writer_shard_kill(server, shard: int, tenant: str = "default") -> None:
    """Kill ONE vertex-range writer shard of a sharded write plane
    (r17, serve/shardplane.py): the shard's WAL handle closes
    un-flushed and its range flips read-only — batches touching it
    refuse 503 while every OTHER range keeps accepting writes. The
    restart is ``plane.restart_shard(shard)`` (per-range WAL replay;
    acked sub-batches survive by append-time fsync) or a standby
    promotion via ``plane.promote_shard``. Acts on an in-process
    SnapshotServer started with ``writer_shards > 1``."""
    ts = server._tenants.get(tenant)
    plane = getattr(ts, "plane", None) if ts is not None else None
    if plane is None:
        raise ValueError(
            f"writer_shard_kill needs a server running with "
            f"writer_shards > 1 (tenant {tenant!r} has no shard plane)"
        )
    plane.kill_shard(int(shard), reason="writer_shard_kill")


def shard_publish_torn(at: int = 1, repeat: int = 1) -> FaultInjector:
    """A coordinator crash BETWEEN stage and commit (r17): every shard's
    per-range arrays are staged, the ``publish_epoch`` record is never
    written. Returns a ready-to-install :class:`FaultInjector` targeting
    the ``shard_publish_commit`` seam (inside the store's fence lock,
    before the stage→final rename). The recovery contract: readers keep
    serving the PREVIOUS committed epoch, and the next startup's
    ``EpochCoordinator.recover()`` finishes the staged generation (or
    sweeps an incomplete one) — never a half-visible epoch."""
    inj = FaultInjector()
    inj.add("shard_publish_commit", preemption, at=at, repeat=repeat)
    return inj


def replica_stale(server, hold: bool = True) -> None:
    """Pin ONE replica to its current snapshot version: /reload becomes
    a no-op (``swapped: false, held: true``), so the replica falls
    behind every publish — the stale replica the committed-version rule
    must keep out of the read path without ever surfacing a
    mixed-version answer. ``replica_stale(server, False)`` releases."""
    server.chaos_hold_version = bool(hold)


@dataclass
class _Rule:
    site: str
    factory: object          # () -> Exception, or a hang() sleeper
    at: int                  # 1-based hit index at which to fire
    repeat: int = 1          # fire on this many consecutive hits
    fired: int = 0


@dataclass
class FaultInjector:
    """Deterministic site/hit-count fault plan (see module docstring)."""

    rules: list = field(default_factory=list)
    hits: dict = field(default_factory=dict)
    log: list = field(default_factory=list)  # (site, hit, ctx) of every hit

    def add(self, site: str, factory, at: int = 1, repeat: int = 1) -> "FaultInjector":
        if at < 1 or repeat < 1:
            raise ValueError("at and repeat are 1-based positive counts")
        self.rules.append(_Rule(site=site, factory=factory, at=at, repeat=repeat))
        return self

    def fired(self, site: str | None = None) -> int:
        return sum(
            r.fired for r in self.rules if site is None or r.site == site
        )

    def __call__(self, site: str, **ctx) -> None:
        n = self.hits[site] = self.hits.get(site, 0) + 1
        self.log.append((site, n, ctx))
        for r in self.rules:
            if r.site == site and r.at <= n < r.at + r.repeat:
                r.fired += 1
                # ctx-aware mutators (poison_labels) corrupt state in
                # place instead of raising; plain factories get no ctx.
                if getattr(r.factory, "wants_ctx", False):
                    out = r.factory(**ctx)
                else:
                    out = r.factory()
                if out is not None:  # hang()/mutators return None
                    raise out

    @contextlib.contextmanager
    def installed(self):
        """Install into the resilience seam for the duration of the block.
        Not reentrant; one injector at a time per process."""
        resilience.set_fault_hook(self)
        try:
            yield self
        finally:
            resilience.set_fault_hook(None)
            _release_abandoned_sleepers()


def corrupt_file(path: str, offset: int = -64, nbytes: int = 16) -> None:
    """Flip ``nbytes`` bytes in place at ``offset`` (negative = from EOF).
    Defaults land inside the last zip member of a small ``.npz``, tripping
    its CRC and the checkpoint checksum."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path!r} is empty; nothing to corrupt")
    pos = offset % size
    nbytes = min(nbytes, size - pos)
    with open(path, "r+b") as f:
        f.seek(pos)
        chunk = f.read(nbytes)
        f.seek(pos)
        f.write(bytes(b ^ 0xFF for b in chunk))


def truncate_file(path: str, keep_fraction: float = 0.5) -> None:
    """Truncate a file to ``keep_fraction`` of its bytes (a partially
    written / torn parquet part or checkpoint)."""
    if not 0 <= keep_fraction < 1:
        raise ValueError("keep_fraction must be in [0, 1)")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(int(size * keep_fraction))


def corrupt_shard(checkpoint_dir: str, shard: int, tag: str = "lpa") -> str:
    """Flip bytes inside ONE shard file of the current sharded-checkpoint
    generation (manifest format, ``pipeline/checkpoint.py:save_sharded``)
    — the torn-multi-file case the per-shard sha256 exists for. Returns
    the damaged path."""
    from graphmine_tpu.pipeline import checkpoint as ckpt

    path = ckpt.shard_file(ckpt.sharded_dir(checkpoint_dir, tag), shard)
    corrupt_file(path)
    return path


def corrupt_manifest(checkpoint_dir: str, tag: str = "lpa") -> str:
    """Flip bytes inside the manifest of the current sharded-checkpoint
    generation (still-parseable JSON with a wrong checksum, or broken
    JSON, depending on where the flip lands — both must roll back).
    Returns the damaged path."""
    from graphmine_tpu.pipeline import checkpoint as ckpt

    path = os.path.join(
        ckpt.sharded_dir(checkpoint_dir, tag), ckpt.MANIFEST_NAME
    )
    corrupt_file(path)
    return path
