"""Deterministic fault injection — every recovery path exercised on CPU.

A :class:`FaultInjector` installs into the
:func:`graphmine_tpu.pipeline.resilience.fault_point` seam and raises a
planned error the Nth time a named site is hit::

    inj = FaultInjector()
    inj.add("lpa_superstep", transient_error, at=2)      # 2nd superstep
    inj.add("lpa_superstep", oom_error, at=4, repeat=2)  # 4th AND 5th hit
    with inj.installed():
        run_pipeline(cfg)
    assert inj.fired("lpa_superstep") == 1

Sites currently instrumented in the driver: ``load``, ``build_graph``,
``lpa_superstep`` (ctx: ``iteration``), ``census``, ``outliers_recursive``,
``outliers_lof``.

The error factories below produce exceptions whose *messages* mimic real
XLA/PJRT runtime failures (``UNAVAILABLE: ...``, ``RESOURCE_EXHAUSTED:
...``), so the production classifier
(:func:`~graphmine_tpu.pipeline.resilience.classify_error`) is the code
under test — not a test double.

File corruptors (:func:`corrupt_file`, :func:`truncate_file`) damage
checkpoints/parquet bytes in place to exercise checksum rollback and
ingestion failure paths.
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass, field

from graphmine_tpu.pipeline import resilience


class InjectedTransientError(Exception):
    """Looks like transient device/RPC weather; classified retryable."""


class InjectedOOM(Exception):
    """Looks like device memory exhaustion; classified degradable."""


class SimulatedPreemption(Exception):
    """A preempted worker: the process dies mid-run. Fatal by contract —
    recovery is a NEW process resuming from the checkpoint, not a retry."""

    graphmine_error_class = resilience.FATAL


class InjectedHang(Exception):
    """Marker used via :func:`hang` (sleeps, never raises)."""


def transient_error() -> Exception:
    return InjectedTransientError(
        "UNAVAILABLE: socket closed; failed to connect to remote runtime "
        "(injected fault)"
    )


def oom_error() -> Exception:
    return InjectedOOM(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "9437184000 bytes (injected fault)"
    )


def preemption() -> Exception:
    return SimulatedPreemption("worker preempted (injected fault)")


# Parked hang() sleepers, each waiting on its OWN event. A single shared
# event is unfixably racy for this: set()-then-clear() can put a notified
# sleeper back to sleep (Event.wait re-checks the flag), and swapping in a
# fresh event races sleepers that haven't sampled the global yet. With one
# event per sleeper, release simply sets every registered event — an event,
# once set, stays set for its owner.
_sleepers_lock = None  # threading.Lock, created lazily
_sleepers: list = []


def _release_abandoned_sleepers() -> None:
    """Wake every parked :func:`hang` sleeper (see ``_sleepers``)."""
    if _sleepers_lock is None:
        return
    with _sleepers_lock:
        for ev in _sleepers:
            ev.set()
        _sleepers.clear()


def hang(seconds: float):
    """Return a 'factory' that sleeps instead of raising — a hung device
    call for watchdog tests. The watchdog abandons the worker thread, so
    the sleep is interruptible: uninstalling the injector releases any
    abandoned sleepers (a process exiting right after the timeout must
    not race runtime teardown against a still-parked thread)."""
    import threading

    global _sleepers_lock
    if _sleepers_lock is None:
        _sleepers_lock = threading.Lock()

    def _sleep():
        ev = threading.Event()
        with _sleepers_lock:
            _sleepers.append(ev)
        ev.wait(seconds)
        with _sleepers_lock:
            if ev in _sleepers:
                _sleepers.remove(ev)
        return None

    _sleep.is_hang = True
    return _sleep


@dataclass
class _Rule:
    site: str
    factory: object          # () -> Exception, or a hang() sleeper
    at: int                  # 1-based hit index at which to fire
    repeat: int = 1          # fire on this many consecutive hits
    fired: int = 0


@dataclass
class FaultInjector:
    """Deterministic site/hit-count fault plan (see module docstring)."""

    rules: list = field(default_factory=list)
    hits: dict = field(default_factory=dict)
    log: list = field(default_factory=list)  # (site, hit, ctx) of every hit

    def add(self, site: str, factory, at: int = 1, repeat: int = 1) -> "FaultInjector":
        if at < 1 or repeat < 1:
            raise ValueError("at and repeat are 1-based positive counts")
        self.rules.append(_Rule(site=site, factory=factory, at=at, repeat=repeat))
        return self

    def fired(self, site: str | None = None) -> int:
        return sum(
            r.fired for r in self.rules if site is None or r.site == site
        )

    def __call__(self, site: str, **ctx) -> None:
        n = self.hits[site] = self.hits.get(site, 0) + 1
        self.log.append((site, n, ctx))
        for r in self.rules:
            if r.site == site and r.at <= n < r.at + r.repeat:
                r.fired += 1
                out = r.factory()
                if out is not None:  # hang() sleepers return None
                    raise out

    @contextlib.contextmanager
    def installed(self):
        """Install into the resilience seam for the duration of the block.
        Not reentrant; one injector at a time per process."""
        resilience.set_fault_hook(self)
        try:
            yield self
        finally:
            resilience.set_fault_hook(None)
            _release_abandoned_sleepers()


def corrupt_file(path: str, offset: int = -64, nbytes: int = 16) -> None:
    """Flip ``nbytes`` bytes in place at ``offset`` (negative = from EOF).
    Defaults land inside the last zip member of a small ``.npz``, tripping
    its CRC and the checkpoint checksum."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path!r} is empty; nothing to corrupt")
    pos = offset % size
    nbytes = min(nbytes, size - pos)
    with open(path, "r+b") as f:
        f.seek(pos)
        chunk = f.read(nbytes)
        f.seek(pos)
        f.write(bytes(b ^ 0xFF for b in chunk))


def truncate_file(path: str, keep_fraction: float = 0.5) -> None:
    """Truncate a file to ``keep_fraction`` of its bytes (a partially
    written / torn parquet part or checkpoint)."""
    if not 0 <= keep_fraction < 1:
        raise ValueError("keep_fraction must be in [0, 1)")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(int(size * keep_fraction))
