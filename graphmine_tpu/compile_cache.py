"""Persistent XLA compile-cache setup, shared by the CLI and bench.

Superstep programs take minutes to compile on TPU at scale; caching them
makes repeat invocations near-instant (measured: the bundled-data
recursive-outlier phase drops 18.7s -> 0.25s on a warm cache).
"""

from __future__ import annotations

import os


def enable_compile_cache(default_dir: str | None = None) -> None:
    """Point jax at a persistent compile cache, respecting the operator.

    Precedence: JAX's own env vars (``JAX_COMPILATION_CACHE_DIR`` /
    ``JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS``) win untouched, then
    ``GRAPHMINE_COMPILE_CACHE``, then ``default_dir`` (``None`` =
    ``~/.cache/graphmine_tpu/xla``). ``GRAPHMINE_NO_COMPILE_CACHE=1``
    disables entirely.
    """
    if os.environ.get("GRAPHMINE_NO_COMPILE_CACHE") == "1":
        return
    import jax

    if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
        cache = (
            os.environ.get("GRAPHMINE_COMPILE_CACHE")
            or default_dir
            or os.path.expanduser("~/.cache/graphmine_tpu/xla")
        )
        jax.config.update("jax_compilation_cache_dir", cache)
    if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
