"""Edge-table ingestion: parquet (reference parity) and SNAP edge lists.

Reference parity surface (``CommunityDetection/Graphframes.py``):
- ``:16``  glob read of snappy parquet parts with 4 string cols ``_c0.._c3``
- ``:26-30`` rename to Parent/ParentDomain/ChildDomain/Child + null filter
  (note the reference maps ``_c2``→ChildDomain and ``_c3``→Child)
- ``:70-74`` edges are (ParentDomain → ChildDomain); duplicates are *kept*
  (LPA sees multiplicity).

Everything here is host-side (NumPy/pyarrow); the device sees only int32
index arrays.
"""

from __future__ import annotations

import glob as _glob
import os
from dataclasses import dataclass

import numpy as np

from graphmine_tpu.io.factorize import factorize


@dataclass
class EdgeTable:
    """Host-side edge table: dense int32 endpoints + vertex-name sidecar.

    The TPU-native replacement for the reference's
    (Graph_Vertices, Graph_Edges) DataFrame pair (``Graphframes.py:67-74``).
    """

    src: np.ndarray  # int32 [E] — ParentDomain index
    dst: np.ndarray  # int32 [E] — ChildDomain index
    names: np.ndarray  # str [V] — vertex id -> domain string
    num_rows_raw: int = 0  # rows before the null filter (Graphframes.py:18)
    weights: np.ndarray | None = None  # float32 [E] — optional edge weights
    # Input-quarantine counts (rows set aside instead of crashing
    # ingestion): keys among null_rows, bad_rows, nan_weights,
    # out_of_range_ids. None = the loader recorded no quarantine info.
    quarantine: dict | None = None

    @property
    def num_vertices(self) -> int:
        return len(self.names)

    @property
    def num_edges(self) -> int:
        return len(self.src)

    def distinct_edges(self) -> np.ndarray:
        """Distinct directed (src, dst) pairs, shape [E', 2]."""
        pairs = np.stack([self.src, self.dst], axis=1)
        return np.unique(pairs, axis=0)


def _from_string_columns(parent_dom: np.ndarray, child_dom: np.ndarray, num_rows_raw: int) -> EdgeTable:
    valid = ~(_isnull(parent_dom) | _isnull(child_dom))  # Graphframes.py:30
    parent_dom, child_dom = parent_dom[valid], child_dom[valid]
    (src, dst), names = factorize(parent_dom, child_dom)
    return EdgeTable(src=src, dst=dst, names=names, num_rows_raw=num_rows_raw)


def _isnull(col: np.ndarray) -> np.ndarray:
    if col.dtype == object:
        return np.frompyfunc(lambda v: v is None, 1, 1)(col).astype(bool)
    return np.zeros(len(col), dtype=bool)


def _add_quarantine(et: EdgeTable, key: str, count: int) -> EdgeTable:
    """Accumulate one quarantine counter onto the table (0 is recorded
    too once any quarantine accounting is active — tests read exact
    counts, not just presence)."""
    et.quarantine = {**(et.quarantine or {}), key: count + (et.quarantine or {}).get(key, 0)}
    return et


def quarantine_nonfinite_weights(et: EdgeTable) -> EdgeTable:
    """Drop edges whose weight is NaN/±inf, counting them as
    ``nan_weights``. A NaN weight would silently poison weighted LPA's
    argmax (NaN sums make every comparison false) — setting the edge
    aside with a counted record is the resilient behavior. No-op for
    unweighted tables."""
    if et.weights is None:
        return et
    bad = ~np.isfinite(et.weights)
    n = int(bad.sum())
    if n:
        keep = ~bad
        et.src, et.dst = et.src[keep], et.dst[keep]
        et.weights = et.weights[keep]
    return _add_quarantine(et, "nan_weights", n)


def edge_table_from_parts(
    src_parts, dst_parts, names, num_rows_raw, w_parts=None
) -> EdgeTable:
    """Assemble an EdgeTable from per-chunk/per-batch part lists — the one
    owner of the concat/empty-dtype/weights-or-None tail shared by every
    streaming ingestion path (parquet batches, native chunked parse,
    chunked NumPy fallback)."""
    cat = lambda parts, dt: (
        np.concatenate(parts) if parts else np.empty(0, dt)
    )
    return EdgeTable(
        src=cat(src_parts, np.int32),
        dst=cat(dst_parts, np.int32),
        names=np.asarray(names),
        num_rows_raw=num_rows_raw,
        weights=None if w_parts is None else cat(w_parts, np.float32),
    )


def _column_codes(col, interner):
    """Intern one Arrow column (Array or ChunkedArray) into dense int32
    codes via ``interner``, taking the dictionary-index fast path when the
    storage is dictionary-encoded.

    The fast path matters (r5): parquet string columns are typically
    PLAIN_DICTIONARY on disk (the reference's own Spark output is), and
    ``to_numpy`` materializes one Python str per ROW — measured ~300K
    rows/s, 84 s of a 196 s e2e pipeline at 25M rows. Interning the
    dictionary VALUES and remapping the int32 indices keeps the per-row
    work in numpy; first-appearance id-assignment order is identical by
    construction (an Arrow dictionary's values are unique), pinned
    byte-exact by ``tests/test_io.py``.

    Null safety (ADVICE r5): the loaders filter null rows BEFORE interning
    (the Graphframes.py:30 parity filter), but this function is also a
    standalone surface — nulls are dropped here too, so ``None`` can never
    be interned as a vertex id (``to_numpy`` on a nullable column yields
    Python ``None`` objects, which the per-row fallback would happily hash
    into the vocabulary). Callers that need row alignment across columns
    must still pre-filter; per-column dropping protects the id space, not
    the pairing.
    """
    import pyarrow as pa

    chunks = col.chunks if isinstance(col, pa.ChunkedArray) else [col]
    parts = []
    for c in chunks:
        if c.null_count:
            c = c.drop_null()
        if pa.types.is_dictionary(c.type):
            parts.append(interner.add_dictionary(
                np.asarray(c.indices),
                c.dictionary.to_numpy(zero_copy_only=False),
            ))
        else:
            parts.append(interner.add(c.to_numpy(zero_copy_only=False)))
    if not parts:
        # an all-null (or empty) column filters to a 0-chunk ChunkedArray
        return np.empty(0, np.int32)
    return (
        np.concatenate(parts) if len(parts) != 1 else parts[0]
    ).astype(np.int32, copy=False)


def load_parquet_edges(path: str, batch_rows: int | None = None) -> EdgeTable:
    """Read a parquet file/dir/glob of outlinks and build the edge table.

    Parity with ``Graphframes.py:16-30``: glob support, null-domain filter
    (done columnar via the Arrow validity mask, not per-row Python),
    edges = (ParentDomain, ChildDomain) with duplicates kept. Columns are
    read dictionary-encoded and interned via the index fast path
    (``_column_codes``) — same ids as the per-row string path, tested.

    ``batch_rows``: stream the files in batches of at most this many rows
    through an incremental interner instead of materializing every string
    column at once — the working capability behind the reference's
    abandoned driver-memory "data slicer" (``Graphframes.py:34-47``).
    Same graph, null filter, and duplicate semantics as the bulk path
    (tested); vertex ids are assigned in per-batch first-appearance order,
    so raw id values differ from the bulk path. Names and name-keyed edges
    (with multiplicity) are identical; LPA partitions can differ on mode
    *ties*, whose smallest-label rule reads the id assignment.
    """
    if batch_rows is not None:
        return _load_parquet_edges_streaming(path, batch_rows)
    import pyarrow as pa
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    from graphmine_tpu.io.factorize import IncrementalFactorizer

    paths = _resolve_paths(path)
    tables = [
        pq.read_table(p, columns=["_c1", "_c2"],
                      read_dictionary=["_c1", "_c2"])
        for p in paths
    ]
    try:
        table = pa.concat_tables(tables, promote_options="permissive")
    except TypeError:
        # pyarrow < 14 has no promote_options; promote=True is the same
        # permissive schema unification there (ADVICE r5: don't fail a
        # previously-working path on older environments)
        table = pa.concat_tables(tables, promote=True)
    num_rows_raw = table.num_rows
    valid = pc.and_(pc.is_valid(table.column("_c1")), pc.is_valid(table.column("_c2")))
    table = table.filter(valid)  # Graphframes.py:30 null-domain filter
    # The interner applied parent-column-first reproduces factorize()'s
    # first-appearance order over concat(parent, child) exactly.
    interner = IncrementalFactorizer()
    src = _column_codes(table.column("_c1"), interner)
    dst = _column_codes(table.column("_c2"), interner)
    et = EdgeTable(
        src=src, dst=dst, names=interner.names(), num_rows_raw=num_rows_raw
    )
    # the null filter IS a quarantine: rows set aside, counted, not fatal
    return _add_quarantine(et, "null_rows", num_rows_raw - table.num_rows)


def _load_parquet_edges_streaming(path: str, batch_rows: int) -> EdgeTable:
    """Batched parquet scan + incremental intern; peak host memory is
    O(batch + vocabulary + edges) instead of O(total rows x string size)."""
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    from graphmine_tpu.io.factorize import IncrementalFactorizer

    if batch_rows <= 0:
        raise ValueError(f"batch_rows must be positive, got {batch_rows}")
    interner = IncrementalFactorizer()
    src_parts, dst_parts = [], []
    num_rows_raw = 0
    for p in _resolve_paths(path):
        pf = pq.ParquetFile(p, read_dictionary=["_c1", "_c2"])
        for batch in pf.iter_batches(batch_size=batch_rows, columns=["_c1", "_c2"]):
            num_rows_raw += batch.num_rows
            valid = pc.and_(
                pc.is_valid(batch.column(0)), pc.is_valid(batch.column(1))
            )
            batch = batch.filter(valid)  # Graphframes.py:30 null filter
            # dictionary-index interning per column (the r5 fast path;
            # falls back to per-row strings for non-dict storage)
            src_parts.append(_column_codes(batch.column(0), interner))
            dst_parts.append(_column_codes(batch.column(1), interner))
    et = edge_table_from_parts(
        src_parts, dst_parts, interner.names(), num_rows_raw
    )
    return _add_quarantine(et, "null_rows", num_rows_raw - et.num_edges)


def _resolve_paths(path: str) -> list[str]:
    if os.path.isdir(path):
        paths = sorted(_glob.glob(os.path.join(path, "*.parquet")))
    else:
        paths = sorted(_glob.glob(path)) or [path]
    if not paths:
        raise FileNotFoundError(f"no parquet files at {path!r}")
    return paths


def iter_line_chunks(path: str, chunk_bytes: int):
    """Yield newline-aligned byte buffers of ~``chunk_bytes`` covering the
    file; the trailing newline-less line (if any) is yielded last. The one
    owner of the carry/boundary logic for both streaming edge-list paths
    (native chunked parse and the NumPy fallback)."""
    with open(path, "rb") as f:
        carry = b""
        while True:
            block = f.read(chunk_bytes)
            if not block:
                if carry:
                    yield carry
                return
            buf = carry + block
            nl = buf.rfind(b"\n")
            if nl < 0:
                carry = buf
                if len(carry) > (1 << 30):
                    raise ValueError(
                        f"no newline in the first GiB of {path!r}; "
                        "not a line-oriented edge list"
                    )
                continue
            carry = buf[nl + 1:]
            yield buf[:nl + 1]


# Above this file size the NumPy fallback streams in bounded chunks
# instead of materializing every row as Python strings (the r2
# np.loadtxt(dtype=str) host-RAM wall, VERDICT weak 5). The native path
# always streams.
_AUTO_STREAM_BYTES = 256 << 20
_DEFAULT_CHUNK_BYTES = 64 << 20


def load_edge_list(path: str, comments: str = "#", use_native: bool = True,
                   weight_col: int | None = None,
                   chunk_bytes: int | None = None,
                   quarantine: bool = False) -> EdgeTable:
    """Load a SNAP-style whitespace edge list (``src dst [weight ...]``).

    IDs may be arbitrary integers or strings; they are densified to int32.
    Ingestion STREAMS (r3): the native C++ parser
    (:mod:`graphmine_tpu.io.native`) feeds bounded chunks through one
    shared interner — peak host memory is O(chunk + vocabulary + edges),
    symmetric to parquet's ``batch_rows`` — so a top-rung file
    (Twitter-2010, 1.4B edges) ingests without a host-RAM wall, weighted
    or not. Without the library, small files take the NumPy bulk path and
    large ones (> 256 MB) a chunked NumPy fallback with the same bound.

    ``weight_col``: 0-based column index holding a per-edge float weight
    (the common 3-column weighted edge-list format uses ``weight_col=2``);
    weights feed weighted LPA via ``graph_from_edge_table``.
    ``chunk_bytes``: override the 64 MB streaming chunk size.

    ``quarantine``: resilient-ingestion mode (the pipeline default via
    ``PipelineConfig.quarantine_inputs``). Rows that would crash the
    strict parsers — too few columns, unparseable weight fields — and
    edges with non-finite weights are counted and set aside on
    ``EdgeTable.quarantine`` instead of raising. Clean files still take
    the fast strict paths (native/NumPy); the tolerant per-line parser
    only engages when a strict parse fails, so the resilient mode costs
    nothing on well-formed data.
    """
    if weight_col is not None and weight_col < 2:
        raise ValueError(
            f"weight_col={weight_col} invalid: columns 0-1 are the endpoints"
        )
    if quarantine:
        try:
            et = load_edge_list(
                path, comments=comments, use_native=use_native,
                weight_col=weight_col, chunk_bytes=chunk_bytes,
            )
            _add_quarantine(et, "bad_rows", 0)
        except ValueError as strict_err:
            # strict parse failed (ragged rows / bad weight fields):
            # re-ingest tolerantly, quarantining the offending rows
            et = _load_edge_list_tolerant(
                path, comments, weight_col,
                chunk_bytes or _DEFAULT_CHUNK_BYTES,
            )
            if et.num_rows_raw and (
                et.quarantine.get("bad_rows") == et.num_rows_raw
            ):
                # EVERY data row set aside: the file and the config
                # disagree wholesale (e.g. a mistyped weight_col on a
                # clean file) — an empty graph would hide the error
                raise ValueError(
                    f"every data row of {path!r} failed to parse under "
                    "the current options — this is a misconfiguration "
                    "(e.g. wrong weight_col), not dirty data"
                ) from strict_err
        return quarantine_nonfinite_weights(et)
    if use_native:
        from graphmine_tpu.io import native

        et = native.load_edge_list_chunked(
            path, comments=comments, weight_col=weight_col,
            chunk_bytes=chunk_bytes or _DEFAULT_CHUNK_BYTES,
        )
        if et is not None:
            return et
        if weight_col is None and chunk_bytes is None:
            # stale .so without the chunk API still serves unweighted loads
            et = native.load_edge_list_native(path, comments=comments)
            if et is not None:
                return et
    big = (
        os.path.exists(path)
        and os.path.getsize(path) > _AUTO_STREAM_BYTES
    )
    if chunk_bytes is not None or big:
        return _load_edge_list_numpy_chunked(
            path, comments, weight_col, chunk_bytes or _DEFAULT_CHUNK_BYTES
        )
    raw = np.loadtxt(path, comments=comments, dtype=str, ndmin=2)
    if len(raw) == 0:
        # no data rows (comment/blank-only file): an empty table, matching
        # the streaming paths (which cannot distinguish this from EOF)
        return edge_table_from_parts(
            [], [], np.empty(0, dtype=object), 0,
            [] if weight_col is not None else None,
        )
    if raw.shape[1] < 2:
        raise ValueError(f"edge list {path!r} needs >= 2 columns")
    weights = None
    if weight_col is not None:
        if weight_col >= raw.shape[1]:
            raise ValueError(
                f"weight_col={weight_col} out of range for a "
                f"{raw.shape[1]}-column edge list (and columns 0-1 are the "
                "endpoints)"
            )
        weights = raw[:, weight_col].astype(np.float32)
    (src, dst), names = factorize(raw[:, 0], raw[:, 1])
    return EdgeTable(src=src, dst=dst, names=names, num_rows_raw=len(raw),
                     weights=weights)


def _load_edge_list_numpy_chunked(
    path: str, comments: str, weight_col: int | None, chunk_bytes: int
) -> EdgeTable:
    """Pure-NumPy streaming fallback: newline-aligned chunks through an
    IncrementalFactorizer. Same ids/weights as the native streaming path
    (tested); peak memory is O(chunk + vocabulary + edges)."""
    import io as _io

    from graphmine_tpu.io.factorize import IncrementalFactorizer

    interner = IncrementalFactorizer()
    src_parts, dst_parts, w_parts = [], [], []
    num_rows = 0
    ncols = None
    for buf in iter_line_chunks(path, chunk_bytes):
        if not buf.strip():
            continue
        raw = np.loadtxt(
            _io.BytesIO(buf), comments=comments, dtype=str, ndmin=2
        )
        if not raw.size:
            continue
        if raw.shape[1] < 2:
            raise ValueError(f"edge list {path!r} needs >= 2 columns")
        # loadtxt enforces rectangularity only WITHIN a chunk; a file
        # whose column count changes across a chunk boundary must fail
        # the same as the bulk path (code-review r4)
        if ncols is None:
            ncols = raw.shape[1]
        elif raw.shape[1] != ncols:
            raise ValueError(
                f"edge list {path!r}: number of columns changed "
                "between data lines"
            )
        num_rows += len(raw)
        src_parts.append(interner.add(raw[:, 0]))
        dst_parts.append(interner.add(raw[:, 1]))
        if weight_col is not None:
            if weight_col >= raw.shape[1]:
                raise ValueError(
                    f"weight_col={weight_col} out of range for "
                    f"a {raw.shape[1]}-column edge list"
                )
            w_parts.append(raw[:, weight_col].astype(np.float32))
    return edge_table_from_parts(
        src_parts, dst_parts, interner.names(), num_rows,
        w_parts if weight_col is not None else None,
    )


def _load_edge_list_tolerant(
    path: str, comments: str, weight_col: int | None,
    chunk_bytes: int = _DEFAULT_CHUNK_BYTES,
) -> EdgeTable:
    """Per-line parser that QUARANTINES malformed rows instead of raising.

    Only reached when a strict parse has already failed (see
    ``load_edge_list(quarantine=True)``): rows with fewer than the
    required columns or unparseable weight fields are counted as
    ``bad_rows`` and set aside; every well-formed row ingests with the
    same interning/id-assignment as the streaming paths. Memory bound is
    the usual O(chunk + vocabulary + edges).
    """
    from graphmine_tpu.io.factorize import IncrementalFactorizer

    interner = IncrementalFactorizer()
    cmt = comments.encode() if comments else None
    need = 2 if weight_col is None else weight_col + 1
    src_parts, dst_parts, w_parts = [], [], []
    num_rows = 0
    bad_rows = 0
    for buf in iter_line_chunks(path, chunk_bytes):
        src_l, dst_l, w_l = [], [], []
        for line in buf.splitlines():
            line = line.strip()
            if not line or (cmt and line.startswith(cmt)):
                continue
            num_rows += 1
            parts = line.split()
            if len(parts) < need:
                bad_rows += 1
                continue
            if weight_col is not None:
                try:
                    w_l.append(float(parts[weight_col]))
                except ValueError:
                    bad_rows += 1
                    continue
            # backslashreplace, not replace: distinct invalid byte
            # sequences must stay distinct vertex ids ('a\xff' and
            # 'a\xfe' both map to 'a�' under replace, silently
            # coalescing two vertices into one)
            src_l.append(parts[0].decode("utf-8", "backslashreplace"))
            dst_l.append(parts[1].decode("utf-8", "backslashreplace"))
        if src_l:
            src_parts.append(interner.add(np.asarray(src_l, dtype=object)))
            dst_parts.append(interner.add(np.asarray(dst_l, dtype=object)))
            if weight_col is not None:
                w_parts.append(np.asarray(w_l, dtype=np.float32))
    et = edge_table_from_parts(
        src_parts, dst_parts, interner.names(), num_rows,
        w_parts if weight_col is not None else None,
    )
    return _add_quarantine(et, "bad_rows", bad_rows)


def from_arrays(src, dst, names=None, quarantine: bool = False) -> EdgeTable:
    """Build an EdgeTable from pre-densified integer endpoint arrays.

    ``quarantine``: drop edges whose endpoints are negative or (when
    ``names`` is given) dangle past the vertex table, counting them as
    ``out_of_range_ids`` — such ids would otherwise wrap or fail deep in
    graph assembly."""
    src = np.asarray(src, dtype=np.int32)
    dst = np.asarray(dst, dtype=np.int32)
    dropped = 0
    if quarantine and len(src):
        ok = (src >= 0) & (dst >= 0)
        if names is not None:
            ok &= (src < len(names)) & (dst < len(names))
        dropped = int((~ok).sum())
        if dropped:
            src, dst = src[ok], dst[ok]
    n = int(max(src.max(initial=-1), dst.max(initial=-1))) + 1 if len(src) else 0
    if names is None:
        names = np.array([str(i) for i in range(n)])
    et = EdgeTable(
        src=src, dst=dst, names=np.asarray(names), num_rows_raw=len(src) + dropped
    )
    return _add_quarantine(et, "out_of_range_ids", dropped) if quarantine else et
