"""ctypes bindings for the native C++ graph builder (``native/libgraphbuild.so``).

The native library provides the hot host-side path the reference delegated to
the JVM (parquet/RDD machinery, ``Graphframes.py:53-74``): streaming
edge-list parsing + open-addressing string interning. Build it with
``make -C native``. When the shared library is absent these bindings return
``None`` and callers fall back to the NumPy implementation.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None
_LIB_TRIED = False


def _lib():
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for cand in (
        os.environ.get("GRAPHMINE_NATIVE_LIB", ""),
        os.path.join(here, "native", "libgraphbuild.so"),
    ):
        if cand and os.path.exists(cand):
            try:
                lib = ctypes.CDLL(cand)
                _bind(lib)
                _LIB = lib
                break
            except OSError:
                continue
    return _LIB


def _bind(lib: ctypes.CDLL) -> None:
    # int64 gb_load_edge_list(const char* path, char comment,
    #                         int32** src, int32** dst,
    #                         char*** names, int64* num_vertices)
    lib.gb_load_edge_list.restype = ctypes.c_int64
    lib.gb_load_edge_list.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char_p)),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.gb_free.restype = None
    lib.gb_free.argtypes = [ctypes.c_void_p]
    lib.gb_free_names.restype = None
    lib.gb_free_names.argtypes = [ctypes.POINTER(ctypes.c_char_p), ctypes.c_int64]
    # int gb_build_message_csr(const int32* src, const int32* dst, int64 e,
    #                          int64 v, int symmetric, int64* ptr,
    #                          int32* recv_sorted, int32* send_sorted)
    # Absent from pre-counting-sort builds of the library; bind when
    # present so a stale .so still serves the edge-list loader.
    if not hasattr(lib, "gb_build_message_csr"):
        return
    lib.gb_build_message_csr.restype = ctypes.c_int
    lib.gb_build_message_csr.argtypes = [
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
    ]
    if not hasattr(lib, "gb_build_message_csr_weighted"):
        return
    lib.gb_build_message_csr_weighted.restype = ctypes.c_int
    lib.gb_build_message_csr_weighted.argtypes = [
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
    ]
    # Chunked streaming parse API (r3). Absent from stale .so builds.
    if not hasattr(lib, "gb_parse_edge_chunk"):
        return
    lib.gb_interner_new.restype = ctypes.c_void_p
    lib.gb_interner_new.argtypes = []
    lib.gb_interner_free.restype = None
    lib.gb_interner_free.argtypes = [ctypes.c_void_p]
    lib.gb_interner_size.restype = ctypes.c_int64
    lib.gb_interner_size.argtypes = [ctypes.c_void_p]
    lib.gb_interner_names.restype = ctypes.c_int64
    lib.gb_interner_names.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char_p)),
    ]
    lib.gb_parse_edge_chunk.restype = ctypes.c_int64
    lib.gb_parse_edge_chunk.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_char,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
    ]


def available() -> bool:
    return _lib() is not None


def load_edge_list_native(path: str, comments: str = "#"):
    """Parse an edge list with the C++ builder. Returns EdgeTable or None."""
    lib = _lib()
    if lib is None or not os.path.exists(path):
        return None
    from graphmine_tpu.io.edges import EdgeTable

    src_p = ctypes.POINTER(ctypes.c_int32)()
    dst_p = ctypes.POINTER(ctypes.c_int32)()
    names_p = ctypes.POINTER(ctypes.c_char_p)()
    nv = ctypes.c_int64(0)
    ne = lib.gb_load_edge_list(
        path.encode(), comments[:1].encode() or b"#",
        ctypes.byref(src_p), ctypes.byref(dst_p), ctypes.byref(names_p), ctypes.byref(nv),
    )
    if ne == -3:
        raise ValueError(f"edge list {path!r} needs >= 2 columns")
    if ne == -4:
        raise ValueError(
            f"edge list {path!r}: number of columns changed between data lines"
        )
    if ne < 0:
        return None
    try:
        if ne == 0:
            src = np.zeros(0, np.int32)
            dst = np.zeros(0, np.int32)
        else:
            src = np.ctypeslib.as_array(src_p, shape=(ne,)).copy()
            dst = np.ctypeslib.as_array(dst_p, shape=(ne,)).copy()
        # object dtype on an empty vocabulary too (comment-only file),
        # matching edges.py's empty-table path (ADVICE r3 / review r4)
        names = (
            np.array([names_p[i].decode() for i in range(nv.value)])
            if nv.value else np.empty(0, dtype=object)
        )
    finally:
        lib.gb_free(src_p)
        lib.gb_free(dst_p)
        lib.gb_free_names(names_p, nv)
    return EdgeTable(src=src, dst=dst, names=names, num_rows_raw=int(ne))


def chunked_parse_available() -> bool:
    lib = _lib()
    return lib is not None and hasattr(lib, "gb_parse_edge_chunk")


def load_edge_list_chunked(path: str, comments: str = "#",
                           weight_col: int | None = None,
                           chunk_bytes: int = 64 << 20):
    """Streaming native parse: bounded chunks through one shared interner.

    Peak host memory is O(chunk + vocabulary + edges int32), killing the
    whole-file wall of both ``np.loadtxt(dtype=str)`` and the bulk native
    path for top-rung edge lists (VERDICT r2 item 4 / weak 5). Weighted
    columns parse natively here (no NumPy string detour). Returns an
    EdgeTable, or None when the library (or its chunk API) is absent.
    Raises ValueError on a malformed weight column or a data line with
    fewer than 2 tokens (parity with the NumPy fallback's hard errors).
    """
    lib = _lib()
    if (
        lib is None
        or not hasattr(lib, "gb_parse_edge_chunk")
        or not os.path.exists(path)
    ):
        return None
    from graphmine_tpu.io.edges import edge_table_from_parts, iter_line_chunks

    comment = comments[:1].encode() or b"#"
    wcol = -1 if weight_col is None else int(weight_col)
    it = lib.gb_interner_new()
    if not it:
        return None
    src_parts, dst_parts, w_parts = [], [], []
    num_rows = 0
    try:
        for buf in iter_line_chunks(path, chunk_bytes):
            src_p = ctypes.POINTER(ctypes.c_int32)()
            dst_p = ctypes.POINTER(ctypes.c_int32)()
            w_p = ctypes.POINTER(ctypes.c_float)()
            ne = lib.gb_parse_edge_chunk(
                it, buf, len(buf), comment, wcol,
                ctypes.byref(src_p), ctypes.byref(dst_p),
                ctypes.byref(w_p),
            )
            if ne == -2:
                raise ValueError(
                    f"edge list {path!r}: weight_col={wcol} missing "
                    "on a data line or not parseable as a float"
                )
            if ne == -3:
                # same hard errors (and messages) as the NumPy paths:
                # which inputs parse must not depend on the .so (ADVICE r3)
                raise ValueError(f"edge list {path!r} needs >= 2 columns")
            if ne == -4:
                raise ValueError(
                    f"edge list {path!r}: number of columns changed "
                    "between data lines"
                )
            if ne < 0:
                # allocation failure: the library freed/nulled its buffers
                return None
            try:
                if ne:
                    src_parts.append(
                        np.ctypeslib.as_array(src_p, shape=(ne,)).copy()
                    )
                    dst_parts.append(
                        np.ctypeslib.as_array(dst_p, shape=(ne,)).copy()
                    )
                    if wcol >= 0:
                        w_parts.append(
                            np.ctypeslib.as_array(w_p, shape=(ne,)).copy()
                        )
                num_rows += int(ne)
            finally:
                lib.gb_free(src_p)
                lib.gb_free(dst_p)
                if wcol >= 0:
                    lib.gb_free(w_p)
        names_p = ctypes.POINTER(ctypes.c_char_p)()
        nv = lib.gb_interner_names(it, ctypes.byref(names_p))
        if nv < 0:
            return None
        try:
            # dtype=object on nv == 0 too: a bare np.array([]) is float64,
            # diverging from edges.py's empty-table path (np.empty(0,
            # dtype=object)) for the same comment-only input (ADVICE r3).
            names = (
                np.array([names_p[i].decode() for i in range(nv)])
                if nv else np.empty(0, dtype=object)
            )
        finally:
            lib.gb_free_names(names_p, nv)
    finally:
        lib.gb_interner_free(it)
    return edge_table_from_parts(
        src_parts, dst_parts, names, num_rows,
        w_parts if wcol >= 0 else None,
    )


def build_message_csr(src, dst, num_vertices: int, symmetric: bool = True,
                      weights=None):
    """Native stable counting-sort message-CSR build.

    Returns ``(ptr int64 [V+1], recv_sorted int32 [M], send_sorted int32
    [M], w_sorted float32 [M] | None)`` matching the NumPy layout in
    ``container.build_graph`` exactly (asserted by tests), or ``None``
    when the library (or, for weighted builds, its weighted entry point)
    is unavailable. Raises ``ValueError`` on out-of-range endpoints
    (parity with the bounds implied by ``num_vertices``).
    """
    lib = _lib()
    if lib is None or not hasattr(lib, "gb_build_message_csr"):
        return None
    if weights is not None and not hasattr(lib, "gb_build_message_csr_weighted"):
        return None  # stale .so: caller falls back to the NumPy sort
    src = np.ascontiguousarray(src, dtype=np.int32)
    dst = np.ascontiguousarray(dst, dtype=np.int32)
    if src.shape != dst.shape or src.ndim != 1:
        raise ValueError("src/dst must be equal-length 1-D arrays")
    e = len(src)
    m = 2 * e if symmetric else e
    ptr = np.empty(num_vertices + 1, dtype=np.int64)
    recv_sorted = np.empty(max(m, 1), dtype=np.int32)
    send_sorted = np.empty(max(m, 1), dtype=np.int32)
    if weights is None:
        rc = lib.gb_build_message_csr(
            src, dst, e, num_vertices, int(symmetric), ptr, recv_sorted,
            send_sorted,
        )
        w_sorted = None
    else:
        weights = np.ascontiguousarray(weights, dtype=np.float32)
        if weights.shape != src.shape:
            raise ValueError("weights must be one float per edge")
        w_sorted = np.empty(max(m, 1), dtype=np.float32)
        rc = lib.gb_build_message_csr_weighted(
            src, dst, weights, e, num_vertices, int(symmetric), ptr,
            recv_sorted, send_sorted, w_sorted,
        )
    if rc != 0:
        raise ValueError("edge endpoint out of range [0, num_vertices)")
    return (
        ptr, recv_sorted[:m], send_sorted[:m],
        None if w_sorted is None else w_sorted[:m],
    )
