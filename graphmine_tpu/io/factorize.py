"""String → dense int32 vertex-id factorization.

The reference assigns vertex IDs with ``sha1(x)[:8]`` (a 32-bit hex string,
``Graphframes.py:57-58``), which collides near ~80K vertices and forces
string-keyed joins. We instead factorize to *dense* int32 indices — the
device-friendly representation every downstream kernel indexes with.

A native C++ fast path (``native/graph_builder.cpp``, loaded via ctypes in
:mod:`graphmine_tpu.io.native`) accelerates edge-list parsing + interning for
large text files; this module is the canonical NumPy implementation and the
fallback.
"""

from __future__ import annotations

import numpy as np


def factorize(*columns: np.ndarray) -> tuple[list[np.ndarray], np.ndarray]:
    """Map string columns to dense int32 codes over their *union* of values.

    Mirrors the vertex-dictionary build of the reference
    (``Graphframes.py:53``: flatMap over both domain columns + distinct),
    but produces contiguous indices instead of hash strings.

    Returns ``(codes, uniques)`` where ``codes[i]`` is the int32 code array
    for ``columns[i]`` and ``uniques`` is the vocabulary (np object/str
    array). Codes are assigned in first-appearance order over the
    concatenated columns — deterministic and stable across runs.
    """
    if not columns:
        raise ValueError("factorize() needs at least one column")
    flat = np.concatenate([np.asarray(c) for c in columns])
    codes_flat, uniques = _factorize_first_appearance(flat)
    out, off = [], 0
    for c in columns:
        n = len(c)
        out.append(codes_flat[off : off + n].astype(np.int32))
        off += n
    return out, uniques


def _factorize_first_appearance(values: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    # np.unique sorts; remap so codes follow first appearance (matches the
    # insertion-order semantics of a hash-map interner, and keeps golden
    # tests independent of locale/collation).
    uniq_sorted, first_idx, inv = np.unique(values, return_index=True, return_inverse=True)
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty_like(order)
    rank[order] = np.arange(len(order))
    codes = rank[inv].astype(np.int32)
    return codes, uniq_sorted[order]


class IncrementalFactorizer:
    """Streaming string -> dense int32 interner for batched ingestion.

    Each :meth:`add` call encodes one column batch, assigning new codes in
    first-appearance order *within the batch* (the batch's unique values
    are looked up / inserted via a dict — O(batch uniques), vectorized
    decode). Peak memory is the vocabulary plus one batch, which is what
    the reference's abandoned data slicer (``Graphframes.py:34-47``) was
    groping toward.
    """

    def __init__(self):
        self._index: dict = {}
        self._names: list = []

    def add(self, column: np.ndarray) -> np.ndarray:
        column = np.asarray(column)
        codes_batch, uniques = _factorize_first_appearance(column)
        return self._intern_uniques(codes_batch, uniques)

    def add_dictionary(self, indices: np.ndarray, dictionary: np.ndarray) -> np.ndarray:
        """Encode a batch given as ``dictionary[indices]`` WITHOUT
        materializing the per-row strings (r5 ingest fast path).

        Equivalent to ``add(dictionary[indices])`` by construction — an
        Arrow dictionary's values are unique, so first-appearance order
        over the int index stream is first-appearance order over the
        value stream, and only the batch's distinct values (``|D|``, not
        ``|rows|``) touch Python. The e2e capture measured the per-row
        string path at ~300K rows/s (84 s of a 196 s pipeline on 25M
        rows); this path moves the per-row work to int32 numpy.
        """
        codes_batch, uniq_idx = _factorize_first_appearance(
            np.asarray(indices)
        )
        return self._intern_uniques(codes_batch, np.asarray(dictionary)[uniq_idx])

    def _intern_uniques(self, codes_batch, uniques) -> np.ndarray:
        lut = np.empty(len(uniques), dtype=np.int32)
        index, names = self._index, self._names
        for i, val in enumerate(uniques.tolist()):
            code = index.get(val)
            if code is None:
                code = len(names)
                index[val] = code
                names.append(val)
            lut[i] = code
        return lut[codes_batch].astype(np.int32)

    def names(self) -> np.ndarray:
        return np.asarray(self._names, dtype=object)
