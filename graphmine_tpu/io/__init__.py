from graphmine_tpu.io.edges import EdgeTable, load_parquet_edges, load_edge_list
from graphmine_tpu.io.factorize import factorize

__all__ = ["EdgeTable", "load_parquet_edges", "load_edge_list", "factorize"]
