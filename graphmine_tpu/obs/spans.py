"""Hierarchical span context: run_id -> phase -> rung -> superstep.

The resilience machine (PRs 1-2) emits every recovery decision as a flat
JSONL record — but with no run, trace, or span identity an operator
cannot reconstruct *which* retry belonged to *which* phase on *which*
mesh rung. A :class:`Tracer` owns one run's identity (``run_id`` +
``trace_id``) and a thread-local stack of open :class:`Span`\\ s; the
:class:`~graphmine_tpu.pipeline.metrics.MetricsSink` stamps every record
with the current span's ids and slash-joined path, so retry / degrade /
mesh_degrade / tripwire / checkpoint records join into one causal
timeline (``tools/obs_report.py``).

Timings are **monotonic** (``time.perf_counter``) — span durations never
go negative under NTP steps; the wall-clock ``start_t`` exists only so
offline reports can align spans with record ``t`` values.

Stdlib-only. :func:`xla_annotation` opportunistically enters a
``jax.profiler.TraceAnnotation`` named by the span path — but only when
jax is *already imported*, so host-side tooling that never touches a
device pays nothing.
"""

from __future__ import annotations

import contextlib
import secrets
import sys
import threading
import time
from dataclasses import dataclass, field


def new_run_id() -> str:
    """Sortable-by-start, collision-safe run identity:
    ``YYYYMMDDTHHMMSS-<6 hex>`` (UTC)."""
    return time.strftime("%Y%m%dT%H%M%S", time.gmtime()) + "-" + secrets.token_hex(3)


def _new_id(nbytes: int = 4) -> str:
    return secrets.token_hex(nbytes)


@dataclass
class Span:
    """One timed node of the span tree. ``path`` is the slash-joined name
    chain from the root (``run/lpa/rung:ring@4/superstep``) — records
    carry it verbatim so offline triage needs no id-graph walk."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    path: str
    start_t: float                      # wall clock, for report alignment
    start_mono: float                   # perf_counter, for durations
    end_mono: float | None = None
    attrs: dict = field(default_factory=dict)
    status: str = "ok"

    @property
    def seconds(self) -> float:
        """Monotonic duration; an open span reports its age so far."""
        end = self.end_mono if self.end_mono is not None else time.perf_counter()
        return end - self.start_mono


class Tracer:
    """One run's span tree. The root span ("run") opens at construction
    and closes via :meth:`close`; :meth:`span` nests under the current
    thread's innermost open span.

    Thread model: each thread has its own open-span stack; a thread with
    no open span (the heartbeat thread, a watchdog worker) falls back to
    the **root** span, so records emitted there still carry the run and
    trace ids. :meth:`latest` returns the most recently entered open span
    across all threads — what the heartbeat reports as the current phase
    without the emitting thread needing any span of its own.
    """

    def __init__(self, run_id: str | None = None):
        self.run_id = run_id or new_run_id()
        self.trace_id = _new_id(8)
        self._local = threading.local()
        self._lock = threading.Lock()
        now = time.time()
        self.root = Span(
            name="run", trace_id=self.trace_id, span_id=_new_id(),
            parent_id=None, path="run", start_t=now,
            start_mono=time.perf_counter(),
        )
        self._latest: Span = self.root

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current(self) -> Span:
        """This thread's innermost open span (the root when none)."""
        stack = self._stack()
        return stack[-1] if stack else self.root

    def latest(self) -> Span:
        """Most recently entered open span across all threads."""
        with self._lock:
            return self._latest

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Open a child span of the current one for the ``with`` block.
        An escaping exception marks ``status="error"`` (and propagates);
        the span always closes with a monotonic end time."""
        parent = self.current()
        sp = Span(
            name=name, trace_id=self.trace_id, span_id=_new_id(),
            parent_id=parent.span_id, path=f"{parent.path}/{name}",
            start_t=time.time(), start_mono=time.perf_counter(),
            attrs=dict(attrs),
        )
        stack = self._stack()
        stack.append(sp)
        with self._lock:
            self._latest = sp
        try:
            yield sp
        except BaseException:
            sp.status = "error"
            raise
        finally:
            sp.end_mono = time.perf_counter()
            if stack and stack[-1] is sp:
                stack.pop()
            else:  # defensive: never let a mismatched exit corrupt the stack
                try:
                    stack.remove(sp)
                except ValueError:
                    pass
            with self._lock:
                if self._latest is sp:
                    self._latest = self.current()

    def close(self) -> Span:
        """End the root span (idempotent); returns it for the run record."""
        if self.root.end_mono is None:
            self.root.end_mono = time.perf_counter()
        return self.root


def xla_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` named by the span path — the
    bridge that lines XLA profiler traces up with the span tree — or a
    null context when jax is not already imported (a tracer used by
    host-only tooling must not drag the runtime in) or the profiler
    API is unavailable."""
    jax = sys.modules.get("jax")
    if jax is None:
        return contextlib.nullcontext()
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()
