"""Hierarchical span context: run_id -> phase -> rung -> superstep.

The resilience machine (PRs 1-2) emits every recovery decision as a flat
JSONL record — but with no run, trace, or span identity an operator
cannot reconstruct *which* retry belonged to *which* phase on *which*
mesh rung. A :class:`Tracer` owns one run's identity (``run_id`` +
``trace_id``) and a thread-local stack of open :class:`Span`\\ s; the
:class:`~graphmine_tpu.pipeline.metrics.MetricsSink` stamps every record
with the current span's ids and slash-joined path, so retry / degrade /
mesh_degrade / tripwire / checkpoint records join into one causal
timeline (``tools/obs_report.py``).

Timings are **monotonic** (``time.perf_counter``) — span durations never
go negative under NTP steps; the wall-clock ``start_t`` exists only so
offline reports can align spans with record ``t`` values.

Cross-process propagation (ISSUE 11, docs/OBSERVABILITY.md "Fleet
tracing"): a :class:`TraceContext` is the wire form of one span's
identity — ``to_header()`` renders a ``traceparent``-style header, the
receiving process parses it with :func:`TraceContext.from_header` and
opens its spans with ``remote=ctx``, adopting the sender's ``trace_id``
and parenting under the sender's span. Every record the receiver emits
then lands in the SAME trace, so ``tools/trace_stitch.py`` can join the
per-process JSONL shards of a fleet (router → replicas → writer →
standby) into one causal timeline with no id-mapping table.

Stdlib-only. :func:`xla_annotation` opportunistically enters a
``jax.profiler.TraceAnnotation`` named by the span path — but only when
jax is *already imported*, so host-side tooling that never touches a
device pays nothing.
"""

from __future__ import annotations

import contextlib
import re
import secrets
import sys
import threading
import time
from dataclasses import dataclass, field


def new_run_id() -> str:
    """Sortable-by-start, collision-safe run identity:
    ``YYYYMMDDTHHMMSS-<6 hex>`` (UTC)."""
    return time.strftime("%Y%m%dT%H%M%S", time.gmtime()) + "-" + secrets.token_hex(3)


def _new_id(nbytes: int = 4) -> str:
    return secrets.token_hex(nbytes)


# The header every fleet hop carries (router -> replica, router ->
# writer, probe). traceparent-STYLE: version-trace_id-span_id-flags,
# with this repo's id widths (16-hex trace, 8-hex span) instead of
# W3C's fixed 32/16 — zero-padding to W3C widths and stripping it back
# is a round-trip hazard a single-format fleet doesn't need.
TRACE_HEADER = "traceparent"

# Parsed ids are echoed into response headers and stamped into records:
# constrain them so a hostile header can't smuggle newlines/quotes
# (the serve/server.py request-id discipline).
_HEX_ID_RE = re.compile(r"[0-9a-f]{8,64}")


@dataclass(frozen=True)
class TraceContext:
    """One span's identity on the wire: what a process needs to open a
    child span of a span living in ANOTHER process."""

    trace_id: str
    span_id: str
    sampled: bool = True

    def to_header(self) -> str:
        """``00-<trace_id>-<span_id>-<01|00>``."""
        return (
            f"00-{self.trace_id}-{self.span_id}-"
            f"{'01' if self.sampled else '00'}"
        )

    @classmethod
    def from_header(cls, value) -> "TraceContext | None":
        """Parse a propagated header; ``None`` on anything malformed —
        an unparseable traceparent must degrade to a fresh local trace,
        never crash a request handler."""
        if not isinstance(value, str) or not value:
            return None
        parts = value.strip().lower().split("-")
        if len(parts) != 4:
            return None
        version, trace_id, span_id, flags = parts
        if not re.fullmatch(r"[0-9a-f]{2}", version):
            return None
        if not _HEX_ID_RE.fullmatch(trace_id):
            return None
        if not _HEX_ID_RE.fullmatch(span_id):
            return None
        if len(flags) != 2:
            return None
        return cls(trace_id, span_id, sampled=flags[-1] == "1")


def sink_trace_header(sink) -> str:
    """The calling thread's current span of ``sink``'s tracer, rendered
    as a propagatable ``traceparent`` header — "" when the sink has no
    tracer (tracing off). The one place the sink→header formula lives;
    every fleet process (router forwards, replica WAL stamps, probes)
    propagates through here so the wire format can never fork."""
    tracer = getattr(sink, "tracer", None)
    if tracer is None:
        return ""
    return tracer.current().context().to_header()


@dataclass
class Span:
    """One timed node of the span tree. ``path`` is the slash-joined name
    chain from the root (``run/lpa/rung:ring@4/superstep``) — records
    carry it verbatim so offline triage needs no id-graph walk."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    path: str
    start_t: float                      # wall clock, for report alignment
    start_mono: float                   # perf_counter, for durations
    end_mono: float | None = None
    attrs: dict = field(default_factory=dict)
    status: str = "ok"

    @property
    def seconds(self) -> float:
        """Monotonic duration; an open span reports its age so far."""
        end = self.end_mono if self.end_mono is not None else time.perf_counter()
        return end - self.start_mono

    def context(self) -> TraceContext:
        """This span's wire identity — what :meth:`to_header` of the
        result propagates to the next process."""
        return TraceContext(self.trace_id, self.span_id)


class Tracer:
    """One run's span tree. The root span ("run") opens at construction
    and closes via :meth:`close`; :meth:`span` nests under the current
    thread's innermost open span.

    Thread model: each thread has its own open-span stack; a thread with
    no open span (the heartbeat thread, a watchdog worker) falls back to
    the **root** span, so records emitted there still carry the run and
    trace ids. :meth:`latest` returns the most recently entered open span
    across all threads — what the heartbeat reports as the current phase
    without the emitting thread needing any span of its own.
    """

    def __init__(self, run_id: str | None = None):
        self.run_id = run_id or new_run_id()
        self.trace_id = _new_id(8)
        self._local = threading.local()
        self._lock = threading.Lock()
        now = time.time()
        self.root = Span(
            name="run", trace_id=self.trace_id, span_id=_new_id(),
            parent_id=None, path="run", start_t=now,
            start_mono=time.perf_counter(),
        )
        self._latest: Span = self.root

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current(self) -> Span:
        """This thread's innermost open span (the root when none)."""
        stack = self._stack()
        return stack[-1] if stack else self.root

    def latest(self) -> Span:
        """Most recently entered open span across all threads."""
        with self._lock:
            return self._latest

    @contextlib.contextmanager
    def span(
        self, name: str, remote: TraceContext | None = None,
        new_trace: bool = False, **attrs,
    ):
        """Open a child span of the current one for the ``with`` block.
        An escaping exception marks ``status="error"`` (and propagates);
        the span always closes with a monotonic end time.

        Cross-process identity (docs/OBSERVABILITY.md "Fleet tracing"):

        - ``remote=ctx`` parents the span under a span living in
          ANOTHER process — it adopts ``ctx.trace_id`` and sets
          ``parent_id`` to the remote span's id, so every record emitted
          inside lands in the propagating process's trace. The path
          restarts at ``name`` (the local path chain belongs to the
          local tree, not the remote one).
        - ``new_trace=True`` mints a fresh ``trace_id`` for the span's
          subtree — the fleet router's root-span-per-request, so each
          request is its OWN trace instead of one run-wide trace.

        Nested spans inherit their parent's ``trace_id`` (not the
        tracer's), so a whole subtree opened under a remote/new-trace
        span stays in that trace.
        """
        if remote is not None and new_trace:
            raise ValueError("span(): remote= and new_trace= are exclusive")
        parent = self.current()
        if remote is not None:
            trace_id, parent_id, path = remote.trace_id, remote.span_id, name
        elif new_trace:
            trace_id, parent_id, path = _new_id(8), None, name
        else:
            trace_id = parent.trace_id
            parent_id = parent.span_id
            path = f"{parent.path}/{name}"
        sp = Span(
            name=name, trace_id=trace_id, span_id=_new_id(),
            parent_id=parent_id, path=path,
            start_t=time.time(), start_mono=time.perf_counter(),
            attrs=dict(attrs),
        )
        stack = self._stack()
        stack.append(sp)
        with self._lock:
            self._latest = sp
        try:
            yield sp
        except BaseException:
            sp.status = "error"
            raise
        finally:
            sp.end_mono = time.perf_counter()
            if stack and stack[-1] is sp:
                stack.pop()
            else:  # defensive: never let a mismatched exit corrupt the stack
                try:
                    stack.remove(sp)
                except ValueError:
                    pass
            with self._lock:
                if self._latest is sp:
                    self._latest = self.current()

    def close(self) -> Span:
        """End the root span (idempotent); returns it for the run record."""
        if self.root.end_mono is None:
            self.root.end_mono = time.perf_counter()
        return self.root


def xla_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` named by the span path — the
    bridge that lines XLA profiler traces up with the span tree — or a
    null context when jax is not already imported (a tracer used by
    host-only tooling must not drag the runtime in) or the profiler
    API is unavailable."""
    jax = sys.modules.get("jax")
    if jax is None:
        return contextlib.nullcontext()
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()
