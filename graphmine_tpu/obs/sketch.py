"""Mergeable quantile sketches over fixed log ladders + drift distance.

The SLO layer answers "how fast" with bucket histograms over a fixed
latency ladder (``obs/histogram.py``); this module answers "what are we
*serving*" the same way: a :class:`QuantileSketch` is a bucket histogram
whose ladder is log-spaced over a VALUE domain — LOF outlier scores,
community sizes — instead of seconds. Reusing the histogram machinery is
the point, not a convenience:

- **mergeable**: sketches over one ladder add counter-wise
  (``Histogram.merge`` — associative and commutative), so per-replica
  sketches roll up into a fleet view exactly like latency histograms
  (pinned by ``tests/test_quality.py`` mirroring the r11 merge suite);
- **JSON-portable**: :meth:`QuantileSketch.to_state` /
  :meth:`QuantileSketch.from_state` round-trip through records and HTTP
  bodies, so the router can merge sketches it fetched from replicas and
  ``obs_report`` can re-plot a distribution from the JSONL alone;
- **comparable**: :func:`psi_distance` is a ladder-aligned population-
  stability-index drift distance between two sketches — THE
  snapshot-over-snapshot drift number the quality plane alerts on.
  Ladder alignment is a hard precondition (mismatched ladders raise,
  same as ``Histogram.merge``): re-binning would fabricate a drift
  neither snapshot exhibits.

Fixed ladders (not data-dependent quantile summaries like t-digest) are
a deliberate trade: slightly coarser tails for *exact* mergeability and
an exact, hand-computable drift formula — the same trade the latency
histograms already made. Stdlib-only, like everything in ``obs/``.
"""

from __future__ import annotations

import math
import os

from graphmine_tpu.obs.histogram import Histogram

__all__ = [
    "DEFAULT_SCORE_LADDER",
    "DEFAULT_SIZE_LADDER",
    "PSI_EPS",
    "QuantileSketch",
    "env_float",
    "log_ladder",
    "psi_distance",
]


def env_float(name: str, default: float) -> float:
    """The quality plane's one env-parsing discipline (shared by
    ``obs/quality.py`` thresholds and ``obs/alerts.py`` rule defaults —
    the AdmissionBounds contract): absent = default, malformed raises
    loudly at construction, never a silent fallback."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError as e:
        raise ValueError(f"{name}={raw!r} is not a float") from e


def log_ladder(lo: float, hi: float, steps_per_octave: int = 1) -> tuple:
    """Geometric bucket bounds from ``lo`` to at least ``hi``:
    ``lo * 2**(i / steps_per_octave)``. Values at or below ``lo`` land in
    the first bucket; values above the last bound land in the implicit
    overflow bucket (the histogram's +Inf)."""
    lo, hi = float(lo), float(hi)
    if lo <= 0 or hi <= lo:
        raise ValueError(f"need 0 < lo < hi (got lo={lo}, hi={hi})")
    if steps_per_octave < 1:
        raise ValueError("steps_per_octave must be >= 1")
    n = math.ceil(math.log2(hi / lo) * steps_per_octave)
    return tuple(lo * 2 ** (i / steps_per_octave) for i in range(n + 1))


# LOF scores cluster tightly around 1.0 (the inlier fixed point) with an
# outlier tail of a few tens: quarter-octave resolution from 1/16 to 64
# keeps the bulk of the distribution out of any single bucket, so a
# drifting scorer moves probability mass between buckets instead of
# hiding inside one.
DEFAULT_SCORE_LADDER = log_ladder(0.0625, 64.0, steps_per_octave=4)

# Community sizes are long-tailed over decades: whole-octave (power-of-
# two) buckets from 1 to 2^30 — the census's natural resolution, and the
# ladder the recursive-LPA size-decile machinery already thinks in.
DEFAULT_SIZE_LADDER = log_ladder(1.0, float(1 << 30), steps_per_octave=1)

# Probability floor for the PSI log-ratio: an empty bucket on one side
# must contribute a LARGE but finite term, not an infinite one.
PSI_EPS = 1e-4


class QuantileSketch(Histogram):
    """A value-domain bucket histogram over one fixed log ladder.

    Inherits the whole histogram contract — thread-safe ``observe``,
    atomic ``snapshot``, counter-wise ``merge`` (ladder-checked),
    interpolated ``quantile`` — and adds bulk ingestion
    (:meth:`add_counts`: the quality pass bins a whole label/score array
    with one vectorized host pass, then deposits the counts here) and a
    JSON state round-trip for records and cross-process merges.
    """

    def __init__(self, name: str = "sketch", help: str = "",
                 buckets=DEFAULT_SCORE_LADDER, labels: dict | None = None):
        super().__init__(name, help, buckets, labels=labels)

    def add_counts(self, counts, total: float = 0.0) -> "QuantileSketch":
        """Deposit pre-binned counts: ``counts`` has one entry per finite
        bound plus the overflow bucket (``len(bounds) + 1``), the shape
        :meth:`to_state` emits. ``total`` accrues into the running sum
        (pass the values' sum when quantile interpolation should stay
        meaningful; 0.0 when only the distribution matters)."""
        counts = [int(c) for c in counts]
        if len(counts) != len(self._bounds) + 1:
            raise ValueError(
                f"counts has {len(counts)} buckets for a "
                f"{len(self._bounds)}-bound ladder (+1 overflow)"
            )
        if any(c < 0 for c in counts):
            raise ValueError("bucket counts must be non-negative")
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._sum += float(total)
        return self

    def to_state(self) -> dict:
        """One JSON-ready atomic read: the record/HTTP wire shape
        (``bounds``/``counts``/``sum``/``count``) the schema registry
        validates all-or-nothing (``SKETCH_KEYS``) and
        :meth:`from_state` reconstructs exactly."""
        snap = self.snapshot()
        return {
            "bounds": [float(b) for b in snap.bounds],
            "counts": [int(c) for c in snap.counts],
            "sum": float(snap.sum),
            "count": int(snap.count),
        }

    @classmethod
    def from_state(cls, state: dict, name: str = "sketch") -> "QuantileSketch":
        """Rebuild a sketch from :meth:`to_state` output (a record field,
        a replica's ``/alertz`` body). Malformed state raises ValueError —
        a router merging replica sketches must refuse a torn payload, not
        fold garbage into the fleet view."""
        try:
            bounds = tuple(float(b) for b in state["bounds"])
            counts = [int(c) for c in state["counts"]]
            total = float(state.get("sum", 0.0))
            sk = cls(name=name, buckets=bounds)
            sk.add_counts(counts, total=total)
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"malformed sketch state: {e!r}") from e
        return sk


def _state_of(sketch) -> tuple:
    """``(bounds, counts)`` of a QuantileSketch/Histogram OR a to_state
    dict — one normalization so :func:`psi_distance` accepts either."""
    if isinstance(sketch, Histogram):
        snap = sketch.snapshot()
        return tuple(snap.bounds), list(snap.counts)
    try:
        return (
            tuple(float(b) for b in sketch["bounds"]),
            [int(c) for c in sketch["counts"]],
        )
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"malformed sketch state: {e!r}") from e


def psi_distance(a, b, eps: float = PSI_EPS) -> float:
    """Population stability index between two sketches on ONE ladder.

    ``PSI = sum_i (p_i - q_i) * ln(p_i / q_i)`` over every bucket
    (overflow included), with per-bucket proportions floored at ``eps``
    so an empty bucket contributes a large finite term instead of an
    infinite one. Symmetric, zero iff the proportions agree, and exactly
    hand-computable (the ``tests/test_quality.py`` pin). The usual
    reading: < 0.1 stable, 0.1-0.25 drifting, > 0.25 shifted — the
    default alert thresholds in ``obs/alerts.py`` follow it.

    Either side may be a :class:`QuantileSketch` or a ``to_state`` dict.
    Mismatched ladders raise (the ``Histogram.merge`` refusal applied to
    comparison): re-binning would fabricate drift. Two empty sketches
    are identically distributed (0.0); one empty side is maximal drift
    over every occupied bucket.
    """
    bounds_a, counts_a = _state_of(a)
    bounds_b, counts_b = _state_of(b)
    if bounds_a != bounds_b:
        raise ValueError(
            f"cannot compare sketches with different ladders "
            f"({len(bounds_a)} vs {len(bounds_b)} bounds)"
        )
    tot_a, tot_b = sum(counts_a), sum(counts_b)
    if tot_a == 0 and tot_b == 0:
        return 0.0
    psi = 0.0
    for ca, cb in zip(counts_a, counts_b):
        p = max(ca / tot_a if tot_a else 0.0, eps)
        q = max(cb / tot_b if tot_b else 0.0, eps)
        psi += (p - q) * math.log(p / q)
    return psi
