"""Bucket histograms: the latency-distribution surface of the registry.

Counters and gauges (``obs/registry.py``) answer "how many" and "how much
right now"; neither can answer the serving layer's control question —
*what is p99 request latency* — because a mean over a long-tailed
distribution hides exactly the tail that admission control and load
shedding key off. A :class:`Histogram` is the Prometheus answer: a fixed
ladder of upper bounds, one counter per bucket, a running sum. Three
properties the serving layer leans on:

- **thread-safe**: ``observe`` is one lock-guarded increment; request
  handler threads, the delta publisher and a concurrent ``/metrics``
  scrape never tear each other (a scrape renders from one atomic
  :meth:`snapshot`, so cumulative bucket counts are always monotone);
- **mergeable**: two histograms over the same bucket ladder add
  counter-wise (:meth:`merge` — associative and commutative, the
  property that lets per-replica histograms roll up into a fleet view,
  pinned by ``tests/test_slo.py``);
- **quantile estimation**: :meth:`quantile` interpolates linearly inside
  the bucket the rank lands in — ``histogram_quantile()`` semantics, so
  the live ``/statusz`` numbers and an offline Prometheus query agree to
  within one bucket by construction.

Stdlib-only, like everything in ``obs/``.
"""

from __future__ import annotations

import bisect
import math
import threading
from dataclasses import dataclass

# Default bucket ladder for request/stage latencies in SECONDS. Denser
# than Prometheus's default at the microsecond end: in-process serving
# lookups resolve in 100us-1ms, and a ladder whose lowest bound is 5ms
# would dump the entire working distribution into one bucket.
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def format_bound(b: float) -> str:
    """Prometheus ``le`` label text: ``0.005``, ``1``, ``+Inf`` — one
    deterministic rendering so successive scrapes diff cleanly."""
    if math.isinf(b):
        return "+Inf"
    return repr(float(b))  # shortest round-trip repr: 0.00025, not 0.0002500…01


def _validated_bounds(buckets) -> tuple:
    """One owner for bucket-ladder validation: finite, strictly
    increasing, non-empty (both Histogram and HistogramFamily construct
    through here, so an invalid ladder can never half-register)."""
    bounds = tuple(float(b) for b in buckets)
    if not bounds:
        raise ValueError("histogram needs at least one bucket bound")
    if any(math.isinf(b) or math.isnan(b) for b in bounds):
        raise ValueError("bucket bounds must be finite (+Inf is implicit)")
    if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
        raise ValueError("bucket bounds must be strictly increasing")
    return bounds


@dataclass(frozen=True)
class HistogramSnapshot:
    """One atomic read of a histogram: finite upper bounds, one count
    per bucket (the LAST entry is the +Inf overflow bucket, so
    ``len(counts) == len(bounds) + 1``), running sum and total count."""

    bounds: tuple
    counts: tuple
    sum: float
    count: int

    def cumulative(self) -> list:
        """Cumulative counts per ``le`` bound (+Inf last) — the
        exposition shape; always monotone non-decreasing."""
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out

    def summary(self) -> dict:
        """The ``{count, p50_s, p99_s}`` block the /statusz latency
        sections serve — one formula, so the quantile set and rounding
        can't drift between pages. Callers with extra fields (error
        rates, p95) spread this and add theirs."""
        return {
            "count": self.count,
            "p50_s": round(self.quantile(0.50), 6),
            "p99_s": round(self.quantile(0.99), 6),
        }

    def quantile(self, q: float) -> float:
        """``histogram_quantile``-style estimate: find the bucket the
        rank lands in, interpolate linearly inside it (uniform-within-
        bucket assumption). Empty histograms report 0.0; a rank landing
        in the +Inf bucket reports the largest finite bound (the honest
        "at least this much" answer Prometheus gives)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        if self.count == 0:
            return 0.0
        rank = q * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            prev = acc
            acc += c
            if acc >= rank and c > 0:
                if i >= len(self.bounds):
                    return self.bounds[-1] if self.bounds else 0.0
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                return lo + (hi - lo) * (rank - prev) / c
        return self.bounds[-1] if self.bounds else 0.0


class Histogram:
    """One labeled bucket histogram (Prometheus semantics).

    ``labels`` distinguish siblings of one metric family (the serving
    layer keys request latency by ``endpoint``); the family owns the
    shared name/help/bucket ladder, this class owns one label-set's
    counters. Use :meth:`~graphmine_tpu.obs.registry.Registry.histogram`
    to get one — direct construction is for tests and offline tooling.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets=DEFAULT_LATENCY_BUCKETS, labels: dict | None = None):
        bounds = _validated_bounds(buckets)
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # +1: the +Inf overflow
        self._sum = 0.0
        self._lock = threading.Lock()

    @property
    def bounds(self) -> tuple:
        return self._bounds

    def observe(self, value: float) -> None:
        """Record one observation: one bisect + one locked increment."""
        v = float(value)
        i = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v

    def snapshot(self) -> HistogramSnapshot:
        """One atomic read — the only way concurrent renderers see this
        histogram, so a mid-observe scrape can never tear sum vs count
        vs buckets apart."""
        with self._lock:
            return HistogramSnapshot(
                bounds=self._bounds, counts=tuple(self._counts),
                sum=self._sum, count=sum(self._counts),
            )

    @property
    def count(self) -> int:
        return self.snapshot().count

    @property
    def sum(self) -> float:
        return self.snapshot().sum

    def quantile(self, q: float) -> float:
        return self.snapshot().quantile(q)

    def merge(self, other: "Histogram") -> "Histogram":
        """Add ``other``'s buckets into this one (associative +
        commutative over a fixed ladder — the per-replica-to-fleet
        rollup operation). Mismatched ladders raise: silently re-binning
        would fabricate a distribution neither replica observed."""
        snap = other.snapshot()
        if snap.bounds != self._bounds:
            raise ValueError(
                f"cannot merge histograms with different bucket ladders "
                f"({len(snap.bounds)} vs {len(self._bounds)} bounds)"
            )
        with self._lock:
            for i, c in enumerate(snap.counts):
                self._counts[i] += c
            self._sum += snap.sum
        return self

    # -- exposition --------------------------------------------------------
    def render_lines(self, extra_labels: dict | None = None) -> list:
        """Prometheus exposition sample lines (no HELP/TYPE — the family
        owns those): cumulative ``_bucket`` per ``le`` (+Inf last), then
        ``_sum`` and ``_count``. Rendered from ONE snapshot, so the
        scrape is internally consistent by construction."""
        snap = self.snapshot()
        labels = dict(extra_labels or {})
        labels.update(self.labels)

        def lab(le: str | None = None) -> str:
            parts = [
                '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"'))
                for k, v in sorted(labels.items())
            ]
            if le is not None:
                parts.append(f'le="{le}"')
            return "{%s}" % ",".join(parts) if parts else ""

        lines = []
        cum = snap.cumulative()
        for b, c in zip(self._bounds, cum):
            lines.append(f"{self.name}_bucket{lab(format_bound(b))} {c}")
        lines.append(f"{self.name}_bucket{lab('+Inf')} {snap.count}")
        lines.append(f"{self.name}_sum{lab()} {snap.sum!r}")
        lines.append(f"{self.name}_count{lab()} {snap.count}")
        return lines


class HistogramFamily:
    """All label-sets of one histogram name: one shared HELP/TYPE and
    bucket ladder, one :class:`Histogram` child per label combination
    (``request_seconds{endpoint="query"}`` vs ``...{endpoint="vertex"}``).
    Lives in the registry's metric dict under the family name, so the
    one-name-one-TYPE rule holds across kinds."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets=DEFAULT_LATENCY_BUCKETS):
        self.name = name
        self.help = help
        # Validate HERE, not lazily in the first child: a family that
        # raised out of the registry's get-or-create must never have
        # been inserted, or the bad ladder would poison the name for
        # every later (valid) call.
        self._bounds = _validated_bounds(buckets)
        self._children: dict = {}
        self._lock = threading.Lock()

    @property
    def bounds(self) -> tuple:
        return self._bounds

    def labels(self, **labels) -> Histogram:
        """Get-or-create the child for one label combination."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = Histogram(
                    self.name, self.help, self._bounds, labels=dict(labels)
                )
            return child

    def children(self) -> list:
        """Children sorted by label set — the deterministic exposition
        (and statusz) order."""
        with self._lock:
            return [self._children[k] for k in sorted(self._children)]

    @property
    def value(self) -> int:
        """Total observations across children — what ``Registry.values``
        (and the heartbeat's gauge fold) reports for a histogram."""
        return sum(c.snapshot().count for c in self.children())
