"""Analytical compute-plane cost model: what a superstep SHOULD cost.

Every compute-plane record so far says *what ran* (``impl_selected``,
``plan_build``, ``superstep_telemetry``) but not *how fast it should have
run* — the crossover constants in ``ops/blocking.py`` and ``ops/lof.py``
encode measured walls, yet nothing at runtime judges achieved throughput
against them. This module closes that gap (ISSUE 12 tentpole), in the
tradition of the GraphBLAST / propagation-blocking line (PAPERS arXiv
1908.01407, 2011.08451) where bytes-moved / slots-per-second accounting
IS the performance argument:

1. **Per-plan cost derivation** — for every superstep family (sort /
   bucketed / blocked, fused and sharded) and LOF impl, derive message
   slots, padded gather slots, bytes gathered/scattered, padding overhead
   and exchanged ICI bytes **directly from the already-built plan/graph
   objects** (:func:`superstep_cost`, :func:`sharded_superstep_cost`,
   :func:`lof_cost`). No new measurement, no device work: the plans
   already hold the exact layout.

2. **Measured rooflines** — per-family achieved-rate anchors seeded from
   the committed silicon captures (BENCH_r04/r05; see
   :data:`ROOFLINE_SEEDS` for per-anchor provenance), overridable by a
   JSON file (``GRAPHMINE_ROOFLINE_FILE``) or per-anchor env vars
   (``GRAPHMINE_ROOFLINE_<NAME>``) so a fresh capture re-seeds the model
   without a code change (docs/OBSERVABILITY.md "Compute-plane
   roofline").

3. **Predicted time** — bytes/slots combined with the anchors into a
   predicted per-superstep time and a predicted work-rate per chip. The
   ``cost`` sub-record (:meth:`CostEstimate.record`) rides every
   ``plan_build`` / ``impl_selected`` / ``superstep_timing`` record, so
   every auto-policy decision ships the numbers that justified it, and
   ``tools/obs_report.py``'s roofline section can render achieved vs
   model from the JSONL alone.

The model is deliberately coarse — a per-superstep budget, not a
simulator. Its job is triage leverage: a window at 0.9x model is noise, a
window at 0.2x model is a real anomaly (imbalance, eviction, a degraded
part) worth reading the telemetry for *before* blaming the device
(docs/RUNBOOKS.md §12).

Import discipline: **stdlib only** — no jax, no numpy. Plan objects are
inspected by duck-typed attributes/shapes so this module loads on a
machine with no accelerator stack at all (the same contract as the rest
of ``obs/`` and both offline tools).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass

_I32 = 4  # bytes per int32/float32 slot — the compute plane's one word size

# ---- measured roofline anchors (single owner) ------------------------------
#
# Values are work-units per second PER CHIP. Provenance discipline: each
# anchor names the capture that seeded it; anchors nobody has measured on
# silicon yet say so ("model seed") and are exactly the ones a future
# capture should replace (tools/bench_diff.py --manifest names the
# pending tiers).
ROOFLINE_SEEDS: dict = {
    # Random-gather slots/s: BENCH_r04/r05 `roofline` tier, TPU v5 lite
    # (131.8M / 132.6M slots/s measured; ops/bucketed_mode.py header).
    # Governs the sort gather and every bucketed/blocked row reduce.
    "gather_slots_per_sec": 1.32e8,
    # Full binned-pass (stream + scatter) slots/s: SEEDED EQUAL to the
    # random gather pending the silicon `blocking` capture — the capture
    # whose `detail.binned_vs_random_gather` ratio is exactly the number
    # that should replace this seed AND move the BLOCKED_MIN_* crossover
    # constants (ROADMAP; tools/bench_diff.py prints the suggestion when
    # it lands).
    "binned_slots_per_sec": 1.32e8,
    # ICI exchange bytes/s per chip: NO bench tier measures this yet —
    # 4.5e10 B/s is a conservative v5e-interconnect model seed (order of
    # magnitude below the advertised peak; the sharded tier's silicon
    # capture is the natural place to measure it).
    "exchange_bytes_per_sec": 4.5e10,
    # Exact-kNN distance pairs/s: the r6 LOF crossover provenance table
    # (ops/lof.py): 65,536 points (=> 65,536^2 pairs) in 2.3 s on v5e.
    "lof_exact_pairs_per_sec": 1.87e9,
    # IVF-flat end-to-end points/s at crossover scale: same table,
    # 262,144 points in 9.0 s (candidate reduction included).
    "lof_ivf_points_per_sec": 2.9e4,
}

_SEED_PROVENANCE = {
    "gather_slots_per_sec": "BENCH_r04/r05 roofline tier (TPU v5e)",
    "binned_slots_per_sec": (
        "seeded = gather pending the silicon `blocking` capture"
    ),
    "exchange_bytes_per_sec": "model seed (unmeasured; no ICI bench tier yet)",
    "lof_exact_pairs_per_sec": "ops/lof.py r6 crossover table (65K in 2.3s)",
    "lof_ivf_points_per_sec": "ops/lof.py r6 crossover table (262K in 9.0s)",
}

# Padding the r4 width ladder measures when no plan exists yet to count
# exactly (~10% — docs/DESIGN.md "bucket ladder"): pre-plan estimates
# (the driver's plan-time impl_selected fires before the build) use it.
_EST_PAD = 1.10


def rooflines(overrides: dict | None = None) -> dict:
    """The active anchor set: ``{name: {"v": rate, "src": provenance}}``.

    Precedence per anchor: ``overrides`` arg (tests, a caller holding a
    fresh capture) → ``GRAPHMINE_ROOFLINE_<NAME>`` env var →
    ``GRAPHMINE_ROOFLINE_FILE`` JSON (``{name: rate}`` — the re-seed
    path docs/OBSERVABILITY.md describes for a new silicon capture) →
    the committed seed. Unknown names in the file/overrides are ignored
    (a newer file must not break an older reader); a malformed file or
    env value raises — a silently-dropped override would un-anchor the
    model without anyone noticing.
    """
    out = {
        k: {"v": float(v), "src": _SEED_PROVENANCE[k]}
        for k, v in ROOFLINE_SEEDS.items()
    }
    path = os.environ.get("GRAPHMINE_ROOFLINE_FILE")
    if path:
        with open(path) as f:
            loaded = json.load(f)
        if not isinstance(loaded, dict):
            raise ValueError(
                f"GRAPHMINE_ROOFLINE_FILE {path} must hold a JSON object "
                f"of anchor -> rate, got {type(loaded).__name__}"
            )
        for k, v in loaded.items():
            if k in out:
                out[k] = {"v": float(v), "src": f"file:{path}"}
    for k in out:
        env = os.environ.get(f"GRAPHMINE_ROOFLINE_{k.upper()}")
        if env:
            out[k] = {"v": float(env), "src": "env"}
    if overrides:
        for k, v in overrides.items():
            if k in out:
                out[k] = {"v": float(v), "src": "caller"}
    return out


@dataclass(frozen=True)
class CostEstimate:
    """Predicted per-superstep (or per-scoring-pass) cost for one
    operating point. All byte/slot figures are per superstep **per
    chip**; ``predicted_per_chip`` is the model's work rate in ``unit``
    (edges/s/chip for superstep families, points/s/chip for LOF)."""

    op: str
    family: str
    devices: int
    slots: int               # real message slots (no padding)
    padded_slots: int        # gathered slots incl. padding
    bytes_gathered: int
    bytes_scattered: int
    padding_overhead: float  # padded_slots / slots
    exchange_bytes: int      # ICI bytes per chip per superstep (0 fused)
    compute_seconds: float   # the model's compute share of one superstep
    exchange_seconds: float  # ... and its exchange share
    predicted_seconds: float  # compute + exchange
    predicted_per_chip: float
    unit: str
    roofline: dict           # the consulted anchors (+ provenance)

    def record(self) -> dict:
        """The ``cost`` sub-record (shape registered as
        ``obs.schema.COST_KEYS`` — a half-stamped copy fails validation
        like a half-stamped trace). This method is the SINGLE builder:
        ``tools/schema_lint.py`` flags inline ``cost={...}`` literals
        anywhere else in the package."""
        return {
            "family": self.family,
            "devices": self.devices,
            "slots": self.slots,
            "padded_slots": self.padded_slots,
            "bytes_gathered": self.bytes_gathered,
            "bytes_scattered": self.bytes_scattered,
            "padding_overhead": round(self.padding_overhead, 4),
            "exchange_bytes": self.exchange_bytes,
            "compute_seconds": _sig(self.compute_seconds),
            "exchange_seconds": _sig(self.exchange_seconds),
            "predicted_seconds": _sig(self.predicted_seconds),
            "predicted_per_chip": round(self.predicted_per_chip, 1),
            "unit": self.unit,
            "roofline": {k: a["v"] for k, a in self.roofline.items()}
            | {"provenance": "; ".join(
                f"{k}: {a['src']}" for k, a in sorted(self.roofline.items())
            )},
        }


def _sig(x: float, digits: int = 4) -> float:
    """Round to significant digits (predicted times span ns to minutes —
    fixed decimal places would zero the small ones)."""
    if x == 0:
        return 0.0
    from math import floor, log10

    return round(x, digits - 1 - floor(log10(abs(x))))


# ---- plan inspection (duck-typed: no jax import) ---------------------------


def _plan_family(plan) -> str:
    if plan is None:
        return "sort"
    if hasattr(plan, "padded_row_slots"):  # ops.blocking.BlockedPlan
        return "blocked"
    if hasattr(plan, "vertex_ids"):        # ops.bucketed_mode.BucketedModePlan
        return "bucketed"
    raise TypeError(f"unknown plan type {type(plan).__name__}")


def _bucketed_padded_slots(plan) -> int:
    mats = plan.send_idx if plan.send_idx is not None else plan.msg_idx
    slots = sum(int(m.shape[0]) * int(m.shape[1]) for m in mats or ())
    if plan.hist_send is not None:
        slots += int(plan.hist_send.shape[0])
    return slots


def _plan_weighted(plan) -> bool:
    return getattr(plan, "weight_mat", None) not in (None, ())


def _sharded_family(sg) -> str:
    """The plan family a built ``ShardedGraph`` runs — shapes only, no
    jax import (``getattr`` because pre-r16 pickled/stub shard objects
    lack the 2D fields). One owner for the cost, footprint and
    shard_exchange consumers."""
    if getattr(sg, "x2d_src_local", None) is not None:
        return "sharded_2d"
    if sg.blk_src is not None:
        return "blocked"
    if sg.bucket_send:
        return "bucketed"
    return "sort"


def allgather_exchange_bytes(sg) -> int:
    """The one-all_gather families' modeled per-chip exchange bytes per
    superstep — every chip receives the other ``D-1`` chunks of the
    padded label vector (``4·Vc·(D-1)``, the ROADMAP scaling ceiling).
    This is the 2D family's comparison ladder, so it has one owner."""
    return _I32 * int(sg.chunk_size) * max(int(sg.num_shards) - 1, 0)


def neighbor_exchange_bytes(sg) -> int:
    """The 2D family's modeled per-chip WIRE bytes per superstep: each
    of the D-1 ppermute shifts ships one buffer of the shared padded
    width B (SPMD needs one program, so every shard pays the max
    boundary), i.e. ``4·(D-1)·B`` — what actually crosses the ICI with
    the current shared-width implementation. On a skewed graph where
    one (shard, peer) boundary approaches Vc this honestly approaches
    the all_gather ladder; :func:`neighbor_frontier_bytes` is the
    unpadded floor a per-pair-width (or frontier-masked) refinement
    would approach."""
    d = max(int(sg.num_shards), 1)
    b = int(getattr(sg, "x2d_boundary", 0))
    return _I32 * (d - 1) * b


def neighbor_frontier_bytes(sg) -> int:
    """The 2D family's exact UNPADDED per-chip boundary bytes per
    superstep — ``4·Σ_peer |boundary(peer)|`` in the ISSUE's terms,
    fleet total divided across chips (ceil): the information content of
    the exchange, before the shared-SPMD-width padding
    :func:`neighbor_exchange_bytes` charges for."""
    d = max(int(sg.num_shards), 1)
    total = int(getattr(sg, "x2d_boundary_total", 0))
    return _I32 * -(-total // d)


# ---- superstep families ----------------------------------------------------


def superstep_cost(
    op: str,
    family: str,
    num_vertices: int,
    num_messages: int,
    num_edges: int,
    plan=None,
    weighted: bool | None = None,
    anchors: dict | None = None,
) -> CostEstimate:
    """Cost of ONE fused (single-device) superstep.

    With ``plan`` (a built BucketedModePlan / BlockedPlan) the padded
    slot counts are **exact** — read off the plan's own matrices; without
    one (the driver's plan-time ``impl_selected`` fires before the
    build, and the sort family never builds one) the r4-measured ~10%
    ladder padding estimates them. ``weighted`` adds the slot-aligned
    float32 weight gather to the byte/time model — weights double the
    gathered bytes, not the slots; the default ``None`` infers it from
    the plan's weight payload, while an explicit ``False`` models an op
    that ignores the payload (CC's min never reads weights even when the
    shared plan carries them).

    Model per family (docs/OBSERVABILITY.md "Compute-plane roofline"):

    - **sort**: one random gather of M label slots (the segment-mode
      sort rides inside the measured gather anchor), scatter V results.
    - **bucketed**: one random gather of the plan's padded slots
      (padding gathers the sentinel — same bandwidth), scatter V.
    - **blocked**: bin phase streams M slots at the binned-pass rate
      (monotone gather + tile scatter), reduce phase gathers the padded
      row slots tile-locally at the gather rate, scatter V.
    """
    a = anchors if anchors is not None else rooflines()
    if plan is not None:
        family = _plan_family(plan)
        if weighted is None:
            weighted = _plan_weighted(plan)
    weighted = bool(weighted)
    m = max(int(num_messages), 1)
    v = int(num_vertices)
    gather = a["gather_slots_per_sec"]["v"]
    binned = a["binned_slots_per_sec"]["v"]
    wf = 2 if weighted else 1
    if family == "sort":
        padded = m
        bytes_g = _I32 * m * wf
        bytes_s = _I32 * v
        compute = (m * wf) / gather
    elif family == "bucketed":
        padded = (
            _bucketed_padded_slots(plan) if plan is not None
            else int(m * _EST_PAD)
        )
        bytes_g = _I32 * padded * wf
        bytes_s = _I32 * v
        compute = (padded * wf) / gather
    elif family == "blocked":
        row_slots = (
            int(plan.padded_row_slots) if plan is not None
            else int(m * _EST_PAD)
        )
        padded = m + row_slots
        # stream pass gathers M label slots + scatters them into the
        # tile; reduce gathers the padded rows (and their weight mats).
        bytes_g = _I32 * (m + row_slots * wf)
        bytes_s = _I32 * m + _I32 * v
        compute = m / binned + (row_slots * wf) / gather
    else:
        raise ValueError(f"unknown superstep family {family!r}")
    return CostEstimate(
        op=op, family=family, devices=1,
        slots=m, padded_slots=padded,
        bytes_gathered=int(bytes_g), bytes_scattered=int(bytes_s),
        padding_overhead=padded / m,
        exchange_bytes=0,
        compute_seconds=compute, exchange_seconds=0.0,
        predicted_seconds=compute,
        predicted_per_chip=num_edges / compute if compute > 0 else 0.0,
        unit="edges/s/chip",
        roofline={
            k: a[k] for k in ("gather_slots_per_sec", "binned_slots_per_sec")
        },
    )


def sharded_superstep_cost(
    op: str,
    sg,
    num_edges: int,
    num_messages: int | None = None,
    weighted: bool | None = None,
    anchors: dict | None = None,
) -> CostEstimate:
    """Cost of ONE sharded superstep, derived from a built
    :class:`~graphmine_tpu.parallel.sharded.ShardedGraph` (shapes only —
    no device sync, no jax import; safe to call at operating-point build
    time on device-resident shards).

    Per-chip compute follows the shard's plan family — blocked bin
    groups (``blk_*``), the stacked bucket plan (``bucket_send``), or
    the sort shard body over the padded ``[D, Mp]`` message arrays — and
    the exchange term models the per-superstep label collective: every
    chip receives the other ``D-1`` chunks of the padded label vector —
    the same bytes whether they arrive as one all_gather (``replicated``)
    or ``D`` ppermute hops (``ring``), so one model serves both
    schedules.
    """
    a = anchors if anchors is not None else rooflines()
    d = int(sg.num_shards)
    gather = a["gather_slots_per_sec"]["v"]
    binned = a["binned_slots_per_sec"]["v"]
    exch_rate = a["exchange_bytes_per_sec"]["v"]
    if weighted is None:  # infer; explicit False models weight-blind ops (CC)
        weighted = (
            sg.msg_weight is not None
            or bool(sg.bucket_weight) or bool(sg.blk_row_weight)
        )
    wf = 2 if weighted else 1
    # NOTE: shard_graph_arrays(lpa_only=True) trims the sort-body arrays
    # (msg_send may be None on a bucketed/blocked partition) — each
    # family reads its padded slot count off its OWN arrays.
    x2d = getattr(sg, "x2d_src_local", None)
    if x2d is not None or sg.blk_src is not None:
        # One compute model for both bin-group families — same bin
        # tiles, same row reduce; the 2D family differs only in where
        # the stream gathers from (the compact table) and in the
        # exchange term set below.
        family = "sharded_2d" if x2d is not None else "blocked"
        stream = x2d if x2d is not None else sg.blk_src
        mp = int(stream.shape[1])            # padded stream slots/shard
        row_slots = sum(
            int(r.shape[1]) * int(r.shape[2]) for r in sg.blk_row_idx
        )
        padded = mp + row_slots
        bytes_g = _I32 * (mp + row_slots * wf)
        bytes_s = _I32 * mp + _I32 * int(sg.chunk_size)
        compute = mp / binned + (row_slots * wf) / gather
    elif sg.bucket_send:
        family = "bucketed"
        mp = None
        padded = sum(
            int(b.shape[1]) * int(b.shape[2]) for b in sg.bucket_send
        )
        bytes_g = _I32 * padded * wf
        bytes_s = _I32 * int(sg.chunk_size)
        compute = (padded * wf) / gather
    else:
        family = "sort"
        mp = int(sg.msg_send.shape[1])       # padded slots per shard
        padded = mp
        bytes_g = _I32 * mp * wf
        bytes_s = _I32 * int(sg.chunk_size)
        compute = (mp * wf) / gather
    m_total = (
        int(num_messages) if num_messages is not None
        else (mp if mp is not None else padded) * d
    )
    m_chip = max(m_total // max(d, 1), 1)    # real slots per chip (mean)
    # Exchange term: the one-all_gather families ship the other D-1
    # label chunks per chip; the 2D family ships one padded boundary
    # buffer per peer — the honest WIRE bytes, padding included (r16 —
    # the bytes drop the `exchange` bench tier and the acceptance pin
    # assert; neighbor_frontier_bytes is the unpadded floor).
    exchange_bytes = (
        neighbor_exchange_bytes(sg) if family == "sharded_2d"
        else allgather_exchange_bytes(sg)
    )
    exchange = exchange_bytes / exch_rate
    predicted = compute + exchange
    return CostEstimate(
        op=op, family=family, devices=d,
        slots=m_chip, padded_slots=padded,
        bytes_gathered=int(bytes_g), bytes_scattered=int(bytes_s),
        padding_overhead=padded / m_chip,
        exchange_bytes=int(exchange_bytes),
        compute_seconds=compute, exchange_seconds=exchange,
        predicted_seconds=predicted,
        predicted_per_chip=(
            num_edges / (predicted * d) if predicted > 0 else 0.0
        ),
        unit="edges/s/chip",
        roofline={
            k: a[k]
            for k in (
                "gather_slots_per_sec", "binned_slots_per_sec",
                "exchange_bytes_per_sec",
            )
        },
    )


# ---- LOF impls -------------------------------------------------------------


def lof_cost(
    impl: str,
    n: int,
    k: int,
    features: int = 8,
    devices: int = 1,
    anchors: dict | None = None,
) -> CostEstimate:
    """Cost of one LOF scoring pass over an ``[n, features]`` cloud.

    - **exact**: all-pairs distances — n² pairs at the measured
      pair rate (the top-k roofline is folded into that anchor); the
      ring-sharded scorer splits the rows, so pairs scale 1/D.
    - **ivf**: the end-to-end measured points/s at crossover scale —
      the candidate-reduction structure (inverted lists, probe fans)
      is data-dependent, so the model anchors on throughput rather
      than pretending to know the candidate count; ``slots`` reports
      the k-neighborhood gathers the LOF formula itself performs.
    """
    a = anchors if anchors is not None else rooflines()
    n = int(n)
    d = max(int(devices), 1)
    if impl not in ("exact", "ivf"):
        raise ValueError(f"unknown LOF impl family {impl!r}")
    if impl == "exact":
        pairs = n * n // d
        compute = pairs / a["lof_exact_pairs_per_sec"]["v"]
        slots = pairs
        bytes_g = _I32 * features * pairs
        keys = ("lof_exact_pairs_per_sec",)
    else:
        compute = n / (a["lof_ivf_points_per_sec"]["v"] * d)
        slots = n * max(k, 1) // d
        bytes_g = _I32 * features * slots
        keys = ("lof_ivf_points_per_sec",)
    return CostEstimate(
        op="lof_knn", family=impl, devices=d,
        slots=slots, padded_slots=slots,
        bytes_gathered=int(bytes_g), bytes_scattered=_I32 * n,
        padding_overhead=1.0,
        exchange_bytes=0,
        compute_seconds=compute, exchange_seconds=0.0,
        predicted_seconds=compute,
        predicted_per_chip=n / (compute * d) if compute > 0 else 0.0,
        unit="points/s/chip",
        roofline={key: a[key] for key in keys},
    )


# ---- achieved-vs-model emission -------------------------------------------


def emit_superstep_timing(
    sink,
    op: str,
    cost: CostEstimate | None,
    iteration: int,
    window: int,
    seconds: float,
    num_edges: int,
    variant: str | None = None,
    cold_compile: bool = False,
) -> dict | None:
    """Emit one ``superstep_timing`` record: achieved wall throughput for
    a window of ``window`` supersteps ending at ``iteration``, judged
    against ``cost``'s model. No-op without a sink or cost (a caller
    that could not build an estimate must not emit a record claiming
    one). The achieved fraction is predicted-time / achieved-time for
    the window — >1 means the model is conservative, far below 1 is the
    triage signal (docs/RUNBOOKS.md §12). Timing comes from the caller's
    EXISTING superstep sync (the driver already blocks per superstep for
    the labels-changed counter) — this adds zero device syncs.

    ``cold_compile=True`` marks a window whose wall time includes an XLA
    trace+compile (the ops fixpoint seams detect it via the jit cache —
    :func:`timed_fixpoint`): the record still ships the honest numbers,
    but obs_report's roofline section excludes such windows from the
    below-model flag — a compile-bearing window reading 0.05x model on
    healthy hardware is exactly the false positive the flag must not
    raise. (The driver-side windows need no marker: like its watchdog,
    the driver excludes each operating point's compile-bearing first
    superstep from the window instead.)
    """
    if sink is None or cost is None:
        return None
    window = max(int(window), 1)
    seconds = float(seconds)
    per_step = seconds / window
    achieved = (
        num_edges * window / seconds / max(cost.devices, 1)
        if seconds > 0 else 0.0
    )
    fraction = (
        cost.predicted_seconds / per_step if per_step > 0 else 0.0
    )
    return sink.emit(
        "superstep_timing",
        op=op,
        family=cost.family,
        variant=variant if variant is not None else cost.family,
        iteration=int(iteration),
        window=window,
        seconds=round(seconds, 6),
        edges_per_sec_per_chip=round(achieved),
        predicted_edges_per_sec_per_chip=round(cost.predicted_per_chip),
        # significant digits, not decimal places: a 1e-6 fraction (tiny
        # CPU smoke runs are dispatch-dominated) must not round to a
        # report-breaking 0.0
        achieved_fraction=_sig(fraction),
        devices=cost.devices,
        cold_compile=bool(cold_compile),
        cost=cost.record(),
    )


def emit_shard_exchange(sink, op: str, sg, **kv) -> dict | None:
    """Emit one ``shard_exchange`` record: the modeled per-chip ICI bytes
    of the shard family that actually ran next to the one-all_gather
    ladder model (``4·Vc·(D-1)``), with the frontier fraction — what
    share of a full label exchange the per-peer boundary tables actually
    ship (1.0 for the one-all_gather families by construction). This is
    the record's single emission point (the ``emit_memory_watermark``
    contract); emitted at the existing telemetry cadence — once per
    sharded repair apply on the serve path (the ``exchange`` bench tier
    carries the same modeled numbers in its per-D ``detail`` rows
    rather than a sink stream). No-op without a sink.

    ``exchange_bytes`` is the WIRE model (padded shared-width buffers —
    what actually ships); ``frontier_bytes`` the exact unpadded
    boundary content, and ``frontier_frac`` its share of the ladder —
    together with ``boundary_slots`` (fleet-total unpadded count) and
    ``padded_boundary`` (the shared SPMD width B) they say how much of
    the exchange is frontier vs padding (the 2D analog of
    ``padding_overhead``)."""
    if sink is None:
        return None
    family = _sharded_family(sg)
    d = int(sg.num_shards)
    ladder = allgather_exchange_bytes(sg)
    if family == "sharded_2d":
        modeled = neighbor_exchange_bytes(sg)
        frontier = neighbor_frontier_bytes(sg)
    else:
        modeled = frontier = ladder
    frac = frontier / ladder if ladder else 1.0
    return sink.emit(
        "shard_exchange",
        op=op,
        family=family,
        devices=d,
        peers=max(d - 1, 0),
        exchange_bytes=int(modeled),
        frontier_bytes=int(frontier),
        ladder_bytes=int(ladder),
        frontier_frac=round(frac, 4),
        boundary_slots=int(getattr(sg, "x2d_boundary_total", 0)),
        padded_boundary=int(getattr(sg, "x2d_boundary", 0)),
        **kv,
    )


class WindowTimer:
    """Tiny accumulator for the driver's per-window wall timing: add each
    superstep's already-measured duration, emit at the telemetry cadence,
    reset on operating-point changes. Host-only; no device interaction."""

    def __init__(self):
        self.seconds = 0.0
        self.steps = 0

    def add(self, seconds: float) -> None:
        self.seconds += float(seconds)
        self.steps += 1

    def reset(self) -> None:
        self.seconds = 0.0
        self.steps = 0

    def flush(
        self, sink, op, cost, iteration, num_edges, variant=None
    ) -> dict | None:
        """Emit the window accumulated so far (if any) and reset."""
        if not self.steps:
            return None
        rec = emit_superstep_timing(
            sink, op, cost, iteration, self.steps, self.seconds,
            num_edges, variant=variant,
        )
        self.reset()
        return rec


def timed_fixpoint(fn, jit_fn=None):
    """``(result, seconds, cold_compile)`` with the result's device work
    completed — shared by the ops-layer fixpoint wrappers (cc/pagerank/
    LPA auto seams) so a jitted while_loop's wall time covers the actual
    compute, not the dispatch. ``fn`` returns a jax array or a tuple
    whose first element is one; duck-typed so this module stays
    jax-free.

    ``jit_fn``: the underlying jitted callable — when its executable
    cache grew across the call, this window paid an XLA trace+compile
    and ``cold_compile`` comes back True (the caller stamps it on the
    timing record so the roofline flag skips the window). Detection is
    best-effort via the private ``_cache_size`` probe: absent the probe,
    windows are reported un-marked rather than guessed at."""
    probe = getattr(jit_fn, "_cache_size", None)
    before = probe() if callable(probe) else None
    t0 = time.perf_counter()
    out = fn()
    head = out[0] if isinstance(out, tuple) else out
    block = getattr(head, "block_until_ready", None)
    if block is not None:
        block()
    seconds = time.perf_counter() - t0
    cold = before is not None and probe() > before
    return out, seconds, cold
