"""Analytical memory-plane model: what a plan SHOULD cost in HBM.

The compute plane has achieved-vs-model attribution (``obs/costmodel.py``,
r13) and the product has a quality plane (r14) — but memory, the resource
every degradation ladder actually trips on, was modeled blind: the
planner picked schedules against hand-seeded byte constants and nothing
ever measured whether a run's real HBM peak matched the plan. This
module is the memory plane's single owner (ISSUE 14), the direct
analogue of the cost model, in the same GraphBLAST / propagation-blocking
tradition (PAPERS arXiv 1908.01407, 2011.08451) where explicit workspace
budgets ARE the scaling argument:

1. **Per-plan footprint inventory** — for every superstep family (sort /
   bucketed / blocked, fused and sharded) and LOF impl, derive a named
   byte inventory **directly off the already-built plan/graph objects**:
   CSR arrays, bucketed width-ladder mats, BlockedPlan stream+tile
   slots, sharded twins plus the per-superstep all_gather exchange
   buffer, LOF exact distance/top-k workspace vs IVF cluster-batched
   workspace, weighted payload doubling
   (:func:`superstep_footprint`, :func:`sharded_superstep_footprint`,
   :func:`lof_footprint`). With a plan the counts are exact (the plan's
   own matrix shapes); without one the estimate is anchored to the seed
   constants below, so the pre-build view can never disagree with the
   planner's accept/reject arithmetic.

2. **One inventory, two consumers** — the byte seeds below
   (:data:`BYTES_PER_EDGE` …) are THE constants
   ``pipeline/planner.py``'s schedule model is derived from
   (``estimate_bytes_per_device`` delegates to
   :func:`schedule_bytes_per_device`); the same seeds decompose into the
   :func:`schedule_inventory` components the ``plan`` record ships. A
   recalibration (obs_report's memory section suggests one when measured
   peaks drift from model) therefore moves the planner and the records
   together, never one without the other.

3. **Measured watermarks** — :func:`emit_memory_watermark` is the single
   builder of schema-registered ``memory_watermark`` records (predicted
   vs achieved bytes + ``headroom_frac``), fed by the driver's
   ``memory_stats()`` samples at the existing phase/rung/telemetry
   cadence (``memory_stats`` is a host-side allocator query — zero extra
   device syncs) with host RSS as the backend-less fallback. The ``mem``
   sub-record (:meth:`MemEstimate.record`) mirrors the ``cost``
   sub-record: one builder, all-or-nothing validation
   (``obs.schema.MEM_KEYS``), ``tools/schema_lint.py`` flags inline
   ``mem={...}`` literals anywhere else.

The model is deliberately coarse — a per-phase budget, not an allocator
simulator. Its job is triage leverage: a rung whose predicted footprint
exceeds budget pre-degrades at plan time with the inventory in the
record (:func:`predegrade_superstep`), and a reactive OOM's degrade
record carries the last watermark + inventory so model-miss vs
fragmentation is triageable from the JSONL alone (docs/RUNBOOKS.md §14).

Import discipline: **stdlib only** — no jax, no numpy. Plan objects are
inspected by duck-typed attributes/shapes so this module loads on a
machine with no accelerator stack at all (the ``obs/`` contract).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from math import sqrt

from graphmine_tpu.obs.costmodel import (
    _bucketed_padded_slots,
    _plan_family,
    _plan_weighted,
)

_I32 = 4  # bytes per int32/float32 slot — the one word size

# ---- byte seeds (single owner) ---------------------------------------------
#
# The DESIGN.md-measured schedule model, decomposed: ``36 B/edge`` on the
# fused path (edge endpoints + message CSR + plan mats + gather
# transient), ``16 B/edge`` more when weighted (msg weights + slot-
# aligned weight mats), and the per-vertex label/exchange terms of each
# schedule. ``pipeline/planner.py`` derives its ``_BYTES_PER_*``
# constants FROM these — edit here, both consumers move.
BYTES_PER_EDGE = 36.0
BYTES_PER_EDGE_WEIGHTED = 16.0
SINGLE_BYTES_PER_VERTEX = 8.0
REPLICATED_BYTES_PER_VERTEX = 16.0
RING_BYTES_PER_VERTEX = 24.0  # divided by D (labels are sharded)

# Pre-plan tile estimate for the blocked family (the real plan knows its
# ``tile_alloc`` exactly): one bin's message-tile budget, mirroring
# ops/blocking.DEFAULT_TILE_SLOTS (2^18 slots = 1 MiB) without importing
# the jax-loading ops layer.
BLOCKED_TILE_SLOTS_EST = 1 << 18

# IVF cluster-batch balance pad (model seed): real Qmax/Lmax are
# data-dependent cluster sizes; the model assumes balanced clusters of
# n/C padded by this factor (k-means imbalance at the measured scales —
# ops/ann.py pads to the true max).
IVF_BALANCE_PAD = 2.0

# The family ladder the plan-time pre-degrade walks — the same
# sharded_2d -> blocked -> bucketed -> sort order as
# planner._SUPERSTEP_DEGRADE (sort is the floor: None, nothing leaner
# exists; sharded_2d's rung drops the per-peer boundary tables back to
# the one-all_gather exchange).
FAMILY_DEGRADE = {
    "sharded_2d": "blocked", "blocked": "bucketed", "bucketed": "sort",
    "sort": None,
}


@dataclass(frozen=True)
class MemEstimate:
    """Predicted peak HBM footprint for one operating point, as a named
    per-device byte inventory. ``exact=True`` when the counts were read
    off a built plan's real matrix shapes; False for pre-build estimates
    (the ~10% ladder pad) and structural model seeds (IVF batches)."""

    op: str
    family: str          # superstep family / LOF impl / schedule name
    devices: int
    weighted: bool
    inventory: dict      # component -> bytes per device
    exact: bool
    unit: str = "bytes/device"

    @property
    def total_bytes(self) -> int:
        return int(sum(self.inventory.values()))

    def record(self) -> dict:
        """The ``mem`` sub-record (shape registered as
        ``obs.schema.MEM_KEYS`` — a half-stamped copy fails validation
        like a half-stamped cost record). This method is the SINGLE
        builder: ``tools/schema_lint.py`` flags inline ``mem={...}``
        literals anywhere else in the package."""
        return {
            "family": self.family,
            "devices": self.devices,
            "weighted": self.weighted,
            "total_bytes": self.total_bytes,
            "inventory": {
                k: int(v) for k, v in sorted(self.inventory.items())
            },
            "exact": self.exact,
            "unit": self.unit,
        }


# ---- schedule model (the planner's consumer) -------------------------------


def schedule_bytes_per_device(
    schedule: str,
    num_vertices: int,
    num_edges: int,
    num_devices: int,
    weighted: bool = False,
) -> int:
    """Modeled peak HBM per device for a whole-run ``schedule`` — the
    EXACT arithmetic ``pipeline/planner.py`` has always planned against
    (one ``int()`` over the float sum, so the planner's accept/reject
    decisions are bit-identical to the pre-ISSUE-14 constants)."""
    v = float(num_vertices)
    e = float(num_edges)
    d = float(max(num_devices, 1))
    edge = BYTES_PER_EDGE + (BYTES_PER_EDGE_WEIGHTED if weighted else 0.0)
    if schedule == "single":
        return int(edge * e + SINGLE_BYTES_PER_VERTEX * v)
    if schedule == "replicated":
        return int(edge * e / d + REPLICATED_BYTES_PER_VERTEX * v)
    if schedule == "ring":
        return int(edge * e / d + RING_BYTES_PER_VERTEX * v / d)
    raise ValueError(f"unknown schedule {schedule!r}")


def schedule_inventory(
    schedule: str,
    num_vertices: int,
    num_edges: int,
    num_devices: int = 1,
    weighted: bool = False,
) -> dict:
    """The seed constants decomposed into named components (per device).
    Component sums reproduce :func:`schedule_bytes_per_device` to within
    per-term rounding: 36 B/edge = endpoints 8 + CSR 16 + plan mats 6 +
    gather transient 6; weighted adds msg weights 8 + weight mats 8; the
    per-vertex terms are each schedule's label/exchange model."""
    v = float(num_vertices)
    e = float(num_edges)
    d = float(max(num_devices, 1))
    div = 1.0 if schedule == "single" else d
    inv = {
        "edge_endpoints": 8.0 * e / div,
        "message_csr": 16.0 * e / div,
        "plan_mats": 6.0 * e / div,
        "gather_transient": 6.0 * e / div,
    }
    if weighted:
        inv["msg_weights"] = 8.0 * e / div
        inv["weight_mats"] = 8.0 * e / div
    if schedule == "single":
        inv["labels"] = 8.0 * v
    elif schedule == "replicated":
        inv["labels_replicated"] = 8.0 * v
        inv["exchange_buffer"] = 8.0 * v
    elif schedule == "ring":
        inv["labels_sharded"] = 8.0 * v / d
        inv["ring_chunks"] = 16.0 * v / d
    else:
        raise ValueError(f"unknown schedule {schedule!r}")
    return {k: int(b) for k, b in inv.items()}


def schedule_footprint(
    schedule: str,
    num_vertices: int,
    num_edges: int,
    num_devices: int = 1,
    weighted: bool = False,
    op: str = "run_plan",
) -> MemEstimate:
    """The whole-run schedule model as a :class:`MemEstimate` — what the
    driver's ``plan`` record ships alongside the planner's verdict."""
    return MemEstimate(
        op=op, family=schedule, devices=max(int(num_devices), 1),
        weighted=bool(weighted),
        inventory=schedule_inventory(
            schedule, num_vertices, num_edges, num_devices, weighted
        ),
        exact=False,
    )


# ---- fused superstep families ----------------------------------------------


def superstep_footprint(
    op: str,
    family: str,
    num_vertices: int,
    num_messages: int,
    num_edges: int | None = None,
    plan=None,
    weighted: bool | None = None,
    num_devices: int = 1,
) -> MemEstimate:
    """Footprint of ONE fused (single-device) superstep operating point.

    With a built ``plan`` the counts are EXACT — the plan's own matrix
    shapes: edge endpoints + the message CSR + labels in/out + msg
    weights, plus per family the width-ladder mats + vertex ids (+
    slot-aligned weight mats) + the gathered transient (bucketed), or
    the sender-major stream pair + the destination-binned tile + reduce
    rows + owners (+ weight mats) + the row-gather transient (blocked).

    WITHOUT a plan (the driver's plan-time pre-degrade fires before any
    build) the estimate is anchored to the SAME seed constants the
    planner accepted the run with — the fused bucketed path IS the
    measured ``BYTES_PER_EDGE`` model, so ``bucketed`` reproduces
    :func:`schedule_inventory`'s single-device decomposition exactly
    (the two consumers can never disagree about the path the planner
    just admitted, so an admitted run never spuriously pre-degrades),
    ``sort`` drops the plan-mats term (the planner's documented
    degradation saving), and ``blocked`` adds the stream pair + tile
    the 36 B/edge seed predates.

    ``num_devices`` (r16): pre-build estimates for a SHARDED operating
    point — the ``sharded_2d`` family (only meaningful there) models the
    per-chip sharded edge arrays + stream/tile + SHARDED labels + the
    per-peer boundary tables at their worst case (boundary = the whole
    peer chunk: the pre-build view cannot know the real boundary, and an
    over-estimate pre-degrades where an under-estimate OOMs); the
    one-all_gather families with ``num_devices > 1`` model the
    replicated schedule's per-chip twin (sharded edge terms + the
    replicated label pair + exchange buffer) so a sharded_2d → blocked
    pre-degrade walk compares per-chip against per-chip. Existing
    single-device callers (``num_devices=1``) are bit-identical to the
    pre-r16 arithmetic.
    """
    if plan is not None:
        family = _plan_family(plan)
        if weighted is None:
            weighted = _plan_weighted(plan)
    weighted = bool(weighted)
    v = int(num_vertices)
    m = max(int(num_messages), 1)
    e = int(num_edges) if num_edges is not None else m // 2
    d = max(int(num_devices), 1)
    if family not in ("sort", "bucketed", "blocked", "sharded_2d"):
        raise ValueError(f"unknown superstep family {family!r}")
    if family == "sharded_2d" and d < 2:
        raise ValueError(
            "family 'sharded_2d' needs num_devices >= 2 (its per-peer "
            "exchange tables have no single-device meaning)"
        )
    if plan is None and family == "sharded_2d":
        vc = -(-v // d)
        mc = -(-m // d)
        base = schedule_inventory("single", v, e, 1, weighted)
        inv = {k: b // d for k, b in base.items() if k != "labels"}
        inv["stream"] = 2 * _I32 * mc
        inv["tile"] = _I32 * min(mc, BLOCKED_TILE_SLOTS_EST)
        inv["labels_sharded"] = 2 * _I32 * vc
        inv["exchange_send_tab"] = _I32 * vc * (d - 1)
        inv["exchange_recv_bufs"] = _I32 * vc * (d - 1)
        return MemEstimate(
            op=op, family=family, devices=d, weighted=weighted,
            inventory=inv, exact=False,
        )
    if plan is None:
        # Seed-anchored estimates (see docstring): the bucketed path is
        # the measured schedule model verbatim, so an admitted run can
        # never pre-degrade off the family the planner just accepted.
        if d > 1:
            inv = schedule_inventory("replicated", v, e, d, weighted)
        else:
            inv = schedule_inventory("single", v, e, 1, weighted)
        if family == "sort":
            del inv["plan_mats"]
        elif family == "blocked":
            mc = -(-m // d)
            inv["stream"] = 2 * _I32 * mc
            inv["tile"] = _I32 * min(mc, BLOCKED_TILE_SLOTS_EST)
        return MemEstimate(
            op=op, family=family, devices=d, weighted=weighted,
            inventory=inv, exact=False,
        )
    inv = {
        "edge_endpoints": 2 * _I32 * e,
        "message_csr": _I32 * (2 * m + v + 1),
        "labels": 2 * _I32 * v,
    }
    if weighted:
        inv["msg_weights"] = _I32 * m
    if family == "sort":
        inv["gather_transient"] = _I32 * m * (2 if weighted else 1)
    elif family == "bucketed":
        padded = _bucketed_padded_slots(plan)
        ids = sum(int(x.shape[0]) for x in (plan.vertex_ids or ()))
        if plan.hist_vertex_ids is not None:
            ids += int(plan.hist_vertex_ids.shape[0])
        inv["plan_mats"] = _I32 * padded
        inv["plan_vertex_ids"] = _I32 * ids
        if weighted:
            inv["weight_mats"] = _I32 * padded
        inv["gather_transient"] = _I32 * padded
    else:
        rows = int(plan.padded_row_slots)
        owners = sum(int(r.shape[0]) for r in plan.row_idx)
        inv["stream"] = 2 * _I32 * m
        inv["tile"] = _I32 * int(plan.tile_alloc)
        inv["reduce_rows"] = _I32 * rows
        inv["row_vertex"] = _I32 * owners
        if weighted:
            inv["weight_mats"] = _I32 * rows
        inv["gather_transient"] = _I32 * rows
    return MemEstimate(
        op=op, family=family, devices=1, weighted=weighted,
        inventory=inv, exact=True,
    )


# ---- sharded supersteps ----------------------------------------------------


def _per_chip_bytes(arr) -> int:
    """Per-chip bytes of one stacked ``[D, ...]`` shard array."""
    n = 1
    for s in arr.shape[1:]:
        n *= int(s)
    return _I32 * n


def sharded_superstep_footprint(
    op: str,
    sg,
    weighted: bool | None = None,
    schedule: str = "replicated",
) -> MemEstimate:
    """Per-chip footprint of ONE sharded superstep, derived from a built
    ``ShardedGraph`` (shapes only — no device sync, no jax import; the
    ``sharded_superstep_cost`` contract).

    The shard arrays are counted at their REAL stacked shapes (the
    sharded twins of the fused inventory, padding included); the label
    terms follow ``schedule``: ``replicated`` holds the full label
    vector + updated copy plus the per-superstep all_gather exchange
    buffer, ``ring`` keeps labels sharded with two rotating ppermute
    chunks + staging (no replicated V-term at all — exactly why it is
    the planner's memory floor)."""
    d = int(sg.num_shards)
    vc = int(sg.chunk_size)
    v = int(sg.num_vertices)
    if weighted is None:
        weighted = (
            sg.msg_weight is not None
            or bool(sg.bucket_weight) or bool(sg.blk_row_weight)
        )
    weighted = bool(weighted)
    # NOTE: shard_graph_arrays(lpa_only=True) trims the sort-body CSR
    # (msg_recv_local/msg_send/degrees may all be None on a bucketed or
    # blocked partition) — count only the arrays that exist, exactly
    # like sharded_superstep_cost.
    inv: dict = {}
    if sg.degrees is not None:
        inv["degrees"] = _per_chip_bytes(sg.degrees)
    msgs = 0
    if sg.msg_recv_local is not None:
        msgs += _per_chip_bytes(sg.msg_recv_local)
    if sg.msg_send is not None:
        msgs += _per_chip_bytes(sg.msg_send)
    if msgs:
        inv["shard_messages"] = msgs
    if sg.msg_weight is not None:
        inv["msg_weights"] = _per_chip_bytes(sg.msg_weight)
    if getattr(sg, "x2d_src_local", None) is not None:
        family = "sharded_2d"
        inv["stream"] = (
            _per_chip_bytes(sg.x2d_src_local) + _per_chip_bytes(sg.blk_pos)
        )
        inv["tile"] = _I32 * int(sg.blk_tile_alloc)
        rows = sum(_per_chip_bytes(r) for r in sg.blk_row_idx)
        inv["reduce_rows"] = rows
        inv["row_vertex"] = sum(
            _per_chip_bytes(t) for t in sg.blk_row_target
        )
        if sg.blk_row_weight:
            inv["weight_mats"] = sum(
                _per_chip_bytes(w) for w in sg.blk_row_weight
            )
        inv["gather_transient"] = rows
        # the per-peer boundary plan: one send table + one received
        # buffer set per peer offset, both at the padded [D-1, B] shape
        inv["exchange_send_tab"] = _per_chip_bytes(sg.x2d_send_tab)
        inv["exchange_recv_bufs"] = _per_chip_bytes(sg.x2d_send_tab)
        # labels stay SHARDED (current + updated chunk) — the whole
        # point: no replicated V-term regardless of `schedule`
        inv["labels_sharded"] = 2 * _I32 * vc
        return MemEstimate(
            op=op, family=family, devices=d, weighted=weighted,
            inventory=inv, exact=True,
        )
    if sg.blk_src is not None:
        family = "blocked"
        inv["stream"] = (
            _per_chip_bytes(sg.blk_src) + _per_chip_bytes(sg.blk_pos)
        )
        inv["tile"] = _I32 * int(sg.blk_tile_alloc)
        rows = sum(_per_chip_bytes(r) for r in sg.blk_row_idx)
        inv["reduce_rows"] = rows
        inv["row_vertex"] = sum(
            _per_chip_bytes(t) for t in sg.blk_row_target
        )
        if sg.blk_row_weight:
            inv["weight_mats"] = sum(
                _per_chip_bytes(w) for w in sg.blk_row_weight
            )
        inv["gather_transient"] = rows
    elif sg.bucket_send:
        family = "bucketed"
        mats = sum(_per_chip_bytes(b) for b in sg.bucket_send)
        inv["plan_mats"] = mats
        inv["plan_vertex_ids"] = sum(
            _per_chip_bytes(t) for t in sg.bucket_target
        )
        if sg.bucket_weight:
            inv["weight_mats"] = sum(
                _per_chip_bytes(w) for w in sg.bucket_weight
            )
        inv["gather_transient"] = mats
    else:
        family = "sort"
        inv["gather_transient"] = msgs // (2 if sg.msg_send is not None
                                           and sg.msg_recv_local is not None
                                           else 1)
    if schedule == "ring":
        inv["labels_sharded"] = 2 * _I32 * vc
        inv["ring_chunks"] = 2 * _I32 * vc
        inv["exchange_staging"] = 2 * _I32 * vc
    else:
        inv["labels_replicated"] = 2 * _I32 * v
        inv["exchange_buffer"] = 2 * _I32 * vc * d
    return MemEstimate(
        op=op, family=family, devices=d, weighted=weighted,
        inventory=inv, exact=True,
    )


# ---- LOF impls -------------------------------------------------------------


def ivf_model_clusters(n: int) -> int:
    """Mirror of ``ops/ann.default_n_clusters`` (~sqrt(N), rounded to a
    multiple of 8, min 8) — duplicated here as a model seed because the
    ops layer imports jax and this module must not."""
    return max(8, int(round(sqrt(max(int(n), 1)) / 8)) * 8)


def lof_footprint(
    impl: str,
    n: int,
    k: int,
    features: int = 8,
    devices: int = 1,
) -> MemEstimate:
    """Workspace footprint of one LOF scoring pass over ``[n, features]``.

    - **exact**: the ``[rows, n]`` all-pairs distance tile (the
      ring-sharded scorer splits the rows 1/D) + the top-k
      distance/index workspace.
    - **ivf**: centers + assignments + ONE cluster-batched search block
      (query block, distance block, per-batch top-k) under the balanced-
      cluster model (``n/C`` padded by :data:`IVF_BALANCE_PAD`); the
      real Qmax/Lmax are data-dependent, which is exactly why the
      measured watermark rides next to this estimate.
    """
    n = int(n)
    k = max(int(k), 1)
    f = int(features)
    d = max(int(devices), 1)
    if impl not in ("exact", "ivf"):
        raise ValueError(f"unknown LOF impl family {impl!r}")
    inv: dict = {"features": _I32 * n * f, "scores": _I32 * n}
    if impl == "exact":
        rows = -(-n // d)
        inv["distance_tile"] = _I32 * rows * n
        inv["topk_workspace"] = 2 * _I32 * rows * k
    else:
        c = ivf_model_clusters(n)
        b = int(IVF_BALANCE_PAD * n / c) + 1
        inv["centers"] = _I32 * c * f
        inv["assignments"] = 2 * _I32 * n
        inv["cluster_batch"] = _I32 * (b * f + b * b + 2 * b * k)
    return MemEstimate(
        op="lof_knn", family=impl, devices=d, weighted=False,
        inventory=inv, exact=False,
    )


# ---- plan-time pre-degrade -------------------------------------------------


def predegrade_superstep(
    family: str,
    num_vertices: int,
    num_messages: int,
    num_edges: int,
    weighted: bool,
    budget_bytes: int,
    num_devices: int = 1,
):
    """Walk the family ladder at PLAN time until the modeled footprint
    fits ``budget_bytes`` — the proactive twin of the driver's reactive
    OOM rungs: a rung the model already knows cannot fit is consumed
    before any device allocation, with the oversized inventory in the
    degrade record instead of an XLA OOM minutes later.

    Returns ``(family, fit_estimate, steps)`` where ``steps`` is the
    ``(from_family, to_family, oversized_estimate)`` descent trail
    (empty = the requested family fits). The sort floor is returned
    even when it does not fit: there is nothing leaner, and the
    planner's schedule model already accepted the run — the reactive
    ladder (and the watermark trail) owns whatever happens next.

    ``num_devices`` (r16): a ``sharded_2d`` starting rung — whose NEW
    plan-time terms are the per-peer boundary tables, modeled at their
    worst case — walks back to the one-all_gather ``blocked`` family and
    onward; every rung is then modeled per-chip on the same mesh."""
    budget = int(budget_bytes)
    steps = []
    while True:
        est = superstep_footprint(
            "lpa_superstep", family, num_vertices, num_messages,
            num_edges=num_edges, weighted=weighted,
            num_devices=num_devices,
        )
        nxt = FAMILY_DEGRADE.get(family)
        if est.total_bytes <= budget or nxt is None:
            return family, est, steps
        steps.append((family, nxt, est))
        family = nxt


# ---- measured watermarks ---------------------------------------------------


def rss_sample() -> dict | None:
    """Host-RSS fallback measurement for backends whose allocator does
    not report ``memory_stats()`` (CPU smoke runs, some tunneled
    runtimes) — the watermark then says so (``source: "rss"``) instead
    of silently comparing device model against nothing."""
    from graphmine_tpu.obs.heartbeat import rss_mb

    rss = rss_mb()
    if rss is None:
        return None
    b = int(rss * (1 << 20))
    return {"bytes_in_use": b, "peak_bytes_in_use": b, "source": "rss"}


def emit_memory_watermark(
    sink,
    op: str,
    est: MemEstimate | None,
    measured: dict | None,
    budget_bytes: int | None = None,
    **kv,
) -> dict | None:
    """Emit one ``memory_watermark`` record: the operating point's
    predicted footprint next to the measured bytes (device allocator or
    RSS fallback), plus ``headroom_frac`` against the planning budget.
    No-op without a sink, estimate or measurement (a record claiming a
    comparison neither side made would poison the waterfall). This is
    the record's single emission point — the schema-registered shape and
    the ``mem`` sub-record builder live together.

    ``achieved_bytes`` is the CURRENT ``bytes_in_use`` at the sampling
    boundary — the phase-attributable number the waterfall and the
    recalibration suggestion compare against this phase's model.
    ``peak_bytes_in_use`` is a process-LIFETIME allocator high-water
    mark (no portable reset exists), so it rides the record as context
    and drives ``headroom_frac`` (how close the PROCESS ever came to the
    budget — the conservative OOM-forecast number), but is never
    attributed to the phase that happened to sample it."""
    if sink is None or est is None or not measured:
        return None
    achieved = measured.get("bytes_in_use")
    if achieved is None:
        achieved = measured.get("peak_bytes_in_use")
    if achieved is None:
        return None
    achieved = int(achieved)
    headroom = None
    # headroom is only meaningful when the measurement and the budget
    # live in the same domain: a host-RSS fallback judged against a
    # per-device HBM budget would print a confident nonsense fraction
    # (and trip low-headroom rules on zero device pressure).
    if budget_bytes and measured.get("source", "device") == "device":
        worst = int(measured.get("peak_bytes_in_use") or achieved)
        headroom = round((int(budget_bytes) - worst) / int(budget_bytes), 4)
    rec = dict(
        op=op,
        predicted_bytes=est.total_bytes,
        achieved_bytes=achieved,
        headroom_frac=headroom,
        source=measured.get("source", "device"),
        mem=est.record(),
        **kv,
    )
    if budget_bytes:
        rec["budget_bytes"] = int(budget_bytes)
    for opt in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        if measured.get(opt) is not None:
            rec[opt] = int(measured[opt])
    return sink.emit("memory_watermark", **rec)


# ---- serve-process accounting ---------------------------------------------


def serve_mem_budget_bytes() -> int | None:
    """The serve-process memory budget headroom is judged against:
    ``GRAPHMINE_SERVE_MEM_BUDGET_BYTES`` (malformed raises loudly — the
    AdmissionBounds discipline) falling back to host ``MemTotal``
    (/proc/meminfo), None where neither exists."""
    raw = os.environ.get("GRAPHMINE_SERVE_MEM_BUDGET_BYTES")
    if raw:
        try:
            return int(float(raw))
        except ValueError as e:
            raise ValueError(
                f"GRAPHMINE_SERVE_MEM_BUDGET_BYTES={raw!r} is not a number"
            ) from e
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return None


# The graphmine_memory_* gauge surface — ONE owner for the metric names
# and help strings (server /metrics, the fleet router, and the WAL's
# segment accounting all export from this table; registry.gauge is
# get-or-create with first-help-wins, so duplicated literals would
# silently diverge).
MEMORY_GAUGE_HELP = {
    "graphmine_memory_rss_bytes":
        "resident set size of this serve process",
    "graphmine_memory_snapshot_bytes":
        "array bytes of the snapshot currently serving queries",
    "graphmine_memory_index_bytes":
        "derived query-index bytes (adjacency, census, explain)",
    "graphmine_memory_wal_segment_bytes":
        "bytes held by retained write-ahead-log segments",
    "graphmine_memory_headroom_frac":
        "fraction of the process memory budget still free",
}

_GAUGE_OF_KEY = {
    "rss_bytes": "graphmine_memory_rss_bytes",
    "snapshot_bytes": "graphmine_memory_snapshot_bytes",
    "index_bytes": "graphmine_memory_index_bytes",
    "wal_segment_bytes": "graphmine_memory_wal_segment_bytes",
    "headroom_frac": "graphmine_memory_headroom_frac",
}


def export_memory_gauges(registry, payload: dict) -> None:
    """Mirror a memory payload's present keys into the
    ``graphmine_memory_*`` gauges (absent/None keys leave their gauge
    untouched — a router payload has no snapshot bytes to zero out)."""
    for key, name in _GAUGE_OF_KEY.items():
        val = payload.get(key)
        if val is not None:
            registry.gauge(name, MEMORY_GAUGE_HELP[name]).set(val)


def host_memory(budget_bytes: int | None = None) -> dict:
    """RSS + headroom for one serve process — the shared core of the
    replica's and the fleet router's ``/statusz`` memory sections."""
    from graphmine_tpu.obs.heartbeat import rss_mb

    rss = rss_mb()
    rss_bytes = int(rss * (1 << 20)) if rss is not None else None
    headroom = None
    if budget_bytes and rss_bytes is not None:
        headroom = round((budget_bytes - rss_bytes) / budget_bytes, 4)
    return {
        "rss_bytes": rss_bytes,
        "budget_bytes": budget_bytes,
        "headroom_frac": headroom,
    }
