"""Run-correlated tracing & telemetry (docs/OBSERVABILITY.md).

Stdlib-only leaf package — safe to import from anywhere in the pipeline
(nothing here imports jax, and :mod:`graphmine_tpu.pipeline.metrics`
builds on it, not the other way around):

- :mod:`graphmine_tpu.obs.spans`      hierarchical span context
  (run_id -> phase -> rung -> superstep) with monotonic timings;
- :mod:`graphmine_tpu.obs.registry`   counter/gauge/histogram registry
  with a Prometheus exporter (textfile or the serve layer's live
  ``GET /metrics``);
- :mod:`graphmine_tpu.obs.histogram`  thread-safe, mergeable bucket
  histograms with ``histogram_quantile``-style estimation — the
  latency-distribution surface the serving SLO endpoints read;
- :mod:`graphmine_tpu.obs.heartbeat`  periodic liveness records (a hung
  run is distinguishable from a dead one);
- :mod:`graphmine_tpu.obs.schema`     the record-schema registry every
  emitted phase name must be declared in (validated in tests and by
  ``tools/obs_report.py``);
- :mod:`graphmine_tpu.obs.costmodel`  the analytical compute-plane cost
  model (r13): per-plan bytes/slots/exchange derivation, measured
  roofline anchors, the ``cost`` sub-record builder and the
  ``superstep_timing`` achieved-vs-model emission;
- :mod:`graphmine_tpu.obs.memmodel`   the analytical memory-plane model
  (ISSUE 14): per-plan HBM footprint inventories, the byte seeds the
  pipeline planner derives its schedule model from, the ``mem``
  sub-record builder and the ``memory_watermark`` emission;
- :mod:`graphmine_tpu.obs.sketch`     mergeable quantile sketches over
  fixed log ladders (the ``Histogram.merge`` contract applied to LOF
  scores and community sizes) + the PSI drift distance;
- :mod:`graphmine_tpu.obs.quality`    the result-quality plane (r14):
  per-publish quality state, snapshot-diff drift, the planted-anomaly
  canary probe and the ``quality_*``/``canary_score`` record emission;
- :mod:`graphmine_tpu.obs.alerts`     the declarative threshold +
  for-duration alert rule engine behind ``/alertz``.
"""

from graphmine_tpu.obs.alerts import AlertManager, AlertRule, default_rules
from graphmine_tpu.obs.costmodel import (
    CostEstimate,
    lof_cost,
    rooflines,
    sharded_superstep_cost,
    superstep_cost,
)
from graphmine_tpu.obs.histogram import Histogram, HistogramFamily
from graphmine_tpu.obs.memmodel import (
    MemEstimate,
    emit_memory_watermark,
    lof_footprint,
    schedule_footprint,
    sharded_superstep_footprint,
    superstep_footprint,
)
from graphmine_tpu.obs.quality import (
    CanaryProbe,
    QualityState,
    run_quality_pass,
)
from graphmine_tpu.obs.registry import Registry
from graphmine_tpu.obs.sketch import QuantileSketch, log_ladder, psi_distance
from graphmine_tpu.obs.spans import (
    TRACE_HEADER,
    Span,
    TraceContext,
    Tracer,
    new_run_id,
)

__all__ = [
    "AlertManager",
    "AlertRule",
    "CanaryProbe",
    "CostEstimate",
    "Histogram",
    "HistogramFamily",
    "MemEstimate",
    "QualityState",
    "QuantileSketch",
    "Registry",
    "Span",
    "TRACE_HEADER",
    "TraceContext",
    "Tracer",
    "default_rules",
    "emit_memory_watermark",
    "lof_cost",
    "lof_footprint",
    "log_ladder",
    "new_run_id",
    "psi_distance",
    "rooflines",
    "run_quality_pass",
    "schedule_footprint",
    "sharded_superstep_cost",
    "sharded_superstep_footprint",
    "superstep_cost",
    "superstep_footprint",
]
