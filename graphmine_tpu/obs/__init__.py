"""Run-correlated tracing & telemetry (docs/OBSERVABILITY.md).

Stdlib-only leaf package — safe to import from anywhere in the pipeline
(nothing here imports jax, and :mod:`graphmine_tpu.pipeline.metrics`
builds on it, not the other way around):

- :mod:`graphmine_tpu.obs.spans`      hierarchical span context
  (run_id -> phase -> rung -> superstep) with monotonic timings;
- :mod:`graphmine_tpu.obs.registry`   counter/gauge/histogram registry
  with a Prometheus exporter (textfile or the serve layer's live
  ``GET /metrics``);
- :mod:`graphmine_tpu.obs.histogram`  thread-safe, mergeable bucket
  histograms with ``histogram_quantile``-style estimation — the
  latency-distribution surface the serving SLO endpoints read;
- :mod:`graphmine_tpu.obs.heartbeat`  periodic liveness records (a hung
  run is distinguishable from a dead one);
- :mod:`graphmine_tpu.obs.schema`     the record-schema registry every
  emitted phase name must be declared in (validated in tests and by
  ``tools/obs_report.py``);
- :mod:`graphmine_tpu.obs.costmodel`  the analytical compute-plane cost
  model (r13): per-plan bytes/slots/exchange derivation, measured
  roofline anchors, the ``cost`` sub-record builder and the
  ``superstep_timing`` achieved-vs-model emission.
"""

from graphmine_tpu.obs.costmodel import (
    CostEstimate,
    lof_cost,
    rooflines,
    sharded_superstep_cost,
    superstep_cost,
)
from graphmine_tpu.obs.histogram import Histogram, HistogramFamily
from graphmine_tpu.obs.registry import Registry
from graphmine_tpu.obs.spans import (
    TRACE_HEADER,
    Span,
    TraceContext,
    Tracer,
    new_run_id,
)

__all__ = [
    "CostEstimate",
    "Histogram",
    "HistogramFamily",
    "Registry",
    "Span",
    "TRACE_HEADER",
    "TraceContext",
    "Tracer",
    "lof_cost",
    "new_run_id",
    "rooflines",
    "sharded_superstep_cost",
    "superstep_cost",
]
