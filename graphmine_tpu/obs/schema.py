"""Record-schema registry: every emitted phase name is declared here.

The metrics stream is an append-only JSONL of heterogeneous records; its
consumers (``tools/obs_report.py``, dashboards, the tests) key on phase
names and required fields. Nothing stops a new call site from emitting a
typo'd phase or dropping a key — except this registry, validated over
every e2e run's records in ``tests/test_obs.py`` (marker ``obs``):
an **unknown phase name fails loudly** instead of rotting the JSONL, and
a registered phase missing a required key does too.

Required keys are the *always-present* set; optional keys are free-form
(records routinely carry extra context). Two cross-cutting rules:

- every record needs ``phase`` (str) and ``t`` (epoch seconds);
- trace identity is all-or-nothing: a record carrying any of
  ``run_id`` / ``trace_id`` / ``span_id`` / ``span_path`` must carry all
  four (a half-stamped record would silently fall out of timeline joins).

Extend with :func:`register` (e.g. from tools that emit their own
records) — registration is the contract, not a fixed builtin list.
"""

from __future__ import annotations

import re

_TRACE_KEYS = ("run_id", "trace_id", "span_id", "span_path")

# phase name -> frozenset of required keys (beyond phase/t).
SCHEMAS: dict = {}


def register(phase: str, *required: str) -> None:
    """Declare a phase and its always-present keys (idempotent; a
    re-registration unions the key sets so split declarations merge)."""
    SCHEMAS[phase] = frozenset(required) | SCHEMAS.get(phase, frozenset())


# ---- run lifecycle --------------------------------------------------------
register("run_start", "pid")
register("run_end", "ok")
register("span", "name", "seconds", "status")
register("heartbeat", "uptime_s")
register("profile_capture", "dir", "ok")

# ---- pipeline phases (timed records carry `seconds`) ----------------------
register("load", "seconds")
register("counts", "rows_raw", "edges", "vertices")
register("quarantine")
register("plan", "schedule", "bytes_per_device", "hbm_budget", "reason")
register("scale_out", "message")
register("warning", "message")
register("build_graph", "seconds")
register("partition", "seconds", "shards", "schedule")
register("lpa", "seconds")            # timed record (graphframes backend)
register("louvain", "seconds", "gamma")
register("leiden", "seconds", "gamma")
register("lpa_iter", "iteration", "labels_changed", "seconds",
         "edges_per_sec", "edges_per_sec_per_chip")
register("superstep_telemetry", "iteration", "labels_changed", "frontier",
         "shard_changed", "imbalance", "devices", "variant")
register("census", "seconds")
register("communities", "count", "largest", "modularity")
register("outliers_recursive_lpa", "seconds")
register("outliers_lof", "seconds", "k", "devices", "features")
register("outlier_summary", "method")
register("ivf_fallback", "guard", "detail")
register("impl_selected", "op", "impl", "n", "reason")
# plan_build: one per superstep-plan materialization (blocked/bucketed —
# ops/blocking.emit_plan_records and the driver's single-device build):
# host build seconds, family, bins/width classes, padded gather slots per
# edge. Host plan cost grows with the tighter ladders; this record keeps
# it visible in obs_report instead of hiding inside first-call latency.
register("plan_build", "op", "family", "seconds", "padded_slots_per_edge")
# superstep_timing (ISSUE 12): achieved-vs-model throughput for one
# window of supersteps, emitted at the existing tripwire/telemetry
# cadence (zero extra device syncs — the driver already blocks per
# superstep) and by the ops-layer fixpoint seams (cc/pagerank/LPA with a
# sink). Carries BOTH sides of the roofline argument: achieved
# edges/s/chip and the cost model's prediction, plus the full `cost`
# sub-record (see COST_KEYS below). obs_report's roofline section
# renders these; windows below a configurable fraction of model are the
# RUNBOOKS §12 triage signal.
register("superstep_timing", "op", "family", "variant", "iteration",
         "window", "seconds", "edges_per_sec_per_chip",
         "predicted_edges_per_sec_per_chip", "achieved_fraction",
         "devices", "cost")

# memory_watermark (ISSUE 14): predicted-vs-measured HBM/RSS for one
# operating point, emitted by obs/memmodel.emit_memory_watermark (the
# single builder) at the existing phase/rung/telemetry cadence — zero
# extra device syncs (memory_stats is a host-side allocator query).
# `headroom_frac` may be None when no budget is known; `source` says
# whether `achieved_bytes` is a device allocator peak ("device") or the
# host-RSS fallback ("rss"). The `mem` sub-record carries the full
# inventory (see MEM_KEYS below). obs_report's memory section renders
# the per-phase predicted-vs-peak waterfall from these.
register("memory_watermark", "op", "predicted_bytes", "achieved_bytes",
         "headroom_frac", "source", "mem")

# shard_exchange (ISSUE 15): modeled per-chip ICI bytes of the shard
# family that ran next to the one-all_gather ladder model (4·Vc·(D-1)),
# with the frontier fraction — the share of a full label exchange the 2D
# family's per-peer boundary tables actually ship. Single builder:
# obs/costmodel.emit_shard_exchange, emitted once per sharded repair
# apply (serve/delta.py); the `exchange` bench tier carries the same
# modeled numbers in its per-D detail rows rather than a sink stream.
register("shard_exchange", "op", "family", "devices", "peers",
         "exchange_bytes", "frontier_bytes", "ladder_bytes",
         "frontier_frac")

# ---- serving records (docs/SERVING.md) ------------------------------------
register("snapshot_publish", "version", "snapshot_id", "path", "bytes",
         "arrays", "seconds")
register("snapshot_load", "version", "path", "seconds")
register("delta_apply", "inserts", "deletes", "method", "iterations",
         "quarantine", "version", "seconds")
register("query_batch", "endpoint", "n", "seconds")
register("repair_fallback", "stage", "reason")

# ---- serving SLO records (docs/OBSERVABILITY.md "serving SLO") ------------
# access_log: one per HTTP request through the serve middleware (slow
# requests additionally carry slow/body_sha256/body_bytes); slo_rollup:
# one per /statusz read — a periodic checkpoint of the quantile/debt
# state so scrape-less runs still leave an SLO trail in the JSONL.
register("access_log", "method", "endpoint", "status", "seconds",
         "request_id")
register("slo_rollup", "uptime_s", "endpoints", "repair_debt")

# ---- serving admission control (docs/SERVING.md "admission control") ------
# admission: one per AdmissionController.resolve — the provenance trail
# of every accept/queue/coalesce/shed verdict with the debt state that
# decided it; delta_coalesce: one per merged apply group; delta_shed:
# one per refused/dropped batch (stage says where: admission front door,
# deadline expiry on the queue, shutdown drain).
register("admission", "verdict", "reason", "queue_depth", "rows",
         "repair_debt")
register("delta_coalesce", "batches", "inserts", "deletes", "rows_in",
         "rows_out")
register("delta_shed", "stage", "reason", "rows", "retry_after_s")

# ---- replicated serving fleet (docs/SERVING.md "Fleet") --------------------
# replica_health: one per replica state-machine transition (joining/
# healthy/degraded/draining/down) from the fleet prober or the rolling
# reload; breaker_transition: one per circuit-breaker state change
# (closed/open/half_open) with the deciding window stats; fleet_route:
# one per routed request — verdict served/no_replica/stale_pin/
# forwarded/read_only/writer_unreachable with the attempt count and the
# version the response was pinned at; fleet_degraded: the loud read-only
# flip when the writer is lost (and its restoration).
register("replica_health", "replica", "from_state", "to_state", "reason")
register("breaker_transition", "replica", "from_state", "to_state",
         "reason")
register("fleet_route", "endpoint", "verdict", "attempts")
register("fleet_degraded", "reason", "read_only")

# ---- durable write path / replicated writers (docs/SERVING.md
# "Replicated writers") --------------------------------------------------
# wal_append: one per fsync'd write-ahead-log append (the durability
# point every acknowledged delta passes through); wal_replay: one per
# startup/promotion replay of the accepted-but-unapplied tail;
# writer_promote: the standby-to-writer failover step (server- and
# fleet-side both emit it, keyed by epoch); publish_fenced: a deposed
# writer's publish refused at the snapshot store by the epoch fence —
# THE split-brain-impossibility record; ship_lag: the standby's
# replication lag while behind the primary's log (rate-limited).
register("wal_append", "seq", "rows", "bytes", "seconds")
register("wal_replay", "entries", "from_seq")
register("writer_promote", "epoch")
register("publish_fenced", "attempted_epoch", "store_epoch", "reason")
register("ship_lag", "lag_entries", "lag_s")

# ---- sharded write plane (r17, serve/shardplane.py; docs/SERVING.md
# "Sharded write plane") ----------------------------------------------------
# shard_publish: one per writer shard per epoch stage — the per-range
# array files written under epochs/epoch-<e>.stage before the commit;
# epoch_commit: the coordinator's durable two-phase commit point — the
# epoch → per-shard version vector mapping readers key off (a crash
# before this record leaves the previous epoch served); shard_degraded:
# a per-range availability transition (killed / read_only / recovered /
# promoted) — shard loss degrades ONE vertex range, and this record is
# the timeline line that says which. Single builder:
# serve/shardplane.emit_shard_record (tools/schema_lint.py flags inline
# emits elsewhere).
register("shard_publish", "epoch", "shard", "version", "arrays")
register("epoch_commit", "epoch", "version_vector", "shards")
register("shard_degraded", "shard", "status", "reason")

# ---- cross-process tracing / time-to-visible SLO (docs/OBSERVABILITY.md
# "Fleet tracing") ---------------------------------------------------------
# delta_stages: one per accepted delta batch at publish time, emitted in
# the BATCH's own trace (the propagated traceparent context) — the
# writer-side causal chain: admission accept -> WAL fsync -> queued ->
# apply -> snapshot publish, each stage in seconds; delta_visible: one
# per (delta, replica) from the fleet router when a replica first serves
# the version that absorbed the delta — the read-side tail of
# time-to-visible, feeding the router's merged histogram.
register("delta_stages", "version", "stages")
register("delta_visible", "replica", "version", "seconds")

# ---- result-quality observability (docs/OBSERVABILITY.md "Result
# quality") -----------------------------------------------------------------
# quality_snapshot: one per snapshot publish — the published result
# distributions (LOF score + community-size sketches, anomaly rate,
# census scalars) from the bounded host-side quality pass
# (obs/quality.run_quality_pass); quality_drift: the snapshot-over-
# parent comparison (partition-matched churn, PSI sketch drift, id-chain
# community births/deaths); canary_score: the frozen planted-anomaly
# probe re-scored through the production LOF scorer — recall@k dropping
# between publishes is a scorer regression by construction; alert: one
# per firing/resolved transition of an obs/alerts.py rule.
register("quality_snapshot", "version", "num_vertices", "num_communities",
         "anomaly_rate", "lof_threshold", "lof_sketch", "size_sketch",
         "seconds")
register("quality_drift", "version", "parent_version", "churn_frac",
         "new_communities", "dissolved_communities", "lof_psi",
         "size_psi", "anomaly_rate_delta")
register("canary_score", "version", "recall_at_k", "recall_k",
         "mean_rank_frac", "num_anomalies", "k")
register("alert", "name", "state", "severity", "metric", "value",
         "threshold")

# ---- recovery / resilience records (docs/RESILIENCE.md) -------------------
register("retry", "stage", "attempt", "backoff_s", "error")
register("retries_exhausted", "stage", "attempts", "error")
register("degrade", "stage", "to", "depth", "error")
register("mesh_degrade", "from_devices", "to_devices", "schedule",
         "iteration", "resumed_from", "dead_devices")
register("tripwire", "kind", "shard", "iteration")
register("watchdog_timeout", "stage", "timeout_s", "checkpointed")
register("resume", "iteration")
register("checkpoint_save", "iteration", "format", "path")
register("checkpoint_rollback", "path", "error")
register("checkpoint_rollback_ok", "path", "iteration")

# ---- multi-tenant serving (ISSUE 16, docs/SERVING.md "Multi-tenant
# serving") -----------------------------------------------------------------
# Records on these phases MAY carry an optional `tenant` key naming the
# owning tenant (serve/tenancy.py grammar). ABSENT means the default
# tenant — the back-compat contract that keeps every pre-tenancy record
# valid — so the key is never required; when present it must be a valid
# tenant id (a malformed value would leak into per-tenant groupings as a
# phantom tenant). obs_report groups admission/quality/alert timelines
# by it.
TENANT_PHASES = frozenset((
    "admission", "delta_coalesce", "delta_shed", "delta_apply",
    "delta_stages", "snapshot_publish", "snapshot_load", "access_log",
    "alert", "quality_snapshot", "quality_drift", "canary_score",
    "wal_append", "wal_replay", "repair_fallback",
    "shard_publish", "epoch_commit", "shard_degraded",
))

# Mirrors serve/tenancy.py TENANT_RE — duplicated by design: obs/ stays
# importable without serve/ (the JSONL consumers are stdlib-only tools).
_TENANT_VALUE_RE = re.compile(r"[a-z0-9_-]{1,64}")

# The recovery phases obs_report joins into the causal timeline.
RECOVERY_PHASES = frozenset((
    "retry", "retries_exhausted", "degrade", "mesh_degrade", "tripwire",
    "watchdog_timeout", "resume", "checkpoint_rollback",
    "checkpoint_rollback_ok", "ivf_fallback", "quarantine",
    "repair_fallback", "delta_shed", "breaker_transition",
    "fleet_degraded", "wal_replay", "writer_promote", "publish_fenced",
    "shard_degraded",
))


# The `cost` sub-record shape (obs/costmodel.CostEstimate.record — the
# single builder; tools/schema_lint.py flags inline cost={...} literals
# elsewhere in the package). Like trace identity, the sub-record is
# all-or-nothing: a record carrying `cost` must carry EVERY key below,
# or the roofline tooling would silently render holes — half-stamped
# cost records fail validation the same way half-stamped traces do.
COST_KEYS = frozenset((
    "family", "devices", "slots", "padded_slots", "bytes_gathered",
    "bytes_scattered", "padding_overhead", "exchange_bytes",
    "compute_seconds", "exchange_seconds", "predicted_seconds",
    "predicted_per_chip", "unit", "roofline",
))

# The `mem` sub-record shape (obs/memmodel.MemEstimate.record — the
# single builder; tools/schema_lint.py flags inline mem={...} literals
# elsewhere in the package). Same all-or-nothing rule as `cost`: a
# record carrying `mem` must carry EVERY key below, or the memory-plane
# tooling (obs_report's waterfall, the recalibration suggestion) would
# silently render holes.
MEM_KEYS = frozenset((
    "family", "devices", "weighted", "total_bytes", "inventory", "exact",
    "unit",
))

# The sketch sub-record shape (obs/sketch.QuantileSketch.to_state — the
# single builder; tools/schema_lint.py flags inline *_sketch={...}
# literals elsewhere). Same all-or-nothing rule as `cost`: a record
# carrying a `*_sketch` dict must carry every key below, or the quality
# tooling (obs_report's quality timeline, the router's counter-wise
# merge) would silently drop or mis-merge the distribution.
SKETCH_KEYS = frozenset(("bounds", "counts", "sum", "count"))


def validate_record(rec) -> list:
    """Problems with one record (empty list = valid)."""
    problems = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not dict"]
    phase = rec.get("phase")
    if not isinstance(phase, str) or not phase:
        return [f"missing/empty phase in {rec!r}"]
    if not isinstance(rec.get("t"), (int, float)):
        problems.append(f"{phase}: missing numeric t")
    required = SCHEMAS.get(phase)
    if required is None:
        problems.append(
            f"unknown phase {phase!r} — register it in "
            "graphmine_tpu/obs/schema.py with its required keys"
        )
    else:
        missing = sorted(k for k in required if k not in rec)
        if missing:
            problems.append(f"{phase}: missing required keys {missing}")
    present = [k for k in _TRACE_KEYS if k in rec]
    if present and len(present) != len(_TRACE_KEYS):
        absent = sorted(set(_TRACE_KEYS) - set(present))
        problems.append(
            f"{phase}: partial trace identity (has {present}, lacks {absent})"
        )
    if "tenant" in rec:
        tval = rec["tenant"]
        if not isinstance(tval, str) or not _TENANT_VALUE_RE.fullmatch(tval):
            problems.append(
                f"{phase}: tenant key {tval!r} does not match the tenant-id "
                "grammar [a-z0-9_-]{1,64} (serve/tenancy.py)"
            )
    for key in rec:
        if not key.endswith("_sketch"):
            continue
        sk = rec[key]
        if not isinstance(sk, dict):
            problems.append(
                f"{phase}: {key} sub-record is {type(sk).__name__}, not "
                "dict — build it with obs/sketch QuantileSketch.to_state()"
            )
        else:
            missing = sorted(k for k in SKETCH_KEYS if k not in sk)
            if missing:
                problems.append(
                    f"{phase}: half-stamped {key} sub-record (missing "
                    f"{missing}) — build it with obs/sketch "
                    "QuantileSketch.to_state()"
                )
    if "mem" in rec:
        mem = rec["mem"]
        if not isinstance(mem, dict):
            problems.append(
                f"{phase}: mem sub-record is {type(mem).__name__}, not "
                "dict — build it with obs/memmodel MemEstimate.record()"
            )
        else:
            missing = sorted(k for k in MEM_KEYS if k not in mem)
            if missing:
                problems.append(
                    f"{phase}: half-stamped mem sub-record (missing "
                    f"{missing}) — build it with obs/memmodel "
                    "MemEstimate.record()"
                )
    if "cost" in rec:
        cost = rec["cost"]
        if not isinstance(cost, dict):
            problems.append(
                f"{phase}: cost sub-record is {type(cost).__name__}, not "
                "dict — build it with obs/costmodel CostEstimate.record()"
            )
        else:
            missing = sorted(k for k in COST_KEYS if k not in cost)
            if missing:
                problems.append(
                    f"{phase}: half-stamped cost sub-record (missing "
                    f"{missing}) — build it with obs/costmodel "
                    "CostEstimate.record()"
                )
    return problems


def validate_records(records) -> list:
    """Flat problem list over a record iterable, each prefixed with its
    position — the loud-failure hook tests run over every e2e stream."""
    problems = []
    for i, rec in enumerate(records):
        problems.extend(f"record {i}: {p}" for p in validate_record(rec))
    return problems
