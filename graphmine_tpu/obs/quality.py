"""Result-quality observability: snapshot quality state, drift, canary.

Every observability layer before this one watches the *infrastructure*
(latency histograms, repair debt, WAL lag, rooflines) — none of them can
see a scorer that silently degrades while serving perfect p99s: an IVF
recall collapse after a bad retrain, a repair-path bias, a drifting
anomaly rate. This module watches the *product* — community labels and
LOF outlier scores — at every snapshot publish:

- :class:`QualityState`: one snapshot's result distributions — the LOF
  score sketch and community-size sketch (``obs/sketch.py`` log
  ladders), anomaly rate (share of scores above the threshold),
  community census scalars. Bounded host work: a handful of O(V)
  vectorized passes.
- :func:`quality_drift`: snapshot-over-parent drift — churned-vertex
  fraction (partition-matched, so a cold recompute's label renumbering
  does not read as churn), new/dissolved community counts, PSI drift of
  both sketches, anomaly-rate delta.
- :class:`CanaryProbe`: a seeded planted-anomaly probe set (generated
  once from the ``datasets.planted_anomaly_graph`` machinery, persisted
  as snapshot arrays + manifest metadata) re-scored through the
  production LOF scorer on every publish. Planted-anomaly recall@k is a
  production tripwire for scorer regressions that infra metrics cannot
  see — the probe's features are frozen, so any recall drop is the
  SCORER moving, never the data.
- :func:`run_quality_pass`: the publish-time orchestrator — computes
  state (+ drift vs parent, + canary score), emits the schema-registered
  ``quality_snapshot`` / ``quality_drift`` / ``canary_score`` records in
  the publishing trace, and mirrors the headline numbers into gauges.

numpy is imported inside functions (the ``serve/delta.py`` discipline)
so the ``obs`` package stays an import-clean stdlib leaf; the quality
pass itself always runs where numpy already is (the serving write path,
the driver's publish phase, bench).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

from graphmine_tpu.obs.sketch import (
    DEFAULT_SCORE_LADDER,
    DEFAULT_SIZE_LADDER,
    QuantileSketch,
    env_float,
    psi_distance,
)

__all__ = [
    "CanaryProbe",
    "DEFAULT_LOF_THRESHOLD",
    "QualityReport",
    "QualityState",
    "export_gauges",
    "lof_threshold",
    "partition_churn",
    "quality_drift",
    "run_quality_pass",
    "sketch_of",
]

# The anomaly-rate threshold: share of vertices with LOF above this is
# the "how many outliers are we serving" gauge (the r6 e2e reports
# LOF > 1.5 as its flagged count — same convention).
DEFAULT_LOF_THRESHOLD = 1.5

# Snapshot array names the canary probe persists (ride publishes the way
# lof_centers does) and the manifest key for its scoring parameters.
CANARY_ARRAYS = ("canary_features", "canary_is_anomaly")
CANARY_META_KEY = "canary"


def lof_threshold() -> float:
    """The env-resolved anomaly threshold (one owner for every caller:
    the quality pass, /statusz, bench). Malformed env raises."""
    return env_float("GRAPHMINE_QUALITY_LOF_THRESHOLD",
                     DEFAULT_LOF_THRESHOLD)


def sketch_of(values, ladder, name: str = "sketch") -> QuantileSketch:
    """Bin a host value array into a fresh sketch with ONE vectorized
    pass (searchsorted + bincount) — the bounded-cost ingestion path the
    per-publish quality pass uses instead of V python-side observes."""
    import numpy as np

    sk = QuantileSketch(name=name, buckets=ladder)
    vals = np.asarray(values, np.float64).reshape(-1)
    if not len(vals):
        return sk
    bounds = np.asarray(sk.bounds, np.float64)
    idx = np.searchsorted(bounds, vals, side="left")
    counts = np.bincount(idx, minlength=len(bounds) + 1)
    sk.add_counts(counts.tolist(), total=float(vals.sum()))
    return sk


@dataclass
class QualityState:
    """The result-quality observables of one published snapshot."""

    version: int = 0
    num_vertices: int = 0
    num_communities: int = 0
    largest_community: int = 0
    anomaly_count: int = 0
    anomaly_rate: float = 0.0
    threshold: float = DEFAULT_LOF_THRESHOLD
    lof_sketch: QuantileSketch = field(
        default_factory=lambda: QuantileSketch(
            "lof_score", buckets=DEFAULT_SCORE_LADDER)
    )
    size_sketch: QuantileSketch = field(
        default_factory=lambda: QuantileSketch(
            "community_size", buckets=DEFAULT_SIZE_LADDER)
    )

    @classmethod
    def from_arrays(
        cls, labels, lof=None, version: int = 0, threshold: float | None = None,
    ) -> "QualityState":
        """Compute the state from host label/score columns: one bincount
        for the census, one binning pass per sketch. O(V) host work —
        the bounded-cost claim ``bench.py``'s ``quality_pass``
        sub-record measures."""
        import numpy as np

        labels = np.asarray(labels).reshape(-1)
        thr = lof_threshold() if threshold is None else float(threshold)
        sizes = np.bincount(labels.astype(np.int64))
        sizes = sizes[sizes > 0]
        lof_arr = (
            np.zeros(0, np.float32) if lof is None
            else np.asarray(lof, np.float32).reshape(-1)
        )
        n_anom = int((lof_arr > thr).sum())
        return cls(
            version=int(version),
            num_vertices=int(len(labels)),
            num_communities=int(len(sizes)),
            largest_community=int(sizes.max()) if len(sizes) else 0,
            anomaly_count=n_anom,
            anomaly_rate=round(n_anom / len(lof_arr), 6) if len(lof_arr) else 0.0,
            threshold=thr,
            lof_sketch=sketch_of(lof_arr, DEFAULT_SCORE_LADDER, "lof_score"),
            size_sketch=sketch_of(
                sizes, DEFAULT_SIZE_LADDER, "community_size"
            ),
        )

    def payload(self) -> dict:
        """The JSON body /statusz and /alertz serve (and the
        ``quality_snapshot`` record carries): scalars + both sketch
        states — the shape the fleet router's counter-wise merge and
        ``obs_report`` both read."""
        return {
            "version": self.version,
            "num_vertices": self.num_vertices,
            "num_communities": self.num_communities,
            "largest_community": self.largest_community,
            "anomaly_count": self.anomaly_count,
            "anomaly_rate": self.anomaly_rate,
            "lof_threshold": self.threshold,
            "lof_sketch": self.lof_sketch.to_state(),
            "size_sketch": self.size_sketch.to_state(),
        }


def partition_churn(parent_labels, labels) -> float:
    """Churned-vertex fraction between two community partitions over the
    common vertex prefix, ROBUST to label renumbering.

    Raw label comparison would read a cold recompute — which renumbers
    every community id while possibly changing nothing — as 100% churn.
    Instead each CHILD community is matched to the parent community it
    overlaps most; a vertex churned iff it is not in its child
    community's majority parent group:
    ``churn = 1 - (sum of per-child-community max overlaps) / V``.
    Exactly 0.0 when the partitions are identical up to renaming;
    hand-computable (the ``tests/test_quality.py`` pin).
    """
    import numpy as np

    parent = np.asarray(parent_labels).reshape(-1)
    child = np.asarray(labels).reshape(-1)
    n = min(len(parent), len(child))
    if n == 0:
        return 0.0
    parent, child = parent[:n].astype(np.int64), child[:n].astype(np.int64)
    # overlap counts per (child, parent) label pair, then the max
    # overlap per child community
    pair = np.stack([child, parent], axis=1)
    uniq, counts = np.unique(pair, axis=0, return_counts=True)
    order = np.lexsort((-counts, uniq[:, 0]))
    uniq, counts = uniq[order], counts[order]
    first = np.ones(len(uniq), bool)
    first[1:] = uniq[1:, 0] != uniq[:-1, 0]
    matched = int(counts[first].sum())
    return round(1.0 - matched / n, 6)


def _label_sets(parent_labels, labels):
    """(new, dissolved) community-id counts by raw id set difference —
    meaningful along warm-repair chains (labels persist), noisy across a
    cold recompute's renumbering; ``churn_frac`` is the renumbering-
    robust signal, these are the cheap id-chain diagnostics."""
    import numpy as np

    p = np.unique(np.asarray(parent_labels).reshape(-1))
    c = np.unique(np.asarray(labels).reshape(-1))
    new = int(len(np.setdiff1d(c, p, assume_unique=True)))
    dissolved = int(len(np.setdiff1d(p, c, assume_unique=True)))
    return new, dissolved


def quality_drift(
    parent: QualityState, state: QualityState, parent_labels, labels,
) -> dict:
    """Snapshot-over-parent drift: the ``quality_drift`` record body."""
    new, dissolved = _label_sets(parent_labels, labels)
    return {
        "version": state.version,
        "parent_version": parent.version,
        "churn_frac": partition_churn(parent_labels, labels),
        "new_communities": new,
        "dissolved_communities": dissolved,
        "lof_psi": round(
            psi_distance(parent.lof_sketch, state.lof_sketch), 6
        ),
        "size_psi": round(
            psi_distance(parent.size_sketch, state.size_sketch), 6
        ),
        "anomaly_rate": state.anomaly_rate,
        "anomaly_rate_delta": round(
            state.anomaly_rate - parent.anomaly_rate, 6
        ),
    }


# ---- canary probe ----------------------------------------------------------


def _probe_features(src, dst, comm, num_vertices: int):
    """Structural per-vertex features of the probe graph, computed ONCE
    at probe creation with plain numpy (no jax — probe generation must
    work anywhere, including the driver's publish phase before any
    device work): degree, distinct-partner count, mean partner degree,
    cross-block partner fraction — the same signal family the production
    feature pass scores, standardized column-wise."""
    import numpy as np

    es = np.concatenate([src, dst]).astype(np.int64)
    ed = np.concatenate([dst, src]).astype(np.int64)
    deg = np.bincount(es, minlength=num_vertices).astype(np.float64)
    pair = es * num_vertices + ed
    uniq = np.unique(pair)
    distinct = np.bincount(
        (uniq // num_vertices), minlength=num_vertices
    ).astype(np.float64)
    nbr_deg_sum = np.bincount(es, weights=deg[ed], minlength=num_vertices)
    mean_nbr_deg = nbr_deg_sum / np.maximum(deg, 1.0)
    cross = np.bincount(
        es, weights=(comm[es] != comm[ed]).astype(np.float64),
        minlength=num_vertices,
    ) / np.maximum(deg, 1.0)
    feats = np.stack([
        np.log1p(deg), np.log1p(distinct), np.log1p(mean_nbr_deg), cross,
    ], axis=1)
    mu = feats.mean(axis=0)
    sd = feats.std(axis=0)
    sd[sd == 0] = 1.0
    return ((feats - mu) / sd).astype(np.float32)


@dataclass
class CanaryProbe:
    """A frozen planted-anomaly probe set, re-scored on every publish.

    ``features`` [N, d] and ``is_anomaly`` [N] are generated once (seeded
    ``datasets.planted_anomaly_graph`` + the numpy structural-feature
    pass above) and persisted in the snapshot (arrays
    :data:`CANARY_ARRAYS`, parameters under manifest key
    :data:`CANARY_META_KEY`), so every publish in a store's lifetime —
    across restarts, failovers and standby promotions — scores the SAME
    probe. :meth:`score` runs the probe through the production scorer
    (``ops.lof.lof_scores``); planted-anomaly recall@k dropping between
    two publishes means the SCORER regressed, because nothing else in
    the comparison moved.
    """

    features: object          # np.ndarray [N, d] float32
    is_anomaly: object        # np.ndarray [N] bool
    k: int = 16
    recall_k: int = 0         # 0 = resolved to 2 * num planted anomalies
    seed: int = 0

    @property
    def num_anomalies(self) -> int:
        import numpy as np

        return int(np.asarray(self.is_anomaly).sum())

    def _recall_k(self) -> int:
        return int(self.recall_k) if self.recall_k else 2 * self.num_anomalies

    @classmethod
    def generate(
        cls, seed: int = 0, num_vertices: int = 384, num_anomalies: int = 6,
        edges_per_vertex: int = 8, edges_per_anomaly: int = 48,
        k: int = 16, recall_k: int = 0,
    ) -> "CanaryProbe":
        """Seeded probe construction: a small planted-community graph
        with injected structural anomalies (uniform cross-graph hubs —
        exactly the signature the production LOF pipeline scores),
        reduced to a frozen feature matrix. Deterministic per seed."""
        from graphmine_tpu.datasets import planted_anomaly_graph

        src, dst, is_anomaly, comm = planted_anomaly_graph(
            num_vertices, num_vertices * edges_per_vertex,
            n_communities=max(8, num_vertices // 48),
            num_anomalies=num_anomalies,
            edges_per_anomaly=edges_per_anomaly,
            seed=seed,
        )
        feats = _probe_features(src, dst, comm, num_vertices)
        return cls(
            features=feats, is_anomaly=is_anomaly, k=k,
            recall_k=recall_k, seed=seed,
        )

    # -- snapshot persistence ---------------------------------------------
    def arrays(self) -> dict:
        """The snapshot arrays a publish attaches (the ``lof_centers``
        pattern: probe identity rides the store, not process memory)."""
        import numpy as np

        return {
            "canary_features": np.asarray(self.features, np.float32),
            "canary_is_anomaly": np.asarray(self.is_anomaly, np.uint8),
        }

    def meta(self) -> dict:
        """The manifest entry (under :data:`CANARY_META_KEY`)."""
        return {
            "seed": int(self.seed),
            "k": int(self.k),
            "recall_k": self._recall_k(),
        }

    @classmethod
    def from_snapshot(cls, snapshot) -> "CanaryProbe | None":
        """Rebuild the probe a snapshot carries (None when it carries
        none — pre-quality stores bootstrap by generating a fresh one)."""
        return cls.from_arrays(snapshot.arrays, snapshot.meta)

    @classmethod
    def from_arrays(cls, arrays: dict, meta: dict) -> "CanaryProbe | None":
        """Rebuild from a raw array dict + manifest meta (the
        ``SnapshotStore.peek_arrays`` shape the driver's publish phase
        reads without a full load)."""
        feats = arrays.get("canary_features")
        mask = arrays.get("canary_is_anomaly")
        if feats is None or mask is None:
            return None
        import numpy as np

        probe_meta = (meta or {}).get(CANARY_META_KEY) or {}
        return cls(
            features=np.asarray(feats, np.float32),
            is_anomaly=np.asarray(mask).astype(bool),
            k=int(probe_meta.get("k", 16)),
            recall_k=int(probe_meta.get("recall_k", 0)),
            seed=int(probe_meta.get("seed", 0)),
        )

    # -- scoring -----------------------------------------------------------
    def score(self, sink=None) -> dict:
        """Re-score the frozen probe through the production LOF scorer
        and rank the planted anomalies: the ``canary_score`` record body.

        ``recall_at_k``: fraction of planted anomalies inside the top
        ``recall_k`` scores (1.0 on a healthy scorer — pinned at probe
        defaults by the tests); ``mean_rank_frac``: mean normalized rank
        of the planted anomalies (0.0 = all ranked first). The
        ``canary_probe`` fault seam between scoring and ranking is where
        the tests inject a scorer regression.
        """
        import numpy as np

        from graphmine_tpu.ops.lof import lof_scores
        from graphmine_tpu.pipeline import resilience

        t0 = time.perf_counter()
        feats = np.asarray(self.features, np.float32)
        scores = np.asarray(
            lof_scores(feats, k=min(self.k, len(feats) - 2), sink=sink)
        )
        # Fault seam (testing/faults.py mutators): corrupt the scores
        # HERE to prove a scorer regression trips the canary alert.
        state = {"scores": scores}
        resilience.fault_point("canary_probe", state=state)
        scores = np.asarray(state["scores"])

        mask = np.asarray(self.is_anomaly).astype(bool)
        n = len(scores)
        order = np.argsort(-scores, kind="stable")
        rank = np.empty(n, np.int64)
        rank[order] = np.arange(n)
        k_eff = min(self._recall_k(), n)
        anom_ranks = rank[mask]
        n_anom = int(mask.sum())
        recall = (
            round(float((anom_ranks < k_eff).sum()) / n_anom, 6)
            if n_anom else 1.0
        )
        return {
            "recall_at_k": recall,
            "recall_k": k_eff,
            "mean_rank_frac": (
                round(float(anom_ranks.mean()) / max(1, n - 1), 6)
                if n_anom else 0.0
            ),
            "num_anomalies": n_anom,
            "num_probe_vertices": n,
            "k": int(self.k),
            "seconds": round(time.perf_counter() - t0, 4),
        }


@dataclass
class QualityReport:
    """One publish's full quality pass: state + optional drift/canary."""

    state: QualityState
    drift: dict | None = None
    canary: dict | None = None
    seconds: float = 0.0

    def payload(self) -> dict:
        """The "quality" section body (/statusz, /alertz): the state
        under ``state`` plus ``drift``/``canary`` when computed."""
        out = {"state": self.state.payload(), "seconds": self.seconds}
        if self.drift is not None:
            out["drift"] = self.drift
        if self.canary is not None:
            out["canary"] = self.canary
        return out

    def values(self) -> dict:
        """The flat metric dict the alert rules evaluate over."""
        out = {
            "quality_anomaly_rate": self.state.anomaly_rate,
            "quality_num_communities": self.state.num_communities,
        }
        if self.drift is not None:
            out.update({
                "quality_lof_psi": self.drift["lof_psi"],
                "quality_size_psi": self.drift["size_psi"],
                "quality_churn_frac": self.drift["churn_frac"],
            })
        if self.canary is not None:
            out["canary_recall"] = self.canary["recall_at_k"]
        return out


def export_gauges(registry, state: QualityState, drift: dict | None = None,
                  canary: dict | None = None) -> None:
    """Mirror the quality headline numbers into scrapeable gauges — one
    owner for the metric names, shared by the publish pass and the
    serving layer's read-time state export."""
    g = registry.gauge
    g("graphmine_quality_anomaly_rate",
      "share of LOF scores above the anomaly threshold").set(
        state.anomaly_rate)
    g("graphmine_quality_num_communities",
      "present communities in the served snapshot").set(
        state.num_communities)
    if drift is not None:
        g("graphmine_quality_churn_frac",
          "partition-matched churned-vertex fraction vs parent").set(
            drift["churn_frac"])
        g("graphmine_quality_lof_psi",
          "PSI drift of the LOF score distribution vs parent").set(
            drift["lof_psi"])
        g("graphmine_quality_size_psi",
          "PSI drift of the community-size distribution vs parent").set(
            drift["size_psi"])
    if canary is not None:
        g("graphmine_quality_canary_recall",
          "planted-anomaly recall@k of the canary probe, last publish",
          ).set(canary["recall_at_k"])


def run_quality_pass(
    labels,
    lof,
    version: int,
    parent_labels=None,
    parent_lof=None,
    parent_version: int | None = None,
    parent_state: QualityState | None = None,
    canary: CanaryProbe | None = None,
    threshold: float | None = None,
    sink=None,
    registry=None,
) -> QualityReport:
    """The bounded publish-time quality pass, one owner for every
    publisher (delta ingestor, driver publish, bench):

    1. compute :class:`QualityState` from the published columns;
    2. with a parent (``parent_labels`` [+ ``parent_state`` to reuse the
       already-computed sketches, or ``parent_lof`` to rebuild them]),
       compute :func:`quality_drift`;
    3. with a :class:`CanaryProbe`, re-score it;
    4. emit ``quality_snapshot`` / ``quality_drift`` / ``canary_score``
       records through ``sink`` (span-stamped by the sink, so they join
       the publishing trace) and mirror gauges into ``registry``.

    Never raises out of the record/gauge tail — result quality telemetry
    must not take a publish down (the caller owns harder failures like a
    malformed labels array, which IS a publish bug).
    """
    t0 = time.perf_counter()
    state = QualityState.from_arrays(
        labels, lof, version=version, threshold=threshold
    )
    drift = None
    if parent_labels is not None:
        if parent_state is None:
            parent_state = QualityState.from_arrays(
                parent_labels, parent_lof,
                version=version - 1 if parent_version is None else parent_version,
                threshold=threshold,
            )
        drift = quality_drift(parent_state, state, parent_labels, labels)
    canary_out = canary.score(sink=sink) if canary is not None else None
    seconds = round(time.perf_counter() - t0, 4)
    report = QualityReport(
        state=state, drift=drift, canary=canary_out, seconds=seconds
    )
    try:
        if sink is not None:
            sink.emit(
                "quality_snapshot", seconds=seconds, **state.payload()
            )
            if drift is not None:
                sink.emit("quality_drift", **drift)
            if canary_out is not None:
                sink.emit(
                    "canary_score", version=state.version, **canary_out
                )
        if registry is not None:
            export_gauges(registry, report.state, report.drift,
                          report.canary)
    except Exception:  # noqa: BLE001 — telemetry must not fail a publish
        pass
    return report
