"""Declarative alerting over gauges and quality records.

The quality plane (``obs/quality.py``) produces *numbers* — drift PSI,
canary recall, anomaly rate, ingest lag. This module turns them into
*verdicts*: a small threshold + for-duration rule engine with a
firing/resolved state machine, the Prometheus-alerting shape reduced to
what a single process needs:

- an :class:`AlertRule` is ``metric OP threshold`` sustained for
  ``for_s`` seconds (0 = fire on first observation);
- the :class:`AlertManager` evaluates every rule against a flat value
  dict on the EXISTING cadences — the serving layer calls
  :meth:`AlertManager.evaluate` from ``/healthz`` (the fleet prober's
  probe loop drives it fleet-wide), ``/alertz`` reads, and every
  snapshot swap — no new threads, no new timers;
- state transitions ``inactive → pending → firing → resolved`` emit one
  schema-registered ``alert`` record each way (firing and resolved only:
  the record stream carries transitions, ``/alertz`` carries the level);
- default rules for the quality plane (canary recall, LOF/size drift,
  anomaly rate, ingest lag) with every threshold ``GRAPHMINE_ALERT_*``
  env-tunable (malformed env raises loudly at construction, the
  AdmissionBounds discipline).

``tools/obs_report.py`` renders the alert timeline next to the quality
records and exits non-zero when the stream ends with a firing
page-severity alert — the CI gate (docs/OBSERVABILITY.md "Result
quality"). Stdlib-only, like everything in ``obs/``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from graphmine_tpu.obs.sketch import env_float

__all__ = [
    "AlertManager",
    "AlertRule",
    "default_rules",
]

# Rule states.
INACTIVE = "inactive"     # condition false, never fired (or fully reset)
PENDING = "pending"       # condition true, for_s not yet sustained
FIRING = "firing"         # condition sustained — the alert
RESOLVED = "resolved"     # condition false again after firing

_OPS = {
    ">": lambda v, t: v > t,
    "<": lambda v, t: v < t,
}


@dataclass(frozen=True)
class AlertRule:
    """One declarative rule: ``metric OP threshold`` for ``for_s``.

    ``severity``: ``"page"`` gates CI (``obs_report`` exits non-zero on
    a stream ending with one firing) and should be reserved for
    conditions that mean *served results are wrong* (the canary);
    ``"warn"`` is the drifting-but-investigate tier.
    """

    name: str
    metric: str               # key into the evaluate() value dict
    op: str                   # ">" or "<"
    threshold: float
    for_s: float = 0.0        # sustained-condition duration before firing
    severity: str = "warn"    # "warn" | "page"
    description: str = ""

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(f"op must be one of {sorted(_OPS)}, got {self.op!r}")
        if self.for_s < 0:
            raise ValueError("for_s must be >= 0")
        if self.severity not in ("warn", "page"):
            raise ValueError(
                f"severity must be 'warn' or 'page', got {self.severity!r}"
            )

    def condition(self, value: float) -> bool:
        return _OPS[self.op](float(value), self.threshold)


def default_rules() -> list:
    """The quality plane's default rule set, every threshold
    ``GRAPHMINE_ALERT_*`` env-tunable (resolved at call time, so a
    server constructed under a test env sees the test thresholds):

    ====================  =====================================  ========
    rule                  fires when                             default
    ====================  =====================================  ========
    canary_recall_low     canary_recall < CANARY_RECALL          0.7
    lof_drift_high        quality_lof_psi > LOF_PSI              0.25
    size_drift_high       quality_size_psi > SIZE_PSI            0.25
    anomaly_rate_high     quality_anomaly_rate > ANOMALY_RATE    0.2
    ingest_lag_high       ingest_lag_s > INGEST_LAG_S            60.0
                          for INGEST_LAG_FOR_S                   5.0
    mem_headroom_low      memory_headroom_frac < MEM_HEADROOM    0.1
    ====================  =====================================  ========

    ``canary_recall_low`` is the one ``page``: the probe's features are
    frozen, so a recall drop is a scorer regression by construction —
    the alert infra metrics cannot raise.
    """
    return [
        AlertRule(
            "canary_recall_low", "canary_recall", "<",
            env_float("GRAPHMINE_ALERT_CANARY_RECALL", 0.7),
            severity="page",
            description="planted-anomaly canary recall collapsed: the "
            "LOF scorer regressed (RUNBOOKS §13)",
        ),
        AlertRule(
            "lof_drift_high", "quality_lof_psi", ">",
            env_float("GRAPHMINE_ALERT_LOF_PSI", 0.25),
            description="LOF score distribution shifted vs parent "
            "snapshot (PSI > threshold)",
        ),
        AlertRule(
            "size_drift_high", "quality_size_psi", ">",
            env_float("GRAPHMINE_ALERT_SIZE_PSI", 0.25),
            description="community size distribution shifted vs parent "
            "snapshot (PSI > threshold)",
        ),
        AlertRule(
            "anomaly_rate_high", "quality_anomaly_rate", ">",
            env_float("GRAPHMINE_ALERT_ANOMALY_RATE", 0.2),
            description="share of vertices scoring above the LOF "
            "threshold is abnormally high",
        ),
        AlertRule(
            "ingest_lag_high", "ingest_lag_s", ">",
            env_float("GRAPHMINE_ALERT_INGEST_LAG_S", 60.0),
            for_s=env_float("GRAPHMINE_ALERT_INGEST_LAG_FOR_S", 5.0),
            description="oldest accepted-but-unapplied delta is older "
            "than the lag bound",
        ),
        AlertRule(
            "mem_headroom_low", "memory_headroom_frac", "<",
            env_float("GRAPHMINE_ALERT_MEM_HEADROOM", 0.1),
            description="serve-process memory headroom below the low "
            "watermark — read the memory waterfall before shrinking the "
            "graph (RUNBOOKS §14)",
        ),
    ]


class _RuleState:
    __slots__ = ("rule", "state", "since", "last_value", "last_change",
                 "times_fired", "times_resolved")

    def __init__(self, rule: AlertRule):
        self.rule = rule
        self.state = INACTIVE
        self.since = 0.0           # when the current condition run began
        self.last_value: float | None = None
        self.last_change = 0.0
        self.times_fired = 0
        self.times_resolved = 0

    def snapshot(self) -> dict:
        r = self.rule
        return {
            "name": r.name,
            "state": self.state,
            "severity": r.severity,
            "metric": r.metric,
            "op": r.op,
            "threshold": r.threshold,
            "for_s": r.for_s,
            "value": self.last_value,
            "times_fired": self.times_fired,
            "times_resolved": self.times_resolved,
            "description": r.description,
        }


class AlertManager:
    """Evaluates a rule set against flat value dicts; owns the per-rule
    state machines; emits ``alert`` records on firing/resolved
    transitions; serves the ``/alertz`` level view.

    A metric ABSENT from a value dict leaves its rule's state untouched
    (a replica with no canary never fires — or resolves — the canary
    rule), which is why evaluation can safely run on partial views like
    ``/healthz``'s. Thread-safe: handler threads, the apply worker and
    the fleet prober all drive :meth:`evaluate` concurrently.
    """

    def __init__(
        self, rules=None, sink=None, registry=None, clock=None,
        tenant: str = "",
    ):
        self.rules = list(default_rules() if rules is None else rules)
        names = [r.name for r in self.rules]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate alert rule names in {names}")
        self.sink = sink
        self.registry = registry
        # Tenant-scoped manager (ISSUE 16): a non-empty tenant stamps
        # every alert record with the owner — tenant A's canary page
        # names A — and skips the unlabelled firing gauge (per-tenant
        # managers racing one gauge would be last-writer-wins noise;
        # the default manager, tenant="", keeps the fleet-facing gauge).
        self.tenant = tenant
        self._clock = clock if clock is not None else time.monotonic
        self._states = {r.name: _RuleState(r) for r in self.rules}
        self._lock = threading.Lock()

    # -- evaluation --------------------------------------------------------
    def evaluate(self, values: dict, now: float | None = None) -> list:
        """One pass over every rule; returns the transitions fired this
        pass as ``(name, from_state, to_state)`` triples. Emission
        happens OUTSIDE the state lock (a sink fsync must not serialize
        /healthz against the apply worker — the serve/admission.py
        discipline)."""
        now = self._clock() if now is None else now
        transitions = []
        emits = []  # (_RuleState, state, value, times_fired) captured
        # UNDER the lock: a concurrent evaluate may overwrite
        # st.last_value before the out-of-lock emission runs, and a
        # "firing" record carrying a value that doesn't satisfy its own
        # threshold would mislead the obs_report timeline.
        with self._lock:
            for st in self._states.values():
                rule = st.rule
                if rule.metric not in values:
                    continue
                value = values[rule.metric]
                if value is None:
                    continue
                st.last_value = float(value)
                cond = rule.condition(value)
                before = st.state
                if cond:
                    if st.state in (INACTIVE, RESOLVED):
                        st.state, st.since = PENDING, now
                    if st.state == PENDING and now - st.since >= rule.for_s:
                        st.state = FIRING
                        st.times_fired += 1
                else:
                    if st.state == PENDING:
                        st.state = INACTIVE
                    elif st.state == FIRING:
                        st.state = RESOLVED
                        st.times_resolved += 1
                if st.state != before:
                    st.last_change = now
                    transitions.append((rule.name, before, st.state))
                    if st.state == FIRING or (
                        st.state == RESOLVED and before == FIRING
                    ):
                        emits.append(
                            (st, st.state, st.last_value, st.times_fired)
                        )
        for st, state, value, times_fired in emits:
            self._emit(st, state, value, times_fired)
        self._export()
        return transitions

    def _emit(
        self, st: _RuleState, state: str, value: float, times_fired: int,
    ) -> None:
        if self.sink is None:
            return
        r = st.rule
        kv = {}
        if self.tenant:
            kv["tenant"] = self.tenant
        self.sink.emit(
            "alert",
            name=r.name,
            state=state,
            severity=r.severity,
            metric=r.metric,
            op=r.op,
            value=value,
            threshold=r.threshold,
            for_s=r.for_s,
            times_fired=times_fired,
            description=r.description,
            **kv,
        )

    def _export(self) -> None:
        if self.registry is None or self.tenant:
            return
        self.registry.gauge(
            "graphmine_alerts_firing", "alert rules currently firing"
        ).set(len(self.firing()))

    # -- level views -------------------------------------------------------
    def firing(self) -> list:
        """Names of rules currently firing."""
        with self._lock:
            return [
                s.rule.name for s in self._states.values()
                if s.state == FIRING
            ]

    def snapshot(self) -> dict:
        """The ``/alertz`` body: every rule's level state."""
        with self._lock:
            rules = [s.snapshot() for s in self._states.values()]
        return {
            "firing": sum(1 for r in rules if r["state"] == FIRING),
            "rules": rules,
        }
