"""Counter/gauge registry + Prometheus textfile exporter.

The JSONL record stream (``MetricsSink``) is the *event* surface; this is
the *level* surface — monotonically increasing counters and
last-value gauges a scrape can read without replaying the event log. Two
exporters:

- the heartbeat thread folds a :meth:`Registry.values` snapshot into each
  ``heartbeat`` record (the JSONL exporter — rides the existing
  crash-safe stream);
- :meth:`Registry.write_textfile` renders the Prometheus *textfile
  collector* format atomically (tmp + ``os.replace``), the standard
  hand-off to a node_exporter sidecar for runs with no scrape endpoint.

Stdlib-only, thread-safe (one registry lock; counters/gauges are touched
from the driver loop, the resilience layer and the heartbeat thread).
"""

from __future__ import annotations

import os
import re
import threading

from graphmine_tpu.obs.histogram import (
    DEFAULT_LATENCY_BUCKETS,
    Histogram,
    HistogramFamily,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


class _Metric:
    __slots__ = ("name", "help", "kind", "_value", "_lock")

    def __init__(self, name: str, help: str, kind: str):
        self.name = name
        self.help = help
        self.kind = kind
        self._value = 0.0
        self._lock = threading.Lock()

    @property
    def value(self):
        with self._lock:
            v = self._value
        return int(v) if float(v).is_integer() else v


class Counter(_Metric):
    """Monotonic event count. ``inc`` only — a counter that can go down
    is a gauge wearing the wrong TYPE line."""

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help, "counter")

    def inc(self, n: float = 1) -> None:
        if n < 0:
            raise ValueError("counters only increase; use a Gauge")
        with self._lock:
            self._value += n


class Gauge(_Metric):
    """Last-observed value (current superstep, devices alive, RSS).
    ``labels`` distinguish siblings of one :class:`GaugeFamily`
    (per-shard WAL gauges, r17); an unlabeled gauge has an empty dict
    and renders exactly as before."""

    __slots__ = ("labels",)

    def __init__(self, name: str, help: str = "", labels: dict | None = None):
        super().__init__(name, help, "gauge")
        self.labels = dict(labels or {})

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n


class GaugeFamily:
    """All label-sets of one gauge name: one shared HELP/TYPE line, one
    :class:`Gauge` child per label combination — the shape the sharded
    write plane's per-shard WAL gauges need
    (``graphmine_serve_wal_pending_entries{shard="2"}``): one unlabeled
    gauge would silently fold a dead shard's backlog into healthy
    ranges. Mirrors :class:`~graphmine_tpu.obs.histogram.HistogramFamily`
    so the one-name-one-TYPE registry rule holds across kinds."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._children: dict = {}
        self._lock = threading.Lock()

    def labels(self, **labels) -> Gauge:
        """Get-or-create the child for one label combination."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = Gauge(
                    self.name, self.help, labels=dict(labels)
                )
            return child

    def children(self) -> list:
        """Children sorted by label set — deterministic exposition order."""
        with self._lock:
            return [self._children[k] for k in sorted(self._children)]

    @property
    def value(self):
        """Sum across children — what ``Registry.values`` (and the
        heartbeat's gauge fold) reports for a labeled family. For the
        WAL backlog gauges the sum IS the whole-plane total; per-shard
        values live in the exposition lines."""
        return sum(c.value for c in self.children())


class Registry:
    """Get-or-create metric registry. Re-requesting a name returns the
    same object; re-requesting it as a different kind raises (one name,
    one TYPE — Prometheus scrapers reject anything else)."""

    def __init__(self):
        self._metrics: dict = {}
        self._lock = threading.Lock()

    def _get(self, name: str, help: str, cls):
        if not _NAME_RE.match(name):
            raise ValueError(
                f"invalid metric name {name!r} (want [a-zA-Z_:][a-zA-Z0-9_:]*)"
            )
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, help, Counter)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        """Get-or-create a gauge. With ``labels``
        (``registry.gauge("wal_pending", shard="2")``) the name becomes
        a :class:`GaugeFamily` and the labeled child is returned; a name
        must stay labeled or unlabeled for its lifetime (mixing would
        emit duplicate series under one TYPE line)."""
        if not labels:
            return self._get(name, help, Gauge)
        if not _NAME_RE.match(name):
            raise ValueError(
                f"invalid metric name {name!r} (want [a-zA-Z_:][a-zA-Z0-9_:]*)"
            )
        with self._lock:
            fam = self._metrics.get(name)
            if fam is None:
                fam = self._metrics[name] = GaugeFamily(name, help)
            elif not isinstance(fam, GaugeFamily):
                raise ValueError(
                    f"metric {name!r} already registered as an unlabeled "
                    f"{fam.kind}; one name is one shape"
                )
        return fam.labels(**labels)

    def histogram(
        self, name: str, help: str = "", buckets=None, **labels
    ) -> Histogram:
        """Get-or-create one labeled child of the ``name`` histogram
        family (``registry.histogram("req_seconds", endpoint="query")``).
        The first call fixes the family's bucket ladder (default
        :data:`~graphmine_tpu.obs.histogram.DEFAULT_LATENCY_BUCKETS`); a
        later call naming a *different* ladder raises — merged/scraped
        buckets must be one ladder per name, same as one TYPE per name.
        """
        if not _NAME_RE.match(name):
            raise ValueError(
                f"invalid metric name {name!r} (want [a-zA-Z_:][a-zA-Z0-9_:]*)"
            )
        with self._lock:
            fam = self._metrics.get(name)
            if fam is None:
                fam = self._metrics[name] = HistogramFamily(
                    name, help,
                    DEFAULT_LATENCY_BUCKETS if buckets is None else buckets,
                )
            elif not isinstance(fam, HistogramFamily):
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}"
                )
            elif buckets is not None and tuple(
                float(b) for b in buckets
            ) != fam.bounds:
                raise ValueError(
                    f"histogram {name!r} already registered with a "
                    "different bucket ladder"
                )
        return fam.labels(**labels)

    def histogram_family(self, name: str) -> HistogramFamily | None:
        """The registered family (all labeled children) or None — how
        ``/statusz`` walks every endpoint's latency distribution."""
        with self._lock:
            fam = self._metrics.get(name)
        return fam if isinstance(fam, HistogramFamily) else None

    def values(self) -> dict:
        """Snapshot of every metric's current value, name-keyed."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: m.value for m in metrics}

    def render_textfile(self, labels: dict | None = None) -> str:
        """Prometheus text exposition, **deterministically ordered** —
        metrics sorted by name, histogram children by label set, label
        keys within a sample alphabetically — so two scrapes of the same
        state are byte-identical and successive scrapes diff cleanly.
        Every metric gets a ``# TYPE`` line (``# HELP`` when help text
        was registered). ``labels`` (e.g. ``{"run_id": ...}``) attach to
        every sample so a scrape distinguishes runs sharing one textfile
        directory. Histograms render per labeled child: cumulative
        ``_bucket`` samples (``le`` ascending, ``+Inf`` last), ``_sum``,
        ``_count`` — each child from one atomic snapshot, so a scrape
        concurrent with ``observe`` is never torn."""
        lab = ""
        if labels:
            parts = ",".join(
                '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"'))
                for k, v in sorted(labels.items())
            )
            lab = "{%s}" % parts
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if isinstance(m, HistogramFamily):
                for child in m.children():
                    lines.extend(child.render_lines(extra_labels=labels))
            elif isinstance(m, GaugeFamily):
                for child in m.children():
                    merged = dict(labels or {})
                    merged.update(child.labels)
                    parts = ",".join(
                        '%s="%s"' % (
                            k,
                            str(v).replace("\\", "\\\\").replace('"', '\\"'),
                        )
                        for k, v in sorted(merged.items())
                    )
                    lines.append(
                        f"{m.name}{{{parts}}} {child.value}"
                        if parts else f"{m.name} {child.value}"
                    )
            else:
                lines.append(f"{m.name}{lab} {m.value}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_textfile(self, path: str, labels: dict | None = None) -> str:
        """Atomically publish :meth:`render_textfile` at ``path`` — the
        node_exporter textfile collector reads whole files, so a torn
        write mid-scrape must be impossible (tmp + ``os.replace``)."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.render_textfile(labels))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path
