"""Periodic liveness records: a hung run must read differently from a
dead one.

A preempted or OOM-killed process simply stops appending to the metrics
stream; so does one wedged inside a hung collective. Without a
heartbeat, offline triage cannot tell which happened — the stream just
*ends*. The :class:`Heartbeat` daemon thread emits a ``heartbeat``
record every ``every_s`` seconds carrying the current span path (which
phase), the registry's gauge/counter snapshot (which superstep, how many
devices alive), process RSS and uptime — so a stream whose heartbeats
continue past its last phase record is *hung*, and one whose heartbeats
stop is *dead* (``tools/obs_report.py`` renders the verdict).

Records ride the sink's existing crash-safe line-buffered stream; when a
``prom_path`` is given each beat also republishes the Prometheus
textfile (:meth:`Registry.write_textfile`). Stdlib-only — devices-alive
comes from the driver-maintained gauge, never from a jax call on the
heartbeat thread (a probe into a wedged runtime would hang the very
thread that exists to report the hang).
"""

from __future__ import annotations

import logging
import threading
import time

log = logging.getLogger("graphmine_tpu")

_PAGESIZE = None

# Latest per-device memory_stats() sample, CACHED by the driver from its
# own thread (ISSUE 14 satellite): the heartbeat thread must NEVER call
# into the runtime itself — a probe into a wedged runtime would hang the
# very thread that exists to report the hang — so it reads this cache
# instead, and a HUNG verdict carries memory context at the age the
# driver last sampled it. RSS-only when no backend ever exposed stats.
_DEV_MEM_LOCK = threading.Lock()
_DEV_MEM: dict | None = None


def note_device_memory(per_device: list) -> None:
    """Cache the driver's latest per-device ``memory_stats()`` sample
    (``[{device, bytes_in_use, peak_bytes_in_use, bytes_limit}, ...]``)
    for heartbeat records. Called from the driver's telemetry cadence,
    never from the heartbeat thread."""
    global _DEV_MEM
    with _DEV_MEM_LOCK:
        _DEV_MEM = {"t": time.time(), "per_device": list(per_device)}


def device_memory() -> dict | None:
    """The cached sample with its staleness (``age_s``), or None when no
    backend has exposed memory stats this process."""
    with _DEV_MEM_LOCK:
        if _DEV_MEM is None:
            return None
        return {
            "age_s": round(time.time() - _DEV_MEM["t"], 1),
            "per_device": list(_DEV_MEM["per_device"]),
        }


def rss_mb() -> float | None:
    """Resident set size in MiB via ``/proc/self/statm`` (Linux), None
    where unavailable — a missing gauge, not a crash, off-Linux."""
    global _PAGESIZE
    try:
        if _PAGESIZE is None:
            import resource  # noqa: F401  (cheap; also warms errno paths)
            import os

            _PAGESIZE = os.sysconf("SC_PAGESIZE")
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return round(pages * _PAGESIZE / (1024 * 1024), 1)
    except (OSError, ValueError, IndexError):
        return None


class Heartbeat:
    """Emit liveness records on a daemon thread until :meth:`stop`.

    ``sink``: a :class:`~graphmine_tpu.pipeline.metrics.MetricsSink`
    (its ``tracer``/``registry``, when present, supply the phase path
    and the gauge snapshot). ``extra``: optional zero-arg callable whose
    dict merges into each record (driver-specific status).
    """

    def __init__(self, sink, every_s: float = 10.0, prom_path: str | None = None,
                 extra=None):
        if every_s <= 0:
            raise ValueError("every_s must be positive")
        self.sink = sink
        self.every_s = float(every_s)
        self.prom_path = prom_path
        self.extra = extra
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._t0 = time.perf_counter()
        self.beats = 0

    def beat(self) -> dict:
        """Emit one heartbeat record now (the thread's body; callable
        directly from tests and from the driver at phase boundaries)."""
        kv = {"uptime_s": round(time.perf_counter() - self._t0, 2)}
        tracer = getattr(self.sink, "tracer", None)
        if tracer is not None:
            kv["busy"] = tracer.latest().path
        registry = getattr(self.sink, "registry", None)
        if registry is not None:
            kv["gauges"] = registry.values()
        rss = rss_mb()
        if rss is not None:
            kv["rss_mb"] = rss
        dm = device_memory()
        if dm is not None:
            # per-device bytes_in_use context for the HUNG verdict
            # (ISSUE 14) — read from the driver-maintained cache, never
            # from a runtime call on this thread (see note_device_memory)
            kv["device_memory"] = dm
        if self.extra is not None:
            kv.update(self.extra())
        self.beats += 1
        rec = self.sink.emit("heartbeat", **kv)
        if self.prom_path and registry is not None:
            try:
                labels = {"run_id": tracer.run_id} if tracer else None
                registry.write_textfile(self.prom_path, labels=labels)
            except OSError:
                pass  # a full disk must not kill the liveness signal
        return rec

    def _loop(self) -> None:
        warned = False
        while not self._stop.wait(self.every_s):
            # One failing beat (a raising `extra` callable, a transient
            # sink error) must not kill the liveness loop: dead-silent
            # heartbeats on a live process are exactly the misdiagnosis
            # ("DEAD") this thread exists to prevent.
            try:
                self.beat()
            except Exception as e:
                if not warned:
                    warned = True
                    log.warning("heartbeat beat failed (will keep "
                                "trying): %r", e)

    def start(self) -> "Heartbeat":
        if self._thread is not None:
            raise RuntimeError("heartbeat already started")
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="graphmine-heartbeat"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Idempotent; joins the thread briefly so a final in-flight beat
        cannot interleave with stream finalization."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=max(2.0, self.every_s))
